"""Host-exec tier profiling — where does a forkserver exec's time go?

Round-2 verdict (weak #1) asked for evidence behind the host tier's
~170-370 execs/s: a per-exec cost breakdown (fork vs pipe vs Python
vs triage) and ExecPool overhead at workers=2..4 even on a 1-core
host.  Run after `make -C native && make -C corpus`:

    python profiling/profile_host.py

Emits one JSON line per measurement; docs/HOST_TIER.md holds the
analyzed numbers and the N-core scaling model.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from killerbeez_tpu.native.exec_backend import (  # noqa: E402
    ExecPool, ExecTarget,
)

TEST = os.path.join(REPO, "corpus", "build", "test")
PERSIST = os.path.join(REPO, "corpus", "build", "test-persist")


def emit(name, execs, dt, **kw):
    row = {"measure": name, "execs_per_sec": round(execs / dt, 1),
           "us_per_exec": round(dt / execs * 1e6, 1), **kw}
    print(json.dumps(row), flush=True)
    return row


def batch_inputs(n):
    inputs = np.zeros((n, 4), dtype=np.uint8)
    inputs[:] = np.frombuffer(b"zzzz", dtype=np.uint8)
    lens = np.full(n, 4, dtype=np.int32)
    return inputs, lens


def c_batch_loop(n=500):
    """The C dispatch loop (kb_target_run_batch): fork+pipe+SHM per
    exec with ONE Python call for the whole batch — the tier's floor
    without Python per-exec costs."""
    t = ExecTarget([TEST], use_stdin=True, coverage=True,
                   use_forkserver=True)
    try:
        inputs, lens = batch_inputs(n)
        t.run_batch(inputs, lens)  # warmup
        t0 = time.time()
        t.run_batch(inputs, lens)
        return emit("C batch loop (fork+pipe+SHM per exec)", n,
                    time.time() - t0)
    finally:
        t.close()


def c_batch_persistent(n=500):
    """Same, persistent mode: no fork per exec (SIGSTOP iteration
    boundaries).  C-loop minus this = the fork+reexec share."""
    t = ExecTarget([PERSIST], use_stdin=True, coverage=True,
                   use_forkserver=True, persistent=1000)
    try:
        inputs, lens = batch_inputs(n)
        t.run_batch(inputs, lens)
        t0 = time.time()
        t.run_batch(inputs, lens)
        return emit("C batch loop, persistent (no fork per exec)", n,
                    time.time() - t0)
    finally:
        t.close()


def python_per_exec(n=300):
    """One Python->ctypes call per exec (the single-exec vtable
    path); difference vs the C batch loop = Python dispatch."""
    t = ExecTarget([TEST], use_stdin=True, coverage=True,
                   use_forkserver=True)
    try:
        t.run(b"zzzz")
        t0 = time.time()
        for _ in range(n):
            t.run(b"zzzz")
        return emit("Python per-exec dispatch", n, time.time() - t0)
    finally:
        t.close()


def full_instrumentation(n=300):
    """The afl instrumentation's batched path: C exec loop + numpy
    classify/novelty per batch (config-2/3 territory)."""
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    instr = instrumentation_factory("afl", None)
    try:
        instr.prepare_host(TEST, use_stdin=True)
        inputs, lens = batch_inputs(n)
        instr.run_batch(inputs, lens)
        t0 = time.time()
        instr.run_batch(inputs, lens)
        return emit("afl instrumentation batch (exec + triage)", n,
                    time.time() - t0)
    finally:
        instr.cleanup()


def pool_scaling(n=400):
    """ExecPool at 1..4 workers.  On this 1-core host >1 workers
    cannot speed anything up — the measurement bounds the POOL'S OWN
    overhead (thread dispatch, batch sharding) and proves
    oversubscribed correctness."""
    rows = []
    for w in (1, 2, 3, 4):
        p = ExecPool([TEST], w, use_stdin=True, coverage=True,
                     use_forkserver=True)
        try:
            inputs, lens = batch_inputs(n)
            p.run_batch(inputs, lens)
            t0 = time.time()
            statuses, _ = p.run_batch(inputs, lens)
            rows.append(emit(f"ExecPool workers={w}", n,
                             time.time() - t0, workers=w,
                             all_ok=bool((statuses == 0).all())))
        finally:
            p.close()
    return rows


def main():
    print(json.dumps({"host_cores": os.cpu_count()}), flush=True)
    c = c_batch_loop()
    p = c_batch_persistent()
    py = python_per_exec()
    instr = full_instrumentation()
    pool_scaling()
    fork_us = c["us_per_exec"] - p["us_per_exec"]
    print(json.dumps({
        "breakdown_us_per_exec": {
            "fork+reexec (C minus persistent)": round(fork_us, 1),
            "pipe+SHM+child runtime (persistent loop)":
                p["us_per_exec"],
            "python dispatch (per-exec minus C loop)":
                round(py["us_per_exec"] - c["us_per_exec"], 1),
            "triage (instr batch minus C loop)":
                round(instr["us_per_exec"] - c["us_per_exec"], 1),
        }}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
