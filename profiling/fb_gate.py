"""Corpus-feedback gate: coverage@N-execs, -fb vs single-seed havoc.

Measures what docs/USAGE.md's feedback section reports: final
`coverage_bytes()` (non-virgin AFL-map bytes) after an equal exec
budget on the bundled CGC-grade KBVM targets, with and without the
corpus-feedback rotation.  Run on the TPU:

    python profiling/fb_gate.py [execs] [batch]
"""
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def coverage_at(target, seed, execs, batch, feedback):
    from killerbeez_tpu.drivers.factory import driver_factory
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.mutators.factory import mutator_factory

    instr = instrumentation_factory("jit_harness", json.dumps({
        "target": target, "engine": "pallas_fused",
        "novelty": "throughput"}))
    mut = mutator_factory("havoc", '{"seed": 3}', seed)
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir="bench_out/fb_gate",
                batch_size=batch, write_findings=False,
                feedback=feedback)
    curve = []  # (execs, coverage) after each chunk of batches
    done = 0
    while done < execs:
        done += batch
        fz.run(done)
        curve.append((done, int(instr.coverage_bytes())))
    return int(instr.coverage_bytes()), fz.stats, curve


def execs_to(curve, level):
    for execs, cov in curve:
        if cov >= level:
            return execs
    return None


def main():
    from killerbeez_tpu.models import targets_cgc
    # default budget spans many FEEDBACK_AUTO cadences (8 batches
    # between rotations on the default path)
    execs = int(sys.argv[1]) if len(sys.argv) > 1 else 131072
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    targets = [
        ("tlvstack_vm", targets_cgc.tlvstack_vm_seed()),
        ("imgparse_vm", targets_cgc.imgparse_vm_seed()),
        ("rledec_vm", targets_cgc.rledec_vm_seed()),
    ]
    # Two regimes per target: the hand-crafted seed (whose coverage
    # SATURATES the reachable universe within the budget on
    # imgparse/rledec — see docs/USAGE.md for the ceilings) and an
    # 8-byte truncation of it — the standard minimal-seed scenario
    # where frontier search is what a fuzzer is actually for.
    wins = 0
    for name, seed in targets:
        rows = []
        target_won = False
        target_lost = False
        for label, sd in (("crafted", seed), ("minimal", seed[:8])):
            base, bs, bc = coverage_at(name, sd, execs, batch, 0)
            # -1 = the PRODUCT DEFAULT path (Fuzzer.FEEDBACK_AUTO
            # cadence) — the gate measures what users actually get
            fb, fs, fc = coverage_at(name, sd, execs, batch, -1)
            level = min(base, fb)
            tb, tf = execs_to(bc, level), execs_to(fc, level)
            if fb > base:
                r = "WIN"
                target_won = True
            elif fb < base:
                r = "lose"
                target_lost = True
            elif tf is not None and tb is not None and tf < tb:
                r = "tie (fb earlier)"
            else:
                r = "tie"
            rows.append(
                f"  {label}-seed: single {base} vs -fb {fb} [{r}] "
                f"(execs-to-{level}: {tb} vs {tf}; crashes "
                f"{bs.crashes}/{fs.crashes})")
        wins += int(target_won and not target_lost)
        print(f"{name}:")
        for r in rows:
            print(r)
    print(f"targets won outright (win in a regime, no regime lost): "
          f"{wins}/3 @ {execs} execs, -b {batch}")


if __name__ == "__main__":
    main()
