"""Break down the fused fuzz step's time on the real chip.

Times each stage of the pipeline (mutation / VM execution /
static-edge triage / full fused step) separately under its own jit,
so BENCH regressions can be attributed.  Run on the TPU:

    python profiling/profile_step.py [target] [B] [L]

Writes a human table to stdout and the raw numbers to
profiling/profile_<target>.json.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, *args, warmup=1, iters=5):
    import jax
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def seed_for(target: str) -> bytes:
    from killerbeez_tpu.models import targets_cgc
    if target in targets_cgc.VM_SEEDS:
        return targets_cgc.VM_SEEDS[target][0]()
    return b"ABC@"


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from killerbeez_tpu import MAP_SIZE, FUZZ_CRASH, FUZZ_HANG, FUZZ_RUNNING
    from killerbeez_tpu.models import targets
    from killerbeez_tpu.models.vm import _run_batch_impl
    from killerbeez_tpu.instrumentation.jit_harness import _fused_step
    from killerbeez_tpu.ops.static_triage import (
        make_static_maps, static_triage,
    )
    from killerbeez_tpu.ops.mutate_core import havoc_at

    target = sys.argv[1] if len(sys.argv) > 1 else "test"
    B = int(sys.argv[2]) if len(sys.argv) > 2 else 32768
    seed = seed_for(target)
    L = int(sys.argv[3]) if len(sys.argv) > 3 else max(8, len(seed))

    prog = targets.get_target(target)
    instrs = jnp.asarray(prog.instrs)
    edge_table = jnp.asarray(prog.edge_table)
    u_np, s_np = make_static_maps(prog.edge_slot)
    u_slots, seg_id = jnp.asarray(u_np), jnp.asarray(s_np)
    print(f"target={target} NI={prog.instrs.shape[0]} "
          f"E={prog.n_edges} U={len(u_np)} mem={prog.mem_size} "
          f"max_steps={prog.max_steps} B={B} L={L}", file=sys.stderr)

    seed_buf = np.zeros(L, dtype=np.uint8)
    seed_buf[:len(seed)] = np.frombuffer(seed, dtype=np.uint8)
    seed_buf = jnp.asarray(seed_buf)
    seed_len = jnp.int32(len(seed))

    @jax.jit
    def mutate(it):
        base = jax.random.fold_in(jax.random.key(0), it)
        keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
            jnp.arange(B, dtype=jnp.uint32))
        return jax.vmap(
            lambda k: havoc_at(seed_buf, seed_len, k, stack_pow2=4))(keys)

    bufs, lens = mutate(jnp.uint32(0))
    jax.block_until_ready(bufs)

    @jax.jit
    def vm_only(bufs, lens):
        return _run_batch_impl(instrs, edge_table, bufs, lens,
                               prog.mem_size, prog.max_steps,
                               prog.n_edges, False)

    res = vm_only(bufs, lens)
    jax.block_until_ready(res.counts)
    steps_used = int(res.steps.max())

    virgin = jnp.full((MAP_SIZE,), 0xFF, dtype=jnp.uint8)
    statuses = jnp.where(res.status == FUZZ_RUNNING, FUZZ_HANG, res.status)

    @jax.jit
    def triage_only(vb, vc, vh, counts, statuses):
        return static_triage(vb, vc, vh, counts, u_slots, seg_id,
                             statuses == FUZZ_CRASH,
                             statuses == FUZZ_HANG)

    @jax.jit
    def fused(vb, vc, vh, it):
        bufs, lens = mutate(it)
        return _fused_step(instrs, edge_table, u_slots, seg_id, bufs,
                           lens, vb, vc, vh, prog.mem_size,
                           prog.max_steps, prog.n_edges, False)

    rows = {}
    rows["mutate"] = timeit(mutate, jnp.uint32(1))
    rows["vm_only"] = timeit(vm_only, bufs, lens)
    rows["triage_only"] = timeit(triage_only, virgin, virgin, virgin,
                                 res.counts, statuses)
    rows["fused_step"] = timeit(fused, virgin, virgin, virgin,
                                jnp.uint32(1))

    print(f"max lane steps used: {steps_used}/{prog.max_steps}",
          file=sys.stderr)
    out = {"target": target, "B": B, "L": L,
           "NI": int(prog.instrs.shape[0]), "E": prog.n_edges,
           "U": int(len(u_np)),
           "max_steps": prog.max_steps, "steps_used": steps_used,
           "times_s": rows,
           "execs_per_sec_fused": B / rows["fused_step"]}
    for k, v in rows.items():
        print(f"{k:14s} {v*1e3:10.2f} ms   {B/v:12.0f} execs/s")
    out_dir = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(out_dir, f"profile_{target}.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
