"""Benchmark: execs/sec/chip on the corpus-test workload.

Measures the fused on-device fuzzing pipeline (havoc mutation -> KBVM
execution of the `test` ABCD-crasher -> AFL-map coverage triage) on
the real chip, against the reference's ~1k execs/sec forkserver
baseline (BASELINE.md). Prints exactly one JSON line.
"""

import json
import sys
import time


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from killerbeez_tpu import MAP_SIZE
    from killerbeez_tpu.models import targets
    from killerbeez_tpu.instrumentation.jit_harness import _fused_step
    from killerbeez_tpu.ops.mutate_core import havoc_at

    BASELINE = 1000.0  # execs/sec, reference forkserver (BASELINE.md)
    B = 32768
    L = 8
    STEPS = 20

    prog = targets.get_target("test")
    instrs = jnp.asarray(prog.instrs)
    seed = b"ABC@"
    seed_buf = np.zeros(L, dtype=np.uint8)
    seed_buf[:len(seed)] = np.frombuffer(seed, dtype=np.uint8)
    seed_buf = jnp.asarray(seed_buf)
    seed_len = jnp.int32(len(seed))

    @jax.jit
    def fuzz_step(vb, vc, vh, it):
        base = jax.random.fold_in(jax.random.key(0), it)
        keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
            jnp.arange(B, dtype=jnp.uint32))
        bufs, lens = jax.vmap(
            lambda k: havoc_at(seed_buf, seed_len, k, stack_pow2=4))(keys)
        statuses, new_paths, uc, uh, ec, vb2, vc2, vh2, _ = _fused_step(
            instrs, bufs, lens, vb, vc, vh, prog.mem_size,
            prog.max_steps, False)
        return vb2, vc2, vh2, jnp.sum(statuses == 2), jnp.sum(new_paths > 0)

    virgin = jnp.full((MAP_SIZE,), 0xFF, dtype=jnp.uint8)
    vb, vc, vh = virgin, virgin, virgin
    # warmup/compile
    vb, vc, vh, crashes, news = fuzz_step(vb, vc, vh, jnp.uint32(0))
    jax.block_until_ready(vb)

    t0 = time.time()
    total_crashes = 0
    for i in range(1, STEPS + 1):
        vb, vc, vh, crashes, news = fuzz_step(vb, vc, vh, jnp.uint32(i))
    total_crashes = int(crashes)
    jax.block_until_ready(vb)
    dt = time.time() - t0

    execs_per_sec = B * STEPS / dt
    print(json.dumps({
        "metric": "execs/sec/chip on corpus test (fused havoc+KBVM+AFL-map triage)",
        "value": round(execs_per_sec, 1),
        "unit": "execs/sec",
        "vs_baseline": round(execs_per_sec / BASELINE, 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
