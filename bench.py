"""Benchmark — the BASELINE.md reproduction matrix.

Emits one JSON line per config, then the headline line LAST (the
driver records the final line):

  1  host file+return_code+bit_flip sanity (reference ~180 execs/s)
  2  host stdin+afl forkserver, single instance (reference ~1k;
     steady state after warmup)
  3  TPU-batch mutation + host forkserver pool (afl workers=N)
  4  fused on-device path on the toy `test` target
  5  multichip CPU-mesh correctness smoke (virtual 8-device mesh)
  4b flagship tlvstack_vm on the XLA engine (pallas-less floor)
  4c imgparse_vm, fused pallas + two-phase
  4d the PRODUCT CLI loop (file+jit_harness+havoc, pallas_fused)
  H  fused pallas + two-phase on the CGC-grade flagship
     (tlvstack_vm, 110 blocks) — the headline metric

Native configs degrade to {"skipped": ...} rows when the host
toolchain or corpus build is unavailable.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
FORKSERVER_BASELINE = 1000.0   # reference forkserver execs/s (BASELINE.md)


def emit(config, metric, value, unit="execs/sec", baseline=None, **kw):
    row = {"config": config, "metric": metric, "value": round(value, 1),
           "unit": unit}
    if baseline:
        row["vs_baseline"] = round(value / baseline, 2)
    row.update(kw)
    print(json.dumps(row), flush=True)
    return row


def stage_split_row(fz):
    """{stage: fraction} for a finished Fuzzer config, plus the
    human-readable summary line on stderr (so the JSON stream stays
    machine-parseable)."""
    split = fz.telemetry.registry.stage_split()
    line = fz.telemetry.stage_summary()
    if line:
        print(f"  [{line}]", file=sys.stderr, flush=True)
    return {s: round(f, 4) for s, f in split.items()}


def build_corpus():
    from killerbeez_tpu.native.build import build_native
    if not build_native():
        return False
    r = subprocess.run(["make", "-C", os.path.join(REPO, "corpus")],
                       capture_output=True, text=True)
    return r.returncode == 0


def bench_host_configs():
    """Configs 1-3: host forkserver tiers."""
    import numpy as np
    from killerbeez_tpu.drivers.factory import driver_factory
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.mutators.factory import mutator_factory

    test_bin = os.path.join(REPO, "corpus", "build", "test")

    def run_config(n_iters, batch, instr_name, instr_opts, driver_name,
                   driver_opts, out_dir, warmup=0):
        """Build, run and ALWAYS tear down one host config (a leaked
        forkserver would hold SHM + CPU for the rest of the bench).
        ``warmup`` executes that many iterations first so the timed
        window measures steady state, not jit compiles (the
        reference's 'Ran N iterations in S seconds' is likewise a
        warm loop)."""
        instr = instrumentation_factory(instr_name, instr_opts)
        drv = None
        try:
            mut = mutator_factory("havoc", '{"seed": 3}', b"ABC@") \
                if instr_name == "afl" else \
                mutator_factory("bit_flip", None, b"ABC@")
            drv = driver_factory(driver_name, driver_opts, instr, mut)
            fz = Fuzzer(drv, output_dir=os.path.join(
                REPO, "bench_out", out_dir), batch_size=batch,
                write_findings=False)
            if warmup:
                fz.run(warmup)
            done = fz.stats.iterations
            warm_crashes = fz.stats.crashes  # exclude warmup findings
            t0 = time.time()
            stats = fz.run(done + n_iters)
            return ((stats.iterations - done) / (time.time() - t0),
                    stats, stats.crashes - warm_crashes, fz)
        finally:
            if drv is not None:
                drv.cleanup()
            instr.cleanup()

    # config 1: file + return_code + bit_flip -n 20 (smoke_test.sh:41-70)
    v, stats, _, fz = run_config(
        20, 20, "return_code", None, "file",
        json.dumps({"path": test_bin, "arguments": "@@"}), "c1")
    emit(1, "file+return_code+bit_flip 20 iters", v, baseline=180.0,
         iterations=stats.iterations,
         stage_split=stage_split_row(fz))

    # config 2: stdin + afl(forkserver) + havoc, single instance
    v, stats, crashes, fz = run_config(
        2000, 500, "afl", None, "stdin",
        json.dumps({"path": test_bin}), "c2", warmup=500)
    emit(2, "stdin+afl forkserver, 1 instance", v,
         baseline=FORKSERVER_BASELINE, crashes=crashes,
         stage_split=stage_split_row(fz))

    # config 3: TPU-batch mutation + host forkserver pool
    workers = os.cpu_count() or 1
    v, stats, crashes, fz = run_config(
        8192, 2048, "afl", json.dumps({"workers": workers}), "stdin",
        json.dumps({"path": test_bin}), "c3", warmup=2048)
    emit(3, f"tpu-batch mutate + forkserver pool x{workers}", v,
         baseline=FORKSERVER_BASELINE, host_cores=workers,
         crashes=crashes, stage_split=stage_split_row(fz))



def _prep_seed(seed):
    import jax.numpy as jnp
    import numpy as np
    L = max(8, len(seed))
    seed_buf = np.zeros(L, dtype=np.uint8)
    seed_buf[:len(seed)] = np.frombuffer(seed, dtype=np.uint8)
    return jnp.asarray(seed_buf), jnp.int32(len(seed))


def _time_fuzz_loop(fuzz_step, batch, steps):
    """Warm up, then time `steps` dependent fuzz steps.  fuzz_step:
    (vb, vc, vh, it) -> (vb, vc, vh, crashes, new_paths)."""
    import jax
    import jax.numpy as jnp
    from killerbeez_tpu import MAP_SIZE
    virgin = jnp.full((MAP_SIZE,), 0xFF, dtype=jnp.uint8)
    vb, vc, vh = virgin, virgin, virgin
    vb, vc, vh, crashes, news = fuzz_step(vb, vc, vh, jnp.uint32(0))
    jax.block_until_ready(vb)
    t0 = time.time()
    for i in range(1, steps + 1):
        vb, vc, vh, crashes, news = fuzz_step(vb, vc, vh,
                                              jnp.uint32(i))
    jax.block_until_ready(vb)
    dt = time.time() - t0
    return batch * steps / dt, int(crashes)


def bench_device(target, batch, steps, seed, stack_pow2=4,
                 engine="xla"):
    """Fused on-device fuzz loop: havoc -> KBVM -> static-edge triage."""
    import jax
    import jax.numpy as jnp
    from killerbeez_tpu import FUZZ_CRASH
    from killerbeez_tpu.models import targets
    from killerbeez_tpu.instrumentation.jit_harness import _fused_step
    from killerbeez_tpu.ops.mutate_core import havoc_at
    from killerbeez_tpu.ops.static_triage import make_static_maps

    prog = targets.get_target(target)
    instrs = jnp.asarray(prog.instrs)
    edge_table = jnp.asarray(prog.edge_table)
    u_np, s_np = make_static_maps(prog.edge_slot)
    u_slots, seg_id = jnp.asarray(u_np), jnp.asarray(s_np)
    seed_buf, seed_len = _prep_seed(seed)

    @jax.jit
    def fuzz_step(vb, vc, vh, it):
        base = jax.random.fold_in(jax.random.key(0), it)
        keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
            jnp.arange(batch, dtype=jnp.uint32))
        bufs, lens = jax.vmap(
            lambda k: havoc_at(seed_buf, seed_len, k,
                               stack_pow2=stack_pow2))(keys)
        statuses, new_paths, uc, uh, ec, vb2, vc2, vh2, _ = _fused_step(
            instrs, edge_table, u_slots, seg_id, bufs, lens, vb, vc, vh,
            prog.mem_size, prog.max_steps, prog.n_edges, False, engine)
        return (vb2, vc2, vh2, jnp.sum(statuses == FUZZ_CRASH),
                jnp.sum(new_paths > 0))

    return _time_fuzz_loop(fuzz_step, batch, steps)


def bench_device_fused(target, batch, steps, seed):
    """Mutation AND execution in ONE pallas_call (ops/vm_kernel
    fuzz_batch_pallas): candidates are born, run and counted while
    resident in VMEM; triage consumes the counts."""
    import jax
    import jax.numpy as jnp
    from killerbeez_tpu import FUZZ_CRASH, FUZZ_HANG, FUZZ_RUNNING
    from killerbeez_tpu.models import targets
    from killerbeez_tpu.ops.static_triage import (
        make_static_maps, static_triage,
    )
    from killerbeez_tpu.ops.vm_kernel import (
        auto_phase1_steps, dot_modes, fuzz_batch_pallas_2phase,
        havoc_words,
    )

    prog = targets.get_target(target)
    ins = jnp.asarray(prog.instrs)
    tbl = jnp.asarray(prog.edge_table)
    u_np, s_np = make_static_maps(prog.edge_slot)
    u_slots, seg_id = jnp.asarray(u_np), jnp.asarray(s_np)
    seed_j, seed_len = _prep_seed(seed)
    # the product's auto two-phase rule (jit_harness phase1_steps=-1)
    p1 = auto_phase1_steps(prog.max_steps)

    @jax.jit
    def fuzz_step(vb, vc, vh, it):
        w = havoc_words(jax.random.fold_in(jax.random.key(0), it),
                        batch)
        res, bufs, lens = fuzz_batch_pallas_2phase(
            ins, tbl, seed_j, seed_len, w, prog.mem_size,
            prog.max_steps, prog.n_edges, phase1_steps=p1,
            dots=dot_modes(prog.instrs, prog.n_edges))
        statuses = jnp.where(res.status == FUZZ_RUNNING, FUZZ_HANG,
                             res.status)
        new_paths, uc, uh, vb2, vc2, vh2 = static_triage(
            vb, vc, vh, res.counts, u_slots, seg_id,
            statuses == FUZZ_CRASH, statuses == FUZZ_HANG)
        return (vb2, vc2, vh2, jnp.sum(statuses == FUZZ_CRASH),
                jnp.sum(new_paths > 0))

    return _time_fuzz_loop(fuzz_step, batch, steps)


def bench_cli_product(target, batch, steps, seed, telemetry=None,
                      out_name="cli_product", engine="pallas_fused",
                      trace=0, feedback=-1):
    """Config 4d: the PRODUCT path — the ordinary Fuzzer loop (what
    `python -m killerbeez_tpu.fuzzer file jit_harness havoc` runs)
    with engine=pallas_fused, measured post-warmup.  The flagship
    bench number must be reproducible here or it's a bench artifact
    (round-2 verdict item 1).  ``telemetry`` passes through to the
    Fuzzer (None = default sink on, False = --no-stats); ``trace``
    turns the flight-recorder span ring on (--trace); ``feedback``
    passes through to the Fuzzer (-1 = auto, 0 = off — the
    --generations A/B pins 0 so both lanes mutate the same seed)."""
    import shutil
    import json as _json
    from killerbeez_tpu.drivers.factory import driver_factory
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.mutators.factory import mutator_factory

    instr = instrumentation_factory(
        "jit_harness", _json.dumps({
            "target": target, "engine": engine,
            "novelty": "throughput"}))
    mut = mutator_factory("havoc", '{"seed": 3}', seed)
    drv = driver_factory("file", None, instr, mut)
    out = os.path.join(REPO, "bench_out", out_name)
    shutil.rmtree(out, ignore_errors=True)
    fz = Fuzzer(drv, output_dir=out, batch_size=batch,
                telemetry=telemetry, trace=trace, feedback=feedback)
    # warmup must cover BOTH compiled paths (per-batch step + K-step
    # superbatch) AND end on a K boundary: a misaligned batch counter
    # would route the first timed batches through the per-batch path
    # (gap < K in _run_batched), mixing per-batch transfers into a
    # window labeled as the superbatch config
    fz.run(3 * fz.ACCUMULATE_AUTO * batch)
    done = fz.stats.iterations             # run(n) targets a TOTAL
    t0 = time.time()
    fz.run(done + batch * steps)
    dt = time.time() - t0
    return (fz.stats.iterations - done) / dt, fz.stats, fz


def bench_stats_overhead(batch=65536, steps=32, target="tlvstack_vm",
                         engine="pallas_fused"):
    """--stats-overhead: the flagship CLI config telemetry-ON
    (default sink, 5s interval) vs --no-stats, emitted as one JSON
    line so BENCH rounds track observability cost over time.  The
    acceptance bar is <= 3% execs/s."""
    from killerbeez_tpu.models import targets_cgc
    seed = targets_cgc.tlvstack_vm_seed()
    v_on, _, fz = bench_cli_product(target, batch, steps, seed,
                                    telemetry=None,
                                    out_name="overhead_on",
                                    engine=engine)
    split = stage_split_row(fz)
    v_off, _, _ = bench_cli_product(target, batch, steps, seed,
                                    telemetry=False,
                                    out_name="overhead_off",
                                    engine=engine)
    overhead = (v_off - v_on) / v_off * 100.0 if v_off else 0.0
    emit("stats-overhead",
         f"telemetry on vs --no-stats ({target}, -b {batch}, "
         f"{steps} steps, {engine})", v_on, unit="execs/sec",
         no_stats_value=round(v_off, 1),
         overhead_pct=round(overhead, 2),
         within_3pct=bool(overhead <= 3.0),
         stage_split=split)
    return overhead


def bench_trace_overhead(batch=65536, steps=32, target="tlvstack_vm",
                         engine="pallas_fused", repeats=3,
                         gate_pct=None):
    """--trace-overhead: the flagship CLI config with the flight
    recorder ON (--trace default ring) vs OFF, emitted as one JSON
    line.  The traced run pays the full cost a real ``--trace``
    campaign pays: per-stage begin/end span records each batch, the
    in-flight lane bookkeeping, AND the trace.json export at run end.
    The measurement repeats and keeps the MINIMUM overhead — run-to-
    run host noise on a shared box exceeds the recorder's true cost,
    and the best-of-N is the defensible hot-path bound the CI gate
    asserts (acceptance bar: <= 2% execs/s)."""
    from killerbeez_tpu.models import targets_cgc
    seed = targets_cgc.tlvstack_vm_seed()
    best = None
    best_pair = (0.0, 0.0)
    best_split = {}
    for _ in range(max(int(repeats), 1)):
        v_on, _, fz = bench_cli_product(target, batch, steps, seed,
                                        out_name="trace_on",
                                        engine=engine, trace=65536)
        split = stage_split_row(fz)
        v_off, _, _ = bench_cli_product(target, batch, steps, seed,
                                        out_name="trace_off",
                                        engine=engine, trace=0)
        overhead = (v_off - v_on) / v_off * 100.0 if v_off else 0.0
        if best is None or overhead < best:
            best, best_pair, best_split = overhead, (v_on, v_off), \
                split
    emit("trace-overhead",
         f"--trace on vs off ({target}, -b {batch}, {steps} steps, "
         f"{engine}, best of {repeats})", best_pair[0],
         unit="execs/sec",
         trace_off_value=round(best_pair[1], 1),
         overhead_pct=round(best, 2),
         within_2pct=bool(best <= 2.0),
         repeats=repeats,
         stage_split=best_split)
    if gate_pct is not None and best > gate_pct:
        print(f"error: trace overhead {best:.2f}% exceeds the "
              f"{gate_pct:.1f}% gate", file=sys.stderr)
        return 1
    return 0


def _sched_campaign(target, policy, seed, batch, execs, out_tag="",
                    feedback=-1, deterministic=False):
    """One (target, policy) scheduling campaign; returns the emitted
    row.  ``rare-edge-learned`` is rare-edge + the learn tier
    (killerbeez_tpu/learn/): the campaign's own admissions train the
    byte-saliency model online and rotations install learned focus
    masks — the A/B against rare-edge-static measures learned vs
    static mask sources on the SAME scheduler.

    ``deterministic`` (the --gate lanes) collapses the triage
    pipeline to depth 1 and trains on a pure label-count cadence:
    the candidate/rotation stream is then a function of the RNG seed
    alone, so the A/B path counts compare mask sources, not
    pipeline-drain timing (at these small budgets the is_ready-probe
    drain reorders admissions across rotation boundaries run to run
    — measured swings bigger than the effect under test)."""
    import json as _json
    import shutil
    from killerbeez_tpu.drivers.factory import driver_factory
    from killerbeez_tpu.fuzzer.cli import (
        _wire_rare_edge_signer, _wire_static_prior,
    )
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.mutators.factory import mutator_factory

    iopts = {"target": target, "novelty": "throughput"}
    learn_tier = None
    if policy == "rare-edge-learned":
        from killerbeez_tpu.learn import LearnTier
        iopts["learn"] = 1
        learn_tier = LearnTier(
            train_interval_s=(0.0 if deterministic else 0.5),
            min_labels=16)
    instr = instrumentation_factory("jit_harness",
                                    _json.dumps(iopts))
    mut = mutator_factory("havoc", '{"seed": 7}', seed)
    drv = driver_factory("file", None, instr, mut)
    out = os.path.join(REPO, "bench_out",
                       f"sched_{target}_{policy}{out_tag}")
    shutil.rmtree(out, ignore_errors=True)
    fz = Fuzzer(drv, output_dir=out, batch_size=batch,
                write_findings=False, feedback=feedback,
                scheduler=("rare-edge"
                           if policy in ("rare-edge-static",
                                         "rare-edge-learned")
                           else policy),
                learn=learn_tier)
    if deterministic:
        fz.PIPELINE_DEPTH = 1
    if policy in ("rare-edge", "rare-edge-static",
                  "rare-edge-learned"):
        _wire_rare_edge_signer(fz, drv)
    if policy == "rare-edge-static":
        _wire_static_prior(fz, drv)
    t0 = time.time()
    stats = fz.run(execs)
    dt = time.time() - t0
    extra = {}
    if learn_tier is not None:
        extra = {"learn_model_version": learn_tier.version,
                 "learn_labels": len(learn_tier.labels),
                 "learn_masks_applied": learn_tier.masks_applied}
    return emit(f"sched-{policy}",
                f"{policy} scheduler on {target} (-b {batch}, "
                f"{execs} execs)",
                stats.iterations / dt,
                coverage_bytes=instr.coverage_bytes(),
                new_paths=stats.new_paths,
                paths_per_kexec=round(
                    1000.0 * stats.new_paths
                    / max(stats.iterations, 1), 3),
                crashes=stats.crashes,
                corpus_arms=len(fz.scheduler.arms),
                rotations=fz.scheduler.rotations,
                target=target, **extra)


def bench_schedulers(schedules, targets=None, batch=1024, execs=131072,
                     seed_tag="minimal"):
    """--schedule: coverage-at-budget comparison of the seed
    scheduling policies (corpus/schedule.py) on the CGC-class
    targets — the fb_gate.py protocol (coverage bytes at a fixed exec
    budget, minimal-seed regime: the scenario coverage-guided
    scheduling exists for), one row per (target, policy).  rare-edge
    signs each admitted entry with one extra exec on a side
    instrumentation instance (the same wiring as the CLI);
    rare-edge-static is rare-edge with the static edge-frequency
    prior installed (analysis.static_edge_prior); rare-edge-learned
    is rare-edge with the learn tier's online-trained masks
    (docs/LEARN.md) — learned vs static mask sources on the same
    scheduler.  Returns {(target, policy): row}."""
    from killerbeez_tpu.models import targets_cgc

    seeds = {
        "tlvstack_vm": targets_cgc.tlvstack_vm_seed(),
        "rledec_vm": targets_cgc.rledec_vm_seed(),
        "imgparse_vm": targets_cgc.imgparse_vm_seed(),
        "fixedform_vm": targets_cgc.fixedform_vm_seed(),
    }
    rows = {}
    for target in (targets or ["tlvstack_vm", "rledec_vm",
                               "imgparse_vm"]):
        seed = seeds[target]
        if seed_tag == "minimal" and target != "fixedform_vm":
            # the standard minimal-seed cut; fixedform is exempt —
            # the family IS a wide fixed-offset form, an 8-byte cut
            # dies at its length check
            seed = seed[:8]
        for policy in schedules:
            rows[(target, policy)] = _sched_campaign(
                target, policy, seed, batch, execs)
    return rows


def bench_schedule_learn_gate(targets, batch, execs):
    """--schedule --gate: the learned-vs-static A/B (ROADMAP item 2's
    acceptance metric: paths-per-exec uplift at equal execs/s).
    Both lanes run the SAME rare-edge scheduler, RNG seed, cadence
    (-fb 4) and budget on the fixed-offset form family
    (``fixedform_vm`` — the "not all bytes are equal" regime: ~16
    live positions of 96, the rest provably never loaded;
    docs/LEARN.md has the honesty caveats for compact-seed families
    where the tier measures flat).  Deterministic campaigns
    (synchronous triage, label-count train cadence), so the path
    counts compare mask sources, not pipeline timing.  The gate
    requires

      * paths-per-exec uplift on >= 1 target family
        (learned new_paths > static new_paths at the fixed budget),
      * equal execs/s: the learned lane holds >= 85% of the static
        lane's rate on every target (mask inference + training must
        not buy coverage by spending the throughput the mode exists
        to preserve; 95% on TPU where shared-runner noise is not an
        excuse).

    The rate check gets one logged re-measure on failure (the PR 9
    shared-runner noise guard — wall-clock is the one noisy input
    left; a genuine regression fails both rounds and the retry lands
    in the artifact, never silent).  Writes
    bench_out/BENCH_schedule_learn.json; exits nonzero on a hard
    fail."""
    import jax

    on_tpu = jax.default_backend() == "tpu"
    rate_floor = 0.95 if on_tpu else 0.85
    from killerbeez_tpu.models import targets_cgc
    seeds = {
        "tlvstack_vm": targets_cgc.tlvstack_vm_seed(),
        "rledec_vm": targets_cgc.rledec_vm_seed(),
        "imgparse_vm": targets_cgc.imgparse_vm_seed(),
        "fixedform_vm": targets_cgc.fixedform_vm_seed(),
    }
    targets = targets or ["fixedform_vm"]

    def measure(tag=""):
        per = {}
        for t in targets:
            seed = seeds[t]
            st = _sched_campaign(t, "rare-edge-static", seed, batch,
                                 execs, out_tag=tag, feedback=4,
                                 deterministic=True)
            ln = _sched_campaign(t, "rare-edge-learned", seed, batch,
                                 execs, out_tag=tag, feedback=4,
                                 deterministic=True)
            per[t] = {
                "static_paths": st["new_paths"],
                "learned_paths": ln["new_paths"],
                "static_execs_per_sec": st["value"],
                "learned_execs_per_sec": ln["value"],
                "rate_ratio": round(ln["value"]
                                    / max(st["value"], 1e-9), 3),
                "learn_model_version": ln.get("learn_model_version"),
                "learn_masks_applied": ln.get("learn_masks_applied"),
            }
        uplift = [t for t, r in per.items()
                  if r["learned_paths"] > r["static_paths"]]
        rate_ok = all(r["rate_ratio"] >= rate_floor
                      for r in per.values())
        return per, uplift, rate_ok

    per, uplift, rate_ok = measure()
    retry = None
    if uplift and not rate_ok:
        # only the WALL-CLOCK rate check is noisy — the campaigns
        # are deterministic, so a paths-uplift failure cannot flip
        # on a re-run and retrying it would just double the gate's
        # cost to report the same regression
        print("schedule-learn gate: rate check failed — "
              "re-measuring both lanes once (shared-runner noise "
              "guard)", file=sys.stderr)
        per2, uplift2, rate_ok2 = measure(tag="_retry")
        retry = per2
        per, uplift, rate_ok = per2, uplift2, rate_ok2
    ok = bool(uplift) and rate_ok
    summary = {
        "metric": "paths-per-exec uplift, learned vs static masks "
                  "(rare-edge scheduler, fixed-offset form family)",
        "targets": per,
        "uplift_targets": uplift,
        "rate_floor": rate_floor,
        "rate_ok": rate_ok,
        "retry": retry,
        "gate_ok": ok,
    }
    out = os.path.join(REPO, "bench_out")
    os.makedirs(out, exist_ok=True)
    with open(os.path.join(out, "BENCH_schedule_learn.json"),
              "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps({"config": "schedule-learn-gate", **{
        k: v for k, v in summary.items() if k != "targets"}}),
        flush=True)
    if not ok:
        print("error: schedule-learn gate failed: "
              + ("no paths-per-exec uplift on any target; "
                 if not uplift else "")
              + ("" if rate_ok else
                 f"learned lane under {rate_floor:.0%} of the "
                 f"static lane's execs/s"), file=sys.stderr)
        return 1
    return 0


def bench_crack(targets=None, batch=256, budget_execs=131072,
                plateau=4, chunk_batches=8):
    """--crack: plateau-crack A/B lane.  For each built-in magic-byte
    target, run the SAME campaign (jit_harness + havoc from an
    uninformative seed — the regime where blind mutation stalls on
    magic bytes) with the crack stage off and on, and report execs
    until 100% of the statically-reachable edge slots are covered
    (or coverage at budget when the run never gets there).  The
    acceptance bar: crack-on reaches full static coverage with
    measurably fewer execs than the scheduler alone."""
    import json as _json
    import shutil
    import numpy as np
    from killerbeez_tpu.analysis import analyze_dataflow
    from killerbeez_tpu.drivers.factory import driver_factory
    from killerbeez_tpu.fuzzer.crack import BranchCracker
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.models import targets as targets_mod
    from killerbeez_tpu.models import targets_cgc  # noqa: F401
    from killerbeez_tpu.mutators.factory import mutator_factory

    for target in (targets or ("test", "cgc_like")):
        prog = targets_mod.get_target(target)
        df = analyze_dataflow(prog)
        ef = np.asarray(prog.edge_from)
        et = np.asarray(prog.edge_to)
        slots = np.asarray(prog.edge_slot)
        # statically-reachable slots: drop edges touching blocks
        # constant propagation proves dead
        goal = {int(s) for f, t, s in zip(ef, et, slots)
                if int(f) not in df.dead_blocks
                and int(t) not in df.dead_blocks}
        for mode in ("off", "on"):
            instr = instrumentation_factory(
                "jit_harness", _json.dumps(
                    {"target": target, "novelty": "throughput"}))
            mut = mutator_factory("havoc", '{"seed": 11}',
                                  b"\x00" * 8)
            drv = driver_factory("file", None, instr, mut)
            out = os.path.join(REPO, "bench_out",
                               f"crack_{target}_{mode}")
            shutil.rmtree(out, ignore_errors=True)
            fz = Fuzzer(drv, output_dir=out, batch_size=batch,
                        write_findings=False)
            if mode == "on":
                fz.cracker = BranchCracker(prog,
                                           plateau_batches=plateau)
            full_at = None
            t0 = time.time()
            while fz.stats.iterations < budget_execs:
                fz.run(fz.stats.iterations + chunk_batches * batch)
                vb = np.asarray(instr.virgin_bits)
                covered = set(np.flatnonzero(vb != 0xFF).tolist())
                if goal <= covered:
                    full_at = fz.stats.iterations
                    break
            dt = time.time() - t0
            vb = np.asarray(instr.virgin_bits)
            covered = set(np.flatnonzero(vb != 0xFF).tolist())
            reg = fz.telemetry.registry
            emit(f"crack-{mode}",
                 f"plateau-crack {mode} on {target} (-b {batch}, "
                 f"plateau {plateau}, blind 8-byte seed)",
                 fz.stats.iterations / dt if dt else 0.0,
                 target=target,
                 execs_to_full_static_coverage=full_at,
                 coverage_slots=len(goal & covered),
                 goal_slots=len(goal),
                 execs=fz.stats.iterations,
                 crashes=fz.stats.crashes,
                 solver_solved=int(reg.counters.get(
                     "solver_solved", 0)),
                 solver_unknown=int(reg.counters.get(
                     "solver_unknown", 0)),
                 solver_injected=int(reg.counters.get(
                     "solver_injected", 0)))


def _descend_engine_sweep(prog, edges, seeds0, engine, *, lanes,
                          budget, scan_iters=8):
    """One engine's frontier sweep: per-edge warm pass (ONE
    minimal-budget descent — budget 1 for the host engine, budget
    ``scan_iters`` for the device engine so the scan shape the
    measured pass uses is the one compiled — primes the per-edge jit
    cache so the measurement is steady-state descent speed, the
    quantity the in-scan engine changes; the cold XLA compile
    constant is identical work amortized by the TPU tier's
    persistent compile cache) then the measured pass.  Returns
    (total_seconds, cracked_edges, cumulative (k, seconds) curve)."""
    from killerbeez_tpu.search import (
        descend_edge, descend_edge_device, seeds_reaching_block,
    )
    traces = {}
    seeds = list(seeds0)
    total = 0.0
    cracked = []
    cum = []
    for e in edges:
        se = seeds_reaching_block(prog, seeds, e[0], cap=24,
                                  trace_cache=traces) or seeds[:16]

        def run(b):
            if engine == "device":
                return descend_edge_device(
                    prog, e, se, lanes=lanes, budget=b,
                    scan_iters=scan_iters, trace_cache=traces)
            return descend_edge(prog, e, se, lanes=lanes, budget=b,
                                trace_cache=traces)

        # warm the per-edge jit cache: the device engine must warm
        # the scan length the measured pass uses (a budget of 1
        # would compile the 1-iteration tail shape instead)
        run(scan_iters if engine == "device" else 1)
        t0 = time.time()
        r = run(budget)
        total += time.time() - t0
        if r.status == "descended":
            cracked.append(e)
            seeds.append(r.input)
            cum.append((len(cracked), round(total, 3)))
    return total, cracked, cum


def _descend_engine_ab(gate, lanes=1024, budget=32, i2s_budget=16,
                       edge_cap=8):
    """Host-driven vs device-resident descent engine at EQUAL
    iteration budget over the solver-unknown frontier: the
    wall-clock-to-crack A/B (one warm pass per edge first — see
    _descend_engine_sweep), plus the input-to-state ablation on the
    operand-compare family.  Per family the device engine must crack
    at least as many edges AND deliver them in less wall-clock
    (strictly more cracks also passes: coverage dominates); one
    logged re-measure on CPU absorbs shared-runner noise (the PR 9
    guard).  Returns (ok, rows)."""
    import numpy as np
    from killerbeez_tpu.analysis.solver import solve_edge
    from killerbeez_tpu.models import targets as targets_mod
    from killerbeez_tpu.models import targets_cgc  # noqa: F401
    from killerbeez_tpu.search import descend_edge_device

    import jax
    on_tpu = jax.default_backend() == "tpu"
    rows = []
    ok = True
    fam_edges = {}
    fam_seeds = {}
    # imgparse's 32-edge frontier is capped (logged below); tlvstack
    # runs WHOLE — its first edges are the deep-exec exhausted ones,
    # and a prefix-only subset would misrepresent the family as
    # exhaust-dominated when most of its frontier cracks
    for tname, cap in (("imgparse_vm", edge_cap),
                       ("tlvstack_vm", 99),
                       ("magicsum_vm", 99)):
        prog = targets_mod.get_target(tname)
        universe = [(int(f), int(t)) for f, t in
                    zip(np.asarray(prog.edge_from),
                        np.asarray(prog.edge_to))]
        seeds, unknown = [], []
        for e in universe:
            r = solve_edge(prog, e)
            if r.status == "solved":
                seeds.append(r.input)
            elif r.status == "unknown":
                unknown.append(e)
        seeds = list(dict.fromkeys(seeds)) or [bytes(8)]
        edges = unknown[:cap]
        if len(unknown) > len(edges):
            print(f"  [descend-ab {tname}: measuring the first "
                  f"{len(edges)} of {len(unknown)} unknown edges "
                  f"(--descend edge cap)]", file=sys.stderr)
        fam_edges[tname] = edges
        fam_seeds[tname] = seeds

        def measure():
            out = {}
            for engine in ("host", "device"):
                prog_ = targets_mod.get_target(tname)
                out[engine] = _descend_engine_sweep(
                    prog_, edges, seeds, engine, lanes=lanes,
                    budget=budget)
            return out

        res = measure()
        retry = None

        # tlvstack's frontier is dominated by deep-exec exhausted
        # edges whose cost is the shared VM while_loop, not the host
        # round-trip this PR removes: on CPU the two engines measure
        # within a few percent of each other there (device won both
        # development runs, 64.4s vs 71.2s and 57.0s vs 57.7s, but
        # the margin is inside shared-runner noise).  Its wall clock
        # is REPORTED, and the gate holds crack-count parity; the
        # strict wall-clock gate rides the families where the claim
        # is the dominant term (imgparse: host python-gen + soft
        # re-tracing per iteration; magicsum: i2s iteration-count
        # collapse) per the ISSUE 15 acceptance wording.
        time_gated = tname != "tlvstack_vm"

        def fam_verdict(res):
            ht, hc, hcum = res["host"]
            dt, dc, dcum = res["device"]
            kstar = min(len(hc), len(dc))
            h_wtc = hcum[kstar - 1][1] if kstar else 0.0
            d_wtc = dcum[kstar - 1][1] if kstar else 0.0
            count_ok = len(dc) >= len(hc)
            time_ok = (len(dc) > len(hc)) or (kstar == 0) \
                or (d_wtc < h_wtc and dt < ht) or not time_gated
            return count_ok and time_ok, kstar, h_wtc, d_wtc

        fam_ok, kstar, h_wtc, d_wtc = fam_verdict(res)
        if gate and not fam_ok and not on_tpu:
            print(f"descend engine A/B on {tname} failed — "
                  "re-measuring both engines once (shared-runner "
                  "noise guard)", file=sys.stderr)
            res = measure()
            fam_ok, kstar, h_wtc, d_wtc = fam_verdict(res)
            retry = True
        ht, hc, hcum = res["host"]
        dt, dc, dcum = res["device"]
        row = emit(
            f"descend-ab-{tname}",
            f"host vs device descent engines on {tname} "
            f"(lanes {lanes}, budget {budget} iterations/edge, "
            f"warm-cache wall clock)",
            dt, unit="seconds",
            host_seconds=round(ht, 2),
            host_cracked=len(hc), device_cracked=len(dc),
            wall_clock_to_crack_k=kstar,
            host_wtc=round(h_wtc, 2), device_wtc=round(d_wtc, 2),
            device_cum=dcum, host_cum=hcum,
            gate_ok=fam_ok, retried=bool(retry))
        rows.append(row)
        ok = ok and fam_ok
        if gate and not fam_ok:
            print(f"FAIL: device descent engine did not beat the "
                  f"host engine on {tname} (host {len(hc)} cracks "
                  f"{ht:.1f}s vs device {len(dc)} cracks {dt:.1f}s)",
                  file=sys.stderr)

    # input-to-state ablation: equal budget, i2s lanes on vs off —
    # the operand-compare edge must separate them.  The ablation
    # budget stays BELOW the ~30+ iterations a coordinate walk needs
    # to carry a byte-granular descent across a 32-bit compare, so
    # the separation measures the mechanism, not patience
    prog = targets_mod.get_target("magicsum_vm")
    seeds = fam_seeds["magicsum_vm"]
    sep = {}
    for flag in (True, False):
        cracked = []
        traces = {}
        for e in fam_edges["magicsum_vm"]:
            r = descend_edge_device(prog, e, seeds, lanes=256,
                                    budget=i2s_budget, scan_iters=8,
                                    i2s=flag, trace_cache=traces)
            if r.status == "descended":
                cracked.append(e)
        sep[flag] = cracked
    only_i2s = [e for e in sep[True] if e not in sep[False]]
    i2s_ok = bool(only_i2s)
    rows.append(emit(
        "descend-i2s-ablation",
        "device engine i2s-on vs i2s-off at equal budget "
        "(magicsum_vm operand-compare family)",
        float(len(only_i2s)), unit="edges",
        i2s_on_cracked=[list(e) for e in sep[True]],
        i2s_off_cracked=[list(e) for e in sep[False]],
        only_i2s=[list(e) for e in only_i2s], gate_ok=i2s_ok))
    if gate and not i2s_ok:
        print("FAIL: input-to-state matching cracked no edge the "
              "probe families alone left uncracked at equal budget",
              file=sys.stderr)
    return ok and i2s_ok, rows


def bench_descend(targets=None, batch=256, budget_execs=65536,
                  plateau=4, chunk_batches=8, descend_budget=16,
                  descend_lanes=256, gate=False):
    """--descend: gradient-search A/B lane.  Three sections:

    1. the blind-seed campaign A/B (crack-only vs crack+descend) on
       the CHECKSUM universes (imgparse/tlvstack) where the exact
       solver's ceiling is known (36/68 and 173/186 static edges) —
       static-EDGE coverage must exceed the solver ceiling;
    2. the ENGINE A/B (ISSUE 15): host-driven vs device-resident
       descent at equal iteration budget over the solver-unknown
       frontier — warm-cache wall-clock-to-crack must drop;
    3. the input-to-state ablation on magicsum_vm — i2s must crack
       >= 1 operand-compare edge the probe families alone left
       uncracked at equal budget.

    ``gate=True`` exits nonzero unless all three hold.  Artifact:
    bench_out/BENCH_descend.json.
    """
    import json as _json
    import shutil
    import numpy as np
    from killerbeez_tpu.drivers.factory import driver_factory
    from killerbeez_tpu.fuzzer.crack import BranchCracker
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.models import targets as targets_mod
    from killerbeez_tpu.models import targets_cgc  # noqa: F401
    from killerbeez_tpu.mutators.factory import mutator_factory

    #: the exact solver's ceiling per target (solved edges; PR 4) —
    #: the descend lane must END ABOVE it
    floors = {"imgparse_vm": 36, "tlvstack_vm": 173}
    ok = True
    rows = []
    for target in (targets or ("imgparse_vm", "tlvstack_vm")):
        prog = targets_mod.get_target(target)
        slots = np.asarray(prog.edge_slot)
        for mode in ("crack", "descend"):
            instr = instrumentation_factory(
                "jit_harness", _json.dumps(
                    {"target": target, "novelty": "throughput"}))
            mut = mutator_factory("havoc", '{"seed": 11}',
                                  b"\x00" * 8)
            drv = driver_factory("file", None, instr, mut)
            out = os.path.join(REPO, "bench_out",
                               f"descend_{target}_{mode}")
            shutil.rmtree(out, ignore_errors=True)
            fz = Fuzzer(drv, output_dir=out, batch_size=batch,
                        write_findings=False)
            # crank the per-crack caps: the lane's job is sweeping a
            # whole static universe within a bounded exec budget, not
            # bounding a live campaign's pause
            fz.cracker = BranchCracker(
                prog, plateau_batches=plateau,
                descend=(descend_budget if mode == "descend" else 0),
                descend_lanes=descend_lanes,
                max_solves=512, max_descends=8)
            t0 = time.time()
            while fz.stats.iterations < budget_execs:
                fz.run(fz.stats.iterations + chunk_batches * batch)
            dt = time.time() - t0
            vb = np.asarray(instr.virgin_bits)
            covered = set(np.flatnonzero(vb != 0xFF).tolist())
            edges_covered = int(sum(1 for s in slots
                                    if int(s) in covered))
            reg = fz.telemetry.registry
            rows.append(emit(
                 f"descend-{mode}",
                 f"gradient-search {mode} on {target} (-b {batch}, "
                 f"plateau {plateau}, blind 8-byte seed)",
                 fz.stats.iterations / dt if dt else 0.0,
                 target=target,
                 edges_covered=edges_covered,
                 edges_total=int(prog.n_edges),
                 solver_ceiling=floors.get(target),
                 execs=fz.stats.iterations,
                 crashes=fz.stats.crashes,
                 solver_solved=int(reg.counters.get(
                     "solver_solved", 0)),
                 search_attempts=int(reg.counters.get(
                     "search_attempts", 0)),
                 search_descended=int(reg.counters.get(
                     "search_descended", 0)),
                 search_i2s_matches=int(reg.counters.get(
                     "search_i2s_matches", 0)),
                 search_exhausted=int(reg.counters.get(
                     "search_exhausted", 0))))
            if mode == "descend" and target in floors \
                    and edges_covered <= floors[target]:
                print(f"FAIL: {target} descend lane covered "
                      f"{edges_covered} static edges <= solver "
                      f"ceiling {floors[target]}", file=sys.stderr)
                ok = False

    engines_ok, ab_rows = _descend_engine_ab(gate, lanes=1024)
    rows.extend(ab_rows)
    ok = ok and engines_ok
    os.makedirs(os.path.join(REPO, "bench_out"), exist_ok=True)
    with open(os.path.join(REPO, "bench_out",
                           "BENCH_descend.json"), "w") as f:
        _json.dump({"rows": rows, "gate_ok": ok}, f, indent=1)
    return 0 if (ok or not gate) else 1


def bench_stateful(targets=None, batch=512, execs=16384, gate=False):
    """--stateful A/B lane: single-shot fuzzing vs sequence fuzzing
    on the stateful target families (models/targets_stateful.py).

    Both lanes run jit_harness + havoc from the SAME framed seed
    bytes for the same exec budget; the single-shot lane executes
    each candidate as one stateless buffer (the pre-session-tier
    semantics), the sequence lane as a framed session with state x
    edge novelty.  The metric that matters is DEEP-STATE EDGES
    CRACKED: edges into blocks that are provably unreachable by any
    single message —

      * dataflow proof: dead under single-shot constant propagation
        (``deep_state_blocks``; r7 and memory are 0 at every
        dispatch, so the state guards fold shut), and
      * solver confirmation: ``solve_edge`` exhaustively refutes
        every candidate path with zero satisfiable paths (status
        unsat, or the bounded-input-model ``unknown`` with
        paths_tried == 0 — the solver's honest spelling of "refuted
        within the model").

    ``--gate``: the sequence lane must crack >= 1 deep-state edge on
    EVERY family while the single-shot lane cracks 0 (it cannot, by
    the proof above — a nonzero count here means the proof or the
    tier is broken).  Deep-edge coverage is read from collision-free
    AFL slots (slots a deep edge shares with a shallow edge are
    excluded from the count).  Artifact: bench_out/
    BENCH_stateful.json."""
    import json as _json
    import shutil
    import numpy as np
    from killerbeez_tpu.analysis.solver import solve_edge, unknown_kind
    from killerbeez_tpu.drivers.factory import driver_factory
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.models import targets_stateful as ts
    from killerbeez_tpu.models.targets import get_target
    from killerbeez_tpu.mutators.factory import mutator_factory

    rows = []
    ok = True
    for target in (targets or ts.stateful_target_names()):
        prog = get_target(target)
        seed = ts.framed_seed(target)
        deep_blocks = ts.deep_state_blocks(prog)
        deep_edges = ts.deep_state_edges(prog)
        ef = np.asarray(prog.edge_from)
        et = np.asarray(prog.edge_to)
        slots = np.asarray(prog.edge_slot)
        deep_set = set(deep_edges)
        shallow_slots = {int(slots[e]) for e in range(len(et))
                         if e not in deep_set}
        deep_slots = sorted({int(slots[e]) for e in deep_edges}
                            - shallow_slots)

        # the static certificate: every deep edge refuted single-shot
        refuted = 0
        for e in deep_edges:
            r = solve_edge(prog, (int(ef[e]), int(et[e])))
            if r.status == "unsat" or (
                    r.status == "unknown" and r.paths_tried == 0
                    and unknown_kind(r.reason) == "model"):
                refuted += 1
        proof_ok = refuted == len(deep_edges) and len(deep_slots) > 0
        rows.append(emit(
            "stateful-proof",
            f"{target}: {len(deep_blocks)} deep blocks / "
            f"{len(deep_edges)} deep edges provably single-shot-"
            f"unreachable (constprop-dead + solver-refuted)",
            refuted, unit="edges_refuted", target=target,
            deep_edges=len(deep_edges),
            deep_slots=len(deep_slots), proof_ok=proof_ok))
        if not proof_ok:
            ok = False

        def run_lane(stateful):
            iopts = {"target": target, "novelty": "throughput"}
            if stateful:
                iopts["stateful"] = 1
            instr = instrumentation_factory("jit_harness",
                                            _json.dumps(iopts))
            mut = mutator_factory("havoc", '{"seed": 7}', seed)
            drv = driver_factory("file", None, instr, mut)
            out = os.path.join(REPO, "bench_out",
                               f"stateful_{target}_"
                               f"{'seq' if stateful else 'single'}")
            shutil.rmtree(out, ignore_errors=True)
            fz = Fuzzer(drv, output_dir=out, batch_size=batch,
                        write_findings=False, feedback=8)
            t0 = time.time()
            stats = fz.run(execs)
            dt = max(time.time() - t0, 1e-9)
            vb = np.asarray(instr.virgin_bits)
            deep_hit = sum(1 for s in deep_slots if vb[s] != 0xFF)
            extra = {}
            st = instr.state_coverage_stats()
            if st is not None:
                extra = {"state_pairs": st[0], "states_seen": st[1]}
            return (stats, stats.iterations / dt, deep_hit, extra)

        sA, rateA, deepA, _ = run_lane(False)
        rows.append(emit(
            "stateful-single",
            f"single-shot fuzzing on {target} (framed seed as one "
            f"stateless buffer, -b {batch}, {execs} execs)", rateA,
            target=target, deep_edges_hit=deepA,
            new_paths=sA.new_paths, crashes=sA.crashes))
        sB, rateB, deepB, extraB = run_lane(True)
        rows.append(emit(
            "stateful-seq",
            f"sequence fuzzing on {target} (session tier, state x "
            f"edge novelty, -b {batch}, {execs} execs)", rateB,
            target=target, deep_edges_hit=deepB,
            new_paths=sB.new_paths, crashes=sB.crashes, **extraB))
        if deepA != 0:
            print(f"FAIL: {target} single-shot lane hit {deepA} "
                  f"deep-state edges — the unreachability proof is "
                  f"broken", file=sys.stderr)
            ok = False
        if deepB < 1:
            print(f"FAIL: {target} sequence lane cracked "
                  f"{deepB} deep-state edges (need >= 1)",
                  file=sys.stderr)
            ok = False
    os.makedirs(os.path.join(REPO, "bench_out"), exist_ok=True)
    with open(os.path.join(REPO, "bench_out",
                           "BENCH_stateful.json"), "w") as f:
        json.dump({"rows": rows, "ok": ok}, f, indent=1)
    if gate and not ok:
        return 1
    return 0


def bench_grammar(names=None, batch=512, execs=16384, g=4,
                  gate=False):
    """--grammar A/B lane: blind havoc vs grammar-structured havoc on
    the generated target zoo's gated instances (models/zoo.py).

    Both lanes run the device-resident generation loop (-G) with
    jit_harness + havoc from the SAME benign seed for the same exec
    budget; the structured lane additionally threads the family's
    compiled grammar tables through the scan (instrumentation option
    ``grammar``), which protects literal/length fields and
    substitutes command tokens from the field's alphabet.  The metric
    is the CERTIFIED DEEP EDGE: the planted bug's single verdict
    branch, certified at generation time (kb-zoo certify doctrine) as
    crash-coincident, benign-seed-missed and witness-reached.

    The zoo families leak NO incremental coverage toward the trigger
    (one fused verdict register, one branch into the win block), so
    blind havoc must jackpot the whole multi-byte command token while
    holding the header intact; the structured lane reaches it with
    ONE token substitution.  ``--gate``: every gated instance must
    certify, the structured lane must crack its deep edge, and the
    blind lane must crack none.  Deep-edge coverage is read from the
    collision-free AFL slot (an instance whose deep edge shares a
    slot with a shallow edge would be excluded — the generators are
    built so it never is).  Artifact: bench_out/BENCH_grammar.json."""
    import json as _json
    import shutil
    import numpy as np
    from killerbeez_tpu.drivers.factory import driver_factory
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.models.zoo import (
        GATED_NAMES, build_zoo, certify_zoo,
    )
    from killerbeez_tpu.mutators.factory import mutator_factory

    rows = []
    ok = True
    for name in (names or GATED_NAMES):
        t = build_zoo(name)
        report = certify_zoo(name)
        if not report["certified"]:
            print(f"FAIL: {name} does not certify: {report}",
                  file=sys.stderr)
            ok = False
        rows.append(emit(
            "zoo-certify",
            f"{name}: planted deep edge {tuple(t.deep_edge)} "
            f"(lint clean, benign seed misses, witness crashes "
            f"through; solver {report['solver']})",
            int(report["certified"]), unit="certified", target=name,
            solver=report["solver"]))

        ef = np.asarray(t.program.edge_from)
        et = np.asarray(t.program.edge_to)
        slots = np.asarray(t.program.edge_slot)
        deep_idx = [e for e in range(len(et))
                    if (int(ef[e]), int(et[e])) == t.deep_edge]
        other = {int(slots[e]) for e in range(len(et))
                 if e not in deep_idx}
        deep_slots = sorted({int(slots[e]) for e in deep_idx}
                            - other)
        if not deep_slots:
            print(f"FAIL: {name} deep edge has no collision-free "
                  f"AFL slot", file=sys.stderr)
            ok = False
            continue

        safe = name.replace(":", "_").replace(",", "_") \
                   .replace("=", "")

        def run_lane(structured):
            iopts = {"target": name, "novelty": "throughput"}
            if structured:
                iopts["grammar"] = t.grammar.to_json()
            instr = instrumentation_factory("jit_harness",
                                            _json.dumps(iopts))
            mut = mutator_factory("havoc", '{"seed": 7}', t.seed)
            drv = driver_factory("file", None, instr, mut)
            out = os.path.join(
                REPO, "bench_out",
                f"grammar_{safe}_"
                f"{'structured' if structured else 'blind'}")
            shutil.rmtree(out, ignore_errors=True)
            fz = Fuzzer(drv, output_dir=out, batch_size=batch,
                        write_findings=False, generations=g,
                        feedback=0)
            t0 = time.time()
            stats = fz.run(execs)
            dt = max(time.time() - t0, 1e-9)
            vb = np.asarray(instr.virgin_bits)
            deep_hit = sum(1 for s in deep_slots if vb[s] != 0xFF)
            return stats, stats.iterations / dt, deep_hit

        sA, rateA, deepA = run_lane(False)
        rows.append(emit(
            "grammar-blind",
            f"blind havoc on {name} (-b {batch} -G {g}, {execs} "
            f"execs, feedback off)", rateA, target=name,
            deep_edges_hit=deepA, new_paths=sA.new_paths,
            crashes=sA.crashes))
        sB, rateB, deepB = run_lane(True)
        rows.append(emit(
            "grammar-structured",
            f"grammar-structured havoc on {name} (-b {batch} -G {g}, "
            f"{execs} execs, feedback off)", rateB, target=name,
            deep_edges_hit=deepB, new_paths=sB.new_paths,
            crashes=sB.crashes))
        if deepA != 0:
            print(f"FAIL: {name} blind lane hit the deep edge "
                  f"({deepA}) — the family is not blind-hostile at "
                  f"this budget", file=sys.stderr)
            ok = False
        if deepB < 1:
            print(f"FAIL: {name} structured lane missed the deep "
                  f"edge (need >= 1)", file=sys.stderr)
            ok = False
    os.makedirs(os.path.join(REPO, "bench_out"), exist_ok=True)
    with open(os.path.join(REPO, "bench_out",
                           "BENCH_grammar.json"), "w") as f:
        json.dump({"rows": rows, "ok": ok}, f, indent=1)
    if gate and not ok:
        return 1
    return 0


def bench_hybrid(batch=256, execs=65536, gate=False):
    """--hybrid A/B lane: the hybrid campaign (TPU proxy coverage
    guidance + cross-tier native confirmation, docs/HYBRID.md) vs the
    native tier ALONE at equal wall clock, on the test/test-plain
    proxy/native pair.

    Lane B (hybrid): a coverage-guided proxy campaign on the ``test``
    KBVM target with the ``test`` binding attached — every unique
    proxy crash is replayed on corpus/build/test-plain and must come
    back ``confirmed``.  Lane A (native alone): blind havoc straight
    on the native binary for the SAME wall clock lane B took — the
    only campaign mode the native tier has by itself here (no
    coverage map without the KBVM proxy).  The 4-byte "ABCD" magic is
    trivial for coverage guidance and a ~2^-24 lottery per blind
    exec, so the A/B isolates what the hybrid bridge buys: native
    ground truth at proxy discovery speed.

    ``--gate``: lane B must record >= 1 native-CONFIRMED crash and
    lane A must find 0.  Degrades to a {"skipped": ...} row (exit 0)
    when the host toolchain is unavailable.  Artifact:
    bench_out/BENCH_hybrid.json."""
    import json as _json
    import random
    import shutil
    from killerbeez_tpu import FUZZ_CRASH
    from killerbeez_tpu.native.exec_backend import classify

    os.makedirs(os.path.join(REPO, "bench_out"), exist_ok=True)
    art = os.path.join(REPO, "bench_out", "BENCH_hybrid.json")
    if not build_corpus():
        row = emit("hybrid-skip",
                   "hybrid A/B skipped: native toolchain / corpus "
                   "build unavailable", 0.0, unit="skipped",
                   skipped="native build unavailable")
        with open(art, "w") as f:
            json.dump({"rows": [row], "ok": None,
                       "skipped": "native build unavailable"}, f,
                      indent=1)
        return 0

    from killerbeez_tpu.drivers.factory import driver_factory
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    from killerbeez_tpu.hybrid import make_bridge
    from killerbeez_tpu.hybrid.registry import open_native
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.mutators.factory import mutator_factory

    rows = []
    seed = b"AAAA"

    # lane B: hybrid — proxy coverage guidance + native confirmation
    bridge = make_bridge("test", repeats=3, queue_cap=64, workers=1)
    instr = instrumentation_factory(
        "jit_harness", _json.dumps({"target": "test",
                                    "novelty": "throughput"}))
    mut = mutator_factory("havoc", '{"seed": 7}', seed)
    drv = driver_factory("file", None, instr, mut)
    out = os.path.join(REPO, "bench_out", "hybrid_ab")
    shutil.rmtree(out, ignore_errors=True)
    fz = Fuzzer(drv, output_dir=out, batch_size=batch,
                write_findings=False, feedback=8, hybrid=bridge)
    t0 = time.time()
    stats = fz.run(execs)
    t_hybrid = max(time.time() - t0, 1e-9)
    c = fz.telemetry.registry.snapshot()["counters"]
    confirmed = int(c.get("hybrid_confirmed", 0))
    rows.append(emit(
        "hybrid-campaign",
        f"hybrid campaign on test/test-plain (-b {batch}, {execs} "
        f"proxy execs + native confirmation)",
        stats.iterations / t_hybrid,
        proxy_crashes=stats.crashes,
        validated=int(c.get("hybrid_validations", 0)),
        confirmed=confirmed,
        proxy_only=int(c.get("hybrid_proxy_only", 0)),
        flaky=int(c.get("hybrid_flaky", 0)),
        native_execs=bridge.native_execs,
        wall_s=round(t_hybrid, 2)))

    # lane A: native alone — blind havoc for the same wall clock
    target = open_native(bridge.binding.native)
    rng = random.Random(7)
    n_execs = 0
    native_crashes = 0
    t0 = time.time()
    try:
        while time.time() - t0 < t_hybrid:
            buf = bytearray(seed)
            for _ in range(rng.randint(1, 4)):
                buf[rng.randrange(len(buf))] = rng.randrange(256)
            kind, _ = classify(target.run(bytes(buf)))
            n_execs += 1
            if kind == FUZZ_CRASH:
                native_crashes += 1
    finally:
        target.close()
    t_native = max(time.time() - t0, 1e-9)
    rows.append(emit(
        "hybrid-native-alone",
        f"native tier alone: blind havoc on test-plain for "
        f"{t_hybrid:.1f}s (equal wall clock)",
        n_execs / t_native, crashes=native_crashes,
        execs=n_execs, wall_s=round(t_native, 2)))

    ok = confirmed >= 1 and native_crashes == 0
    if confirmed < 1:
        print("FAIL: hybrid lane recorded no native-confirmed crash",
              file=sys.stderr)
    if native_crashes != 0:
        print(f"FAIL: blind native lane found {native_crashes} "
              f"crashes — the A/B no longer isolates coverage "
              f"guidance", file=sys.stderr)
    with open(art, "w") as f:
        json.dump({"rows": rows, "ok": ok}, f, indent=1)
    if gate and not ok:
        return 1
    return 0


def bench_repair(gate=False):
    """--repair lane: the counterexample-guided conformance pipeline
    (docs/ANALYSIS.md 'Conformance & repair') end-to-end against the
    built-in test⇄hybrid-safe semantic gap, plus the honesty-contract
    negative: an out-of-model gap must come back ``unrepairable``.

    Positive: probe the ``test_safe`` binding (native tier never
    crashes, proxy keeps the full ABCD magic) to mint real
    kbz-proxy-gap-v1 counterexamples, then ``run_repair`` must (a)
    localize the divergence to the actual differing guard — the
    branch whose guarding constant is the 'D' byte, found from
    dataflow, not hardcoded — and (b) emit a patch verified
    verdict-identical to native on every gap input and both
    certification seeds.  Negative: a gap claiming the loop-free
    ``test`` proxy should HANG has no patch in the typed space, so
    the verdict must be ``unrepairable`` with a machine-readable
    reason, never a silent best-effort patch.

    ``--gate`` exits nonzero on any miss.  Degrades to a
    {"skipped": ...} row (exit 0) when the host toolchain is
    unavailable.  Artifact: bench_out/BENCH_repair.json."""
    import hashlib
    import shutil
    from killerbeez_tpu import FUZZ_HANG

    os.makedirs(os.path.join(REPO, "bench_out"), exist_ok=True)
    art = os.path.join(REPO, "bench_out", "BENCH_repair.json")
    if not build_corpus():
        row = emit("repair-skip",
                   "conformance repair lane skipped: native "
                   "toolchain / corpus build unavailable", 0.0,
                   unit="skipped",
                   skipped="native build unavailable")
        with open(art, "w") as f:
            json.dump({"rows": [row], "ok": None,
                       "skipped": "native build unavailable"}, f,
                      indent=1)
        return 0

    from killerbeez_tpu.analysis.dataflow import analyze_dataflow
    from killerbeez_tpu.analysis.repair import run_repair
    from killerbeez_tpu.hybrid.gaps import GapIndex, make_gap_report
    from killerbeez_tpu.hybrid.registry import get_binding
    from killerbeez_tpu.tools.repair_tool import _probe

    rows = []
    ok = True

    # positive lane: the controlled test⇄hybrid-safe gap
    binding = get_binding("test_safe")
    gaps_dir = os.path.join(REPO, "bench_out", "repair_gaps")
    shutil.rmtree(gaps_dir, ignore_errors=True)
    t0 = time.time()
    n_gaps = _probe(binding, gaps_dir, repeats=3)
    result, patched = run_repair(binding, gaps_dir)
    wall = max(time.time() - t0, 1e-9)

    # the actual differing guard: hybrid-safe drops the final 'D'
    # check, so blame must land on the branch whose guarding
    # constant is ord('D') — looked up from dataflow, not pinned
    program = binding.program()
    want_pcs = {f.pc for f in analyze_dataflow(program).branches
                if f.const == ord("D")}
    blamed = [c.get("blame", {}).get("pc")
              for c in result.get("clusters") or []]
    localized = any(pc in want_pcs for pc in blamed)
    repaired = result.get("status") == "repaired" and \
        patched is not None
    if n_gaps < 1:
        ok = False
        print("FAIL: probe minted no proxy-gap reports",
              file=sys.stderr)
    if not repaired:
        ok = False
        print(f"FAIL: repair verdict {result.get('status')!r} "
              f"({result.get('reason')!r}) — expected repaired",
              file=sys.stderr)
    if not localized:
        ok = False
        print(f"FAIL: blame {blamed} missed the differing guard "
              f"{sorted(want_pcs)}", file=sys.stderr)
    rows.append(emit(
        "repair-gap-corpus",
        "probe test_safe gap corpus + counterexample-guided repair",
        n_gaps / wall, unit="gaps/sec", gaps=n_gaps,
        status=result.get("status"), blamed=blamed,
        want_pcs=sorted(want_pcs),
        patches=result.get("patches"), wall_s=round(wall, 2)))

    # negative lane: an out-of-model gap (native claims the
    # loop-free proxy hangs) must be honestly unrepairable
    oom_dir = os.path.join(REPO, "bench_out", "repair_gaps_oom")
    shutil.rmtree(oom_dir, ignore_errors=True)
    faithful = get_binding("test")
    buf = b"zzzz"
    idx = GapIndex(oom_dir)
    idx.admit(make_gap_report(
        md5=hashlib.md5(buf).hexdigest(), kind="crash",
        binding=faithful.name, proxy_target=faithful.proxy_target,
        proxy_status=2, native_argv=["bench"],
        native_delivery="stdin",
        statuses=[FUZZ_HANG] * 3, repro=3, repeats=3,
        t=1.0, input_bytes=buf))
    oom, oom_patched = run_repair(faithful, oom_dir)
    honest = oom.get("status") == "unrepairable" and \
        oom_patched is None and bool(oom.get("reason"))
    if not honest:
        ok = False
        print(f"FAIL: out-of-model gap got {oom.get('status')!r} "
              f"({oom.get('reason')!r}) — expected an honest "
              f"unrepairable", file=sys.stderr)
    rows.append(emit(
        "repair-out-of-model",
        "out-of-model gap (native hang on loop-free proxy) stays "
        "unrepairable", 1.0 if honest else 0.0, unit="honest",
        status=oom.get("status"), reason=oom.get("reason")))

    with open(art, "w") as f:
        json.dump({"rows": rows, "ok": ok}, f, indent=1)
    if gate and not ok:
        return 1
    return 0


#: the --vsa gate universe and the uplift requirement: strictly more
#: solved static edges than the plain solver on at least this many
#: of the targets, zero per-edge regressions anywhere
VSA_GATE_TARGETS = ("imgparse_vm", "rledec_vm", "tlvstack_vm")
VSA_GATE_MIN_UPLIFTED = 2


def bench_vsa(gate=False):
    """--vsa lane: the value-set solver-uplift gate (ISSUE 19).

    For every static edge of each gate target, solve with the plain
    solver and with VSA seeding + the visit-cap escalation ladder,
    both at DEFAULT budgets.  The gate requires:

      * zero regressions — no edge's verdict rank drops
        (solved > unsat > unknown) under --vsa;
      * strictly more solved edges on >= VSA_GATE_MIN_UPLIFTED
        targets;
      * every newly-solved edge's witness INDEPENDENTLY re-verified
        here by concrete replay (the synthesized input must walk the
        edge — not just trusted from the solver's own check);
      * every newly-unsat edge carrying an exhaustive-refutation
        certificate (caps unhit at the refuting rung).

    Artifact: bench_out/BENCH_vsa.json."""
    import numpy as np

    from killerbeez_tpu.analysis.dataflow import analyze_dataflow
    from killerbeez_tpu.analysis.solver import (
        concrete_run, solve_edge, solve_edge_vsa,
    )
    from killerbeez_tpu.analysis.vsa import analyze_vsa
    from killerbeez_tpu.models.targets import get_target
    from killerbeez_tpu.models import targets_cgc  # noqa: F401

    os.makedirs(os.path.join(REPO, "bench_out"), exist_ok=True)
    art = os.path.join(REPO, "bench_out", "BENCH_vsa.json")
    rank = {"solved": 2, "unsat": 1, "unknown": 0}
    rows = []
    ok = True
    uplifted = 0
    for name in VSA_GATE_TARGETS:
        program = get_target(name)
        edges = sorted(
            (int(f), int(t)) for f, t in
            zip(np.asarray(program.edge_from),
                np.asarray(program.edge_to)))
        df = analyze_dataflow(program)
        t0 = time.time()
        vsa = analyze_vsa(program)
        fixpoint_s = time.time() - t0
        base_n = {"solved": 0, "unsat": 0, "unknown": 0}
        vsa_n = {"solved": 0, "unsat": 0, "unknown": 0}
        regressions, unverified, uncertified = [], [], []
        t0 = time.time()
        for e in edges:
            b = solve_edge(program, e)
            v = solve_edge_vsa(program, e, vsa=vsa, dataflow=df)
            base_n[b.status] += 1
            vsa_n[v.status] += 1
            key = f"{e[0]}:{e[1]}"
            if rank[v.status] < rank[b.status]:
                regressions.append(key)
            if v.status == "solved" and b.status != "solved":
                if e not in concrete_run(program, v.input).edges:
                    unverified.append(key)
            if v.status == "unsat" and b.status != "unsat":
                cert = (v.vsa or {}).get("certificate")
                if not (cert and cert.get("exhaustive")):
                    uncertified.append(key)
        wall = max(time.time() - t0, 1e-9)
        up = vsa_n["solved"] > base_n["solved"]
        uplifted += up
        if regressions:
            ok = False
            print(f"FAIL: {name} verdicts regressed under --vsa: "
                  f"{regressions}", file=sys.stderr)
        if unverified:
            ok = False
            print(f"FAIL: {name} newly-solved witnesses failed "
                  f"replay: {unverified}", file=sys.stderr)
        if uncertified:
            ok = False
            print(f"FAIL: {name} newly-unsat edges lack exhaustive "
                  f"certificates: {uncertified}", file=sys.stderr)
        rows.append(emit(
            f"vsa-{name}",
            f"plain vs VSA-seeded solver over {len(edges)} static "
            f"edges at default budgets",
            len(edges) / wall, unit="edges/sec",
            base=base_n, vsa=vsa_n, uplift=up,
            regressions=regressions,
            fixpoint_s=round(fixpoint_s, 3),
            n_branch_facts=len(vsa.branches),
            wall_s=round(wall, 2)))
    if uplifted < VSA_GATE_MIN_UPLIFTED:
        ok = False
        print(f"FAIL: VSA uplift on {uplifted} target(s) < required "
              f"{VSA_GATE_MIN_UPLIFTED} of {len(VSA_GATE_TARGETS)}",
              file=sys.stderr)
    rows.append(emit(
        "vsa-summary",
        f"targets with strictly more solved edges under --vsa "
        f"(need >= {VSA_GATE_MIN_UPLIFTED})",
        float(uplifted), unit="targets", ok=ok))
    with open(art, "w") as f:
        json.dump({"rows": rows, "ok": ok}, f, indent=1)
    if gate and not ok:
        return 1
    return 0


BENCH_R05_GATE = 1807549.5   # BENCH_r05 headline: execs/s/chip,
#                              fused-pallas superbatch on tlvstack_vm


def bench_generations(target="tlvstack_vm", batch=65536, steps=32,
                      gs=(4, 16, 64), engine="pallas_fused",
                      gate=False):
    """--generations A/B lane: the host-driven superbatch CLI loop vs
    the device-resident generation loop (ops/generations.py) at
    G in ``gs``, same target/batch/engine/exec budget.

    Emits one JSON row per config plus a summary row, and writes a
    BENCH_r06-style artifact to bench_out/BENCH_generations.json.
    ``gate=True`` exits nonzero unless (a) the best device-resident
    config beats the host-driven baseline measured in the same
    session, and (b) on TPU hardware, it strictly exceeds BENCH_r05's
    1 807 549 execs/s/chip absolute number (the ISSUE 9 acceptance
    bar; skipped with a named reason on CPU, where the absolute
    number is unreachable by construction and the relative A/B is
    the honest signal).

    BOTH lanes run with corpus feedback pinned OFF: that makes the
    candidate streams bit-identical (the --generations determinism
    contract), so the A/B measures exactly what the mode claims —
    eliminating the per-batch host round-trip — and nothing else.
    With feedback on the comparison confounds loop overhead with a
    *seed-depth* difference: the device ring mutates a novelty-
    admitted (deeper) seed almost every generation while the host
    bandit rotates lazily, and batch wall time follows the deepest
    lane (the engines early-exit when every lane halts), so execs/s
    shifts for reasons that are corpus policy, not dispatch cost
    (docs/GENERATIONS.md)."""
    import shutil
    import json as _json
    import jax
    from killerbeez_tpu.drivers.factory import driver_factory
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.models import targets_cgc
    from killerbeez_tpu.mutators.factory import mutator_factory

    seed = targets_cgc.tlvstack_vm_seed() if target == "tlvstack_vm" \
        else targets_cgc.imgparse_vm_seed()
    on_tpu = jax.devices()[0].platform == "tpu"
    rows = []

    v_host, st, fz = bench_cli_product(target, batch, steps, seed,
                                       out_name="gen_base",
                                       engine=engine, feedback=0)
    rows.append(emit(
        "gen-host",
        f"host-driven superbatch baseline ({target}, -b {batch}, "
        f"{steps} steps, {engine}, feedback off)", v_host,
        new_paths=st.new_paths, stage_split=stage_split_row(fz)))

    def run_gen(g):
        instr = instrumentation_factory(
            "jit_harness", _json.dumps({
                "target": target, "engine": engine,
                "novelty": "throughput"}))
        mut = mutator_factory("havoc", '{"seed": 3}', seed)
        drv = driver_factory("file", None, instr, mut)
        out = os.path.join(REPO, "bench_out", f"gen_{g}")
        shutil.rmtree(out, ignore_errors=True)
        fz = Fuzzer(drv, output_dir=out, batch_size=batch,
                    generations=g, feedback=0)
        # warmup covers compile + the steady dispatch shape; the
        # timed window then runs >= 2 full-G dispatches
        fz.run(2 * g * batch)
        done = fz.stats.iterations
        steps_eff = max(steps, 2 * g)
        t0 = time.time()
        fz.run(done + batch * steps_eff)
        dt = time.time() - t0
        return (fz.stats.iterations - done) / dt, fz

    best = (0.0, 0)
    for g in gs:
        v, fz = run_gen(g)
        reg = fz.telemetry.registry
        rows.append(emit(
            f"gen-G{g}",
            f"device-resident generations G={g} ({target}, "
            f"-b {batch}, {engine}, feedback off)", v,
            speedup_vs_host=round(v / v_host, 3) if v_host else None,
            new_paths=fz.stats.new_paths,
            ring_filled=int(reg.gauges.get("gen_ring_filled", 0)),
            findings_ring_drops=int(reg.counters.get(
                "findings_ring_drops", 0)),
            stage_split=stage_split_row(fz)))
        if v > best[0]:
            best = (v, g)

    rel_ok = best[0] > v_host
    retry = None
    if gate and not rel_ok and not on_tpu:
        # a short wall-clock A/B on a shared CI runner can invert on
        # noisy-neighbor contention alone: re-measure BOTH lanes once
        # and gate on the fresh pair.  A genuine regression fails
        # both rounds; the retry is recorded in the artifact, never
        # silent.
        print("generations gate: relative A/B failed — re-measuring "
              "both lanes once (shared-runner noise guard)",
              file=sys.stderr)
        v_host2, _, _ = bench_cli_product(
            target, batch, steps, seed, out_name="gen_base_retry",
            engine=engine, feedback=0)
        v2, _ = run_gen(best[1])
        retry = {"host": round(v_host2, 1), "gen": round(v2, 1),
                 "speedup_vs_host": round(v2 / v_host2, 3)
                 if v_host2 else None}
        rel_ok = v2 > v_host2
    abs_ok = best[0] > BENCH_R05_GATE if on_tpu else None
    summary = {
        "metric": f"execs/sec/chip on {target} (device-resident "
                  f"generation loop, best G={best[1]}, {engine})",
        "value": round(best[0], 1),
        "unit": "execs/sec",
        "host_baseline": round(v_host, 1),
        "speedup_vs_host": round(best[0] / v_host, 3)
        if v_host else None,
        "gate_relative_ok": rel_ok,
        "gate_absolute": BENCH_R05_GATE,
        "gate_absolute_ok": abs_ok if on_tpu else
        "skipped: CPU backend (absolute bar is a TPU number; "
        "relative A/B gates here)",
    }
    if retry is not None:
        summary["retry"] = retry
    print(json.dumps(summary), flush=True)
    os.makedirs(os.path.join(REPO, "bench_out"), exist_ok=True)
    with open(os.path.join(REPO, "bench_out",
                           "BENCH_generations.json"), "w") as f:
        json.dump({"rows": rows, "parsed": summary}, f, indent=1)
    if gate:
        if not rel_ok:
            print(f"FAIL: best device-resident config "
                  f"({best[0]:.0f} execs/s, G={best[1]}) did not "
                  f"beat the host-driven baseline ({v_host:.0f})",
                  file=sys.stderr)
            return 1
        if on_tpu and not abs_ok:
            print(f"FAIL: device-resident loop {best[0]:.0f} "
                  f"execs/s/chip <= BENCH_r05 gate "
                  f"{BENCH_R05_GATE:.0f}", file=sys.stderr)
            return 1
    return 0


def bench_mesh_generations(target="tlvstack_vm", batch=2048, steps=8,
                           gs=(4, 16, 64), engine=None,
                           mesh_spec="4,2", gate=False):
    """--generations --mesh A/B lane: the host-driven mesh loop
    (per-batch dispatch + ICI folds) vs the mesh-resident generation
    scan (shard_map'd ops/generations with in-scan dp folds) at G in
    ``gs`` on the same (dp, mp) mesh, same target/batch/exec budget,
    BOTH lanes feedback-off so the A/B isolates round-trip
    elimination (the single-chip lane's doctrine, bench_generations).

    Writes a MULTICHIP_generations.json artifact next to
    BENCH_generations.json.  ``gate=True`` exits nonzero unless the
    best mesh-generations config beats the host-driven mesh loop
    measured in the same session (one logged re-measure on CPU — the
    shared-runner noise guard); on TPU hardware the best config's
    PER-CHIP rate must additionally hold the BENCH_r05
    1 807 549 execs/s/chip bar (skipped with a named reason on CPU,
    where the absolute number is unreachable by construction)."""
    import shutil
    import json as _json
    import jax
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.models import targets_cgc
    from killerbeez_tpu.mutators.factory import mutator_factory
    from killerbeez_tpu.parallel import (
        ShardedCampaignDriver, parse_mesh_spec,
    )

    n_dp, n_mp = parse_mesh_spec(mesh_spec)
    n_chips = n_dp * n_mp
    if len(jax.devices()) < n_chips:
        print(f"error: mesh {mesh_spec} needs {n_chips} devices, "
              f"{len(jax.devices())} visible (CPU: set XLA_FLAGS="
              f"--xla_force_host_platform_device_count={n_chips})",
              file=sys.stderr)
        return 2
    on_tpu = jax.devices()[0].platform == "tpu"
    if engine is None:
        engine = "pallas_fused" if on_tpu else "xla"
    seed = targets_cgc.tlvstack_vm_seed() if target == "tlvstack_vm" \
        else targets_cgc.imgparse_vm_seed()
    rows = []

    def run_mesh(name, g):
        instr = instrumentation_factory(
            "jit_harness", _json.dumps({
                "target": target, "engine": engine,
                "novelty": "throughput"}))
        mut = mutator_factory("havoc", '{"seed": 3}', seed)
        drv = ShardedCampaignDriver(mesh_spec, instr, mut,
                                    batch_size=batch)
        out = os.path.join(REPO, "bench_out", name)
        shutil.rmtree(out, ignore_errors=True)
        fz = Fuzzer(drv, output_dir=out, batch_size=batch,
                    generations=g, feedback=0)
        # warmup covers compile + the steady dispatch shape; the
        # timed window then runs whole dispatches only
        fz.run(2 * max(g, 1) * batch)
        done = fz.stats.iterations
        steps_eff = max(steps, 2 * max(g, 1))
        t0 = time.time()
        fz.run(done + batch * steps_eff)
        dt = time.time() - t0
        return (fz.stats.iterations - done) / dt, fz

    v_host, fz = run_mesh("meshgen_base", 0)
    rows.append(emit(
        "meshgen-host",
        f"host-driven mesh loop ({target}, --mesh {mesh_spec}, "
        f"-b {batch}, {steps} steps, {engine}, feedback off)",
        v_host, new_paths=fz.stats.new_paths,
        stage_split=stage_split_row(fz)))

    best = (0.0, 0)
    for g in gs:
        v, fz = run_mesh(f"meshgen_{g}", g)
        reg = fz.telemetry.registry
        rows.append(emit(
            f"meshgen-G{g}",
            f"mesh-resident generations G={g} ({target}, --mesh "
            f"{mesh_spec}, -b {batch}, {engine}, feedback off)", v,
            speedup_vs_host=round(v / v_host, 3) if v_host else None,
            per_chip=round(v / n_chips, 1),
            new_paths=fz.stats.new_paths,
            ring_filled=int(reg.gauges.get("gen_ring_filled", 0)),
            findings_ring_drops=int(reg.counters.get(
                "findings_ring_drops", 0)),
            stage_split=stage_split_row(fz)))
        if v > best[0]:
            best = (v, g)

    rel_ok = best[0] > v_host
    retry = None
    if gate and not rel_ok and not on_tpu:
        # same shared-runner noise guard as the single-chip lane:
        # re-measure BOTH lanes once and gate on the fresh pair —
        # recorded in the artifact, never silent
        print("mesh-generations gate: relative A/B failed — "
              "re-measuring both lanes once (shared-runner noise "
              "guard)", file=sys.stderr)
        v_host2, _ = run_mesh("meshgen_base_retry", 0)
        v2, _ = run_mesh(f"meshgen_{best[1]}_retry", best[1])
        retry = {"host": round(v_host2, 1), "gen": round(v2, 1),
                 "speedup_vs_host": round(v2 / v_host2, 3)
                 if v_host2 else None}
        rel_ok = v2 > v_host2
    per_chip = best[0] / n_chips
    abs_ok = per_chip > BENCH_R05_GATE if on_tpu else None
    summary = {
        "metric": f"execs/sec on {target} (mesh-resident generation "
                  f"scan, --mesh {mesh_spec}, best G={best[1]}, "
                  f"{engine})",
        "value": round(best[0], 1),
        "unit": "execs/sec",
        "per_chip": round(per_chip, 1),
        "mesh": {"dp": n_dp, "mp": n_mp},
        "host_baseline": round(v_host, 1),
        "speedup_vs_host": round(best[0] / v_host, 3)
        if v_host else None,
        "gate_relative_ok": rel_ok,
        "gate_absolute": BENCH_R05_GATE,
        "gate_absolute_ok": abs_ok if on_tpu else
        "skipped: CPU backend (absolute bar is a TPU per-chip "
        "number; relative A/B gates here)",
    }
    if retry is not None:
        summary["retry"] = retry
    print(json.dumps(summary), flush=True)
    os.makedirs(os.path.join(REPO, "bench_out"), exist_ok=True)
    with open(os.path.join(REPO, "bench_out",
                           "MULTICHIP_generations.json"), "w") as f:
        json.dump({"rows": rows, "parsed": summary}, f, indent=1)
    if gate:
        if not rel_ok:
            print(f"FAIL: best mesh-generations config "
                  f"({best[0]:.0f} execs/s, G={best[1]}) did not "
                  f"beat the host-driven mesh loop ({v_host:.0f})",
                  file=sys.stderr)
            return 1
        if on_tpu and not abs_ok:
            print(f"FAIL: mesh-resident scan {per_chip:.0f} "
                  f"execs/s/chip <= BENCH_r05 gate "
                  f"{BENCH_R05_GATE:.0f}", file=sys.stderr)
            return 1
    return 0


def bench_multichip_smoke():
    """Config 5: sharded step on a virtual 8-device CPU mesh, run in a
    subprocess (the driver env exposes one real chip; see
    __graft_entry__.dryrun_multichip for why a subprocess)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = " ".join(f for f in env.get("XLA_FLAGS", "").split()
                     if "host_platform_device_count" not in f)
    env["XLA_FLAGS"] = (flags +
                        " --xla_force_host_platform_device_count=8").strip()
    code = r"""
import json, sys, time
sys.path.insert(0, %r)
import jax
jax.config.update('jax_platforms', 'cpu')
import jax.numpy as jnp, numpy as np
from killerbeez_tpu.models import targets, targets_cgc
from killerbeez_tpu.parallel import (make_mesh, make_sharded_fuzz_step,
                                     sharded_state_init)
mesh = make_mesh(4, 2)
prog = targets.get_target('tlvstack_vm')
step = make_sharded_fuzz_step(prog, mesh, batch_per_device=64, max_len=32)
state = sharded_state_init(mesh, prog.map_size)
seed = targets_cgc.tlvstack_vm_seed()
buf = np.zeros(32, np.uint8); buf[:len(seed)] = np.frombuffer(seed, np.uint8)
state, st, rets, uc, uh, ec, bufs, lens, _c = step(
    state, jnp.asarray(buf), jnp.int32(len(seed)), jnp.int32(0))
jax.block_until_ready(state.virgin_bits)
t0 = time.time(); N = 5
for i in range(1, N + 1):
    state, st, rets, uc, uh, ec, bufs, lens, _c = step(
        state, jnp.asarray(buf), jnp.int32(len(seed)), jnp.int32(i))
jax.block_until_ready(state.virgin_bits)
dt = time.time() - t0
print(json.dumps({'ok': True, 'execs_per_sec': 64 * 4 * N / dt,
                  'new_first_batch': int((rets > 0).sum())}))
""" % (REPO,)
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=480)
    last = (r.stdout.strip().splitlines() or ["{}"])[-1]
    try:
        d = json.loads(last)
    except json.JSONDecodeError:
        d = {}
    if r.returncode == 0 and d.get("ok"):
        emit(5, "multichip smoke (virtual 8-dev CPU mesh, dp=4 mp=2)",
             d["execs_per_sec"], ok=True)
    else:
        emit(5, "multichip smoke (virtual 8-dev CPU mesh)", 0.0,
             ok=False, error=r.stderr[-300:])


def bench_qemu_tier():
    """Config 4q: the binary-only tier — UnTracer-mode kb-trace on an
    UNINSTRUMENTED CGC-grade binary via the native protocol driver
    (steady-state: breakpoint-free execs at native PTRACE_CONT
    speed).  Reference point: its patched QEMU reaches
    hundreds-to-thousands of execs/s on server hardware
    (docs/AFL.md:52-55 claims ~3x stock afl-qemu)."""
    import re
    bt = os.path.join(REPO, "native", "build", "bench-trace")
    kt = os.path.join(REPO, "native", "build", "kb-trace")
    tgt = os.path.join(REPO, "corpus", "build", "tlvstack-plain")
    seed = os.path.join(REPO, "corpus", "seeds", "tlvstack.stk")
    if not all(os.path.exists(p) for p in (bt, kt, tgt, seed)):
        emit("4q", "binary-only tier fixtures missing", 0.0,
             skipped="native/corpus build unavailable")
        return
    env = dict(os.environ, BT_STDIN=seed)
    r = subprocess.run([bt, "1000", "--", kt, tgt], env=env,
                       capture_output=True, text=True, timeout=120)
    m = re.search(r"= (\d+) execs/s", r.stdout)
    if not m:
        raise RuntimeError(f"bench-trace: {r.stdout[-200:]}"
                           f"{r.stderr[-200:]}")
    emit("4q", "binary-only UnTracer kb-trace on tlvstack-plain "
         "(uninstrumented)", float(m.group(1)),
         baseline=FORKSERVER_BASELINE)


def main():
    from killerbeez_tpu.models import targets_cgc

    if "--schedule" in sys.argv[1:]:
        # scheduler-comparison mode:
        #   python bench.py --schedule bandit,rare-edge,rr \
        #       [target ...] [-b BATCH] [-n EXECS] [--gate]
        # --gate runs the learned-vs-static mask A/B instead (the
        # ROADMAP item 2 acceptance lane: paths-per-exec uplift at
        # equal execs/s, docs/LEARN.md)
        from killerbeez_tpu.corpus.schedule import SCHEDULERS
        # rare-edge-static / rare-edge-learned: rare-edge + a mask/
        # prior source (not separate Scheduler classes — wiring
        # variants)
        policies = sorted(SCHEDULERS) + ["rare-edge-static",
                                         "rare-edge-learned"]
        rest = sys.argv[1:]
        gate = "--gate" in rest
        if gate:
            rest.remove("--gate")
        i = rest.index("--schedule")
        nxt = rest[i + 1] if i + 1 < len(rest) else ""
        cand = [s for s in nxt.split(",") if s]
        # the next token is a policy list when it looks like one
        # (contains a comma or names a policy); a policy-looking
        # token with a typo is an ERROR, not a silent fallback to
        # all-policies-on-a-nonexistent-target; anything else is a
        # target/flag and the default policies apply
        looks_like_policies = "," in nxt or (
            cand and cand[0] in policies)
        if looks_like_policies:
            bad = [s for s in cand if s not in policies]
            if bad:
                print(f"error: unknown scheduler(s) {bad} "
                      f"(choose from {policies})",
                      file=sys.stderr)
                return 2
            schedules, tail = cand, rest[i + 2:]
        else:
            schedules, tail = policies, rest[i + 1:]
        tail = rest[:i] + tail          # targets may precede the flag
        batch, execs, tgts = 1024, 131072, []
        j = 0
        while j < len(tail):
            if tail[j] == "-b":
                batch = int(tail[j + 1]); j += 2
            elif tail[j] == "-n":
                execs = int(tail[j + 1]); j += 2
            else:
                tgts.append(tail[j]); j += 1
        known = ("tlvstack_vm", "rledec_vm", "imgparse_vm",
                 "fixedform_vm")
        bad_t = [t for t in tgts if t not in known]
        if bad_t:
            print(f"error: unknown target(s) {bad_t} "
                  f"(choose from {list(known)})", file=sys.stderr)
            return 2
        if gate:
            if "-n" not in tail:
                execs = 32768   # gate default: pre-saturation budget
            return bench_schedule_learn_gate(tgts or None, batch,
                                             execs)
        bench_schedulers(schedules, targets=tgts or None,
                        batch=batch, execs=execs)
        return 0

    if "--grammar" in sys.argv[1:]:
        # grammar-structured A/B mode over the generated target zoo:
        #   python bench.py --grammar [zoo:name ...] [-b BATCH]
        #       [-n EXECS] [-G GENS] [--gate]
        from killerbeez_tpu.models.zoo import parse_zoo_name
        rest = [a for a in sys.argv[1:] if a != "--grammar"]
        gate = "--gate" in rest
        rest = [a for a in rest if a != "--gate"]
        batch, execs, gens, names = 512, 16384, 4, []
        j = 0
        while j < len(rest):
            if rest[j] == "-b":
                batch = int(rest[j + 1]); j += 2
            elif rest[j] == "-n":
                execs = int(rest[j + 1]); j += 2
            elif rest[j] == "-G":
                gens = int(rest[j + 1]); j += 2
            else:
                names.append(rest[j]); j += 1
        for n in names:
            try:
                parse_zoo_name(n)
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
        return bench_grammar(names=names or None, batch=batch,
                             execs=execs, g=gens, gate=gate)

    if "--stateful" in sys.argv[1:]:
        # stateful session-tier A/B mode:
        #   python bench.py --stateful [target ...] [-b BATCH]
        #       [-n EXECS] [--gate]
        from killerbeez_tpu.models import targets_stateful as _ts
        rest = [a for a in sys.argv[1:] if a != "--stateful"]
        gate = "--gate" in rest
        rest = [a for a in rest if a != "--gate"]
        batch, execs, tgts = 512, 16384, []
        j = 0
        while j < len(rest):
            if rest[j] == "-b":
                batch = int(rest[j + 1]); j += 2
            elif rest[j] == "-n":
                execs = int(rest[j + 1]); j += 2
            else:
                tgts.append(rest[j]); j += 1
        known = _ts.stateful_target_names()
        bad_t = [t for t in tgts if t not in known]
        if bad_t:
            print(f"error: unknown stateful target(s) {bad_t} "
                  f"(choose from {known})", file=sys.stderr)
            return 2
        return bench_stateful(targets=tgts or None, batch=batch,
                              execs=execs, gate=gate)

    if "--hybrid" in sys.argv[1:]:
        # hybrid cross-tier A/B mode:
        #   python bench.py --hybrid [-b BATCH] [-n EXECS] [--gate]
        rest = [a for a in sys.argv[1:] if a != "--hybrid"]
        gate = "--gate" in rest
        rest = [a for a in rest if a != "--gate"]
        batch, execs = 256, 65536
        j = 0
        while j < len(rest):
            if rest[j] == "-b":
                batch = int(rest[j + 1]); j += 2
            elif rest[j] == "-n":
                execs = int(rest[j + 1]); j += 2
            else:
                print(f"error: unknown --hybrid arg {rest[j]!r}",
                      file=sys.stderr)
                return 2
        return bench_hybrid(batch=batch, execs=execs, gate=gate)

    if "--repair" in sys.argv[1:]:
        # conformance repair lane:
        #   python bench.py --repair [--gate]
        rest = [a for a in sys.argv[1:] if a != "--repair"]
        gate = "--gate" in rest
        rest = [a for a in rest if a != "--gate"]
        if rest:
            print(f"error: unknown --repair arg {rest[0]!r}",
                  file=sys.stderr)
            return 2
        return bench_repair(gate=gate)

    if "--vsa" in sys.argv[1:]:
        # value-set solver-uplift lane:
        #   python bench.py --vsa [--gate]
        rest = [a for a in sys.argv[1:] if a != "--vsa"]
        gate = "--gate" in rest
        rest = [a for a in rest if a != "--gate"]
        if rest:
            print(f"error: unknown --vsa arg {rest[0]!r}",
                  file=sys.stderr)
            return 2
        return bench_vsa(gate=gate)

    if "--crack" in sys.argv[1:]:
        # plateau-crack A/B mode:
        #   python bench.py --crack [target ...] [-b BATCH] [-n EXECS]
        rest = [a for a in sys.argv[1:] if a != "--crack"]
        batch, budget, tgts = 256, 131072, []
        j = 0
        while j < len(rest):
            if rest[j] == "-b":
                batch = int(rest[j + 1]); j += 2
            elif rest[j] == "-n":
                budget = int(rest[j + 1]); j += 2
            else:
                tgts.append(rest[j]); j += 1
        from killerbeez_tpu.models.targets import target_names
        from killerbeez_tpu.models import targets_cgc  # noqa: F401
        bad = [t for t in tgts if t not in target_names()]
        if bad:
            print(f"error: unknown target(s) {bad} "
                  f"(choose from {target_names()})", file=sys.stderr)
            return 2
        bench_crack(targets=tgts or None, batch=batch,
                    budget_execs=budget)
        return 0

    if "--descend" in sys.argv[1:]:
        # gradient-search A/B mode (checksum universes):
        #   python bench.py --descend [target ...] [-b BATCH]
        #       [-n EXECS] [--budget DISPATCHES] [--gate]
        rest = [a for a in sys.argv[1:] if a != "--descend"]
        gate = "--gate" in rest
        if gate:
            rest.remove("--gate")
        batch, budget, dbudget, tgts = 256, 65536, 16, []
        j = 0
        while j < len(rest):
            if rest[j] == "-b":
                batch = int(rest[j + 1]); j += 2
            elif rest[j] == "-n":
                budget = int(rest[j + 1]); j += 2
            elif rest[j] == "--budget":
                dbudget = int(rest[j + 1]); j += 2
            else:
                tgts.append(rest[j]); j += 1
        from killerbeez_tpu.models.targets import target_names
        bad = [t for t in tgts if t not in target_names()]
        if bad:
            print(f"error: unknown target(s) {bad} "
                  f"(choose from {target_names()})", file=sys.stderr)
            return 2
        return bench_descend(targets=tgts or None, batch=batch,
                             budget_execs=budget,
                             descend_budget=dbudget, gate=gate)

    if "--generations" in sys.argv[1:]:
        # device-resident generation-loop A/B mode:
        #   python bench.py --generations [-b BATCH] [-s STEPS]
        #       [-g 4,16,64] [--mesh dp,mp] [engine] [--gate]
        # --mesh runs the MESH lane (host-driven mesh loop vs the
        # mesh-resident generation scan, MULTICHIP_generations.json)
        rest = [a for a in sys.argv[1:] if a != "--generations"]
        gate = "--gate" in rest
        if gate:
            rest.remove("--gate")
        batch, steps, gs, engine, mesh_spec = \
            65536, 32, (4, 16, 64), None, None
        j = 0
        while j < len(rest):
            if rest[j] == "-b":
                batch = int(rest[j + 1]); j += 2
            elif rest[j] == "-s":
                steps = int(rest[j + 1]); j += 2
            elif rest[j] == "-g":
                gs = tuple(int(x) for x in rest[j + 1].split(","))
                j += 2
            elif rest[j] == "--mesh":
                mesh_spec = rest[j + 1]; j += 2
            else:
                engine = rest[j]; j += 1
        if mesh_spec is not None:
            return bench_mesh_generations(
                batch=batch, steps=steps, gs=gs, engine=engine,
                mesh_spec=mesh_spec, gate=gate)
        if engine is None:
            import jax
            engine = "pallas_fused" \
                if jax.devices()[0].platform == "tpu" else "xla"
        return bench_generations(batch=batch, steps=steps, gs=gs,
                                 engine=engine, gate=gate)

    if "--trace-overhead" in sys.argv[1:]:
        # flight-recorder cost mode: optional trailing args override
        # batch/steps/engine (CPU verification uses small shapes);
        # --gate turns the <=2% bar into a nonzero exit (the CI lane)
        rest = [a for a in sys.argv[1:] if a != "--trace-overhead"]
        gate = None
        if "--gate" in rest:
            rest.remove("--gate")
            gate = 2.0
        batch = int(rest[0]) if rest else 65536
        steps = int(rest[1]) if len(rest) > 1 else 32
        engine = rest[2] if len(rest) > 2 else "pallas_fused"
        return bench_trace_overhead(batch=batch, steps=steps,
                                    engine=engine, gate_pct=gate)

    if "--stats-overhead" in sys.argv[1:]:
        # standalone observability-cost mode: optional trailing args
        # override batch/steps (CPU verification uses small shapes)
        rest = [a for a in sys.argv[1:] if a != "--stats-overhead"]
        batch = int(rest[0]) if rest else 65536
        steps = int(rest[1]) if len(rest) > 1 else 32
        engine = rest[2] if len(rest) > 2 else "pallas_fused"
        bench_stats_overhead(batch=batch, steps=steps, engine=engine)
        return 0

    if build_corpus():
        try:
            bench_host_configs()
        except Exception as e:  # report, don't lose device rows
            emit(0, "host-config failure", 0.0, error=str(e)[:200])
    else:
        emit(0, "host configs", 0.0, skipped="native toolchain "
             "or corpus build unavailable")

    v4, _ = bench_device("test", 32768, 60, b"ABC@")
    emit(4, "jit_harness fused on-device (toy `test` target)", v4,
         baseline=FORKSERVER_BASELINE)

    try:
        bench_multichip_smoke()
    except Exception as e:
        emit(5, "multichip smoke", 0.0, ok=False, error=str(e)[:200])

    vx, _ = bench_device("tlvstack_vm", 16384, 60,
                         targets_cgc.tlvstack_vm_seed())
    emit("4b", "flagship tlvstack_vm, xla engine", vx,
         baseline=FORKSERVER_BASELINE)

    try:
        vi, _ = bench_device_fused("imgparse_vm", 16384, 60,
                                   targets_cgc.imgparse_vm_seed())
        emit("4c", "imgparse_vm (chunked-format CGC target, fused pallas)",
             vi, baseline=FORKSERVER_BASELINE)
    except Exception as e:  # pallas unavailable: keep the headline alive
        emit("4c", "imgparse_vm fused pallas unavailable", 0.0, ok=False,
             error=str(e)[:200])

    try:
        # 64k lanes/batch + K=8 superbatch: the config that saturates
        # the kernel rate through the CLI (1.82M measured; 32k
        # batches read 1.3-1.6M depending on tunnel state)
        vc_, st, fz = bench_cli_product("tlvstack_vm", 65536, 32,
                                        targets_cgc.tlvstack_vm_seed())
        emit("4d", "PRODUCT CLI loop (file+jit_harness+havoc, "
             "pallas_fused, -b 65536 -K 8) on tlvstack_vm", vc_,
             baseline=FORKSERVER_BASELINE, new_paths=st.new_paths,
             stage_split=stage_split_row(fz))
    except Exception as e:
        emit("4d", "product CLI loop unavailable", 0.0, ok=False,
             error=str(e)[:200])

    try:
        # device-resident generation loop at the flagship shape: one
        # G=16 config in the default matrix (the full G sweep + gate
        # runs via `python bench.py --generations --gate`)
        bench_generations(batch=65536, steps=32, gs=(16,))
    except Exception as e:
        emit("4g", "device-resident generations unavailable", 0.0,
             ok=False, error=str(e)[:200])

    try:
        bench_qemu_tier()
    except Exception as e:
        emit("4q", "binary-only (UnTracer kb-trace) unavailable", 0.0,
             ok=False, error=str(e)[:200])

    # headline LAST: the CGC-grade flagship with mutation AND
    # execution fused into one Pallas kernel (falls back to the XLA
    # engine number if the kernel won't compile in this environment)
    try:
        vH, _ = bench_device_fused("tlvstack_vm", 16384, 60,
                                   targets_cgc.tlvstack_vm_seed())
        engine_used = "fused pallas"
    except Exception as e:
        emit("4p", "pallas engine unavailable", 0.0, ok=False,
             error=str(e)[:200])
        vH, engine_used = vx, "xla"
    print(json.dumps({
        "metric": "execs/sec/chip on tlvstack_vm (110-block CGC-grade "
                  f"target; {engine_used} havoc+KBVM+static-edge "
                  "triage, two-phase tail scheduling, exact-bf16 "
                  "stacked-limb MXU dots, i16 counts carry)",
        "value": round(vH, 1),
        "unit": "execs/sec",
        "vs_baseline": round(vH / FORKSERVER_BASELINE, 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
