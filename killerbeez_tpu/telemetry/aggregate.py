"""Snapshot merging — fold N registry snapshots into one campaign
view.

Used twice: the (dp, mp) mesh campaign folds per-shard stat dicts
each sync epoch (parallel/campaign.py), and the manager folds worker
heartbeats into the ``/api/stats/<campaign>`` response.  The merge is
associative and commutative (property-tested in
tests/test_telemetry.py), so fold order — shard order, heartbeat
arrival order, tree vs linear reduction — can never change the
answer:

  * counters    — summed (totals add across workers)
  * gauges      — max (a fleet's corpus size / pipeline depth is the
                  worst-case view; summing would double-count shared
                  state)
  * EMA rates   — weight-weighted mean, weights summed: a worker
                  that has observed half a horizon contributes half
                  strength.  (rate*weight, weight) pairs add, which
                  is what makes the mean associative.
  * histograms  — bucket-wise counts summed (p50/p90/p99 re-derived
                  from the merged counts — quantiles never average)
  * health      — per-worker liveness dicts union; on conflict the
                  record with the newest ``last_seen`` wins (tie:
                  worse status), ``first_seen`` min / ``last_seen``
                  max — a total order, so the fold stays associative
  * start_time  — min; ``t`` — max (the merged view spans the fleet)
  * event logs  — exact-duplicate-deduped union, sorted into one
                  fleet timeline (``merge_events``; snapshots carrying
                  an ``events`` list fold through it automatically)
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from .metrics import percentiles_from_counts


def _merge_rates(a: Dict[str, Dict[str, float]],
                 b: Dict[str, Dict[str, float]]
                 ) -> Dict[str, Dict[str, float]]:
    out = dict(a)
    for k, rb in b.items():
        ra = out.get(k)
        if ra is None:
            out[k] = dict(rb)
            continue
        w = ra.get("weight", 0.0) + rb.get("weight", 0.0)
        if w <= 0:
            out[k] = {"rate": 0.0, "weight": 0.0}
        else:
            out[k] = {
                "rate": (ra.get("rate", 0.0) * ra.get("weight", 0.0)
                         + rb.get("rate", 0.0) * rb.get("weight", 0.0)
                         ) / w,
                "weight": w,
            }
    return out


def _merge_hists(a: Dict[str, Dict], b: Dict[str, Dict]
                 ) -> Dict[str, Dict]:
    out = {k: dict(v) for k, v in a.items()}
    for k, hb in b.items():
        ha = out.get(k)
        if ha is None:
            out[k] = dict(hb)
            continue
        ca, cb = list(ha.get("counts", [])), list(hb.get("counts", []))
        if len(ca) < len(cb):
            ca += [0] * (len(cb) - len(ca))
        for i, v in enumerate(cb):
            ca[i] += v
        out[k] = {"counts": ca,
                  "total": ha.get("total", 0) + hb.get("total", 0),
                  "sum": ha.get("sum", 0.0) + hb.get("sum", 0.0)}
        # quantiles are re-derived from the merged counts — merging
        # per-worker p50s would be wrong AND order-dependent
        out[k].update(percentiles_from_counts(ca))
    return out


#: health-status severity (worse = higher) — the ONE ordering behind
#: both the merge tie-break here and the manager monitor's
#: escalation checks (manager/fleet.py imports it)
STATUS_RANK = {"healthy": 0, "stale": 1, "dead": 2}
_STATUS_RANK = STATUS_RANK


def merge_health(a: Optional[Dict[str, Dict]],
                 b: Optional[Dict[str, Dict]]) -> Dict[str, Dict]:
    """Fold per-worker health dicts (``{worker: {status, first_seen,
    last_seen, ...}}``).  Per worker: the record with the greater
    ``(last_seen, status severity)`` supplies the fields (a TOTAL
    order — associative + commutative), then ``first_seen`` takes the
    min and ``last_seen`` the max across both."""
    def _key(h: Dict) -> tuple:
        return (h.get("last_seen", 0.0),
                _STATUS_RANK.get(h.get("status"), 0))

    out = {w: dict(h) for w, h in (a or {}).items()}
    for w, hb in (b or {}).items():
        ha = out.get(w)
        if ha is None:
            out[w] = dict(hb)
            continue
        win = dict(hb) if _key(hb) >= _key(ha) else dict(ha)
        fs = [h.get("first_seen") for h in (ha, hb)
              if h.get("first_seen") is not None]
        ls = [h.get("last_seen") for h in (ha, hb)
              if h.get("last_seen") is not None]
        if fs:
            win["first_seen"] = min(fs)
        if ls:
            win["last_seen"] = max(ls)
        out[w] = win
    return out


def merge_events(a: List[Dict], b: Optional[List[Dict]] = None
                 ) -> List[Dict]:
    """Fold event-log record lists into one fleet timeline.

    Exact duplicates (a record forwarded through two paths, or the
    same heartbeat replayed) collapse to one; the result is sorted by
    ``(t, worker, seq, canonical json)`` — a TOTAL order, which is
    what makes the fold associative and commutative regardless of
    which worker's log arrives first."""
    seen: Dict[str, Dict] = {}
    for rec in list(a) + list(b or []):
        seen.setdefault(json.dumps(rec, sort_keys=True), rec)
    return sorted(
        seen.values(),
        key=lambda r: (r.get("t", 0), str(r.get("worker", "")),
                       r.get("seq", 0),
                       json.dumps(r, sort_keys=True)))


def merge_two(a: Dict[str, object], b: Dict[str, object]
              ) -> Dict[str, object]:
    ca, cb = a.get("counters", {}), b.get("counters", {})
    counters = dict(ca)
    for k, v in cb.items():
        counters[k] = counters.get(k, 0) + v
    ga, gb = a.get("gauges", {}), b.get("gauges", {})
    gauges = dict(ga)
    for k, v in gb.items():
        gauges[k] = max(gauges.get(k, float("-inf")), v)
    out: Dict[str, object] = {
        "counters": counters,
        "gauges": gauges,
        "rates": _merge_rates(a.get("rates", {}), b.get("rates", {})),
        "hists": _merge_hists(a.get("hists", {}), b.get("hists", {})),
    }
    ev_a, ev_b = a.get("events"), b.get("events")
    if ev_a or ev_b:
        out["events"] = merge_events(ev_a or [], ev_b or [])
    h_a, h_b = a.get("health"), b.get("health")
    if h_a or h_b:
        out["health"] = merge_health(h_a, h_b)
    st = [s.get("start_time") for s in (a, b)
          if s.get("start_time") is not None]
    ts = [s.get("t") for s in (a, b) if s.get("t") is not None]
    if st:
        out["start_time"] = min(st)
    if ts:
        out["t"] = max(ts)
    if st and ts:
        out["elapsed"] = out["t"] - out["start_time"]
    # derived values are recomputed, never merged: a mean of ratios
    # is not the ratio of the sums
    rates = out["rates"]
    execs_rate = rates.get("execs", {})
    elapsed = out.get("elapsed") or 0
    out["derived"] = {
        "execs_per_sec": (counters.get("execs", 0) / elapsed
                          if elapsed and elapsed > 0 else 0.0),
        "execs_per_sec_ema": execs_rate.get("rate", 0.0),
    }
    return out


def merge(snapshots: List[Dict[str, object]]
          ) -> Optional[Dict[str, object]]:
    """Fold any number of snapshots; [] -> None, [s] -> normalized s."""
    if not snapshots:
        return None
    acc: Dict[str, object] = {"counters": {}, "gauges": {},
                              "rates": {}, "hists": {}}
    for s in snapshots:
        acc = merge_two(acc, s)
    return acc
