"""Snapshot writers — AFL-ecosystem-compatible campaign stats files.

Three artifacts under ``<output>/``, refreshed on a wall-clock
interval from the fuzzing loop's own thread (no background thread:
``maybe_flush()`` is a cheap time check per batch):

  * ``fuzzer_stats``  — ``key = value`` lines, the AFL contract
    (afl-whatsup, FMViz and every dashboard in that ecosystem parse
    this).  Written atomically: tmp file + ``os.replace`` so a tailer
    never sees a torn write.
  * ``plot_data``     — append-only CSV of cumulative counters, one
    row per flush (afl-plot's input).  Monotone by construction.
  * ``stats.jsonl``   — one full registry snapshot per flush
    (structured stream for kb-stats and the manager heartbeat).

All writes degrade to a warning: telemetry must never kill a
campaign over a full disk.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

from ..utils.fileio import ensure_dir
from ..utils.logging import WARNING_MSG
from .metrics import MetricsRegistry

PLOT_FIELDS = ("unix_time", "execs_done", "paths_total", "crashes",
               "unique_crashes", "hangs", "unique_hangs",
               "corpus_count", "execs_per_sec")


def _corpus_seen(snap: Dict[str, object]) -> float:
    """The seen-corpus gauge, tolerating pre-split snapshots that
    still carry the conflated ``corpus_size`` name."""
    g = snap.get("gauges", {})
    return g.get("corpus_seen", g.get("corpus_size", 0))


def write_fuzzer_stats(path: str, snap: Dict[str, object],
                       extra: Optional[Dict[str, object]] = None
                       ) -> None:
    """Atomic ``key = value`` dump of one snapshot (AFL layout)."""
    c = snap.get("counters", {})
    d = snap.get("derived", {})
    g = snap.get("gauges", {})
    rows = {
        "start_time": int(snap.get("start_time", 0)),
        "last_update": int(snap.get("t", 0)),
        # AFL's find-recency epochs (afl-whatsup reads these to call a
        # campaign stuck/alive); sourced from the flight recorder's
        # event timestamps, mirrored as gauges so fleet merges take
        # the max — "most recent find anywhere" — automatically
        "last_path": int(g.get("last_path", 0)),
        "last_crash": int(g.get("last_crash", 0)),
        "last_hang": int(g.get("last_hang", 0)),
        "run_time": int(snap.get("elapsed", 0)),
        "fuzzer_pid": os.getpid(),
        "execs_done": int(c.get("execs", 0)),
        "execs_per_sec": round(d.get("execs_per_sec", 0.0), 2),
        "execs_per_sec_ema": round(d.get("execs_per_sec_ema", 0.0), 2),
        "paths_total": int(c.get("new_paths", 0)),
        "crashes": int(c.get("crashes", 0)),
        "unique_crashes": int(c.get("unique_crashes", 0)),
        "hangs": int(c.get("hangs", 0)),
        "unique_hangs": int(c.get("unique_hangs", 0)),
        "exec_errors": int(c.get("errors", 0)),
        # corpus_count stays the AFL wire name; the source gauge is
        # corpus_seen (distinct new-path inputs ever recorded —
        # corpus_size is the pre-split name, read for old snapshots)
        "corpus_count": int(_corpus_seen(snap)),
        "corpus_arms": int(snap.get("gauges", {})
                           .get("corpus_arms", 0)),
        "afl_version": "killerbeez-tpu",
    }
    if extra:
        rows.update(extra)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        for k, v in rows.items():
            f.write(f"{k:<18}: {v}\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)               # atomic on POSIX


def plot_row(snap: Dict[str, object]) -> str:
    c = snap.get("counters", {})
    d = snap.get("derived", {})
    vals = (int(snap.get("t", 0)), int(c.get("execs", 0)),
            int(c.get("new_paths", 0)), int(c.get("crashes", 0)),
            int(c.get("unique_crashes", 0)), int(c.get("hangs", 0)),
            int(c.get("unique_hangs", 0)),
            int(_corpus_seen(snap)),
            round(d.get("execs_per_sec", 0.0), 2))
    return ", ".join(str(v) for v in vals)


class StatsSink:
    """Owns the three files for one campaign output directory."""

    def __init__(self, output_dir: str, registry: MetricsRegistry,
                 interval_s: float = 5.0):
        self.output_dir = output_dir
        self.registry = registry
        self.interval_s = float(interval_s)
        self._last_flush = 0.0          # first maybe_flush() writes
        self._plot_header_done = False
        try:
            ensure_dir(output_dir)
        except OSError as e:
            WARNING_MSG("stats dir unavailable: %s", e)

    # -- paths ----------------------------------------------------------

    @property
    def fuzzer_stats_path(self) -> str:
        return os.path.join(self.output_dir, "fuzzer_stats")

    @property
    def plot_data_path(self) -> str:
        return os.path.join(self.output_dir, "plot_data")

    @property
    def jsonl_path(self) -> str:
        return os.path.join(self.output_dir, "stats.jsonl")

    # -- writing --------------------------------------------------------

    def maybe_flush(self) -> bool:
        """Flush if the interval elapsed; cheap no-op otherwise."""
        now = self.registry._time()
        if now - self._last_flush < self.interval_s:
            return False
        self.flush()
        return True

    def flush(self) -> None:
        snap = self.registry.snapshot()
        self._last_flush = snap["t"]
        try:
            write_fuzzer_stats(self.fuzzer_stats_path, snap)
            mode = "a" if self._plot_header_done else "w"
            with open(self.plot_data_path, mode) as f:
                if not self._plot_header_done:
                    f.write("# " + ", ".join(PLOT_FIELDS) + "\n")
                    self._plot_header_done = True
                f.write(plot_row(snap) + "\n")
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps(snap) + "\n")
        except OSError as e:
            WARNING_MSG("stats flush failed: %s", e)


def parse_fuzzer_stats(path: str) -> Dict[str, str]:
    """Read a ``key = value`` file back into a dict (tooling/tests)."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            if ":" in line:
                k, v = line.split(":", 1)
                out[k.strip()] = v.strip()
    return out


def read_latest_snapshot(path: str,
                         window: int = 1 << 16
                         ) -> Optional[Dict[str, object]]:
    """Newest complete snapshot from a ``stats.jsonl`` (or its
    output directory) — the shared tailer behind the worker
    heartbeat and kb-stats.  Reads only the last ``window`` bytes
    (O(1) however long the campaign has run) and walks backwards to
    the first line that parses, so a record torn mid-append never
    drops the beat — the previous complete record serves instead."""
    if os.path.isdir(path):
        path = os.path.join(path, "stats.jsonl")
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - window))
            chunk = f.read()
    except OSError:
        return None
    for line in reversed(chunk.splitlines()):
        if line.strip():
            try:
                return json.loads(line)
            except ValueError:
                continue    # torn tail or window-truncated head
    return None
