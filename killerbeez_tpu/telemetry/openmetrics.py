"""OpenMetrics text exposition — the fleet's standard scrape surface.

Renders ``MetricsRegistry.snapshot()`` dicts (and their
``aggregate.merge`` folds) in the Prometheus / OpenMetrics text
format, so the manager's ``/metrics`` endpoint and ``kb-stats
--openmetrics`` plug straight into the existing monitoring ecosystem
(Prometheus scrape -> Grafana) without a sidecar exporter.

Type mapping from the registry's four series kinds:

  * counters   -> ``counter``  (sample name gains the ``_total``
                  suffix the spec requires; a raw name already ending
                  in ``_total`` keeps it as the suffix)
  * gauges     -> ``gauge``
  * EMA rates  -> ``gauge``    (``<name>_rate``: a decayed
                  events/second is a last-value sample, not a
                  monotone total)
  * histograms -> ``histogram`` (cumulative ``_bucket{le=...}``
                  series over the registry's static log2 edges,
                  plus ``_count`` / ``_sum``)
  * derived    -> ``gauge``    (``execs_per_sec`` & co)

Metric/label names are sanitized to the spec's charset (anything
else becomes ``_``); label values are escaped (``\\``, ``\"``,
newline).  The exposition always ends with ``# EOF``.  Conformance is
pinned by the strict pure-python parser in the test suite
(tests/openmetrics_parser.py), which CI runs against a live manager
scrape.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Tuple

from .metrics import HIST_BUCKETS

#: exposition content type (the version Prometheus negotiates)
CONTENT_TYPE = ("application/openmetrics-text; version=1.0.0; "
                "charset=utf-8")

#: default metric namespace
PREFIX = "kbz"

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_BAD = re.compile(r"[^a-zA-Z0-9_]")


def sanitize_metric_name(name: str) -> str:
    """Coerce an arbitrary registry series name into the spec's
    ``[a-zA-Z_:][a-zA-Z0-9_:]*`` charset (collisions after
    sanitization merge into one family — acceptable for telemetry)."""
    name = _NAME_BAD.sub("_", str(name)) or "_"
    if name[0].isdigit():
        name = "_" + name
    return name


def sanitize_label_name(name: str) -> str:
    name = _LABEL_BAD.sub("_", str(name)) or "_"
    if name[0].isdigit():
        name = "_" + name
    return name


def escape_label_value(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace("\"", "\\\"")
            .replace("\n", "\\n"))


def _fmt_value(v: float) -> str:
    """Sample value formatting: integral floats print as integers
    (smaller exposition, same parse), everything else as repr."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class Family:
    """One metric family: a name, a type, and labeled samples."""

    __slots__ = ("name", "kind", "help", "samples")

    def __init__(self, name: str, kind: str,
                 help_text: Optional[str] = None):
        self.name = name
        self.kind = kind
        self.help = help_text
        #: [(sample name, label pairs, value)]
        self.samples: List[Tuple[str, Tuple[Tuple[str, str], ...],
                                 float]] = []


def new_families() -> "Dict[str, Family]":
    return {}


def _family(fams: Dict[str, Family], name: str, kind: str,
            help_text: Optional[str] = None) -> Optional[Family]:
    """Get-or-create; a name already claimed by a DIFFERENT type
    keeps its first type (the sample is dropped rather than emitting
    a malformed exposition)."""
    fam = fams.get(name)
    if fam is None:
        fam = fams[name] = Family(name, kind, help_text)
    return fam if fam.kind == kind else None


def _labels(labels: Optional[Dict[str, str]]
            ) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple((sanitize_label_name(k), str(v))
                 for k, v in labels.items())


def add_counter(fams: Dict[str, Family], name: str, value: float,
                labels: Optional[Dict[str, str]] = None,
                help_text: Optional[str] = None) -> None:
    if name.endswith("_total"):
        name = name[:-len("_total")]
    fam = _family(fams, name, "counter", help_text)
    if fam is not None and math.isfinite(float(value)):
        fam.samples.append((name + "_total", _labels(labels),
                            max(0.0, float(value))))


def add_gauge(fams: Dict[str, Family], name: str, value: float,
              labels: Optional[Dict[str, str]] = None,
              help_text: Optional[str] = None) -> None:
    fam = _family(fams, name, "gauge", help_text)
    if fam is not None and math.isfinite(float(value)):
        fam.samples.append((name, _labels(labels), float(value)))


def add_histogram(fams: Dict[str, Family], name: str,
                  hist: Dict[str, Any],
                  labels: Optional[Dict[str, str]] = None,
                  help_text: Optional[str] = None) -> None:
    """One registry histogram (per-bucket ``counts`` over the static
    HIST_BUCKETS edges) as a cumulative OpenMetrics histogram; counts
    beyond the known edges fold into ``+Inf``."""
    fam = _family(fams, name, "histogram", help_text)
    if fam is None:
        return
    counts = [int(c) for c in hist.get("counts", [])]
    lab = _labels(labels)
    cum = 0
    for i, edge in enumerate(HIST_BUCKETS):
        if i < len(counts):
            cum += counts[i]
        fam.samples.append((name + "_bucket",
                            lab + (("le", repr(float(edge))),), cum))
    cum += sum(counts[len(HIST_BUCKETS):])
    fam.samples.append((name + "_bucket", lab + (("le", "+Inf"),),
                        cum))
    fam.samples.append((name + "_count", lab, cum))
    fam.samples.append((name + "_sum", lab,
                        max(0.0, float(hist.get("sum", 0.0)))))


def add_snapshot(fams: Dict[str, Family], snap: Dict[str, Any],
                 labels: Optional[Dict[str, str]] = None,
                 prefix: str = PREFIX,
                 include_hists: bool = True) -> None:
    """Fold one registry snapshot into the family set under
    ``labels`` — called once per worker (labels ``{campaign,
    worker}``) and once per fleet fold (labels ``{campaign}``, with
    ``prefix="kbz_fleet"`` so per-worker and fleet-total families
    never mix in one sum())."""
    for k, v in (snap.get("counters") or {}).items():
        add_counter(fams, f"{prefix}_{sanitize_metric_name(k)}", v,
                    labels)
    for k, v in (snap.get("gauges") or {}).items():
        add_gauge(fams, f"{prefix}_{sanitize_metric_name(k)}", v,
                  labels)
    for k, r in (snap.get("rates") or {}).items():
        add_gauge(fams,
                  f"{prefix}_{sanitize_metric_name(k)}_rate",
                  (r or {}).get("rate", 0.0), labels,
                  help_text="EMA events/second")
    for k, v in (snap.get("derived") or {}).items():
        add_gauge(fams, f"{prefix}_{sanitize_metric_name(k)}", v,
                  labels)
    if include_hists:
        # "_duration_seconds", not "_seconds": the registry already
        # pairs every histogram with a "<name>_seconds" total counter
        # and the two must land in distinct families
        for k, h in (snap.get("hists") or {}).items():
            add_histogram(
                fams,
                f"{prefix}_{sanitize_metric_name(k)}"
                "_duration_seconds",
                h or {}, labels, help_text="stage latency seconds")


def render_families(fams: Dict[str, Family]) -> str:
    """The exposition: families sorted by name, ``# TYPE`` (and
    optional ``# HELP``) before their samples, ``# EOF`` last."""
    out: List[str] = []
    for name in sorted(fams):
        fam = fams[name]
        if not fam.samples:
            continue
        if fam.help:
            out.append(f"# HELP {name} {fam.help}")
        out.append(f"# TYPE {name} {fam.kind}")
        seen = set()
        for sample_name, labels, value in fam.samples:
            key = (sample_name, labels)
            if key in seen:          # spec: no duplicate name+labels
                continue
            seen.add(key)
            if labels:
                body = ",".join(
                    f'{k}="{escape_label_value(v)}"'
                    for k, v in labels)
                out.append(f"{sample_name}{{{body}}} "
                           f"{_fmt_value(value)}")
            else:
                out.append(f"{sample_name} {_fmt_value(value)}")
    out.append("# EOF")
    return "\n".join(out) + "\n"


def render_snapshot(snap: Dict[str, Any],
                    labels: Optional[Dict[str, str]] = None,
                    prefix: str = PREFIX) -> str:
    """One snapshot as a full exposition (``kb-stats
    --openmetrics``)."""
    fams = new_families()
    add_snapshot(fams, snap, labels, prefix=prefix)
    return render_families(fams)
