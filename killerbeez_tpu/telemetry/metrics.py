"""Process-local metrics registry — the campaign's single source of
truth for runtime counters.

Four series kinds, all O(1) per record call (no locks on the hot
path; one fuzzing loop owns the registry and the sink reads
snapshots, which in CPython are consistent dict reads):

  * counters    — monotone totals (execs, crashes, bytes written)
  * gauges      — last-value samples (corpus size, pipeline depth)
  * EMA rates   — exponentially-decayed events/second with an
                  explicit observation weight, so shard/worker rates
                  merge as a weighted mean (see aggregate.merge)
  * histograms  — fixed log2 buckets over seconds (stage latencies)

``StageTimer`` times the loop's phases (mutate-dispatch, execute,
host-transfer, triage-reduce, corpus-feedback, fs-write) from the
HOST's perspective: it timestamps around the existing lazy-array
materialization boundaries (``np.asarray`` on a prefetched device
array) and never calls ``block_until_ready``, so the superbatch path
stays fully async — "execute" measures dispatch cost and
"host_transfer" measures how long the host actually waited for a
transfer that was prefetched batches ago, which is the number that
matters for pipeline tuning (PTrix-style stage utilization).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

#: log2 bucket upper bounds in seconds for stage histograms:
#: 1us .. ~65s, doubling; the last bucket is +inf
HIST_BUCKETS: List[float] = [1e-6 * (2 ** i) for i in range(27)]

#: canonical loop stage names, in pipeline order (glossary in
#: docs/OBSERVABILITY.md)
STAGES = ("mutate", "execute", "host_transfer", "triage",
          "corpus_feedback", "fs_write", "learn")


class EmaRate:
    """Events/second EMA over a ``tau``-second horizon.

    ``add(n)`` is O(1): it decays the running rate by the elapsed
    wall-clock gap and folds the new observation in.  ``weight``
    grows toward 1 with observed time, so a rate that has only seen
    half a horizon merges at half strength (aggregate.merge's
    rate-weighted mean) instead of dominating a long-lived peer.
    """

    __slots__ = ("tau", "_rate", "_weight", "_last", "_time")

    def __init__(self, tau: float = 30.0, time_fn=time.monotonic):
        self.tau = float(tau)
        self._rate = 0.0
        self._weight = 0.0
        self._last: Optional[float] = None
        self._time = time_fn

    def add(self, n: float) -> None:
        now = self._time()
        if self._last is None:
            self._last = now
            return                      # first sample only anchors t0
        dt = now - self._last
        self._last = now
        if dt <= 0:
            return
        alpha = min(dt / self.tau, 1.0)
        self._rate += alpha * (n / dt - self._rate)
        self._weight += alpha * (1.0 - self._weight)

    @property
    def rate(self) -> float:
        return self._rate

    @property
    def weight(self) -> float:
        return self._weight

    def as_dict(self) -> Dict[str, float]:
        return {"rate": self._rate, "weight": self._weight}


#: quantiles estimated from bucket counts (snapshot keys p50/p90/p99)
PERCENTILES = ((0.5, "p50"), (0.9, "p90"), (0.99, "p99"))


def _quantile_from_counts(counts, total: int, q: float) -> float:
    """One q-quantile estimate over per-bucket counts against the
    static HIST_BUCKETS edges: linear interpolation inside the
    bucket, overflow bucket clamps to the last finite edge.  The
    single implementation behind ``percentiles_from_counts`` and
    ``Histogram.percentile`` — they must never diverge."""
    target = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= target:
            if i >= len(HIST_BUCKETS):
                return HIST_BUCKETS[-1]          # overflow bucket
            lo = HIST_BUCKETS[i - 1] if i > 0 else 0.0
            frac = (target - (cum - c)) / c if c else 1.0
            return lo + frac * (HIST_BUCKETS[i] - lo)
    return HIST_BUCKETS[-1]


def percentiles_from_counts(counts) -> Dict[str, float]:
    """p50/p90/p99 estimates from per-bucket counts.  Shared by
    ``Histogram.as_dict`` and ``aggregate._merge_hists`` so merged
    fleet histograms re-derive their quantiles from the merged
    counts instead of averaging per-worker quantiles (which would be
    wrong and non-associative)."""
    counts = [int(c) for c in counts]
    total = sum(counts)
    if total <= 0:
        return {}
    return {key: _quantile_from_counts(counts, total, q)
            for q, key in PERCENTILES}


class Histogram:
    """Fixed-bucket histogram: ``buckets[i]`` counts observations
    <= HIST_BUCKETS[i]; the final slot is the overflow bucket."""

    __slots__ = ("counts", "total", "sum")

    def __init__(self):
        self.counts = [0] * (len(HIST_BUCKETS) + 1)
        self.total = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        lo, hi = 0, len(HIST_BUCKETS)
        while lo < hi:                  # bisect over static edges
            mid = (lo + hi) // 2
            if v <= HIST_BUCKETS[mid]:
                hi = mid
            else:
                lo = mid + 1
        self.counts[lo] += 1
        self.total += 1
        self.sum += v

    def percentile(self, q: float) -> float:
        """Estimate the q-quantile (0 < q <= 1) from the buckets."""
        if self.total <= 0:
            return 0.0
        return _quantile_from_counts(self.counts, self.total, q)

    def as_dict(self) -> Dict[str, object]:
        d: Dict[str, object] = {"counts": list(self.counts),
                                "total": self.total, "sum": self.sum}
        d.update(percentiles_from_counts(self.counts))
        return d


class MetricsRegistry:
    """Named series, created on first touch; snapshot() is the wire
    format every consumer (sink, aggregate, manager, TUI) reads."""

    def __init__(self, time_fn=time.time):
        self._time = time_fn
        self.start_time = time_fn()
        self._run_start: Optional[float] = None
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.rates: Dict[str, EmaRate] = {}
        self.hists: Dict[str, Histogram] = {}

    # -- record calls (hot path) ---------------------------------------

    def count(self, name: str, n: float = 1.0) -> None:
        self.counters[name] = self.counters.get(name, 0.0) + n

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = float(value)

    def rate(self, name: str, n: float, tau: float = 30.0) -> None:
        r = self.rates.get(name)
        if r is None:
            r = self.rates[name] = EmaRate(tau)
        r.add(n)

    def observe(self, name: str, seconds: float) -> None:
        h = self.hists.get(name)
        if h is None:
            h = self.hists[name] = Histogram()
        h.observe(seconds)
        # stage time split wants totals, not just distributions
        self.count(name + "_seconds", seconds)

    # -- views ----------------------------------------------------------

    def elapsed(self) -> float:
        """Lifetime wall-clock: the ONE definition of campaign age
        (VERDICT item: the CLI and loop used to disagree)."""
        return max(self._time() - self.start_time, 1e-9)

    # -- run windows: rates divide by ACTIVE fuzzing time, so warm-up
    # gaps between run() calls (bench does this) don't dilute them --

    def run_started(self) -> None:
        self._run_start = self._time()

    def run_ended(self) -> None:
        if self._run_start is not None:
            self.count("run_seconds", self._time() - self._run_start)
            self._run_start = None

    def active_seconds(self) -> float:
        s = self.counters.get("run_seconds", 0.0)
        if self._run_start is not None:
            s += self._time() - self._run_start
        return s

    def execs_per_sec(self) -> float:
        """Lifetime rate over active run time (falls back to campaign
        age when the owner never marks run windows)."""
        e = self.active_seconds() or self.elapsed()
        return self.counters.get("execs", 0.0) / e

    def execs_per_sec_ema(self) -> float:
        r = self.rates.get("execs")
        return r.rate if r is not None else 0.0

    def stage_split(self) -> Dict[str, float]:
        """{stage: fraction of accounted stage time}, for the bench
        summary line and the TUI bar."""
        totals = {s: self.counters.get(s + "_seconds", 0.0)
                  for s in STAGES}
        acc = sum(totals.values())
        if acc <= 0:
            return {}
        return {s: t / acc for s, t in totals.items() if t > 0}

    def snapshot(self) -> Dict[str, object]:
        return {
            "t": self._time(),
            "start_time": self.start_time,
            "elapsed": self.elapsed(),
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "rates": {k: v.as_dict() for k, v in self.rates.items()},
            "hists": {k: v.as_dict() for k, v in self.hists.items()},
            "derived": {
                "execs_per_sec": self.execs_per_sec(),
                "execs_per_sec_ema": self.execs_per_sec_ema(),
            },
        }


class _Span:
    __slots__ = ("reg", "stage", "tracer", "_t0", "_lane")

    def __init__(self, reg: MetricsRegistry, stage: str, tracer=None):
        self.reg = reg
        self.stage = stage
        self.tracer = tracer
        self._t0 = 0.0
        self._lane = 0

    def __enter__(self) -> "_Span":
        if self.tracer is not None:
            # pin the lane at entry: the loop may retarget the
            # recorder's current lane mid-span (triaging another
            # batch inside a corpus_feedback span), and a B/E pair
            # split across lanes would corrupt both lanes' stacks
            self._lane = self.tracer.lane
            self.tracer.begin(self.stage)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.reg.observe(self.stage, time.perf_counter() - self._t0)
        if self.tracer is not None:
            self.tracer.end(self.stage, lane=self._lane)


class StageTimer:
    """Context-manager stopwatch over a registry's stage series.

    ``with timer("triage"): ...`` records one histogram observation
    plus the running ``<stage>_seconds`` counter.  Spans nest (an
    fs_write inside a triage span double-counts wall time by design:
    the split reports where the host spent attention, not a
    partition).  perf_counter is ~40ns per call; at one timing per
    batch (1k-64k execs) the overhead is unmeasurable.  No device
    syncs: callers time around materialization points that already
    exist.

    When a ``TraceRecorder`` is attached (``--trace``), every timed
    stage also records a begin/end span on the recorder's CURRENT
    lane — the fuzzing loop points that lane at the in-flight batch's
    pipeline slot, so the one instrumentation site feeds both the
    aggregate split and the flight-recorder timeline.
    """

    __slots__ = ("reg", "tracer")

    def __init__(self, registry: MetricsRegistry, tracer=None):
        self.reg = registry
        self.tracer = tracer

    def __call__(self, stage: str) -> _Span:
        return _Span(self.reg, stage, self.tracer)
