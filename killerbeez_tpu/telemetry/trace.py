"""Pipeline trace recorder — the flight recorder's span tier.

PR 1's registry answers "how much"; this module answers "WHEN".  A
``TraceRecorder`` is a bounded ring buffer of begin/end span and
instant events over ``perf_counter`` timestamps, cheap enough to sit
on the fuzzing loop's hot path (one tuple build + one list store per
record, no I/O, no locks — ~O(100ns)) and exported on demand as
Chrome trace-event JSON that Perfetto / ``chrome://tracing`` render
directly.  PTrix (arxiv 1905.10499) is the model: throughput problems
become debuggable when you can SEE per-batch pipeline occupancy, not
just aggregate counters.

Lane model: each event carries a ``lane`` (the Chrome ``tid``).  The
fuzzing loop assigns every in-flight batch one of ``PIPELINE_DEPTH``
pipeline lanes, so its mutate → dispatch → in-flight → transfer →
triage spans stack into one row per pipeline slot; cold stages
(crack, corpus sync, mesh shards) get named lanes of their own.
Lanes are registered by name (``lane_id("crack")``) and exported as
``thread_name`` metadata so the viewer labels the rows.

Ring discipline: when the buffer wraps, the OLDEST events are
overwritten — a long campaign keeps its most recent window, like a
hardware trace buffer.  Export rebalances: an ``E`` whose ``B`` was
overwritten is dropped, and spans still open at export time (a
mid-span shutdown) get synthetic closes, so the emitted JSON always
has balanced B/E pairs.

Tracing is OFF by default (``--trace [max_spans]`` / ``trace=``);
when off the loop never touches this module.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ..utils.logging import WARNING_MSG

#: default ring capacity in EVENTS (a span is two events); at ~6
#: events per batch this holds the last ~10k batches
DEFAULT_MAX_EVENTS = 1 << 16


class _LaneSpan:
    """Context manager: record one span, optionally on a named lane,
    restoring the recorder's current lane on exit (cold-path helper —
    the hot loop calls begin/end directly)."""

    __slots__ = ("tr", "name", "lane", "args", "_prev")

    def __init__(self, tr: "TraceRecorder", name: str,
                 lane: Optional[int], args: Optional[Dict]):
        self.tr = tr
        self.name = name
        self.lane = lane
        self.args = args
        self._prev = 0

    def __enter__(self) -> "_LaneSpan":
        self._prev = self.tr.lane
        if self.lane is not None:
            self.tr.lane = self.lane
        self.tr.begin(self.name, args=self.args)
        return self

    def __exit__(self, *exc) -> None:
        # end on the lane the span BEGAN on (code inside may have
        # retargeted the recorder), then restore the caller's lane
        self.tr.end(self.name,
                    lane=self.lane if self.lane is not None
                    else self._prev)
        self.tr.lane = self._prev


class TraceRecorder:
    """Bounded ring of trace events with Chrome trace-event export."""

    def __init__(self, max_events: int = DEFAULT_MAX_EVENTS,
                 clock=time.perf_counter, wall=time.time):
        self.max_events = max(int(max_events), 4)
        self._buf: List[Optional[tuple]] = [None] * self.max_events
        self._n = 0                      # events ever recorded
        self._clock = clock
        self._t0 = clock()
        #: wall-clock anchor for overlaying events.jsonl (wall times)
        #: onto the perf_counter span timeline
        self.wall_t0 = wall()
        #: current lane (Chrome tid); the loop points this at the
        #: in-flight batch's pipeline slot before dispatch/triage
        self.lane = 0
        self._lane_names: Dict[str, int] = {}
        self._next_lane = 64             # named lanes above the
        #                                  pipeline-slot range

    # -- hot path -------------------------------------------------------

    def begin(self, name: str, lane: Optional[int] = None,
              args: Optional[Dict[str, Any]] = None) -> None:
        self._buf[self._n % self.max_events] = (
            "B", name, self.lane if lane is None else lane,
            self._clock(), args, None)
        self._n += 1

    def end(self, name: str, lane: Optional[int] = None) -> None:
        self._buf[self._n % self.max_events] = (
            "E", name, self.lane if lane is None else lane,
            self._clock(), None, None)
        self._n += 1

    def instant(self, name: str, lane: Optional[int] = None,
                args: Optional[Dict[str, Any]] = None) -> None:
        self._buf[self._n % self.max_events] = (
            "i", name, self.lane if lane is None else lane,
            self._clock(), args, None)
        self._n += 1

    # async pairs (Chrome ph b/e): for operations that span OTHER
    # spans' boundaries — a batch's in-flight window opens at dispatch
    # and closes at triage, with arbitrary sync spans beginning and
    # ending in between on the same lane.  Sync B/E pairs are matched
    # by per-lane STACK discipline, which such an operation would
    # corrupt; async pairs match by (lane, name, id) instead.

    def async_begin(self, name: str, aid: int,
                    lane: Optional[int] = None,
                    args: Optional[Dict[str, Any]] = None) -> None:
        self._buf[self._n % self.max_events] = (
            "b", name, self.lane if lane is None else lane,
            self._clock(), args, aid)
        self._n += 1

    def async_end(self, name: str, aid: int,
                  lane: Optional[int] = None) -> None:
        self._buf[self._n % self.max_events] = (
            "e", name, self.lane if lane is None else lane,
            self._clock(), None, aid)
        self._n += 1

    # -- lanes ----------------------------------------------------------

    def lane_id(self, name: str) -> int:
        """Stable tid for a named lane (registered on first use)."""
        tid = self._lane_names.get(name)
        if tid is None:
            tid = self._lane_names[name] = self._next_lane
            self._next_lane += 1
        return tid

    def name_lane(self, tid: int, name: str) -> None:
        """Label an existing numeric lane (pipeline slots)."""
        self._lane_names[name] = int(tid)

    @property
    def recorded(self) -> int:
        return self._n

    @property
    def dropped(self) -> int:
        """Events lost to ring wrap-around."""
        return max(0, self._n - self.max_events)

    # -- export ---------------------------------------------------------

    def _ordered(self) -> List[tuple]:
        """Buffer contents oldest-first."""
        if self._n <= self.max_events:
            return [e for e in self._buf[:self._n]]
        i = self._n % self.max_events
        return self._buf[i:] + self._buf[:i]

    def to_chrome(self) -> Dict[str, Any]:
        """Chrome trace-event JSON object with BALANCED pairs: orphan
        ends (begin lost to ring wrap) are dropped, spans still open
        (mid-span shutdown) get a synthetic close at the last observed
        timestamp.  Sync B/E pairs balance per-lane by stack; async
        b/e pairs balance by (lane, name, id)."""
        events: List[Dict[str, Any]] = []
        pid = os.getpid()
        open_stacks: Dict[int, List[int]] = {}   # tid -> [event idx]
        open_async: Dict[tuple, int] = {}        # (tid,name,id) -> idx
        last_ts = 0.0
        for ev in self._ordered():
            ph, name, tid, t, args, aid = ev
            ts = (t - self._t0) * 1e6            # us, trace-relative
            last_ts = max(last_ts, ts)
            if ph == "E":
                stack = open_stacks.get(tid)
                if not stack:
                    continue                     # begin wrapped away
                stack.pop()
                events.append({"ph": "E", "name": name, "pid": pid,
                               "tid": tid, "ts": round(ts, 3)})
                continue
            if ph == "e":
                if open_async.pop((tid, name, aid), None) is None:
                    continue                     # begin wrapped away
                events.append({"ph": "e", "cat": "pipeline",
                               "id": aid, "name": name, "pid": pid,
                               "tid": tid, "ts": round(ts, 3)})
                continue
            rec = {"ph": ph, "name": name, "pid": pid, "tid": tid,
                   "ts": round(ts, 3)}
            if args:
                rec["args"] = args
            if ph == "B":
                open_stacks.setdefault(tid, []).append(len(events))
            elif ph == "b":
                rec["cat"] = "pipeline"
                rec["id"] = aid
                open_async[(tid, name, aid)] = len(events)
            elif ph == "i":
                rec["s"] = "t"                   # thread-scoped mark
            events.append(rec)
        # mid-span shutdown: close whatever is still open, innermost
        # first, so every begin has an end
        for tid, stack in open_stacks.items():
            for idx in reversed(stack):
                b = events[idx]
                events.append({"ph": "E", "name": b["name"],
                               "pid": pid, "tid": tid,
                               "ts": round(last_ts, 3)})
        for (tid, name, aid) in open_async:
            events.append({"ph": "e", "cat": "pipeline", "id": aid,
                           "name": name, "pid": pid, "tid": tid,
                           "ts": round(last_ts, 3)})
        for name, tid in sorted(self._lane_names.items(),
                                key=lambda kv: kv[1]):
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid, "tid": tid,
                           "args": {"name": name}})
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "tool": "killerbeez-tpu flight recorder",
                #: wall time of trace ts==0 — kb-timeline uses this to
                #: place events.jsonl records on the span timeline
                "wall_t0": self.wall_t0,
                "events_recorded": self._n,
                "events_dropped": self.dropped,
            },
        }

    def export(self, path: str) -> bool:
        """Atomically write the Chrome trace JSON; degrades to a
        warning (the sink's discipline — observability never kills a
        campaign)."""
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                # default=str absorbs non-JSON span/instant args
                # (numpy scalars, bytes): export runs in run()'s
                # finally and must never mask the run's real outcome
                json.dump(self.to_chrome(), f, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            return True
        except (OSError, TypeError, ValueError) as e:
            WARNING_MSG("trace export to %s failed: %s", path, e)
            return False

    # -- cold-path sugar ------------------------------------------------

    def span(self, name: str, lane: Optional[str] = None,
             args: Optional[Dict[str, Any]] = None) -> _LaneSpan:
        """``with tr.span("crack", lane="crack"): ...`` — records one
        span on a named lane and restores the previous lane."""
        tid = self.lane_id(lane) if lane is not None else None
        return _LaneSpan(self, name, tid, args)


def load_chrome_trace(path: str) -> Optional[Dict[str, Any]]:
    """Read a trace.json back (kb-timeline / tests)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
