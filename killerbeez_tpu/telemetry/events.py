"""Campaign event log — the flight recorder's history tier.

A finished campaign used to leave only aggregate counters; this
module gives it a durable timeline: ``events.jsonl`` under the output
dir records WHEN the things that shape a campaign happened — finds,
plateaus, crack injections, sync rounds, scheduler rotations, stats
flushes — one JSON record per line, schema-versioned, append-only.
FMViz (arxiv 2112.13207) is the model: post-hoc visualization of
campaign history is a first-class fuzzing tool, and it needs the
history to exist.

Record schema (``SCHEMA_VERSION`` 1)::

    {"v": 1, "seq": N, "t": <unix time>, "type": <EVENT_TYPES>,
     ...type-specific fields}

``seq`` is monotone per log file ACROSS restarts: a resumed campaign
scans the existing tail and continues numbering, so cursors
(``kb-timeline``, the manager's ``/api/events`` exchange) never see a
seq twice.  Writes follow the stats sink's crash discipline — one
buffered ``write()`` + flush per record, warnings instead of raises,
and readers skip torn tail lines.

Event type contract (what reconciles against ``fuzzer_stats``):

* ``new_path``  — one per ``new_paths`` counter increment
* ``crash``     — one per ``unique_crashes`` increment (the raw
  ``crashes`` running total rides in the record)
* ``hang``      — one per ``unique_hangs`` increment
* ``plateau`` / ``crack_injection`` — the crack stage's trigger and
  its injection outcome
* ``sync_round`` — one per corpus-sync round that ran
* ``scheduler_pick`` — one per seed rotation
* ``flush``     — one per stats-file flush
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterator, List, Optional

from ..resilience.chaos import chaos_point
from ..utils.fileio import ensure_dir
from ..utils.logging import WARNING_MSG

SCHEMA_VERSION = 1

EVENT_TYPES = ("new_path", "crash", "hang", "plateau",
               "crack_injection", "sync_round", "scheduler_pick",
               "flush",
               # fleet-observatory records (manager-origin: the
               # worker health registry and the alert evaluator emit
               # these into the same campaign stream)
               "worker_stale", "worker_dead", "worker_returned",
               "alert",
               # resilience records (resilience/): a dispatch the
               # watchdog had to kill, and a classified device loss
               # the supervisor will re-probe for
               "watchdog_stall", "device_lost",
               # --generations: the host-side replay of one device
               # seed-slot ring admission (the device-resident loop's
               # analogue of scheduler_pick + admission)
               "ring_admit",
               # partition-tolerant fleet (corpus/gossip.py +
               # quarantine.py): one peer-exchange round, a batch of
               # rejected synced-in entries, and a peer crossing the
               # poison threshold into a timed ban
               "gossip_round", "sync_quarantine", "peer_banned",
               # stateful session tier (killerbeez_tpu/stateful/):
               # the state x edge coverage high-water rose — pairs =
               # touched (state, edge) buckets, states = distinct
               # protocol states seen (kb-timeline's session section)
               "state_cov",
               # learned mutation shaping (killerbeez_tpu/learn/):
               # one completed on-device training round of the
               # byte-saliency model — version, label counts, the
               # final batch loss
               "learn_update",
               # hybrid bridge (killerbeez_tpu/hybrid/): one cross-
               # tier validation verdict — a TPU-tier finding
               # replayed on the real native binary, with md5, kind,
               # verdict (confirmed / proxy_only / flaky), repro
               # counts and wall time (docs/HYBRID.md)
               "cross_tier_validate",
               # hybrid bridge: a proxy_only divergence — the soft
               # proxy crashed where the native binary did not; the
               # event names the machine-readable gap report path
               "proxy_gap")

#: events a fleet worker forwards to the manager alongside heartbeats
TERMINAL_EVENTS = ("crash", "hang", "plateau")

EVENTS_FILE = "events.jsonl"

#: rotated-out predecessor (``--events-max-mb``): at most one
#: generation is kept — rotation replaces any previous ``.1``
ROTATED_SUFFIX = ".1"


def _resolve_path(path: str) -> str:
    if os.path.isdir(path) or not path.endswith(".jsonl"):
        return os.path.join(path, EVENTS_FILE)
    return path


def _scan_tail_seq(path: str, window: int) -> int:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - window))
            chunk = f.read()
    except OSError:
        return -1
    best = -1
    for line in chunk.splitlines():
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict):
                continue                 # foreign/scalar line
            best = max(best, int(rec.get("seq", -1)))
        except (ValueError, TypeError):
            continue                     # torn tail / truncated head
    return best


def last_event_seq(path: str, window: int = 1 << 16) -> int:
    """Highest seq among the readable records in the file's tail
    window (-1 when none) — the resume anchor.  O(1) in file size,
    torn-tail tolerant, same discipline as the heartbeat tailer.  A
    log that was just rotated (empty live file) anchors on the
    rotated predecessor's tail, so seq stays monotone across both
    rotation and ``--resume``."""
    path = _resolve_path(path)
    best = _scan_tail_seq(path, window)
    if best < 0:
        best = _scan_tail_seq(path + ROTATED_SUFFIX, window)
    return best


def read_events(path: str, since_seq: int = -1,
                types: Optional[List[str]] = None
                ) -> Iterator[Dict[str, Any]]:
    """Yield records with seq > ``since_seq`` (optionally filtered by
    type), skipping unparseable lines.  The rotated predecessor
    (``events.jsonl.1``) is read first when present, so consumers
    (kb-timeline, reconciliation) see one seamless stream across a
    ``--events-max-mb`` rotation."""
    path = _resolve_path(path)
    for p in (path + ROTATED_SUFFIX, path):
        try:
            f = open(p)
        except OSError:
            continue
        with f:
            for line in f:
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict):
                    continue
                try:
                    if int(rec.get("seq", -1)) <= since_seq:
                        continue
                except (TypeError, ValueError):
                    continue             # foreign/corrupt record

                if types is not None and rec.get("type") not in types:
                    continue
                yield rec


class EventLog:
    """Append-only writer for one campaign's ``events.jsonl``.

    ``fresh=True`` truncates any existing log and restarts seq at 0 —
    a NEW campaign reusing an output dir must not inherit the previous
    run's timeline (its counters restart too, so stale events would
    break reconciliation and re-forward old terminal events); the
    default continues the existing log's monotone seq (``--resume``).

    ``max_bytes`` (CLI ``--events-max-mb``) caps the live file: when
    an append pushes past the cap the file rotates to
    ``events.jsonl.1`` (replacing any previous generation) and a
    fresh live file continues the SAME monotone seq, so long
    campaigns hold at most two generations on disk while cursors and
    ``--resume`` anchors stay valid.  0 = unbounded (default).
    """

    def __init__(self, path: str, time_fn=time.time,
                 fresh: bool = False, max_bytes: int = 0):
        self.path = _resolve_path(path)
        self._time = time_fn
        self._fh = None
        self.max_bytes = int(max_bytes)
        self.rotations = 0
        try:
            ensure_dir(os.path.dirname(self.path) or ".")
        except OSError as e:
            WARNING_MSG("event log dir unavailable: %s", e)
        if fresh:
            try:
                open(self.path, "w").close()
            except OSError as e:
                WARNING_MSG("event log truncate failed: %s", e)
            # a stale rotated generation from a PREVIOUS campaign
            # must not leak into this timeline's readers
            try:
                os.unlink(self.path + ROTATED_SUFFIX)
            except OSError:
                pass
            self._seq = 0
        else:
            # monotone seq across --resume: continue past the
            # existing log
            self._seq = last_event_seq(self.path) + 1
        #: last emission wall time per type (the sink sources AFL's
        #: last_path / last_crash / last_hang from these)
        self.last_times: Dict[str, float] = {}

    @property
    def next_seq(self) -> int:
        return self._seq

    def ensure_seq_at_least(self, seq: int) -> None:
        """Raise the next seq to at least ``seq`` — the resume path
        floors the stream at the checkpoint's high-water so a torn or
        truncated log can never make seq regress for cursor
        consumers."""
        self._seq = max(self._seq, int(seq))

    def emit(self, etype: str, **fields) -> Dict[str, Any]:
        """Append one record; returns it (even when the write failed —
        in-process consumers still see the event)."""
        rec: Dict[str, Any] = {"v": SCHEMA_VERSION, "seq": self._seq,
                               "t": self._time(), "type": etype}
        rec.update(fields)
        self._seq += 1
        self.last_times[etype] = rec["t"]
        try:
            # chaos seam: the event append is a persistence path too
            # (ENOSPC degrades to the warning below; kill mode is the
            # mid-append power cut readers must heal from)
            chaos_point("event_append", path=self.path)
            if self._fh is None:
                self._fh = open(self.path, "a")
                # a previous process killed mid-append leaves a torn
                # tail with no newline; appending straight onto it
                # would weld two records into one unreadable line
                if self._fh.tell() > 0:
                    with open(self.path, "rb") as rf:
                        rf.seek(-1, os.SEEK_END)
                        if rf.read(1) != b"\n":
                            self._fh.write("\n")
            # one write() per record: a kill tears at most the tail
            # line, which every reader skips.  default=str absorbs
            # non-JSON field types (numpy scalars, bytes) — the
            # emitting tier must never be able to kill the campaign
            self._fh.write(json.dumps(rec, default=str) + "\n")
            self._fh.flush()
            if self.max_bytes > 0 and self._fh.tell() >= self.max_bytes:
                self._rotate()
        except (OSError, TypeError, ValueError) as e:
            WARNING_MSG("event log append failed: %s", e)
        return rec

    def _rotate(self) -> None:
        """Roll the live file to ``events.jsonl.1`` (previous
        generation replaced — the cap bounds TOTAL footprint at
        ~2x max_bytes); the next emit reopens a fresh live file and
        seq continues monotone from memory."""
        try:
            self._fh.close()
        except OSError:
            pass
        self._fh = None
        try:
            os.replace(self.path, self.path + ROTATED_SUFFIX)
            self.rotations += 1
        except OSError as e:
            # a persistently failing replace (.1 is a directory,
            # permissions) must not re-warn and re-attempt on every
            # subsequent emit — rotation turns itself off
            self.max_bytes = 0
            WARNING_MSG("event log rotation failed (%s); rotation "
                        "disabled, log grows unbounded", e)

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
