"""Campaign observability subsystem.

The reference fuzzer's only runtime signal is log lines plus a final
stats struct (fuzzer/main.c prints iteration counts at exit); this
package gives the port the AFL ecosystem's signals instead: a
process-local metrics registry with stage timers (metrics.py),
periodic AFL-compatible ``fuzzer_stats`` / ``plot_data`` /
``stats.jsonl`` writers (sink.py), and an associative snapshot merge
(aggregate.py) used by both the (dp, mp) mesh campaign fold and the
manager's ``/api/stats/<campaign>`` fleet view.  ``kb-stats``
(tools/stats_tui.py) renders either stream live.

Typical wiring (the Fuzzer does this itself; ``telemetry=False``
disables the file sink, the registry always runs):

    tl = Telemetry(output_dir="output")
    tl.registry.count("execs", 4096)
    with tl.timer("triage"):
        ...
    tl.maybe_flush()
"""

from __future__ import annotations

from typing import Dict, Optional

from .aggregate import merge, merge_two
from .metrics import (
    EmaRate, Histogram, MetricsRegistry, StageTimer, STAGES,
)
from .sink import StatsSink, parse_fuzzer_stats, read_latest_snapshot

__all__ = [
    "EmaRate", "Histogram", "MetricsRegistry", "StageTimer", "STAGES",
    "StatsSink", "Telemetry", "merge", "merge_two",
    "parse_fuzzer_stats", "read_latest_snapshot",
]


class Telemetry:
    """One campaign's registry + optional file sink, bundled."""

    def __init__(self, output_dir: Optional[str] = None,
                 interval_s: float = 5.0,
                 registry: Optional[MetricsRegistry] = None):
        self.registry = registry or MetricsRegistry()
        self.timer = StageTimer(self.registry)
        self.sink = (StatsSink(output_dir, self.registry, interval_s)
                     if output_dir else None)

    def maybe_flush(self) -> None:
        if self.sink is not None:
            self.sink.maybe_flush()

    def flush(self) -> None:
        if self.sink is not None:
            self.sink.flush()

    def snapshot(self) -> Dict[str, object]:
        return self.registry.snapshot()

    def stage_summary(self) -> str:
        """One-line stage-time split, e.g.
        ``stage split: execute 62% | triage 21% | ...`` (empty string
        before any stage has been timed)."""
        split = self.registry.stage_split()
        if not split:
            return ""
        parts = [f"{s} {f:.0%}" for s, f in
                 sorted(split.items(), key=lambda kv: -kv[1])]
        return "stage split: " + " | ".join(parts)
