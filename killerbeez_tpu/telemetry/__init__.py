"""Campaign observability subsystem.

The reference fuzzer's only runtime signal is log lines plus a final
stats struct (fuzzer/main.c prints iteration counts at exit); this
package gives the port the AFL ecosystem's signals instead: a
process-local metrics registry with stage timers (metrics.py),
periodic AFL-compatible ``fuzzer_stats`` / ``plot_data`` /
``stats.jsonl`` writers (sink.py), an associative snapshot merge
(aggregate.py) used by both the (dp, mp) mesh campaign fold and the
manager's ``/api/stats/<campaign>`` fleet view, and — the flight
recorder — a bounded ring-buffer span tracer with Chrome trace-event
export (trace.py) plus a typed append-only campaign event log
(events.py).  ``kb-stats`` (tools/stats_tui.py) renders the live
stream; ``kb-timeline`` (tools/timeline_tool.py) analyzes the
recorded one.

Typical wiring (the Fuzzer does this itself; ``telemetry=False``
disables the file sink, the registry always runs; ``trace=`` turns
the span recorder on):

    tl = Telemetry(output_dir="output", trace=True)
    tl.registry.count("execs", 4096)
    with tl.timer("triage"):
        ...
    tl.event("new_path", md5=digest)
    tl.maybe_flush()
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from .aggregate import merge, merge_events, merge_health, merge_two
from .events import (
    EVENT_TYPES, EVENTS_FILE, EventLog, SCHEMA_VERSION,
    TERMINAL_EVENTS, last_event_seq, read_events,
)
from .metrics import (
    EmaRate, Histogram, MetricsRegistry, StageTimer, STAGES,
    percentiles_from_counts,
)
from .openmetrics import render_snapshot as render_openmetrics
from .sink import StatsSink, parse_fuzzer_stats, read_latest_snapshot
from .trace import TraceRecorder, load_chrome_trace

__all__ = [
    "EVENT_TYPES", "EVENTS_FILE", "EmaRate", "EventLog", "Histogram",
    "MetricsRegistry", "SCHEMA_VERSION", "STAGES", "StageTimer",
    "StatsSink", "TERMINAL_EVENTS", "Telemetry", "TraceRecorder",
    "last_event_seq", "load_chrome_trace", "merge", "merge_events",
    "merge_health", "merge_two", "parse_fuzzer_stats",
    "percentiles_from_counts", "read_events", "read_latest_snapshot",
    "render_openmetrics",
]

#: event types whose emission stamps an AFL find-recency gauge (the
#: sink writes them as fuzzer_stats last_path/last_crash/last_hang;
#: gauges merge with max, so the fleet view shows the newest find)
_LAST_GAUGES = {"new_path": "last_path", "crash": "last_crash",
                "hang": "last_hang"}


class Telemetry:
    """One campaign's registry + optional file sink, event log and
    span recorder, bundled."""

    def __init__(self, output_dir: Optional[str] = None,
                 interval_s: float = 5.0,
                 registry: Optional[MetricsRegistry] = None,
                 trace=None, events=None,
                 fresh_events: bool = False,
                 events_max_bytes: int = 0):
        self.registry = registry or MetricsRegistry()
        # trace: None/False/0 = off; True = default ring; int = ring
        # capacity in events; a TraceRecorder passes through
        if trace is True:
            trace = TraceRecorder()
        elif isinstance(trace, bool):       # False
            trace = None
        elif isinstance(trace, int):
            trace = TraceRecorder(max_events=trace) if trace > 0 \
                else None
        self.trace: Optional[TraceRecorder] = trace
        self.timer = StageTimer(self.registry, trace)
        self.sink = (StatsSink(output_dir, self.registry, interval_s)
                     if output_dir else None)
        # the event log rides the sink by default: file-less runs
        # (bench loops, library callers) stay artifact-free.
        # fresh_events truncates an inherited log (a NEW campaign
        # reusing an output dir; --resume continues instead)
        if events is None:
            events = (EventLog(output_dir, fresh=fresh_events,
                               max_bytes=events_max_bytes)
                      if output_dir else None)
        elif events is False:
            events = None
        self.events: Optional[EventLog] = events

    def event(self, etype: str, **fields) -> None:
        """Record one campaign event: appends to events.jsonl (when
        the log is on), stamps the find-recency gauge, and drops an
        instant marker on the span timeline so Perfetto shows finds
        in place."""
        gauge = _LAST_GAUGES.get(etype)
        if gauge is not None:
            self.registry.gauge(gauge, time.time())
        if self.events is not None:
            self.events.emit(etype, **fields)
        if self.trace is not None:
            self.trace.instant(etype, args=fields or None)

    def maybe_flush(self) -> None:
        if self.sink is not None and self.sink.maybe_flush():
            self._note_flush()

    def flush(self) -> None:
        if self.sink is not None:
            self.sink.flush()
            self._note_flush()

    def _note_flush(self) -> None:
        if self.events is not None:
            self.events.emit(
                "flush",
                execs=int(self.registry.counters.get("execs", 0)))

    def export_trace(self, path: str) -> bool:
        """Write the span ring as Chrome trace JSON (no-op when
        tracing is off)."""
        if self.trace is None:
            return False
        return self.trace.export(path)

    def snapshot(self) -> Dict[str, object]:
        return self.registry.snapshot()

    def stage_summary(self) -> str:
        """One-line stage-time split, e.g.
        ``stage split: execute 62% | triage 21% | ...`` (empty string
        before any stage has been timed)."""
        split = self.registry.stage_split()
        if not split:
            return ""
        parts = [f"{s} {f:.0%}" for s, f in
                 sorted(split.items(), key=lambda kv: -kv[1])]
        return "stage split: " + " | ".join(parts)
