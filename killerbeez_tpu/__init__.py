"""killerbeez_tpu — a TPU-native fuzzing framework.

A from-scratch re-design of the Killerbeez fuzzing framework
(reference: grimm-co/killerbeez) for TPU hardware via JAX/XLA/Pallas.

The reference decomposes a fuzzer into three pluggable component types
(reference fuzzer/main.c): a *driver* delivers input to the target, an
*instrumentation* runs the target and classifies the outcome
(crash/hang/new-path), and a *mutator* generates candidate inputs.
killerbeez_tpu keeps that decomposition as its API but makes the inner
loop array-shaped: a batch of candidate inputs is a ``uint8[B, L]``
tensor, coverage is an AFL-style 64KB edge bitmap per lane, and
mutation -> execution -> novelty -> triage is one jitted step.

Package map:
  utils/            logging, JSON option parsing, state serialization
  ops/              coverage bitmap ops, hashing (device + host)
  mutators/         vmapped byte-tensor mutators behind the mutator vtable
  models/           the KBVM bytecode VM (TPU-native "QEMU mode") + targets
  instrumentation/  jit_harness / return_code / forkserver-afl backends
  drivers/          file / stdin / network drivers
  fuzzer/           the batched main loop + CLI
  parallel/         multi-chip shard_map tier (ICI coverage allreduce)
  tools/            merger / tracer / picker / minimize
  manager/          distributed job manager (REST + sqlite work queue)
  native/           C/C++ host-side exec backend (forkserver protocol)
"""

__version__ = "0.2.0"

MAP_SIZE_POW2 = 16
MAP_SIZE = 1 << MAP_SIZE_POW2  # AFL-compatible edge bitmap size (reference afl_progs/config.h:314-315)

# the fuzzer CLI's -b/--batch-size default, shared with the
# supervisor's mesh-degrade divisor check (a campaign that never
# passed -b must still shrink dp against the batch it actually runs)
DEFAULT_BATCH_SIZE = 1024

# Fuzz verdicts (reference killerbeez-utils global_types.h, via SURVEY §2.11)
FUZZ_NONE = 0
FUZZ_HANG = 1
FUZZ_CRASH = 2
FUZZ_RUNNING = 3
FUZZ_ERROR = 4

FUZZ_RESULT_NAMES = {
    FUZZ_NONE: "none",
    FUZZ_HANG: "hang",
    FUZZ_CRASH: "crash",
    FUZZ_RUNNING: "running",
    FUZZ_ERROR: "error",
}
