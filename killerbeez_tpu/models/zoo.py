"""The generated target zoo: parameterized KBVM program families
with PLANTED, CERTIFIED deep bugs.

Each family is a program GENERATOR over a small parameter space —
the knobs that make a bug blind-havoc-hostile by construction:

  * ``tlv``    — nested TLV headers (``depth`` levels): each level
                 pins a type byte and checks its length byte against
                 the measured remainder;
  * ``chain``  — a width-``width`` run of consecutive length fields,
                 each one byte, each required to equal the measured
                 input length minus its own offset (the mutual
                 consistency blind insert/delete always breaks);
  * ``cksum``  — a 32-bit little-endian magic (one wide compare — the
                 dictionary/derivation wide-constant shape) plus a
                 running sum/xor checksum over the payload
                 (``style``) that must match a header byte.

Behind the structure sits a COMMAND TOKEN field: a small operation
alphabet (the protocol's command set), with the planted bug behind
the one rare command the benign seed never uses.  ``bug`` widens the
token (2 + bug bytes), deepening the jackpot blind havoc would need.

The deep gate deliberately leaks NO incremental coverage: every
structural constraint and the trigger-command compare fold into one
verdict register and ONE branch into the crash block (an unchecked
wild store, so the deep edge and the crash coincide).  Blind
coverage-guided havoc cannot climb it byte-by-byte — it must hold
the whole header AND jackpot the trigger token in one candidate.  A
grammar-structured lane, by contrast, protects literals and lengths
by construction and reaches the trigger by ONE token substitution
from the field's alphabet.  That separation is what ``bench.py
--grammar`` A/B-gates.

Zoo targets resolve through the ordinary target registry under
``zoo:`` names — ``zoo:tlv:depth=2,bug=1`` — so every tool
(kb-lint, kb-solve, bench, --target options) takes them unchanged.
``build_zoo`` returns the full bundle: program, benign seed, crash
witness, deep edge, and the family's hand-written grammar.

Certification (``certify_zoo``, surfaced as ``kb-zoo certify`` and
the CI zoo lane): the program lints clean of errors, the benign seed
does NOT reach the deep edge, the constructed witness DOES crash
through it (exact concrete semantics), and the exact solver's
verdict on the deep edge is recorded (``sat`` where the constraint
walk is in reach; the checksum loop lands ``unknown`` by design —
the witness is then the certificate, same doctrine as magicsum_vm).
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple

from ..grammar.spec import Grammar, Rule, blob, length, lit, token
from .compiler import Assembler
from .vm import Program

#: registry-name prefix for generated targets
ZOO_PREFIX = "zoo:"

#: the bench/CI-gated family instances: deep enough that blind havoc
#: at bench budgets provably whiffs, shallow enough that a structured
#: campaign cracks them in seconds on CPU
GATED_NAMES = (
    "zoo:tlv:depth=2,bug=1",
    "zoo:chain:width=3,bug=1",
    "zoo:cksum:style=sum,bug=1",
)

_DEFAULTS = {
    "tlv": {"depth": 2, "bug": 1},
    "chain": {"width": 3, "bug": 1},
    "cksum": {"style": "sum", "bug": 1},
}
_BOUNDS = {"depth": (1, 4), "width": (1, 6), "bug": (0, 4)}

def _tokens(bug: int) -> Tuple[Tuple[bytes, ...], bytes]:
    """The command alphabet at planted-bug depth ``bug``: four benign
    operation tokens plus the trigger, all ``2 + bug`` bytes wide."""
    w = 2 + bug
    benign = tuple(bytes([k, k << 4]).ljust(w, b"\x00")
                   for k in range(1, 5))
    trigger = (b"\xEE\x66" + b"\xEE" * bug)[:w]
    return benign + (trigger,), trigger


class ZooTarget(NamedTuple):
    name: str                       # canonical "zoo:..." name
    family: str
    params: Dict
    program: Program
    seed: bytes                     # benign: every guard but the bug
    crash: bytes                    # witness: crashes through the bug
    deep_edge: Tuple[int, int]      # (from_block, to_block)
    grammar: Grammar                # the family's structure spec


def parse_zoo_name(name: str) -> Tuple[str, Dict]:
    """``zoo:family[:k=v,...]`` -> (family, full param dict)."""
    if not name.startswith(ZOO_PREFIX):
        raise ValueError(f"not a zoo target name: {name!r}")
    rest = name[len(ZOO_PREFIX):]
    family, _, raw = rest.partition(":")
    if family not in _DEFAULTS:
        raise ValueError(
            f"unknown zoo family {family!r}; known: "
            f"{', '.join(sorted(_DEFAULTS))}")
    params = dict(_DEFAULTS[family])
    for item in filter(None, raw.split(",")):
        k, eq, v = item.partition("=")
        if not eq or k not in params:
            raise ValueError(
                f"bad zoo parameter {item!r} for family {family!r} "
                f"(knobs: {', '.join(sorted(params))})")
        params[k] = v if k == "style" else int(v)
    for k, v in params.items():
        if k == "style":
            if v not in ("sum", "xor"):
                raise ValueError("cksum style must be sum or xor")
        else:
            lo, hi = _BOUNDS[k]
            if not (lo <= v <= hi):
                raise ValueError(
                    f"zoo {k}={v} out of range [{lo}, {hi}]")
    return family, params


def zoo_name(family: str, params: Dict) -> str:
    """Canonical name (sorted knobs) for a (family, params) pair."""
    items = ",".join(f"{k}={params[k]}" for k in sorted(params))
    return f"{ZOO_PREFIX}{family}:{items}"


class _Gen:
    """Assembler wrapper that counts blocks, so generators can report
    the deep edge as (guard_block, win_block) indices directly."""

    def __init__(self, name: str, mem_size: int, max_steps: int):
        self.a = Assembler(name, mem_size=mem_size,
                           max_steps=max_steps)
        self.nb = 0

    def block(self) -> int:
        self.a.block()
        self.nb += 1
        return self.nb - 1

    def expect(self, index: int, value: int, fail: str) -> int:
        """expect_byte + its match-path block; returns that block."""
        self.a.expect_byte(1, 2, index, value, fail)
        self.nb += 1
        return self.nb - 1

    def win(self) -> None:
        """The planted bug: an unchecked wild store, then halt."""
        self.a.ldi(6, -1)
        self.a.ldi(7, 1)
        self.a.stm(6, 7)
        self.a.halt(0)

    # -- fused-verdict folds (r6 accumulates, 0 = all constraints
    # -- hold; ONE branch consumes it — no incremental coverage leak)

    def acc_init(self) -> None:
        self.a.ldi(6, 0)

    def fold_byte(self, index: int, value: int) -> None:
        """r6 |= input[index] ^ value."""
        a = self.a
        a.ldi(1, index)
        a.ldb(2, 1)
        a.ldi(3, value)
        a.alu("xor", 4, 2, 3)
        a.alu("or", 6, 6, 4)

    def fold_len(self, index: int, offset: int) -> None:
        """r6 |= input[index] ^ (len - offset) (r5 holds len)."""
        a = self.a
        a.ldi(1, index)
        a.ldb(2, 1)
        a.ldi(3, offset)
        a.alu("sub", 4, 5, 3)
        a.alu("xor", 4, 2, 4)
        a.alu("or", 6, 6, 4)

    def verdict(self, fail: str) -> Tuple[int, int]:
        """The single deep branch: r6 != 0 -> fail, else fall into
        the win block (wild store).  Returns (guard, win) blocks."""
        guard = self.nb - 1
        self.a.ldi(3, 0)
        self.a.br("ne", 6, 3, fail)
        win = self.block()
        self.win()
        return guard, win


def _gen_tlv(depth: int, bug: int):
    g = _Gen(f"zoo_tlv_d{depth}", mem_size=16, max_steps=1024)
    a = g.a
    tokens, trigger = _tokens(bug)
    W = len(trigger)
    header = 2 + 2 * depth
    total = header + W + 2
    g.block()                               # entry
    a.load_len(5)
    a.ldi(3, total)
    a.br("lt", 5, 3, "exit")
    g.block()
    # the magic is ordinary shallow coverage (blind climbs it fine)
    g.expect(0, ord("Z"), "exit")
    g.expect(1, ord("1"), "exit")
    g.acc_init()
    for i in range(1, depth + 1):
        g.fold_byte(2 * i, 0x10 + (i - 1))
        # level i's length byte == measured remainder after its header
        g.fold_len(2 * i + 1, 2 + 2 * i)
    for j, tb in enumerate(trigger):
        g.fold_byte(header + j, tb)
    guard, win = g.verdict("exit")
    a.label("exit")
    g.block()
    a.halt(0)
    prog = a.build(block_seed=0x200 + depth * 16 + bug)

    def body(tok: bytes) -> bytes:
        pay = tok + b"\x00\x00"
        out = bytearray(b"Z1")
        for i in range(1, depth + 1):
            out += bytes([0x10 + (i - 1),
                          2 * (depth - i) + len(pay)])
        return bytes(out) + pay

    return prog, body(tokens[0]), body(trigger), (guard, win)


def _gen_chain(width: int, bug: int):
    g = _Gen(f"zoo_chain_w{width}", mem_size=16, max_steps=1024)
    a = g.a
    tokens, trigger = _tokens(bug)
    W = len(trigger)
    header = 1 + width
    total = header + W + 2
    g.block()                               # entry
    a.load_len(5)
    a.ldi(3, total)
    a.br("lt", 5, 3, "exit")
    g.block()
    g.expect(0, 0xC5, "exit")
    g.acc_init()
    for i in range(1, width + 1):
        # field i (at position i) == len - (i + 1): consecutive
        # fields differ by exactly 1 and the last measures the tail
        g.fold_len(i, i + 1)
    for j, tb in enumerate(trigger):
        g.fold_byte(header + j, tb)
    guard, win = g.verdict("exit")
    a.label("exit")
    g.block()
    a.halt(0)
    prog = a.build(block_seed=0x300 + width * 16 + bug)

    def body(tok: bytes) -> bytes:
        pay = tok + b"\x00\x00"
        total_b = header + len(pay)
        fields = bytes(total_b - (i + 1) for i in range(1, width + 1))
        return bytes([0xC5]) + fields + pay

    return prog, body(tokens[0]), body(trigger), (guard, win)


_CKSUM_MAGIC = 0x4D534B43               # "CKSM" little-endian


def _cksum(style: str, payload: bytes) -> int:
    if style == "sum":
        return sum(payload) & 0xFF
    ck = 0
    for x in payload:
        ck ^= x
    return ck


def _gen_cksum(style: str, bug: int):
    g = _Gen(f"zoo_cksum_{style}", mem_size=16, max_steps=2048)
    a = g.a
    tokens, trigger = _tokens(bug)
    W = len(trigger)
    pay0 = 5 + W                            # payload start (summed)
    total = pay0 + 2
    g.block()                               # entry
    a.load_len(5)
    a.ldi(3, total)
    a.br("lt", 5, 3, "exit")
    g.block()                               # assemble 32-bit LE magic
    a.ldi(6, 256)
    a.ldi(1, 3)
    a.ldb(3, 1)
    for i in (2, 1, 0):
        a.alu("mul", 3, 3, 6)
        a.ldi(1, i)
        a.ldb(2, 1)
        a.alu("add", 3, 3, 2)               # r3 = LE word b0..b3
    a.ldi(4, _CKSUM_MAGIC >> 16)            # LDI is 2^24-bounded:
    a.ldi(7, 16)                            # build the word hi/lo
    a.alu("shl", 4, 4, 7)
    a.ldi(7, _CKSUM_MAGIC & 0xFFFF)
    a.alu("or", 4, 4, 7)
    a.br("ne", 3, 4, "exit")                # ONE wide compare
    g.block()
    a.ldi(7, 0)                             # checksum acc
    a.ldi(1, pay0)                          # i = payload start: the
    #                                         command token is NOT
    #                                         summed, so a token
    #                                         substitution keeps the
    #                                         seed's checksum valid
    g.block()                               # loop head
    a.label("ck_loop")
    a.br("ge", 1, 5, "ck_cmp")
    g.block()                               # body
    a.ldb(2, 1)
    a.alu("add" if style == "sum" else "xor", 7, 7, 2)
    a.addi(1, 1, 1)
    a.jmp("ck_loop")
    a.label("ck_cmp")
    g.block()
    g.acc_init()                            # r6 = verdict
    a.ldi(3, 255)
    a.alu("and", 7, 7, 3)                   # acc & 0xFF
    a.ldi(1, 4)
    a.ldb(2, 1)                             # stored checksum byte
    a.alu("xor", 4, 2, 7)
    a.alu("or", 6, 6, 4)
    for j, tb in enumerate(trigger):
        g.fold_byte(5 + j, tb)
    guard, win = g.verdict("exit")
    a.label("exit")
    g.block()
    a.halt(0)
    prog = a.build(block_seed=0x400 + (style == "xor") * 16 + bug)

    def body(tok: bytes) -> bytes:
        pay = b"\x01\x02"
        return b"CKSM" + bytes([_cksum(style, pay)]) + tok + pay

    return prog, body(tokens[0]), body(trigger), (guard, win)


def _cmd_field(bug: int):
    """The command-token field: the full operation alphabet, trigger
    included — a structured lane reaches the planted bug by ONE token
    substitution here."""
    alpha, _ = _tokens(bug)
    return token(list(alpha), width=2 + bug)


def _grammar_tlv(depth: int, bug: int) -> Grammar:
    fields = [lit(b"Z1")]
    for i in range(depth):
        fields.append(lit(bytes([0x10 + i])))
        # every level's length byte tracks total-length deltas; the
        # innermost parse-measures the tail exactly
        fields.append(length(of="tail", width=1))
    fields.append(_cmd_field(bug))
    fields.append(blob(0, name="tail"))
    return Grammar(rules={"msg": Rule("msg", tuple(fields))},
                   start="msg")


def _grammar_chain(width: int, bug: int) -> Grammar:
    fields = [lit(b"\xC5")]
    fields += [length(of="tail", width=1) for _ in range(width)]
    fields.append(_cmd_field(bug))
    fields.append(blob(0, name="tail"))
    return Grammar(rules={"msg": Rule("msg", tuple(fields))},
                   start="msg")


def _grammar_cksum(style: str, bug: int) -> Grammar:
    fields = (lit(b"CKSM"), blob(1, name="ck"), _cmd_field(bug),
              blob(0, name="tail"))
    return Grammar(rules={"msg": Rule("msg", fields)}, start="msg")


_FAMILIES = {
    "tlv": (lambda p: _gen_tlv(p["depth"], p["bug"]),
            lambda p: _grammar_tlv(p["depth"], p["bug"])),
    "chain": (lambda p: _gen_chain(p["width"], p["bug"]),
              lambda p: _grammar_chain(p["width"], p["bug"])),
    "cksum": (lambda p: _gen_cksum(p["style"], p["bug"]),
              lambda p: _grammar_cksum(p["style"], p["bug"])),
}


def zoo_families() -> Dict[str, Dict]:
    """family -> default parameter dict (the generator knobs)."""
    return {k: dict(v) for k, v in _DEFAULTS.items()}


def build_zoo(name: str) -> ZooTarget:
    """Generate the full bundle for one ``zoo:...`` name
    (deterministic: same name, same program bytes)."""
    family, params = parse_zoo_name(name)
    gen, gram = _FAMILIES[family]
    program, seed, crash, deep_edge = gen(params)
    return ZooTarget(name=zoo_name(family, params), family=family,
                     params=params, program=program, seed=seed,
                     crash=crash, deep_edge=deep_edge,
                     grammar=gram(params))


def zoo_program(name: str) -> Program:
    """The target-registry hook: just the Program."""
    return build_zoo(name).program


def certify_zoo(name: str, solve_budget: int = 20000) -> Dict:
    """Certify one zoo instance's planted bug at generation time.

    Hard requirements (``certified``): lints clean of errors, the
    benign seed misses the deep edge AND exits clean, the witness
    crashes THROUGH the deep edge under exact concrete semantics.
    The solver verdict is recorded alongside (``sat`` = the edge is
    also constraint-walk reachable; ``unknown`` on the checksum
    family's loop is expected and fine — the witness certifies)."""
    from .. import FUZZ_CRASH, FUZZ_NONE
    from ..analysis.lint import SEV_ERROR, lint_program
    from ..analysis.solver import concrete_run, solve_edge

    t = build_zoo(name)
    findings = lint_program(t.program)
    errors = [f.as_dict() for f in findings
              if f.severity == SEV_ERROR]
    seed_tr = concrete_run(t.program, t.seed)
    crash_tr = concrete_run(t.program, t.crash)
    seed_ok = (t.deep_edge not in seed_tr.edges
               and seed_tr.status == FUZZ_NONE)
    crash_ok = (t.deep_edge in crash_tr.edges
                and crash_tr.status == FUZZ_CRASH)
    sv = solve_edge(t.program, t.deep_edge, budget=solve_budget)
    return {
        "name": t.name,
        "deep_edge": [int(t.deep_edge[0]), int(t.deep_edge[1])],
        "lint_errors": errors,
        "seed_benign": bool(seed_ok),
        "witness_crashes": bool(crash_ok),
        "solver": sv.status,
        "certified": bool(not errors and seed_ok and crash_ok),
    }
