"""CGC-grade KBVM targets — device-side ports of the realistic corpus
parsers (corpus/tlvstack.c, corpus/imgparse.c).

These are the bench/flagship targets: ~100+ basic blocks, loops with
hit-count variation, multi-stage validation, and planted memory bugs
expressed through the KBVM's native unsafety (out-of-bounds LDM/STM
crashes the lane — the analogue of the C versions' wild-pointer
SIGSEGVs).  They replace the role of the reference's prebuilt CGC
challenge binaries (/root/reference/corpus/cgc/) with original
programs.

Register conventions (r0 is never written => always 0):
  tlvstack_vm: r1=ip  r2=op  r3=arg  r4,r5,r7=scratch  r6=sp
  imgparse_vm: r1=off r2=type r3=len r4,r5,r7=scratch  r6=field
"""

from __future__ import annotations

from .compiler import Assembler
from .vm import Program
from .targets import register_target

# tlvstack_vm memory map (mem_size=72):
#   [0..31]  operand stack     [32..47] slots
#   [48]     privilege flag    [49..71] scratch for PRIV leaves
_STACK_BASE = 0
_STACK_MAX = 32
_SLOT_BASE = 32
_PRIV_FLAG = 48
_KEYWORD = b"KBVMLOCK"


def _need_stack(a: Assembler, min_depth: int, bad: str) -> None:
    """Branch to ``bad`` unless sp (r6) >= min_depth; starts a block
    on the ok path."""
    a.ldi(5, min_depth)
    a.br("lt", 6, 5, bad)
    a.block()


def _need_room(a: Assembler, bad: str) -> None:
    """Branch to ``bad`` unless sp (r6) < STACK_MAX."""
    a.ldi(5, _STACK_MAX)
    a.br("ge", 6, 5, bad)


@register_target("tlvstack_vm")
def tlvstack_vm() -> Program:
    """KBVM port of corpus/tlvstack.c: "STK1" magic then [op][arg]
    command pairs driving an operand-stack machine.

    Ops 0x01..0x0b mirror the C target (PUSH/POP/ADD/MUL/DUP/STORE/
    LOAD/PICK/SWAP/SIND/HALT) including both planted bugs:

      * PICK bounds `depth` against sp*8 instead of sp — out-of-range
        picks read clamped garbage (the C build reads mapped garbage
        below the stack; the VM clamps to 0 for the same effect);
      * SIND range-checks the popped address with a SIGNED `addr < 16`
        so a negative address (e.g. from MUL wraparound) passes and
        the store lands below the slot array — far-negative addresses
        leave the VM memory entirely (lane crash, the SIGSEGV
        analogue) while small negatives silently corrupt the stack.

    Two extra tiers give the target CGC-scale block count:
      * 0x0c KEY — match the next 8 input bytes against "KBVMLOCK"
        byte-by-byte (one block per matched byte) to set a privilege
        flag;
      * 0x0d PRIV — requires the flag; dispatches arg through a
        5-level binary tree to one of 32 leaf routines (63 blocks).
    """
    a = Assembler("tlvstack_vm", mem_size=72, max_steps=1024)

    a.block()                                     # entry
    a.load_len(4)
    a.ldi(5, 4)
    a.br("lt", 4, 5, "bad")
    a.block()
    a.expect_byte(4, 5, 0, ord("S"), "bad")
    a.expect_byte(4, 5, 1, ord("T"), "bad")
    a.expect_byte(4, 5, 2, ord("K"), "bad")
    a.expect_byte(4, 5, 3, ord("1"), "bad")
    a.ldi(1, 4)                                   # ip = 4
    a.ldi(6, _STACK_BASE)                         # sp = 0

    a.label("loop")
    a.block()                                     # loop head
    a.load_len(4)
    a.addi(5, 1, 2)
    a.br("lt", 4, 5, "bad")                       # need ip+2 <= len
    a.block()                                     # fetch block
    a.ldb(2, 1)                                   # op = input[ip]
    a.addi(5, 1, 1)
    a.ldb(3, 5)                                   # arg = input[ip+1]
    a.addi(1, 1, 2)
    for op, handler in [(0x01, "op_push"), (0x02, "op_pop"),
                        (0x03, "op_add"), (0x04, "op_mul"),
                        (0x05, "op_dup"), (0x06, "op_store"),
                        (0x07, "op_load"), (0x08, "op_pick"),
                        (0x09, "op_swap"), (0x0a, "op_sind"),
                        (0x0b, "op_halt"), (0x0c, "op_key"),
                        (0x0d, "op_priv")]:
        a.ldi(5, op)
        a.br("eq", 2, 5, handler)
    a.jmp("bad")

    a.label("op_push")
    a.block()
    _need_room(a, "bad")
    a.block()
    a.stm(6, 3)                                   # mem[sp] = arg
    a.addi(6, 6, 1)
    a.jmp("loop")

    a.label("op_pop")
    a.block()
    _need_stack(a, 1, "bad")
    a.addi(6, 6, -1)
    a.jmp("loop")

    for name, alu in [("op_add", "add"), ("op_mul", "mul")]:
        a.label(name)
        a.block()
        _need_stack(a, 2, "bad")
        a.addi(6, 6, -1)
        a.ldm(4, 6)                               # a = pop
        a.addi(6, 6, -1)
        a.ldm(5, 6)                               # b = pop
        a.alu(alu, 4, 4, 5)
        a.stm(6, 4)                               # push result
        a.addi(6, 6, 1)
        a.jmp("loop")

    a.label("op_dup")
    a.block()
    _need_stack(a, 1, "bad")
    _need_room(a, "bad")
    a.addi(5, 6, -1)
    a.ldm(4, 5)
    a.stm(6, 4)
    a.addi(6, 6, 1)
    a.jmp("loop")

    a.label("op_store")
    a.block()
    a.ldi(5, 16)
    a.br("ge", 3, 5, "bad")                       # arg < 16
    a.block()
    _need_stack(a, 1, "bad")
    a.addi(6, 6, -1)
    a.ldm(4, 6)
    a.addi(5, 3, _SLOT_BASE)
    a.stm(5, 4)                                   # slots[arg] = pop
    a.jmp("loop")

    a.label("op_load")
    a.block()
    a.ldi(5, 16)
    a.br("ge", 3, 5, "bad")
    a.block()
    _need_room(a, "bad")
    a.addi(5, 3, _SLOT_BASE)
    a.ldm(4, 5)
    a.stm(6, 4)
    a.addi(6, 6, 1)
    a.jmp("loop")

    a.label("op_pick")
    a.block()
    _need_stack(a, 1, "bad")
    _need_room(a, "bad")
    a.ldi(7, 3)
    a.alu("shl", 5, 6, 7)                         # r5 = sp * 8
    a.br("ge", 3, 5, "bad")                       # BUG: depth < sp*8
    a.block()
    a.addi(5, 6, -1)
    a.alu("sub", 5, 5, 3)                         # idx = sp-1-depth
    a.br("ge", 5, 0, "pick_ok")                   # idx >= 0?
    a.block()                                     # under-stack pick:
    a.ldi(5, 0)                                   # clamped garbage read
    a.label("pick_ok")
    a.block()
    a.ldm(4, 5)
    a.stm(6, 4)
    a.addi(6, 6, 1)
    a.jmp("loop")

    a.label("op_swap")
    a.block()
    _need_stack(a, 2, "bad")
    a.addi(5, 6, -1)
    a.ldm(4, 5)                                   # top
    a.addi(7, 6, -2)
    a.ldm(2, 7)                                   # below (r2 free now)
    a.stm(5, 2)
    a.stm(7, 4)
    a.jmp("loop")

    a.label("op_sind")
    a.block()
    _need_stack(a, 2, "bad")
    a.addi(6, 6, -1)
    a.ldm(4, 6)                                   # addr = pop
    a.addi(6, 6, -1)
    a.ldm(7, 6)                                   # val = pop
    a.ldi(5, 16)
    a.br("ge", 4, 5, "bad")                       # BUG: signed compare,
    a.block()                                     # negatives pass
    a.addi(5, 4, _SLOT_BASE)
    a.stm(5, 7)                                   # far-negative addr ->
    a.jmp("loop")                                 # OOB store -> crash

    a.label("op_halt")
    a.block()
    a.halt(0)

    # --- 0x0c KEY: byte-wise keyword match sets the privilege flag ---
    a.label("op_key")
    a.block()
    a.load_len(4)
    a.addi(5, 1, len(_KEYWORD))
    a.br("lt", 4, 5, "bad")                       # need 8 more bytes
    a.block()
    for i, ch in enumerate(_KEYWORD):
        a.addi(4, 1, i)
        a.ldb(4, 4)                               # input[ip+i]
        a.ldi(5, ch)
        a.br("ne", 4, 5, "bad")
        a.block()                                 # one block per match
    a.addi(1, 1, len(_KEYWORD))                   # consume keyword
    a.ldi(4, _PRIV_FLAG)
    a.ldi(5, 1)
    a.stm(4, 5)                                   # priv = 1
    a.jmp("loop")

    # --- 0x0d PRIV: 5-level binary dispatch to 32 leaf routines ---
    a.label("op_priv")
    a.block()
    a.ldi(4, _PRIV_FLAG)
    a.ldm(4, 4)
    a.ldi(5, 1)
    a.br("ne", 4, 5, "bad")                       # needs privilege
    a.block()

    # root: reject arg >= 32, then walk the tree
    a.ldi(5, 32)
    a.br("lt", 3, 5, "node_0_32")
    a.jmp("bad")

    def _tree(lo: int, hi: int) -> None:
        """Emit the arg-dispatch subtree for leaves [lo, hi): internal
        nodes branch on arg >= mid; each node and leaf is one block."""
        if hi - lo == 1:
            a.label(f"leaf_{lo}")
            a.block()                             # leaf block
            # distinct tiny computation: scratch[49 + lo % 23] += lo+1
            a.ldi(4, 49 + lo % 23)
            a.ldm(5, 4)
            a.addi(5, 5, lo + 1)
            a.stm(4, 5)
            a.jmp("loop")
            return
        mid = (lo + hi) // 2
        a.label(f"node_{lo}_{hi}")
        a.block()                                 # internal node block
        a.ldi(5, mid)
        hi_target = f"node_{mid}_{hi}" if hi - mid > 1 else f"leaf_{mid}"
        a.br("ge", 3, 5, hi_target)
        lo_target = f"node_{lo}_{mid}" if mid - lo > 1 else f"leaf_{lo}"
        a.jmp(lo_target)
        _tree(lo, mid)
        _tree(mid, hi)

    _tree(0, 32)

    a.label("bad")
    a.block()
    a.halt(1)
    return a.build(block_seed=0x57AC)


# imgparse_vm memory map (mem_size=136):
#   [0..63]    framebuffer (8x8 max at first-header time)
#   [64..127]  palette (64 entries)
#   [128] w  [129] h  [130] have_header  [131] pal_count  [132] rows
_FB_BASE = 0
_FB_CAP = 64                  # 8x8
_PAL_BASE = 64
_F_W, _F_H, _F_HAVE, _F_PALCNT, _F_ROWS = 128, 129, 130, 131, 132


@register_target("imgparse_vm")
def imgparse_vm() -> Program:
    """KBVM port of corpus/imgparse.c: "QIMG" magic then chunks
    [type][len][payload...][cksum], cksum = sum(payload) & 0xFF.

    Chunk types mirror the C target ('H' header / 'P' palette /
    'D' data row / 'C' comment / 'E' end) with both planted bugs:

      * header re-send skips the framebuffer bound check (only the
        FIRST header is validated against the 8x8 buffer; later ones
        just overwrite w/h up to the 40x40 "sanity" cap), so a second,
        larger header makes the next row store at row*w past the
        framebuffer — out of VM memory entirely -> lane crash;
      * palette lookup indexes mem[PAL_BASE + pixel] without checking
        the pixel against pal_count: pixels >= 72 run off the end of
        VM memory -> lane crash (the C build reads mapped garbage;
        the VM's bound is tighter so the same bug is observable).
    """
    a = Assembler("imgparse_vm", mem_size=136, max_steps=1024)

    a.block()                                     # entry
    a.load_len(4)
    a.ldi(5, 4)
    a.br("lt", 4, 5, "bad")
    a.block()
    a.expect_byte(4, 5, 0, ord("Q"), "bad")
    a.expect_byte(4, 5, 1, ord("I"), "bad")
    a.expect_byte(4, 5, 2, ord("M"), "bad")
    a.expect_byte(4, 5, 3, ord("G"), "bad")
    a.ldi(1, 4)                                   # off = 4

    a.label("chunk_loop")
    a.block()
    a.load_len(4)
    a.addi(5, 1, 2)
    a.br("lt", 4, 5, "bad")                       # need type+len bytes
    a.block()
    a.ldb(2, 1)                                   # type
    a.addi(5, 1, 1)
    a.ldb(3, 5)                                   # len
    a.addi(1, 1, 2)                               # off -> payload
    a.addi(5, 1, 1)
    a.alu("add", 5, 5, 3)
    a.br("lt", 4, 5, "bad")                       # payload+cksum present

    # checksum loop: r6 = i, r7 = acc
    a.block()
    a.ldi(6, 0)
    a.ldi(7, 0)
    a.label("ck_loop")
    a.br("ge", 6, 3, "ck_done")
    a.block()                                     # hit-count bucket
    a.alu("add", 4, 1, 6)
    a.ldb(4, 4)
    a.alu("add", 7, 7, 4)
    a.addi(6, 6, 1)
    a.jmp("ck_loop")
    a.label("ck_done")
    a.block()
    a.ldi(5, 255)
    a.alu("and", 7, 7, 5)
    a.alu("add", 4, 1, 3)
    a.ldb(4, 4)                                   # stored cksum
    a.br("ne", 7, 4, "bad")
    a.block()

    for ch, handler in [("H", "h_chunk"), ("P", "p_chunk"),
                        ("D", "d_chunk"), ("C", "consume"),
                        ("E", "e_chunk")]:
        a.ldi(5, ord(ch))
        a.br("eq", 2, 5, handler)
    a.jmp("bad")

    a.label("consume")                            # shared chunk epilogue
    a.block()
    a.addi(1, 1, 1)
    a.alu("add", 1, 1, 3)                         # off += len + 1
    a.jmp("chunk_loop")

    # ---- 'H': [w, h, depth] ----
    a.label("h_chunk")
    a.block()
    a.ldi(5, 3)
    a.br("ne", 3, 5, "bad")                       # len == 3
    a.block()
    a.ldb(4, 1)                                   # w = payload[0]
    a.addi(5, 1, 1)
    a.ldb(6, 5)                                   # h = payload[1]
    a.addi(5, 1, 2)
    a.ldb(7, 5)                                   # d = payload[2]
    a.ldi(5, 1)
    a.br("lt", 4, 5, "bad")                       # w >= 1
    a.br("lt", 6, 5, "bad")                       # h >= 1
    a.ldi(5, 40)
    a.br("ge", 4, 5, "bad")                       # w < 40 ("sanity")
    a.br("ge", 6, 5, "bad")                       # h < 40
    a.block()
    for d, lbl in [(1, "d_ok"), (2, "d_ok"), (4, "d_ok"), (8, "d_ok")]:
        a.ldi(5, d)
        a.br("eq", 7, 5, lbl)
    a.jmp("bad")
    a.label("d_ok")
    a.block()
    a.ldi(5, _F_HAVE)
    a.ldm(5, 5)
    a.ldi(7, 1)
    a.br("eq", 5, 7, "h_store")                   # BUG: re-send skips
    a.block()                                     # the fb bound check
    a.ldi(5, 9)
    a.br("ge", 4, 5, "bad")                       # first header: w <= 8
    a.br("ge", 6, 5, "bad")                       # first header: h <= 8
    a.block()
    a.label("h_store")
    a.block()
    a.ldi(5, _F_W)
    a.stm(5, 4)
    a.ldi(5, _F_H)
    a.stm(5, 6)
    a.ldi(5, _F_HAVE)
    a.ldi(4, 1)
    a.stm(5, 4)
    a.jmp("consume")

    # ---- 'P': [count, colors...] ----
    a.label("p_chunk")
    a.block()
    a.ldi(5, 1)
    a.br("lt", 3, 5, "bad")                       # len >= 1
    a.block()
    a.ldb(4, 1)                                   # count = payload[0]
    a.ldi(5, 1)
    a.br("lt", 4, 5, "bad")
    a.ldi(5, 65)
    a.br("ge", 4, 5, "bad")                       # count <= 64
    a.block()
    a.addi(5, 4, 1)
    a.br("ne", 3, 5, "bad")                       # len == 1 + count
    a.block()
    a.ldi(6, 0)                                   # i = 0
    a.label("pal_loop")
    a.br("ge", 6, 4, "pal_done")
    a.block()                                     # hit-count bucket
    a.addi(5, 1, 1)
    a.alu("add", 5, 5, 6)
    a.ldb(7, 5)                                   # color byte
    a.addi(5, 6, _PAL_BASE)
    a.stm(5, 7)
    a.addi(6, 6, 1)
    a.jmp("pal_loop")
    a.label("pal_done")
    a.block()
    a.ldi(5, _F_PALCNT)
    a.stm(5, 4)
    a.jmp("consume")

    # ---- 'D': [row, pixels...] ----
    a.label("d_chunk")
    a.block()
    a.ldi(5, _F_HAVE)
    a.ldm(5, 5)
    a.ldi(4, 1)
    a.br("ne", 5, 4, "bad")                       # need a header
    a.block()
    a.ldi(5, 1)
    a.br("lt", 3, 5, "bad")                       # len >= 1
    a.block()
    a.ldb(4, 1)                                   # row = payload[0]
    a.ldi(5, _F_H)
    a.ldm(5, 5)
    a.br("ge", 4, 5, "bad")                       # row < h (validated!)
    a.block()
    a.ldi(5, _F_W)
    a.ldm(5, 5)                                   # r5 = w
    a.addi(7, 3, -1)
    a.br("lt", 7, 5, "bad")                       # len-1 >= w
    a.block()
    a.alu("mul", 4, 4, 5)                         # dst = row * w (BUG:
    a.ldi(6, 0)                                   # unchecked vs FB_CAP)
    a.label("row_loop")
    a.br("ge", 6, 5, "row_done")
    a.block()                                     # hit-count bucket
    a.addi(7, 1, 1)
    a.alu("add", 7, 7, 6)
    a.ldb(7, 7)                                   # px = payload[1+i]
    # palette indirection when pal_count > 0
    a.ldi(2, _F_PALCNT)                           # r2 free post-dispatch
    a.ldm(2, 2)
    a.br("eq", 2, 0, "px_store")
    a.block()
    a.addi(2, 7, _PAL_BASE)                       # BUG: px unchecked
    a.ldm(7, 2)                                   # vs pal_count
    a.label("px_store")
    a.block()
    a.alu("add", 2, 4, 6)                         # fb index = dst + i
    a.stm(2, 7)                                   # OOB when resized
    a.addi(6, 6, 1)
    a.jmp("row_loop")
    a.label("row_done")
    a.block()
    a.ldi(5, _F_ROWS)
    a.ldm(4, 5)
    a.addi(4, 4, 1)
    a.stm(5, 4)
    a.jmp("consume")

    # ---- 'E' ----
    a.label("e_chunk")
    a.block()
    a.halt(0)

    a.label("bad")
    a.block()
    a.halt(1)
    return a.build(block_seed=0x16C)


# rledec_vm memory map (mem_size=80):
#   [0..63]  output buffer (OUT_CAP=64)   [64..79] scratch "heap"
_RLE_CAP = 64


@register_target("rledec_vm")
def rledec_vm() -> Program:
    """KBVM port of corpus/rledec.c: "RLE2" [out_len u16le] then
    run / literal / back-reference tokens decoded into a 64-byte
    output region (the C build uses 1024; the VM scales the cap to
    its memory, same bug shape).

    The planted bug is the classic decompressor CVE: output space is
    accounted with a SIGNED 16-bit budget instead of checking the
    cursor, and the reject only fires while the cursor still LOOKS
    in-bounds (`op + cnt <= CAP`) — the first token that both
    exhausts the budget and crosses the buffer end slips through,
    and the emit loop walks past the output region: bytes 64..79
    silently corrupt scratch (the C version's heap walk), then the
    cursor leaves VM memory entirely and the lane crashes.

    Registers: r1=ip r2=out-cursor r3=budget r4,r5=scratch
    r6=tok/byte/dist r7=cnt.  Exit codes mirror the C returns
    (1=short/bad magic, 2=out_len too big, 3=truncated token,
    4=zero count, 5=budget reject, 6=bad distance, 7=bad token,
    8=stream ended without 0x03).
    """
    a = Assembler("rledec_vm", mem_size=80, max_steps=1024)

    def budget_reject(tag: str) -> None:
        """budget -= cnt (signed-16 wrap); reject ONLY when negative
        AND op+cnt <= CAP — the conditioned check from the C."""
        a.alu("sub", 3, 3, 7)
        a.ldi(5, 0xFFFF)
        a.alu("and", 3, 3, 5)                     # short truncation
        a.ldi(4, 15)
        a.alu("shr", 5, 3, 4)                     # sign bit
        a.br("eq", 5, 0, f"bgt_ok_{tag}")         # budget >= 0
        a.block()                                 # negative budget
        a.alu("add", 4, 2, 7)                     # op + cnt
        a.ldi(5, _RLE_CAP + 1)
        a.br("lt", 4, 5, "x5")                    # looks in-bounds
        a.block()                                 # ESCAPE: overflow
        a.label(f"bgt_ok_{tag}")
        a.block()

    a.block()                                     # entry
    a.load_len(4)
    a.ldi(5, 6)
    a.br("lt", 4, 5, "x1")
    a.block()
    a.expect_byte(4, 5, 0, ord("R"), "x1")
    a.expect_byte(4, 5, 1, ord("L"), "x1")
    a.expect_byte(4, 5, 2, ord("E"), "x1")
    a.expect_byte(4, 5, 3, ord("2"), "x1")
    a.ldi(4, 4)                                   # out_len = LE16
    a.ldb(4, 4)
    a.ldi(5, 5)
    a.ldb(5, 5)
    a.ldi(7, 8)
    a.alu("shl", 5, 5, 7)
    a.alu("or", 3, 4, 5)                          # budget = out_len
    a.ldi(5, _RLE_CAP + 1)
    a.br("ge", 3, 5, "x2")
    a.block()
    a.ldi(1, 6)                                   # ip = 6
    a.ldi(2, 0)                                   # op = 0

    a.label("loop")
    a.block()
    a.load_len(4)
    a.br("ge", 1, 4, "x8")                        # stream ran out
    a.block()                                     # fetch token
    a.ldb(6, 1)
    a.addi(1, 1, 1)
    a.ldi(5, 0x03)
    a.br("eq", 6, 5, "done")
    a.block()
    a.load_len(4)
    a.br("ge", 1, 4, "x3")
    a.block()                                     # fetch count
    a.ldb(7, 1)
    a.addi(1, 1, 1)
    a.br("eq", 7, 0, "x4")
    a.block()
    a.ldi(5, 0x00)
    a.br("eq", 6, 5, "t_run")
    a.ldi(5, 0x01)
    a.br("eq", 6, 5, "t_lit")
    a.ldi(5, 0x02)
    a.br("eq", 6, 5, "t_bref")
    a.jmp("x7")

    a.label("t_run")                              # emit byte n times
    a.block()
    a.load_len(4)
    a.br("ge", 1, 4, "x3")
    a.block()
    a.ldb(6, 1)                                   # fill byte
    a.addi(1, 1, 1)
    budget_reject("run")
    a.label("run_emit")
    a.block()                                     # per-byte hit count
    a.br("eq", 7, 0, "loop")
    a.stm(2, 6)                                   # out[op] = byte
    a.addi(2, 2, 1)
    a.addi(7, 7, -1)
    a.jmp("run_emit")

    a.label("t_lit")                              # verbatim copy
    a.block()
    a.alu("add", 5, 1, 7)
    a.load_len(4)
    a.br("lt", 4, 5, "x3")                        # ip + cnt > len
    a.block()
    budget_reject("lit")
    a.label("lit_emit")
    a.block()
    a.br("eq", 7, 0, "loop")
    a.ldb(6, 1)
    a.stm(2, 6)
    a.addi(1, 1, 1)
    a.addi(2, 2, 1)
    a.addi(7, 7, -1)
    a.jmp("lit_emit")

    a.label("t_bref")                             # back-reference
    a.block()
    a.load_len(4)
    a.br("ge", 1, 4, "x3")
    a.block()
    a.ldb(6, 1)                                   # dist
    a.addi(1, 1, 1)
    a.br("eq", 6, 0, "x6")
    a.block()
    a.br("lt", 2, 6, "x6")                        # dist > op
    a.block()
    budget_reject("bref")
    a.label("bref_emit")
    a.block()
    a.br("eq", 7, 0, "loop")
    a.alu("sub", 5, 2, 6)                         # src = op - dist
    a.ldm(4, 5)
    a.stm(2, 4)
    a.addi(2, 2, 1)
    a.addi(7, 7, -1)
    a.jmp("bref_emit")

    a.label("done")
    a.block()
    a.halt(0)
    for code in (1, 2, 3, 4, 5, 6, 7, 8):
        a.label(f"x{code}")
        a.block()
        a.halt(code)
    return a.build(block_seed=0x41E)


#: fixedform_vm field offsets (everything else is NEVER loaded)
_FORM_LEN = 96
_FORM_HANDLERS = 8
_FORM_FIELD_VALUES = 16


@register_target("fixedform_vm")
def fixedform_vm() -> Program:
    """Fixed-offset form parser — the "not all bytes are equal"
    target family (arxiv 1711.04596; the learn tier's bench regime,
    docs/LEARN.md).

    Real-world headers put their meaning at FIXED offsets and ignore
    the bytes between: this family makes that structure exact and
    provable.  A 96-byte form carries ~16 live positions —

      [0..1]  magic "FM"            [8]   version ladder (6 values)
      [16]    type -> 8 handlers    [24+k] handler k's field ladder
                                           (16 values each)
      [32]    repeat count (hit-count-bucket loop that reads NOTHING)
      [64]^[65] key/lock gate -> bonus ladder at [72]
      [80]    0xEE arms the planted bug; [81] is the unchecked
              store index (version 6 + type 7 only)

    — and every other byte is never the operand of an LDB: mutating
    it cannot change ANY branch, ever (the dataflow layer proves the
    dead regions; kb-lint shows no dependency on them).  Uniform
    havoc therefore wastes ~5/6 of its primary edits; a mask that
    concentrates on the live offsets is worth ~6x effective mutation
    density — and because the SAME offsets keep yielding new ladder
    values all campaign long, positional saliency is stable, which
    is exactly what a lineage-trained model can learn.  Coverage is
    wide (magic partials + 6 + 8 + 8x16 value blocks + count buckets
    + bonus ladder) so short campaigns don't saturate.
    """
    a = Assembler("fixedform_vm", mem_size=32, max_steps=512)
    a.block()                                   # entry
    a.load_len(1)
    a.ldi(2, 82)
    a.br("lt", 1, 2, "bad")                     # short form
    a.block()
    a.expect_byte(3, 4, 0, ord("F"), "bad")     # magic
    a.expect_byte(3, 4, 1, ord("M"), "bad")

    def ladder(tag: str, off: int, values: int, done: str) -> None:
        """One value ladder over input[off]: each matched value gets
        its own coverage block (walking coverage at a fixed
        position), unmatched values fall through to ``done``."""
        a.ldi(3, off)
        a.ldb(2, 3)                             # r2 = input[off]
        for v in range(values):
            a.ldi(4, v + 1)
            a.br("ne", 2, 4, f"{tag}_n{v}")
            a.block()                           # value-(v+1) block
            a.jmp(done)
            a.label(f"{tag}_n{v}")
            a.block()
        a.jmp(done)

    # version ladder at [8] (r7 keeps the raw byte for the bug gate)
    a.ldi(3, 8)
    a.ldb(7, 3)
    ladder("ver", 8, 6, "ver_done")
    a.label("ver_done")
    a.block()

    # type dispatch at [16] -> handler k's own field ladder at [24+k]
    a.ldi(3, 16)
    a.ldb(6, 3)                                 # r6 = type
    for k in range(_FORM_HANDLERS):
        a.ldi(2, k + 1)
        a.br("ne", 6, 2, f"ty_n{k}")
        a.block()                               # handler-k block
        ladder(f"h{k}", 24 + k, _FORM_FIELD_VALUES, f"h{k}_done")
        a.label(f"h{k}_done")
        a.block()
        a.jmp("ty_done")
        a.label(f"ty_n{k}")
        a.block()
    a.label("ty_done")
    a.block()

    # repeat-count loop at [32]: the body block's hit count walks the
    # AFL buckets; the body READS no input (count buckets only)
    a.ldi(3, 32)
    a.ldb(2, 3)                                 # r2 = count
    a.ldi(4, 24)
    a.br("ge", 4, 2, "cnt_ok")                  # clamp to 24
    a.block()
    a.alu("add", 2, 4, 0)                       # r2 = 24 (r0 == 0)
    a.label("cnt_ok")
    a.block()
    a.ldi(3, 0)                                 # r3 = i
    a.label("cnt_loop")
    a.br("ge", 3, 2, "cnt_done")
    a.block()                                   # bucket body
    a.addi(3, 3, 1)
    a.jmp("cnt_loop")
    a.label("cnt_done")
    a.block()

    # key/lock gate: input[64] ^ input[65] == 0x5A opens the bonus
    # ladder at [72] (two-byte coupled fields — compensated edits)
    a.ldi(3, 64)
    a.ldb(4, 3)
    a.ldi(3, 65)
    a.ldb(5, 3)
    a.alu("xor", 4, 4, 5)
    a.ldi(5, 0x5A)
    a.br("ne", 4, 5, "no_bonus")
    a.block()                                   # gate open
    ladder("bonus", 72, _FORM_FIELD_VALUES, "bonus_done")
    a.label("bonus_done")
    a.block()
    a.label("no_bonus")
    a.block()

    # planted bug: version 6 + type 7 + input[80] == 0xEE stores to
    # the UNCHECKED index input[81] (mem_size 32 -> OOB crash)
    a.ldi(2, 6)
    a.br("ne", 7, 2, "done")
    a.ldi(2, 7)
    a.br("ne", 6, 2, "done")
    a.block()
    a.expect_byte(3, 4, 80, 0xEE, "done")
    a.ldi(3, 81)
    a.ldb(4, 3)                                 # r4 = store index
    a.stm(4, 2)                                 # BUG: unchecked
    a.block()
    a.label("done")
    a.block()
    a.halt(0)
    a.label("bad")
    a.block()
    a.halt(1)
    return a.build(block_seed=0xF1F)


@register_target("magicsum_vm")
def magicsum_vm() -> Program:
    """The input-to-state micro-family (Redqueen's motivating shape):
    a 32-bit value assembled VERBATIM from the first four input bytes
    (little-endian) must equal a multiply-accumulate checksum of the
    remaining payload.

        [b0 b1 b2 b3 | payload...],  len >= 6
        stored = b0 + 256*b1 + 65536*b2 + 16777216*b3
        acc    = fold(payload, 0x51D5A3, acc*33 + byte)
        stored == acc  ->  the win block (planted wild store)

    Why it exists: the exact solver reports the compare ``unknown``
    (the checksum loop's length-dependent revisits blow the visit
    cap), and coordinate probe walks need ~30+ iterations to carry a
    byte-granular descent across all four stored bytes — but the
    engine OBSERVES both operands at the compare, so input-to-state
    matching writes the observed checksum straight into b0..b3 and
    cracks it in one generation.  ``bench.py --descend`` gates that
    separation (i2s-on vs i2s-off at equal budget) and
    tests/test_device_descent.py pins the <= 2-dispatch crack."""
    a = Assembler("magicsum_vm", mem_size=8, max_steps=512)
    a.block()                                     # 0: entry
    a.load_len(5)
    a.ldi(2, 6)
    a.br("lt", 5, 2, "exit")                      # len < 6 -> exit
    a.block()                                     # 1: assemble stored
    a.ldi(6, 256)
    a.ldi(1, 3)
    a.ldb(3, 1)                                   # r3 = b3
    a.alu("mul", 3, 3, 6)
    a.ldi(1, 2)
    a.ldb(2, 1)
    a.alu("add", 3, 3, 2)                         # (b3*256)+b2
    a.alu("mul", 3, 3, 6)
    a.ldi(1, 1)
    a.ldb(2, 1)
    a.alu("add", 3, 3, 2)
    a.alu("mul", 3, 3, 6)
    a.ldi(1, 0)
    a.ldb(2, 1)
    a.alu("add", 3, 3, 2)                         # r3 = stored (LE)
    a.ldi(4, 0x51D5A3)                            # acc seed constant
    a.ldi(1, 4)                                   # i = 4
    a.block()                                     # 2: loop head
    a.label("msum_loop")
    a.br("ge", 1, 5, "msum_cmp")                  # i >= len -> compare
    a.block()                                     # 3: body
    a.ldb(2, 1)
    a.ldi(7, 33)
    a.alu("mul", 4, 4, 7)
    a.alu("add", 4, 4, 2)                         # acc = acc*33 + b
    a.addi(1, 1, 1)
    a.jmp("msum_loop")
    a.label("msum_cmp")
    a.block()                                     # 4: compare
    a.br("ne", 3, 4, "exit")                      # stored != acc
    a.block()                                     # 5: win
    a.ldi(6, -1)
    a.ldi(7, 1)
    a.stm(6, 7)                                   # planted wild store
    a.halt(0)
    a.label("exit")
    a.block()                                     # 6
    a.halt(0)
    return a.build(block_seed=0x3A61)


# --------------------------------------------------------------------
# Seeds and crash reproducers (tests + bench starting corpus)
# --------------------------------------------------------------------

def _chunk(type_byte: bytes, payload: bytes) -> bytes:
    return type_byte + bytes([len(payload)]) + payload + \
        bytes([sum(payload) & 0xFF])


def tlvstack_vm_seed() -> bytes:
    """Happy path: arithmetic, slots, and an unlocked PRIV call."""
    ops = [(0x01, 5), (0x01, 7), (0x03, 0), (0x06, 0), (0x07, 0),
           (0x02, 0)]
    body = b"".join(bytes(p) for p in ops)
    body += bytes([0x0C, 0]) + _KEYWORD            # unlock
    body += bytes([0x0D, 11])                      # one PRIV leaf
    body += bytes([0x0B, 0])                       # halt
    return b"STK1" + body


def tlvstack_vm_crash() -> bytes:
    """MUL wraparound -> negative address passes SIND's signed bound
    check -> store far below the slot array -> lane crash."""
    ops = [(0x01, 255), (0x05, 0), (0x04, 0),      # 255*255
           (0x05, 0), (0x04, 0),                   # ^2 wraps negative
           (0x01, 1), (0x09, 0), (0x0A, 0)]        # val, swap, SIND
    return b"STK1" + b"".join(bytes(p) for p in ops)


def imgparse_vm_seed() -> bytes:
    out = b"QIMG"
    out += _chunk(b"H", bytes([4, 4, 1]))
    out += _chunk(b"P", bytes([2, 0x10, 0x20]))
    out += _chunk(b"D", bytes([0]) + bytes([i & 1 for i in range(4)]))
    out += _chunk(b"C", b"hi")
    out += _chunk(b"E", b"")
    return out


def imgparse_vm_crash() -> bytes:
    """Header re-send resizes past the framebuffer: row 38 x width 39
    stores far outside VM memory."""
    out = b"QIMG"
    out += _chunk(b"H", bytes([4, 4, 1]))          # first header: sane
    out += _chunk(b"H", bytes([39, 39, 1]))        # BUG: unchecked resize
    out += _chunk(b"D", bytes([38]) + bytes(39))
    return out


def fixedform_vm_seed() -> bytes:
    """Happy path: magic + version 1, type 1, field 1, count 1 —
    every other byte zero (the live offsets all hold their lowest
    ladder value, so the whole ladder space is left to the fuzzer)."""
    form = bytearray(_FORM_LEN)
    form[0:2] = b"FM"
    form[8] = 1                                # version
    form[16] = 1                               # type -> handler 0
    form[24] = 1                               # handler 0 field
    form[32] = 1                               # repeat count
    return bytes(form)


def fixedform_vm_crash() -> bytes:
    """version 6 + type 7 + 0xEE arm byte -> unchecked store at
    index 200 (mem_size 32)."""
    form = bytearray(_FORM_LEN)
    form[0:2] = b"FM"
    form[8] = 6
    form[16] = 7
    form[80] = 0xEE
    form[81] = 200
    return bytes(form)


def rledec_vm_seed() -> bytes:
    """Byte-identical to the native seed (corpus/seeds.py
    rledec_seed): every token type, 16 bytes emitted, budget exact."""
    out = b"RLE2" + (16).to_bytes(2, "little")
    out += bytes([0x00, 8, ord("A")])             # run of 8 'A'
    out += bytes([0x01, 4]) + b"abcd"             # literal
    out += bytes([0x02, 4, 4])                    # back-reference
    out += bytes([0x03])
    return out


def rledec_vm_crash() -> bytes:
    """Budget down to 4, then a 60-byte run: budget goes negative
    AND the cursor crosses the cap, so the conditioned reject never
    fires — the emit loop walks off the output region (the native
    repro's shape, scaled to the VM's 64-byte cap)."""
    out = b"RLE2" + (64).to_bytes(2, "little")
    out += bytes([0x00, 60, ord("A")])            # budget 4, op 60
    out += bytes([0x00, 60, ord("B")])            # escapes the check
    return out


def magicsum_vm_seed() -> bytes:
    """Blind seed: zero stored field + two zero payload bytes (the
    checksum of which is far from 0 thanks to the acc constant, so
    the compare edge starts a full 32-bit distance away)."""
    return bytes(6)


def magicsum_vm_crash() -> bytes:
    """stored == checksum(payload): acc = (0x51D5A3*33 + 0)*33 + 0,
    written little-endian into b0..b3."""
    acc = 0x51D5A3
    for b in (0, 0):
        acc = (acc * 33 + b) & 0xFFFFFFFF
    return acc.to_bytes(4, "little") + bytes(2)


VM_SEEDS = {
    "tlvstack_vm": (tlvstack_vm_seed, tlvstack_vm_crash),
    "magicsum_vm": (magicsum_vm_seed, magicsum_vm_crash),
    "imgparse_vm": (imgparse_vm_seed, imgparse_vm_crash),
    "rledec_vm": (rledec_vm_seed, rledec_vm_crash),
    "fixedform_vm": (fixedform_vm_seed, fixedform_vm_crash),
}
