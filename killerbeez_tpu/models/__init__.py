"""Target execution models.

The reference runs native target binaries under a forkserver, QEMU, or
DynamoRIO (SURVEY §2.3/§2.5/§2.6). The TPU-native equivalent of the
binary-translation tier is the **KBVM**: targets are compiled to a
fixed int32 instruction tensor and executed *batched on-device* — a
``lax.scan`` step machine under ``vmap``, with AFL-style edge coverage
(``trace[cur ^ prev]++``, ``prev = cur >> 1``) recorded from BLOCK
instructions the compiler inserts at basic-block heads, exactly where
afl-as puts its trampolines (reference afl_progs/afl-as.c).
"""

from .vm import Program, VMResult, compile_runner, run_batch
from .compiler import Assembler, assign_block_ids
from . import targets
from . import targets_cgc  # registers the CGC-grade targets
from . import targets_stateful  # registers the session-tier targets

__all__ = ["Program", "VMResult", "compile_runner", "run_batch",
           "Assembler", "assign_block_ids", "targets"]
