"""Stateful built-in targets — protocol state machines for the
session tier (killerbeez_tpu/stateful/).

Conventions these targets follow (and docs/STATEFUL.md documents):

  * r7 is the protocol state register (StatefulSpec.state_reg): it
    persists across messages, handlers never use it as scratch;
  * scratch registers are re-initialized (LDI) before use in every
    handler — register values carry over from the previous message;
  * cross-message data lives in scratch memory (query counters,
    expected handshake tokens), which also persists.

Both families are built so their DEEP states are provably
unreachable by single-shot inputs: every deep handler is guarded by
an ``r7 == <state>`` check, and in a single-shot execution r7 is the
constant 0 at every dispatch — ``analysis.dataflow`` constant
propagation folds the guards and reports the deep blocks dead
(``deep_state_blocks`` below returns exactly that set; the bench
``--stateful`` gate and kb-lint's state-reachability check both
consume it).  Only a SEQUENCE that first drives the state machine
can light them.

  * ``session_auth`` — login -> query -> quit.  States: 0 START,
    1 AUTHED, 2 DONE.  The planted crash needs login ('L' + the
    "pw" password), at least two authed queries, and a 'Z' query
    payload — three-message minimum.
  * ``tcp_like``     — SYN -> ACK -> DATA/FIN -> FIN handshake and
    teardown.  States: 0 CLOSED, 1 SYN_SEEN, 2 ESTABLISHED,
    3 FIN_WAIT, 4 DONE.  The ACK must echo the SYN's token + 1
    (stored in scratch memory by the SYN handler), and the DATA
    handler stores through an unchecked payload index — the memory
    bug is only reachable in ESTABLISHED.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..stateful import StatefulSpec
from ..stateful.framing import frame_messages
from .compiler import Assembler
from .vm import Program
from .targets import register_target

#: session-tier configuration per stateful target (consumed by the
#: CLI --stateful auto-spec, kb-lint, bench --stateful and tests)
STATEFUL_SPECS: Dict[str, StatefulSpec] = {
    "session_auth": StatefulSpec(m_max=4, n_states=8, state_reg=7),
    "tcp_like": StatefulSpec(m_max=4, n_states=8, state_reg=7),
}

#: canonical benign session seeds (valid protocol runs that end
#: cleanly — the corpus anchor bench/CI fuzz from)
_SEED_SEQUENCES: Dict[str, List[bytes]] = {
    "session_auth": [b"Lpw", b"QA", b"X"],
    "tcp_like": [b"S\x10", b"A\x11", b"D\x05A", b"F"],
}


def stateful_target_names() -> List[str]:
    return sorted(STATEFUL_SPECS)


def get_stateful_spec(name: str) -> Optional[StatefulSpec]:
    return STATEFUL_SPECS.get(name)


def seed_sequence(name: str) -> List[bytes]:
    if name not in _SEED_SEQUENCES:
        raise ValueError(f"no seed sequence for {name!r}")
    return list(_SEED_SEQUENCES[name])


def framed_seed(name: str) -> bytes:
    """The canonical seed, framed for the target's spec."""
    return frame_messages(seed_sequence(name),
                          STATEFUL_SPECS[name].m_max)


def deep_state_blocks(program: Program) -> List[int]:
    """Blocks provably unreachable by ANY single-shot input: dead
    under single-shot constant propagation (r7 and memory start 0 and
    nothing sets them before the state guards), but CFG-reachable —
    i.e., exactly the sequence-only coverage.  This is the static
    proof the bench --stateful gate cites: an edge into one of these
    blocks cracked by sequence fuzzing is an edge single-shot fuzzing
    cannot reach."""
    from ..analysis import analyze_dataflow, build_cfg
    cfg = build_cfg(program)
    df = analyze_dataflow(program)
    return sorted(b for b in df.dead_blocks if b in cfg.reachable)


def deep_state_edges(program: Program) -> List[int]:
    """Edge indices whose DESTINATION is a deep-state block."""
    import numpy as np
    deep = set(deep_state_blocks(program))
    et = np.asarray(program.edge_to)
    return [int(e) for e in range(len(et)) if int(et[e]) in deep]


@register_target("session_auth")
def session_auth_target() -> Program:
    """login -> query -> quit session daemon (see module docstring).

    Message grammar: byte 0 = command.
      'L' <pw bytes "pw">   login (START only)
      'Q' <payload>         query (AUTHED only; 'Z' payload after two
                            authed queries hits the planted crash)
      'X'                   quit (AUTHED -> DONE teardown block)
    """
    a = Assembler("session_auth", mem_size=16, max_steps=128)
    a.block()                           # entry / dispatch
    a.ldi(1, 0)
    a.ldb(1, 1)                         # r1 = command byte
    a.ldi(2, ord("L"))
    a.br("eq", 1, 2, "login")
    a.ldi(2, ord("Q"))
    a.br("eq", 1, 2, "query")
    a.ldi(2, ord("X"))
    a.br("eq", 1, 2, "quit")
    a.jmp("bad")

    a.label("login")
    a.block()                           # login attempt
    a.ldi(2, 0)
    a.br("ne", 7, 2, "relogin")         # already past START?
    a.block()                           # fresh login
    a.expect_byte(2, 3, 1, ord("p"), "badpw")
    a.expect_byte(2, 3, 2, ord("w"), "badpw")
    a.ldi(7, 1)                         # -> AUTHED
    a.halt(0)
    a.label("badpw")
    a.block()
    a.halt(1)
    a.label("relogin")
    a.block()
    a.halt(4)

    a.label("query")
    a.block()                           # query dispatch
    a.ldi(2, 1)
    a.br("ne", 7, 2, "denied")
    a.block()                           # DEEP: authed query
    a.ldi(2, 1)
    a.ldm(3, 2)                         # r3 = mem[1] query count
    a.addi(3, 3, 1)
    a.stm(2, 3)                         # mem[1] = count + 1
    a.ldi(4, 1)
    a.ldb(4, 4)                         # r4 = payload byte
    a.ldi(5, ord("Z"))
    a.br("ne", 4, 5, "q_done")
    a.block()                           # DEEP: 'Z' query
    a.ldi(2, 2)
    a.br("lt", 3, 2, "q_done")          # fewer than two queries yet
    a.block()                           # DEEP: the planted crash
    a.ldi(5, -1)
    a.ldi(6, 1)
    a.stm(5, 6)                         # wild-pointer write
    a.label("q_done")
    a.block()                           # DEEP: clean query exit
    a.halt(0)
    a.label("denied")
    a.block()
    a.halt(2)

    a.label("quit")
    a.block()                           # quit dispatch
    a.ldi(2, 1)
    a.br("ne", 7, 2, "quit_noauth")
    a.block()                           # DEEP: authed teardown
    a.ldi(7, 2)                         # -> DONE
    a.halt(0)
    a.label("quit_noauth")
    a.block()
    a.halt(3)

    a.label("bad")
    a.block()
    a.halt(1)
    return a.build(block_seed=0x5E55)


@register_target("tcp_like")
def tcp_like_target() -> Program:
    """SYN/ACK handshake + data + two-step teardown (see module
    docstring).

    Message grammar: byte 0 = command.
      'S' <token>        SYN (CLOSED only): remembers token+1 as the
                         expected ack cookie, -> SYN_SEEN
      'A' <cookie>       ACK (SYN_SEEN only): cookie must equal
                         token+1, -> ESTABLISHED
      'D' <idx> <value>  DATA (ESTABLISHED only): mem[idx] = value,
                         idx UNCHECKED — the planted bug
      'F'                FIN: ESTABLISHED -> FIN_WAIT,
                         FIN_WAIT -> DONE (teardown complete)
    """
    a = Assembler("tcp_like", mem_size=32, max_steps=128)
    a.block()                           # entry / dispatch
    a.ldi(1, 0)
    a.ldb(1, 1)                         # r1 = command byte
    a.ldi(2, ord("S"))
    a.br("eq", 1, 2, "syn")
    a.ldi(2, ord("A"))
    a.br("eq", 1, 2, "ack")
    a.ldi(2, ord("D"))
    a.br("eq", 1, 2, "data")
    a.ldi(2, ord("F"))
    a.br("eq", 1, 2, "fin")
    a.jmp("rst")

    a.label("syn")
    a.block()
    a.ldi(2, 0)
    a.br("ne", 7, 2, "rst")             # SYN only from CLOSED
    a.block()                           # remember the ack cookie
    a.ldi(3, 1)
    a.ldb(3, 3)                         # r3 = token
    a.addi(3, 3, 1)                     # cookie = token + 1
    a.ldi(4, 0)
    a.stm(4, 3)                         # mem[0] = cookie
    a.ldi(7, 1)                         # -> SYN_SEEN
    a.halt(0)

    a.label("ack")
    a.block()
    a.ldi(2, 1)
    a.br("ne", 7, 2, "rst")             # ACK only from SYN_SEEN
    a.block()                           # DEEP: cookie check
    a.ldi(3, 1)
    a.ldb(3, 3)                         # r3 = echoed cookie
    a.ldi(4, 0)
    a.ldm(5, 4)                         # r5 = expected cookie
    a.br("ne", 3, 5, "bad_ack")
    a.block()                           # DEEP: ESTABLISHED
    a.ldi(7, 2)
    a.halt(0)
    a.label("bad_ack")
    a.block()                           # DEEP: wrong cookie -> reset
    a.ldi(7, 0)
    a.halt(2)

    a.label("data")
    a.block()
    a.ldi(2, 2)
    a.br("ne", 7, 2, "rst")             # DATA only in ESTABLISHED
    a.block()                           # DEEP: the unchecked store
    a.ldi(3, 1)
    a.ldb(3, 3)                         # r3 = idx (payload byte 1)
    a.ldi(4, 2)
    a.ldb(4, 4)                         # r4 = value (payload byte 2)
    a.stm(3, 4)                         # BUG: idx 0..255, mem is 32
    a.block()                           # DEEP: data stored
    a.halt(0)

    a.label("fin")
    a.block()
    a.ldi(2, 2)
    a.br("eq", 7, 2, "fin_estab")
    a.ldi(2, 3)
    a.br("eq", 7, 2, "fin_wait")
    a.jmp("rst")
    a.label("fin_estab")
    a.block()                           # DEEP: -> FIN_WAIT
    a.ldi(7, 3)
    a.halt(0)
    a.label("fin_wait")
    a.block()                           # DEEP: teardown complete
    a.ldi(7, 4)
    a.halt(0)

    a.label("rst")
    a.block()
    a.halt(1)
    return a.build(block_seed=0x7C91)
