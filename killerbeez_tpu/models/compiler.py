"""Assembler for KBVM programs.

Plays the role of the reference's compile-time instrumentation
(afl_progs/afl-as.c): targets are written against a tiny assembler
API; ``block()`` marks basic-block heads and ``build()`` assigns each
one a deterministic pseudo-random coverage id — the same scheme
afl-as uses (random cur_loc per block, edge = cur ^ prev).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from .. import MAP_SIZE
from .vm import (
    ALU_ADD, ALU_AND, ALU_MUL, ALU_OR, ALU_SHL, ALU_SHR, ALU_SUB, ALU_XOR,
    CMP_EQ, CMP_GE, CMP_LT, CMP_NE, N_REGS,
    OP_ALU, OP_ADDI, OP_BLOCK, OP_BR, OP_CRASH, OP_HALT, OP_JMP, OP_LDB,
    OP_LDI, OP_LDM, OP_LEN, OP_STM, Program,
)

_ALU_NAMES = {"add": ALU_ADD, "sub": ALU_SUB, "and": ALU_AND, "or": ALU_OR,
              "xor": ALU_XOR, "shl": ALU_SHL, "shr": ALU_SHR,
              "mul": ALU_MUL}
_CMP_NAMES = {"eq": CMP_EQ, "ne": CMP_NE, "lt": CMP_LT, "ge": CMP_GE}

Ref = Union[str, int]  # label name or absolute pc


class Assembler:
    """Builds a Program. Registers are r0..r7; labels are strings."""

    def __init__(self, name: str = "anon", mem_size: int = 64,
                 max_steps: int = 256):
        self.name = name
        self.mem_size = mem_size
        self.max_steps = max_steps
        self.rows: List[List[Union[int, str]]] = []
        self.labels: Dict[str, int] = {}
        self._n_blocks = 0
        # (module_name, first_block_index) marks; blocks before the
        # first mark belong to the default "target" module
        self._module_marks: List[Tuple[str, int]] = []

    # -- assembly -------------------------------------------------------

    def _reg(self, r: int) -> int:
        if not (0 <= r < N_REGS):
            raise ValueError(f"register r{r} out of range")
        return r

    def _emit(self, op: int, a: Union[int, str] = 0,
              b: Union[int, str] = 0, c: Union[int, str] = 0) -> int:
        for field in (a, b, c):
            # the batched engine fetches instructions through an f32
            # matmul (vm._step_batched), exact only below 2^24
            if isinstance(field, int) and abs(field) >= (1 << 24):
                raise ValueError(
                    f"instruction field {field} exceeds the engine's "
                    f"2^24 exact-integer bound; build large constants "
                    f"with shl/or")
        self.rows.append([op, a, b, c])
        return len(self.rows) - 1

    def label(self, name: str) -> None:
        if name in self.labels:
            raise ValueError(f"duplicate label {name!r}")
        self.labels[name] = len(self.rows)

    def block(self) -> None:
        """Basic-block head: coverage point (id assigned at build)."""
        self._n_blocks += 1
        self._emit(OP_BLOCK, f"__block_{self._n_blocks - 1}")

    def module(self, name: str) -> None:
        """Start a coverage module: subsequent blocks belong to it
        (the reference's per-module maps — a target's shared libraries
        each get their own map + virgin state,
        dynamorio_instrumentation.h:27-41; here modules are
        block-index ranges with their own 64KB slot space)."""
        if self._module_marks and \
                self._module_marks[-1][1] == self._n_blocks:
            raise ValueError(
                f"module {name!r} would start at the same block as "
                f"{self._module_marks[-1][0]!r} (empty module)")
        self._module_marks.append((name, self._n_blocks))

    def halt(self, code: int = 0) -> None:
        self._emit(OP_HALT, code)

    def crash(self) -> None:
        self._emit(OP_CRASH)

    def ldb(self, rd: int, rs: int) -> None:
        """rd = input[r[rs]] (0 when out of bounds)."""
        self._emit(OP_LDB, self._reg(rd), self._reg(rs))

    def ldi(self, rd: int, imm: int) -> None:
        self._emit(OP_LDI, self._reg(rd), int(imm))

    def alu(self, op: str, rd: int, ra: int, rb: int) -> None:
        sel = _ALU_NAMES[op]
        self._emit(OP_ALU, self._reg(rd), self._reg(ra),
                   sel | (self._reg(rb) << 3))

    def addi(self, rd: int, ra: int, imm: int) -> None:
        self._emit(OP_ADDI, self._reg(rd), self._reg(ra), int(imm))

    def jmp(self, target: Ref) -> None:
        self._emit(OP_JMP, target)

    def br(self, cmp: str, ra: int, rb: int, target: Ref) -> None:
        """if r[ra] <cmp> r[rb]: goto target."""
        sel = _CMP_NAMES[cmp]
        self._emit(OP_BR, self._reg(ra), sel | (self._reg(rb) << 2),
                   target)

    def load_len(self, rd: int) -> None:
        self._emit(OP_LEN, self._reg(rd))

    def ldm(self, rd: int, ra: int) -> None:
        """rd = mem[r[ra]]; out-of-bounds crashes the lane."""
        self._emit(OP_LDM, self._reg(rd), self._reg(ra))

    def stm(self, ra: int, rb: int) -> None:
        """mem[r[ra]] = r[rb]; out-of-bounds crashes the lane."""
        self._emit(OP_STM, self._reg(ra), self._reg(rb))

    # -- convenience macros --------------------------------------------

    def expect_byte(self, index_reg_scratch: int, value_reg_scratch: int,
                    index: int, value: int, fail: Ref) -> None:
        """if input[index] != value: goto fail  (burns two scratch regs).
        Starts a new coverage block on the match path."""
        self.ldi(index_reg_scratch, index)
        self.ldb(index_reg_scratch, index_reg_scratch)
        self.ldi(value_reg_scratch, value)
        self.br("ne", index_reg_scratch, value_reg_scratch, fail)
        self.block()

    # -- build ----------------------------------------------------------

    def build(self, block_seed: int = 0xB10C) -> Program:
        # a trailing module() with no blocks after it passes the
        # consecutive-mark check in module() but would build a
        # (name, lo, hi) range with lo == hi — reject it the same way
        if self._module_marks and \
                self._module_marks[-1][1] == self._n_blocks:
            raise ValueError(
                f"module {self._module_marks[-1][0]!r} would start "
                f"at the same block as the program ends "
                f"(empty module)")
        ids = assign_block_ids(self._n_blocks, block_seed)
        instrs = np.zeros((len(self.rows), 4), dtype=np.int32)
        for i, row in enumerate(self.rows):
            out = []
            for field in row:
                if isinstance(field, str):
                    if field.startswith("__block_"):
                        out.append(int(ids[int(field[8:])]))
                    elif field in self.labels:
                        out.append(self.labels[field])
                    else:
                        raise ValueError(f"undefined label {field!r}")
                else:
                    out.append(int(field))
            instrs[i] = out
        marks = self._module_marks
        if not marks or marks[0][1] > 0:
            marks = [("target", 0)] + marks
        modules = tuple(
            (name, lo, marks[i + 1][1] if i + 1 < len(marks)
             else self._n_blocks)
            for i, (name, lo) in enumerate(marks))
        return Program(instrs=instrs, name=self.name,
                       mem_size=self.mem_size, max_steps=self.max_steps,
                       n_blocks=self._n_blocks,
                       block_ids=tuple(int(x) for x in ids),
                       modules=modules)


def assign_block_ids(n_blocks: int, seed: int = 0xB10C) -> np.ndarray:
    """Deterministic pseudo-random coverage ids, one per basic block
    (afl-as picks ``random() % MAP_SIZE`` per block; deterministic
    here so programs are reproducible artifacts)."""
    rng = np.random.default_rng(seed)
    return rng.integers(0, MAP_SIZE, size=n_blocks, dtype=np.int64)
