"""Built-in KBVM targets — fresh re-creations of the reference's
corpus fixtures (SURVEY §2.9), written against the assembler API (no
code taken from /root/reference; semantics described in SURVEY).

  * ``test``     — the canonical 4-byte "ABCD" -> wild-pointer-write
                   crasher (reference corpus/test behavior): each
                   matched prefix byte enters a new basic block, so
                   coverage deepens as the fuzzer homes in.
  * ``hang``     — input starting with 'H' spins forever (step-budget
                   hang, reference corpus/hang).
  * ``libtest``  — main + a "shared library" routine with its own
                   block-id range (reference corpus/libtest, used for
                   coverage_libraries-style tests).
  * ``cgc_like`` — a small packet parser (magic, type, length,
                   checksum loop, type-specific handlers, one
                   memory-safety bug) standing in for the CGC corpus.
"""

from __future__ import annotations

from typing import Callable, Dict

from .compiler import Assembler
from .vm import Program

_REGISTRY: Dict[str, Callable[[], Program]] = {}


def register_target(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def target_names():
    return sorted(_REGISTRY)


def get_target(name: str) -> Program:
    if name.startswith("zoo:"):
        # generated target zoo (models/zoo.py): parameterized family
        # instances with certified planted bugs, resolved by name so
        # every --target consumer takes them unchanged
        from .zoo import zoo_program
        return zoo_program(name)
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown target {name!r}; known: {', '.join(target_names())}")
    return _REGISTRY[name]()


def load_program_from_options(options: Dict, missing_hint: str
                              ) -> Program:
    """Resolve an instrumentation option dict to a Program: either a
    compiled ``program_file`` (.npz) or a built-in ``target`` name,
    with an optional ``max_steps`` override. Shared by the device
    instrumentations (jit_harness, ipt)."""
    import numpy as np

    if "program_file" in options:
        d = np.load(options["program_file"], allow_pickle=False)
        modules = ()
        if "module_names" in d:
            modules = tuple(
                (str(n), int(lo), int(hi)) for n, lo, hi in
                zip(d["module_names"], d["modules_lo"],
                    d["modules_hi"]))
        prog = Program(
            instrs=d["instrs"].astype(np.int32),
            name=str(d["name"]) if "name" in d else "file",
            mem_size=int(d["mem_size"]), max_steps=int(d["max_steps"]),
            n_blocks=int(d.get("n_blocks", 0)),
            block_ids=tuple(int(b) for b in d.get("block_ids", ())),
            modules=modules)
    else:
        target = options.get("target")
        if not target:
            raise ValueError(missing_hint)
        prog = get_target(target)
    if "max_steps" in options:
        prog = Program(instrs=prog.instrs, name=prog.name,
                       mem_size=prog.mem_size,
                       max_steps=int(options["max_steps"]),
                       n_blocks=prog.n_blocks,
                       block_ids=prog.block_ids,
                       modules=prog.modules)
    return prog


def load_program_file(path: str) -> Program:
    """Load one compiled ``.npz`` program (kb-compile output, or a
    kb-repair ``--apply`` patched proxy)."""
    return load_program_from_options(
        {"program_file": path}, missing_hint="program_file")


@register_target("test")
def test_target() -> Program:
    """'ABCD' crasher: nested per-byte checks, crash = store through a
    wild pointer (mem index -1), like the reference's NULL write."""
    a = Assembler("test", mem_size=16, max_steps=64)
    a.block()                       # entry block
    a.load_len(1)
    a.ldi(2, 4)
    a.br("lt", 1, 2, "exit")        # len < 4 -> plain exit
    a.block()                       # len-ok block
    a.expect_byte(3, 4, 0, ord("A"), "exit")
    a.expect_byte(3, 4, 1, ord("B"), "exit")
    a.expect_byte(3, 4, 2, ord("C"), "exit")
    a.expect_byte(3, 4, 3, ord("D"), "exit")
    # full match: write through a wild pointer -> crash
    a.ldi(5, -1)
    a.ldi(6, 1)
    a.stm(5, 6)
    a.halt(0)                       # unreachable
    a.label("exit")
    a.block()
    a.halt(0)
    return a.build(block_seed=0x7E57)


@register_target("hang")
def hang_target() -> Program:
    """Spins forever when input[0] == 'H' (hang = step budget
    exhausted), else exits clean."""
    a = Assembler("hang", mem_size=8, max_steps=128)
    a.block()
    a.ldi(1, 0)
    a.ldb(1, 1)
    a.ldi(2, ord("H"))
    a.br("ne", 1, 2, "exit")
    a.block()                       # the spin block
    a.label("spin")
    a.jmp("spin")
    a.label("exit")
    a.block()
    a.halt(0)
    return a.build(block_seed=0x4A46)


@register_target("libtest")
def libtest_target() -> Program:
    """Main program plus a 'library' routine: when input[0] == 'L' the
    lane runs the library blocks (built with a distinct block-id seed
    range via a second assembler pass is not needed — the ids live in
    the same map, but the library block ids are queryable from
    Program.block_ids[3:], which the per-module coverage tests use)."""
    a = Assembler("libtest", mem_size=8, max_steps=128)
    a.block()                       # 0: main entry
    a.ldi(1, 0)
    a.ldb(1, 1)
    a.ldi(2, ord("L"))
    a.br("ne", 1, 2, "exit")
    a.block()                       # 1: call-site block
    a.jmp("lib")
    a.label("ret")
    a.block()                       # 2: return block
    a.halt(0)
    a.label("exit")
    a.block()                       # 3: plain-exit block
    a.halt(0)
    # --- "library": its own coverage module (own 64KB map + virgin
    # state, like the reference's per-library target_module_t) ---
    a.module("libtest1")
    a.label("lib")
    a.block()                       # 4: lib entry
    a.ldi(3, 1)
    a.ldb(3, 3)
    a.ldi(4, ord("X"))
    a.br("ne", 3, 4, "libout")
    a.block()                       # 5: lib deep block
    a.label("libout")
    a.block()                       # 6: lib exit block
    a.jmp("ret")
    return a.build(block_seed=0x11B7)


@register_target("cgc_like")
def cgc_like_target() -> Program:
    """Packet parser in the spirit of the CGC corpus binaries:

      bytes: 'C' 'G' <type> <len> <payload...>

    type 1: sums payload (loop blocks -> hit-count buckets);
    type 2: stores payload bytes into mem at offsets read from the
    payload itself — an unchecked index is the planted memory bug;
    type 3: echoes (distinct block).
    """
    a = Assembler("cgc_like", mem_size=32, max_steps=256)
    a.block()                                   # entry
    a.load_len(1)
    a.ldi(2, 4)
    a.br("lt", 1, 2, "bad")                     # too short
    a.block()
    a.expect_byte(3, 4, 0, ord("C"), "bad")     # magic
    a.expect_byte(3, 4, 1, ord("G"), "bad")
    # r5 = type, r6 = declared payload len
    a.ldi(3, 2)
    a.ldb(5, 3)
    a.ldi(3, 3)
    a.ldb(6, 3)
    # clamp declared len to actual remaining bytes: r7 = len - 4
    a.addi(7, 1, -4)
    a.br("ge", 7, 6, "len_ok")                  # remaining >= declared?
    a.block()
    a.alu("add", 6, 7, 0)                       # r6 = remaining (r0==0)
    a.label("len_ok")
    a.block()
    # dispatch on type
    a.ldi(2, 1)
    a.br("eq", 5, 2, "type1")
    a.ldi(2, 2)
    a.br("eq", 5, 2, "type2")
    a.ldi(2, 3)
    a.br("eq", 5, 2, "type3")
    a.jmp("bad")

    a.label("type1")                            # checksum loop
    a.block()
    a.ldi(2, 0)                                 # r2 = acc
    a.ldi(3, 0)                                 # r3 = i
    a.label("t1_loop")
    a.br("ge", 3, 6, "t1_done")
    a.block()                                   # loop body block (hit counts)
    a.addi(4, 3, 4)                             # r4 = 4 + i
    a.ldb(4, 4)
    a.alu("add", 2, 2, 4)
    a.addi(3, 3, 1)
    a.jmp("t1_loop")
    a.label("t1_done")
    a.block()
    a.halt(0)

    a.label("type2")                            # keyed store: planted bug
    a.block()
    a.ldi(3, 4)
    a.ldb(4, 3)                                 # r4 = payload[0] = index
    a.ldi(3, 5)
    a.ldb(2, 3)                                 # r2 = payload[1] = value
    # BUG: index used unchecked; mem_size=32, payload[0] can be 0..255
    a.stm(4, 2)
    a.block()
    a.halt(0)

    a.label("type3")                            # echo
    a.block()
    a.halt(0)

    a.label("bad")
    a.block()
    a.halt(1)
    return a.build(block_seed=0xC6C)
