"""KBVM — a batched bytecode VM on TPU.

One VM lane executes one candidate input; ``vmap`` runs thousands of
lanes in lockstep over a shared instruction tensor, and ``lax.scan``
drives the step machine with a static step budget (the hang timeout —
the reference's completion-poll timeout, driver/driver.c:44-46,
becomes "ran out of steps without HALT").

Instruction format: int32[NI, 4] rows ``(opcode, a, b, c)``.

  op  name    semantics
  0   HALT    status = FUZZ_NONE, exit_code = a
  1   BLOCK   coverage: cur = a; edge = cur ^ prev; prev = cur >> 1
  2   LDB     r[a] = input[r[b]]  (0 if index out of [0, length))
  3   LDI     r[a] = b
  4   ALU     r[a] = r[b] <op c> r[... ] — c selects ADD/SUB/AND/OR/
              XOR/SHL/SHR/MUL of r[b] and r[(c >> 3)]; see _ALU
  5   ADDI    r[a] = r[b] + c
  6   JMP     pc = a
  7   BR      conditional: if r[a] <cmp b> r[...]: pc = target — b
              packs (cmp, rb), c = target; see _CMP
  8   CRASH   status = FUZZ_CRASH (explicit fault, e.g. assert)
  9   LEN     r[a] = input length
  10  LDM     r[a] = mem[r[b]]; OUT-OF-BOUNDS -> FUZZ_CRASH (memory
              unsafety is the realistic bug model: a NULL/wild pointer
              dereference crashes the lane like a segfault)
  11  STM     mem[r[a]] = r[b]; OOB -> FUZZ_CRASH

Registers: 8 x int32. Scratch memory: ``mem_size`` x int32 per lane.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import FUZZ_CRASH, FUZZ_NONE, FUZZ_RUNNING, MAP_SIZE

N_REGS = 8

OP_HALT = 0
OP_BLOCK = 1
OP_LDB = 2
OP_LDI = 3
OP_ALU = 4
OP_ADDI = 5
OP_JMP = 6
OP_BR = 7
OP_CRASH = 8
OP_LEN = 9
OP_LDM = 10
OP_STM = 11
N_OPS = 12

ALU_ADD, ALU_SUB, ALU_AND, ALU_OR, ALU_XOR, ALU_SHL, ALU_SHR, ALU_MUL = \
    range(8)
CMP_EQ, CMP_NE, CMP_LT, CMP_GE = range(4)


@dataclass(frozen=True)
class Program:
    """A compiled target: shared instruction tensor + metadata.

    The coverage-edge universe of a KBVM program is STATIC — every
    dynamically possible (prev BLOCK, next BLOCK) pair is enumerable
    from the instruction graph at build time.  ``__post_init__``
    derives it once (`compute_edges`): the batched engine then
    accumulates a dense uint8[B, n_edges+1] hit-count table instead of
    materializing per-step edge streams, and triage runs over the few
    hundred real edges instead of sorting [B, max_steps] streams or
    scanning 64KB maps.  afl-as has no such luxury (targets are opaque
    binaries); this is the jit-harness tier's structural advantage.

    Derived fields (filled automatically):
      edge_from  int32[E]  source block index (-1 = program entry)
      edge_to    int32[E]  destination block index
      edge_slot  int32[E]  AFL map slot: to_id ^ (from_id >> 1)
      edge_table int32[n_blocks+1, n_blocks]  (from+1, to) -> edge
                 index; impossible pairs -> E (overflow column)
    """
    instrs: np.ndarray            # int32[NI, 4]
    name: str = "anon"
    mem_size: int = 64
    max_steps: int = 256          # hang budget (per-exec step cap)
    n_blocks: int = 0             # number of BLOCK instructions
    block_ids: Tuple[int, ...] = ()
    modules: Tuple[Tuple[str, int, int], ...] = ()  # (name, lo, hi) blocks
    edge_from: Optional[np.ndarray] = None
    edge_to: Optional[np.ndarray] = None
    edge_slot: Optional[np.ndarray] = None
    edge_table: Optional[np.ndarray] = None

    def __post_init__(self):
        assert self.instrs.ndim == 2 and self.instrs.shape[1] == 4
        assert self.instrs.dtype == np.int32
        if np.abs(self.instrs[:, 1:]).max(initial=0) >= (1 << 24):
            raise ValueError(
                "instruction field exceeds the batched engine's 2^24 "
                "exact-integer bound (f32 matmul fetch); build large "
                "constants with shl/or")
        if self.edge_table is None:
            instrs, ef, et, es, tbl, n_blocks, ids = compute_edges(
                self.instrs)
            object.__setattr__(self, "instrs", instrs)
            object.__setattr__(self, "edge_from", ef)
            object.__setattr__(self, "edge_to", et)
            object.__setattr__(self, "edge_table", tbl)
            if not self.n_blocks:
                object.__setattr__(self, "n_blocks", n_blocks)
            if not self.block_ids:
                object.__setattr__(self, "block_ids", ids)
            if not self.modules:
                object.__setattr__(
                    self, "modules", (("target", 0, self.n_blocks),))
            # per-module slot spaces: an edge lands in the map of its
            # DESTINATION block's module (winafl writes the edge into
            # the current block's module map), at global offset
            # module_index * MAP_SIZE
            mod_of_block = np.zeros(max(self.n_blocks, 1),
                                    dtype=np.int64)
            for m, (_, lo, hi) in enumerate(self.modules):
                mod_of_block[lo:hi] = m
            es = es + (mod_of_block[et] * MAP_SIZE if len(et)
                       else 0)
            object.__setattr__(self, "edge_slot", es.astype(np.int32))
        # assign_block_ids draws MAP_SIZE-bounded ids: birthday
        # collisions silently alias distinct blocks in the AFL map
        # (kb-lint reports the exact pairs)
        n_dup = self.n_blocks - len(set(self.block_ids))
        if n_dup > 0:
            from ..utils.logging import WARNING_MSG
            WARNING_MSG(
                "program %r: %d duplicate coverage id(s) among %d "
                "blocks alias in the AFL map (re-seed "
                "assign_block_ids; kb-lint shows the pairs)",
                self.name, n_dup, self.n_blocks)

    @property
    def n_edges(self) -> int:
        return int(self.edge_from.shape[0])

    @property
    def map_size(self) -> int:
        """Total coverage-map bytes: one 64KB map per module."""
        return max(len(self.modules), 1) * MAP_SIZE

    @property
    def module_names(self) -> Tuple[str, ...]:
        return tuple(m[0] for m in self.modules)


def compute_edges(instrs: np.ndarray):
    """Enumerate the static edge universe of an instruction tensor.

    Returns ``(instrs', edge_from, edge_to, edge_slot, edge_table,
    n_blocks, block_ids)`` where instrs' is a copy with each BLOCK
    row's b field set to the block's ordinal index (the engine reads
    it to key the edge table).

    An edge (f, t) exists when some instruction path runs from block
    f's body to block t's BLOCK head without crossing another BLOCK;
    f = -1 models the entry path (prev_loc starts at 0, so the slot is
    just t's id — matching the dynamic ``cur ^ prev`` fold).
    """
    ni = instrs.shape[0]
    instrs = instrs.copy()
    block_pcs = [pc for pc in range(ni) if instrs[pc, 0] == OP_BLOCK]
    idx_of_pc = {pc: k for k, pc in enumerate(block_pcs)}
    for k, pc in enumerate(block_pcs):
        instrs[pc, 2] = k
    nb = len(block_pcs)
    ids = tuple(int(instrs[pc, 1]) & (MAP_SIZE - 1) for pc in block_pcs)

    def succs(pc):
        op, a, b, c = instrs[pc]
        if op in (OP_HALT, OP_CRASH):
            return []
        if op == OP_JMP:
            return [int(a)]
        if op == OP_BR:
            return [int(c), pc + 1]
        return [pc + 1]

    pairs = set()
    def walk(from_idx, start_pc):
        seen = set()
        stack = [start_pc]
        while stack:
            pc = stack.pop()
            if pc in seen or pc < 0 or pc >= ni:
                continue           # out-of-range pc = crash, no edge
            seen.add(pc)
            if instrs[pc, 0] == OP_BLOCK:
                pairs.add((from_idx, idx_of_pc[pc]))
                continue
            stack.extend(succs(pc))

    walk(-1, 0)
    for k, pc in enumerate(block_pcs):
        walk(k, pc + 1)

    order = sorted(pairs)
    e = len(order)
    edge_from = np.array([f for f, _ in order] or [], dtype=np.int32)
    edge_to = np.array([t for _, t in order] or [], dtype=np.int32)
    slot = []
    for f, t in order:
        prev_loc = 0 if f < 0 else (ids[f] >> 1)
        slot.append(ids[t] ^ prev_loc)
    edge_slot = np.array(slot or [], dtype=np.int32)
    edge_table = np.full((nb + 1, max(nb, 1)), e, dtype=np.int32)
    for k, (f, t) in enumerate(order):
        edge_table[f + 1, t] = k
    return (instrs, edge_from, edge_to, edge_slot, edge_table, nb, ids)


class VMResult(NamedTuple):
    """Per-lane execution outcome.

    ``counts`` is the production coverage record: hit counts over the
    program's static edge universe (last column = overflow for pairs
    outside the enumerated table — never taken for well-formed
    programs).  ``edge_ids`` is the optional time-ordered stream
    (tracer / ipt / parity tests); fuzz steps run with it disabled.
    ``path_hash`` is an order-aware hash of the block-id sequence,
    folded incrementally during execution (the ipt tier's path
    identity without materializing the stream).
    """
    status: jax.Array      # int32[B]: FUZZ_NONE / FUZZ_CRASH / FUZZ_RUNNING
    exit_code: jax.Array   # int32[B]
    counts: jax.Array      # uint8[B, E+1] static-edge hit counts
    steps: jax.Array       # int32[B] steps actually executed
    path_hash: jax.Array   # uint32[B]
    edge_ids: Optional[jax.Array] = None  # int32[B, T] (-1 = no edge)


def _step(instrs, edge_table, input_buf, input_len, mem_size, state):
    """One VM step for one lane (the readable reference engine the
    batched one-hot engine is parity-tested against). state = (pc,
    regs, mem, prev_loc, status, exit_code, prev_idx, counts,
    path_hash). Returns (state, edge_id)."""
    pc, regs, mem, prev_loc, status, exit_code, prev_idx, counts, \
        path_hash = state
    ni = instrs.shape[0]
    row = instrs[jnp.clip(pc, 0, ni - 1)]
    op, a, b, c = row[0], row[1], row[2], row[3]

    running = status == FUZZ_RUNNING
    nxt = pc + 1

    # decode fields used by several ops
    rb_idx = (c >> 3) & (N_REGS - 1)
    alu_sel = c & 7
    cmp_sel = b & 3
    cmp_rb = (b >> 2) & (N_REGS - 1)

    ra = regs[jnp.clip(a, 0, N_REGS - 1)]
    rb = regs[jnp.clip(b, 0, N_REGS - 1)]

    # --- per-op results (all computed; select by op) ---
    # LDB
    ldb_idx = rb
    ldb_ok = (ldb_idx >= 0) & (ldb_idx < input_len)
    ldb_val = jnp.where(
        ldb_ok,
        input_buf[jnp.clip(ldb_idx, 0, input_buf.shape[0] - 1)].astype(
            jnp.int32),
        0)
    # ALU
    x, y = rb, regs[rb_idx]
    shift = jnp.clip(y, 0, 31)
    alu_val = jnp.select(
        [alu_sel == ALU_ADD, alu_sel == ALU_SUB, alu_sel == ALU_AND,
         alu_sel == ALU_OR, alu_sel == ALU_XOR, alu_sel == ALU_SHL,
         alu_sel == ALU_SHR, alu_sel == ALU_MUL],
        [x + y, x - y, x & y, x | y, x ^ y, x << shift,
         jax.lax.shift_right_logical(x, shift), x * y],
        default=jnp.int32(0))
    # BR
    cmp_y = regs[cmp_rb]
    taken = jnp.select(
        [cmp_sel == CMP_EQ, cmp_sel == CMP_NE, cmp_sel == CMP_LT,
         cmp_sel == CMP_GE],
        [ra == cmp_y, ra != cmp_y, ra < cmp_y, ra >= cmp_y],
        default=False)
    # LDM / STM
    mem_idx = rb
    mem_ok_ld = (mem_idx >= 0) & (mem_idx < mem_size)
    ldm_val = jnp.where(
        mem_ok_ld, mem[jnp.clip(mem_idx, 0, mem_size - 1)], 0)
    stm_idx = ra
    mem_ok_st = (stm_idx >= 0) & (stm_idx < mem_size)

    # --- new pc ---
    new_pc = jnp.select(
        [op == OP_JMP, op == OP_BR],
        [a, jnp.where(taken, c, nxt)],
        default=nxt)

    # --- new register file (one scatter) ---
    wr_val = jnp.select(
        [op == OP_LDB, op == OP_LDI, op == OP_ALU, op == OP_ADDI,
         op == OP_LEN, op == OP_LDM],
        [ldb_val, b, alu_val, rb + c, input_len, ldm_val],
        default=jnp.int32(0))
    writes_reg = jnp.isin(op, jnp.asarray(
        [OP_LDB, OP_LDI, OP_ALU, OP_ADDI, OP_LEN, OP_LDM]))
    reg_target = jnp.where(writes_reg, jnp.clip(a, 0, N_REGS - 1), N_REGS)
    new_regs = regs.at[reg_target].set(wr_val, mode="drop")

    # --- memory write ---
    do_store = (op == OP_STM) & mem_ok_st
    mem_target = jnp.where(do_store, jnp.clip(stm_idx, 0, mem_size - 1),
                           mem_size)
    new_mem = mem.at[mem_target].set(rb, mode="drop")

    # --- status transitions ---
    crashes = (op == OP_CRASH) | \
              ((op == OP_LDM) & ~mem_ok_ld) | \
              ((op == OP_STM) & ~mem_ok_st) | \
              (pc < 0) | (pc >= ni)
    halts = op == OP_HALT
    new_status = jnp.where(crashes, FUZZ_CRASH,
                           jnp.where(halts, FUZZ_NONE, status))
    new_exit = jnp.where(halts, a, exit_code)

    # --- coverage ---
    is_block = (op == OP_BLOCK) & running
    cur_loc = a & (MAP_SIZE - 1)
    edge_id = jnp.where(is_block, cur_loc ^ prev_loc, -1)
    new_prev = jnp.where(is_block, cur_loc >> 1, prev_loc)
    nb = edge_table.shape[1]
    cur_idx = jnp.clip(b, 0, nb - 1)
    eidx = edge_table[jnp.clip(prev_idx, 0, nb), cur_idx]
    new_counts = counts.at[jnp.where(is_block, eidx,
                                     counts.shape[0] - 1)].add(
        jnp.where(is_block, jnp.uint8(1), jnp.uint8(0)), mode="drop")
    new_prev_idx = jnp.where(is_block, cur_idx + 1, prev_idx)
    new_hash = jnp.where(
        is_block, _mix32(path_hash ^ cur_loc.astype(jnp.uint32)),
        path_hash)

    # lanes that already halted/crashed freeze in place
    def keep(new, old):
        return jnp.where(running, new, old)

    out_state = (keep(new_pc, pc), keep(new_regs, regs),
                 keep(new_mem, mem), keep(new_prev, prev_loc),
                 keep(new_status, status), keep(new_exit, exit_code),
                 keep(new_prev_idx, prev_idx), new_counts,
                 keep(new_hash, path_hash))
    return out_state, edge_id


def _run_one(instrs, edge_table, n_edges, mem_size, max_steps,
             input_buf, input_len):
    """Execute one lane to completion (or step budget).

    Uses ``while_loop`` rather than a fixed-length scan: under vmap
    the loop runs until every lane halts (or the budget), so a batch
    whose longest path is 25 steps costs 25 iterations, not the full
    hang budget — a ~2x win on crash-hunting workloads.
    """
    state0 = (jnp.int32(0),
              jnp.zeros(N_REGS, dtype=jnp.int32),
              jnp.zeros(mem_size, dtype=jnp.int32),
              jnp.int32(0),
              jnp.int32(FUZZ_RUNNING),
              jnp.int32(0),
              jnp.int32(0),
              jnp.zeros(n_edges + 1, dtype=jnp.uint8),
              jnp.uint32(0))
    edges0 = jnp.full((max_steps,), -1, dtype=jnp.int32)

    def cond(carry):
        state, _, i = carry
        return (state[4] == FUZZ_RUNNING) & (i < max_steps)

    def body(carry):
        state, edges, i = carry
        new_state, edge = _step(instrs, edge_table, input_buf,
                                input_len, mem_size, state)
        edges = edges.at[i].set(edge, mode="drop")
        return new_state, edges, i + 1

    final, edges, steps = jax.lax.while_loop(cond, body,
                                             (state0, edges0,
                                              jnp.int32(0)))
    return VMResult(status=final[4], exit_code=final[5],
                    counts=final[7], steps=steps, path_hash=final[8],
                    edge_ids=edges)


# --------------------------------------------------------------------
# Batched one-hot engine — the production path
# --------------------------------------------------------------------
#
# ``vmap(_run_one)`` is semantically right but lowers every per-lane
# read (instruction fetch, register read, memory load) to a
# scalar-per-lane gather, which TPUs execute poorly: the whole VM ran
# at ~70ms / 32k-lane batch.  The batched engine below keeps ALL state
# lane-major ([B, ...]) and replaces every dynamic index with a
# one-hot compare-select over the (small, static) indexed axis —
# registers (8), memory (mem_size), instructions (NI), input bytes
# (L).  That is nominally more arithmetic, but it is pure fused
# elementwise/reduction work the VPU streams at full rate: ~8x faster
# end-to-end, bit-identical results (parity-tested against _run_one).

def _onehot_pick(table, idx, axis_len):
    """out[b] = table[b, idx[b]] without a gather: one-hot over the
    last axis (static, small)."""
    lanes = jnp.arange(axis_len, dtype=jnp.int32)[None, :]
    return jnp.sum(jnp.where(lanes == idx[:, None], table, 0), axis=1)


def _mix32(x):
    """murmur3 finalizer — the per-block path-hash mixer."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _step_batched(instrs, edge_table, bufs_t, lengths, mem_size,
                  record_stream, state):
    """One VM step for ALL lanes. state = (pc, regs, mem, prev_loc,
    status, exit_code, prev_idx, counts, path_hash, edges, i,
    lane_steps); arrays are [B, ...]; bufs_t is the transposed input
    [L, B] so byte selects run over static rows."""
    (pc, regs, mem, prev_loc, status, exit_code, prev_idx, counts,
     path_hash, edges, i, lane_steps) = state
    ni = instrs.shape[0]
    L = bufs_t.shape[0]
    running = status == FUZZ_RUNNING

    pcc = jnp.clip(pc, 0, ni - 1)
    onehot_pc = pcc[:, None] == jnp.arange(ni, dtype=jnp.int32)[None, :]
    # instruction fetch as an MXU matmul: the one-hot row has exactly
    # one 1, so the f32 dot is exact for any field < 2^24 (block ids
    # are < 2^16, imms/pcs far smaller) and XLA fuses the compare into
    # the matmul operand instead of materializing [B, NI, 4] selects
    row = jax.lax.dot(onehot_pc.astype(jnp.float32),
                      instrs.astype(jnp.float32),
                      precision=jax.lax.Precision.HIGHEST)
    row = row.astype(jnp.int32)                              # [B, 4]
    op, a, b, c = row[:, 0], row[:, 1], row[:, 2], row[:, 3]

    rb_idx = (c >> 3) & (N_REGS - 1)
    alu_sel = c & 7
    cmp_sel = b & 3
    cmp_rb = (b >> 2) & (N_REGS - 1)

    ra = _onehot_pick(regs, jnp.clip(a, 0, N_REGS - 1), N_REGS)
    rb = _onehot_pick(regs, jnp.clip(b, 0, N_REGS - 1), N_REGS)
    ry = _onehot_pick(regs, rb_idx, N_REGS)
    cmp_y = _onehot_pick(regs, cmp_rb, N_REGS)

    # LDB: one-hot over the (transposed) input rows
    ldb_ok = (rb >= 0) & (rb < lengths)
    lsel = jnp.clip(rb, 0, L - 1)
    lidx = jnp.arange(L, dtype=jnp.int32)[:, None]
    ldb_val = jnp.sum(
        jnp.where(lidx == lsel[None, :], bufs_t, 0), axis=0
    ).astype(jnp.int32)
    ldb_val = jnp.where(ldb_ok, ldb_val, 0)

    x, y = rb, ry
    shift = jnp.clip(y, 0, 31)
    alu_val = jnp.select(
        [alu_sel == ALU_ADD, alu_sel == ALU_SUB, alu_sel == ALU_AND,
         alu_sel == ALU_OR, alu_sel == ALU_XOR, alu_sel == ALU_SHL,
         alu_sel == ALU_SHR, alu_sel == ALU_MUL],
        [x + y, x - y, x & y, x | y, x ^ y, x << shift,
         jax.lax.shift_right_logical(x, shift), x * y],
        default=jnp.int32(0))
    taken = jnp.select(
        [cmp_sel == CMP_EQ, cmp_sel == CMP_NE, cmp_sel == CMP_LT,
         cmp_sel == CMP_GE],
        [ra == cmp_y, ra != cmp_y, ra < cmp_y, ra >= cmp_y],
        default=False)

    midx = jnp.arange(mem_size, dtype=jnp.int32)[None, :]
    mem_ok_ld = (rb >= 0) & (rb < mem_size)
    ldm_val = _onehot_pick(mem, jnp.clip(rb, 0, mem_size - 1), mem_size)
    ldm_val = jnp.where(mem_ok_ld, ldm_val, 0)
    mem_ok_st = (ra >= 0) & (ra < mem_size)

    nxt = pc + 1
    new_pc = jnp.select([op == OP_JMP, op == OP_BR],
                        [a, jnp.where(taken, c, nxt)], nxt)
    wr_val = jnp.select(
        [op == OP_LDB, op == OP_LDI, op == OP_ALU, op == OP_ADDI,
         op == OP_LEN, op == OP_LDM],
        [ldb_val, b, alu_val, rb + c, lengths, ldm_val],
        default=jnp.int32(0))
    writes_reg = jnp.isin(op, jnp.asarray(
        [OP_LDB, OP_LDI, OP_ALU, OP_ADDI, OP_LEN, OP_LDM]))
    ridx = jnp.arange(N_REGS, dtype=jnp.int32)[None, :]
    wmask = (writes_reg & running)[:, None] & \
        (ridx == jnp.clip(a, 0, N_REGS - 1)[:, None])
    new_regs = jnp.where(wmask, wr_val[:, None], regs)

    do_store = (op == OP_STM) & mem_ok_st & running
    smask = do_store[:, None] & \
        (midx == jnp.clip(ra, 0, mem_size - 1)[:, None])
    new_mem = jnp.where(smask, rb[:, None], mem)

    crashes = (op == OP_CRASH) | \
              ((op == OP_LDM) & ~mem_ok_ld) | \
              ((op == OP_STM) & ~mem_ok_st) | \
              (pc < 0) | (pc >= ni)
    halts = op == OP_HALT
    new_status = jnp.where(crashes, FUZZ_CRASH,
                           jnp.where(halts, FUZZ_NONE, status))
    new_exit = jnp.where(halts & running, a, exit_code)

    is_block = (op == OP_BLOCK) & running
    cur_loc = a & (MAP_SIZE - 1)
    new_prev = jnp.where(is_block, cur_loc >> 1, prev_loc)

    # static-edge hit counts: the BLOCK row's b field is the block
    # ordinal; (prev block, this block) keys the edge table.  The
    # two-level lookup runs as a matmul + masked pick (no per-lane
    # gather, same trick as the instruction fetch).
    nb = edge_table.shape[1]
    cur_idx = jnp.clip(b, 0, nb - 1)
    onehot_prev = (prev_idx[:, None]
                   == jnp.arange(edge_table.shape[0],
                                 dtype=jnp.int32)[None, :])
    rows_e = jax.lax.dot(onehot_prev.astype(jnp.float32),
                         edge_table.astype(jnp.float32),
                         precision=jax.lax.Precision.HIGHEST)  # [B, nb]
    eidx = _onehot_pick(rows_e.astype(jnp.int32), cur_idx, nb)
    n_e = counts.shape[1]                         # E + 1 (overflow)
    emask_e = (jnp.arange(n_e, dtype=jnp.int32)[None, :]
               == eidx[:, None]) & is_block[:, None]
    new_counts = counts + emask_e.astype(jnp.uint8)
    new_prev_idx = jnp.where(is_block, cur_idx + 1, prev_idx)
    new_hash = jnp.where(
        is_block, _mix32(path_hash ^ cur_loc.astype(jnp.uint32)),
        path_hash)

    if record_stream:
        edge = jnp.where(is_block, cur_loc ^ prev_loc, -1)
        t = edges.shape[1]
        emask = (jnp.arange(t, dtype=jnp.int32)[None, :] == i) & \
            running[:, None]
        new_edges = jnp.where(emask, edge[:, None], edges)
    else:
        new_edges = edges

    def keep(new, old):
        return jnp.where(running, new, old)

    return (keep(new_pc, pc),
            jnp.where(running[:, None], new_regs, regs),
            jnp.where(running[:, None], new_mem, mem),
            keep(new_prev, prev_loc),
            keep(new_status, status),
            keep(new_exit, exit_code),
            keep(new_prev_idx, prev_idx),
            new_counts, keep(new_hash, path_hash),
            new_edges, i + 1,
            lane_steps + running.astype(jnp.int32))


@partial(jax.jit, static_argnames=("mem_size", "max_steps", "n_edges",
                                   "record_stream"))
def _run_batch_impl(instrs, edge_table, inputs, lengths, mem_size,
                    max_steps, n_edges, record_stream=False):
    b = inputs.shape[0]
    state0 = (jnp.zeros(b, jnp.int32),
              jnp.zeros((b, N_REGS), jnp.int32),
              jnp.zeros((b, mem_size), jnp.int32),
              jnp.zeros(b, jnp.int32),
              jnp.full(b, FUZZ_RUNNING, jnp.int32),
              jnp.zeros(b, jnp.int32),
              jnp.zeros(b, jnp.int32),                     # prev_idx
              jnp.zeros((b, n_edges + 1), jnp.uint8),      # counts
              jnp.zeros(b, jnp.uint32),                    # path_hash
              (jnp.full((b, max_steps), -1, jnp.int32)
               if record_stream else jnp.zeros((b, 0), jnp.int32)),
              jnp.int32(0),
              jnp.zeros(b, jnp.int32))
    bufs_t = inputs.T
    lengths = lengths.astype(jnp.int32)

    def cond(s):
        return jnp.any(s[4] == FUZZ_RUNNING) & (s[10] < max_steps)

    def body(s):
        return _step_batched(instrs, edge_table, bufs_t, lengths,
                             mem_size, record_stream, s)

    final = jax.lax.while_loop(cond, body, state0)
    return VMResult(status=final[4], exit_code=final[5],
                    counts=final[7], steps=final[11],
                    path_hash=final[8],
                    edge_ids=final[9] if record_stream else None)


def run_batch(program: Program, inputs: jax.Array, lengths: jax.Array,
              record_stream: bool = True) -> VMResult:
    """Execute a uint8[B, L] candidate batch through the program.

    Lanes still RUNNING after ``program.max_steps`` are hangs —
    callers map FUZZ_RUNNING -> FUZZ_HANG, mirroring the reference's
    wait-loop timeout.  ``record_stream=False`` skips the [B, T] edge
    stream (production fuzz steps use the static-edge counts).
    """
    return _run_batch_impl(jnp.asarray(program.instrs),
                           jnp.asarray(program.edge_table),
                           inputs, lengths,
                           program.mem_size, program.max_steps,
                           program.n_edges, record_stream)


# --------------------------------------------------------------------
# Distance-returning execute variant (the gradient-search objective)
# --------------------------------------------------------------------
#
# Angora (arxiv 1803.01307) treats an uncracked branch as a black-box
# distance function over the input bytes and descends it; the batched
# engine makes the expensive half of that — "evaluate the objective on
# thousands of candidate inputs" — one device dispatch.  The variant
# below threads a per-lane best-distance accumulator through the SAME
# ``_step_batched`` transition as the production engine: coverage
# counts, statuses, steps and path hashes are bit-identical when the
# distance output is ignored (parity-pinned in tests/test_search.py).

#: distance of a lane that never reached the target branch while in
#: the objective's source block (float32-representable "infinity")
DIST_UNREACHED = 3.0e38


def _branch_distance(sel: int, x, y):
    """Angora's branch-distance table for ONE comparison direction:
    0.0 exactly when ``x <sel> y`` holds (judged in exact int32), a
    positive magnitude otherwise.  ``sel`` is the CANONICAL compare —
    callers wanting the fall-through successor pass the negated
    compare (eq<->ne, lt<->ge), so distance 0 always means "the
    branch goes the way the target edge needs".  Magnitudes are
    float32 (|operand| < 2^24 exact — byte-derived values in
    practice); only the zero test must be, and is, exact."""
    xf = x.astype(jnp.float32)
    yf = y.astype(jnp.float32)
    if sel == CMP_EQ:
        sat = x == y
        mag = jnp.abs(xf - yf)
    elif sel == CMP_NE:
        sat = x != y
        mag = jnp.float32(1.0)
    elif sel == CMP_LT:
        sat = x < y
        mag = xf - yf + jnp.float32(1.0)
    else:  # CMP_GE
        sat = x >= y
        mag = yf - xf
    return jnp.where(sat, jnp.float32(0.0),
                     jnp.maximum(mag, jnp.float32(1.0)))


def _dist_loop_core(instrs, edge_table, inputs, lengths, mem_size,
                    max_steps, n_edges, specs, capture):
    """``_run_batch_impl`` plus per-lane min-distance accumulators
    for K observed branches at once — the shared (un-jitted) core of
    the host dispatch wrappers AND the device-resident descent scan
    (``search/device_descent.py`` inlines it per scan iteration).

    ``specs`` is a static tuple of ``(branch_pc, from_idx, sel,
    x_idx, y_idx)`` tuples.  Each distance is sampled from the state
    ENTERING a step, before ``_step_batched`` runs it, whenever a
    still-running lane is about to execute that branch pc with that
    source block as its last block (``prev_idx == from_idx + 1`` —
    the edge-table row key), so the observations never perturb the
    transition.  One dispatch therefore scores a whole guard
    CURRICULUM (the path conditions into a frontier block plus the
    frontier branch itself) for every candidate.

    ``capture`` (static) additionally records the CONCRETE compare
    operand values at the min-distance sample of each spec —
    int32[B, K] ``x``/``y`` register values, the raw material of
    Redqueen-style input-to-state matching (copy what the program
    actually compared against back into the input).  With capture
    off the carried state is exactly the historical one, so the
    ``capture_operands=False`` path stays bit-identical.

    Returns ``(VMResult, best, cap_x, cap_y)`` (capture arrays are
    zeros-shaped [B, K] when capture is off)."""
    b = inputs.shape[0]
    k_n = len(specs)
    state0 = (jnp.zeros(b, jnp.int32),
              jnp.zeros((b, N_REGS), jnp.int32),
              jnp.zeros((b, mem_size), jnp.int32),
              jnp.zeros(b, jnp.int32),
              jnp.full(b, FUZZ_RUNNING, jnp.int32),
              jnp.zeros(b, jnp.int32),
              jnp.zeros(b, jnp.int32),                     # prev_idx
              jnp.zeros((b, n_edges + 1), jnp.uint8),      # counts
              jnp.zeros(b, jnp.uint32),                    # path_hash
              jnp.zeros((b, 0), jnp.int32),
              jnp.int32(0),
              jnp.zeros(b, jnp.int32))
    best0 = jnp.full((b, k_n), DIST_UNREACHED, jnp.float32)
    k_cap = k_n if capture else 0      # capture off: nothing carried
    caps0 = (jnp.zeros((b, k_cap), jnp.int32),
             jnp.zeros((b, k_cap), jnp.int32))
    bufs_t = inputs.T
    lengths = lengths.astype(jnp.int32)

    def cond(carry):
        s = carry[0]
        return jnp.any(s[4] == FUZZ_RUNNING) & (s[10] < max_steps)

    def body(carry):
        s, best, (cap_x, cap_y) = carry
        running = s[4] == FUZZ_RUNNING
        cols = []
        xcols, ycols = [], []
        for k, (branch_pc, from_idx, sel, x_idx, y_idx) \
                in enumerate(specs):
            at = (s[0] == branch_pc) & (s[6] == from_idx + 1) & running
            x, y = s[1][:, x_idx], s[1][:, y_idx]
            d = _branch_distance(sel, x, y)
            cols.append(jnp.where(at, jnp.minimum(best[:, k], d),
                                  best[:, k]))
            if capture:
                upd = at & (d < best[:, k])
                xcols.append(jnp.where(upd, x, cap_x[:, k]))
                ycols.append(jnp.where(upd, y, cap_y[:, k]))
        best = jnp.stack(cols, axis=1)
        caps = ((jnp.stack(xcols, axis=1), jnp.stack(ycols, axis=1))
                if capture else (cap_x, cap_y))
        return (_step_batched(instrs, edge_table, bufs_t, lengths,
                              mem_size, False, s), best, caps)

    final, best, caps = jax.lax.while_loop(cond, body,
                                           (state0, best0, caps0))
    return (VMResult(status=final[4], exit_code=final[5],
                     counts=final[7], steps=final[11],
                     path_hash=final[8], edge_ids=None),
            best, caps[0], caps[1])


@partial(jax.jit, static_argnames=("mem_size", "max_steps", "n_edges",
                                   "specs"))
def _run_batch_dist_impl(instrs, edge_table, inputs, lengths, mem_size,
                         max_steps, n_edges, specs):
    res, best, _, _ = _dist_loop_core(
        instrs, edge_table, inputs, lengths, mem_size, max_steps,
        n_edges, specs, False)
    return res, best


@partial(jax.jit, static_argnames=("mem_size", "max_steps", "n_edges",
                                   "specs"))
def _run_batch_dist_ops_impl(instrs, edge_table, inputs, lengths,
                             mem_size, max_steps, n_edges, specs):
    return _dist_loop_core(
        instrs, edge_table, inputs, lengths, mem_size, max_steps,
        n_edges, specs, True)


def run_batch_distances(program: Program, inputs: jax.Array,
                        lengths: jax.Array, specs,
                        capture_operands: bool = False):
    """Execute a candidate batch and return, per lane, the minimum
    branch distance observed at each of the K ``specs`` (tuples of
    ``(branch_pc, from_idx, sel, x_idx, y_idx)``) — float32[B, K],
    ``DIST_UNREACHED`` where never sampled.  The VMResult is
    bit-identical to ``run_batch(..., record_stream=False)``.
    ``search/objective.py`` derives specs from target edges.

    ``capture_operands=True`` returns ``(res, dists, cap_x, cap_y)``
    where the int32[B, K] capture arrays hold the concrete compare
    operand values observed at each spec's min-distance sample (0
    where never sampled — check ``dists`` for reachability).  The
    input-to-state matcher copies these observed values back into
    candidate bytes at the operands' dynamic byte-dependency
    positions (Redqueen's move, bought from the engine's own state
    instead of a shadow tracer)."""
    specs = tuple(tuple(int(v) for v in s) for s in specs)
    if not specs:
        raise ValueError("at least one branch spec is required")
    args = (jnp.asarray(program.instrs),
            jnp.asarray(program.edge_table),
            inputs, lengths, program.mem_size, program.max_steps,
            program.n_edges, specs)
    if capture_operands:
        return _run_batch_dist_ops_impl(*args)
    return _run_batch_dist_impl(*args)


def run_batch_distance(program: Program, inputs: jax.Array,
                       lengths: jax.Array, *, branch_pc: int,
                       from_idx: int, sel: int, x_idx: int,
                       y_idx: int) -> Tuple[VMResult, jax.Array]:
    """Single-branch convenience wrapper over
    ``run_batch_distances`` (returns float32[B])."""
    res, best = run_batch_distances(
        program, inputs, lengths,
        ((branch_pc, from_idx, sel, x_idx, y_idx),))
    return res, best[:, 0]


def compile_runner(program: Program, record_stream: bool = True):
    """Return a jitted ``(inputs, lengths) -> VMResult`` closure with
    the instruction tensor baked in (constant-folded by XLA)."""
    instrs = jnp.asarray(program.instrs)
    edge_table = jnp.asarray(program.edge_table)

    @jax.jit
    def runner(inputs, lengths):
        return _run_batch_impl(instrs, edge_table, inputs, lengths,
                               program.mem_size, program.max_steps,
                               program.n_edges, record_stream)

    return runner
