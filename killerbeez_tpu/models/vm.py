"""KBVM — a batched bytecode VM on TPU.

One VM lane executes one candidate input; ``vmap`` runs thousands of
lanes in lockstep over a shared instruction tensor, and ``lax.scan``
drives the step machine with a static step budget (the hang timeout —
the reference's completion-poll timeout, driver/driver.c:44-46,
becomes "ran out of steps without HALT").

Instruction format: int32[NI, 4] rows ``(opcode, a, b, c)``.

  op  name    semantics
  0   HALT    status = FUZZ_NONE, exit_code = a
  1   BLOCK   coverage: cur = a; edge = cur ^ prev; prev = cur >> 1
  2   LDB     r[a] = input[r[b]]  (0 if index out of [0, length))
  3   LDI     r[a] = b
  4   ALU     r[a] = r[b] <op c> r[... ] — c selects ADD/SUB/AND/OR/
              XOR/SHL/SHR/MUL of r[b] and r[(c >> 3)]; see _ALU
  5   ADDI    r[a] = r[b] + c
  6   JMP     pc = a
  7   BR      conditional: if r[a] <cmp b> r[...]: pc = target — b
              packs (cmp, rb), c = target; see _CMP
  8   CRASH   status = FUZZ_CRASH (explicit fault, e.g. assert)
  9   LEN     r[a] = input length
  10  LDM     r[a] = mem[r[b]]; OUT-OF-BOUNDS -> FUZZ_CRASH (memory
              unsafety is the realistic bug model: a NULL/wild pointer
              dereference crashes the lane like a segfault)
  11  STM     mem[r[a]] = r[b]; OOB -> FUZZ_CRASH

Registers: 8 x int32. Scratch memory: ``mem_size`` x int32 per lane.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import FUZZ_CRASH, FUZZ_NONE, FUZZ_RUNNING, MAP_SIZE

N_REGS = 8

OP_HALT = 0
OP_BLOCK = 1
OP_LDB = 2
OP_LDI = 3
OP_ALU = 4
OP_ADDI = 5
OP_JMP = 6
OP_BR = 7
OP_CRASH = 8
OP_LEN = 9
OP_LDM = 10
OP_STM = 11
N_OPS = 12

ALU_ADD, ALU_SUB, ALU_AND, ALU_OR, ALU_XOR, ALU_SHL, ALU_SHR, ALU_MUL = \
    range(8)
CMP_EQ, CMP_NE, CMP_LT, CMP_GE = range(4)


@dataclass(frozen=True)
class Program:
    """A compiled target: shared instruction tensor + metadata."""
    instrs: np.ndarray            # int32[NI, 4]
    name: str = "anon"
    mem_size: int = 64
    max_steps: int = 256          # hang budget (per-exec step cap)
    n_blocks: int = 0             # number of BLOCK instructions
    block_ids: Tuple[int, ...] = ()

    def __post_init__(self):
        assert self.instrs.ndim == 2 and self.instrs.shape[1] == 4
        assert self.instrs.dtype == np.int32


class VMResult(NamedTuple):
    """Per-lane execution outcome."""
    status: jax.Array      # int32[B]: FUZZ_NONE / FUZZ_CRASH / FUZZ_RUNNING
    exit_code: jax.Array   # int32[B]
    edge_ids: jax.Array    # int32[B, T] edge stream (-1 = no edge)
    steps: jax.Array       # int32[B] steps actually executed


def _step(instrs, input_buf, input_len, mem_size, state):
    """One VM step for one lane. state = (pc, regs, mem, prev_loc,
    status, exit_code). Returns (state, edge_id)."""
    pc, regs, mem, prev_loc, status, exit_code = state
    ni = instrs.shape[0]
    row = instrs[jnp.clip(pc, 0, ni - 1)]
    op, a, b, c = row[0], row[1], row[2], row[3]

    running = status == FUZZ_RUNNING
    nxt = pc + 1

    # decode fields used by several ops
    rb_idx = (c >> 3) & (N_REGS - 1)
    alu_sel = c & 7
    cmp_sel = b & 3
    cmp_rb = (b >> 2) & (N_REGS - 1)

    ra = regs[jnp.clip(a, 0, N_REGS - 1)]
    rb = regs[jnp.clip(b, 0, N_REGS - 1)]

    # --- per-op results (all computed; select by op) ---
    # LDB
    ldb_idx = rb
    ldb_ok = (ldb_idx >= 0) & (ldb_idx < input_len)
    ldb_val = jnp.where(
        ldb_ok,
        input_buf[jnp.clip(ldb_idx, 0, input_buf.shape[0] - 1)].astype(
            jnp.int32),
        0)
    # ALU
    x, y = rb, regs[rb_idx]
    shift = jnp.clip(y, 0, 31)
    alu_val = jnp.select(
        [alu_sel == ALU_ADD, alu_sel == ALU_SUB, alu_sel == ALU_AND,
         alu_sel == ALU_OR, alu_sel == ALU_XOR, alu_sel == ALU_SHL,
         alu_sel == ALU_SHR, alu_sel == ALU_MUL],
        [x + y, x - y, x & y, x | y, x ^ y, x << shift,
         jax.lax.shift_right_logical(x, shift), x * y],
        default=jnp.int32(0))
    # BR
    cmp_y = regs[cmp_rb]
    taken = jnp.select(
        [cmp_sel == CMP_EQ, cmp_sel == CMP_NE, cmp_sel == CMP_LT,
         cmp_sel == CMP_GE],
        [ra == cmp_y, ra != cmp_y, ra < cmp_y, ra >= cmp_y],
        default=False)
    # LDM / STM
    mem_idx = rb
    mem_ok_ld = (mem_idx >= 0) & (mem_idx < mem_size)
    ldm_val = jnp.where(
        mem_ok_ld, mem[jnp.clip(mem_idx, 0, mem_size - 1)], 0)
    stm_idx = ra
    mem_ok_st = (stm_idx >= 0) & (stm_idx < mem_size)

    # --- new pc ---
    new_pc = jnp.select(
        [op == OP_JMP, op == OP_BR],
        [a, jnp.where(taken, c, nxt)],
        default=nxt)

    # --- new register file (one scatter) ---
    wr_val = jnp.select(
        [op == OP_LDB, op == OP_LDI, op == OP_ALU, op == OP_ADDI,
         op == OP_LEN, op == OP_LDM],
        [ldb_val, b, alu_val, rb + c, input_len, ldm_val],
        default=jnp.int32(0))
    writes_reg = jnp.isin(op, jnp.asarray(
        [OP_LDB, OP_LDI, OP_ALU, OP_ADDI, OP_LEN, OP_LDM]))
    reg_target = jnp.where(writes_reg, jnp.clip(a, 0, N_REGS - 1), N_REGS)
    new_regs = regs.at[reg_target].set(wr_val, mode="drop")

    # --- memory write ---
    do_store = (op == OP_STM) & mem_ok_st
    mem_target = jnp.where(do_store, jnp.clip(stm_idx, 0, mem_size - 1),
                           mem_size)
    new_mem = mem.at[mem_target].set(rb, mode="drop")

    # --- status transitions ---
    crashes = (op == OP_CRASH) | \
              ((op == OP_LDM) & ~mem_ok_ld) | \
              ((op == OP_STM) & ~mem_ok_st) | \
              (pc < 0) | (pc >= ni)
    halts = op == OP_HALT
    new_status = jnp.where(crashes, FUZZ_CRASH,
                           jnp.where(halts, FUZZ_NONE, status))
    new_exit = jnp.where(halts, a, exit_code)

    # --- coverage ---
    is_block = (op == OP_BLOCK) & running
    cur_loc = a & (MAP_SIZE - 1)
    edge_id = jnp.where(is_block, cur_loc ^ prev_loc, -1)
    new_prev = jnp.where(is_block, cur_loc >> 1, prev_loc)

    # lanes that already halted/crashed freeze in place
    def keep(new, old):
        return jnp.where(running, new, old)

    out_state = (keep(new_pc, pc), keep(new_regs, regs),
                 keep(new_mem, mem), keep(new_prev, prev_loc),
                 keep(new_status, status), keep(new_exit, exit_code))
    return out_state, edge_id


def _run_one(instrs, mem_size, max_steps, input_buf, input_len):
    """Execute one lane to completion (or step budget).

    Uses ``while_loop`` rather than a fixed-length scan: under vmap
    the loop runs until every lane halts (or the budget), so a batch
    whose longest path is 25 steps costs 25 iterations, not the full
    hang budget — a ~2x win on crash-hunting workloads.
    """
    state0 = (jnp.int32(0),
              jnp.zeros(N_REGS, dtype=jnp.int32),
              jnp.zeros(mem_size, dtype=jnp.int32),
              jnp.int32(0),
              jnp.int32(FUZZ_RUNNING),
              jnp.int32(0))
    edges0 = jnp.full((max_steps,), -1, dtype=jnp.int32)

    def cond(carry):
        state, _, i = carry
        return (state[4] == FUZZ_RUNNING) & (i < max_steps)

    def body(carry):
        state, edges, i = carry
        new_state, edge = _step(instrs, input_buf, input_len, mem_size,
                                state)
        edges = edges.at[i].set(edge, mode="drop")
        return new_state, edges, i + 1

    final, edges, steps = jax.lax.while_loop(cond, body,
                                             (state0, edges0,
                                              jnp.int32(0)))
    return VMResult(status=final[4], exit_code=final[5], edge_ids=edges,
                    steps=steps)


# --------------------------------------------------------------------
# Batched one-hot engine — the production path
# --------------------------------------------------------------------
#
# ``vmap(_run_one)`` is semantically right but lowers every per-lane
# read (instruction fetch, register read, memory load) to a
# scalar-per-lane gather, which TPUs execute poorly: the whole VM ran
# at ~70ms / 32k-lane batch.  The batched engine below keeps ALL state
# lane-major ([B, ...]) and replaces every dynamic index with a
# one-hot compare-select over the (small, static) indexed axis —
# registers (8), memory (mem_size), instructions (NI), input bytes
# (L).  That is nominally more arithmetic, but it is pure fused
# elementwise/reduction work the VPU streams at full rate: ~8x faster
# end-to-end, bit-identical results (parity-tested against _run_one).

def _onehot_pick(table, idx, axis_len):
    """out[b] = table[b, idx[b]] without a gather: one-hot over the
    last axis (static, small)."""
    lanes = jnp.arange(axis_len, dtype=jnp.int32)[None, :]
    return jnp.sum(jnp.where(lanes == idx[:, None], table, 0), axis=1)


def _step_batched(instrs, bufs_t, lengths, mem_size, state):
    """One VM step for ALL lanes. state = (pc, regs, mem, prev_loc,
    status, exit_code, edges, i, lane_steps); arrays are [B, ...];
    bufs_t is the transposed input [L, B] so byte selects run over
    static rows."""
    pc, regs, mem, prev_loc, status, exit_code, edges, i, lane_steps = state
    ni = instrs.shape[0]
    L = bufs_t.shape[0]
    running = status == FUZZ_RUNNING

    pcc = jnp.clip(pc, 0, ni - 1)
    onehot_pc = pcc[:, None] == jnp.arange(ni, dtype=jnp.int32)[None, :]
    row = jnp.sum(jnp.where(onehot_pc[:, :, None], instrs[None, :, :], 0),
                  axis=1)                                    # [B, 4]
    op, a, b, c = row[:, 0], row[:, 1], row[:, 2], row[:, 3]

    rb_idx = (c >> 3) & (N_REGS - 1)
    alu_sel = c & 7
    cmp_sel = b & 3
    cmp_rb = (b >> 2) & (N_REGS - 1)

    ra = _onehot_pick(regs, jnp.clip(a, 0, N_REGS - 1), N_REGS)
    rb = _onehot_pick(regs, jnp.clip(b, 0, N_REGS - 1), N_REGS)
    ry = _onehot_pick(regs, rb_idx, N_REGS)
    cmp_y = _onehot_pick(regs, cmp_rb, N_REGS)

    # LDB: one-hot over the (transposed) input rows
    ldb_ok = (rb >= 0) & (rb < lengths)
    lsel = jnp.clip(rb, 0, L - 1)
    lidx = jnp.arange(L, dtype=jnp.int32)[:, None]
    ldb_val = jnp.sum(
        jnp.where(lidx == lsel[None, :], bufs_t, 0), axis=0
    ).astype(jnp.int32)
    ldb_val = jnp.where(ldb_ok, ldb_val, 0)

    x, y = rb, ry
    shift = jnp.clip(y, 0, 31)
    alu_val = jnp.select(
        [alu_sel == ALU_ADD, alu_sel == ALU_SUB, alu_sel == ALU_AND,
         alu_sel == ALU_OR, alu_sel == ALU_XOR, alu_sel == ALU_SHL,
         alu_sel == ALU_SHR, alu_sel == ALU_MUL],
        [x + y, x - y, x & y, x | y, x ^ y, x << shift,
         jax.lax.shift_right_logical(x, shift), x * y],
        default=jnp.int32(0))
    taken = jnp.select(
        [cmp_sel == CMP_EQ, cmp_sel == CMP_NE, cmp_sel == CMP_LT,
         cmp_sel == CMP_GE],
        [ra == cmp_y, ra != cmp_y, ra < cmp_y, ra >= cmp_y],
        default=False)

    midx = jnp.arange(mem_size, dtype=jnp.int32)[None, :]
    mem_ok_ld = (rb >= 0) & (rb < mem_size)
    ldm_val = _onehot_pick(mem, jnp.clip(rb, 0, mem_size - 1), mem_size)
    ldm_val = jnp.where(mem_ok_ld, ldm_val, 0)
    mem_ok_st = (ra >= 0) & (ra < mem_size)

    nxt = pc + 1
    new_pc = jnp.select([op == OP_JMP, op == OP_BR],
                        [a, jnp.where(taken, c, nxt)], nxt)
    wr_val = jnp.select(
        [op == OP_LDB, op == OP_LDI, op == OP_ALU, op == OP_ADDI,
         op == OP_LEN, op == OP_LDM],
        [ldb_val, b, alu_val, rb + c, lengths, ldm_val],
        default=jnp.int32(0))
    writes_reg = jnp.isin(op, jnp.asarray(
        [OP_LDB, OP_LDI, OP_ALU, OP_ADDI, OP_LEN, OP_LDM]))
    ridx = jnp.arange(N_REGS, dtype=jnp.int32)[None, :]
    wmask = (writes_reg & running)[:, None] & \
        (ridx == jnp.clip(a, 0, N_REGS - 1)[:, None])
    new_regs = jnp.where(wmask, wr_val[:, None], regs)

    do_store = (op == OP_STM) & mem_ok_st & running
    smask = do_store[:, None] & \
        (midx == jnp.clip(ra, 0, mem_size - 1)[:, None])
    new_mem = jnp.where(smask, rb[:, None], mem)

    crashes = (op == OP_CRASH) | \
              ((op == OP_LDM) & ~mem_ok_ld) | \
              ((op == OP_STM) & ~mem_ok_st) | \
              (pc < 0) | (pc >= ni)
    halts = op == OP_HALT
    new_status = jnp.where(crashes, FUZZ_CRASH,
                           jnp.where(halts, FUZZ_NONE, status))
    new_exit = jnp.where(halts & running, a, exit_code)

    is_block = (op == OP_BLOCK) & running
    cur_loc = a & (MAP_SIZE - 1)
    edge = jnp.where(is_block, cur_loc ^ prev_loc, -1)
    new_prev = jnp.where(is_block, cur_loc >> 1, prev_loc)
    t = edges.shape[1]
    emask = (jnp.arange(t, dtype=jnp.int32)[None, :] == i) & \
        running[:, None]
    new_edges = jnp.where(emask, edge[:, None], edges)

    def keep(new, old):
        return jnp.where(running, new, old)

    return (keep(new_pc, pc),
            jnp.where(running[:, None], new_regs, regs),
            jnp.where(running[:, None], new_mem, mem),
            keep(new_prev, prev_loc),
            keep(new_status, status),
            keep(new_exit, exit_code),
            new_edges, i + 1,
            lane_steps + running.astype(jnp.int32))


@partial(jax.jit, static_argnames=("mem_size", "max_steps"))
def _run_batch_impl(instrs, inputs, lengths, mem_size, max_steps):
    b = inputs.shape[0]
    state0 = (jnp.zeros(b, jnp.int32),
              jnp.zeros((b, N_REGS), jnp.int32),
              jnp.zeros((b, mem_size), jnp.int32),
              jnp.zeros(b, jnp.int32),
              jnp.full(b, FUZZ_RUNNING, jnp.int32),
              jnp.zeros(b, jnp.int32),
              jnp.full((b, max_steps), -1, jnp.int32),
              jnp.int32(0),
              jnp.zeros(b, jnp.int32))
    bufs_t = inputs.T
    lengths = lengths.astype(jnp.int32)

    def cond(s):
        return jnp.any(s[4] == FUZZ_RUNNING) & (s[7] < max_steps)

    def body(s):
        return _step_batched(instrs, bufs_t, lengths, mem_size, s)

    final = jax.lax.while_loop(cond, body, state0)
    return VMResult(status=final[4], exit_code=final[5],
                    edge_ids=final[6], steps=final[8])


def run_batch(program: Program, inputs: jax.Array, lengths: jax.Array
              ) -> VMResult:
    """Execute a uint8[B, L] candidate batch through the program.

    Lanes still RUNNING after ``program.max_steps`` are hangs —
    callers map FUZZ_RUNNING -> FUZZ_HANG, mirroring the reference's
    wait-loop timeout.
    """
    return _run_batch_impl(jnp.asarray(program.instrs), inputs, lengths,
                           program.mem_size, program.max_steps)


def compile_runner(program: Program):
    """Return a jitted ``(inputs, lengths) -> VMResult`` closure with
    the instruction tensor baked in (constant-folded by XLA)."""
    instrs = jnp.asarray(program.instrs)

    @jax.jit
    def runner(inputs, lengths):
        return _run_batch_impl(instrs, inputs, lengths,
                               program.mem_size, program.max_steps)

    return runner
