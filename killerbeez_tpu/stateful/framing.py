"""Sequence framing codec — one buffer, many messages.

A framed sequence is the stateful tier's wire format: the whole
session travels as ONE candidate buffer so every existing surface
(mutators, corpus store, findings files, sync, the device rings)
carries sequences without change, and the device parses the framing
itself inside the jitted session scan.

Layout (``m_max`` is a static per-target constant, StatefulSpec):

    byte 0              message count c
    bytes 1 .. m_max    per-message length bytes l_0 .. l_{m_max-1}
    bytes 1+m_max ..    message payloads, concatenated in order

Parsing is TOTAL — any byte string decodes to a valid sequence, so
havoc-mutated buffers always execute (a fuzzer's codec must never
reject its own mutants):

    * bytes at/past the buffer's logical length read as 0;
    * the count clips into [1, m_max];
    * message k starts where message k-1 ended and its length clips
      to the bytes actually remaining (possibly 0 — an empty message
      is a legal zero-length exec).

``unframe`` (host, numpy-free) and ``parse_frames`` (device, jnp)
implement the SAME clipping rules and are parity-pinned against each
other in tests/test_stateful.py — host-driven and in-scan session
paths must agree on where every message boundary sits.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

#: per-message length field is one byte
MAX_MSG_LEN = 255


def header_len(m_max: int) -> int:
    return 1 + int(m_max)


def frame_messages(msgs: Sequence[bytes], m_max: int) -> bytes:
    """Encode a message list into one framed buffer (strict: callers
    framing seeds must fit the format; the PARSER is the total one)."""
    if not 1 <= len(msgs) <= m_max:
        raise ValueError(
            f"sequence has {len(msgs)} messages, format allows "
            f"1..{m_max}")
    for k, m in enumerate(msgs):
        if len(m) > MAX_MSG_LEN:
            raise ValueError(
                f"message {k} is {len(m)} bytes (> {MAX_MSG_LEN})")
    hdr = bytearray(header_len(m_max))
    hdr[0] = len(msgs)
    for k, m in enumerate(msgs):
        hdr[1 + k] = len(m)
    return bytes(hdr) + b"".join(msgs)


def unframe(buf: bytes, m_max: int) -> List[bytes]:
    """Total host-side parse: ``buf`` (its full length is the logical
    length) -> the list of messages the device would execute."""
    n = len(buf)

    def byte_at(i: int) -> int:
        return buf[i] if 0 <= i < n else 0

    m = min(max(byte_at(0), 1), m_max)
    out: List[bytes] = []
    off = header_len(m_max)
    for k in range(m):
        want = byte_at(1 + k)
        ln = min(want, max(n - off, 0))
        out.append(bytes(buf[off:off + ln]))
        off += ln
    return out


def parse_frames_np(bufs: np.ndarray, lengths: np.ndarray,
                    m_max: int) -> Tuple[np.ndarray, np.ndarray,
                                         np.ndarray]:
    """Batched numpy parse (host replay / tools): uint8[B, L] +
    int32[B] -> (m int32[B], offs int32[B, m_max], mlens
    int32[B, m_max]).  Messages k >= m have offset/length 0."""
    bufs = np.asarray(bufs, dtype=np.uint8)
    lengths = np.asarray(lengths, dtype=np.int64)
    b, L = bufs.shape
    hdr = header_len(m_max)

    def byte_at(i):
        ok = (i < lengths) & (i < L)
        return np.where(ok, bufs[:, min(i, L - 1)], 0).astype(np.int64)

    m = np.clip(byte_at(0), 1, m_max)
    offs = np.zeros((b, m_max), dtype=np.int32)
    mlens = np.zeros((b, m_max), dtype=np.int32)
    off = np.full(b, hdr, dtype=np.int64)
    for k in range(m_max):
        live = k < m
        want = byte_at(1 + k)
        ln = np.minimum(want, np.maximum(lengths - off, 0))
        ln = np.where(live, ln, 0)
        offs[:, k] = np.where(live, off, 0)
        mlens[:, k] = ln
        off = off + ln
    return m.astype(np.int32), offs, mlens


def parse_frames(bufs, lengths, m_max: int):
    """Device-side parse, bit-identical to ``parse_frames_np`` (and
    to ``unframe`` row-wise).  jnp arrays in, jnp arrays out; runs
    inside the jitted session scan."""
    import jax.numpy as jnp

    L = bufs.shape[1]
    hdr = header_len(m_max)
    lengths = lengths.astype(jnp.int32)

    def byte_at(i: int):
        ok = (i < lengths) & (i < L)
        return jnp.where(ok, bufs[:, min(i, L - 1)].astype(jnp.int32),
                         0)

    m = jnp.clip(byte_at(0), 1, m_max)
    offs = []
    mlens = []
    off = jnp.full(lengths.shape, hdr, dtype=jnp.int32)
    for k in range(m_max):
        live = k < m
        want = byte_at(1 + k)
        ln = jnp.minimum(want, jnp.maximum(lengths - off, 0))
        ln = jnp.where(live, ln, 0)
        offs.append(jnp.where(live, off, 0))
        mlens.append(ln)
        off = off + ln
    return (m, jnp.stack(offs, axis=1), jnp.stack(mlens, axis=1))


def reframe(buf: bytes, msgs: Sequence[bytes], m_max: int) -> bytes:
    """Re-encode mutated per-message payloads over an existing framed
    buffer's shape (the multipart round-trip primitive): message
    boundaries come from the NEW payload lengths, count from the
    message list — ``unframe(reframe(...))`` always returns exactly
    ``msgs`` (clipped to the strict-format bounds)."""
    del buf  # shape comes entirely from msgs; kept for call symmetry
    clipped = [bytes(m[:MAX_MSG_LEN]) for m in list(msgs)[:m_max]]
    if not clipped:
        clipped = [b""]
    return frame_messages(clipped, m_max)


def compose_manager_seed(msgs: Sequence[bytes]) -> bytes:
    """Encode a message list as a multipart (manager) mutator seed —
    the mem-array form whose parts become the children's seeds.
    Pair with the manager mutator's ``{"framed": 1}`` option so the
    composites come out as framed sequences."""
    from ..utils.serialization import encode_mem_array
    return encode_mem_array(list(msgs)).encode("ascii")


def main(argv=None) -> int:
    """kb-frame — frame message files/strings into one sequence file.

    Usage: kb-frame -o seq.bin [--m-max 4] msg1.bin msg2.bin ...
           kb-frame -o seq.bin -s 'Lpw' -s 'Q' -s 'X'
    """
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="kb-frame",
        description="frame messages into a stateful-tier sequence")
    p.add_argument("msgs", nargs="*", help="message files, in order")
    p.add_argument("-s", "--string", action="append", default=[],
                   help="literal message string (repeatable; "
                        "appended after file messages)")
    p.add_argument("-o", "--output", required=True)
    p.add_argument("--m-max", type=int, default=4,
                   help="sequence capacity (must match the target's "
                        "StatefulSpec; default 4)")
    args = p.parse_args(argv)
    try:
        parts: List[bytes] = []
        for path in args.msgs:
            with open(path, "rb") as f:
                parts.append(f.read())
        parts.extend(s.encode() for s in args.string)
        framed = frame_messages(parts, args.m_max)
        with open(args.output, "wb") as f:
            f.write(framed)
        print(f"{args.output}: {len(parts)} message(s), "
              f"{len(framed)} bytes")
        return 0
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    import sys
    sys.exit(main())
