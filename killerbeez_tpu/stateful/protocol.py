"""Static protocol-state analysis — which abstract states can a
session ever reach?

A stateful target's protocol state machine is encoded in the program
text: constants ASSIGNED to the state register (``LDI r7, s`` —
transitions) and constants COMPARED against it (``BR eq r7, rK`` —
guards).  Because the dataflow layer already proves blocks dead under
single-shot constant propagation, the multi-message reachability
question reduces to a fixpoint over per-state single-shot analyses:

    reached = {0}                       # sessions start in state 0
    repeat:
      for s in reached:
        analyze the program with the state register INITIALLY s
        (one prepended LDI; jump targets shift by one) — every
        state-constant assignment in a block live under that
        analysis is a reachable transition target
    until no new state appears

The result powers three surfaces:

  * kb-lint's ``state-unreachable`` check — a state the program
    guards on (or assigns) that NO session can reach from the
    initial state is dead protocol surface, almost certainly a bug
    in the target's state machine;
  * the downgrade of single-shot ``dead-block`` warnings to
    ``session-only-block`` info for blocks a session CAN light
    (the whole point of the tier — they are not dead weight);
  * the session half of the deep-edge story: the bench
    ``--stateful`` gate certifies single-shot unreachability via
    ``models.targets_stateful.deep_state_blocks`` + the solver, and
    ``session_reachable_blocks`` here is how a session CAN light
    those same blocks (pinned against deep_state_blocks in
    tests/test_stateful.py).
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

import numpy as np

from ..models.vm import (
    N_REGS, OP_BLOCK, OP_BR, OP_CRASH, OP_HALT, OP_JMP, OP_LDI,
    Program,
)
from . import StatefulSpec

#: fixpoint safety valve: more distinct states than this and the
#: analysis reports what it found so far (never loops unbounded)
MAX_TRACKED_STATES = 64


def with_initial_state(program: Program, state_reg: int,
                       value: int) -> Program:
    """A copy of ``program`` whose state register starts at ``value``:
    one ``LDI`` prepended at pc 0, every JMP/BR target shifted by
    one.  Block ordinals are unchanged, so dead-block sets compare
    directly against the original program's."""
    instrs = np.asarray(program.instrs).copy()
    for pc in range(instrs.shape[0]):
        op = int(instrs[pc, 0])
        if op == OP_JMP:
            instrs[pc, 1] += 1
        elif op == OP_BR:
            instrs[pc, 3] += 1
    pre = np.array([[OP_LDI, state_reg, int(value), 0]],
                   dtype=np.int32)
    return Program(instrs=np.concatenate([pre, instrs]),
                   name=f"{program.name}@s{value}",
                   mem_size=program.mem_size,
                   max_steps=program.max_steps)


def state_assignments(program: Program,
                      state_reg: int) -> List[Tuple[int, int]]:
    """(pc, value) for every ``LDI state_reg, value`` row."""
    instrs = np.asarray(program.instrs)
    return [(pc, int(instrs[pc, 2]))
            for pc in range(instrs.shape[0])
            if int(instrs[pc, 0]) == OP_LDI
            and int(instrs[pc, 1]) == state_reg]


def state_compares(program: Program, state_reg: int) -> Set[int]:
    """Constants the program compares the state register against —
    guard states.  Resolves the non-state operand by scanning back
    through the straight-line run before the branch for its LDI
    (the idiom every handler uses: ``ldi rK, s; br eq r7, rK``)."""
    instrs = np.asarray(program.instrs)
    ni = instrs.shape[0]

    def const_of(reg: int, from_pc: int):
        for pc in range(from_pc - 1, -1, -1):
            op, a, b, c = (int(v) for v in instrs[pc])
            if op in (OP_JMP, OP_BR, OP_HALT, OP_CRASH, OP_BLOCK):
                return None          # left the straight-line run
            if op == OP_LDI and a == reg:
                return b
            if op in (2, 4, 5, 9, 10) and a == reg:
                return None          # reg rewritten non-constantly
        return None

    out: Set[int] = set()
    for pc in range(ni):
        if int(instrs[pc, 0]) != OP_BR:
            continue
        ra = int(instrs[pc, 1])
        rb = (int(instrs[pc, 2]) >> 2) & (N_REGS - 1)
        if ra == state_reg:
            c = const_of(rb, pc)
            if c is not None:
                out.add(c)
        elif rb == state_reg:
            c = const_of(ra, pc)
            if c is not None:
                out.add(c)
    return out


def _block_of_pc(program: Program, pc: int) -> int:
    """Ordinal of the coverage block containing ``pc`` (-1 = the
    entry region before the first BLOCK)."""
    instrs = np.asarray(program.instrs)
    block = -1
    for p in range(min(pc, instrs.shape[0] - 1) + 1):
        if int(instrs[p, 0]) == OP_BLOCK:
            block += 1
    return block


def reachable_states(program: Program, spec: StatefulSpec
                     ) -> Tuple[Set[int], Dict[int, Set[int]]]:
    """The fixpoint: (states reachable from 0 across messages,
    {state: blocks live when a message starts in it})."""
    from ..analysis import analyze_dataflow, build_cfg
    assigns = state_assignments(program, spec.state_reg)
    reached: Set[int] = {0}
    live_by_state: Dict[int, Set[int]] = {}
    frontier = [0]
    while frontier and len(reached) <= MAX_TRACKED_STATES:
        s = frontier.pop()
        ps = with_initial_state(program, spec.state_reg, s)
        cfg = build_cfg(ps)
        df = analyze_dataflow(ps)
        live = set(cfg.reachable) - set(df.dead_blocks)
        live_by_state[s] = live
        for pc, v in assigns:
            blk = _block_of_pc(program, pc)
            if (blk == -1 or blk in live) and v not in reached:
                reached.add(v)
                frontier.append(v)
    return reached, live_by_state


def session_reachable_blocks(program: Program,
                             spec: StatefulSpec) -> Set[int]:
    """Blocks some session (any reachable state at message entry)
    can light — the union kb-lint's dead-block downgrade consumes
    (lint_program computes it inline from reachable_states to share
    one fixpoint run; this is the standalone spelling)."""
    _, live = reachable_states(program, spec)
    out: Set[int] = set()
    for blocks in live.values():
        out |= blocks
    return out


def declared_states(program: Program, spec: StatefulSpec) -> Set[int]:
    """Every state constant the program mentions (assignments and
    guards) — the vocabulary the reachability check audits."""
    return ({v for _, v in state_assignments(program, spec.state_reg)}
            | state_compares(program, spec.state_reg))


def unreachable_states(program: Program, spec: StatefulSpec,
                       _reached: Set[int] = None) -> List[int]:
    """Declared states no session reaches from the initial state —
    kb-lint's ``state-unreachable`` payload (initial state 0 is
    always reachable; negative guard constants are sentinels, not
    states, and are ignored).  ``_reached`` lets lint_program reuse
    its fixpoint result instead of re-running it."""
    if _reached is None:
        _reached, _ = reachable_states(program, spec)
    return sorted(v for v in declared_states(program, spec)
                  if v >= 0 and v not in _reached)
