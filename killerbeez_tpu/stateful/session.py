"""Device-resident session execution — message k runs from the
machine state message k-1 left behind.

The persistent-server model: between messages the lane's pc re-enters
the program top (the dispatch loop of a network daemon), REGISTERS
and SCRATCH MEMORY persist (where stateful targets keep their
protocol state and session data), and the coverage chain (prev block
/ prev edge-table index) resets so every message is an independent
walk of the program's STATIC edge universe — inter-message edges
would otherwise fall outside the enumerable (prev, cur) table and
vanish into the overflow column.  The session's path hash keeps
folding across messages (order-aware session identity).

Verdict semantics per session:

  * a message that CRASHES ends the session with FUZZ_CRASH — later
    messages do not execute (frozen lanes, exactly like the batched
    engine's halted-lane freeze);
  * a message still running after ``max_steps`` is a hang: the
    session reports FUZZ_RUNNING and callers map it to FUZZ_HANG,
    the same contract as ``vm.run_batch``;
  * otherwise the session completes FUZZ_NONE with the LAST
    message's exit code.

State x edge attribution: each message's edge hit counts are added
both to the session total (the classic AFL map dimension) and to the
``se_counts[lane, s, :]`` row where ``s`` is the abstract protocol
state ENTERING the message (``state_reg`` clipped to ``n_states``,
read after the previous message) — PTrix-style state-sensitive
feedback with the state machine's own notion of position.

Two executors, parity-pinned against each other:

  * ``run_session_batch`` — the in-scan path: ONE jitted program
    scans the messages (scan-within-the-scan when the generation
    loop drives it);
  * ``host_reference_session_batch`` — the host-driven per-message
    reference loop: framing parsed on host, one device dispatch per
    message, machine state round-tripping through numpy.  This is
    the semantic anchor the acceptance gate pins the device path to.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import FUZZ_CRASH, FUZZ_NONE, FUZZ_RUNNING
from ..models.vm import N_REGS, _step_batched
from . import StatefulSpec
from .framing import parse_frames, parse_frames_np


class SessionResult(NamedTuple):
    """Per-lane outcome of a batched session execution.  ``status``
    keeps the engine contract: FUZZ_RUNNING = the session hung
    (callers map to FUZZ_HANG).  ``counts`` is the session-total
    static-edge record (the classic map dimension); ``se_counts`` the
    state x edge record (uint8[B, n_states, E+1], wrapping like every
    AFL count)."""
    status: jax.Array      # int32[B]
    exit_code: jax.Array   # int32[B]
    counts: jax.Array      # uint8[B, E+1] session-total edge counts
    steps: jax.Array       # int32[B] total steps across messages
    path_hash: jax.Array   # uint32[B] folded across messages
    msgs: jax.Array        # int32[B] messages actually executed
    state_final: jax.Array  # int32[B] abstract state after the last msg
    se_counts: jax.Array   # uint8[B, S, E+1] state x edge counts


def _gather_message(bufs, offs):
    """msg[b, i] = bufs[b, offs[b] + i] (clipped) — the per-message
    window of the framed buffer.  A gather, not a one-hot matmul:
    this runs once per MESSAGE, not once per VM step, so the
    engine's gather-avoidance rationale does not apply."""
    L = bufs.shape[1]
    idx = offs[:, None] + jnp.arange(L, dtype=jnp.int32)[None, :]
    return jnp.take_along_axis(bufs, jnp.clip(idx, 0, L - 1), axis=1)


def _exec_message(instrs, edge_table, msg_bufs, mlens, regs, mem,
                  path_hash, live, mem_size, max_steps, n_edges):
    """One message for all lanes, from carried machine state: pc and
    the coverage chain re-enter at zero, ``live=False`` lanes freeze
    (status FUZZ_NONE -> every _step_batched update masks off).
    Returns (status, exit_code, msg_counts, regs', mem', path_hash',
    lane_steps)."""
    b = msg_bufs.shape[0]
    state0 = (jnp.zeros(b, jnp.int32),                    # pc
              regs, mem,
              jnp.zeros(b, jnp.int32),                    # prev_loc
              jnp.where(live, FUZZ_RUNNING, FUZZ_NONE
                        ).astype(jnp.int32),              # status
              jnp.zeros(b, jnp.int32),                    # exit_code
              jnp.zeros(b, jnp.int32),                    # prev_idx
              jnp.zeros((b, n_edges + 1), jnp.uint8),     # counts
              path_hash,
              jnp.zeros((b, 0), jnp.int32),               # edges (off)
              jnp.int32(0),
              jnp.zeros(b, jnp.int32))                    # lane_steps
    bufs_t = msg_bufs.T
    mlens = mlens.astype(jnp.int32)

    def cond(s):
        return jnp.any(s[4] == FUZZ_RUNNING) & (s[10] < max_steps)

    def body(s):
        return _step_batched(instrs, edge_table, bufs_t, mlens,
                             mem_size, False, s)

    f = jax.lax.while_loop(cond, body, state0)
    return f[4], f[5], f[7], f[1], f[2], f[8], f[11]


@partial(jax.jit, static_argnames=("mem_size", "max_steps", "n_edges",
                                   "m_max", "n_states", "state_reg"))
def _run_session_impl(instrs, edge_table, bufs, lengths, mem_size,
                      max_steps, n_edges, m_max, n_states, state_reg):
    """The in-scan session executor: parse framing on device, then
    scan the (static) m_max message slots with the machine state in
    the carry.  Inactive slots (k >= count, or the session already
    crashed/hung) execute as frozen lanes — zero counts, zero
    steps — so the scan is shape-static and branch-free."""
    b, L = bufs.shape
    bufs = bufs.astype(jnp.uint8)
    m, offs, mlens = parse_frames(bufs, lengths, m_max)

    def one_message(carry, xs):
        (regs, mem, ph, crashed, hung, exit_code, counts, se, steps,
         state_abs, msgs_done) = carry
        k, off_k, len_k = xs
        live = (k < m) & ~crashed & ~hung
        msg = _gather_message(bufs, off_k)
        st, ec, mc, regs2, mem2, ph2, lane_steps = _exec_message(
            instrs, edge_table, msg, len_k, regs, mem, ph, live,
            mem_size, max_steps, n_edges)
        crashed = crashed | (live & (st == FUZZ_CRASH))
        hung = hung | (live & (st == FUZZ_RUNNING))
        completed = live & (st == FUZZ_NONE)
        exit_code = jnp.where(completed, ec, exit_code)
        counts = counts + mc
        # state x edge: attribute this message's counts to the state
        # ENTERING it (one-hot over the small state axis — no gather)
        onehot = (jnp.arange(n_states, dtype=jnp.int32)[None, :]
                  == state_abs[:, None])
        se = se + (onehot[:, :, None].astype(jnp.uint8)
                   * mc[:, None, :])
        steps = steps + lane_steps
        regs = jnp.where(live[:, None], regs2, regs)
        mem = jnp.where(live[:, None], mem2, mem)
        ph = jnp.where(live, ph2, ph)
        state_abs = jnp.where(
            live, jnp.clip(regs[:, state_reg], 0, n_states - 1),
            state_abs)
        msgs_done = msgs_done + live.astype(jnp.int32)
        return (regs, mem, ph, crashed, hung, exit_code, counts, se,
                steps, state_abs, msgs_done), None

    carry0 = (jnp.zeros((b, N_REGS), jnp.int32),
              jnp.zeros((b, mem_size), jnp.int32),
              jnp.zeros(b, jnp.uint32),
              jnp.zeros(b, bool), jnp.zeros(b, bool),
              jnp.zeros(b, jnp.int32),
              jnp.zeros((b, n_edges + 1), jnp.uint8),
              jnp.zeros((b, n_states, n_edges + 1), jnp.uint8),
              jnp.zeros(b, jnp.int32),
              jnp.zeros(b, jnp.int32),
              jnp.zeros(b, jnp.int32))
    xs = (jnp.arange(m_max, dtype=jnp.int32), offs.T, mlens.T)
    carry, _ = jax.lax.scan(one_message, carry0, xs)
    (regs, mem, ph, crashed, hung, exit_code, counts, se, steps,
     state_abs, msgs_done) = carry
    status = jnp.where(crashed, FUZZ_CRASH,
                       jnp.where(hung, FUZZ_RUNNING, FUZZ_NONE)
                       ).astype(jnp.int32)
    return SessionResult(status=status, exit_code=exit_code,
                         counts=counts, steps=steps, path_hash=ph,
                         msgs=msgs_done, state_final=state_abs,
                         se_counts=se)


def run_session_batch(program, inputs, lengths,
                      spec: StatefulSpec) -> SessionResult:
    """Execute a uint8[B, L] framed-sequence batch through
    ``program`` as sessions.  Pure (no virgin maps touched) — triage
    layers on top exactly like ``vm.run_batch``."""
    return _run_session_impl(
        jnp.asarray(program.instrs), jnp.asarray(program.edge_table),
        jnp.asarray(inputs, dtype=jnp.uint8),
        jnp.asarray(lengths, dtype=jnp.int32),
        program.mem_size, program.max_steps, program.n_edges,
        spec.m_max, spec.n_states, spec.state_reg)


# --------------------------------------------------------------------
# Host-driven per-message reference loop (the parity anchor)
# --------------------------------------------------------------------

@partial(jax.jit, static_argnames=("mem_size", "max_steps",
                                   "n_edges"))
def _host_msg_step(instrs, edge_table, msg_bufs, mlens, regs, mem,
                   path_hash, live, mem_size, max_steps, n_edges):
    """One message as ONE device dispatch (the host loop's unit)."""
    return _exec_message(instrs, edge_table, msg_bufs, mlens, regs,
                         mem, path_hash, live, mem_size, max_steps,
                         n_edges)


def host_reference_session_batch(program, inputs, lengths,
                                 spec: StatefulSpec) -> SessionResult:
    """The reference semantics ``run_session_batch`` is pinned to:
    framing parsed on HOST (``parse_frames_np``), one device dispatch
    per message, machine state round-tripping through numpy between
    messages.  Bit-identical results (tests/test_stateful.py)."""
    inputs = np.asarray(inputs, dtype=np.uint8)
    lengths = np.asarray(lengths, dtype=np.int64)
    b, L = inputs.shape
    S = spec.n_states
    E1 = program.n_edges + 1
    m, offs, mlens = parse_frames_np(inputs, lengths, spec.m_max)

    regs = np.zeros((b, N_REGS), np.int32)
    mem = np.zeros((b, program.mem_size), np.int32)
    ph = np.zeros(b, np.uint32)
    crashed = np.zeros(b, bool)
    hung = np.zeros(b, bool)
    exit_code = np.zeros(b, np.int32)
    counts = np.zeros((b, E1), np.uint8)
    se = np.zeros((b, S, E1), np.uint8)
    steps = np.zeros(b, np.int32)
    state_abs = np.zeros(b, np.int32)
    msgs_done = np.zeros(b, np.int32)

    instrs = jnp.asarray(program.instrs)
    edge_table = jnp.asarray(program.edge_table)
    idx_cols = np.arange(L, dtype=np.int64)
    for k in range(spec.m_max):
        live = (k < m) & ~crashed & ~hung
        if not live.any():
            break
        idx = np.clip(offs[:, k, None].astype(np.int64)
                      + idx_cols[None, :], 0, L - 1)
        msg = np.take_along_axis(inputs, idx, axis=1)
        st, ec, mc, regs2, mem2, ph2, lane_steps = (
            np.asarray(a) for a in _host_msg_step(
                instrs, edge_table, jnp.asarray(msg),
                jnp.asarray(mlens[:, k]), jnp.asarray(regs),
                jnp.asarray(mem), jnp.asarray(ph), jnp.asarray(live),
                program.mem_size, program.max_steps, program.n_edges))
        crashed |= live & (st == FUZZ_CRASH)
        hung |= live & (st == FUZZ_RUNNING)
        completed = live & (st == FUZZ_NONE)
        exit_code = np.where(completed, ec, exit_code)
        counts = counts + mc          # uint8 wrap, like the engine
        onehot = (np.arange(S)[None, :] == state_abs[:, None])
        se = se + (onehot[:, :, None].astype(np.uint8)
                   * mc[:, None, :])
        steps = steps + lane_steps
        regs = np.where(live[:, None], regs2, regs)
        mem = np.where(live[:, None], mem2, mem)
        ph = np.where(live, ph2, ph)
        state_abs = np.where(
            live, np.clip(regs[:, spec.state_reg], 0, S - 1),
            state_abs)
        msgs_done = msgs_done + live.astype(np.int32)
    status = np.where(crashed, FUZZ_CRASH,
                      np.where(hung, FUZZ_RUNNING, FUZZ_NONE)
                      ).astype(np.int32)
    return SessionResult(status=status, exit_code=exit_code,
                         counts=counts, steps=steps, path_hash=ph,
                         msgs=msgs_done, state_final=state_abs,
                         se_counts=se)


# --------------------------------------------------------------------
# Signatures (corpus sidecars / showmap / kb-corpus)
# --------------------------------------------------------------------

def state_edge_pairs(se_row: np.ndarray,
                     edge_slot: np.ndarray) -> list:
    """One lane's state x edge signature as sorted ``[state, slot]``
    pairs (AFL map slots, the externally meaningful edge id; the
    overflow column is dropped).  The sidecar / picker / kb-corpus
    wire format."""
    se_row = np.asarray(se_row)
    slots = np.asarray(edge_slot)
    pairs = set()
    ss, ee = np.nonzero(se_row[:, :-1])
    for s, e in zip(ss, ee):
        pairs.add((int(s), int(slots[e])))
    return [[s, e] for s, e in sorted(pairs)]


def run_single_session(program, buf: bytes,
                       spec: StatefulSpec) -> Tuple[SessionResult,
                                                    list]:
    """One framed input as a 1-lane session (tools / the admission
    signer).  Returns (its SessionResult row, its state x edge
    signature pairs)."""
    L = max(((len(buf) + 7) // 8) * 8, 8)
    arr = np.zeros((1, L), dtype=np.uint8)
    if buf:
        arr[0, :len(buf)] = np.frombuffer(buf, dtype=np.uint8)
    res = run_session_batch(program, arr,
                            np.array([len(buf)], np.int32), spec)
    res = SessionResult(*(np.asarray(a) for a in res))
    return res, state_edge_pairs(res.se_counts[0], program.edge_slot)
