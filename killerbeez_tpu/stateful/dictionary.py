"""Message-scoped dictionaries — per-message token groups for the
multipart mutator.

``analysis.extract_dictionary`` gives a sequence target ONE flat
token pool — and, worse, an INCOMPLETE one: deep-handler constants
(the query trigger byte, post-handshake magics) sit in blocks that
are dead under single-shot constant propagation, so the single-shot
extraction never even sees them.  Here message k of a seed sequence
gets the dictionary of the program analyzed with the state register
initially in k's ENTERING protocol state
(``protocol.with_initial_state``): gated-off handlers contribute
nothing, deep handlers surface exactly where they apply:

    groups = extract_dictionary_groups(program, spec, seed_msgs)
    # session_auth: ["L","Q","X","p","pw","w"]   <- START message
    #               ["L","Q","X","Z"]            <- AUTHED messages

``manager_options_for_target`` packages that into ready-to-use
multipart (manager) mutator options — one ``dictionary`` child per
message with its scoped token group, framed composition on — the
turnkey structure-aware mutation config for a stateful built-in.
"""

from __future__ import annotations

import json
from typing import List, Optional, Sequence

from . import StatefulSpec
from .framing import frame_messages
from .protocol import with_initial_state


def entering_states(program, spec: StatefulSpec,
                    msgs: Sequence[bytes]) -> List[int]:
    """The abstract protocol state entering each message of a CONCRETE
    seed sequence (state 0 for message 0, then each prefix's final
    state — one tiny session execution per prefix)."""
    from .session import run_single_session
    states = [0]
    for k in range(1, len(msgs)):
        framed = frame_messages(list(msgs[:k]), spec.m_max)
        res, _ = run_single_session(program, framed, spec)
        states.append(int(res.state_final[0]))
    return states


def extract_dictionary_groups(program, spec: StatefulSpec,
                              msgs: Sequence[bytes],
                              max_tokens: int = 64
                              ) -> List[List[bytes]]:
    """Per-message token groups for ``msgs`` (see module docstring).

    Message k's group is the dictionary of the program ANALYZED WITH
    THE STATE REGISTER INITIALLY in k's entering state
    (``with_initial_state``): handlers the state machine gates off
    are dead under that analysis and contribute nothing, while
    deep-handler tokens — invisible to the single-shot extraction
    precisely because their blocks are single-shot-dead — surface in
    the states that can reach them.  The login password lands in the
    START group, the query trigger byte in the AUTHED group."""
    from ..analysis import extract_dictionary
    states = entering_states(program, spec, msgs)
    cache = {}
    groups: List[List[bytes]] = []
    for s in states:
        if s not in cache:
            cache[s] = extract_dictionary(
                with_initial_state(program, spec.state_reg, s),
                max_tokens=max_tokens)
        groups.append(list(cache[s]))
    return groups


def manager_options_for_target(target_name: str,
                               msgs: Optional[Sequence[bytes]] = None,
                               spec: Optional[StatefulSpec] = None
                               ) -> str:
    """Ready-made multipart (manager) mutator options JSON for a
    stateful built-in: one ``dictionary`` child per seed message
    with its message-scoped token group, framed composition on.
    Pair with ``stateful.framing.compose_manager_seed`` for the seed:

        opts = manager_options_for_target("session_auth")
        seed = compose_manager_seed(seed_sequence("session_auth"))
        mut = mutator_factory("manager", opts, seed)
    """
    from ..models.targets import get_target
    from ..models.targets_stateful import (
        get_stateful_spec, seed_sequence,
    )
    program = get_target(target_name)
    spec = spec or get_stateful_spec(target_name)
    if spec is None:
        raise ValueError(
            f"{target_name!r} has no registered StatefulSpec")
    msgs = list(msgs) if msgs is not None \
        else seed_sequence(target_name)
    groups = extract_dictionary_groups(program, spec, msgs)
    return json.dumps({
        "mutators": ["dictionary"] * len(msgs),
        # tokens as int lists: json-safe for arbitrary bytes (the
        # dictionary mutator's list-of-ints form)
        "mutator_options": [
            {"tokens": [list(t) for t in g]} for g in groups],
        "framed": 1,
        "m_max": spec.m_max,
    })
