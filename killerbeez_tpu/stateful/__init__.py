"""Stateful protocol fuzzing tier — device-resident session sequences.

The reference framework's driver layer exists so network/TCP
state-machine targets can be fuzzed message-by-message (network
drivers feed one mutated packet at a time into a live process); the
TPU tier treated every input as one stateless buffer until this
package.  Here an input is a *framed sequence* of messages
(``framing.py``), the batched KBVM executes message k from the
machine state message k-1 left behind — registers and scratch memory
checkpointed per lane on device, pc re-entering at the program top
like a persistent-mode server's dispatch loop (``session.py``) — and
novelty gains a second dimension: a state x edge virgin map keyed by
(abstract protocol state entering the message, static edge), the
PTrix move of feeding the fuzzer state-sensitive coverage beyond the
plain edge map (``coverage.py``).

The abstract protocol state is the value of a designated KBVM
register (``state_reg``, r7 by convention) clipped to ``n_states``
buckets: stateful targets keep their protocol state there across
messages precisely because registers persist.  Message boundaries
reset pc, coverage chain (prev block) and status; registers, memory
and the path hash carry over — so the static edge universe stays
exact (every message is an independent walk of the program text) and
the interesting cross-message signal lands in the state x edge map,
where it belongs.

Wired end to end: jit_harness ``{"stateful": 1}`` options, the
``--stateful`` CLI flag, the single-chip and mesh generation scans
(the sequence loop is a scan-within-the-scan), multipart/framed
structure-aware mutation, per-entry state-coverage sidecars, and the
stateful built-in target families in ``models/targets_stateful.py``.
See docs/STATEFUL.md for the sequence format, coverage semantics and
stand-down rules.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class StatefulSpec:
    """Session-tier configuration for one target.

    ``m_max``     maximum messages per sequence (static scan length);
    ``n_states``  abstract-state buckets (state values clip into
                  [0, n_states));
    ``state_reg`` the KBVM register holding the protocol state
                  (read AFTER each message; r7 by convention).
    """
    m_max: int = 4
    n_states: int = 16
    state_reg: int = 7

    def __post_init__(self):
        if not (1 <= self.m_max <= 32):
            raise ValueError("m_max must be in [1, 32]")
        if not (2 <= self.n_states <= 256):
            raise ValueError("n_states must be in [2, 256]")
        if not (0 <= self.state_reg < 8):
            raise ValueError("state_reg must be r0..r7")


from .framing import (  # noqa: E402
    frame_messages, parse_frames, parse_frames_np, unframe,
)
from .session import SessionResult, run_session_batch  # noqa: E402

__all__ = [
    "StatefulSpec", "frame_messages", "unframe", "parse_frames",
    "parse_frames_np", "SessionResult", "run_session_batch",
]
