"""State x edge novelty — the session tier's second virgin map.

The classic AFL map answers "did this input light an edge (bucket) we
have never seen"; protocol targets need "did it light an edge FROM a
protocol state we have never seen it from" — PTrix's observation that
path/state-sensitive feedback is what unlocks state machines.  The
map here is tiny and exact: ``n_states x (E+1)`` uint8 hit counts
over the program's static edge universe (edge-index space, not AFL
slot space — the state dimension never aliases through slot
collisions), classified into AFL count buckets and AND-folded into a
``virgin_state`` byte map with exactly ``has_new_bits`` semantics.

Two triage modes mirroring the classic ones (jit_harness novelty):

  * ``state_triage_exact``    — lanes judged sequentially (lane i
    sees the virgin map after lanes < i): the parity mode;
  * ``state_triage``          — throughput mode: all lanes vs the
    incoming map, in-batch dedup by classified-map hash, one
    OR-folded virgin clear.  Over-reports within a batch the same
    benign way the classic throughput path does.

Both return AFL ret codes per lane (2 = a never-seen (state, edge)
pair, 1 = only a new hit-count bucket, 0 = nothing) and the updated
virgin map.  The combined novelty verdict the session tier feeds
triage/admission is ``max(classic_ret, state_ret)`` — the state
dimension ADDS findings, it never suppresses classic ones.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.coverage import classify_counts, has_new_bits
from ..ops.sparse_coverage import first_occurrence, stream_hash


def state_map_size(n_states: int, n_edges: int) -> int:
    """Bytes in a program's state x edge virgin map."""
    return int(n_states) * (int(n_edges) + 1)


def fresh_virgin_state(n_states: int, n_edges: int) -> jnp.ndarray:
    return jnp.full((state_map_size(n_states, n_edges),), 0xFF,
                    dtype=jnp.uint8)


def _classify_flat(se_counts) -> jnp.ndarray:
    """uint8[B, S, E+1] -> classified uint8[B, S*(E+1)]."""
    b = se_counts.shape[0]
    return classify_counts(se_counts.reshape(b, -1))


def state_triage(virgin_state, se_counts,
                 ) -> Tuple[jax.Array, jax.Array]:
    """Throughput-mode state novelty.  Args: virgin uint8[S*(E+1)],
    se_counts uint8[B, S, E+1].  Returns (rets int32[B], virgin')."""
    cls = _classify_flat(se_counts)
    v = virgin_state[None, :]
    new_count = jnp.any((cls & v) != 0, axis=1)
    new_tuple = jnp.any((cls != 0) & (v == 0xFF), axis=1)
    rets = jnp.where(new_tuple, 2, jnp.where(new_count, 1, 0)
                     ).astype(jnp.int32)
    hashes = stream_hash(cls.astype(jnp.uint32))
    first = first_occurrence(hashes, jnp.ones(hashes.shape, bool))
    rets = jnp.where(first, rets, 0)
    seen = jax.lax.reduce(
        jnp.where((rets > 0)[:, None], cls, jnp.uint8(0)),
        jnp.uint8(0), jax.lax.bitwise_or, dimensions=(0,))
    return rets, virgin_state & ~seen


def state_triage_exact(virgin_state, se_counts,
                       ) -> Tuple[jax.Array, jax.Array]:
    """Sequential-parity state novelty: lane i is judged against the
    virgin map after lanes < i — bit-for-bit what a single-exec loop
    would report (the stateful parity gates run in this mode, like
    the classic ``exact`` novelty)."""
    cls = _classify_flat(se_counts)

    def step(v, c):
        ret, v2 = has_new_bits(v, c)
        return v2, ret

    virgin2, rets = jax.lax.scan(step, virgin_state, cls)
    return rets, virgin2


def np_state_triage_exact(virgin_state: np.ndarray,
                          se_counts: np.ndarray
                          ) -> Tuple[np.ndarray, np.ndarray]:
    """Pure-numpy witness of ``state_triage_exact`` (host replay for
    the parity suites — the same role np_select_slot plays for the
    generation scan's slot policy)."""
    from ..ops.coverage import COUNT_CLASS_LOOKUP
    v = np.asarray(virgin_state).copy()
    se = np.asarray(se_counts)
    b = se.shape[0]
    cls = COUNT_CLASS_LOOKUP[se.reshape(b, -1)]
    rets = np.zeros(b, np.int32)
    for i in range(b):
        t = cls[i]
        new_tuple = bool(((t != 0) & (v == 0xFF)).any())
        new_count = bool((t & v).any())
        rets[i] = 2 if new_tuple else (1 if new_count else 0)
        v &= ~t
    return rets, v


def state_coverage_stats(virgin_state: np.ndarray,
                         n_states: int) -> Tuple[int, int]:
    """(touched state x edge pairs, distinct states seen) from a
    virgin map — the telemetry gauges' source."""
    v = np.asarray(virgin_state).reshape(n_states, -1)
    touched = v != 0xFF
    return int(touched.sum()), int(touched.any(axis=1).sum())
