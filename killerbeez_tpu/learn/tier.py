"""LearnTier — the campaign-side owner of learned mutation shaping.

One instance rides a Fuzzer (fuzzer/loop.py, ``--learn``): it
collects labels from the admission stream (positives) and the
rejected-lane stream (negatives), trains the byte-saliency model
(learn/model.py) ON THE DEVICE between fuzzing dispatches, and
serves the result through two mask paths:

  * ``scan_params()`` — the raw weights, handed to the device
    generation scans (-G single-chip and --mesh) which run inference
    per generation on the selected seed-ring slot with zero host
    involvement;
  * ``focus_positions_for()`` — host-loop mode: the quantized mask
    of the freshly rotated seed installed via
    ``Mutator.set_focus_mask`` (the ``learned`` mask source beside
    the crack stage's static ``edge_dep_mask``).

Stand-down / parity doctrine: until the first training round
(``version`` 0) the model's output layer is zero, masks quantize to
all-ones, and the shaped scans are bit-identical to the unshaped
ones (tests/test_learn.py pins it); host-loop masks are only
installed once the model has trained AND the mask actually excludes
something.  State (weights + version + label counters) persists
through the PR 8 unified checkpoint epoch so ``--resume`` restores
the model; label samples rebuild from provenance sidecars
(dataset.samples_from_entries) — explicit reject negatives restart
empty, which only slows re-sharpening, never corrupts it.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.logging import WARNING_MSG
from . import dataset, model


class LearnTier:
    """Labels in, trained masks out (see module doc)."""

    def __init__(self, train_interval_s: float = 5.0,
                 min_labels: int = 64, steps_per_round: int = 8,
                 batch: int = 256, lr: float = model.LEARN_RATE,
                 sample_cap: int = 8192, max_len: int = 4096,
                 time_fn=time.time):
        self.params = model.init_params()
        #: 0 = untrained (all-ones masks, the parity regime); each
        #: completed training round increments it
        self.version = 0
        self.labels = dataset.LabelBuffer(cap=sample_cap,
                                          max_len=max_len)
        self.train_interval_s = float(train_interval_s)
        self.min_labels = int(min_labels)
        self.steps_per_round = int(steps_per_round)
        self.batch = int(batch)
        self.lr = float(lr)
        self._time = time_fn
        self._last_train = 0.0
        self._labels_at_train = 0
        self.train_steps = 0
        self.masks_applied = 0
        #: bounded budget of reject negatives per admission-free
        #: stretch (rejected bucket-only lanes can vastly outnumber
        #: admissions — unbounded they drown the positives)
        self._reject_budget = 64
        #: positive-label informativeness cap: a stacked-havoc child
        #: whose diff rewrites more than this many positions carries
        #: ~no positional signal (a block clone smears the bitmap
        #: over half the buffer) — its provenance is still recorded,
        #: but the learn tier trains only on small, attributable
        #: diffs, the "Not all bytes are equal" ground-truth regime
        self.informative_diff = 24

    # -- label intake ----------------------------------------------------

    def note_admission(self, parent_key: str, parent: bytes,
                       child: bytes, mutator: str,
                       stage: Optional[str] = None
                       ) -> Optional[Dict[str, Any]]:
        """One admitted edge-novel child: label the parent positions
        its mutation touched as positive, sample untouched positions
        as background negatives, and return the provenance record
        the admission writes into the child's sidecar.  Never raises
        — learning is observability-grade, a label failure must not
        stop triage."""
        try:
            prov = dataset.make_provenance(parent, child, mutator,
                                           stage)
            bm = dataset.diff_bitmap(parent, child,
                                     self.labels.max_len)
            pos = np.flatnonzero(bm)
            if pos.size and pos.size <= self.informative_diff:
                # small diff: the mutated positions are attributable
                self.labels.add(parent_key, parent, pos, 1)
                pm = np.zeros(min(len(parent), self.labels.max_len),
                              np.uint8)
                inb = pos[pos < pm.size]
                pm[inb] = 1
                self.labels.add_background(parent_key, parent, pm)
            self._reject_budget = 64
            return prov
        except Exception as e:
            WARNING_MSG("learn: admission label failed: %s", e)
            return None

    def note_reject(self, parent_key: str, parent: bytes,
                    child: bytes) -> None:
        """One interesting-but-not-admitted lane (bucket-only new
        path): its mutated positions are explicit negatives — the
        admission ledger's rejects, budget-capped between
        admissions."""
        if self._reject_budget <= 0:
            return
        try:
            bm = dataset.diff_bitmap(parent, child,
                                     self.labels.max_len)
            pos = np.flatnonzero(bm)
            if pos.size and pos.size <= self.informative_diff:
                self._reject_budget -= 1
                self.labels.add(parent_key, parent, pos, 0, cap=8)
        except Exception as e:
            WARNING_MSG("learn: reject label failed: %s", e)

    def bootstrap(self, entries, parent_bytes) -> int:
        """Rebuild labels from persisted provenance sidecars
        (--resume / a pre-populated corpus)."""
        try:
            return dataset.samples_from_entries(
                self.labels, entries, parent_bytes,
                informative_diff=self.informative_diff)
        except Exception as e:
            WARNING_MSG("learn: bootstrap failed: %s", e)
            return 0

    # -- training --------------------------------------------------------

    def ready_to_train(self) -> bool:
        if len(self.labels) < self.min_labels or \
                self.labels.positives == 0:
            return False
        if self._time() - self._last_train < self.train_interval_s:
            return False
        # retrain only when new labels arrived since the last round
        # — judged on the MONOTONE intake counter, not the buffer
        # length (which pins at cap once the FIFO saturates and
        # would stall training for the rest of the campaign)
        return self.labels.total_added != self._labels_at_train \
            or self.version == 0

    def train_round(self) -> Optional[float]:
        """``steps_per_round`` SGD steps on fresh sample batches (on
        the accelerator — the model shares the chip with the
        fuzzer).  Returns the final batch loss, or None if there was
        nothing to train on."""
        last = None
        for _ in range(self.steps_per_round):
            b = self.labels.make_batch(self.batch)
            if b is None:
                return last
            bufs, lens, poss, ys = b
            X = model.batch_features(bufs, lens, poss)
            # class rebalance: admissions are rare — upweight
            # positives to parity with the negative mass
            npos = max(float(ys.sum()), 1.0)
            nneg = max(float(len(ys) - ys.sum()), 1.0)
            w = np.where(ys > 0, nneg / npos, 1.0).astype(np.float32)
            self.params, loss = model.train_step(
                self.params, X, ys, w, self.lr)
            self.train_steps += 1
            last = float(loss)
        self.version += 1
        self._last_train = self._time()
        self._labels_at_train = self.labels.total_added
        return last

    def maybe_train(self, registry=None, telemetry=None) -> bool:
        """The loop's between-dispatches hook: train when due, fold
        the counters/gauges, emit one ``learn_update`` campaign
        event per completed round."""
        if registry is not None:
            registry.counters["learn_masks_applied"] = \
                self.masks_applied
            registry.gauge("learn_label_count", len(self.labels))
        if not self.ready_to_train():
            return False
        loss = self.train_round()
        if registry is not None:
            registry.counters["learn_train_steps"] = self.train_steps
            registry.gauge("learn_model_version", self.version)
            registry.gauge("learn_label_count", len(self.labels))
        if telemetry is not None:
            telemetry.event(
                "learn_update", version=int(self.version),
                labels=int(len(self.labels)),
                positives=int(self.labels.positives),
                train_steps=int(self.train_steps),
                loss=(round(loss, 5) if loss is not None else None))
        return True

    # -- mask serving ----------------------------------------------------

    def scan_params(self):
        """The weights for in-scan inference (the generation scans
        run model.masked_saliency per generation themselves)."""
        return self.params

    def mask_for(self, seed: bytes) -> Optional[np.ndarray]:
        """uint8 mask over ``seed`` under the current model, or None
        while untrained (version 0 — all-ones by construction, not
        worth a device call)."""
        if self.version == 0 or not seed:
            return None
        L = max(((len(seed) + 7) // 8) * 8, 8)
        buf = np.zeros(L, np.uint8)
        buf[:len(seed)] = np.frombuffer(bytes(seed), np.uint8)
        return np.asarray(model.masked_saliency(
            self.params, buf, np.int32(len(seed))))

    def focus_positions_for(self, seed: bytes
                            ) -> Optional[List[int]]:
        """Host-loop mask source: the positions the model keeps, or
        None when shaping would be a no-op (untrained, mask
        all-ones over the live prefix, or mask empty — an empty mask
        must never pin mutation to nothing, the set_focus_mask
        contract)."""
        mask = self.mask_for(seed)
        if mask is None:
            return None
        live = mask[:len(seed)]
        pos = np.flatnonzero(live).tolist()
        if not pos or len(pos) == len(seed):
            return None
        self.masks_applied += 1
        return pos

    # -- persistence (the PR 8 unified checkpoint epoch) -----------------

    def state_dict(self) -> Dict[str, Any]:
        return {"version": int(self.version),
                "train_steps": int(self.train_steps),
                "masks_applied": int(self.masks_applied),
                "params": model.encode_params(self.params)}

    def load_state(self, d: Dict[str, Any]) -> None:
        try:
            if isinstance(d.get("params"), dict):
                self.params = model.decode_params(d["params"])
            self.version = int(d.get("version", 0))
            self.train_steps = int(d.get("train_steps", 0))
            self.masks_applied = int(d.get("masks_applied", 0))
        except (KeyError, TypeError, ValueError) as e:
            WARNING_MSG("learn: checkpoint restore failed (fresh "
                        "model): %s", e)
            self.params = model.init_params()
            self.version = 0
