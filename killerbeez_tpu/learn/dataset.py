"""Lineage -> labeled training data for the byte-saliency model.

The corpus store already records WHO produced every admitted entry
(the ``parent`` sidecar field); this module adds WHAT was mutated —
a mutated-byte bitmap (child vs parent diff) recorded at admission
time as the ``provenance`` sidecar field — and turns the accumulated
lineage into (parent bytes, position, label) samples:

  * **positives** — parent positions whose mutation produced an
    admitted edge-novel child (the provenance bitmap, one sample per
    set bit);
  * **negatives** — parent positions whose mutation produced nothing
    the campaign kept: the loop feeds the diff of REJECTED
    interesting lanes (bucket-only new paths that did not admit)
    through ``add_negative``, and ``add_background`` samples parent
    positions no admitted child ever touched.

Samples live in a bounded FIFO buffer (oldest evicted) keyed by
parent md5 so one parent buffer is stored once no matter how many
children it labels.  ``samples_from_entries`` rebuilds positives
from persisted provenance sidecars on ``--resume`` — old sidecars
without the field simply contribute nothing (the learn tier skips
them, by design).

Provenance sidecar schema (optional, docs/LEARN.md)::

    {"mutator": "havoc", "stage": "havoc" | null,
     "bitmap": <base64 packbits over child length>, "bytes": N}
"""

from __future__ import annotations

import base64
import binascii
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

#: hard cap on positions one admission may contribute (a havoc block
#: op can rewrite half the buffer — unbounded, one admission would
#: flood the buffer with near-duplicate samples)
MAX_POSITIONS_PER_SAMPLE = 32


def diff_bitmap(parent: bytes, child: bytes,
                max_len: int = 0) -> np.ndarray:
    """uint8[len(child)] bitmap of the mutated CHILD positions:
    1 where the child byte differs from the parent's (positions past
    the common length — inserted/garbage tail bytes — count as
    mutated).  ``max_len`` truncates (0 = no cap)."""
    c = np.frombuffer(bytes(child), dtype=np.uint8)
    p = np.frombuffer(bytes(parent), dtype=np.uint8)
    if max_len:
        c = c[:max_len]
        p = p[:max_len]
    n = len(c)
    out = np.ones(n, dtype=np.uint8)
    m = min(n, len(p))
    out[:m] = (c[:m] != p[:m]).astype(np.uint8)
    return out


def bitmap_to_b64(bitmap: np.ndarray) -> str:
    return base64.b64encode(
        np.packbits(np.asarray(bitmap, np.uint8) != 0).tobytes()
    ).decode()


def b64_to_bitmap(s: str, n: int) -> Optional[np.ndarray]:
    """Decode a packed bitmap back to uint8[n]; None on garbage (a
    torn or hostile sidecar must never raise into the loop)."""
    try:
        raw = base64.b64decode(s, validate=True)
    except (binascii.Error, ValueError, TypeError):
        return None
    bits = np.unpackbits(np.frombuffer(raw, dtype=np.uint8))
    if len(bits) < n:
        return None
    return bits[:n].astype(np.uint8)


def make_provenance(parent: bytes, child: bytes, mutator: str,
                    stage: Optional[str] = None) -> Dict[str, Any]:
    """The admission-time provenance record: mutator id, stage, and
    the child-vs-parent mutated-byte bitmap."""
    bm = diff_bitmap(parent, child)
    return {"mutator": str(mutator),
            "stage": (str(stage) if stage is not None else None),
            "bitmap": bitmap_to_b64(bm),
            "bytes": int(bm.sum())}


def provenance_positions(prov: Dict[str, Any],
                         n: int) -> Optional[np.ndarray]:
    """Mutated positions from one provenance record (clipped to
    ``n``); None when the record is absent/garbage."""
    if not isinstance(prov, dict):
        return None
    bm = b64_to_bitmap(prov.get("bitmap", ""), n) \
        if isinstance(prov.get("bitmap"), str) else None
    if bm is None:
        return None
    return np.flatnonzero(bm)


class LabelBuffer:
    """Bounded (parent, position, label) sample store.

    Parent buffers are interned by md5 (one copy regardless of how
    many samples reference them); samples evict FIFO at ``cap``.
    ``make_batch`` materializes a training batch as padded arrays
    for ``model.batch_features``."""

    def __init__(self, cap: int = 8192, max_len: int = 4096,
                 seed: int = 0x5eed):
        self.cap = int(cap)
        self.max_len = int(max_len)
        self._bufs: Dict[str, np.ndarray] = {}
        self._lens: Dict[str, int] = {}
        #: (parent_key, position, label)
        self._samples: deque = deque()
        self._rng = np.random.default_rng(seed)
        self.positives = 0
        self.negatives = 0
        #: MONOTONE intake counter (never decremented by eviction) —
        #: the "new labels arrived" signal.  len(self) pins at cap
        #: once the FIFO saturates, so a length comparison would
        #: stall training forever on a long campaign.
        self.total_added = 0

    def __len__(self) -> int:
        return len(self._samples)

    def _intern(self, key: str, buf: bytes) -> Optional[str]:
        if key in self._bufs:
            return key
        raw = np.frombuffer(bytes(buf)[:self.max_len], dtype=np.uint8)
        if raw.size == 0:
            return None
        self._bufs[key] = raw
        self._lens[key] = raw.size
        return key

    def _evict(self) -> None:
        while len(self._samples) > self.cap:
            key, _pos, label = self._samples.popleft()
            if label:
                self.positives -= 1
            else:
                self.negatives -= 1
        # drop interned buffers no remaining sample references
        # (cheap: only when the intern table outgrew the samples)
        if len(self._bufs) > len(self._samples) + 8:
            live = {k for k, _p, _l in self._samples}
            for k in list(self._bufs):
                if k not in live:
                    del self._bufs[k], self._lens[k]

    def add(self, key: str, buf: bytes, positions, label: int,
            cap: int = MAX_POSITIONS_PER_SAMPLE) -> int:
        """Add samples for ``positions`` of one parent buffer (the
        per-admission position cap samples down deterministically via
        the buffer's own RNG).  Returns how many were added."""
        key = self._intern(key, buf)
        if key is None:
            return 0
        n = self._lens[key]
        pos = np.asarray([p for p in np.asarray(positions).ravel()
                          if 0 <= int(p) < n], dtype=np.int64)
        if pos.size == 0:
            return 0
        if pos.size > cap:
            pos = self._rng.choice(pos, size=cap, replace=False)
        for p in pos:
            self._samples.append((key, int(p), int(bool(label))))
        if label:
            self.positives += int(pos.size)
        else:
            self.negatives += int(pos.size)
        self.total_added += int(pos.size)
        self._evict()
        return int(pos.size)

    def add_background(self, key: str, buf: bytes, bitmap,
                       n: int = 8) -> int:
        """Sample ``n`` never-mutated parent positions as weak
        negatives (the complement of an admission's bitmap) — keeps
        the classes from degenerating when the loop sees few
        explicit rejects."""
        bm = np.asarray(bitmap, np.uint8)
        zeros = np.flatnonzero(bm == 0)
        if zeros.size == 0:
            return 0
        take = min(n, zeros.size)
        picks = self._rng.choice(zeros, size=take, replace=False)
        return self.add(key, buf, picks, 0, cap=take)

    def make_batch(self, n: int
                   ) -> Optional[Tuple[np.ndarray, np.ndarray,
                                       np.ndarray, np.ndarray]]:
        """(bufs uint8[N, L], lens int32[N], positions int32[N],
        labels float32[N]) — N random samples padded to one static
        width; None while the buffer is empty."""
        if not self._samples:
            return None
        idx = self._rng.integers(0, len(self._samples), size=int(n))
        samples = [self._samples[int(i)] for i in idx]
        L = max(self._lens[k] for k, _p, _l in samples)
        L = max(((L + 7) // 8) * 8, 8)
        bufs = np.zeros((len(samples), L), np.uint8)
        lens = np.zeros(len(samples), np.int32)
        poss = np.zeros(len(samples), np.int32)
        ys = np.zeros(len(samples), np.float32)
        for i, (k, p, label) in enumerate(samples):
            raw = self._bufs[k]
            bufs[i, :raw.size] = raw
            lens[i] = raw.size
            poss[i] = p
            ys[i] = float(label)
        return bufs, lens, poss, ys


def samples_from_entries(buffer: LabelBuffer, entries, parent_bytes,
                         informative_diff: int =
                         MAX_POSITIONS_PER_SAMPLE) -> int:
    """Rebuild positive (and background-negative) samples from
    persisted provenance sidecars — the ``--resume`` path.  ``entries``
    are CorpusEntry-likes (md5 / buf / parent / provenance attrs);
    ``parent_bytes(md5_or_base) -> bytes|None`` resolves parents.
    Entries without provenance (pre-learn sidecars) are skipped, and
    so are diffs wider than ``informative_diff`` — the caller passes
    the tier's live threshold so a resumed campaign trains on
    exactly the samples the uninterrupted one would have.  Returns
    the number of labeled entries consumed."""
    used = 0
    for e in entries:
        prov = getattr(e, "provenance", None)
        if not isinstance(prov, dict):
            continue
        parent = parent_bytes(getattr(e, "parent", None) or "base")
        if not parent:
            continue
        pos = provenance_positions(prov, len(e.buf))
        if pos is None or pos.size == 0 or \
                pos.size > informative_diff:
            # large diffs carry ~no positional signal (the tier's
            # informative-diff rule, applied on replay too)
            continue
        # positions index the CHILD; label the PARENT positions that
        # were rewritten (clip to the parent's length)
        key = getattr(e, "parent", None) or "base"
        added = buffer.add(key, parent, pos, 1)
        if added:
            used += 1
            bm = np.zeros(min(len(parent), buffer.max_len), np.uint8)
            inb = pos[pos < bm.size]
            bm[inb] = 1
            buffer.add_background(key, parent, bm)
    return used
