"""killerbeez_tpu.learn — on-TPU learned mutation shaping.

A byte-saliency model ("Not all bytes are equal", arxiv 1711.04596)
trained from the corpus store's own lineage: which parent byte
positions, when mutated, produced admitted children.  Training runs
on the fuzzing chip between dispatches (plain jax.grad SGD), and
inference runs INSIDE the device generation scans — the model and
the fuzzer share the accelerator, so shaping happens per generation
with zero host involvement.  docs/LEARN.md has the dataset schema,
parity rules and honesty caveats.
"""

from .dataset import (
    LabelBuffer, b64_to_bitmap, bitmap_to_b64, diff_bitmap,
    make_provenance, provenance_positions, samples_from_entries,
)
from .model import (
    FEATURES, WINDOW, apply_model, batch_features, decode_params,
    encode_params, feature_at, init_params, masked_saliency,
    n_params, quantize_mask, saliency_logits, train_step,
)
from .tier import LearnTier

__all__ = [
    "FEATURES", "WINDOW", "LabelBuffer", "LearnTier", "apply_model",
    "b64_to_bitmap", "batch_features", "bitmap_to_b64",
    "decode_params", "diff_bitmap", "encode_params", "feature_at",
    "init_params", "make_provenance", "masked_saliency", "n_params",
    "provenance_positions", "quantize_mask", "saliency_logits",
    "samples_from_entries", "train_step",
]
