"""Byte-saliency model — "Not all bytes are equal" (arxiv
1711.04596) scaled down to live ON the fuzzing chip.

A tiny MLP over sliding byte windows predicts, per seed byte
position, the probability that mutating that position produces an
admitted (edge-novel) child.  Everything here is pure JAX so the
same functions serve three callers:

  * **training** — plain ``jax.grad`` SGD (no optax, no optimizer
    state to checkpoint beyond the weights) on labeled
    (parent bytes, position) samples, run on the device between
    fuzzing dispatches (learn/tier.py owns the cadence);
  * **in-scan inference** — ``saliency_logits`` vmapped over every
    position of the selected seed-ring slot INSIDE the device
    generation scan (ops/generations.py), quantized to the focus
    mask the masked havoc kernel consumes;
  * **host-loop inference** — ``mask_positions`` feeds
    ``Mutator.set_focus_mask`` at rotation boundaries (the
    ``learned`` mask source beside the static ``edge_dep_mask``).

The parity anchor the whole tier rests on: ``init_params`` zeroes
the OUTPUT layer, so an untrained (version-0) model emits logit
exactly 0.0 for every input, ``quantize_mask`` maps that to the
all-ones mask, and the masked mutation kernel with an all-ones mask
is bit-identical to the unmasked one (ops/mutate_core.py) — a
campaign with learning enabled but no training yet IS the
historical campaign, pinned in tests/test_learn.py.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.serialization import decode_array, encode_array

#: byte-window width per position (centered; zero-padded at the
#: buffer edges) — the model's whole receptive field
WINDOW = 9
#: hidden layer widths (D -> H1 -> H2 -> 1); ~1k parameters total —
#: small enough that a training round between dispatches is noise
#: next to one fuzzing batch
HIDDEN = (32, 16)
#: feature dimension: WINDOW byte values + relative position +
#: normalized length
FEATURES = WINDOW + 2
#: default SGD learning rate (plain, no momentum — nothing beyond
#: the weights needs checkpointing)
LEARN_RATE = 0.5

Params = Tuple[jax.Array, ...]   # (w1, b1, w2, b2, w3, b3)


def init_params(seed: int = 0x6b7a) -> Params:
    """Deterministic init: small random hidden layers, ZERO output
    layer — logits are exactly 0.0 until the first train step, which
    is what makes the version-0 mask all-ones (see module doc)."""
    k1, k2 = jax.random.split(jax.random.key(seed))
    h1, h2 = HIDDEN
    w1 = jax.random.normal(k1, (FEATURES, h1), jnp.float32) \
        * (1.0 / np.sqrt(FEATURES))
    w2 = jax.random.normal(k2, (h1, h2), jnp.float32) \
        * (1.0 / np.sqrt(h1))
    return (w1, jnp.zeros((h1,), jnp.float32),
            w2, jnp.zeros((h2,), jnp.float32),
            jnp.zeros((h2,), jnp.float32), jnp.zeros((), jnp.float32))


def n_params(params: Params) -> int:
    return int(sum(int(np.prod(p.shape)) for p in params))


def feature_at(buf, length, pos):
    """Features of ONE (buffer, position) pair: the WINDOW bytes
    around ``pos`` (zero outside the live prefix), the relative
    position, and the normalized length.  The ONE featurizer — the
    train batch builder and both inference paths vmap this exact
    function, so a model never sees train/serve skew."""
    L = buf.shape[-1]
    length = jnp.maximum(length, 1)
    idx = jnp.arange(L, dtype=jnp.int32)
    half = WINDOW // 2
    offs = jnp.arange(-half, half + 1, dtype=jnp.int32)
    wpos = pos + offs
    valid = (wpos >= 0) & (wpos < length)
    # one-hot gather (no per-lane dynamic gather on the VPU — the
    # read_bytes discipline from ops/mutate_core.py)
    oh = wpos[:, None] == idx[None, :]                   # [W, L]
    win = jnp.sum(jnp.where(oh, buf[None, :].astype(jnp.float32),
                            0.0), axis=1)
    win = jnp.where(valid, win / 255.0, 0.0)
    rel = pos.astype(jnp.float32) / length.astype(jnp.float32)
    scale = jnp.minimum(length, 256).astype(jnp.float32) / 256.0
    return jnp.concatenate([win, rel[None], scale[None]])


def apply_model(params: Params, x):
    """Logit for one feature vector (vmap for batches)."""
    w1, b1, w2, b2, w3, b3 = params
    h = jnp.tanh(x @ w1 + b1)
    h = jnp.tanh(h @ w2 + b2)
    return h @ w3 + b3


def saliency_logits(params: Params, buf, length):
    """Per-byte saliency logits for one seed buffer: float32[L],
    position p's logit = apply_model(feature_at(buf, length, p)).
    Pure and jit-safe — this is the function the generation scans
    inline (one tiny [L, D] matmul chain per generation)."""
    L = buf.shape[-1]
    feats = jax.vmap(lambda p: feature_at(buf, length, p))(
        jnp.arange(L, dtype=jnp.int32))
    return jax.vmap(lambda f: apply_model(params, f))(feats)


def quantize_mask(logits, length):
    """Quantize saliency to the uint8[L] focus mask the masked havoc
    kernel consumes: 1 = mutable.  Threshold is logit 0 (p = 0.5), so
    the version-0 model (logits exactly 0.0) yields ALL-ONES — the
    parity anchor.  Positions PAST the live prefix stay 1 (mutable by
    default): the model has no labels there, and havoc edits grow the
    candidate length mid-stack — a mask that zeroed the tail would
    diverge from the unmasked kernel the moment an insert lands (the
    kernel re-clips to the CURRENT length every edit).  A mask the
    model zeroed completely falls back to uniform INSIDE the kernel
    (ops/mutate_core._havoc_one), never here — the quantizer stays a
    pure threshold."""
    L = logits.shape[-1]
    idx = jnp.arange(L, dtype=jnp.int32)
    return jnp.where(idx < jnp.maximum(length, 1),
                     (logits >= 0.0).astype(jnp.uint8),
                     jnp.uint8(1))


def masked_saliency(params: Params, buf, length):
    """saliency -> mask in one call (the scan's per-generation
    inference step)."""
    return quantize_mask(saliency_logits(params, buf, length), length)


def _loss(params: Params, X, y, w):
    """Weighted sigmoid binary cross-entropy (stable log1p form).
    ``w`` rebalances the classes — admissions are rare, so positives
    are upweighted by the caller to keep the decision boundary from
    collapsing to all-negative."""
    logits = jax.vmap(lambda f: apply_model(params, f))(X)
    per = jnp.maximum(logits, 0) - logits * y + \
        jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return jnp.sum(per * w) / jnp.maximum(jnp.sum(w), 1e-6)


@partial(jax.jit, static_argnames=())
def train_step(params: Params, X, y, w, lr):
    """One plain-SGD step on a labeled feature batch; returns
    (params', loss).  jax.grad, no optimizer state — the checkpoint
    epoch serializes only the weights."""
    loss, grads = jax.value_and_grad(_loss)(params, X, y, w)
    new = tuple(p - lr * g for p, g in zip(params, grads))
    return new, loss


def batch_features(bufs, lengths, positions):
    """Featurize a labeled sample batch: (uint8[N, L], int32[N],
    int32[N]) -> float32[N, D] via the one shared featurizer."""
    return jax.vmap(feature_at)(
        jnp.asarray(bufs, jnp.uint8),
        jnp.asarray(lengths, jnp.int32),
        jnp.asarray(positions, jnp.int32))


# -- (de)serialization (checkpoint epoch / kb tools) --------------------

_PARAM_NAMES = ("w1", "b1", "w2", "b2", "w3", "b3")


def encode_params(params: Params) -> Dict[str, Any]:
    return {name: encode_array(np.asarray(p, np.float32))
            for name, p in zip(_PARAM_NAMES, params)}


def decode_params(d: Dict[str, Any]) -> Params:
    ref = init_params()
    out = []
    for name, template in zip(_PARAM_NAMES, ref):
        arr = decode_array(d[name]).astype(np.float32)
        if arr.shape != template.shape:
            raise ValueError(
                f"learn model param {name}: shape {arr.shape} != "
                f"{tuple(template.shape)} (incompatible checkpoint)")
        out.append(jnp.asarray(arr))
    return tuple(out)
