"""Leveled logging configured by a JSON option string.

Mirrors the reference's logging contract (SURVEY §2.11, §5): the CLI
takes ``-l '{"level":0}'`` and every module logs timestamped leveled
lines; CRITICAL doubles as the finding event stream (reference
fuzzer/main.c:393-401).

Levels follow the reference's ordering: DEBUG=0 INFO=1 WARNING=2
ERROR=3 CRITICAL=4 FATAL=5 — a configured level N shows messages with
level >= N.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Optional, TextIO

LEVEL_DEBUG = 0
LEVEL_INFO = 1
LEVEL_WARNING = 2
LEVEL_ERROR = 3
LEVEL_CRITICAL = 4
LEVEL_FATAL = 5

_LEVEL_NAMES = ["DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL", "FATAL"]


class _LogState:
    level: int = LEVEL_INFO
    # None = resolve sys.stderr at write time.  Binding the stream at import
    # time makes every later write target whatever stderr was when this
    # module was first imported — under pytest that can be a capture stream
    # that is closed long before the logging call, turning unrelated tests
    # into "I/O operation on closed file" failures depending on collection
    # order.  Only an explicitly configured {"file": ...} stream is pinned.
    stream: Optional[TextIO] = None
    filename: Optional[str] = None
    _fh: Optional[TextIO] = None


_state = _LogState()


def setup_logging(options: Optional[str] = None) -> None:
    """Configure logging from a JSON option string.

    Accepted keys: ``level`` (int 0-5), ``file`` (path; appended to).
    ``None`` or ``""`` keeps defaults (INFO to stderr).
    """
    if not options:
        return
    opts = json.loads(options) if isinstance(options, str) else dict(options)
    if "level" in opts:
        lvl = int(opts["level"])
        if not (LEVEL_DEBUG <= lvl <= LEVEL_FATAL):
            raise ValueError(f"log level out of range: {lvl}")
        _state.level = lvl
    if "file" in opts:
        fh = open(opts["file"], "a", buffering=1)
        if _state._fh is not None:
            _state._fh.close()
        _state._fh = fh
        _state.filename = opts["file"]
        _state.stream = fh


def logging_help() -> str:
    return (
        "Logging options (JSON):\n"
        '  {"level": N}  minimum level shown: 0=DEBUG 1=INFO 2=WARNING '
        "3=ERROR 4=CRITICAL 5=FATAL (default 1)\n"
        '  {"file": "path"}  append log lines to a file instead of stderr\n'
    )


def _log(level: int, fmt: str, *args) -> None:
    if level < _state.level:
        return
    msg = (fmt % args) if args else fmt
    stamp = time.strftime("%Y-%m-%d %H:%M:%S")
    stream = _state.stream if _state.stream is not None else sys.stderr
    try:
        stream.write(f"{stamp} - {_LEVEL_NAMES[level]} - {msg}\n")
    except ValueError:
        # A pinned stream (log file or an inherited redirect) was closed
        # out from under us; fall back to the live stderr rather than
        # turning a log line into a crash.
        if stream is not sys.stderr:
            _state.stream = None
            _state._fh = None
            sys.stderr.write(f"{stamp} - {_LEVEL_NAMES[level]} - {msg}\n")


def DEBUG_MSG(fmt: str, *args) -> None:
    _log(LEVEL_DEBUG, fmt, *args)


def INFO_MSG(fmt: str, *args) -> None:
    _log(LEVEL_INFO, fmt, *args)


def WARNING_MSG(fmt: str, *args) -> None:
    _log(LEVEL_WARNING, fmt, *args)


def ERROR_MSG(fmt: str, *args) -> None:
    _log(LEVEL_ERROR, fmt, *args)


def CRITICAL_MSG(fmt: str, *args) -> None:
    _log(LEVEL_CRITICAL, fmt, *args)


def FATAL_MSG(fmt: str, *args) -> None:
    """Log at FATAL and raise — the reference's FATAL_MSG exits the process."""
    _log(LEVEL_FATAL, fmt, *args)
    raise FatalError((fmt % args) if args else fmt)


class FatalError(RuntimeError):
    """Raised by FATAL_MSG instead of the reference's exit(1)."""


def get_logger():
    """Return the module-level log functions as a namespace-like tuple."""
    return (DEBUG_MSG, INFO_MSG, WARNING_MSG, ERROR_MSG, CRITICAL_MSG,
            FATAL_MSG)


def set_level(level: int) -> None:
    _state.level = level


def get_level() -> int:
    return _state.level
