"""Serialization helpers for component state and multi-part inputs.

The reference serializes instrumentation/mutator state as JSON strings
with base64 payloads (reference afl_instrumentation.c:62-79) and
multi-part inputs via encode_mem_array/decode_mem_array (reference
network_server_driver.c:544). Same contracts here.
"""

from __future__ import annotations

import base64
import json
import zlib
from typing import Any, Dict, List, Sequence, Union

import numpy as np

Buf = Union[bytes, bytearray, memoryview]


def b64(buf: Buf) -> str:
    return base64.b64encode(bytes(buf)).decode("ascii")


def unb64(s: str) -> bytes:
    return base64.b64decode(s)


def encode_array(arr: np.ndarray, compress: bool = True) -> Dict[str, Any]:
    """Encode a numpy array as a JSON-safe dict (base64, optionally
    zlib-compressed — virgin maps are mostly 0xFF and compress ~1000x)."""
    raw = np.ascontiguousarray(arr).tobytes()
    payload = zlib.compress(raw) if compress else raw
    return {
        "dtype": str(arr.dtype),
        "shape": list(arr.shape),
        "zlib": bool(compress),
        "data": b64(payload),
    }


def decode_array(d: Dict[str, Any]) -> np.ndarray:
    raw = unb64(d["data"])
    if d.get("zlib"):
        raw = zlib.decompress(raw)
    return np.frombuffer(raw, dtype=np.dtype(d["dtype"])).reshape(d["shape"]).copy()


def encode_mem_array(bufs: Sequence[Buf]) -> str:
    """Serialize a list of byte buffers to a JSON string (multi-part
    last-input serialization, reference network_server_driver.c:544)."""
    return json.dumps([b64(b) for b in bufs])


def decode_mem_array(s: str) -> List[bytes]:
    return [unb64(x) for x in json.loads(s)]


def state_dumps(state: Dict[str, Any]) -> str:
    """Component get_state contract: a self-contained JSON string."""
    return json.dumps(state)


def state_loads(s: str) -> Dict[str, Any]:
    if not s:
        return {}
    return json.loads(s)
