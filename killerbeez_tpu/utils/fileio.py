"""File IO helpers (reference killerbeez-utils: read_file,
write_buffer_to_file, file_exists, get_temp_filename, md5)."""

from __future__ import annotations

import hashlib
import os
import tempfile
from typing import Optional, Union

Buf = Union[bytes, bytearray, memoryview]


def read_file(path: Union[str, os.PathLike]) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def write_buffer_to_file(path: Union[str, os.PathLike], buf: Buf) -> None:
    # chaos seam (resilience/chaos.py): finding/repro writes can be
    # made to tear, hit ENOSPC, or die mid-write under --chaos
    from ..resilience.chaos import chaos_point
    data = bytes(buf)
    chaos_point("fs_write", path=str(path), data=data)
    with open(path, "wb") as f:
        f.write(data)


def file_exists(path: Union[str, os.PathLike]) -> bool:
    return os.path.isfile(path)


def get_temp_filename(prefix: str = "kbz", suffix: str = "") -> str:
    fd, path = tempfile.mkstemp(prefix=prefix, suffix=suffix)
    os.close(fd)
    return path


def md5_hex(buf: Buf) -> str:
    """Findings are deduped by md5 of the input buffer
    (reference fuzzer/main.c:410-413)."""
    return hashlib.md5(bytes(buf)).hexdigest()


def ensure_dir(path: Union[str, os.PathLike]) -> None:
    os.makedirs(path, exist_ok=True)
