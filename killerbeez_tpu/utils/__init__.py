"""Reconstruction of the killerbeez-utils surface (SURVEY §2.11).

The reference's utility library is a sibling repo absent from the
checkout; this package rebuilds the API surface inferred from call
sites: leveled logging configured by a JSON option string, JSON option
parsing helpers (the PARSE_OPTION_* macro family), file IO helpers,
and mem-array encoding for multi-part input serialization.
"""

from .logging import (
    setup_logging,
    logging_help,
    DEBUG_MSG,
    INFO_MSG,
    WARNING_MSG,
    ERROR_MSG,
    CRITICAL_MSG,
    FATAL_MSG,
    get_logger,
)
from .options import (
    parse_options,
    get_option,
    add_int_option_to_json,
    add_option_to_json,
)
from .fileio import (
    read_file,
    write_buffer_to_file,
    file_exists,
    get_temp_filename,
    md5_hex,
)
from .serialization import encode_mem_array, decode_mem_array

__all__ = [
    "setup_logging", "logging_help", "get_logger",
    "DEBUG_MSG", "INFO_MSG", "WARNING_MSG", "ERROR_MSG", "CRITICAL_MSG",
    "FATAL_MSG",
    "parse_options", "get_option", "add_int_option_to_json",
    "add_option_to_json",
    "read_file", "write_buffer_to_file", "file_exists", "get_temp_filename",
    "md5_hex",
    "encode_mem_array", "decode_mem_array",
]
