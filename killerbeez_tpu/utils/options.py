"""JSON option-string parsing — the PARSE_OPTION_* macro family.

Every pluggable component in the reference takes a JSON option string
(``-d/-i/-m``) parsed by PARSE_OPTION_{STRING,INT,DOUBLE,ARRAY,
INT_ARRAY} macros into its state struct (SURVEY §5, e.g. reference
file_driver.c:44-50, afl_instrumentation.c:359-371). Here a component
declares an option schema and gets a validated dict back.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Mapping, Optional, Sequence


class OptionError(ValueError):
    pass


def parse_options(options: Optional[str],
                  schema: Optional[Mapping[str, type]] = None,
                  defaults: Optional[Mapping[str, Any]] = None
                  ) -> Dict[str, Any]:
    """Parse a JSON option string against a schema.

    ``schema`` maps option name -> expected type (int, float, str, bool,
    list). Unknown keys are rejected when a schema is given (the
    reference silently ignores them, but strictness catches typos —
    the help text tells the user the valid set). ``defaults`` seeds the
    result.
    """
    result: Dict[str, Any] = dict(defaults or {})
    if options is None or options == "":
        return result
    if isinstance(options, str):
        try:
            opts = json.loads(options)
        except json.JSONDecodeError as e:
            raise OptionError(f"invalid JSON options: {e}") from e
    else:
        opts = dict(options)
    if not isinstance(opts, dict):
        raise OptionError("options must be a JSON object")
    for key, value in opts.items():
        if schema is not None:
            if key not in schema:
                raise OptionError(
                    f"unknown option {key!r}; valid: {sorted(schema)}")
            want = schema[key]
            if want in (int, float) and isinstance(value, bool):
                raise OptionError(f"option {key!r} must be {want.__name__}")
            if want is float and isinstance(value, int):
                value = float(value)
            if not isinstance(value, want):
                raise OptionError(
                    f"option {key!r} must be {want.__name__}, "
                    f"got {type(value).__name__}")
        result[key] = value
    return result


def get_option(opts: Mapping[str, Any], name: str, default: Any = None) -> Any:
    return opts.get(name, default)


def add_option_to_json(options: Optional[str], name: str,
                       value: Any) -> str:
    """Return a new option string with ``name`` set (reference
    add_int_option_to_json generalized)."""
    opts = json.loads(options) if options else {}
    opts[name] = value
    return json.dumps(opts)


def add_int_option_to_json(options: Optional[str], name: str,
                           value: int) -> str:
    return add_option_to_json(options, name, int(value))


def format_help(component: str, schema: Mapping[str, type],
                descriptions: Mapping[str, str]) -> str:
    """Self-describing per-module help aggregated by factories
    (reference driver_factory.c:146-158)."""
    lines = [f"{component} options (JSON):"]
    for key in sorted(schema):
        t = schema[key].__name__
        lines.append(f"  {key} ({t}): {descriptions.get(key, '')}")
    return "\n".join(lines) + "\n"
