"""kb-solve — path-condition solving for KBVM program edges.

Given a target (built-in name or compiled ``.npz``) and an edge of
its static universe, print the concrete input the solver synthesized
to traverse it — or the honest unsat/unknown reason.  The CI smoke
lane drives ``--require-solved`` to fail the build when a previously-
solvable edge regresses.

Usage:
    kb-solve test                         # every static edge
    kb-solve tlvstack_vm --edge 4:5       # one edge (from:to, -1=entry)
    kb-solve cgc_like --block 7           # any edge into block 7
    kb-solve test --json --explain
    kb-solve test --require-solved 11     # CI gate
    kb-solve imgparse_vm --vsa --explain  # value-set seeding +
                                          # per-byte domain verdicts
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Tuple

import numpy as np

from ..analysis.solver import (
    DEFAULT_BUDGET, DEFAULT_MAX_LEN, DEFAULT_MAX_VISITS, solve_edge,
)


def _parse_edge(s: str) -> Tuple[int, int]:
    try:
        f, t = s.split(":")
        return int(f), int(t)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"edge must be from:to block indices (-1 = entry), "
            f"got {s!r}")


def _load_program(args):
    from ..models import targets, targets_cgc  # noqa: F401
    if args.program_file:
        return targets.load_program_from_options(
            {"program_file": args.program_file}, "program_file missing")
    if not args.target:
        raise ValueError("a target name or --program-file is required")
    return targets.get_target(args.target)


def solve_report(program, edges, *, budget: int, max_visits: int,
                 max_len: int, explain: bool,
                 vsa: bool = False) -> dict:
    """The --json payload (and the CI smoke lane's data source).
    ``vsa=True`` routes every edge through ``solve_edge_vsa``
    (byte-domain seeding + the visit-cap escalation ladder) and
    attaches each verdict's ``vsa`` metadata; False (the default)
    keeps the report bit-identical to the pre-VSA tool."""
    out = {"target": program.name, "edges": {}, "solved": 0,
           "unsat": 0, "unknown": 0}
    vsa_doc = df = None
    if vsa:
        from ..analysis.dataflow import analyze_dataflow
        from ..analysis.solver import solve_edge_vsa
        from ..analysis.vsa import analyze_vsa
        vsa_doc = analyze_vsa(program)
        df = analyze_dataflow(program)
    for e in edges:
        if vsa:
            r = solve_edge_vsa(program, e, vsa=vsa_doc,
                               budget=budget, max_visits=max_visits,
                               max_len=max_len, dataflow=df)
        else:
            r = solve_edge(program, e, budget=budget,
                           max_visits=max_visits, max_len=max_len)
        d = r.as_dict()
        if not explain:
            d.pop("conditions", None)
        out["edges"][f"{e[0]}:{e[1]}"] = d
        out[r.status] += 1
    return out


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="kb-solve",
        description="solve path conditions of KBVM static edges into "
                    "concrete inputs (analysis/solver.py)")
    p.add_argument("target", nargs="?",
                   help="built-in target name (kb-lint lists them)")
    p.add_argument("--program-file",
                   help="compiled .npz program instead of a built-in")
    p.add_argument("--edge", action="append", type=_parse_edge,
                   metavar="F:T",
                   help="edge to solve as from:to block indices "
                        "(-1 = entry); repeatable; default = every "
                        "edge of the static universe")
    p.add_argument("--block", type=int,
                   help="solve every edge INTO this block index")
    p.add_argument("--budget", type=int, default=DEFAULT_BUDGET,
                   help="path-search expansion budget per edge "
                        f"(default {DEFAULT_BUDGET})")
    p.add_argument("--max-visits", type=int,
                   default=DEFAULT_MAX_VISITS,
                   help="per-pc visit cap on candidate paths (loop "
                        f"unrolling depth; default {DEFAULT_MAX_VISITS})")
    p.add_argument("--max-len", type=int, default=DEFAULT_MAX_LEN,
                   help="synthesized input length cap "
                        f"(default {DEFAULT_MAX_LEN})")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    p.add_argument("--vsa", action="store_true",
                   help="seed byte domains from the value-set "
                        "fixpoint and escalate visit caps on honest "
                        "visit-cap unknowns (analysis/vsa.py)")
    p.add_argument("--explain", action="store_true",
                   help="print the collected path condition of each "
                        "solved edge; with --vsa, also the VSA "
                        "domain that pruned (or failed to prune) "
                        "each free byte of unknown edges")
    p.add_argument("--require-solved", type=int, metavar="N",
                   help="exit 1 unless at least N edges solved (the "
                        "CI smoke gate: a previously-solvable edge "
                        "going dark fails the lane)")
    args = p.parse_args(argv)
    try:
        program = _load_program(args)
    except (ValueError, FileNotFoundError, KeyError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    universe = [(int(f), int(t)) for f, t in
                zip(np.asarray(program.edge_from),
                    np.asarray(program.edge_to))]
    edges = list(args.edge or [])
    if args.block is not None:
        edges += [e for e in universe if e[1] == args.block]
    if not edges:
        edges = universe
    edges = list(dict.fromkeys(edges))  # dedupe: repeated --edge /
    # --block overlaps must not double-count toward --require-solved

    rep = solve_report(program, edges, budget=args.budget,
                       max_visits=args.max_visits,
                       max_len=args.max_len, explain=args.explain,
                       vsa=args.vsa)
    ok = (args.require_solved is None
          or rep["solved"] >= args.require_solved)

    if args.json:
        if args.require_solved is not None:
            rep["require_solved"] = args.require_solved
            rep["require_met"] = ok
        print(json.dumps(rep, indent=2))
    else:
        print(f"{program.name}: {len(edges)} edge(s) — "
              f"{rep['solved']} solved, {rep['unsat']} unsat, "
              f"{rep['unknown']} unknown")
        for key, d in rep["edges"].items():
            if d["status"] == "solved":
                buf = bytes.fromhex(d["input_hex"])
                print(f"  {key}: solved len={d['length']} {buf!r}")
                if args.explain:
                    for c in d.get("conditions", []):
                        print(f"      {c}")
            else:
                print(f"  {key}: {d['status']} ({d['reason']})")
            if args.explain and d.get("vsa"):
                v = d["vsa"]
                if v.get("visit_ladder"):
                    print(f"      vsa: visit ladder "
                          f"{v['visit_ladder']}, seeded bytes "
                          f"{v.get('seeded_bytes', [])}")
                for var, desc in sorted(
                        v.get("domains", {}).items()):
                    print(f"      vsa: {var}: {desc}")
                if v.get("certificate"):
                    c = v["certificate"]
                    print(f"      vsa: unsat certificate — "
                          f"exhaustive at max_visits="
                          f"{c['max_visits']}, "
                          f"{c['expansions']} expansions, "
                          f"{len(c['forced_guards'])} forced "
                          f"guard(s)")
        if args.require_solved is not None and not ok:
            print(f"FAIL: {rep['solved']} solved < required "
                  f"{args.require_solved}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
