"""showmap — run one input, print its coverage bitmap.

Parity with the reference's afl-showmap (afl_progs/afl-showmap.c,
SURVEY §2.5): execute the target once on the given input and print the
nonzero bitmap slots as ``slot:count`` lines — the debugging /
toolchain-self-test primitive (the reference's Makefile self-test
asserts two different inputs produce different maps).

Usage:
    python -m killerbeez_tpu.tools.showmap file afl -sf input.bin \
        -d '{"path": "corpus/build/test", "arguments": "@@"}'
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from ..drivers.factory import driver_factory
from ..instrumentation.factory import instrumentation_factory
from ..utils.fileio import read_file, write_buffer_to_file
from ..utils.logging import setup_logging
from .tracer import force_edges_option


def show_map(driver, instrumentation, input_bytes: bytes) -> List[str]:
    driver.test_input(input_bytes)
    edges = instrumentation.get_edges()
    if edges is None:
        raise ValueError(
            f"{instrumentation.name} cannot report coverage slots")
    return [f"{e}:{c}" for e, c in sorted(edges)]


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="killerbeez-tpu-showmap",
        description="run one input and print its coverage map")
    p.add_argument("driver", help="driver name (file, stdin, ...)")
    p.add_argument("instrumentation",
                   help="instrumentation name (afl, jit_harness, ...)")
    p.add_argument("-sf", "--seed-file", required=True, help="the input")
    p.add_argument("-d", "--driver-options", help="driver JSON options")
    p.add_argument("-i", "--instrumentation-options",
                   help="instrumentation JSON options (edges forced on)")
    p.add_argument("-o", "--output",
                   help="write slot:count lines here (default stdout)")
    p.add_argument("-l", "--logging-options", help="logging JSON options")
    args = p.parse_args(argv)
    try:
        setup_logging(args.logging_options)
        instrumentation = instrumentation_factory(
            args.instrumentation,
            force_edges_option(args.instrumentation_options))
        driver = driver_factory(args.driver, args.driver_options,
                                instrumentation, None)
        lines = show_map(driver, instrumentation,
                         read_file(args.seed_file))
        text = "".join(f"{ln}\n" for ln in lines)
        if args.output:
            write_buffer_to_file(args.output, text.encode())
        else:
            sys.stdout.write(text)
        driver.cleanup()
        instrumentation.cleanup()
        return 0
    except (ValueError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
