"""showmap — run one input, print its coverage bitmap.

Parity with the reference's afl-showmap (afl_progs/afl-showmap.c,
SURVEY §2.5): execute the target once on the given input and print the
nonzero bitmap slots as ``slot:count`` lines — the debugging /
toolchain-self-test primitive (the reference's Makefile self-test
asserts two different inputs produce different maps).

Usage:
    python -m killerbeez_tpu.tools.showmap file afl -sf input.bin \
        -d '{"path": "corpus/build/test", "arguments": "@@"}'
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from ..drivers.factory import driver_factory
from ..instrumentation.factory import instrumentation_factory
from ..utils.fileio import read_file, write_buffer_to_file
from ..utils.logging import INFO_MSG, setup_logging
from .tracer import force_edges_option


def show_map(driver, instrumentation, input_bytes: bytes) -> List[str]:
    driver.test_input(input_bytes)
    edges = instrumentation.get_edges()
    if edges is None:
        raise ValueError(
            f"{instrumentation.name} cannot report coverage slots")
    return [f"{e}:{c}" for e, c in sorted(edges)]


def static_summary(program, dynamic_slots) -> str:
    """One-line static-universe context for a dynamic trace: how much
    of the statically-enumerable edge universe this one input lit up
    (KBVM targets only — the universe is exact, vm.compute_edges)."""
    from ..analysis import build_cfg
    from ..analysis.lint import universe_stats

    s = universe_stats(program, build_cfg(program))
    import numpy as np
    static = set(int(x) for x in np.asarray(program.edge_slot))
    hit = len(static & set(int(d) for d in dynamic_slots))
    pct = 100.0 * hit / s["n_slots"] if s["n_slots"] else 0.0
    return (f"static universe: {s['n_blocks']} blocks, "
            f"{s['n_edges']} edges over {s['n_slots']} slots "
            f"({s['n_modules']} module(s)); input covered "
            f"{hit}/{s['n_slots']} static slots ({pct:.1f}%); "
            f"longest loop-free path {s['longest_acyclic_path']} of "
            f"max_steps {s['max_steps']}")


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="killerbeez-tpu-showmap",
        description="run one input and print its coverage map")
    p.add_argument("driver", help="driver name (file, stdin, ...)")
    p.add_argument("instrumentation",
                   help="instrumentation name (afl, jit_harness, ...)")
    p.add_argument("-sf", "--seed-file", required=True, help="the input")
    p.add_argument("-d", "--driver-options", help="driver JSON options")
    p.add_argument("-i", "--instrumentation-options",
                   help="instrumentation JSON options (edges forced on)")
    p.add_argument("-o", "--output",
                   help="write slot:count lines here (default stdout)")
    p.add_argument("-l", "--logging-options", help="logging JSON options")
    args = p.parse_args(argv)
    try:
        setup_logging(args.logging_options)
        instrumentation = instrumentation_factory(
            args.instrumentation,
            force_edges_option(args.instrumentation_options))
        driver = driver_factory(args.driver, args.driver_options,
                                instrumentation, None)
        lines = show_map(driver, instrumentation,
                         read_file(args.seed_file))
        # KBVM targets: report the static edge universe next to the
        # dynamic trace (logged, so stdout stays slot:count parseable)
        program = getattr(instrumentation, "program", None)
        if program is not None:
            INFO_MSG("%s", static_summary(
                program, (int(ln.split(":")[0]) for ln in lines)))
        # stateful session tier: the input's state x edge record
        # (state:slot:count lines, logged so stdout stays slot:count
        # parseable for the classic consumers)
        sp_fn = getattr(instrumentation, "get_state_pairs", None)
        pairs = sp_fn() if sp_fn is not None else None
        if pairs:
            states = sorted({s for s, _, _ in pairs})
            INFO_MSG("state coverage: %d protocol state(s) %s, "
                     "%d state x edge pair(s): %s",
                     len(states), states, len(pairs),
                     " ".join(f"{s}:{e}:{c}" for s, e, c in pairs))
        text = "".join(f"{ln}\n" for ln in lines)
        if args.output:
            write_buffer_to_file(args.output, text.encode())
        else:
            sys.stdout.write(text)
        driver.cleanup()
        instrumentation.cleanup()
        return 0
    except (ValueError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
