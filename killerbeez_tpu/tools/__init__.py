"""Side tools (reference SURVEY §2.7): merger, tracer, minimize,
picker, showmap — each a small CLI over the same driver /
instrumentation factories the fuzzer uses.

Run as ``python -m killerbeez_tpu.tools.<tool> ...``.
"""
