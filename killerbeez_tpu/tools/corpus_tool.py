"""kb-corpus — inspect and minimize a persistent corpus store.

Operator-side companion to ``--corpus-dir`` (corpus/store.py): list
entries with their bandit stats and lineage, summarize coverage, and
compact the store offline.  Wires the existing side tools together:
signatures for unsigned entries come from one showmap-style execution
per entry (tools/showmap.py), compaction is the greedy edge cover the
minimize tool and the manager's ``/api/minimize`` already use, and
``stats --states`` folds serialized instrumentation states through
the merger (tools/merger.py) to report fleet coverage next to the
store's.

    kb-corpus ls out/corpus
    kb-corpus heat out/corpus --top 4
    kb-corpus stats out/corpus --states node0.state node1.state -I afl
    kb-corpus compact out/corpus --dry-run
    kb-corpus compact out/corpus --sign file afl \\
        -d '{"path": "corpus/build/test", "arguments": "@@"}'
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Dict, List, Optional

from ..corpus.store import CorpusEntry, CorpusStore
from ..tools.minimize import greedy_edge_cover
from ..utils.logging import INFO_MSG, setup_logging


def _fmt_age(seconds: float) -> str:
    for unit, div in (("d", 86400), ("h", 3600), ("m", 60)):
        if seconds >= div:
            return f"{seconds / div:.1f}{unit}"
    return f"{seconds:.0f}s"


def render_ls(entries: List[CorpusEntry]) -> str:
    lines = [f"{'md5':<32}  {'size':>6}  {'edges':>5}  {'states':>6}  "
             f"{'sel':>6}  {'finds':>6}  {'src':<5}  {'age':>6}  "
             f"parent"]
    now = time.time()
    for e in entries:
        n_states = (len({p[0] for p in e.state_sig})
                    if e.state_sig else None)
        lines.append(
            f"{e.md5:<32}  {len(e.buf):>6}  "
            f"{len(e.sig) if e.sig else '-':>5}  "
            f"{n_states if n_states is not None else '-':>6}  "
            f"{e.selections:>6.2f}  {e.finds:>6.2f}  "
            f"{e.source:<5}  {_fmt_age(max(now - e.discovered, 0)):>6}"
            f"  {e.parent or '-'}")
    return "\n".join(lines)


def render_stats(entries: List[CorpusEntry],
                 merged_coverage: Optional[int] = None) -> str:
    signed = [e for e in entries if e.sig]
    edges: Dict[int, int] = {}
    for e in signed:
        for s in e.sig:
            edges[s] = edges.get(s, 0) + 1
    lines = [
        f"entries        : {len(entries)} "
        f"({len(signed)} signed, {len(entries) - len(signed)} unsigned)",
        f"total bytes    : {sum(len(e.buf) for e in entries)}",
        f"distinct edges : {len(edges)}",
    ]
    if edges:
        rare = sorted(edges.items(), key=lambda kv: (kv[1], kv[0]))[:5]
        lines.append("rarest edges   : " + ", ".join(
            f"{s} (hit by {n})" for s, n in rare))
    # stateful session tier: the corpus-wide state x edge frontier
    # (entries carry their state_sig sidecars from the session
    # signer; kb-corpus is the offline view of the state_cov gauges)
    st_entries = [e for e in entries if e.state_sig]
    if st_entries:
        pairs = {tuple(p) for e in st_entries for p in e.state_sig}
        per_state: Dict[int, int] = {}
        for s, _slot in pairs:
            per_state[s] = per_state.get(s, 0) + 1
        lines.append(
            f"state coverage : {len(per_state)} protocol states, "
            f"{len(pairs)} state x edge pairs across "
            f"{len(st_entries)} session entries ("
            + ", ".join(f"s{s}:{n}" for s, n in
                        sorted(per_state.items())) + ")")
    # mutation-provenance summary (learn tier): do this campaign's
    # sidecars carry enough byte-diff labels to train on?
    labeled = [e for e in entries
               if isinstance(getattr(e, "provenance", None), dict)]
    if entries:
        line = (f"provenance     : {len(labeled)} labeled / "
                f"{len(entries) - len(labeled)} unlabeled entries "
                f"(byte-diff training labels)")
        if labeled:
            from ..learn.dataset import provenance_positions
            hist: Dict[int, int] = {}
            for e in labeled:
                pos = provenance_positions(e.provenance, len(e.buf))
                if pos is None:
                    continue
                for p in pos.tolist():
                    hist[p] = hist.get(p, 0) + 1
            if hist:
                top = sorted(hist.items(),
                             key=lambda kv: (-kv[1], kv[0]))[:5]
                line += "; top mutated positions: " + ", ".join(
                    f"{p} (x{n})" for p, n in top)
        lines.append(line)
    by_src: Dict[str, int] = {}
    for e in entries:
        by_src[e.source] = by_src.get(e.source, 0) + 1
    lines.append("sources        : " + ", ".join(
        f"{k} {v}" for k, v in sorted(by_src.items())))
    top = sorted(entries, key=lambda e: -e.finds)[:5]
    if top and top[0].finds > 0:
        lines.append("top finders    : " + ", ".join(
            f"{e.md5[:8]} ({e.finds:.2f})" for e in top
            if e.finds > 0))
    if merged_coverage is not None:
        lines.append(f"state coverage : {merged_coverage} virgin "
                     "bytes touched (merged instrumentation states)")
    return "\n".join(lines)


def make_showmap_signer(driver_name: str, instr_name: str,
                        driver_opts: Optional[str],
                        instr_opts: Optional[str]):
    """One showmap-style execution per entry: build the driver +
    instrumentation pair once (edges forced on, exactly like the
    showmap tool) and return ``bytes -> [edge slot, ...]``."""
    from ..drivers.factory import driver_factory
    from ..instrumentation.factory import instrumentation_factory
    from .tracer import force_edges_option

    instr = instrumentation_factory(instr_name,
                                    force_edges_option(instr_opts))
    driver = driver_factory(driver_name, driver_opts, instr, None)

    def sign(buf: bytes) -> Optional[List[int]]:
        driver.test_input(buf)
        edges = instr.get_edges()
        return [e for e, _ in edges] if edges else None

    return sign


def compact(store: CorpusStore, entries: List[CorpusEntry],
            signer=None, dry_run: bool = False) -> List[str]:
    """Drop entries whose edges are fully covered by the rest of the
    store (greedy edge cover — the minimize tool's algorithm).
    Unsigned entries are kept — redundancy can't be proven without a
    signature (pass --sign to compute them).  Returns the removed
    md5s."""
    if signer is not None:
        from ..corpus.store import coverage_hash
        for e in entries:
            if e.sig is None:
                sig = signer(e.buf)
                if sig:
                    e.sig = sorted(set(sig))
                    e.cov_hash = coverage_hash(e.sig, e.buf,
                                               e.state_sig)
                    if not dry_run:
                        store.update_meta(e)
    signed = {e.md5: set(e.sig) for e in entries if e.sig}
    kept = set(greedy_edge_cover(signed))
    removed = [md5 for md5 in signed if md5 not in kept]
    if not dry_run:
        for md5 in removed:
            store.remove(md5)
    return removed


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="kb-corpus",
        description="inspect / summarize / compact a persistent "
                    "corpus store (--corpus-dir)")
    p.add_argument("command", choices=["ls", "stats", "compact",
                                       "heat"])
    p.add_argument("store", help="corpus store directory")
    p.add_argument("--sign", nargs=2, metavar=("DRIVER", "INSTR"),
                   help="sign unsigned entries with one execution "
                        "each through this driver/instrumentation "
                        "pair (showmap semantics, edges forced on)")
    p.add_argument("-d", "--driver-options", help="driver JSON options")
    p.add_argument("-i", "--instrumentation-options",
                   help="instrumentation JSON options for --sign")
    p.add_argument("-I", "--instrumentation",
                   help="instrumentation name for --states merging")
    p.add_argument("--states", nargs="+",
                   help="serialized instrumentation states to fold "
                        "through the merger and report coverage for "
                        "(stats)")
    p.add_argument("--dry-run", action="store_true",
                   help="compact: report what would be removed, "
                        "remove nothing")
    p.add_argument("--entry", metavar="MD5",
                   help="heat: one parent's panel (md5 prefix ok) "
                        "instead of the hottest parents")
    p.add_argument("--top", type=int, default=4,
                   help="heat: how many parent panels (default 4)")
    p.add_argument("--hex-width", type=int, default=16,
                   help="heat: bytes per hex-dump row (default 16)")
    p.add_argument("--no-color", action="store_true",
                   help="heat: character ramp instead of ANSI")
    p.add_argument("--base", metavar="FILE",
                   help="heat: the campaign's base seed file, so "
                        "first-generation lineage (parent 'base') "
                        "renders too")
    p.add_argument("-l", "--logging-options", help="logging JSON options")
    args = p.parse_args(argv)
    try:
        setup_logging(args.logging_options)
        store = CorpusStore(args.store)
        entries = store.load()
        if args.command == "ls":
            print(render_ls(entries))
            return 0
        if args.command == "heat":
            # FMViz-style per-byte mutation heat from the lineage's
            # provenance bitmaps (tools/heat.py)
            from .heat import render_store_heat
            base = None
            if args.base:
                with open(args.base, "rb") as f:
                    base = f.read()
            print(render_store_heat(
                entries, top=args.top, width=args.hex_width,
                color=not args.no_color, only_md5=args.entry,
                base=base))
            return 0
        if args.command == "stats":
            merged_cov = None
            if args.states:
                if not args.instrumentation:
                    print("error: --states needs -I/--instrumentation",
                          file=sys.stderr)
                    return 2
                from ..instrumentation.factory import (
                    instrumentation_factory,
                )
                from .merger import merge_state_files
                merged = merge_state_files(
                    args.instrumentation,
                    args.instrumentation_options, args.states)
                probe = instrumentation_factory(
                    args.instrumentation,
                    args.instrumentation_options)
                probe.set_state(merged)
                cov_fn = getattr(probe, "coverage_bytes", None)
                merged_cov = cov_fn() if cov_fn else None
                probe.cleanup()
            print(render_stats(entries, merged_cov))
            return 0
        signer = None
        if args.sign:
            signer = make_showmap_signer(
                args.sign[0], args.sign[1], args.driver_options,
                args.instrumentation_options)
        removed = compact(store, entries, signer=signer,
                          dry_run=args.dry_run)
        verb = "would remove" if args.dry_run else "removed"
        INFO_MSG("compact: %s %d of %d entries (edges covered by "
                 "the rest)", verb, len(removed), len(entries))
        for md5 in removed:
            print(md5)
        return 0
    except (ValueError, FileNotFoundError, NotImplementedError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
