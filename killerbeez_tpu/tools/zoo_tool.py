"""kb-zoo — the generated target zoo (models/zoo.py) CLI.

Families are parameterized KBVM program generators with planted,
certified deep bugs; instances resolve anywhere a ``--target`` is
taken, under ``zoo:family:k=v,...`` names.

Usage:
    kb-zoo list                       # families, knobs, gated names
    kb-zoo certify [names...]         # certify (default: gated set)
    kb-zoo certify --json             # machine-readable report
    kb-zoo generate zoo:tlv:depth=2,bug=1 --out DIR
        # write program.npz + seed + crash witness + grammar.json

``certify`` exits 1 when any requested instance fails certification
(lint errors, a non-benign seed, or a witness that does not crash
through the deep edge) — the CI zoo lane gates on this.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from ..models.zoo import (
    GATED_NAMES, build_zoo, certify_zoo, zoo_families,
)


def _cmd_list(args) -> int:
    fams = zoo_families()
    print("zoo families (knob defaults):")
    for fam, params in sorted(fams.items()):
        knobs = ", ".join(f"{k}={v}" for k, v in sorted(params.items()))
        print(f"  {fam:8s} {knobs}")
    print("gated instances (bench --grammar / CI zoo lane):")
    for n in GATED_NAMES:
        print(f"  {n}")
    return 0


def _cmd_certify(args) -> int:
    names: List[str] = args.names or list(GATED_NAMES)
    reports = [certify_zoo(n) for n in names]
    ok = all(r["certified"] for r in reports)
    if args.json:
        print(json.dumps({"certified": ok, "targets": reports},
                         indent=2))
    else:
        for r in reports:
            mark = "ok " if r["certified"] else "FAIL"
            print(f"  {mark} {r['name']}: deep edge "
                  f"{tuple(r['deep_edge'])}, solver {r['solver']}, "
                  f"seed benign {r['seed_benign']}, witness crashes "
                  f"{r['witness_crashes']}, "
                  f"{len(r['lint_errors'])} lint error(s)")
        print("certified" if ok else "CERTIFICATION FAILED")
    return 0 if ok else 1


def _cmd_generate(args) -> int:
    import numpy as np

    t = build_zoo(args.name)
    os.makedirs(args.out, exist_ok=True)
    p = t.program
    np.savez(os.path.join(args.out, "program.npz"),
             instrs=np.asarray(p.instrs, dtype=np.int32),
             name=p.name, mem_size=p.mem_size, max_steps=p.max_steps,
             n_blocks=p.n_blocks,
             block_ids=np.asarray(p.block_ids, dtype=np.int64))
    with open(os.path.join(args.out, "seed"), "wb") as f:
        f.write(t.seed)
    with open(os.path.join(args.out, "crash"), "wb") as f:
        f.write(t.crash)
    with open(os.path.join(args.out, "grammar.json"), "w",
              encoding="utf-8") as f:
        f.write(t.grammar.to_json())
    report = certify_zoo(args.name)
    with open(os.path.join(args.out, "certificate.json"), "w",
              encoding="utf-8") as f:
        json.dump(report, f, indent=2)
    print(f"{t.name} -> {args.out} (certified: "
          f"{report['certified']})")
    return 0 if report["certified"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="kb-zoo", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list", help="families, knobs, gated instances")
    c = sub.add_parser("certify", help="certify zoo instances")
    c.add_argument("names", nargs="*",
                   help="zoo:... names (default: the gated set)")
    c.add_argument("--json", action="store_true")
    g = sub.add_parser("generate", help="materialize one instance")
    g.add_argument("name", help="zoo:family:k=v,... instance name")
    g.add_argument("--out", required=True, help="output directory")
    args = ap.parse_args(argv)
    return {"list": _cmd_list, "certify": _cmd_certify,
            "generate": _cmd_generate}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
