"""minimize — greedy edge-cover working-set selection.

Parity with the reference manager's minimizer
(python/manager/controller/Minimize.py:10-40, SURVEY §2.8): given the
deterministic edge sets of a corpus (tracer output files), repeatedly
pick the input covering the most still-uncovered edges until no input
adds coverage. The survivors are the minimized working set.

Usage:
    python -m killerbeez_tpu.tools.minimize -o keep.txt \
        edges/input_a.txt edges/input_b.txt ...
"""

from __future__ import annotations

import argparse
import sys
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..utils.logging import INFO_MSG, setup_logging
from .tracer import read_edge_file


def greedy_edge_cover(edge_sets: Dict[str, Set[int]]) -> List[str]:
    """Greedy set cover: returns the chosen keys in pick order.
    Deterministic: ties break on the lexically smallest key."""
    uncovered: Set[int] = set()
    for edges in edge_sets.values():
        uncovered |= edges
    chosen: List[str] = []
    remaining = dict(edge_sets)
    while uncovered and remaining:
        best_key, best_gain = None, 0
        for key in sorted(remaining):
            gain = len(remaining[key] & uncovered)
            if gain > best_gain:
                best_key, best_gain = key, gain
        if best_key is None:
            break
        chosen.append(best_key)
        uncovered -= remaining.pop(best_key)
    return chosen


def minimize_edge_files(paths: Iterable[str],
                        pairs: bool = False) -> Tuple[List[str], int]:
    """Greedy cover over tracer files; returns (kept paths, total
    distinct edges covered).  ``pairs=True`` reads the reference's
    from:to record format (tracer -f pairs) instead of slot:count."""
    if pairs:
        from .tracer import read_pair_file
        edge_sets = {p: read_pair_file(p) for p in paths}
    else:
        edge_sets = {p: set(read_edge_file(p).keys()) for p in paths}
    kept = greedy_edge_cover(edge_sets)
    covered = set().union(*(edge_sets[k] for k in kept)) if kept else set()
    return kept, len(covered)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="killerbeez-tpu-minimize",
        description="select a minimal working set by greedy edge cover")
    p.add_argument("edge_files", nargs="+",
                   help="tracer edge files, one per corpus input")
    p.add_argument("-o", "--output",
                   help="write kept file names here (default stdout)")
    p.add_argument("-p", "--pairs", action="store_true",
                   help="edge files are from:to pair records "
                        "(tracer -f pairs) instead of slot:count")
    p.add_argument("-l", "--logging-options", help="logging JSON options")
    args = p.parse_args(argv)
    try:
        setup_logging(args.logging_options)
        kept, covered = minimize_edge_files(args.edge_files, args.pairs)
        text = "".join(f"{k}\n" for k in kept)
        if args.output:
            from ..utils.fileio import write_buffer_to_file
            write_buffer_to_file(args.output, text.encode())
        else:
            sys.stdout.write(text)
        INFO_MSG("kept %d of %d inputs covering %d edges",
                 len(kept), len(args.edge_files), covered)
        return 0
    except (ValueError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
