"""picker — target determinism analysis + ignore-byte mask derivation.

Parity with the reference picker tool (picker/main.c:163-282,
SURVEY §2.7): run each seed ``-n`` times, classify the target's
coverage behavior (no-path / single-path / path-per-file /
multi-path-same-file) and emit a mask of bitmap bytes that vary across
repeated runs of the SAME input — the nondeterministic bytes an afl
instrumentation should exclude from novelty
(``{"ignore_bytes_file": ...}``).

The mask derivation is a pure array reduction (byte-wise variance
across [seeds, runs, MAP_SIZE]) — the reference's per-byte comparison
loops collapse into one vectorized pass.

Usage:
    python -m killerbeez_tpu.tools.picker file afl \
        -d '{"path": "corpus/build/test", "arguments": "@@"}' \
        -o mask.json seeds/a.bin seeds/b.bin
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

import numpy as np

from ..ops.coverage import COUNT_CLASS_LOOKUP
from ..drivers.factory import driver_factory
from ..instrumentation.factory import instrumentation_factory
from ..utils.fileio import read_file, write_buffer_to_file
from ..utils.logging import INFO_MSG, setup_logging
from ..utils.serialization import encode_array

CLASS_NO_PATH = "no_path"
CLASS_SINGLE_PATH = "single_path"
CLASS_PATH_PER_FILE = "path_per_file"
CLASS_MULTI_PATH_SAME_FILE = "multi_path_same_file"


def collect_traces(driver, instrumentation, seeds: List[bytes],
                   num_iterations: int = 5) -> np.ndarray:
    """uint8[n_seeds, n_runs, MAP_SIZE] of classified bitmaps.

    The seeds x N-runs matrix executes as ONE batch through the C
    exec backend when the driver can describe a host-exec spec
    (stdin/file targets — the reference picker's nested loops,
    picker/main.c:163-227, collapsed into a single dispatch across
    the instance pool); other drivers fall back to per-exec calls."""
    if not hasattr(instrumentation, "last_trace"):
        raise ValueError(
            f"{instrumentation.name} does not expose raw bitmaps "
            "(picker needs an afl-style instrumentation)")
    batched = _collect_batched(driver, instrumentation, seeds,
                               num_iterations)
    if batched is not None:
        return batched
    rows = []
    for seed in seeds:
        runs = []
        for _ in range(num_iterations):
            driver.test_input(seed)
            trace = instrumentation.last_trace()
            if trace is None:
                raise ValueError("target produced no bitmap")
            runs.append(COUNT_CLASS_LOOKUP[trace])
        rows.append(np.stack(runs))
    return np.stack(rows)


def _collect_batched(driver, instrumentation, seeds: List[bytes],
                     num_iterations: int):
    """One exec-backend batch for the whole seeds x runs matrix;
    None when this driver/instrumentation pair can't batch host
    execs (network drivers, device backends)."""
    try:
        spec = driver._host_exec_spec()
        instrumentation.prepare_host(**spec)
        target = instrumentation._target
    except (NotImplementedError, AttributeError, KeyError):
        return None
    if target is None or not hasattr(target, "run_batch"):
        return None
    # determinism analysis must run every repeat of a seed through
    # ONE forkserver instance: across pool workers, address-space
    # differences would read as target nondeterminism (the reference
    # picker is likewise single-instance)
    if hasattr(target, "targets"):
        target = target.targets[0]
    from ..mutators.base import pack_byte_rows
    inputs, lens = pack_byte_rows(
        [s for s in seeds for _ in range(num_iterations)])
    _, bitmaps = target.run_batch(inputs, lens, want_bitmaps=True)
    if bitmaps is None:
        return None
    cls = COUNT_CLASS_LOOKUP[bitmaps]
    return cls.reshape(len(seeds), num_iterations, -1)


def derive_ignore_mask(traces: np.ndarray) -> np.ndarray:
    """Bytes that differ across repeated runs of the same seed
    (uint8[MAP_SIZE], 1 = nondeterministic -> ignore)."""
    varies = (traces != traces[:, :1, :]).any(axis=(0, 1))
    return varies.astype(np.uint8)


def classify_target(traces: np.ndarray) -> str:
    """Reference picker's 4-way module classification
    (picker/main.c:163-227), applied to the whole target."""
    if not traces.any():
        return CLASS_NO_PATH
    stable = not (traces != traces[:, :1, :]).any()
    per_seed = traces[:, 0, :]
    all_same_across_seeds = bool(
        (per_seed == per_seed[:1]).all()) if len(per_seed) > 1 else True
    if not stable:
        return CLASS_MULTI_PATH_SAME_FILE
    if all_same_across_seeds:
        return CLASS_SINGLE_PATH
    return CLASS_PATH_PER_FILE


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="killerbeez-tpu-picker",
        description="classify target determinism and derive novelty "
                    "ignore masks")
    p.add_argument("driver", help="driver name (file, stdin, ...)")
    p.add_argument("instrumentation",
                   help="instrumentation name (afl, ...)")
    p.add_argument("seeds", nargs="+", help="seed input files")
    p.add_argument("-n", "--iterations", type=int, default=5,
                   help="runs per seed (default 5)")
    p.add_argument("-d", "--driver-options", help="driver JSON options")
    p.add_argument("-i", "--instrumentation-options",
                   help="instrumentation JSON options")
    p.add_argument("-o", "--output", required=True,
                   help="JSON report path ({classification, "
                        "ignore_bytes, nondeterministic_bytes})")
    p.add_argument("-l", "--logging-options", help="logging JSON options")
    args = p.parse_args(argv)
    try:
        setup_logging(args.logging_options)
        # device KBVM targets report raw bitmaps only with edge
        # recording on (jit_harness.last_trace; same forcing as
        # showmap/tracer — a no-op for host instrumentations)
        from .tracer import force_edges_option
        instrumentation = instrumentation_factory(
            args.instrumentation,
            force_edges_option(args.instrumentation_options))
        driver = driver_factory(args.driver, args.driver_options,
                                instrumentation, None)
        seeds = [read_file(s) for s in args.seeds]
        traces = collect_traces(driver, instrumentation, seeds,
                                args.iterations)
        mask = derive_ignore_mask(traces)
        report: Dict[str, object] = {
            "classification": classify_target(traces),
            "nondeterministic_bytes": int(mask.sum()),
            "ignore_bytes": encode_array(mask),
        }
        # KBVM targets carry an exact static universe: report it next
        # to the dynamic determinism analysis (a single_path verdict
        # over 3% of the static universe reads very differently from
        # one over 80%)
        program = getattr(instrumentation, "program", None)
        if program is not None:
            from ..analysis import build_cfg
            from ..analysis.lint import universe_stats
            report["static"] = universe_stats(program,
                                              build_cfg(program))
        # stateful session tier: the seeds' state x edge signatures
        # (which protocol states each seed drives and what it covers
        # from them) — the state-aware twin of the static section
        sig_fn = getattr(instrumentation, "state_signature", None)
        spec = getattr(instrumentation, "stateful_spec", None)
        if sig_fn is not None and spec is not None:
            per_seed = [sig_fn(s) for s in seeds]
            all_pairs = sorted({tuple(p) for sig in per_seed
                                for p in (sig or [])})
            report["state"] = {
                "n_states": int(spec.n_states),
                "m_max": int(spec.m_max),
                "state_reg": int(spec.state_reg),
                "states_reached": sorted({p[0] for p in all_pairs}),
                "pairs": [list(p) for p in all_pairs],
                "per_seed": [{"file": f, "pairs": sig or []}
                             for f, sig in zip(args.seeds, per_seed)],
            }
        # per-module report (reference picker/main.c:163-282 walks
        # modules): classification + partition-LOCAL ignore mask per
        # module; the top-level full-map mask stays the
        # ignore_bytes_file consumption format
        ranges = instrumentation.module_map_ranges()
        if ranges and len(ranges) > 1:  # single module: the top-level
            # fields ARE the per-module report; don't duplicate 64KB
            mods: Dict[str, object] = {}
            for name, lo, hi in ranges:
                sub = traces[:, :, lo:hi]
                sub_mask = derive_ignore_mask(sub)
                mods[name] = {
                    "classification": classify_target(sub),
                    "nondeterministic_bytes": int(sub_mask.sum()),
                    "ignore_bytes": encode_array(sub_mask),
                    "range": [int(lo), int(hi)],
                }
            report["modules"] = mods
        write_buffer_to_file(args.output,
                             json.dumps(report).encode())
        INFO_MSG("target is %s; %d nondeterministic bitmap bytes -> %s",
                 report["classification"],
                 report["nondeterministic_bytes"], args.output)
        for name, m in (report.get("modules") or {}).items():
            INFO_MSG("  module %s: %s, %d nondeterministic bytes",
                     name, m["classification"],
                     m["nondeterministic_bytes"])
        driver.cleanup()
        instrumentation.cleanup()
        return 0
    except (ValueError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
