"""kb-repair — counterexample-guided proxy conformance repair.

Consumes a campaign's accumulated ``kbz-proxy-gap-v1`` reports
(``<output>/proxy_gaps/``), localizes each divergence cluster to the
guard it indicts, searches the bounded typed patch space, and emits
either a VERIFIED patched proxy or an honest ``unrepairable`` verdict
with a machine-readable reason (docs/ANALYSIS.md, "Conformance &
repair").

Usage:
    kb-repair --binding test_safe --gaps-dir out/proxy_gaps
    kb-repair ... --json                # machine-readable result
    kb-repair ... --apply               # save the patched .npz,
                                        #   install <name>+repaired
                                        #   (re-certified), write the
                                        #   repair ledger
    kb-repair ... --require-repaired    # exit 1 unless repaired
    kb-repair --binding test_safe --probe --gaps-dir d
                                        # generate the gap corpus by
                                        #   probing BOTH tiers with
                                        #   solver witnesses (needs
                                        #   the native substrate)

Exit codes: 0 done; 1 ``--require-repaired`` unmet; 2 usage or
substrate error (unknown binding, native tier unavailable for
``--probe``/``--apply`` re-certification).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
from typing import List, Optional

from ..analysis.repair import (
    run_repair, save_patched_program, write_repair_ledger,
)


def _probe(binding, gaps_dir: str, repeats: int = 3) -> int:
    """Mint gap reports by probing both tiers with solver-synthesized
    must-crash witnesses (+ benign seed + crash seeds).  Divergences
    land through the same GapIndex path the campaign bridge uses;
    agreements write nothing.  Returns the number of gap reports."""
    import numpy as np

    from .. import FUZZ_CRASH, FUZZ_HANG
    from ..analysis.dataflow import analyze_dataflow
    from ..analysis.solver import solve_edge
    from ..hybrid.gaps import GapIndex, make_gap_report, \
        proxy_trace_edge
    from ..hybrid.registry import proxy_verdict
    from ..hybrid.validate import NativeValidator, ValidationItem

    program = binding.program()
    df = analyze_dataflow(program)
    probes: List[bytes] = [bytes(binding.benign_seed)]
    probes += [bytes(s) for s in binding.crash_seeds]
    ef = np.asarray(program.edge_from)
    et = np.asarray(program.edge_to)
    for i in range(len(ef)):
        if int(et[i]) not in df.must_crash_blocks:
            continue
        res = solve_edge(program, (int(ef[i]), int(et[i])))
        if res.status == "solved" and res.input is not None:
            probes.append(res.input)
            # variants: same guard, distinct inputs — a CLUSTER of
            # counterexamples, not a single sample
            probes.append(res.input + b"xx")
            probes.append(res.input + b"\x00\x01")
    validator = NativeValidator(binding, repeats=repeats)
    index = GapIndex(gaps_dir)
    n = 0
    seen = set()
    try:
        for buf in probes:
            if buf in seen:
                continue
            seen.add(buf)
            status = proxy_verdict(binding, buf)
            if status not in (FUZZ_CRASH, FUZZ_HANG):
                continue            # proxy-benign: nothing to claim
            kind = "crash" if status == FUZZ_CRASH else "hang"
            md5 = hashlib.md5(buf).hexdigest()
            result = validator.validate(
                ValidationItem(kind, buf, md5, proxy_status=status))
            if result["verdict"] != "proxy_only":
                continue
            report = make_gap_report(
                md5=md5, kind=kind, binding=binding.name,
                proxy_target=binding.proxy_target,
                proxy_status=status,
                native_argv=binding.native.argv,
                native_delivery=binding.native.delivery,
                statuses=result.get("statuses", []),
                repro=result.get("repro", 0),
                repeats=result.get("repeats", 0),
                t=result.get("t"),
                input_bytes=buf,
                edge=proxy_trace_edge(program, buf))
            if index.admit(report):
                n += 1
    finally:
        validator.close()
    return n


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="kb-repair",
        description="counterexample-guided proxy repair over "
                    "accumulated kbz-proxy-gap-v1 reports")
    p.add_argument("--binding", required=True,
                   help="proxy binding name (hybrid registry)")
    p.add_argument("--gaps-dir", required=True,
                   help="the campaign's proxy_gaps/ directory")
    p.add_argument("--json", action="store_true",
                   help="machine-readable kbz-proxy-repair-v1 result "
                        "on stdout")
    p.add_argument("--apply", action="store_true",
                   help="on a repaired verdict: save the patched "
                        ".npz, install the re-certified "
                        "<binding>+repaired binding, and write the "
                        "repair ledger (unrepairable/no-gaps runs "
                        "write only the ledger)")
    p.add_argument("--out",
                   help="patched program path for --apply (default "
                        "<gaps-dir>/repaired_<binding>.npz)")
    p.add_argument("--require-repaired", action="store_true",
                   help="exit 1 unless the verdict is 'repaired' "
                        "(CI conformance gate)")
    p.add_argument("--probe", action="store_true",
                   help="FIRST mint gap reports by probing both "
                        "tiers with solver witnesses (requires the "
                        "native substrate)")
    p.add_argument("--repeats", type=int, default=3,
                   help="native replays per probe input (default 3)")
    args = p.parse_args(argv)

    from ..hybrid.registry import get_binding
    try:
        binding = get_binding(args.binding)
    except KeyError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.probe:
        from ..native.build import build_error, native_available
        exe = binding.native.argv[0]
        if not native_available():
            print(f"error: --probe needs the native tier: "
                  f"{build_error()}", file=sys.stderr)
            return 2
        if not os.path.exists(exe):
            print(f"error: --probe needs the native binary: {exe} "
                  f"(make -C corpus)", file=sys.stderr)
            return 2
        n = _probe(binding, args.gaps_dir, repeats=args.repeats)
        if not args.json:
            print(f"probe: {n} gap report(s) in {args.gaps_dir}")

    result, patched = run_repair(binding, args.gaps_dir)

    if args.apply:
        write_repair_ledger(args.gaps_dir, result)
        if patched is not None:
            out = args.out or os.path.join(
                args.gaps_dir, f"repaired_{binding.name}.npz")
            save_patched_program(patched, out)
            result["program_file"] = out
            from ..hybrid.registry import (
                CertificationError, install_repaired,
            )
            try:
                installed = install_repaired(binding, out)
                result["installed"] = installed.name
            except CertificationError as e:
                # honesty: a patch the native tier refuses to
                # re-certify is NOT a repair
                result["status"] = "unrepairable"
                result["reason"] = f"recertify:{e}"
                result["installed"] = None
                patched = None

    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print(f"{binding.name}: {result['status']}"
              + (f" ({result['reason']})" if result.get("reason")
                 else ""))
        for crec in result.get("clusters") or []:
            blame = crec.get("blame") or {}
            print(f"  edge {crec.get('edge')} "
                  f"{crec.get('proxy_cls')}->{crec.get('native_cls')}"
                  f" [{len(crec.get('inputs') or [])} input(s)]: "
                  f"{crec['status']}"
                  + (f" blame pc {blame.get('pc')} "
                     f"cmp {blame.get('cmp')}" if blame else "")
                  + (f" patch {crec.get('patch_desc')}"
                     if crec.get("patch_desc") else "")
                  + (f" reason {crec.get('reason')}"
                     if crec.get("reason") else ""))

    if args.require_repaired and result["status"] != "repaired":
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
