"""kb-descend — gradient-guided search over the solver-unknown frontier.

The standalone face of ``search/descent.py``: run the exact solver
over a target's static universe, take the edges it honestly reports
``unknown`` (checksum loops, deep loop-carried state), and descend
their branch distances on device — seeded from the solver's own
solved witnesses, chained so each cracked edge's witness seeds the
deeper ones.  Every reported witness is concretely verified to
traverse its edge (the same honesty contract as kb-solve).

Usage:
    kb-descend imgparse_vm                    # the whole unknown set
    kb-descend tlvstack_vm --edge 12:13       # one edge
    kb-descend imgparse_vm --json --budget 24
    kb-descend imgparse_vm --require-cracked 8   # CI gate
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Tuple

import numpy as np

from ..analysis.solver import solve_edge, unknown_kind
from ..search import (
    DEFAULT_DESCENT_BUDGET, DEFAULT_LANES, DEFAULT_SCAN_ITERS,
    descend_edge, descend_edge_device, seeds_reaching_block,
)
from .solve_tool import _load_program, _parse_edge

#: chained escalation passes: a cracked edge's witness re-seeds the
#: edges still pending (deep frontiers unlock level by level)
DEFAULT_ROUNDS = 3


def descend_report(program, edges: List[Tuple[int, int]],
                   seeds: List[bytes], *, budget: int, lanes: int,
                   rounds: int, intake: dict,
                   engine: str = "device",
                   scan_iters: int = DEFAULT_SCAN_ITERS) -> dict:
    """Chained descent over ``edges``; the report carries per-round
    device-dispatch and candidate-evaluation counts (the
    machine-readable denominator the bench wall-clock gate divides
    by) alongside the per-edge verdicts."""
    out = {"target": program.name, "edges": {}, "cracked": 0,
           "exhausted": 0, "intake": intake, "engine": engine,
           "scan_iters": (scan_iters if engine == "device" else 1),
           "rounds": [], "dispatches": 0, "evals": 0}
    pending = list(edges)
    results = {}
    traces: dict = {}       # one reference replay per seed, shared
    for rnd in range(max(rounds, 1)):
        nxt = []
        r_disp = r_evals = r_cracked = 0
        for e in pending:
            se = seeds_reaching_block(program, seeds, e[0], cap=24,
                                      trace_cache=traces) \
                or seeds[:16]
            if engine == "device":
                r = descend_edge_device(program, e, se or [b"\x00"],
                                        budget=budget, lanes=lanes,
                                        scan_iters=scan_iters,
                                        trace_cache=traces)
            else:
                r = descend_edge(program, e, se or [b"\x00"],
                                 budget=budget, lanes=lanes,
                                 trace_cache=traces)
            results[e] = r
            r_disp += int(r.dispatches)
            r_evals += int(r.evals)
            if r.status == "descended":
                seeds.append(r.input)
                r_cracked += 1
            else:
                nxt.append(e)
        out["rounds"].append({"round": rnd, "attempted": len(pending),
                              "cracked": r_cracked,
                              "dispatches": r_disp, "evals": r_evals})
        out["dispatches"] += r_disp
        out["evals"] += r_evals
        if not nxt or len(nxt) == len(pending):
            break
        pending = nxt
    for e in edges:
        r = results[e]
        out["edges"][f"{e[0]}:{e[1]}"] = r.as_dict()
        out["cracked" if r.status == "descended" else "exhausted"] += 1
    return out


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="kb-descend",
        description="branch-distance descent over the edges the "
                    "exact solver reports unknown (search/descent.py)")
    p.add_argument("target", nargs="?",
                   help="built-in target name (kb-lint lists them)")
    p.add_argument("--program-file",
                   help="compiled .npz program instead of a built-in")
    p.add_argument("--edge", action="append", type=_parse_edge,
                   metavar="F:T",
                   help="edge to descend as from:to block indices; "
                        "repeatable; default = every edge the solver "
                        "returns unknown on")
    p.add_argument("--block", type=int,
                   help="descend every unknown edge INTO this block")
    p.add_argument("--budget", type=int,
                   default=DEFAULT_DESCENT_BUDGET,
                   help="device dispatches per edge per round "
                        f"(default {DEFAULT_DESCENT_BUDGET})")
    p.add_argument("--lanes", type=int, default=DEFAULT_LANES,
                   help="candidate lanes per dispatch "
                        f"(default {DEFAULT_LANES})")
    p.add_argument("--engine", choices=("device", "host"),
                   default="device",
                   help="descent engine: 'device' (default) runs R "
                        "iterations per dispatch in one lax.scan "
                        "with input-to-state operand matching "
                        "(stands down to host per edge when "
                        "needed); 'host' forces the host-driven "
                        "engine")
    p.add_argument("--scan-iters", type=int,
                   default=DEFAULT_SCAN_ITERS, metavar="R",
                   help="device engine: iterations fused per "
                        f"dispatch (default {DEFAULT_SCAN_ITERS})")
    p.add_argument("--rounds", type=int, default=DEFAULT_ROUNDS,
                   help="chained escalation passes (a cracked edge's "
                        "witness seeds the rest; default "
                        f"{DEFAULT_ROUNDS})")
    p.add_argument("--seed-file", action="append", default=[],
                   help="extra population seed file (repeatable); "
                        "solver witnesses always ride along")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    p.add_argument("--require-cracked", type=int, metavar="N",
                   help="exit 1 unless at least N edges produced a "
                        "verified witness (the CI floor on the "
                        "checksum universes the exact solver provably "
                        "cannot solve)")
    args = p.parse_args(argv)
    try:
        program = _load_program(args)
    except (ValueError, FileNotFoundError, KeyError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    # intake: the exact solver runs first — descent only ever works
    # the frontier the exact tier could not crack
    universe = [(int(f), int(t)) for f, t in
                zip(np.asarray(program.edge_from),
                    np.asarray(program.edge_to))]
    seeds: List[bytes] = []
    unknown: List[Tuple[int, int]] = []
    intake = {"solved": 0, "unsat": 0, "unknown": 0,
              "unknown_kinds": {}}
    for e in universe:
        r = solve_edge(program, e)
        intake[r.status] += 1
        if r.status == "solved":
            seeds.append(r.input)
        elif r.status == "unknown":
            unknown.append(e)
            k = unknown_kind(r.reason)
            intake["unknown_kinds"][k] = \
                intake["unknown_kinds"].get(k, 0) + 1
    seeds = list(dict.fromkeys(seeds))
    for path in args.seed_file:
        try:
            with open(path, "rb") as f:
                seeds.append(f.read())
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    edges = list(args.edge or [])
    if args.block is not None:
        edges += [e for e in unknown if e[1] == args.block]
    if not edges:
        edges = list(unknown)
    edges = list(dict.fromkeys(edges))
    if not edges:
        print(f"{program.name}: the exact solver left no unknown "
              f"edges — nothing to descend")
        return 0

    rep = descend_report(program, edges, seeds, budget=args.budget,
                         lanes=args.lanes, rounds=args.rounds,
                         intake=intake, engine=args.engine,
                         scan_iters=args.scan_iters)
    ok = (args.require_cracked is None
          or rep["cracked"] >= args.require_cracked)

    if args.json:
        if args.require_cracked is not None:
            rep["require_cracked"] = args.require_cracked
            rep["require_met"] = ok
        print(json.dumps(rep, indent=2))
    else:
        print(f"{program.name}: {len(edges)} edge(s) beyond the "
              f"solver ceiling — {rep['cracked']} cracked, "
              f"{rep['exhausted']} exhausted "
              f"({rep['engine']} engine, {rep['dispatches']} "
              f"dispatches / {rep['evals']} evals; "
              f"intake: {intake['solved']} solved / "
              f"{intake['unknown']} unknown / {intake['unsat']} unsat)")
        for key, d in rep["edges"].items():
            if d["status"] == "descended":
                buf = bytes.fromhex(d["input_hex"])
                soft = " [soft-grad]" if d.get("soft_used") else ""
                soft += " [i2s]" if d.get("i2s") else ""
                print(f"  {key}: cracked in {d['steps']} iterations"
                      f" ({d.get('dispatches', d['steps'])} "
                      f"dispatches){soft} len={d['length']} {buf!r}")
            else:
                bd = d.get("best_dist")
                print(f"  {key}: exhausted ({d['steps']} iterations"
                      f" / {d.get('dispatches', d['steps'])} "
                      f"dispatches, best distance "
                      f"{'unreached' if bd is None else bd})")
        if args.require_cracked is not None and not ok:
            print(f"FAIL: {rep['cracked']} cracked < required "
                  f"{args.require_cracked}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
