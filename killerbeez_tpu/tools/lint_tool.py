"""kb-lint — static analysis lint over KBVM programs.

Runs the analysis subsystem (``killerbeez_tpu/analysis/``) over
built-in targets and/or compiled ``.npz`` programs and reports
defects: unreachable blocks, AFL map-slot collisions, duplicate
coverage ids, empty modules, ``max_steps`` shortfalls, statically
dead and must-crash blocks.  Exit code 1 when any error-severity
finding exists (the CI lint lane gates on this), else 0.

Usage:
    kb-lint                       # all built-in targets
    kb-lint tlvstack_vm test      # specific targets
    kb-lint --program-file p.npz  # a compiled program
    kb-lint --json                # machine-readable report
    kb-lint --dict tlvstack_vm    # print the auto-dictionary too
    kb-lint --vsa                 # + value-set checks (infeasible-
                                  # edge, value-range-contradiction,
                                  # guaranteed-oob-store)
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

from ..analysis import (
    analyze_dataflow, build_cfg, extract_dictionary, lint_program,
)
from ..analysis.lint import SEV_ERROR, SEV_WARNING, universe_stats


def _target_location(name) -> Dict:
    """Repo-relative source location of a built-in target's builder —
    GitHub's SARIF ingestion renders results only through a
    physicalLocation, so findings anchor on the target definition."""
    import inspect
    import os
    from ..models import targets
    try:
        fn = targets._REGISTRY[name]
        path = inspect.getsourcefile(fn)
        _, line = inspect.getsourcelines(fn)
        return {"uri": os.path.relpath(path).replace(os.sep, "/"),
                "line": int(line)}
    except (KeyError, OSError, TypeError, ValueError):
        return {"uri": f"kbvm/{name}", "line": 1}


def _load_programs(args) -> List:
    """[(program, sarif location)] for every requested target."""
    # import both registries: targets_cgc registers on import
    from ..models import targets, targets_cgc  # noqa: F401
    import os

    names = list(args.targets)
    if args.all_targets or (not names and not args.program_file
                            and not args.gaps_dir):
        names = targets.target_names()
    progs = []
    for name in names:
        progs.append((targets.get_target(name),
                      _target_location(name)))
    for path in args.program_file or []:
        progs.append((targets.load_program_from_options(
            {"program_file": path}, "program_file missing"),
            {"uri": os.path.relpath(path).replace(os.sep, "/"),
             "line": 1}))
    return progs


def lint_report(program, want_dict: bool = False,
                want_vsa: bool = False) -> Dict:
    """One target's full report (the --json per-target payload).
    Stateful targets (registered in models.targets_stateful) get the
    session-tier checks automatically: state-unreachable /
    state-clip warnings and the dead-block -> session-only-block
    downgrade.  ``want_vsa`` runs the value-set fixpoint, enables
    the infeasible-edge / value-range-contradiction /
    guaranteed-oob-store checks, and adds a ``vsa`` stats section
    mirroring ``stats``; off (the default) the report is
    bit-identical to the pre-VSA tool."""
    from ..models.targets_stateful import get_stateful_spec
    cfg = build_cfg(program)
    df = analyze_dataflow(program)
    vsa = None
    if want_vsa:
        from ..analysis.vsa import analyze_vsa
        vsa = analyze_vsa(program)
    findings = lint_program(program, cfg, df,
                            stateful=get_stateful_spec(program.name),
                            vsa=vsa)
    rep = {
        "stats": universe_stats(program, cfg),
        "findings": [f.as_dict() for f in findings],
        "errors": sum(f.severity == SEV_ERROR for f in findings),
        "warnings": sum(f.severity == SEV_WARNING for f in findings),
    }
    if vsa is not None:
        from ..analysis.vsa import vsa_stats
        rep["vsa"] = vsa_stats(vsa)
    if want_dict:
        rep["dictionary"] = [t.decode("latin-1")
                             for t in extract_dictionary(program, df)]
    return rep


def conformance_reports(gaps_dir: str, threshold: int
                        ) -> Dict[str, Dict]:
    """Conformance findings as per-BINDING pseudo-reports, so each
    SARIF result anchors a physicalLocation on that binding's proxy
    program source line (the same gap the original checks closed) —
    key ``conformance:<binding>`` -> {report, sarif location}."""
    from ..analysis.conformance import conformance_lint
    from ..hybrid.registry import get_binding

    findings = conformance_lint(gaps_dir, threshold)
    by_binding: Dict[str, List] = {}
    for f in findings:
        by_binding.setdefault(
            f.data.get("binding") or "?", []).append(f)
    out: Dict[str, Dict] = {}
    for binding, fs in sorted(by_binding.items()):
        loc = {"uri": f"kbvm/{binding}", "line": 1}
        try:
            loc = _target_location(
                get_binding(binding).proxy_target)
        except Exception:
            pass                    # unknown binding: logical anchor
        out[f"conformance:{binding}"] = {
            "report": {
                "findings": [f.as_dict() for f in fs],
                "errors": sum(f.severity == SEV_ERROR for f in fs),
                "warnings": sum(f.severity == SEV_WARNING
                                for f in fs),
            },
            "location": loc,
        }
    return out


_SARIF_LEVELS = {"error": "error", "warning": "warning", "info": "note"}


def sarif_report(reports: Dict,
                 locations: Optional[Dict[str, Dict]] = None) -> Dict:
    """SARIF 2.1.0 document over per-target reports — one rule per
    check id, one result per finding.  Each result carries BOTH a
    logical location addressing ``<target>:pc<N>`` (KBVM programs
    have no per-pc source) and a physical location anchored on the
    target's builder source (``locations``: report key -> {uri,
    line}) — GitHub's SARIF ingestion requires the physical location
    to render PR annotations at all."""
    locations = locations or {}
    rules: Dict[str, Dict] = {}
    results = []
    for name, rep in reports.items():
        phys = locations.get(name, {"uri": f"kbvm/{name}", "line": 1})
        for f in rep["findings"]:
            code = f["code"]
            if code not in rules:
                rules[code] = {
                    "id": code,
                    "shortDescription": {"text": code},
                    "defaultConfiguration": {
                        "level": _SARIF_LEVELS[f["severity"]]},
                }
            data = f.get("data", {})
            loc = name if "pc" not in data else f"{name}:pc{data['pc']}"
            results.append({
                "ruleId": code,
                "level": _SARIF_LEVELS[f["severity"]],
                "message": {"text": f["message"]},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {"uri": phys["uri"]},
                        "region": {"startLine": phys["line"]},
                    },
                    "logicalLocations": [{
                        "name": name,
                        "fullyQualifiedName": loc,
                        "kind": "module",
                    }],
                }],
                "properties": {"target": name, **data},
            })
    return {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "kb-lint",
                "informationUri":
                    "https://github.com/grimm-co/killerbeez",
                "rules": sorted(rules.values(),
                                key=lambda r: r["id"]),
            }},
            "results": results,
        }],
    }


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="kb-lint",
        description="static-analysis lint over KBVM programs "
                    "(CFG + dataflow defect checks)")
    p.add_argument("targets", nargs="*",
                   help="built-in target names (default: all)")
    p.add_argument("--all", action="store_true", dest="all_targets",
                   help="lint every built-in target (the default "
                        "when no names are given; explicit for CI)")
    p.add_argument("--program-file", action="append",
                   help="compiled .npz program (repeatable)")
    fmt = p.add_mutually_exclusive_group()
    fmt.add_argument("--json", action="store_true",
                     help="machine-readable report on stdout")
    fmt.add_argument("--sarif", action="store_true",
                     help="SARIF 2.1.0 report on stdout (one rule "
                          "per check id) — the CI lane uploads this "
                          "to annotate findings on PRs")
    p.add_argument("--dict", action="store_true", dest="want_dict",
                   help="include the extracted auto-dictionary")
    p.add_argument("--vsa", action="store_true", dest="want_vsa",
                   help="run the value-set fixpoint: enables the "
                        "infeasible-edge / value-range-contradiction"
                        " / guaranteed-oob-store checks and a 'vsa' "
                        "stats section in --json")
    p.add_argument("--gaps-dir",
                   help="a campaign's proxy_gaps/ directory: run the "
                        "conformance checks (proxy-gap-backlog, "
                        "conformance-drift) over its index + repair "
                        "ledger")
    p.add_argument("--gap-backlog", type=int, default=8,
                   help="unconsumed gap reports tolerated before "
                        "proxy-gap-backlog fires (default 8)")
    args = p.parse_args(argv)
    try:
        progs = _load_programs(args)
    except (ValueError, FileNotFoundError, KeyError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    reports = {}
    locs = {}
    errors = warnings = 0
    for prog, loc in progs:
        rep = lint_report(prog, want_dict=args.want_dict,
                          want_vsa=args.want_vsa)
        key, n = prog.name, 2
        while key in reports:           # same-named programs must not
            key = f"{prog.name}#{n}"    # overwrite each other
            n += 1
        reports[key] = rep
        locs[key] = loc
        errors += rep["errors"]
        warnings += rep["warnings"]

    if args.gaps_dir:
        for key, ent in conformance_reports(
                args.gaps_dir, args.gap_backlog).items():
            reports[key] = ent["report"]
            locs[key] = ent["location"]
            errors += ent["report"]["errors"]
            warnings += ent["report"]["warnings"]

    if args.json:
        print(json.dumps({"targets": reports, "errors": errors,
                          "warnings": warnings}, indent=2))
        return 1 if errors else 0

    if args.sarif:
        print(json.dumps(sarif_report(reports, locs), indent=2))
        return 1 if errors else 0

    for name, rep in reports.items():
        s = rep.get("stats")
        if s is None:               # conformance pseudo-reports
            print(f"{name}:")
        else:
            print(f"{name}: {s['n_blocks']} blocks, {s['n_edges']} "
                  f"edges ({s['n_slots']} slots, {s['n_modules']} "
                  f"module(s)), longest loop-free path "
                  f"{s['longest_acyclic_path']} of max_steps "
                  f"{s['max_steps']}")
        for f in rep["findings"]:
            print(f"  {f['severity']}: [{f['code']}] {f['message']}")
        if args.want_dict:
            toks = ", ".join(repr(t.encode('latin-1'))
                             for t in rep["dictionary"][:16])
            print(f"  dictionary ({len(rep['dictionary'])} tokens): "
                  f"{toks}")
    total = f"{errors} error(s), {warnings} warning(s) across " \
            f"{len(reports)} program(s)"
    print(total if errors or warnings else f"clean: {total}")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
