"""kb-lint — static analysis lint over KBVM programs.

Runs the analysis subsystem (``killerbeez_tpu/analysis/``) over
built-in targets and/or compiled ``.npz`` programs and reports
defects: unreachable blocks, AFL map-slot collisions, duplicate
coverage ids, empty modules, ``max_steps`` shortfalls, statically
dead and must-crash blocks.  Exit code 1 when any error-severity
finding exists (the CI lint lane gates on this), else 0.

Usage:
    kb-lint                       # all built-in targets
    kb-lint tlvstack_vm test      # specific targets
    kb-lint --program-file p.npz  # a compiled program
    kb-lint --json                # machine-readable report
    kb-lint --dict tlvstack_vm    # print the auto-dictionary too
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

from ..analysis import (
    analyze_dataflow, build_cfg, extract_dictionary, lint_program,
)
from ..analysis.lint import SEV_ERROR, SEV_WARNING, universe_stats


def _load_programs(args) -> List:
    # import both registries: targets_cgc registers on import
    from ..models import targets, targets_cgc  # noqa: F401

    names = list(args.targets)
    if args.all_targets or (not names and not args.program_file):
        names = targets.target_names()
    progs = []
    for name in names:
        progs.append(targets.get_target(name))
    for path in args.program_file or []:
        progs.append(targets.load_program_from_options(
            {"program_file": path},
            "program_file missing"))
    return progs


def lint_report(program, want_dict: bool = False) -> Dict:
    """One target's full report (the --json per-target payload)."""
    cfg = build_cfg(program)
    df = analyze_dataflow(program)
    findings = lint_program(program, cfg, df)
    rep = {
        "stats": universe_stats(program, cfg),
        "findings": [f.as_dict() for f in findings],
        "errors": sum(f.severity == SEV_ERROR for f in findings),
        "warnings": sum(f.severity == SEV_WARNING for f in findings),
    }
    if want_dict:
        rep["dictionary"] = [t.decode("latin-1")
                             for t in extract_dictionary(program, df)]
    return rep


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="kb-lint",
        description="static-analysis lint over KBVM programs "
                    "(CFG + dataflow defect checks)")
    p.add_argument("targets", nargs="*",
                   help="built-in target names (default: all)")
    p.add_argument("--all", action="store_true", dest="all_targets",
                   help="lint every built-in target (the default "
                        "when no names are given; explicit for CI)")
    p.add_argument("--program-file", action="append",
                   help="compiled .npz program (repeatable)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    p.add_argument("--dict", action="store_true", dest="want_dict",
                   help="include the extracted auto-dictionary")
    args = p.parse_args(argv)
    try:
        progs = _load_programs(args)
    except (ValueError, FileNotFoundError, KeyError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    reports = {}
    errors = warnings = 0
    for prog in progs:
        rep = lint_report(prog, want_dict=args.want_dict)
        key, n = prog.name, 2
        while key in reports:           # same-named programs must not
            key = f"{prog.name}#{n}"    # overwrite each other
            n += 1
        reports[key] = rep
        errors += rep["errors"]
        warnings += rep["warnings"]

    if args.json:
        print(json.dumps({"targets": reports, "errors": errors,
                          "warnings": warnings}, indent=2))
        return 1 if errors else 0

    for name, rep in reports.items():
        s = rep["stats"]
        print(f"{name}: {s['n_blocks']} blocks, {s['n_edges']} edges "
              f"({s['n_slots']} slots, {s['n_modules']} module(s)), "
              f"longest loop-free path {s['longest_acyclic_path']} "
              f"of max_steps {s['max_steps']}")
        for f in rep["findings"]:
            print(f"  {f['severity']}: [{f['code']}] {f['message']}")
        if args.want_dict:
            toks = ", ".join(repr(t.encode('latin-1'))
                             for t in rep["dictionary"][:16])
            print(f"  dictionary ({len(rep['dictionary'])} tokens): "
                  f"{toks}")
    total = f"{errors} error(s), {warnings} warning(s) across " \
            f"{len(reports)} program(s)"
    print(total if errors or warnings else f"clean: {total}")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
