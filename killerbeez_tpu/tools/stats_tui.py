"""kb-stats — live campaign view (curses-free ANSI TUI).

Tails a campaign's ``stats.jsonl`` (written by the fuzzer's telemetry
sink) or polls a manager's ``/api/stats/<campaign>`` fleet endpoint,
and redraws one compact dashboard frame per interval: exec rates
(lifetime + EMA), finding counts, new-path rate, corpus size and the
pipeline stage-time split.  No curses dependency — plain ANSI cursor
control, so it works over any ssh/tmux and degrades to sequential
frames when piped (``--once`` prints a single frame and exits).

    kb-stats output/                         # local campaign
    kb-stats output/stats.jsonl --interval 2
    kb-stats --manager http://mgr:8650 --campaign 7   # fleet view
    kb-stats output/ --once --openmetrics    # Prometheus text format

``--once`` exits nonzero with a clear message when the campaign has
produced no stats yet, so scripts can gate on it.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from typing import Any, Dict, List, Optional

from ..telemetry.metrics import STAGES, percentiles_from_counts
from ..telemetry.openmetrics import render_snapshot
from ..telemetry.sink import read_latest_snapshot as read_local

BAR_W = 40


def read_manager(url: str, campaign: str) -> Optional[Dict[str, Any]]:
    """Merged fleet snapshot from the manager stats endpoint."""
    try:
        with urllib.request.urlopen(
                f"{url}/api/stats/{campaign}", timeout=10) as resp:
            body = json.loads(resp.read())
        merged = body.get("merged")
        if merged is not None:
            merged["_n_workers"] = body.get("n_workers", 0)
        return merged
    except (OSError, ValueError):
        return None


def _fmt_n(v: float) -> str:
    for unit, div in (("G", 1e9), ("M", 1e6), ("k", 1e3)):
        if abs(v) >= div:
            return f"{v / div:.2f}{unit}"
    return f"{v:.0f}" if v == int(v) else f"{v:.1f}"


def _bar(frac: float, width: int = BAR_W) -> str:
    n = int(round(max(0.0, min(1.0, frac)) * width))
    return "#" * n + "-" * (width - n)


def _fmt_secs(v: float) -> str:
    if v >= 1.0:
        return f"{v:.2f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.1f}ms"
    return f"{v * 1e6:.0f}us"


def _stage_percentiles(snap: Dict[str, Any],
                       stage: str) -> Dict[str, float]:
    """p50/p99 for one stage: read from the snapshot when present
    (new registries emit them), else re-derive from the bucket
    counts (old snapshots)."""
    h = snap.get("hists", {}).get(stage)
    if not h:
        return {}
    if "p50" in h:
        return h
    return percentiles_from_counts(h.get("counts", []))


def render(snap: Dict[str, Any]) -> str:
    """One dashboard frame as a plain string (ANSI-free: the caller
    owns cursor control, tests own assertions)."""
    c = snap.get("counters", {})
    g = snap.get("gauges", {})
    d = snap.get("derived", {})
    r = snap.get("rates", {})
    elapsed = float(snap.get("elapsed", 0.0))
    lines: List[str] = []
    head = "kb-stats — campaign telemetry"
    if "_n_workers" in snap:
        head += f" ({snap['_n_workers']} workers, merged)"
    lines.append(head)
    lines.append("=" * len(head))
    lines.append(
        f"  run time : {int(elapsed) // 3600:02d}:"
        f"{int(elapsed) % 3600 // 60:02d}:{int(elapsed) % 60:02d}"
        f"    execs : {_fmt_n(c.get('execs', 0))}")
    lines.append(
        f"  execs/s  : {_fmt_n(d.get('execs_per_sec', 0.0))} lifetime"
        f" | {_fmt_n(d.get('execs_per_sec_ema', 0.0))} recent")
    seen = g.get("corpus_seen", g.get("corpus_size", 0))
    lines.append(
        f"  paths    : {_fmt_n(c.get('new_paths', 0))} total"
        f" | {r.get('new_paths', {}).get('rate', 0.0):.2f}/s recent"
        f" | corpus {_fmt_n(seen)} seen")
    if "corpus_arms" in g or "corpus_favored" in g \
            or c.get("corpus_synced_in") or c.get("corpus_synced_out"):
        line = (f"  corpus   : {int(g.get('corpus_arms', 0))} arms"
                f" | {int(g.get('corpus_favored', 0))} favored")
        if c.get("corpus_synced_in") or c.get("corpus_synced_out"):
            line += (f" | synced {_fmt_n(c.get('corpus_synced_in', 0))}"
                     f" in / {_fmt_n(c.get('corpus_synced_out', 0))}"
                     " out")
        lines.append(line)
    if c.get("gossip_rounds") or c.get("sync_quarantined") \
            or c.get("peers_banned"):
        line = (f"  gossip   : "
                f"{_fmt_n(c.get('gossip_entries_in', 0))} in / "
                f"{_fmt_n(c.get('gossip_entries_out', 0))} out"
                f" | {int(g.get('gossip_peers', 0))} peers"
                f" | {_fmt_n(c.get('gossip_rounds', 0))} rounds")
        if c.get("sync_quarantined"):
            line += (f" | {_fmt_n(c.get('sync_quarantined', 0))} "
                     "quarantined")
        if c.get("peers_banned") or g.get("peers_banned_active"):
            line += (f" | {int(g.get('peers_banned_active', 0))} "
                     f"banned ({_fmt_n(c.get('peers_banned', 0))} "
                     "lifetime)")
        lines.append(line)
    if c.get("hybrid_validations") or g.get("validation_queue_depth"):
        line = (f"  hybrid   : "
                f"{_fmt_n(c.get('hybrid_validations', 0))} validated"
                f" | {_fmt_n(c.get('hybrid_confirmed', 0))} confirmed"
                f" / {_fmt_n(c.get('hybrid_proxy_only', 0))} "
                f"proxy-only"
                f" / {_fmt_n(c.get('hybrid_flaky', 0))} flaky"
                f" | queue {int(g.get('validation_queue_depth', 0))}")
        if c.get("hybrid_proxy_gaps"):
            line += (f" | {_fmt_n(c.get('hybrid_proxy_gaps', 0))} "
                     "gap reports")
        lines.append(line)
    if c.get("repair_attempts"):
        line = (f"  repair   : "
                f"{_fmt_n(c.get('repair_attempts', 0))} attempts"
                f" | {_fmt_n(c.get('repair_repaired', 0))} repaired"
                f" / {_fmt_n(c.get('repair_unrepairable', 0))} "
                "unrepairable")
        if c.get("repair_errors"):
            line += f" | {_fmt_n(c.get('repair_errors', 0))} errors"
        lines.append(line)
    if c.get("solver_attempts") or g.get("solver_frontier"):
        line = (f"  solver   : "
                f"{_fmt_n(c.get('solver_solved', 0))} solved"
                f" | {_fmt_n(c.get('solver_unsat', 0))} unsat"
                f" | {_fmt_n(c.get('solver_unknown', 0))} unknown"
                f" | {int(g.get('solver_frontier', 0))} frontier "
                f"pending")
        if c.get("solver_injected"):
            line += f" | {_fmt_n(c.get('solver_injected', 0))} injected"
        lines.append(line)
    if c.get("search_attempts") or c.get("search_i2s_matches") \
            or g.get("descent_iterations_per_dispatch"):
        line = (f"  descent  : "
                f"{_fmt_n(c.get('search_descended', 0))} descended"
                f" | {_fmt_n(c.get('search_exhausted', 0))} exhausted"
                f" | {_fmt_n(c.get('search_attempts', 0))} attempts")
        if g.get("descent_iterations_per_dispatch"):
            line += (f" | {int(g.get('descent_iterations_per_dispatch', 0))} "
                     "iters/dispatch (device-resident)")
        if c.get("search_i2s_matches"):
            line += (f" | {_fmt_n(c.get('search_i2s_matches', 0))} "
                     "i2s matches")
        lines.append(line)
    if g.get("generations_per_dispatch"):
        line = (f"  genloop  : "
                f"{int(g.get('generations_per_dispatch', 0))} "
                f"generations/dispatch (device-resident)"
                f" | ring {int(g.get('gen_ring_filled', 0))} "
                f"slots filled")
        if c.get("findings_ring_drops"):
            line += (f" | {_fmt_n(c.get('findings_ring_drops', 0))} "
                     "findings-ring drops")
        lines.append(line)
    if g.get("learn_model_version") or c.get("learn_train_steps") \
            or g.get("learn_label_count"):
        line = (f"  learn    : model v{int(g.get('learn_model_version', 0))}"
                f" | {_fmt_n(g.get('learn_label_count', 0))} labels"
                f" | {_fmt_n(c.get('learn_train_steps', 0))} train "
                f"steps")
        if c.get("learn_masks_applied"):
            line += (f" | {_fmt_n(c.get('learn_masks_applied', 0))} "
                     "masks applied")
        lines.append(line)
    if g.get("state_cov_pairs"):
        lines.append(
            f"  stateful : "
            f"{int(g.get('state_cov_states', 0))} protocol states "
            f"seen | {_fmt_n(g.get('state_cov_pairs', 0))} "
            f"state x edge pairs covered")
    lines.append(
        f"  crashes  : {_fmt_n(c.get('crashes', 0))}"
        f" ({_fmt_n(c.get('unique_crashes', 0))} unique)"
        f"    hangs : {_fmt_n(c.get('hangs', 0))}"
        f" ({_fmt_n(c.get('unique_hangs', 0))} unique)"
        f"    errors : {_fmt_n(c.get('errors', 0))}")
    depth = g.get("pipeline_depth")
    if depth is not None:
        lines.append(f"  pipeline : {int(depth)} batches in flight")
    totals = {s: c.get(s + "_seconds", 0.0) for s in STAGES}
    acc = sum(totals.values())
    if acc > 0:
        lines.append("  stage split (host-attention seconds):")
        for s, t in sorted(totals.items(), key=lambda kv: -kv[1]):
            if t > 0:
                row = (f"    {s:<15} {_bar(t / acc)} "
                       f"{t / acc:6.1%}  ({t:.2f}s)")
                p = _stage_percentiles(snap, s)
                if p:
                    row += (f"  p50 {_fmt_secs(p['p50'])}"
                            f" p99 {_fmt_secs(p['p99'])}")
                lines.append(row)
    return "\n".join(lines)


def _frame(args) -> Optional[Dict[str, Any]]:
    if args.manager:
        return read_manager(args.manager, args.campaign)
    return read_local(args.path)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="kb-stats",
        description="live campaign stats view (tails stats.jsonl or "
                    "polls a manager /api/stats endpoint)")
    p.add_argument("path", nargs="?", default="output",
                   help="campaign output dir or stats.jsonl path "
                        "(default ./output)")
    p.add_argument("--manager",
                   help="manager base URL (e.g. http://mgr:8650); "
                        "reads the merged fleet view instead of a "
                        "local file")
    p.add_argument("--campaign",
                   help="campaign key for --manager (job id)")
    p.add_argument("-i", "--interval", type=float, default=1.0,
                   help="refresh seconds (default 1)")
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit (no ANSI)")
    p.add_argument("--json", action="store_true",
                   help="with --once: print the raw registry "
                        "snapshot as JSON (CI / scripts — no "
                        "rendering, no TTY assumptions)")
    p.add_argument("--openmetrics", action="store_true",
                   help="with --once: print the snapshot in the "
                        "OpenMetrics text format (the same renderer "
                        "behind the manager's /metrics; pipe to a "
                        "node_exporter textfile collector)")
    args = p.parse_args(argv)
    if args.manager and not args.campaign:
        print("error: --manager needs --campaign", file=sys.stderr)
        return 2
    if (args.json or args.openmetrics) and not args.once:
        print("error: --json/--openmetrics need --once",
              file=sys.stderr)
        return 2
    if args.json and args.openmetrics:
        print("error: --json and --openmetrics are mutually "
              "exclusive", file=sys.stderr)
        return 2
    if args.once:
        snap = _frame(args)
        # an empty dict is as useless as a missing file: scripts gate
        # on this exit, so "no campaign stats" must be LOUD, not an
        # all-zero report with exit 0
        if snap is None or not snap.get("counters"):
            if args.manager:
                print(f"error: no fleet stats for campaign "
                      f"{args.campaign!r} at {args.manager} (no "
                      f"worker heartbeat yet, or wrong campaign "
                      f"key)", file=sys.stderr)
            else:
                print(f"error: no campaign stats under {args.path!r} "
                      f"(stats.jsonl/fuzzer_stats missing or empty "
                      f"— is the fuzzer running with stats enabled, "
                      f"i.e. without --no-stats?)", file=sys.stderr)
            return 1
        if args.openmetrics:
            sys.stdout.write(render_snapshot(snap))
        else:
            print(json.dumps(snap) if args.json else render(snap))
        return 0
    try:
        while True:
            snap = _frame(args)
            # home + clear-to-end redraw (no flicker, no curses)
            sys.stdout.write("\x1b[H\x1b[J")
            sys.stdout.write(render(snap) if snap is not None
                             else "waiting for stats ...")
            sys.stdout.write("\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
