"""merger — fold N serialized instrumentation states into one.

Parity with the reference merger tool (merger/merger.c:79-108,
SURVEY §2.7): load each state file, fold ``instrumentation.merge``
over them, and dump the combined state. This is the offline,
cross-host coverage "allreduce"; the on-line equivalent is the ICI
bitwise-OR collective in ``parallel.distributed``.

Usage:
    python -m killerbeez_tpu.tools.merger afl -o merged.state \
        node0.state node1.state node2.state
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from ..instrumentation.factory import instrumentation_factory
from ..utils.fileio import read_file, write_buffer_to_file
from ..utils.logging import INFO_MSG, setup_logging


def merge_state_files(instrumentation_name: str,
                      instrumentation_options: Optional[str],
                      state_files: List[str]) -> str:
    """Fold the states in ``state_files`` left-to-right; returns the
    combined serialized state."""
    if not state_files:
        raise ValueError("merger needs at least one state file")
    instr = instrumentation_factory(instrumentation_name,
                                    instrumentation_options)
    try:
        instr.set_state(read_file(state_files[0]).decode())
        for path in state_files[1:]:
            instr.merge(read_file(path).decode())
        return instr.get_state()
    finally:
        instr.cleanup()


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="killerbeez-tpu-merger",
        description="merge serialized instrumentation states")
    p.add_argument("instrumentation", help="instrumentation name (afl, ...)")
    p.add_argument("states", nargs="+", help="state files to merge")
    p.add_argument("-i", "--instrumentation-options",
                   help="instrumentation JSON options")
    p.add_argument("-o", "--output", required=True,
                   help="write the merged state here")
    p.add_argument("-l", "--logging-options", help="logging JSON options")
    args = p.parse_args(argv)
    try:
        setup_logging(args.logging_options)
        merged = merge_state_files(args.instrumentation,
                                   args.instrumentation_options,
                                   args.states)
        write_buffer_to_file(args.output, merged.encode())
        INFO_MSG("merged %d states -> %s", len(args.states), args.output)
        return 0
    except (ValueError, FileNotFoundError, NotImplementedError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
