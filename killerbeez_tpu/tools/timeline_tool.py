"""kb-timeline — flight-recorder analysis (critical path, stage
occupancy, pipeline bubbles).

Loads a campaign's ``trace.json`` (the ``--trace`` span ring, Chrome
trace-event JSON) plus ``events.jsonl`` and ``fuzzer_stats`` when
present, and answers the questions the aggregate stats can't: where
did wall-clock go per stage, how full was the pipeline, WHERE are the
bubbles (device idle while the host mutates/triages), and do the
recorded events reconcile with the counters.  This is the artifact
that shows a dispatch-vs-triage race in one glance instead of a
debugging session.

    kb-timeline output/                 # human report + ANSI lane view
    kb-timeline output/ --json          # machine report
    kb-timeline output/trace.json --width 100 --bubble-ms 5
    kb-timeline --fleet http://mgr:8650 --campaign 7   # fleet merge

``--fleet`` pulls every worker's forwarded event stream (plus the
manager's health/alert records) from ``/api/events/<campaign>`` and
merges them onto ONE wall-clock axis — the records carry the same
wall timestamps the local overlay anchors on ``wall_t0``, so a
two-worker campaign reads as one timeline: who found what when,
which worker went dead, when the alert fired.

Not to be confused with ``kb-trace`` (the host-tier ptrace edge
harvester, ``tools/tracer.py`` / ``native/``): kb-trace records what a
HOST TARGET executed; kb-timeline analyzes what the TPU-tier fuzzing
PIPELINE did.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from ..telemetry.aggregate import merge_events
from ..telemetry.events import read_events
from ..telemetry.sink import parse_fuzzer_stats
from ..telemetry.trace import load_chrome_trace

#: stages that are HOST attention (bubble attribution candidates);
#: "execute" is the device dispatch, "in_flight" is occupancy
HOST_STAGES = ("mutate", "host_transfer", "triage", "learn",
               "corpus_feedback",
               "fs_write", "crack", "sync_round")

#: lane-view glyph per span name (top-of-stack wins)
GLYPHS = {"mutate": "m", "execute": "x", "host_transfer": "h",
          "triage": "t", "corpus_feedback": "c", "fs_write": "w",
          "in_flight": ".", "crack": "K", "sync_round": "s"}


# -- span reconstruction ------------------------------------------------


def spans_from_chrome(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Pair B/E events (per tid, stack discipline) and async b/e
    pairs (matched by tid+name+id — the in-flight windows, which
    cross sync span boundaries) back into ``{name, tid, t0, t1}``
    spans (microseconds, trace-relative)."""
    spans: List[Dict[str, Any]] = []
    stacks: Dict[int, List[Dict[str, Any]]] = {}
    open_async: Dict[tuple, Dict[str, Any]] = {}
    for ev in doc.get("traceEvents", []):
        ph = ev.get("ph")
        tid = int(ev.get("tid", 0))
        if ph == "B":
            stacks.setdefault(tid, []).append(
                {"name": ev.get("name", "?"), "tid": tid,
                 "t0": float(ev.get("ts", 0.0)), "t1": None,
                 "args": ev.get("args")})
        elif ph == "E":
            stack = stacks.get(tid)
            if stack:
                s = stack.pop()
                s["t1"] = float(ev.get("ts", 0.0))
                spans.append(s)
        elif ph == "b":
            open_async[(tid, ev.get("name"), ev.get("id"))] = {
                "name": ev.get("name", "?"), "tid": tid,
                "t0": float(ev.get("ts", 0.0)), "t1": None,
                "args": ev.get("args")}
        elif ph == "e":
            s = open_async.pop(
                (tid, ev.get("name"), ev.get("id")), None)
            if s is not None:
                s["t1"] = float(ev.get("ts", 0.0))
                spans.append(s)
    spans.sort(key=lambda s: (s["t0"], s["t1"]))
    return spans


def lane_names_from_chrome(doc: Dict[str, Any]) -> Dict[int, str]:
    names: Dict[int, str] = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            names[int(ev.get("tid", 0))] = \
                (ev.get("args") or {}).get("name", "")
    return names


def instants_from_chrome(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [{"name": ev.get("name", "?"), "tid": int(ev.get("tid", 0)),
             "ts": float(ev.get("ts", 0.0)), "args": ev.get("args")}
            for ev in doc.get("traceEvents", []) if ev.get("ph") == "i"]


# -- interval math ------------------------------------------------------


def _union_len(ivals: List[Tuple[float, float]]) -> float:
    """Total length covered by a set of (t0, t1) intervals."""
    total = 0.0
    end = float("-inf")
    for t0, t1 in sorted(ivals):
        if t1 <= end:
            continue
        total += t1 - max(t0, end)
        end = t1
    return total


def stage_report(spans: List[Dict[str, Any]]
                 ) -> Tuple[Dict[str, Dict[str, float]], float]:
    """Per-stage {total_us, count, occupancy} plus the trace window
    length.  ``total_us`` sums span durations (nesting double-counts,
    matching the registry's attention split); ``occupancy`` is the
    fraction of the window with >= 1 span of that stage open."""
    if not spans:
        return {}, 0.0
    w0 = min(s["t0"] for s in spans)
    w1 = max(s["t1"] for s in spans)
    window = max(w1 - w0, 1e-9)
    by: Dict[str, List[Tuple[float, float]]] = {}
    out: Dict[str, Dict[str, float]] = {}
    for s in spans:
        by.setdefault(s["name"], []).append((s["t0"], s["t1"]))
    def _q(durs, permille):
        # nearest-rank index ceil(q*n)-1 in exact integer math (a
        # floor over n-1 would bias every tail percentile LOW — with
        # 2 spans the p99 would report the MINIMUM duration)
        n = len(durs)
        rank = -(-permille * n // 1000)          # ceil
        return durs[min(n - 1, max(0, rank - 1))]

    for name, ivals in by.items():
        durs = sorted(t1 - t0 for t0, t1 in ivals)
        out[name] = {
            "total_us": sum(durs),
            "count": len(ivals),
            "occupancy": _union_len(ivals) / window,
            # span-duration quantiles (nearest rank) — the per-stage
            # latency shape, matching the registry histograms'
            # p50/p90/p99 keys
            "p50_us": _q(durs, 500),
            "p90_us": _q(durs, 900),
            "p99_us": _q(durs, 990),
        }
    return out, window


def detect_bubbles(spans: List[Dict[str, Any]],
                   threshold_us: Optional[float] = None
                   ) -> Tuple[List[Dict[str, Any]], float]:
    """Pipeline-bubble detection: a bubble is a gap between
    consecutive device dispatches (``execute`` spans, merged across
    lanes) during which HOST stages were busy — the device sat idle
    while the host mutated/triaged/synced.  Returns (bubbles,
    threshold_used).  The auto threshold is 4x the median
    dispatch-to-dispatch gap (floored at 200us): a steady pipeline's
    natural cadence never alarms, a stall several times that does."""
    ex = sorted([(s["t0"], s["t1"]) for s in spans
                 if s["name"] == "execute"])
    if len(ex) < 3:
        return [], 0.0
    merged: List[Tuple[float, float]] = []
    for t0, t1 in ex:
        if merged and t0 <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], t1))
        else:
            merged.append((t0, t1))
    gaps = [(a1, b0) for (a0, a1), (b0, b1)
            in zip(merged, merged[1:]) if b0 > a1]
    if not gaps:
        return [], 0.0
    if threshold_us is None:
        sizes = sorted(b - a for a, b in gaps)
        median = sizes[len(sizes) // 2]
        threshold_us = max(4.0 * median, 200.0)
    host = [s for s in spans if s["name"] in HOST_STAGES]
    bubbles: List[Dict[str, Any]] = []
    for g0, g1 in gaps:
        dur = g1 - g0
        if dur < threshold_us:
            continue
        # attribute to the host stage holding the most of the gap
        overlap: Dict[str, float] = {}
        for s in host:
            o = min(s["t1"], g1) - max(s["t0"], g0)
            if o > 0:
                overlap[s["name"]] = overlap.get(s["name"], 0.0) + o
        if not overlap:
            continue                     # idle-idle: not a host bubble
        dominant = max(overlap.items(), key=lambda kv: kv[1])
        bubbles.append({
            "t0_us": g0, "duration_us": dur,
            "dominant_stage": dominant[0],
            "dominant_us": dominant[1],
            "host_overlap_us": sum(overlap.values()),
        })
    return bubbles, threshold_us


def generations_report(spans: List[Dict[str, Any]],
                       instants: Optional[List[Dict[str, Any]]] = None
                       ) -> Optional[Dict[str, Any]]:
    """--generations campaign analysis: each device dispatch's
    in-flight span carries its generation count in the span args;
    report dispatch/generation totals, DEVICE occupancy over the
    generation window (fraction of the window with a G-generation
    dispatch in flight) and host-stage occupancy over the same
    window.  ``device_bound`` is the ROADMAP item 1 acceptance call:
    the device, not host mutate/triage, holds the critical path.

    Mesh campaigns (--generations on --mesh) additionally stamp one
    ``shard_generations`` instant per dp shard per dispatch; those
    fold into a ``shards`` section — per-shard dispatch/generation
    totals plus each shard's generation occupancy over the window.
    The dispatch is ONE mesh program (shards advance in lockstep, so
    the instants are stamped host-side for every shard together):
    the rows certify that each shard spent the window inside
    G-generation dispatches at mesh scale, they are not a per-shard
    divergence detector — a slow or wedged shard stalls the whole
    program and shows up as mesh-wide occupancy loss or a watchdog
    stall, never as one diverging row."""
    disp = [s for s in spans
            if s.get("name") == "in_flight"
            and (s.get("args") or {}).get("generations")]
    if not disp:
        return None
    w0 = min(s["t0"] for s in disp)
    w1 = max(s["t1"] for s in disp)
    window = max(w1 - w0, 1e-9)
    gens = [int(s["args"]["generations"]) for s in disp]
    dev = _union_len([(s["t0"], s["t1"]) for s in disp]) / window
    host_iv = [(max(s["t0"], w0), min(s["t1"], w1))
               for s in spans if s["name"] in HOST_STAGES
               and s["t1"] > w0 and s["t0"] < w1]
    host = _union_len(host_iv) / window
    report = {
        "dispatches": len(disp),
        "generations_total": sum(gens),
        "generations_min": min(gens),
        "generations_max": max(gens),
        "device_occupancy": dev,
        "host_occupancy": host,
        "window_us": window,
        "device_bound": bool(dev > host),
    }
    shard_marks = [ev for ev in (instants or [])
                   if ev.get("name") == "shard_generations"
                   and (ev.get("args") or {}).get("shard")
                   is not None]
    if shard_marks:
        # dispatch intervals sorted once; a shard's occupancy is the
        # union of the dispatch windows it stamped a mark inside
        ivals = sorted((s["t0"], s["t1"]) for s in disp)
        shards: Dict[str, Dict[str, Any]] = {}
        for ev in shard_marks:
            a = ev["args"]
            d = shards.setdefault(str(int(a["shard"])), {
                "dispatches": 0, "generations_total": 0,
                "_ivals": []})
            d["dispatches"] += 1
            d["generations_total"] += int(a.get("generations", 0))
            ts = float(ev["ts"])
            # the campaign stamps shard instants at dispatch time,
            # just BEFORE the loop opens the dispatch's in_flight
            # window (and while the previous window is still open
            # under the double buffer): attribute the mark to the
            # window whose OPEN is nearest — "first still-open
            # window" would hand every mark to the previous dispatch
            # and drop the final window from every shard's union
            hit = min(ivals, key=lambda iv: abs(iv[0] - ts)) \
                if ivals else None
            if hit is not None:
                d["_ivals"].append(hit)
        for d in shards.values():
            d["occupancy"] = _union_len(d.pop("_ivals")) / window
        report["shards"] = dict(sorted(shards.items(),
                                       key=lambda kv: int(kv[0])))
        report["n_shards"] = len(shards)
    return report


def sessions_report(events: List[Dict[str, Any]]
                    ) -> Optional[Dict[str, Any]]:
    """Stateful session tier: the campaign's ``state_cov`` events
    (one per state x edge coverage high-water increase,
    fuzzer/loop.py) as a growth summary — how many protocol states
    the campaign reached and how the state x edge frontier moved
    over the run.  None for non-stateful campaigns."""
    sc = [e for e in list(events)
          if e.get("type") == "state_cov"]
    if not sc:
        return None
    sc.sort(key=lambda e: float(e.get("t", 0.0)))
    first, last = sc[0], sc[-1]
    return {
        "increases": len(sc),
        "pairs": int(last.get("pairs", 0)),
        "states": int(last.get("states", 0)),
        "first_pairs": int(first.get("pairs", 0)),
        "window_s": float(last.get("t", 0.0))
        - float(first.get("t", 0.0)),
    }


# -- events -------------------------------------------------------------


def event_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    counts: Dict[str, int] = {}
    last: Dict[str, float] = {}
    for e in events:
        t = e.get("type", "?")
        counts[t] = counts.get(t, 0) + 1
        last[t] = max(last.get(t, 0.0), float(e.get("t", 0.0)))
    return {"counts": counts, "last": last, "total": len(events)}


def reconcile(events: List[Dict[str, Any]],
              stats: Dict[str, str]) -> Dict[str, Any]:
    """Check the event-log contract against fuzzer_stats: one
    new_path event per paths_total, one crash per unique_crashes, one
    hang per unique_hangs."""
    counts: Dict[str, int] = {}
    for e in events:
        t = e.get("type", "?")
        counts[t] = counts.get(t, 0) + 1
    out: Dict[str, Any] = {}
    for etype, key in (("new_path", "paths_total"),
                       ("crash", "unique_crashes"),
                       ("hang", "unique_hangs")):
        want = int(stats.get(key, 0))
        got = counts.get(etype, 0)
        out[etype] = {"events": got, key: want, "ok": got == want}
    out["ok"] = all(v["ok"] for v in out.values()
                    if isinstance(v, dict))
    return out


# -- fleet mode ---------------------------------------------------------

#: glyph per event type in the fleet lane view (one row per worker)
FLEET_GLYPHS = {"new_path": ".", "crash": "C", "hang": "H",
                "plateau": "P", "crack_injection": "K",
                "sync_round": "s", "scheduler_pick": "r",
                "flush": "f", "worker_stale": "S",
                "worker_dead": "D", "worker_returned": "R",
                "alert": "A"}


def fetch_fleet_events(manager_url: str, campaign: str
                       ) -> List[Dict[str, Any]]:
    """Drain ``/api/events/<campaign>`` through its cursor and return
    ONE merged, deduped, total-ordered stream with each record tagged
    by its origin worker (``merge_events`` — the same fold the
    heartbeat aggregates use)."""
    events: List[Dict[str, Any]] = []
    since = 0
    while True:
        url = (f"{manager_url}/api/events/{campaign}"
               f"?since={since}")
        with urllib.request.urlopen(url, timeout=30) as resp:
            body = json.loads(resp.read())
        rows = body.get("events") or []
        if not rows:
            break
        for r in rows:
            rec = r.get("event")
            if not isinstance(rec, dict):
                continue
            rec = dict(rec)
            rec.setdefault("worker", r.get("worker", "?"))
            events.append(rec)
        latest = int(body.get("latest", since))
        if latest <= since:
            break
        since = latest
    return merge_events(events)


def fleet_report(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-worker and per-type summary over the merged wall-clock
    stream."""
    counts: Dict[str, int] = {}
    by_worker: Dict[str, Dict[str, int]] = {}
    ts = [float(e.get("t", 0.0)) for e in events]
    for e in events:
        t = e.get("type", "?")
        counts[t] = counts.get(t, 0) + 1
        w = str(e.get("worker", "?"))
        by_worker.setdefault(w, {})
        by_worker[w][t] = by_worker[w].get(t, 0) + 1
    active_alerts = {}
    for e in events:                     # stream order = final state
        if e.get("type") == "alert" and e.get("alert"):
            active_alerts[e["alert"]] = bool(e.get("active"))
    return {
        "total": len(events),
        "t0": min(ts) if ts else 0.0,
        "t1": max(ts) if ts else 0.0,
        "window_s": (max(ts) - min(ts)) if ts else 0.0,
        "counts": counts,
        "workers": by_worker,
        "active_alerts": sorted(a for a, on in active_alerts.items()
                                if on),
    }


def render_fleet(report: Dict[str, Any],
                 events: List[Dict[str, Any]],
                 width: int = 72) -> str:
    """One wall-clock axis, one lane per worker (the manager's
    health/alert records ride the ``_manager`` lane)."""
    lines: List[str] = []
    head = "kb-timeline — fleet event timeline (merged wall clock)"
    lines.append(head)
    lines.append("=" * len(head))
    lines.append(
        f"  window  : {report['window_s']:.1f}s  "
        f"({report['total']} events, "
        f"{len(report['workers'])} streams)")
    pairs = ", ".join(f"{k} x{v}" for k, v in
                      sorted(report["counts"].items()))
    lines.append(f"  events  : {pairs}")
    if report["active_alerts"]:
        lines.append("  alerts  : "
                     + ", ".join(report["active_alerts"])
                     + " ACTIVE")
    t0, window = report["t0"], max(report["window_s"], 1e-9)
    scale = (width - 1) / window
    label_w = max([len(w) for w in report["workers"]] + [6])
    glyphs = "  ".join(f"{g}={n}" for n, g in FLEET_GLYPHS.items())
    lines.append(f"  lanes ({glyphs}):")
    for w in sorted(report["workers"]):
        cells = [" "] * width
        for e in events:
            if str(e.get("worker", "?")) != w:
                continue
            i = int((float(e.get("t", 0.0)) - t0) * scale)
            if 0 <= i < width:
                cells[i] = FLEET_GLYPHS.get(e.get("type"), "#")
        lines.append(f"  {w:<{label_w}} |{''.join(cells)}|")
    lines.append(f"  {'':<{label_w}} |0{' ' * (width - 2)}|  "
                 f"({report['window_s']:.1f}s window)")
    return "\n".join(lines)


# -- rendering ----------------------------------------------------------


def _fmt_us(us: float) -> str:
    if us >= 1e6:
        return f"{us / 1e6:.2f}s"
    if us >= 1e3:
        return f"{us / 1e3:.2f}ms"
    return f"{us:.0f}us"


def lane_view(spans: List[Dict[str, Any]],
              instants: List[Dict[str, Any]],
              lane_names: Dict[int, str], window: float,
              width: int = 72) -> List[str]:
    """One text row per lane, a glyph per time bucket (top-of-stack
    span wins; later spans overwrite earlier in the same bucket), and
    an events row overlaying instant markers."""
    if not spans or window <= 0:
        return []
    t0 = min(s["t0"] for s in spans)
    scale = width / window
    rows: List[str] = []
    tids = sorted({s["tid"] for s in spans})
    label_w = max([len(lane_names.get(t, f"lane-{t}")) for t in tids]
                  + [6])
    for tid in tids:
        cells = [" "] * width
        for s in sorted((s for s in spans if s["tid"] == tid),
                        key=lambda s: (s["t1"] - s["t0"]),
                        reverse=True):
            a = int((s["t0"] - t0) * scale)
            b = int((s["t1"] - t0) * scale)
            g = GLYPHS.get(s["name"], "#")
            for i in range(max(a, 0), min(b + 1, width)):
                cells[i] = g
        name = lane_names.get(tid, f"lane-{tid}")
        rows.append(f"  {name:<{label_w}} |{''.join(cells)}|")
    if instants:
        cells = [" "] * width
        for ev in instants:
            i = int((ev["ts"] - t0) * scale)
            if 0 <= i < width:
                cells[i] = "!"
        rows.append(f"  {'events':<{label_w}} |{''.join(cells)}|")
    rows.append(f"  {'':<{label_w}} "
                f"|0{' ' * (width - 2)}|  ({_fmt_us(window)} window)")
    return rows


def render(report: Dict[str, Any], lanes: List[str]) -> str:
    lines: List[str] = []
    head = "kb-timeline — flight-recorder analysis"
    lines.append(head)
    lines.append("=" * len(head))
    window = report.get("window_us", 0.0)
    lines.append(f"  trace window : {_fmt_us(window)}  "
                 f"({report.get('span_count', 0)} spans, "
                 f"{report.get('lane_count', 0)} lanes)")
    stages = report.get("stages", {})
    if stages:
        lines.append("  per-stage wall clock (host attention):")
        acc = sum(v["total_us"] for k, v in stages.items()
                  if k != "in_flight") or 1.0
        for name, v in sorted(stages.items(),
                              key=lambda kv: -kv[1]["total_us"]):
            if name == "in_flight":
                continue
            frac = v["total_us"] / acc
            row = (
                f"    {name:<15} {_fmt_us(v['total_us']):>10}  "
                f"{frac:6.1%}  ({int(v['count'])} spans, "
                f"{v['occupancy']:.1%} occupancy)")
            if "p50_us" in v:
                row += (f"  p50 {_fmt_us(v['p50_us'])}"
                        f" p99 {_fmt_us(v['p99_us'])}")
            lines.append(row)
        cp = report.get("critical_path")
        if cp:
            lines.append(f"  critical path : {cp} "
                         f"(highest occupancy outside the device)")
        inf = stages.get("in_flight")
        if inf:
            lines.append(
                f"  pipeline      : {inf['occupancy']:.1%} of the "
                f"window with batches in flight "
                f"({int(inf['count'])} batches)")
    gr = report.get("generations")
    if gr:
        lines.append(
            f"  generations   : {gr['dispatches']} device dispatches"
            f" x {gr['generations_min']}"
            + (f"-{gr['generations_max']}"
               if gr["generations_max"] != gr["generations_min"]
               else "")
            + f" generations ({gr['generations_total']} total)")
        lines.append(
            f"                  device {gr['device_occupancy']:.1%} "
            f"vs host {gr['host_occupancy']:.1%} occupancy over the "
            f"generation window — "
            + ("DEVICE-bound (host stages off the critical path)"
               if gr["device_bound"] else "host-bound"))
        for sid, sd in (gr.get("shards") or {}).items():
            lines.append(
                f"    shard-{sid:<4} {sd['dispatches']} dispatches, "
                f"{sd['generations_total']} generations, "
                f"{sd['occupancy']:.1%} occupancy")
    sr = report.get("sessions")
    if sr:
        lines.append(
            f"  sessions      : {sr['states']} protocol states "
            f"reached, {sr['pairs']} state x edge pairs covered "
            f"({sr['increases']} coverage increases over "
            f"{sr['window_s']:.1f}s)")
    bubbles = report.get("bubbles", [])
    lines.append(
        f"  bubbles       : {len(bubbles)} detected, "
        f"{_fmt_us(report.get('bubble_total_us', 0.0))} total "
        f"(threshold {_fmt_us(report.get('bubble_threshold_us', 0.0))})")
    for b in bubbles[:8]:
        lines.append(
            f"    @{_fmt_us(b['t0_us'])}: device idle "
            f"{_fmt_us(b['duration_us'])} while host ran "
            f"{b['dominant_stage']} ({_fmt_us(b['dominant_us'])})")
    if len(bubbles) > 8:
        lines.append(f"    ... {len(bubbles) - 8} more")
    ev = report.get("events")
    if ev:
        pairs = ", ".join(f"{k} x{v}" for k, v in
                          sorted(ev["counts"].items()))
        lines.append(f"  events        : {pairs}")
    rec = report.get("reconcile")
    if rec:
        ok = "OK" if rec.get("ok") else "MISMATCH"
        lines.append(
            f"  reconcile     : {ok} (new_path "
            f"{rec['new_path']['events']}/"
            f"{rec['new_path']['paths_total']}, crash "
            f"{rec['crash']['events']}/"
            f"{rec['crash']['unique_crashes']}, hang "
            f"{rec['hang']['events']}/"
            f"{rec['hang']['unique_hangs']} vs fuzzer_stats)")
    if lanes:
        glyphs = "  ".join(f"{g}={n}" for n, g in GLYPHS.items())
        lines.append("  lane view (" + glyphs + "):")
        lines.extend(lanes)
    return "\n".join(lines)


# -- entry --------------------------------------------------------------


def analyze(out_dir: str, trace_path: Optional[str] = None,
            bubble_us: Optional[float] = None
            ) -> Tuple[Optional[Dict[str, Any]], List[Dict], Dict]:
    """Returns (chrome doc or None, events list, fuzzer_stats dict)."""
    if trace_path is None:
        trace_path = os.path.join(out_dir, "trace.json")
    doc = load_chrome_trace(trace_path)
    events = list(read_events(os.path.join(out_dir, "events.jsonl")))
    stats: Dict[str, str] = {}
    try:
        stats = parse_fuzzer_stats(os.path.join(out_dir,
                                                "fuzzer_stats"))
    except OSError:
        pass
    return doc, events, stats


def build_report(doc: Optional[Dict[str, Any]],
                 events: List[Dict[str, Any]],
                 stats: Dict[str, str],
                 bubble_us: Optional[float] = None) -> Dict[str, Any]:
    report: Dict[str, Any] = {}
    if doc is not None:
        spans = spans_from_chrome(doc)
        stages, window = stage_report(spans)
        bubbles, thresh = detect_bubbles(spans, bubble_us)
        host = {k: v for k, v in stages.items()
                if k not in ("execute", "in_flight")}
        report.update({
            "window_us": window,
            "span_count": len(spans),
            "lane_count": len({s["tid"] for s in spans}),
            "stages": stages,
            "critical_path": (max(host.items(),
                                  key=lambda kv: kv[1]["occupancy"])[0]
                              if host else None),
            "bubbles": bubbles,
            "bubble_total_us": sum(b["duration_us"] for b in bubbles),
            "bubble_threshold_us": thresh,
            "trace_meta": doc.get("otherData", {}),
        })
        gr = generations_report(spans, instants_from_chrome(doc))
        if gr:
            report["generations"] = gr
    if events:
        report["events"] = event_summary(events)
        sr = sessions_report(events)
        if sr:
            report["sessions"] = sr
    if events and stats:
        report["reconcile"] = reconcile(events, stats)
    return report


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="kb-timeline",
        description="flight-recorder analysis: per-stage wall clock, "
                    "pipeline occupancy, bubble detection and event "
                    "overlay from a --trace campaign's trace.json + "
                    "events.jsonl")
    p.add_argument("path", nargs="?", default="output",
                   help="campaign output dir, or a trace.json path "
                        "(default ./output)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report (scripts/CI)")
    p.add_argument("--width", type=int, default=72,
                   help="lane-view width in columns (default 72)")
    p.add_argument("--bubble-ms", type=float, default=None,
                   help="explicit bubble threshold in ms (default: "
                        "4x the median dispatch gap)")
    p.add_argument("--no-lanes", action="store_true",
                   help="skip the ANSI lane view")
    p.add_argument("--heat", action="store_true",
                   help="append the corpus store's per-byte "
                        "mutation-heat panels (FMViz-style, from "
                        "<path>/corpus provenance sidecars)")
    p.add_argument("--base", metavar="FILE",
                   help="--heat: the campaign's base seed file, so "
                        "first-generation lineage renders too")
    p.add_argument("--fleet", metavar="MANAGER_URL",
                   help="merge the fleet's event streams from a "
                        "manager (/api/events/<campaign>) onto one "
                        "wall-clock axis instead of reading a local "
                        "output dir; needs --campaign")
    p.add_argument("--campaign",
                   help="campaign key for --fleet (job id)")
    args = p.parse_args(argv)

    if args.fleet:
        if not args.campaign:
            print("error: --fleet needs --campaign", file=sys.stderr)
            return 2
        try:
            events = fetch_fleet_events(args.fleet, args.campaign)
        except (OSError, ValueError) as e:
            print(f"error: fleet event fetch from {args.fleet} "
                  f"failed: {e}", file=sys.stderr)
            return 1
        if not events:
            print(f"error: no events for campaign "
                  f"{args.campaign!r} at {args.fleet}",
                  file=sys.stderr)
            return 1
        report = fleet_report(events)
        if args.json:
            print(json.dumps({"report": report, "events": events},
                             indent=2))
        else:
            print(render_fleet(report, events, width=args.width))
        return 0

    path = args.path
    if os.path.isfile(path):
        out_dir, trace_path = os.path.dirname(path) or ".", path
    else:
        out_dir, trace_path = path, None
    doc, events, stats = analyze(out_dir, trace_path)
    if doc is None and not events:
        print("error: no trace.json or events.jsonl under "
              f"{args.path} (run the fuzzer with --trace)",
              file=sys.stderr)
        return 1
    bubble_us = (args.bubble_ms * 1e3 if args.bubble_ms is not None
                 else None)
    report = build_report(doc, events, stats, bubble_us)
    heat_text = None
    if args.heat:
        # lineage heat next to the time axis: which parent bytes the
        # campaign profited from mutating (tools/heat.py)
        from ..corpus.store import CorpusStore
        from .heat import heat_report, render_store_heat
        store_dir = os.path.join(out_dir, "corpus")
        if not os.path.isdir(store_dir):
            print(f"error: --heat needs a corpus store at "
                  f"{store_dir} (run with --corpus-dir)",
                  file=sys.stderr)
            return 1
        heat_entries = CorpusStore(store_dir).load()
        base = None
        if args.base:
            with open(args.base, "rb") as f:
                base = f.read()
        if args.json:
            report["heat"] = heat_report(heat_entries, base=base)
        else:
            heat_text = render_store_heat(heat_entries, base=base)
    if args.json:
        print(json.dumps(report, indent=2))
        return 0
    lanes: List[str] = []
    if doc is not None and not args.no_lanes:
        spans = spans_from_chrome(doc)
        lanes = lane_view(spans, instants_from_chrome(doc),
                          lane_names_from_chrome(doc),
                          report.get("window_us", 0.0),
                          width=args.width)
    print(render(report, lanes))
    if heat_text is not None:
        print("\nmutation heat (corpus lineage):")
        print(heat_text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
