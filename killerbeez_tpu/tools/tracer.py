"""tracer — harvest the deterministic edge set of one input.

Parity with the reference tracer tool (tracer/main.c:109-270,
SURVEY §3.4): run a single input ``-n`` times (default 5) with the
instrumentation forced into edges mode, keep only edges observed in
EVERY run (the deterministic set), and write them as ``edge:count``
text lines. The manager's corpus minimization consumes these files
(greedy edge cover, tools/minimize.py).

Usage:
    python -m killerbeez_tpu.tools.tracer file afl -sf input.bin \
        -d '{"path": "corpus/build/test", "arguments": "@@"}' \
        -o edges.txt
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

from ..drivers.factory import driver_factory
from ..instrumentation.factory import instrumentation_factory
from ..utils.fileio import read_file, write_buffer_to_file
from ..utils.logging import INFO_MSG, setup_logging


def force_edges_option(options: Optional[str]) -> str:
    """Merge {"edges": 1} into an instrumentation option string
    (reference tracer/main.c:182-185 forces the same)."""
    opts = json.loads(options) if options else {}
    opts["edges"] = 1
    return json.dumps(opts)


def trace_deterministic_edges(driver, instrumentation,
                              input_bytes: bytes,
                              num_iterations: int = 5
                              ) -> Dict[int, int]:
    """Run the input ``num_iterations`` times; return {edge_id:
    min hit count} for edges present in every run."""
    counts: Optional[Dict[int, int]] = None
    for _ in range(num_iterations):
        driver.test_input(input_bytes)
        edges = instrumentation.get_edges()
        if edges is None:
            raise ValueError(
                f"{instrumentation.name} cannot report edges "
                "(needs edges mode support)")
        run = dict(edges)
        if counts is None:
            counts = run
        else:
            counts = {e: min(c, run[e])
                      for e, c in counts.items() if e in run}
    return counts or {}


def trace_deterministic_pairs(driver, instrumentation,
                              input_bytes: bytes,
                              num_iterations: int = 5):
    """Per-module (from, to) records present in every run — the
    reference tracer's ``instrumentation_edge_t`` intersect
    (tracer/main.c:239-252).  One target execution per iteration; all
    modules are harvested from the same run.  Returns
    {module_name: {(from_id, to_id), ...}}."""
    modules = instrumentation.get_module_info() or ["target"]
    per_mod = None
    for _ in range(num_iterations):
        driver.test_input(input_bytes)
        run: Dict[str, set] = {}
        for module in modules:
            rec = instrumentation.get_edge_pairs(module)
            if rec is None:
                raise ValueError(
                    f"{instrumentation.name} cannot report (from, to) "
                    "edge records (needs a static edge universe)")
            run[module] = {(f, t) for f, t, _ in rec}
        if per_mod is None:
            per_mod = run
        else:
            per_mod = {m: per_mod[m] & run[m] for m in modules}
    return per_mod or {}


def write_edge_file(path: str, edges: Dict[int, int]) -> None:
    text = "".join(f"{e}:{c}\n" for e, c in sorted(edges.items()))
    write_buffer_to_file(path, text.encode())


def write_pair_file(path: str, pairs) -> None:
    """Reference text edge format: one ``from:to`` line per edge
    (tracer/main.c:254-270)."""
    text = "".join(f"{f}:{t}\n" for f, t in sorted(pairs))
    write_buffer_to_file(path, text.encode())


def read_pair_file(path: str):
    """{(from, to), ...} from a reference-format edge file."""
    pairs = set()
    for line in read_file(path).decode().splitlines():
        line = line.strip()
        if not line:
            continue
        f, t = line.split(":")
        pairs.add((int(f), int(t)))
    return pairs


def read_edge_file(path: str) -> Dict[int, int]:
    edges: Dict[int, int] = {}
    for line in read_file(path).decode().splitlines():
        line = line.strip()
        if not line:
            continue
        e, c = line.split(":")
        edges[int(e)] = int(c)
    return edges


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="killerbeez-tpu-tracer",
        description="dump the deterministic edge set of one input")
    p.add_argument("driver", help="driver name (file, stdin, ...)")
    p.add_argument("instrumentation",
                   help="instrumentation name (afl, jit_harness, ...)")
    p.add_argument("-sf", "--seed-file", required=True,
                   help="the input to trace")
    p.add_argument("-n", "--iterations", type=int, default=5,
                   help="runs to intersect (default 5)")
    p.add_argument("-d", "--driver-options", help="driver JSON options")
    p.add_argument("-i", "--instrumentation-options",
                   help="instrumentation JSON options (edges forced on)")
    p.add_argument("-o", "--output", required=True,
                   help="edge file to write (edge:count lines; pairs "
                        "mode appends .<module> with >1 module)")
    p.add_argument("-f", "--format", choices=("slots", "pairs"),
                   default="slots",
                   help='"slots" = slot:count lines; "pairs" = the '
                        "reference's from:to text records, one file "
                        "per module (tracer/main.c:254-270)")
    p.add_argument("-l", "--logging-options", help="logging JSON options")
    args = p.parse_args(argv)
    try:
        setup_logging(args.logging_options)
        instrumentation = instrumentation_factory(
            args.instrumentation,
            force_edges_option(args.instrumentation_options))
        driver = driver_factory(args.driver, args.driver_options,
                                instrumentation, None)
        data = read_file(args.seed_file)
        if args.format == "pairs":
            per_mod = trace_deterministic_pairs(
                driver, instrumentation, data, args.iterations)
            for module, pairs in per_mod.items():
                out = args.output if len(per_mod) == 1 else \
                    f"{args.output}.{module}"
                write_pair_file(out, pairs)
                INFO_MSG("%s: %d deterministic edges (of %d runs) -> %s",
                         module, len(pairs), args.iterations, out)
        else:
            edges = trace_deterministic_edges(
                driver, instrumentation, data, args.iterations)
            write_edge_file(args.output, edges)
            INFO_MSG("%d deterministic edges (of %d runs) -> %s",
                     len(edges), args.iterations, args.output)
        driver.cleanup()
        instrumentation.cleanup()
        return 0
    except (ValueError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
