"""Per-byte mutation-heat rendering (FMViz-style) from the corpus
store's mutation-provenance sidecars.

Every admitted entry records WHICH child byte positions its mutation
rewrote (the learn tier's provenance bitmap, corpus/store.py).
Folding those bitmaps back onto each PARENT's buffer yields a
per-byte heat count: how many admitted, edge-novel children came
from mutating that byte.  Rendered over a hex dump it is the classic
FMViz picture — hot bytes are where the format yields, cold runs are
the magic words, length fields and framing the campaign never
profited from touching (exactly the bytes a grammar pin protects;
docs/GRAMMAR.md).

Shared by ``kb-corpus heat`` (store-wide, per-parent panels) and
``kb-timeline --heat`` (the campaign output dir's ``corpus/`` store
next to the flight recorder's time axis).  Pure stdlib + numpy; the
ANSI ramp degrades to a character ramp under ``color=False``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

#: character ramp for the no-color heat line (cold -> hot)
RAMP = " .:-=+*#%@"

#: ANSI SGR per heat bucket (cold -> hot): dim, green, yellow, red
_COLORS = ("2", "32", "33", "31")


class _BaseEntry:
    """Pseudo-entry for the campaign's base seed (lineage root
    ``parent == "base"`` — not itself a store entry)."""

    def __init__(self, buf: bytes):
        self.md5 = "base"
        self.buf = bytes(buf)


def accumulate_heat(entries, base: Optional[bytes] = None
                    ) -> List[Tuple[object, np.ndarray, int]]:
    """Fold every child's provenance bitmap onto its parent buffer.

    ``entries`` are CorpusEntry-likes (md5 / buf / parent /
    provenance attrs); ``base`` optionally supplies the campaign's
    base seed bytes so first-generation children (lineage root
    ``"base"``) render too.  Returns ``[(parent_entry, counts,
    children), ...]`` sorted hottest-first, where ``counts`` is
    int64[len(parent.buf)] admitted-children-per-position and
    ``children`` is how many labeled children contributed.  Parents
    that cannot be resolved to bytes (evicted entries, ``base``
    without the seed) are skipped; children without provenance (pre-
    learn sidecars) contribute nothing, by design."""
    from ..learn.dataset import provenance_positions

    by_md5 = {e.md5: e for e in entries}
    if base:
        by_md5.setdefault("base", _BaseEntry(base))
    counts: Dict[str, np.ndarray] = {}
    kids: Dict[str, int] = {}
    for e in entries:
        prov = getattr(e, "provenance", None)
        if not isinstance(prov, dict):
            continue
        parent = by_md5.get(getattr(e, "parent", None) or "base")
        if parent is None or not parent.buf:
            continue
        pos = provenance_positions(prov, len(e.buf))
        if pos is None or pos.size == 0:
            continue
        acc = counts.setdefault(
            parent.md5, np.zeros(len(parent.buf), np.int64))
        # positions index the CHILD; heat lands on the parent bytes
        # that were rewritten (clip to the parent's length)
        inb = pos[pos < acc.size]
        if inb.size == 0:
            continue
        acc[inb] += 1
        kids[parent.md5] = kids.get(parent.md5, 0) + 1
    out = [(by_md5[m], c, kids.get(m, 0)) for m, c in counts.items()]
    out.sort(key=lambda t: (-int(t[1].sum()), t[0].md5))
    return out


def _bucket(count: int, peak: int) -> int:
    """0..len(_COLORS)-1 heat bucket (0 = never mutated)."""
    if count <= 0 or peak <= 0:
        return 0
    return 1 + min(int(3 * (count - 1) / max(peak, 1)),
                   len(_COLORS) - 2)


def render_heat(buf: bytes, counts: np.ndarray, width: int = 16,
                color: bool = True) -> str:
    """One parent's heat panel: a hex dump with each byte shaded by
    its admitted-mutation count (ANSI ramp), or — with ``color``
    off — a character-ramp line under each hex row."""
    buf = bytes(buf)
    counts = np.asarray(counts, np.int64)
    peak = int(counts.max()) if counts.size else 0
    lines = []
    for off in range(0, len(buf), width):
        row = buf[off:off + width]
        hexes, chars, heats = [], [], []
        for j, b in enumerate(row):
            c = int(counts[off + j]) if off + j < counts.size else 0
            h = f"{b:02x}"
            if color:
                h = f"\x1b[{_COLORS[_bucket(c, peak)]}m{h}\x1b[0m"
            hexes.append(h)
            chars.append(chr(b) if 32 <= b < 127 else ".")
            heats.append(RAMP[min(int(9 * c / peak) if peak else 0,
                                  9)] * 2)
        pad = "   " * (width - len(row))
        lines.append(f"{off:08x}  {' '.join(hexes)}{pad}  "
                     f"|{''.join(chars)}|")
        if not color:
            lines.append(f"{'':8}  {' '.join(heats)}")
    return "\n".join(lines)


def render_store_heat(entries, top: int = 4, width: int = 16,
                      color: bool = True,
                      only_md5: Optional[str] = None,
                      base: Optional[bytes] = None) -> str:
    """The store-wide view: the ``top`` hottest parents' panels (or
    one specific parent via ``only_md5``), each headed by its
    lineage stats, plus a legend."""
    panels = accumulate_heat(entries, base=base)
    if only_md5:
        panels = [p for p in panels
                  if p[0].md5.startswith(only_md5)]
        if not panels:
            return (f"no mutation provenance accumulated on parent "
                    f"{only_md5!r} (children carry the bitmaps; the "
                    f"parent must still be in the store)")
    if not panels:
        return ("no renderable mutation provenance — run a campaign "
                "with the learn tier's sidecars to collect heat, "
                "and pass the base seed (--base) when the lineage "
                "still roots at it")
    lines = []
    for e, counts, children in panels[:max(top, 1)]:
        hot = int(np.argmax(counts)) if counts.size else 0
        lines.append(
            f"parent {e.md5}  ({len(e.buf)} bytes, {children} "
            f"admitted children, hottest byte {hot} "
            f"x{int(counts[hot]) if counts.size else 0})")
        lines.append(render_heat(e.buf, counts, width=width,
                                 color=color))
        lines.append("")
    legend = ("legend: " + ("dim/green/yellow/red = never/cool/warm/"
                            "hot" if color else
                            f"ramp '{RAMP}' cold -> hot"))
    lines.append(legend)
    return "\n".join(lines)


def heat_report(entries, top: int = 4,
                base: Optional[bytes] = None) -> List[Dict]:
    """JSON-able per-parent heat summary (kb-timeline --json)."""
    out = []
    for e, counts, children in accumulate_heat(entries,
                                               base=base)[:top]:
        nz = np.flatnonzero(counts)
        out.append({
            "parent": e.md5, "bytes": len(e.buf),
            "children": int(children),
            "mutated_positions": int(nz.size),
            "peak": int(counts.max()) if counts.size else 0,
            "counts": counts.tolist(),
        })
    return out
