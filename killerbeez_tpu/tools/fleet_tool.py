"""kb-fleet — fleet observatory console (afl-whatsup, one level up).

Polls a manager's ``/api/fleet`` endpoints and renders the fleet the
way kb-stats renders one campaign: a per-worker health/rate table
(who is healthy/stale/dead, how fast each worker is going, who found
what), fleet totals from the merged snapshot, and the alert
evaluator's current states.  Plain ANSI like kb-stats — works over
any ssh/tmux, degrades to sequential frames when piped.

    kb-fleet http://mgr:8650                      # campaigns index
    kb-fleet http://mgr:8650 --campaign 7         # health/rate table
    kb-fleet http://mgr:8650 --campaign 7 --watch # live redraw
    kb-fleet http://mgr:8650 --campaign 7 --json  # raw API body
    kb-fleet http://mgr:8650 --campaign 7 --plot-data > fleet_plot
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from typing import Any, Dict, List, Optional


def _get(url: str) -> Any:
    with urllib.request.urlopen(url, timeout=30) as resp:
        return json.loads(resp.read())


def _fmt_n(v: float) -> str:
    for unit, div in (("G", 1e9), ("M", 1e6), ("k", 1e3)):
        if abs(v) >= div:
            return f"{v / div:.2f}{unit}"
    return f"{v:.0f}" if v == int(v) else f"{v:.1f}"


def _fmt_age(s: float) -> str:
    if s >= 3600:
        return f"{s / 3600:.1f}h"
    if s >= 60:
        return f"{s / 60:.1f}m"
    return f"{s:.1f}s"


def render_index(body: Dict[str, Any], url: str) -> str:
    lines = [f"kb-fleet — campaigns @ {url}"]
    lines.append("=" * len(lines[0]))
    campaigns = body.get("campaigns", {})
    if not campaigns:
        lines.append("  (no campaigns have heartbeated yet)")
    for name, c in sorted(campaigns.items()):
        lines.append(
            f"  {name:<16} {c.get('n_workers', 0)} workers "
            f"({c.get('healthy', 0)} healthy / "
            f"{c.get('stale', 0)} stale / {c.get('dead', 0)} dead)")
    return "\n".join(lines)


def render_fleet(body: Dict[str, Any], url: str) -> str:
    lines: List[str] = []
    head = f"kb-fleet — campaign {body.get('campaign')} @ {url}"
    lines.append(head)
    lines.append("=" * len(head))
    counts = body.get("counts", {})
    cfg = body.get("config", {})
    lines.append(
        f"  workers : {body.get('n_workers', 0)} "
        f"({counts.get('healthy', 0)} healthy / "
        f"{counts.get('stale', 0)} stale / "
        f"{counts.get('dead', 0)} dead)"
        f"    [stale>{cfg.get('stale_after', 0):g}s "
        f"dead>{cfg.get('dead_after', 0):g}s]")
    merged = body.get("merged") or {}
    c = merged.get("counters", {})
    r = merged.get("rates", {})
    if c:
        lines.append(
            f"  fleet   : {_fmt_n(c.get('execs', 0))} execs"
            f" | {_fmt_n(r.get('execs', {}).get('rate', 0.0))}/s ema"
            f" | {_fmt_n(c.get('new_paths', 0))} paths"
            f" | {_fmt_n(c.get('crashes', 0))} crashes "
            f"({_fmt_n(c.get('unique_crashes', 0))} uniq)"
            f" | {_fmt_n(c.get('hangs', 0))} hangs")
    if c.get("gossip_rounds") or c.get("sync_quarantined") \
            or c.get("peers_banned"):
        lines.append(
            f"  gossip  : "
            f"{_fmt_n(c.get('gossip_entries_in', 0))} in / "
            f"{_fmt_n(c.get('gossip_entries_out', 0))} out"
            f" | {_fmt_n(c.get('sync_quarantined', 0))} quarantined"
            f" | {_fmt_n(c.get('peers_banned', 0))} peer bans")
    # hybrid campaigns (docs/HYBRID.md): per-tier worker fold + the
    # cross-tier validation rollup — hidden for pure TPU fleets
    tiers = body.get("tiers") or {}
    if len(tiers) > 1 or (tiers and "tpu" not in tiers):
        parts = []
        for t in sorted(tiers):
            tv = tiers[t]
            tc = tv.get("counters", {})
            parts.append(
                f"{t} {tv.get('n_workers', 0)}w "
                f"({_fmt_n(tc.get('execs', 0))} execs, "
                f"{_fmt_n(tc.get('crashes', 0))} crashes)")
        lines.append("  tiers   : " + " | ".join(parts))
    val = body.get("validation") or {}
    if val.get("validations") or val.get("queue_depth"):
        v = val.get("verdicts", {})
        lines.append(
            f"  hybrid  : {_fmt_n(val.get('validations', 0))} "
            f"validated"
            f" | {_fmt_n(v.get('confirmed', 0))} confirmed / "
            f"{_fmt_n(v.get('proxy_only', 0))} proxy-only / "
            f"{_fmt_n(v.get('flaky', 0))} flaky"
            f" | queue {val.get('queue_depth', 0)} "
            f"(oldest {_fmt_age(val.get('queue_age_s', 0.0))})")
    active = [a for a in body.get("alerts", []) if a.get("active")]
    if active:
        now = body.get("t", time.time())
        for a in active:
            since = a.get("since")
            age = f" for {_fmt_age(now - since)}" if since else ""
            det = a.get("details") or {}
            det_s = (" (" + ", ".join(f"{k}={v}"
                                      for k, v in sorted(det.items()))
                     + ")") if det else ""
            lines.append(f"  ALERT   : {a['alert']} active{age}"
                         f"{det_s}")
    else:
        lines.append("  alerts  : none active")
    workers = body.get("workers", {})
    if workers:
        lines.append("")
        lines.append(
            f"  {'worker':<18} {'status':<8} {'tier':<7} "
            f"{'last seen':>9} "
            f"{'execs':>8} {'execs/s':>9} {'paths':>6} "
            f"{'crashes':>7} {'hangs':>6}")
        for name in sorted(workers):
            w = workers[name]
            s = w.get("stats", {})
            meta = w.get("meta") or {}
            tier = meta.get("tier") or "tpu"
            lines.append(
                f"  {name:<18} {w.get('status', '?'):<8} "
                f"{tier:<7} "
                f"{_fmt_age(w.get('age', 0.0)):>9} "
                f"{_fmt_n(s.get('execs', 0)):>8} "
                f"{_fmt_n(s.get('execs_per_sec_ema', 0.0)):>9} "
                f"{_fmt_n(s.get('new_paths', 0)):>6} "
                f"{_fmt_n(s.get('crashes', 0)):>7} "
                f"{_fmt_n(s.get('hangs', 0)):>6}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="kb-fleet",
        description="fleet observatory console: per-worker health/"
                    "rate table, fleet totals and alert states from "
                    "a manager's /api/fleet endpoints")
    p.add_argument("manager", help="manager base URL "
                                   "(e.g. http://mgr:8650)")
    p.add_argument("--campaign",
                   help="campaign key (job id); omit to list "
                        "campaigns")
    p.add_argument("--json", action="store_true",
                   help="print the raw API response (scripts/CI)")
    p.add_argument("--watch", action="store_true",
                   help="ANSI live-redraw mode (ctrl-c exits)")
    p.add_argument("-i", "--interval", type=float, default=2.0,
                   help="refresh seconds for --watch (default 2)")
    p.add_argument("--plot-data", action="store_true",
                   help="dump the fleet-wide afl-plot-compatible "
                        "CSV from /api/fleet/<campaign>/series and "
                        "exit")
    args = p.parse_args(argv)
    url = args.manager.rstrip("/")

    if args.plot_data:
        if not args.campaign:
            print("error: --plot-data needs --campaign",
                  file=sys.stderr)
            return 2
        try:
            with urllib.request.urlopen(
                    f"{url}/api/fleet/{args.campaign}/series"
                    f"?format=plot", timeout=30) as resp:
                sys.stdout.write(resp.read().decode())
            return 0
        except (OSError, ValueError) as e:
            print(f"error: series fetch failed: {e}",
                  file=sys.stderr)
            return 1

    def frame() -> Optional[str]:
        try:
            if args.campaign:
                body = _get(f"{url}/api/fleet/{args.campaign}")
                # the no-workers gate applies to --json too: the
                # documented contract is a nonzero exit scripts can
                # gate on, and --json is the scripting mode
                if not body.get("n_workers"):
                    print(f"error: no workers seen for campaign "
                          f"{args.campaign!r} at {url}",
                          file=sys.stderr)
                    return None
                if args.json:
                    return json.dumps(body, indent=2)
                return render_fleet(body, url)
            body = _get(f"{url}/api/fleet")
            if args.json:
                return json.dumps(body, indent=2)
            return render_index(body, url)
        except (OSError, ValueError) as e:
            print(f"error: manager at {url} unreachable: {e}",
                  file=sys.stderr)
            return None

    if not args.watch:
        out = frame()
        if out is None:
            return 1
        print(out)
        return 0
    try:
        while True:
            out = frame()
            sys.stdout.write("\x1b[H\x1b[J")
            sys.stdout.write(out if out is not None
                             else "waiting for manager ...")
            sys.stdout.write("\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
