"""ctypes bindings for libkbexec — the C++ host exec backend.

``ExecTarget`` wraps one target process configuration: plain
fork+execve or forkserver (fds 198/199 protocol), optional SysV-SHM
coverage bitmap, persistence, deferred startup and LD_PRELOAD.

Status codes from the C layer (kb_exec.cpp):
    0..255   exit code          512+sig  killed by signal
    -1       hang (timeout)     -2       backend error
``classify()`` maps them to the framework's FUZZ_* verdicts.
"""

from __future__ import annotations

import ctypes as ct
import os
import tempfile
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .. import FUZZ_CRASH, FUZZ_ERROR, FUZZ_HANG, FUZZ_NONE
from .build import exec_lib_path, preload_path

KB_MAP_SIZE = 1 << 16
# per-module partitioning (KB_MODULES=1): mirrors kb_protocol.h
KB_N_MODULES = 8
KB_MOD_SIZE = KB_MAP_SIZE // KB_N_MODULES
KB_MODTAB_NAME = 64


class CrashInfo(ct.Structure):
    """Mirror of kb_crash_info (kb_exec.cpp debugger mode)."""
    _fields_ = [("signal_no", ct.c_int32),
                ("si_code", ct.c_int32),
                ("fault_addr", ct.c_uint64),
                ("pc", ct.c_uint64)]


_lib = None


def _load() -> ct.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    lib = ct.CDLL(exec_lib_path())
    lib.kb_target_create.restype = ct.c_void_p
    lib.kb_target_create.argtypes = [
        ct.POINTER(ct.c_char_p), ct.c_int, ct.c_char_p, ct.c_int,
        ct.c_char_p, ct.c_int, ct.c_int, ct.c_long, ct.c_int]
    lib.kb_target_start.restype = ct.c_int
    lib.kb_target_start.argtypes = [ct.c_void_p, ct.c_double]
    lib.kb_target_run.restype = ct.c_int
    lib.kb_target_run.argtypes = [ct.c_void_p, ct.c_char_p, ct.c_int32,
                                  ct.c_double]
    lib.kb_target_run_batch.restype = ct.c_int
    lib.kb_target_run_batch.argtypes = [
        ct.c_void_p, ct.c_void_p, ct.c_void_p, ct.c_int, ct.c_int,
        ct.c_double, ct.c_void_p, ct.c_void_p]
    lib.kb_target_launch.restype = ct.c_int
    lib.kb_target_launch.argtypes = [ct.c_void_p, ct.c_double]
    lib.kb_target_alive.restype = ct.c_int
    lib.kb_target_alive.argtypes = [ct.c_void_p]
    lib.kb_target_wait_done.restype = ct.c_int
    lib.kb_target_wait_done.argtypes = [ct.c_void_p, ct.c_double]
    lib.kb_target_fork.restype = ct.c_int
    lib.kb_target_fork.argtypes = [ct.c_void_p, ct.c_double]
    lib.kb_target_resume.restype = ct.c_int
    lib.kb_target_resume.argtypes = [ct.c_void_p, ct.c_double]
    lib.kb_target_run_debug.restype = ct.c_int
    lib.kb_target_run_debug.argtypes = [
        ct.c_void_p, ct.c_char_p, ct.c_int32, ct.c_double,
        ct.POINTER(CrashInfo)]
    lib.kb_target_trace_bits.restype = ct.POINTER(ct.c_uint8)
    lib.kb_target_trace_bits.argtypes = [ct.c_void_p]
    lib.kb_target_module_table.restype = ct.c_void_p
    lib.kb_target_module_table.argtypes = [ct.c_void_p]
    lib.kb_target_add_env.argtypes = [ct.c_void_p, ct.c_char_p]
    lib.kb_target_clear_trace.argtypes = [ct.c_void_p]
    lib.kb_target_pid.restype = ct.c_int
    lib.kb_target_pid.argtypes = [ct.c_void_p]
    lib.kb_target_total_execs.restype = ct.c_long
    lib.kb_target_total_execs.argtypes = [ct.c_void_p]
    lib.kb_target_stop.argtypes = [ct.c_void_p]
    lib.kb_target_free.argtypes = [ct.c_void_p]
    lib.kb_last_error.restype = ct.c_char_p
    _lib = lib
    return lib


def classify(status: int) -> Tuple[int, int]:
    """(FUZZ_* verdict, exit_code) from a backend status code."""
    if status == -1:
        return FUZZ_HANG, -1
    if status == -2:
        return FUZZ_ERROR, -2
    if status >= 512:
        return FUZZ_CRASH, status - 512
    return FUZZ_NONE, status


def pool_token_matches(arg: str, input_file: str) -> bool:
    """True when ``arg`` carries ``input_file`` in a form ExecPool can
    re-point per worker: the whole token, or a --flag=<path> value."""
    return arg == input_file or arg.endswith("=" + input_file)


def classify_batch(statuses_raw: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized classify() over a raw status array: (verdicts,
    exit_codes).  The single definition of the status encoding for
    batched host tiers (afl, host ipt) — ``<= -2`` covers both the
    error sentinel and result-padding lanes (-3)."""
    verdicts = np.full(len(statuses_raw), FUZZ_NONE, dtype=np.int32)
    verdicts[statuses_raw >= 512] = FUZZ_CRASH
    verdicts[statuses_raw == -1] = FUZZ_HANG
    verdicts[statuses_raw <= -2] = FUZZ_ERROR
    exit_codes = np.where(statuses_raw >= 512, statuses_raw - 512,
                          np.maximum(statuses_raw, 0)).astype(np.int32)
    return verdicts, exit_codes


def replay_message_train(target: "ExecTarget",
                         messages: Sequence[bytes],
                         mode: str = "stdin_train",
                         addr: Optional[Tuple[str, int]] = None,
                         timeout: Optional[float] = None,
                         connect_timeout: float = 5.0) -> int:
    """Replay a translated message train (hybrid bridge,
    docs/HYBRID.md) on a native target; returns a raw status code.

    ``stdin_train`` concatenates the messages onto the target's
    stdin — the child reads them sequentially off the pipe, which is
    the reference's stdin replay of a session.  ``tcp`` is the
    network_client / send_tcp_input pattern: launch() the server,
    connect, send each message as one write, half-close, then
    wait_done() for the verdict.  Connection failure returns the
    error sentinel (-2), never an exception — the validator's
    retry/backoff owns transient transport faults.
    """
    if mode in ("stdin", "stdin_train", "file"):
        return target.run(b"".join(messages), timeout)
    if mode != "tcp":
        raise ValueError(f"unknown replay mode {mode!r}")
    if not addr:
        raise ValueError("tcp replay needs addr=(host, port)")
    import socket

    target.launch()
    sock = None
    deadline = time.monotonic() + connect_timeout
    while time.monotonic() < deadline:
        try:
            sock = socket.create_connection(addr, timeout=1.0)
            break
        except OSError:
            if not target.alive():
                break
            time.sleep(0.05)
    if sock is None:
        target.wait_done(0.01)      # reap the launched child
        return -2
    try:
        with sock:
            for m in messages:
                sock.sendall(bytes(m))
            try:
                sock.shutdown(socket.SHUT_WR)
            except OSError:
                pass
            # drain any reply so the server isn't blocked on write
            sock.settimeout(0.25)
            try:
                while sock.recv(4096):
                    pass
            except OSError:
                pass
    except OSError:
        pass                        # verdict comes from wait_done
    return target.wait_done(timeout)


class ExecTarget:
    """One configured target; reusable across many executions."""

    def __init__(self, argv: Sequence[str], *,
                 use_stdin: bool = False,
                 input_file: Optional[str] = None,
                 use_forkserver: bool = False,
                 preload: Optional[str] = None,
                 use_preload_forkserver: bool = False,
                 persistent: int = 0,
                 deferred: bool = False,
                 mem_limit_mb: int = 0,
                 coverage: bool = False,
                 timeout: float = 2.0,
                 extra_env: Optional[Sequence[str]] = None):
        self._lib = _load()
        self.timeout = float(timeout)
        self._owns_input_file = input_file is None and use_stdin
        if self._owns_input_file:
            fd, input_file = tempfile.mkstemp(prefix="kb_input_")
            os.close(fd)
        self.input_file = input_file
        if use_preload_forkserver and not preload:
            preload = preload_path()

        c_argv = (ct.c_char_p * (len(argv) + 1))()
        for i, a in enumerate(argv):
            c_argv[i] = a.encode()
        c_argv[len(argv)] = None
        self._h = self._lib.kb_target_create(
            c_argv, int(use_stdin),
            input_file.encode() if input_file else None,
            int(use_forkserver),
            preload.encode() if preload else None,
            int(persistent), int(deferred), int(mem_limit_mb),
            int(coverage))
        if not self._h:
            raise RuntimeError(
                f"kb_target_create: {self._lib.kb_last_error().decode()}")
        for kv in (extra_env or ()):
            # per-target child env (e.g. KB_MODULES=1) — never the
            # fuzzer's own process-global environment
            self._lib.kb_target_add_env(self._h, kv.encode())
        self.coverage = coverage
        self.use_forkserver = use_forkserver
        self._started = False

    def start(self, timeout: float = 10.0) -> None:
        if self._lib.kb_target_start(self._h, timeout) != 0:
            raise RuntimeError(
                f"kb_target_start: {self._lib.kb_last_error().decode()}")
        self._started = True

    def _ensure_started(self) -> None:
        if not self._started:
            self.start()

    def run(self, data: bytes, timeout: Optional[float] = None) -> int:
        """Execute one input; returns the raw backend status code."""
        self._ensure_started()
        return self._lib.kb_target_run(
            self._h, data, len(data),
            self.timeout if timeout is None else timeout)

    def run_batch(self, inputs: np.ndarray, lengths: np.ndarray,
                  want_bitmaps: bool = True,
                  timeout: Optional[float] = None
                  ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Execute a [B, L] uint8 batch. Returns (statuses int32[B],
        bitmaps uint8[B, 64K] or None). One ctypes call for the whole
        batch — the C layer loops, clearing + copying the SHM bitmap
        per exec."""
        self._ensure_started()
        inputs = np.ascontiguousarray(inputs, dtype=np.uint8)
        lengths = np.ascontiguousarray(lengths, dtype=np.int32)
        n, stride = inputs.shape
        statuses = np.empty(n, dtype=np.int32)
        bitmaps = (np.empty((n, KB_MAP_SIZE), dtype=np.uint8)
                   if (want_bitmaps and self.coverage) else None)
        done = self._lib.kb_target_run_batch(
            self._h, inputs.ctypes.data_as(ct.c_void_p),
            lengths.ctypes.data_as(ct.c_void_p), n, stride,
            self.timeout if timeout is None else timeout,
            statuses.ctypes.data_as(ct.c_void_p),
            bitmaps.ctypes.data_as(ct.c_void_p)
            if bitmaps is not None else None)
        if done < n:
            statuses[done:] = -2
            if bitmaps is not None:
                # never triage uninitialized rows: zero = no coverage
                bitmaps[done:] = 0
        return statuses, bitmaps

    def run_debug(self, data: bytes, timeout: Optional[float] = None
                  ) -> Tuple[int, dict]:
        """Execute one input under ptrace (debugger mode, no
        forkserver); returns (status, crash_info dict). crash_info
        carries signal/si_code/fault_addr/pc when the run faulted."""
        self._ensure_started()
        info = CrashInfo()
        st = self._lib.kb_target_run_debug(
            self._h, data, len(data),
            self.timeout if timeout is None else timeout,
            ct.byref(info))
        return st, {"signal": int(info.signal_no),
                    "si_code": int(info.si_code),
                    "fault_addr": int(info.fault_addr),
                    "pc": int(info.pc)}

    def launch(self, timeout: float = 10.0) -> int:
        """Start one exec WITHOUT waiting (network-driver pattern:
        start the server, talk to it, then wait_done). Returns pid."""
        self._ensure_started()
        pid = self._lib.kb_target_launch(self._h, timeout)
        if pid <= 0:
            raise RuntimeError(
                f"kb_target_launch: {self._lib.kb_last_error().decode()}")
        return pid

    def alive(self) -> bool:
        return bool(self._lib.kb_target_alive(self._h))

    def wait_done(self, timeout: Optional[float] = None) -> int:
        """Collect the verdict of a launch()ed exec; kills on timeout
        (hang). Returns a raw backend status code."""
        return self._lib.kb_target_wait_done(
            self._h, self.timeout if timeout is None else timeout)

    def fork_stopped(self, timeout: float = 10.0) -> int:
        """FORK command: spawn a child left SIGSTOPped (tracer attach
        window). Returns the child pid."""
        self._ensure_started()
        pid = self._lib.kb_target_fork(self._h, timeout)
        if pid <= 0:
            raise RuntimeError(
                f"kb_target_fork: {self._lib.kb_last_error().decode()}")
        return pid

    def resume(self, timeout: Optional[float] = None) -> int:
        """RUN + GET_STATUS on a forked child; returns a status code."""
        return self._lib.kb_target_resume(
            self._h, self.timeout if timeout is None else timeout)

    def trace_bits(self) -> Optional[np.ndarray]:
        """Zero-copy view of the live SHM coverage bitmap."""
        if not self.coverage:
            return None
        ptr = self._lib.kb_target_trace_bits(self._h)
        return np.ctypeslib.as_array(ptr, shape=(KB_MAP_SIZE,))

    def clear_trace(self) -> None:
        self._lib.kb_target_clear_trace(self._h)

    def module_table(self) -> List[str]:
        """Names of the modules that claimed map partitions (targets
        run with KB_MODULES=1); index = submap number.  Warns once if
        an entry's degraded-accounting flag (byte KB_MODTAB_NAME-1,
        set by kb_rt on table overflow or truncated-name merge) shows
        that its partition aliases multiple modules."""
        ptr = self._lib.kb_target_module_table(self._h)
        if not ptr:
            return []
        out = []
        degraded = []
        for i in range(KB_N_MODULES):
            # bounded read: byte KB_MODTAB_NAME-1 is the flag, not
            # part of the (always-NUL-terminated-within-width) name
            name = ct.string_at(ptr + i * KB_MODTAB_NAME,
                                KB_MODTAB_NAME - 1).split(b"\x00")[0]
            if name:
                out.append(name.decode(errors="replace"))
                flag = ct.string_at(
                    ptr + i * KB_MODTAB_NAME + KB_MODTAB_NAME - 1, 1)
                # bit 0 = partition aliases multiple modules; bit 1
                # is kb_rt's "name truncated" bookkeeping, not by
                # itself a degradation
                if flag[0] & 1:
                    degraded.append(out[-1])
        if degraded and not getattr(self, "_modtab_warned", False):
            self._modtab_warned = True
            from ..utils.logging import WARNING_MSG
            WARNING_MSG(
                "per-module coverage degraded: partition(s) %s alias "
                "multiple modules (>%d kb-cc modules registered, or "
                "basenames truncated at %d chars collided)",
                degraded, KB_N_MODULES, KB_MODTAB_NAME - 1)
        return out

    def total_execs(self) -> int:
        return int(self._lib.kb_target_total_execs(self._h))

    def stop(self) -> None:
        if self._h:
            self._lib.kb_target_stop(self._h)

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.kb_target_free(self._h)
            self._h = None
        if self._owns_input_file and self.input_file:
            try:
                os.unlink(self.input_file)
            except OSError:
                pass
            self.input_file = None

    def __enter__(self) -> "ExecTarget":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass


class ExecPool:
    """N independent forkserver instances fed batch shards in parallel.

    The reference scales host throughput by running N fuzzer processes
    with distinct SHM names (dynamorio_instrumentation.c:418-431 picks
    a random fuzzer_id per instance); here one fuzzer process shards
    each batch across N ``ExecTarget`` instances — each with its own
    forkserver, IPC_PRIVATE SHM segment and private input file — on a
    thread pool.  ctypes releases the GIL for the duration of
    ``kb_target_run_batch``, so the C exec loops genuinely overlap.

    File-mode delivery (``input_file`` set): each worker derives a
    private ``<input_file>.wN`` path and gets the argv with the
    driver's ``@@`` substitution re-pointed at it, matching the
    reference's per-instance input files
    (dynamorio_instrumentation.c:418-431).  Stdin mode mints a temp
    file per worker as before.

    The single-exec surface (``run``/``trace_bits``/...) delegates to
    worker 0, so an ExecPool drops into ExecTarget call sites.
    """

    def __init__(self, argv: Sequence[str], n_workers: int, **kwargs):
        from concurrent.futures import ThreadPoolExecutor
        input_file = kwargs.pop("input_file", None)
        self._derived_files: list = []
        if input_file:
            if not any(pool_token_matches(a, input_file) for a in argv):
                raise ValueError(
                    "ExecPool file mode needs the input file as an "
                    f"argv token (or --flag={input_file!r} value); it "
                    "is absent or embedded mid-argument (callers "
                    "degrade such targets to a single instance)")
            self.targets = []
            root, ext = os.path.splitext(input_file)
            for i in range(max(n_workers, 1)):
                # suffix BEFORE the extension: format-sniffing targets
                # that validate the input path's extension keep seeing
                # it (in.png -> in.w0.png, not in.png.w0).  Only whole
                # tokens and --flag=<path> values are re-pointed — a
                # raw substring replace would corrupt companion
                # arguments like --dict=<input>.dict that nobody
                # stages per worker.
                f_i = f"{root}.w{i}{ext}"
                argv_i = [
                    f_i if a == input_file
                    else (a[:-len(input_file)] + f_i
                          if a.endswith("=" + input_file) else a)
                    for a in argv]
                self.targets.append(
                    ExecTarget(argv_i, input_file=f_i, **kwargs))
                self._derived_files.append(f_i)
        else:
            self.targets = [ExecTarget(argv, **kwargs)
                            for _ in range(max(n_workers, 1))]
        self._tp = ThreadPoolExecutor(max_workers=len(self.targets))
        self.coverage = self.targets[0].coverage
        self.timeout = self.targets[0].timeout

    @property
    def n_workers(self) -> int:
        return len(self.targets)

    def run_batch(self, inputs: np.ndarray, lengths: np.ndarray,
                  want_bitmaps: bool = True,
                  timeout: Optional[float] = None
                  ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        n = inputs.shape[0]
        bounds = np.linspace(0, n, len(self.targets) + 1).astype(int)
        shards = [(self.targets[i], bounds[i], bounds[i + 1])
                  for i in range(len(self.targets))
                  if bounds[i + 1] > bounds[i]]
        futs = [self._tp.submit(t.run_batch, inputs[lo:hi],
                                lengths[lo:hi], want_bitmaps, timeout)
                for t, lo, hi in shards]
        stats, maps = [], []
        for f in futs:
            s, m = f.result()
            stats.append(s)
            maps.append(m)
        statuses = np.concatenate(stats) if stats else \
            np.empty(0, dtype=np.int32)
        bitmaps = (np.concatenate(maps)
                   if want_bitmaps and self.coverage and maps else None)
        return statuses, bitmaps

    # -- single-exec surface: worker 0 ---------------------------------

    def run(self, data: bytes, timeout: Optional[float] = None) -> int:
        return self.targets[0].run(data, timeout)

    def run_debug(self, data: bytes, timeout: Optional[float] = None):
        return self.targets[0].run_debug(data, timeout)

    def launch(self, timeout: float = 10.0) -> int:
        return self.targets[0].launch(timeout)

    def alive(self) -> bool:
        return self.targets[0].alive()

    def wait_done(self, timeout: Optional[float] = None) -> int:
        return self.targets[0].wait_done(timeout)

    def trace_bits(self) -> Optional[np.ndarray]:
        return self.targets[0].trace_bits()

    def clear_trace(self) -> None:
        self.targets[0].clear_trace()

    def module_table(self) -> List[str]:
        return self.targets[0].module_table()

    def total_execs(self) -> int:
        return sum(t.total_execs() for t in self.targets)

    def close(self) -> None:
        self._tp.shutdown(wait=True)
        for t in self.targets:
            t.close()
        for f in self._derived_files:
            try:
                os.unlink(f)
            except OSError:
                pass
        self._derived_files = []

    def __enter__(self) -> "ExecPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
