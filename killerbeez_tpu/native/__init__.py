"""Native (C/C++) host components: exec backend, target runtime,
preload forkserver, kb-cc wrapper. See native/ at the repo root for
the sources and killerbeez_tpu.native.exec_backend for the bindings."""

from .build import (  # noqa: F401
    build_native, kb_cc_path, native_available, preload_path, rt_obj_path,
)
from .exec_backend import ExecTarget, KB_MAP_SIZE  # noqa: F401
