"""On-demand build of the native components (native/ at the repo
root) — make is invoked at most once per process and only when an
artifact is missing or older than its sources. Keeps `pip install`
out of the loop: the toolchain (gcc/g++/make) is part of the image.
"""

from __future__ import annotations

import os
import subprocess
import threading
from typing import Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
# The C sources live in the repository's top-level native/ (KB_NATIVE_DIR
# overrides).  A pip-installed wheel does not ship them: the host exec
# tier (afl/return_code/debug/preload) needs a source checkout — the
# device tiers (jit_harness/ipt) work from the wheel alone.
NATIVE_DIR = os.environ.get(
    "KB_NATIVE_DIR", os.path.join(_REPO_ROOT, "native"))
BUILD_DIR = os.path.join(NATIVE_DIR, "build")

_lock = threading.Lock()
_built = False
_build_error: Optional[str] = None

_ARTIFACTS = ("libkbexec.so", "kb_rt.o", "libkbpreload.so", "kb-cc",
              "kb-trace")
_SOURCES = ("kb_exec.cpp", "kb_rt.c", "kb_preload.c", "kb_cc.c",
            "kb_trace.c", "kb_protocol.h", "Makefile")


def _stale() -> bool:
    try:
        newest_src = max(
            os.path.getmtime(os.path.join(NATIVE_DIR, s)) for s in _SOURCES)
    except OSError:
        return True
    for a in _ARTIFACTS:
        p = os.path.join(BUILD_DIR, a)
        if not os.path.exists(p) or os.path.getmtime(p) < newest_src:
            return True
    return False


def build_native(force: bool = False) -> bool:
    """Ensure native artifacts exist and are current. Returns True on
    success; failures are cached (native_available() stays False)."""
    global _built, _build_error
    with _lock:
        if _built and not force:
            return _build_error is None
        _built = True
        if not os.path.isdir(NATIVE_DIR):
            _build_error = (f"native source dir missing: {NATIVE_DIR} — the host exec tier needs a source checkout (or KB_NATIVE_DIR pointing at the native/ sources); pip-installed wheels ship only the device tiers")
            return False
        if not force and not _stale():
            _build_error = None
            return True
        proc = subprocess.run(
            ["make", "-C", NATIVE_DIR], capture_output=True, text=True)
        if proc.returncode != 0:
            _build_error = proc.stderr[-2000:]
            return False
        _build_error = None
        return True


def native_available() -> bool:
    return build_native()


def build_error() -> Optional[str]:
    build_native()
    return _build_error


def _artifact(name: str) -> str:
    if not build_native():
        raise RuntimeError(f"native build failed: {_build_error}")
    return os.path.join(BUILD_DIR, name)


def exec_lib_path() -> str:
    return _artifact("libkbexec.so")


def rt_obj_path() -> str:
    return _artifact("kb_rt.o")


def preload_path() -> str:
    return _artifact("libkbpreload.so")


def kb_cc_path() -> str:
    return _artifact("kb-cc")


def kb_trace_path() -> str:
    """The bundled binary-only tracer (the QEMU-mode tier's default
    emulator: forkserver + per-PC SHM coverage over ptrace)."""
    return _artifact("kb-trace")
