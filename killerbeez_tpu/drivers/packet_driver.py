"""Shared base for packet-sequence drivers (network_server /
network_client): both deliver the input as an ordered sequence of
network packets, mutate multi-part inputs via
``mutate_extended(MUTATE_MULTIPLE_INPUTS|i)`` and serialize the last
input with ``encode_mem_array`` (reference driver/network_*_driver.c
share the same glue through driver.c helpers — SURVEY §2.2)."""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from .. import FUZZ_NONE
from ..instrumentation.base import BatchResult
from ..mutators.base import MUTATE_MULTIPLE_INPUTS
from ..utils.serialization import decode_mem_array, encode_mem_array
from .base import BatchOutcome, Driver


class PacketDriver(Driver):
    """Delivers inputs as packet sequences; subclasses implement
    ``_run(parts)`` for their connection direction."""

    def __init__(self, options, instrumentation, mutator=None):
        super().__init__(options, instrumentation, mutator)
        if "path" not in self.options or "port" not in self.options:
            raise ValueError(
                f'{self.name} needs {{"path": ..., "port": ...}}')
        self.port = int(self.options["port"])
        self.udp = bool(self.options["udp"])
        self.num_inputs = 1
        self.input_sizes: List[int] = []
        if self.mutator is not None:
            self.num_inputs, self.input_sizes = \
                self.mutator.get_input_info()

    def _check_input_info(self) -> None:
        # Multi-input is the point of packet drivers; any part count.
        pass

    @property
    def supports_batch(self) -> bool:
        # Candidate GENERATION batches on-device (the manager mutator
        # runs every child's turns in one call); delivery stays
        # per-exec — live sockets can't be vectorized.
        return self.mutator is not None and self.mutator.batch_capable

    def test_batch(self, n: int, pad_to: Optional[int] = None,
                   prefetch_next=True) -> BatchOutcome:
        """Batch-mutate ``n`` packet sequences, deliver them one
        connection at a time, and assemble host-side result arrays
        (statuses/novelty from the instrumentation after each run).
        Saved inputs are encoded mem arrays, like the single-exec
        path's last_input."""
        if not self.supports_batch:
            raise RuntimeError(f"{self.name}: batch path unavailable")
        if hasattr(self.mutator, "mutate_batch_parts"):
            seqs = self.mutator.mutate_batch_parts(n)
        else:
            bufs, lens = self.mutator.mutate_batch(n)
            # one bulk transfer, not 2n per-lane device round trips
            bufs, lens = np.asarray(bufs), np.asarray(lens)
            seqs = [[bufs[j, :int(lens[j])].tobytes()] for j in range(n)]
        instr = self.instrumentation
        total = pad_to if (pad_to is not None and pad_to > n) else n
        statuses = np.full(total, FUZZ_NONE, dtype=np.int32)
        new_paths = np.zeros(total, dtype=np.int32)
        uc = np.zeros(total, dtype=bool)
        uh = np.zeros(total, dtype=bool)
        encoded: List[bytes] = []
        for j, parts in enumerate(seqs):
            statuses[j] = self._run(parts)
            new_paths[j] = instr.is_new_path()
            uc[j] = instr.last_unique_crash()
            uh[j] = instr.last_unique_hang()
            encoded.append(encode_mem_array(parts).encode())
        self.last_input = encoded[-1] if encoded else None
        from ..mutators.base import pack_byte_rows
        inputs, lengths = pack_byte_rows(encoded or [b""])
        if total > inputs.shape[0]:
            inputs = np.concatenate(
                [inputs, np.zeros((total - inputs.shape[0],
                                   inputs.shape[1]), np.uint8)])
            lengths = np.concatenate(
                [lengths, np.zeros(total - lengths.shape[0], np.int32)])
        result = BatchResult(statuses=statuses, new_paths=new_paths,
                             unique_crashes=uc, unique_hangs=uh,
                             exit_codes=np.zeros(total, dtype=np.int32))
        return BatchOutcome(result=result, inputs=inputs,
                            lengths=lengths)

    def _cmd_line(self) -> str:
        return (f'{self.options["path"]} '
                f'{self.options["arguments"]}').strip()

    def _run(self, parts: List[bytes]) -> int:
        raise NotImplementedError

    # -- vtable ---------------------------------------------------------

    def test_input(self, buf: bytes) -> int:
        """Input is an encoded mem array of packets (reference
        decode_mem_array contract); raw bytes = one packet."""
        try:
            parts = decode_mem_array(buf.decode())
        except Exception:
            parts = [buf]
        self.last_input = encode_mem_array(parts).encode()
        return self._run(parts)

    def test_next_input(self) -> Optional[int]:
        if self.mutator is None:
            raise RuntimeError(f"{self.name}: no mutator attached")
        parts: List[bytes] = []
        if self.num_inputs > 1:
            for i in range(self.num_inputs):
                part = self.mutator.mutate_extended(
                    MUTATE_MULTIPLE_INPUTS | i)
                if part is None:
                    return None
                parts.append(part)
        else:
            buf = self.mutator.mutate()
            if buf is None:
                return None
            parts = [buf]
        self.last_input = encode_mem_array(parts).encode()
        return self._run(parts)

    def get_last_input(self) -> Optional[bytes]:
        return self.last_input
