"""Shared base for packet-sequence drivers (network_server /
network_client): both deliver the input as an ordered sequence of
network packets, mutate multi-part inputs via
``mutate_extended(MUTATE_MULTIPLE_INPUTS|i)`` and serialize the last
input with ``encode_mem_array`` (reference driver/network_*_driver.c
share the same glue through driver.c helpers — SURVEY §2.2)."""

from __future__ import annotations

from typing import List, Optional

from ..mutators.base import MUTATE_MULTIPLE_INPUTS
from ..utils.serialization import decode_mem_array, encode_mem_array
from .base import Driver


class PacketDriver(Driver):
    """Delivers inputs as packet sequences; subclasses implement
    ``_run(parts)`` for their connection direction."""

    def __init__(self, options, instrumentation, mutator=None):
        super().__init__(options, instrumentation, mutator)
        if "path" not in self.options or "port" not in self.options:
            raise ValueError(
                f'{self.name} needs {{"path": ..., "port": ...}}')
        self.port = int(self.options["port"])
        self.udp = bool(self.options["udp"])
        self.num_inputs = 1
        self.input_sizes: List[int] = []
        if self.mutator is not None:
            self.num_inputs, self.input_sizes = \
                self.mutator.get_input_info()

    def _check_input_info(self) -> None:
        # Multi-input is the point of packet drivers; any part count.
        pass

    @property
    def supports_batch(self) -> bool:
        return False  # live-socket interaction is inherently per-exec

    def _cmd_line(self) -> str:
        return (f'{self.options["path"]} '
                f'{self.options["arguments"]}').strip()

    def _run(self, parts: List[bytes]) -> int:
        raise NotImplementedError

    # -- vtable ---------------------------------------------------------

    def test_input(self, buf: bytes) -> int:
        """Input is an encoded mem array of packets (reference
        decode_mem_array contract); raw bytes = one packet."""
        try:
            parts = decode_mem_array(buf.decode())
        except Exception:
            parts = [buf]
        self.last_input = encode_mem_array(parts).encode()
        return self._run(parts)

    def test_next_input(self) -> Optional[int]:
        if self.mutator is None:
            raise RuntimeError(f"{self.name}: no mutator attached")
        parts: List[bytes] = []
        if self.num_inputs > 1:
            for i in range(self.num_inputs):
                part = self.mutator.mutate_extended(
                    MUTATE_MULTIPLE_INPUTS | i)
                if part is None:
                    return None
                parts.append(part)
        else:
            buf = self.mutator.mutate()
            if buf is None:
                return None
            parts = [buf]
        self.last_input = encode_mem_array(parts).encode()
        return self._run(parts)

    def get_last_input(self) -> Optional[bytes]:
        return self.last_input
