"""Driver factories (reference driver_factory.c:25-132): the reference
exposes driver_factory (no deps), driver_instrumentation_factory,
driver_mutator_factory and driver_all_factory; here one factory takes
optional instrumentation/mutator and the aggregated help mirrors
driver_help (driver_factory.c:146-158)."""

from __future__ import annotations

from typing import Dict, Optional, Type

from .base import Driver

_REGISTRY: Dict[str, Type[Driver]] = {}


def register_driver(cls: Type[Driver]) -> Type[Driver]:
    _REGISTRY[cls.name] = cls
    return cls


def driver_names() -> list[str]:
    return sorted(_REGISTRY)


def driver_factory(name: str, options: Optional[str],
                   instrumentation, mutator=None) -> Driver:
    """driver_all_factory equivalent: name -> driver wired to its
    instrumentation and (optionally) mutator."""
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown driver {name!r}; known: {', '.join(driver_names())}")
    return _REGISTRY[name](options, instrumentation, mutator)


def driver_help() -> str:
    return "\n".join(_REGISTRY[n].help() for n in driver_names())
