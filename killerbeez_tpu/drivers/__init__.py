"""Driver layer: delivers candidate inputs to the target
(reference driver/driver.h:26-34 vtable + factories)."""

from .base import Driver
from .factory import (
    driver_factory, driver_help, driver_names, register_driver,
)
from .file_driver import FileDriver
from .network_client import NetworkClientDriver
from .network_server import NetworkServerDriver
from .stdin_driver import StdinDriver

__all__ = ["Driver", "driver_factory", "driver_help", "driver_names",
           "register_driver", "FileDriver", "StdinDriver",
           "NetworkServerDriver", "NetworkClientDriver"]
