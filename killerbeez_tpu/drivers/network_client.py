"""network_client driver — the target is a client; the fuzzer binds a
listener, the instrumentation starts the target, the target connects
to us, and we push the packet sequence down the accepted connection.

Behavioral parity with the reference network_client driver
(SURVEY §2.2, reference driver/network_client_driver.c:200-320:
start_listener -> enable -> accept -> send packets -> wait)."""

from __future__ import annotations

import socket
import time
from typing import List, Optional

from ..mutators.base import MUTATE_MULTIPLE_INPUTS
from ..utils.logging import DEBUG_MSG
from ..utils.serialization import decode_mem_array, encode_mem_array
from .. import FUZZ_ERROR, FUZZ_NONE
from .base import Driver
from .factory import register_driver


@register_driver
class NetworkClientDriver(Driver):
    """Fuzzes a client target that connects to the fuzzer's listener."""
    name = "network_client"
    OPTION_SCHEMA = {"path": str, "arguments": str, "port": int,
                     "ip": str, "udp": int, "sleeps": list,
                     "timeout": float, "accept_timeout": float}
    OPTION_DESCS = {
        "path": "target client executable",
        "arguments": "argument string for the target",
        "port": "port we listen on for the target (required)",
        "ip": "bind address (default 127.0.0.1)",
        "udp": "1 = datagram socket instead of TCP",
        "sleeps": "per-packet pre-send sleeps in ms",
        "timeout": "seconds to wait for target exit after sending",
        "accept_timeout": "seconds to wait for the target to connect "
                          "(default 5)",
    }
    DEFAULTS = {"arguments": "", "ip": "127.0.0.1", "udp": 0,
                "timeout": 2.0, "accept_timeout": 5.0}

    def __init__(self, options, instrumentation, mutator=None):
        super().__init__(options, instrumentation, mutator)
        if "path" not in self.options or "port" not in self.options:
            raise ValueError(
                'network_client needs {"path": ..., "port": ...}')
        self.port = int(self.options["port"])
        self.udp = bool(self.options["udp"])
        self.num_inputs = 1
        if self.mutator is not None:
            self.num_inputs, _ = self.mutator.get_input_info()
        self._listener: Optional[socket.socket] = None

    def _check_input_info(self) -> None:
        pass  # multi-input allowed

    @property
    def supports_batch(self) -> bool:
        return False

    def _cmd_line(self) -> str:
        return (f'{self.options["path"]} '
                f'{self.options["arguments"]}').strip()

    # -- listener (reference start_listener) ----------------------------

    def _ensure_listener(self) -> socket.socket:
        if self._listener is not None:
            return self._listener
        kind = socket.SOCK_DGRAM if self.udp else socket.SOCK_STREAM
        s = socket.socket(socket.AF_INET, kind)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.options["ip"], self.port))
        if not self.udp:
            s.listen(1)
        self._listener = s
        return s

    def _run(self, parts: List[bytes]) -> int:
        listener = self._ensure_listener()
        self.instrumentation.start_process(self._cmd_line())
        sleeps = self.options.get("sleeps") or []
        try:
            if self.udp:
                conn, peer = None, None
                listener.settimeout(float(self.options["accept_timeout"]))
                # learn the client's address from its first datagram
                _, peer = listener.recvfrom(65536)
                for i, part in enumerate(parts):
                    if i < len(sleeps) and sleeps[i]:
                        time.sleep(float(sleeps[i]) / 1000.0)
                    listener.sendto(part, peer)
            else:
                listener.settimeout(float(self.options["accept_timeout"]))
                conn, _ = listener.accept()
                # accept() returns a blocking socket regardless of the
                # listener's timeout; without this a stuck target that
                # stops reading would block sendall forever
                conn.settimeout(float(self.options["timeout"]))
                with conn:
                    for i, part in enumerate(parts):
                        if i < len(sleeps) and sleeps[i]:
                            time.sleep(float(sleeps[i]) / 1000.0)
                        conn.sendall(part)
        except OSError as e:
            DEBUG_MSG("network_client send failed: %s", e)
            verdict = self.instrumentation.wait_done(0.1)
            return verdict if verdict != FUZZ_NONE else FUZZ_ERROR
        return self.instrumentation.wait_done(
            float(self.options["timeout"]))

    # -- vtable ---------------------------------------------------------

    def test_input(self, buf: bytes) -> int:
        try:
            parts = decode_mem_array(buf.decode())
        except Exception:
            parts = [buf]
        self.last_input = encode_mem_array(parts).encode()
        return self._run(parts)

    def test_next_input(self) -> Optional[int]:
        if self.mutator is None:
            raise RuntimeError("network_client: no mutator attached")
        parts: List[bytes] = []
        if self.num_inputs > 1:
            for i in range(self.num_inputs):
                part = self.mutator.mutate_extended(
                    MUTATE_MULTIPLE_INPUTS | i)
                if part is None:
                    return None
                parts.append(part)
        else:
            buf = self.mutator.mutate()
            if buf is None:
                return None
            parts = [buf]
        self.last_input = encode_mem_array(parts).encode()
        return self._run(parts)

    def cleanup(self) -> None:
        if self._listener is not None:
            self._listener.close()
            self._listener = None
