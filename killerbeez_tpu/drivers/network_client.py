"""network_client driver — the target is a client; the fuzzer binds a
listener, the instrumentation starts the target, the target connects
to us, and we push the packet sequence down the accepted connection.

Behavioral parity with the reference network_client driver
(SURVEY §2.2, reference driver/network_client_driver.c:200-320:
start_listener -> enable -> accept -> send packets -> wait)."""

from __future__ import annotations

import socket
import time
from typing import List

from ..utils.logging import DEBUG_MSG
from .. import FUZZ_ERROR, FUZZ_NONE
from .factory import register_driver
from .packet_driver import PacketDriver


@register_driver
class NetworkClientDriver(PacketDriver):
    """Fuzzes a client target that connects to the fuzzer's listener."""
    name = "network_client"
    OPTION_SCHEMA = {"path": str, "arguments": str, "port": int,
                     "ip": str, "udp": int, "sleeps": list,
                     "timeout": float, "accept_timeout": float}
    OPTION_DESCS = {
        "path": "target client executable",
        "arguments": "argument string for the target",
        "port": "port we listen on for the target (required)",
        "ip": "bind address (default 127.0.0.1)",
        "udp": "1 = datagram socket instead of TCP",
        "sleeps": "per-packet pre-send sleeps in ms",
        "timeout": "seconds to wait for target exit after sending",
        "accept_timeout": "seconds to wait for the target to connect "
                          "(default 5)",
    }
    DEFAULTS = {"arguments": "", "ip": "127.0.0.1", "udp": 0,
                "timeout": 2.0, "accept_timeout": 5.0}

    # -- listener (reference start_listener) ----------------------------

    def _make_listener(self) -> socket.socket:
        """Fresh socket per exec: a reused listener can hold stale
        state from the PREVIOUS (now dead) target — a leftover datagram
        would teach the UDP path a stale peer address, and a leftover
        backlog connection would be accepted as this exec's target."""
        kind = socket.SOCK_DGRAM if self.udp else socket.SOCK_STREAM
        s = socket.socket(socket.AF_INET, kind)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.options["ip"], self.port))
        if not self.udp:
            s.listen(1)
        return s

    def _run(self, parts: List[bytes]) -> int:
        listener = self._make_listener()
        self.instrumentation.start_process(self._cmd_line())
        sleeps = self.options.get("sleeps") or []
        try:
            if self.udp:
                listener.settimeout(float(self.options["accept_timeout"]))
                # learn the client's address from its first datagram
                _, peer = listener.recvfrom(65536)
                for i, part in enumerate(parts):
                    if i < len(sleeps) and sleeps[i]:
                        time.sleep(float(sleeps[i]) / 1000.0)
                    listener.sendto(part, peer)
            else:
                listener.settimeout(float(self.options["accept_timeout"]))
                conn, _ = listener.accept()
                # accept() returns a blocking socket regardless of the
                # listener's timeout; without this a stuck target that
                # stops reading would block sendall forever
                conn.settimeout(float(self.options["timeout"]))
                with conn:
                    for i, part in enumerate(parts):
                        if i < len(sleeps) and sleeps[i]:
                            time.sleep(float(sleeps[i]) / 1000.0)
                        conn.sendall(part)
        except OSError as e:
            DEBUG_MSG("network_client send failed: %s", e)
            verdict = self.instrumentation.wait_done(0.1)
            return verdict if verdict != FUZZ_NONE else FUZZ_ERROR
        finally:
            listener.close()
        return self.instrumentation.wait_done(
            float(self.options["timeout"]))
