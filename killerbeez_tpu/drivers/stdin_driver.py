"""stdin driver — delivers input on the target's standard input
(reference stdin_driver.c:29-106; the forkserver feeds the bytes to
the child's stdin there, subprocess stdin here)."""

from __future__ import annotations

from .base import Driver
from .factory import register_driver


@register_driver
class StdinDriver(Driver):
    """Runs `path arguments` with input bytes on stdin."""
    name = "stdin"
    OPTION_SCHEMA = {"path": str, "arguments": str, "timeout": float}
    OPTION_DESCS = {
        "path": "target executable (host backends)",
        "arguments": "extra argument string (no @@ substitution)",
        "timeout": "seconds before a run counts as a hang",
    }
    DEFAULTS = {"arguments": ""}

    def __init__(self, options, instrumentation, mutator=None):
        super().__init__(options, instrumentation, mutator)
        self._device_backed = instrumentation.device_backed
        if not self._device_backed and "path" not in self.options:
            raise ValueError(
                'stdin driver needs {"path": target} for host backends')

    def _cmd_line(self) -> str:
        args = self.options["arguments"]
        return f'{self.options["path"]} {args}'.strip()

    def _host_exec_spec(self):
        return {"cmd_line": self._cmd_line(), "use_stdin": True,
                "input_file": None}

    def test_input(self, buf: bytes) -> int:
        self.last_input = bytes(buf)
        if self._device_backed:
            self.instrumentation.enable(input_bytes=buf)
        else:
            self.instrumentation.enable(input_bytes=buf,
                                        cmd_line=self._cmd_line())
        return self.instrumentation.get_fuzz_result()
