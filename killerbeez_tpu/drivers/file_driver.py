"""file driver — delivers input via a file path in the target argv.

Parity with the reference file driver (file_driver.c): mutated input
is written to a test file, ``@@`` in the argument string is replaced
by its path, and the instrumentation runs the command. When the
instrumentation is device-backed (jit_harness), bytes are handed to
the device directly — the "file" is the input tensor; no disk I/O per
exec (the per-exec disk write is the first hot spot SURVEY §3.1 calls
out for lifting).
"""

from __future__ import annotations

import os
from typing import Optional

from ..utils.fileio import get_temp_filename, write_buffer_to_file
from .base import Driver
from .factory import register_driver


@register_driver
class FileDriver(Driver):
    """Runs `path arguments` with @@ replaced by the input file."""
    name = "file"
    OPTION_SCHEMA = {"path": str, "arguments": str, "timeout": float,
                     "test_filename": str}
    OPTION_DESCS = {
        "path": "target executable (host backends)",
        "arguments": "argument string; @@ becomes the input path "
                     "(default just @@)",
        "timeout": "seconds before a run counts as a hang",
        "test_filename": "fixed input filename (default: a temp file)",
    }
    DEFAULTS = {"arguments": "@@"}

    def __init__(self, options, instrumentation, mutator=None):
        super().__init__(options, instrumentation, mutator)
        self._device_backed = instrumentation.device_backed
        if not self._device_backed and "path" not in self.options:
            raise ValueError(
                'file driver needs {"path": target} for host backends')
        self.test_filename = self.options.get("test_filename") or \
            get_temp_filename("kbz_input_")

    def _cmd_line(self) -> str:
        args = self.options["arguments"].replace("@@", self.test_filename)
        return f'{self.options["path"]} {args}'

    def _host_exec_spec(self):
        # The exec backend stages the input file itself in the batched
        # path (C-side write per exec, no Python file I/O).
        return {"cmd_line": self._cmd_line(), "use_stdin": False,
                "input_file": self.test_filename}

    def test_input(self, buf: bytes) -> int:
        self.last_input = bytes(buf)
        if self._device_backed:
            self.instrumentation.enable(input_bytes=buf)
        else:
            write_buffer_to_file(self.test_filename, buf)
            self.instrumentation.enable(cmd_line=self._cmd_line())
        return self.instrumentation.get_fuzz_result()

    def cleanup(self) -> None:
        if not self.options.get("test_filename") and \
                os.path.exists(self.test_filename):
            os.unlink(self.test_filename)
