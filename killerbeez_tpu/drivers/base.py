"""Driver base class (reference driver/driver.h:26-34).

Vtable parity: cleanup / test_input / test_next_input /
get_last_input. ``test_next_input`` returns the FUZZ_* verdict or
``None`` when the mutator is exhausted (the reference's -2 return,
fuzzer/main.c:374-383).

TPU addition: ``test_batch(n)`` — mutate and execute ``n`` candidates
in one device round-trip when both the mutator and the
instrumentation support batching.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, NamedTuple, Optional

import numpy as np

from ..instrumentation.base import BatchResult, Instrumentation
from ..mutators.base import Mutator
from ..utils.options import format_help, parse_options


class BatchOutcome(NamedTuple):
    result: BatchResult
    inputs: np.ndarray    # uint8[B, L]
    lengths: np.ndarray   # int32[B]
    #: device-compacted interesting-lane report (fused path only) —
    #: lets triage skip the full inputs transfer on slow links
    compact: Optional[Any] = None


class Driver:
    name = "base"
    OPTION_SCHEMA: Dict[str, type] = {}
    OPTION_DESCS: Dict[str, str] = {}
    DEFAULTS: Dict[str, Any] = {}

    #: telemetry StageTimer installed by the Fuzzer; drivers time the
    #: mutate/execute boundary with it (dispatch-side only — device
    #: calls return lazy arrays, so no sync is forced here)
    stage_timer = None

    def _span(self, stage: str):
        t = self.stage_timer
        return t(stage) if t is not None else contextlib.nullcontext()

    def __init__(self, options: Optional[str],
                 instrumentation: Instrumentation,
                 mutator: Optional[Mutator] = None):
        self.options = parse_options(options, self.OPTION_SCHEMA,
                                     self.DEFAULTS)
        self.instrumentation = instrumentation
        self.mutator = mutator
        self.last_input: Optional[bytes] = None
        self._check_input_info()

    def _check_input_info(self) -> None:
        """Single-input drivers require num_inputs == 1 (reference
        file_driver.c:137-139)."""
        if self.mutator is not None:
            num, _ = self.mutator.get_input_info()
            if num != 1:
                raise ValueError(
                    f"{self.name} driver requires a single-input mutator, "
                    f"got {num} parts")

    @property
    def supports_batch(self) -> bool:
        host_ok = (self.instrumentation.device_backed or
                   type(self)._host_exec_spec is not Driver._host_exec_spec)
        return (self.instrumentation.supports_batch and host_ok
                and self.mutator is not None
                and self.mutator.batch_capable)

    def _host_exec_spec(self) -> Dict[str, Any]:
        """How a host backend should execute the target for the
        batched path: {"cmd_line", "use_stdin", "input_file"}.
        Drivers that can't describe one don't batch host backends."""
        raise NotImplementedError(
            f"{self.name}: no host-exec batch support")

    # -- single-exec ----------------------------------------------------

    def test_input(self, buf: bytes) -> int:
        raise NotImplementedError

    def test_next_input(self) -> Optional[int]:
        """Mutate then test (reference generic_test_next_input,
        driver/driver.c:75-89). None = mutator exhausted."""
        if self.mutator is None:
            raise RuntimeError(f"{self.name}: no mutator attached")
        buf = self.mutator.mutate()
        if buf is None:
            return None
        return self.test_input(buf)

    def get_last_input(self) -> Optional[bytes]:
        if self.last_input is None and \
                getattr(self, "_last_batch_tail", None) is not None:
            bufs, lens, i = self._last_batch_tail
            # slice FIRST (on device for lazy arrays) so only one row
            # transfers; drop the reference so the batch isn't pinned
            self.last_input = np.asarray(
                bufs[i, :int(lens[i])]).tobytes()
            self._last_batch_tail = None
        return self.last_input

    # -- batched --------------------------------------------------------

    def test_batch(self, n: int, pad_to: Optional[int] = None,
                   prefetch_next=True) -> BatchOutcome:
        """Mutate + execute ``n`` candidates. ``pad_to`` keeps the lane
        dimension shape-stable across tail batches (no XLA recompile):
        device backends get the input tensor padded with copies of
        lane 0 (on-device duplicates are coverage no-ops and nearly
        free), host backends execute only the ``n`` real lanes and pad
        the result arrays instead (a padded lane would cost a real
        fork+exec). Callers triage only the first ``n`` lanes.
        ``prefetch_next``: size of the FOLLOWING batch (host drivers
        pre-generate exactly that many lanes during this batch's
        execs); 0/False skips, True means "same as n"."""
        if not self.supports_batch:
            raise RuntimeError(f"{self.name}: batch path unavailable")
        wants_fused = getattr(self.instrumentation, "wants_fused", None)
        if (self.instrumentation.device_backed and wants_fused is not None
                and wants_fused(self.mutator)):
            # fused mutate+execute: the instrumentation generates the
            # mutator's lanes inside the VM kernel (bit-identical
            # candidates, no HBM round-trip between mutate and exec)
            its = self.mutator.peek_iterations(n)
            with self._span("execute"):     # mutation is in-kernel
                result, bufs, lens, compact = \
                    self.instrumentation.run_batch_fused(
                        self.mutator, its, pad_to=pad_to)
            self.mutator.advance(n)
            if n > 0:
                self._last_batch_tail = (bufs, lens, n - 1)
                self.last_input = None
            return BatchOutcome(result=result, inputs=bufs,
                                lengths=lens, compact=compact)
        with self._span("mutate"):
            bufs, lens = self.mutator.mutate_batch(n)
        if self.instrumentation.device_backed:
            if pad_to is not None and pad_to > n:
                # keep lazy device arrays lazy (np.concatenate would
                # sync and bounce them through the host)
                if isinstance(bufs, np.ndarray):
                    xp = np
                else:
                    import jax.numpy as xp
                pad = pad_to - n
                bufs = xp.concatenate(
                    [bufs, xp.repeat(bufs[:1], pad, axis=0)], axis=0)
                lens = xp.concatenate([lens, xp.repeat(lens[:1], pad)])
            with self._span("execute"):
                result = self.instrumentation.run_batch(bufs, lens)
        else:
            # idempotent per target key; re-binds if a single exec
            # rebuilt the instrumentation's target in between
            self.instrumentation.prepare_host(**self._host_exec_spec())
            # generate the NEXT batch now: its device->host copies
            # land while this batch's target processes execute
            if prefetch_next:
                with self._span("mutate"):
                    self.mutator.prefetch_batch(
                        n if prefetch_next is True
                        else int(prefetch_next))
            with self._span("execute"):
                result = self.instrumentation.run_batch(bufs, lens,
                                                        pad_to=pad_to)
        if n > 0:
            # defer materialization (get_last_input slices on demand):
            # .tobytes() here would sync the host to this batch and
            # break the loop's one-batch pipeline
            self._last_batch_tail = (bufs, lens, n - 1)
            self.last_input = None
        return BatchOutcome(result=result, inputs=bufs, lengths=lens)

    def supports_fused_multi(self) -> bool:
        """True when test_batch_fused_multi can run: fused device path
        with a multi-step instrumentation (the CLI's K-step
        device-side accumulation)."""
        instr = self.instrumentation
        wants = getattr(instr, "wants_fused", None)
        return (self.supports_batch and instr.device_backed
                and getattr(self, "batch_quantum", 1) == 1
                and hasattr(instr, "run_batch_fused_multi")
                # edges mode records per-batch count tensors, which
                # the multi path does not maintain
                and not getattr(instr, "options", {}).get("edges")
                and wants is not None and wants(self.mutator))

    def test_batch_fused_multi(self, n: int, k: int):
        """K consecutive fused batches of ``n`` in one device
        dispatch; candidate/verdict streams are bit-identical to k
        test_batch(n) calls.  Returns the stacked lazy device arrays
        (packed[k, B], bufs[k, B, L], lens[k, B], stacked compact) —
        the Fuzzer loop owns slicing them into per-step triage."""
        its = self.mutator.peek_iterations(n)
        with self._span("execute"):
            packed, bufs, lens, compact = \
                self.instrumentation.run_batch_fused_multi(
                    self.mutator, its, k, pad_to=n)
        self.mutator.advance(k * n)
        if n > 0:
            self._last_batch_tail = (bufs[k - 1], lens[k - 1], n - 1)
            self.last_input = None
        return packed, bufs, lens, compact

    def supports_batch_generations(self) -> bool:
        """True when test_batch_generations can run: a device-backed
        instrumentation with the generation loop (jit_harness), a
        fused-capable mutator with no focus mask installed, and a
        single-chip batch quantum.  Re-checked per dispatch — the
        same stand-down discipline the fused superbatch path uses.
        Mesh campaigns override BOTH methods with the sharded
        generation scan (parallel/campaign.py), so the single-chip
        quantum gate here never stands a --mesh campaign down."""
        instr = self.instrumentation
        supports = getattr(instr, "supports_generations", None)
        return (self.supports_batch and instr.device_backed
                and getattr(self, "batch_quantum", 1) == 1
                and supports is not None and supports(self.mutator))

    def test_batch_generations(self, n: int, g: int,
                               pad_to: Optional[int] = None,
                               reseed: bool = True):
        """``g`` full fuzzing generations in one device dispatch
        (mutate -> execute -> triage -> ring reseed all on device);
        the host gets back only the bounded findings ring + admission
        ledger (a lazy GenerationOutcome).  Generation j consumed
        iterations ``it0 + j*n``; the mutator advances by g*n."""
        its = self.mutator.peek_iterations(n)
        with self._span("execute"):     # the whole loop is in-kernel
            out = self.instrumentation.run_batch_generations(
                self.mutator, its, g, pad_to=pad_to, reseed=reseed)
        self.mutator.advance(g * n)
        # the per-exec last-input contract doesn't apply: candidate
        # tensors never leave the device in this mode
        self._last_batch_tail = None
        self.last_input = None
        return out

    def cleanup(self) -> None:
        pass

    @classmethod
    def help(cls) -> str:
        head = f"{cls.name} driver"
        doc = (cls.__doc__ or "").strip().splitlines()
        if doc:
            head += f" — {doc[0]}"
        return head + "\n" + format_help(cls.name, cls.OPTION_SCHEMA,
                                         cls.OPTION_DESCS)
