"""network_server driver — the target is a server; the fuzzer
connects and delivers the input as a sequence of network packets.

Behavioral parity with the reference network_server driver
(SURVEY §2.2, reference driver/network_server_driver.c): start the
target via the instrumentation's async enable, poll until the port is
listening, connect (TCP or UDP), send N packets with optional
inter-packet sleeps, then wait for process completion with the
timeout->FUZZ_HANG rule. Multi-packet inputs come from multipart
mutators via ``mutate_extended(MUTATE_MULTIPLE_INPUTS|i)`` and the
last input serializes via ``encode_mem_array``.
"""

from __future__ import annotations

import socket
import time
from typing import List

from ..utils.logging import DEBUG_MSG, WARNING_MSG
from .. import FUZZ_ERROR, FUZZ_NONE
from .factory import register_driver
from .packet_driver import PacketDriver


_TCP_LISTEN = 0x0A


def is_port_listening(port: int, udp: bool = False,
                      host: str = "127.0.0.1") -> bool:
    """True when a socket is bound/listening on host:port, determined
    from /proc/net/{tcp,udp} WITHOUT connecting (reference
    is_port_listening reads the kernel table for the same reason: a
    probe connection would consume the target's accept()). A listener
    on INADDR_ANY matches any host."""
    try:
        want = int.from_bytes(socket.inet_aton(host), "little")
    except OSError:
        want = None  # non-IPv4 host string: match port only
    tables = (["/proc/net/udp", "/proc/net/udp6"] if udp
              else ["/proc/net/tcp", "/proc/net/tcp6"])
    for table in tables:
        v6 = table.endswith("6")
        try:
            with open(table) as f:
                lines = f.readlines()[1:]
        except OSError:
            continue
        for ln in lines:
            fields = ln.split()
            if len(fields) < 4:
                continue
            try:
                addr_hex, port_hex = fields[1].split(":")
                local_port = int(port_hex, 16)
                state = int(fields[3], 16)
            except (ValueError, IndexError):
                continue
            if local_port != port:
                continue
            if not udp and state != _TCP_LISTEN:
                continue
            if want is not None:
                if v6:
                    # match v4-mapped (::ffff:a.b.c.d) or in6addr_any
                    tail = int(addr_hex[-8:], 16)
                    if int(addr_hex, 16) != 0 and tail != want:
                        continue
                else:
                    addr = int(addr_hex, 16)
                    if addr != 0 and addr != want:
                        continue
            return True
    return False


@register_driver
class NetworkServerDriver(PacketDriver):
    """Fuzzes a server target over TCP/UDP packet sequences."""
    name = "network_server"
    OPTION_SCHEMA = {"path": str, "arguments": str, "port": int,
                     "ip": str, "udp": int, "sleeps": list,
                     "timeout": float,
                     "skip_network_check": int, "listen_timeout": float}
    OPTION_DESCS = {
        "path": "target server executable",
        "arguments": "argument string for the target",
        "port": "port the target listens on (required)",
        "ip": "target address (default 127.0.0.1)",
        "udp": "1 = datagrams instead of a TCP stream",
        "sleeps": "per-packet pre-send sleeps in ms",
        "timeout": "seconds to wait for target exit after sending "
                   "(then FUZZ_HANG; default 2.0)",
        "skip_network_check": "1 = don't wait for the port to listen",
        "listen_timeout": "max seconds to wait for the port (default 5)",
    }
    DEFAULTS = {"arguments": "", "ip": "127.0.0.1", "udp": 0,
                "timeout": 2.0, "skip_network_check": 0,
                "listen_timeout": 5.0}

    # -- packet delivery ------------------------------------------------

    def _wait_listening(self) -> bool:
        if self.options["skip_network_check"]:
            return True
        deadline = time.time() + float(self.options["listen_timeout"])
        while time.time() < deadline:
            if self.instrumentation.is_process_done():
                return False  # died before listening
            if is_port_listening(self.port, self.udp,
                                 self.options["ip"]):
                return True
            time.sleep(0.005)
        return False

    def _send_packets(self, parts: List[bytes]) -> bool:
        sleeps = self.options.get("sleeps") or []
        try:
            if self.udp:
                sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            else:
                sock = socket.create_connection(
                    (self.options["ip"], self.port), timeout=2.0)
            with sock:
                for i, part in enumerate(parts):
                    if i < len(sleeps) and sleeps[i]:
                        time.sleep(float(sleeps[i]) / 1000.0)
                    if self.udp:
                        sock.sendto(part,
                                    (self.options["ip"], self.port))
                    else:
                        sock.sendall(part)
            return True
        except OSError as e:
            DEBUG_MSG("network_server send failed: %s", e)
            return False

    def _run(self, parts: List[bytes]) -> int:
        self.instrumentation.start_process(self._cmd_line())
        if not self._wait_listening():
            if self.instrumentation.is_process_done():
                # died before listening: collect the verdict (a crash
                # before listen is still a crash)
                return self.instrumentation.wait_done(0.1)
            # alive but never opened the port: a config/startup problem,
            # not a hang — don't let it pollute the hang virgin map
            WARNING_MSG("network_server: target never listened on port "
                        "%d within %.1fs", self.port,
                        float(self.options["listen_timeout"]))
            return self.instrumentation.abort_process()
        if not self._send_packets(parts):
            # a mid-sequence crash resets the connection and fails the
            # send — the target's verdict is the real signal
            verdict = self.instrumentation.wait_done(0.1)
            return verdict if verdict != FUZZ_NONE else FUZZ_ERROR
        return self.instrumentation.wait_done(
            float(self.options["timeout"]))

    def cleanup(self) -> None:
        try:
            if not self.instrumentation.is_process_done():
                self.instrumentation.wait_done(0.0)
        except (NotImplementedError, RuntimeError) as e:
            WARNING_MSG("network_server cleanup: %s", e)
