"""network_server driver — the target is a server; the fuzzer
connects and delivers the input as a sequence of network packets.

Behavioral parity with the reference network_server driver
(SURVEY §2.2, reference driver/network_server_driver.c): start the
target via the instrumentation's async enable, poll until the port is
listening, connect (TCP or UDP), send N packets with optional
inter-packet sleeps, then wait for process completion with the
timeout->FUZZ_HANG rule. Multi-packet inputs come from multipart
mutators via ``mutate_extended(MUTATE_MULTIPLE_INPUTS|i)`` and the
last input serializes via ``encode_mem_array``.
"""

from __future__ import annotations

import socket
import time
from typing import List, Optional

from ..mutators.base import MUTATE_MULTIPLE_INPUTS
from ..utils.logging import DEBUG_MSG, WARNING_MSG
from ..utils.serialization import decode_mem_array, encode_mem_array
from .. import FUZZ_ERROR, FUZZ_NONE
from .base import Driver
from .factory import register_driver


_TCP_LISTEN = 0x0A


def is_port_listening(port: int, udp: bool = False,
                      host: str = "127.0.0.1") -> bool:
    """True when a socket is bound/listening on host:port, determined
    from /proc/net/{tcp,udp} WITHOUT connecting (reference
    is_port_listening reads the kernel table for the same reason: a
    probe connection would consume the target's accept()). A listener
    on INADDR_ANY matches any host."""
    try:
        want = int.from_bytes(socket.inet_aton(host), "little")
    except OSError:
        want = None  # non-IPv4 host string: match port only
    tables = (["/proc/net/udp", "/proc/net/udp6"] if udp
              else ["/proc/net/tcp", "/proc/net/tcp6"])
    for table in tables:
        v6 = table.endswith("6")
        try:
            with open(table) as f:
                lines = f.readlines()[1:]
        except OSError:
            continue
        for ln in lines:
            fields = ln.split()
            if len(fields) < 4:
                continue
            try:
                addr_hex, port_hex = fields[1].split(":")
                local_port = int(port_hex, 16)
                state = int(fields[3], 16)
            except (ValueError, IndexError):
                continue
            if local_port != port:
                continue
            if not udp and state != _TCP_LISTEN:
                continue
            if want is not None:
                if v6:
                    # match v4-mapped (::ffff:a.b.c.d) or in6addr_any
                    tail = int(addr_hex[-8:], 16)
                    if int(addr_hex, 16) != 0 and tail != want:
                        continue
                else:
                    addr = int(addr_hex, 16)
                    if addr != 0 and addr != want:
                        continue
            return True
    return False


@register_driver
class NetworkServerDriver(Driver):
    """Fuzzes a server target over TCP/UDP packet sequences."""
    name = "network_server"
    OPTION_SCHEMA = {"path": str, "arguments": str, "port": int,
                     "ip": str, "udp": int, "sleeps": list,
                     "timeout": float, "ratio": float,
                     "skip_network_check": int, "listen_timeout": float}
    OPTION_DESCS = {
        "path": "target server executable",
        "arguments": "argument string for the target",
        "port": "port the target listens on (required)",
        "ip": "target address (default 127.0.0.1)",
        "udp": "1 = datagrams instead of a TCP stream",
        "sleeps": "per-packet pre-send sleeps in ms",
        "timeout": "seconds to wait for target exit after sending "
                   "(then FUZZ_HANG; default 2.0)",
        "ratio": "mutate-buffer size ratio (default 2.0)",
        "skip_network_check": "1 = don't wait for the port to listen",
        "listen_timeout": "max seconds to wait for the port (default 5)",
    }
    DEFAULTS = {"arguments": "", "ip": "127.0.0.1", "udp": 0,
                "timeout": 2.0, "ratio": 2.0, "skip_network_check": 0,
                "listen_timeout": 5.0}

    def __init__(self, options, instrumentation, mutator=None):
        super().__init__(options, instrumentation, mutator)
        if "path" not in self.options or "port" not in self.options:
            raise ValueError(
                'network_server needs {"path": ..., "port": ...}')
        self.port = int(self.options["port"])
        self.udp = bool(self.options["udp"])
        self.num_inputs = 1
        self.input_sizes: List[int] = []
        if self.mutator is not None:
            self.num_inputs, self.input_sizes = \
                self.mutator.get_input_info()
        self._last_parts: Optional[List[bytes]] = None

    def _check_input_info(self) -> None:
        # Multi-input is this driver's point; accept any part count.
        pass

    @property
    def supports_batch(self) -> bool:
        return False  # live-socket interaction is inherently per-exec

    def _cmd_line(self) -> str:
        return (f'{self.options["path"]} '
                f'{self.options["arguments"]}').strip()

    # -- packet delivery ------------------------------------------------

    def _wait_listening(self) -> bool:
        if self.options["skip_network_check"]:
            return True
        deadline = time.time() + float(self.options["listen_timeout"])
        while time.time() < deadline:
            if self.instrumentation.is_process_done():
                return False  # died before listening
            if is_port_listening(self.port, self.udp,
                                 self.options["ip"]):
                return True
            time.sleep(0.005)
        return False

    def _send_packets(self, parts: List[bytes]) -> bool:
        sleeps = self.options.get("sleeps") or []
        try:
            if self.udp:
                sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            else:
                sock = socket.create_connection(
                    (self.options["ip"], self.port), timeout=2.0)
            with sock:
                for i, part in enumerate(parts):
                    if i < len(sleeps) and sleeps[i]:
                        time.sleep(float(sleeps[i]) / 1000.0)
                    if self.udp:
                        sock.sendto(part,
                                    (self.options["ip"], self.port))
                    else:
                        sock.sendall(part)
            return True
        except OSError as e:
            DEBUG_MSG("network_server send failed: %s", e)
            return False

    def _run(self, parts: List[bytes]) -> int:
        self.instrumentation.start_process(self._cmd_line())
        if not self._wait_listening():
            # died or never listened: collect the verdict (a crash
            # before listen is still a crash)
            return self.instrumentation.wait_done(0.1)
        if not self._send_packets(parts):
            # a mid-sequence crash resets the connection and fails the
            # send — the target's verdict is the real signal
            verdict = self.instrumentation.wait_done(0.1)
            return verdict if verdict != FUZZ_NONE else FUZZ_ERROR
        return self.instrumentation.wait_done(
            float(self.options["timeout"]))

    # -- vtable ---------------------------------------------------------

    def test_input(self, buf: bytes) -> int:
        """Input is an encoded mem array of packets (reference
        decode_mem_array contract)."""
        try:
            parts = decode_mem_array(buf.decode())
        except Exception:
            parts = [buf]  # raw bytes: single packet
        self._last_parts = parts
        self.last_input = encode_mem_array(parts).encode()
        return self._run(parts)

    def test_next_input(self) -> Optional[int]:
        if self.mutator is None:
            raise RuntimeError("network_server: no mutator attached")
        parts: List[bytes] = []
        if self.num_inputs > 1:
            for i in range(self.num_inputs):
                part = self.mutator.mutate_extended(
                    MUTATE_MULTIPLE_INPUTS | i)
                if part is None:
                    return None
                parts.append(part)
        else:
            buf = self.mutator.mutate()
            if buf is None:
                return None
            parts = [buf]
        self._last_parts = parts
        self.last_input = encode_mem_array(parts).encode()
        return self._run(parts)

    def get_last_input(self) -> Optional[bytes]:
        return self.last_input

    def cleanup(self) -> None:
        try:
            if not self.instrumentation.is_process_done():
                self.instrumentation.wait_done(0.0)
        except (NotImplementedError, RuntimeError) as e:
            WARNING_MSG("network_server cleanup: %s", e)
