"""Batched branch-distance descent — the solver-unknown frontier tier.

PR 4's exact solver is honest about checksum-style loops: they come
back ``unknown``.  Angora's answer (arxiv 1803.01307) is to treat the
uncracked branch as a black-box distance objective over the input
bytes and descend it; this engine runs that search with the expensive
half on device — one ``run_batch_distances`` dispatch scores the
whole candidate population against the whole GUARD CURRICULUM (the
deciding branches of the path into the frontier block, plus the
edge's own) at once, and lanes rank by (deepest guard sampled,
distance there).

Per iteration the population is rebuilt around the elite front —
stratified per curriculum stage, with probe centers spanning the
best elite, structurally-distinct ties (zero-extended siblings) and
one back-stage repair candidate:

  * the elites themselves (monotone best-so-far),
  * finite-difference coordinate probes per center
    (+/- {1, 2, 4, 16, 64} per byte — numeric descent moves, operand
    dependency positions first),
  * compensated PAIR probes (+d on an operand byte, +/-d or +/-2d on
    a second byte) that move an operand THROUGH sum-style integrity
    checks instead of dying at them,
  * dictionary-token insertion sweeps and window duplications —
    command-stream targets gate depth counters on how many
    well-formed records precede the branch, which no fixed-position
    byte move can change,
  * ES mutants: rank-weighted parent, dictionary-biased byte values,
    length/structure moves ("not all bytes are equal", arxiv
    1711.04596: mutation dimensions restrict to the solver's
    dependency-byte mask when one is known),
  * uniform recombination of elite pairs over the mask positions,
  * ``jax.grad`` proposals through the float32-relaxed soft-KBVM
    when the path slice to the blocking guard is arithmetic-only
    (soft.py),
  * fresh reseeds after a stagnation window (restart, wider radius).

All deterministic probe families cycle their combo lists under
per-batch quotas with cursors keyed by CENTER ROLE, so the sweep
keeps advancing when centers churn between equally-ranked lanes.

Witness detection does not rely on the distance at all: the engine
reads the target edge's own hit count from the returned coverage map,
so a candidate that traverses the edge by ANY path is caught.  The
honesty contract matches the solver: a witness is re-checked through
the pure-Python reference interpreter before it is ever reported.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis.solver import concrete_run
from ..models.vm import DIST_UNREACHED, run_batch_distances
from ..utils.logging import DEBUG_MSG
from .objective import BranchObjective, edge_objectives
from .soft import slice_operand_deps, soft_refine, trace_slice

#: staged-key sentinel ranking below any lane that sampled a guard
_KEY_UNREACHED = (1 << 20, float(DIST_UNREACHED))

#: curriculum size cap (guards into the block + the edge's own
#: deciding branches; jit specializes per K)
MAX_GUARDS = 8

#: device dispatches per edge before the engine reports ``exhausted``
DEFAULT_DESCENT_BUDGET = 48

#: candidate lanes per dispatch (the population size)
DEFAULT_LANES = 1024

#: iterations with no best-distance improvement before a restart
STAGNATION_WINDOW = 8

#: elite front size
N_ELITE = 16

#: finite-difference probe deltas (both signs tried): the small
#: steps walk counters/length fields exactly, the big ones detect
#: the descent direction across most of a byte's range
_PROBE_DELTAS = (1, 2, 4, 16, 64)


@dataclass
class DescentResult:
    """Outcome of one edge descent.

    ``status``:
      descended — ``input`` concretely traverses the edge (verified
                  against the reference interpreter; never guessed)
      exhausted — the step budget ran out; ``best_dist`` is the
                  closest the population got (``DIST_UNREACHED`` =
                  no candidate ever reached the branch in-block)
    """
    edge: Tuple[int, int]
    status: str
    input: Optional[bytes] = None
    steps: int = 0              # search iterations spent
    evals: int = 0              # candidate executions scored
    best_dist: float = float(DIST_UNREACHED)
    objective: str = ""
    reason: str = ""
    soft_used: bool = False
    #: which engine produced the result: "host" (this module — one
    #: device dispatch per iteration) or "device" (device_descent.py
    #: — R iterations fused per dispatch)
    engine: str = "host"
    #: device dispatches actually issued (== iterations for the host
    #: engine; iterations / scan_iters for the in-scan engine) — the
    #: bench wall-clock gate's machine-readable denominator
    dispatches: int = 0
    iterations: int = 0
    #: True when the witness came from an input-to-state lane
    i2s: bool = False

    def as_dict(self) -> Dict:
        d = {"edge": list(self.edge), "status": self.status,
             "steps": self.steps, "evals": self.evals,
             "best_dist": (None if self.best_dist >= DIST_UNREACHED
                           else float(self.best_dist)),
             "objective": self.objective, "reason": self.reason,
             "soft_used": self.soft_used, "engine": self.engine,
             "dispatches": self.dispatches,
             "iterations": self.iterations, "i2s": self.i2s}
        if self.input is not None:
            d["input_hex"] = self.input.hex()
            d["length"] = len(self.input)
        return d


def _edge_index(program, edge: Tuple[int, int]) -> Optional[int]:
    ef = np.asarray(program.edge_from)
    et = np.asarray(program.edge_to)
    hit = np.flatnonzero((ef == edge[0]) & (et == edge[1]))
    return int(hit[0]) if len(hit) else None


def _pack(rows: Sequence[bytes], lanes: int, L: int):
    bufs = np.zeros((lanes, L), dtype=np.uint8)
    lens = np.zeros((lanes,), dtype=np.int32)
    for i, r in enumerate(rows[:lanes]):
        r = r[:L]
        bufs[i, :len(r)] = np.frombuffer(r, dtype=np.uint8)
        lens[i] = len(r)
    # unused lanes repeat row 0 (coverage no-ops, same convention as
    # the fuzzing loop's batch padding)
    for i in range(len(rows), lanes):
        bufs[i] = bufs[0]
        lens[i] = lens[0]
    return bufs, lens


class _Population:
    """Host-side candidate generator around a rank-ordered elite
    front; all randomness comes from one seeded Generator so descents
    are reproducible."""

    def __init__(self, seeds: List[bytes], mask: Optional[List[int]],
                 lanes: int, rng: np.random.Generator,
                 max_len: int = 64,
                 tokens: Sequence[bytes] = ()):
        self.lanes = lanes
        self.rng = rng
        self.mask = mask
        self.max_len = max_len
        #: static-analysis dictionary (branch-compare constants):
        #: opcode/type/magic bytes the target actually compares
        #: against — ES value draws prefer them, and insertion moves
        #: splice them in whole
        self.tokens = [t for t in tokens if t]
        self.values = sorted({t[0] for t in self.tokens if len(t) == 1}
                             | {b for t in self.tokens for b in t})
        # zero-extended seed variants ride along from the start: the
        # frontier branches behind length/checksum guards often need
        # LONGER inputs than any corpus entry (a zero extension keeps
        # trailing sum-checksums self-consistent), and byte moves
        # alone can never grow a lane
        # interleaved with their parents so they survive the elite
        # cut even when the caller supplies a deep seed pool
        self.seeds = []
        for s in seeds:
            self.seeds.append(s)
            for ext in (4, 8, 16):
                if len(s) + ext <= max_len:
                    self.seeds.append(s + b"\x00" * ext)
        #: (staged key, bytes) elites, best first — the key is
        #: (guards past the deepest one sampled, distance there), so
        #: tuple order IS curriculum order
        self.elite: List[Tuple[tuple, bytes]] = \
            [(_KEY_UNREACHED, s) for s in self.seeds[:N_ELITE]]
        #: one representative per curriculum stage (deceptive-fitness
        #: guard: a lane that re-broke an early checksum while fixing
        #: the primary operand ranks below a local optimum, yet is
        #: the right probe center for the repair move)
        self.centers: List[bytes] = [s for _, s in self.elite[:4]]
        self.center_keys: List[tuple] = [k for k, _ in self.elite[:4]]
        self.radius = 1
        #: per-(family, center) probe rotation cursors, so several
        #: centers of different shapes cycle their combo lists
        #: independently
        self._cursors: Dict[tuple, int] = {}
        #: dynamic per-path taint of the CURRENT objective's operands
        #: (soft.slice_operand_deps on the best elite): probe moves
        #: concentrate on these positions when known
        self.focus: Optional[List[int]] = None

    def positions(self, buf: bytes) -> List[int]:
        if self.mask:
            p = [i for i in self.mask if i < len(buf)]
            if p:
                return p
        return list(range(len(buf)))

    def _cycle(self, family: str, role: int, combos: list,
               quota: int) -> list:
        """Take the next ``quota`` entries of ``combos``, resuming
        where this (family, center-role) left off last iteration.
        Keying on the ROLE (position in the centers list), not the
        center's bytes, keeps the sweep advancing when the center
        churns between equally-ranked lanes — byte-keyed cursors
        reset on every churn and starve the deeper combos."""
        if not combos or quota <= 0:
            return []
        key = (family, role)
        start = self._cursors.get(key, 0) % len(combos)
        n = min(quota, len(combos))
        self._cursors[key] = (start + n) % len(combos)
        return [combos[(start + k) % len(combos)] for k in range(n)]

    def _rand_value(self) -> int:
        """A byte value: dictionary-biased — the target only ever
        compares against a handful of constants (opcodes, type tags,
        bounds), and a uniform draw finds them at 1/256."""
        if self.values and self.rng.random() < 0.5:
            return int(self.values[int(self.rng.integers(
                len(self.values)))])
        return int(self.rng.integers(256))

    def _insert(self, b: bytearray) -> None:
        """Structural insertion: splice a duplicated window or a
        dictionary token (+ random arg byte) at a random offset,
        shifting the tail.  Command-stream/TLV targets gate depth
        counters ("sp >= 2") on how many well-formed records precede
        the branch — no set of fixed-position byte moves can ADD a
        record, an insertion can."""
        if len(b) >= self.max_len:
            return
        p = int(self.rng.integers(len(b) + 1))
        if self.tokens and self.rng.random() < 0.5:
            t = self.tokens[int(self.rng.integers(len(self.tokens)))]
            ins = bytes(t) + bytes([self._rand_value()])
        else:
            w = int(self.rng.choice((1, 2, 4)))
            lo = max(p - w, 0)
            ins = bytes(b[lo:p]) or bytes([self._rand_value()])
        ins = ins[:self.max_len - len(b)]
        b[p:p] = ins

    def _mutate(self, buf: bytes, k: int) -> bytes:
        b = bytearray(buf)
        structural = self.mask is None  # a dependency mask pins
        r = self.rng.random()           # positions: no shifting then
        if structural and r < 0.15:
            self._insert(b)
        elif structural and r < 0.22 and len(b) > 2:
            # deletion: drop a window (the inverse structural move)
            w = int(self.rng.choice((1, 2, 4)))
            p = int(self.rng.integers(max(len(b) - w, 1)))
            del b[p:p + w]
        elif r < 0.3:
            # length move: grow (zeros or noise) or shrink —
            # structural guards ("payload+cksum present") gate on
            # length, and the dependency-byte mask can't know that
            delta = int(self.rng.integers(1, 9))
            if self.rng.random() < 0.7 and \
                    len(b) + delta <= self.max_len:
                ext = (b"\x00" * delta if self.rng.random() < 0.5
                       else bytes(self.rng.integers(0, 256, delta,
                                                    dtype=np.uint8)))
                b.extend(ext)
            elif len(b) > delta:
                del b[-delta:]
        pos = self.positions(bytes(b))
        for i in self.rng.choice(pos, size=min(k, len(pos)),
                                 replace=False) if pos else ():
            m = self.rng.random()
            if m < 0.5:
                b[i] = self._rand_value()
            else:
                delta = int(self.rng.choice((1, 2, 4, 16, 64))) * \
                    (1 if self.rng.random() < 0.5 else -1)
                b[i] = (b[i] + delta) & 0xFF
        return bytes(b)

    def _parent(self) -> bytes:
        # rank-weighted pick: geometric over the elite front
        r = min(int(self.rng.geometric(0.5)) - 1, len(self.elite) - 1)
        return self.elite[r][1]

    def _insert_probes(self, best: bytes, role: int,
                       quota: int) -> List[bytes]:
        """Deterministic dictionary-insertion sweep around the best
        elite: every (position, token) splice, rotated across
        iterations.  Command-stream targets need whole well-formed
        records ADDED before the target branch; enumerating the
        splices finds them in O(1) iterations where random insertion
        needs many."""
        if not self.tokens or self.mask is not None:
            return []
        variants: List[bytes] = []
        for t in self.tokens:
            variants.append(bytes(t))
            if len(t) == 1:
                variants.append(bytes(t) + b"\x00")  # opcode + arg
        # variant-major: each token sweeps every position before the
        # cycle moves to the next token, so early-dictionary tokens
        # (magic, low opcodes) land within the first iterations even
        # under small per-center quotas
        combos = [(p, v) for v in variants
                  for p in range(len(best) + 1)]
        out = []
        for p, v in self._cycle("ins", role, combos, quota):
            if len(best) + len(v) <= self.max_len:
                b = bytearray(best)
                b[p:p] = v
                out.append(bytes(b))
        return out

    def _probe_center(self, best: bytes, role: int, n_single: int,
                      n_pair: int, n_insert: int) -> List[bytes]:
        """Deterministic probe families around ONE center, each
        cycled across iterations so every combo gets its turn under
        the per-batch quotas."""
        out: List[bytes] = []
        pos = self.positions(best)
        out.extend(self._insert_probes(best, role, n_insert))
        # single-coordinate probes (finite differences: the numeric-
        # descent moves); operand-dependency positions go first
        hot = [i for i in (self.focus or []) if i in set(pos)]
        cold = [i for i in pos if i not in set(hot)]
        combos = [(i, s) for i in hot + cold for d in _PROBE_DELTAS
                  for s in (d, -d)]
        for i, s in self._cycle("one", role, combos, n_single):
            b = bytearray(best)
            b[i] = (b[i] + s) & 0xFF
            out.append(bytes(b))
        # PAIR probes: +d on byte i with a compensating delta on byte
        # j.  A lone byte move through a sum-style integrity check
        # (checksums, counters) kills reachability before the target
        # branch ever samples; the compensated pair preserves linear
        # invariants while still moving the operand.  When the
        # operand's dynamic byte deps are known, i ranges over THEM
        # (j — the compensator, e.g. the checksum byte — stays
        # unrestricted).  Compensation variants: +/-d (same-weight
        # sums) and +/-2d (a grown record re-bases a moved integrity
        # byte on other operands — 2d covers the unit-growth case)
        isrc = hot or pos
        pcombos = [(i, j, d, s)
                   for d in (1, 4, 16, 64)
                   for i in isrc for j in pos if i != j
                   for s in (d, -d, 2 * d, -2 * d)]
        for i, j, d, s in self._cycle("two", role, pcombos, n_pair):
            b = bytearray(best)
            b[i] = (b[i] + d) & 0xFF
            b[j] = (b[j] + s) & 0xFF
            out.append(bytes(b))
        return out

    def batch(self, extra: Sequence[bytes] = ()) -> List[bytes]:
        out: List[bytes] = [e[1] for e in self.elite]
        out.extend(extra)
        # every center is probed every batch: the zero-extended
        # sibling (or a back-stage repair lane) must not wait for a
        # rotation turn it may never get
        centers = self.centers or [self.elite[0][1]]
        nc = len(centers)
        for role, best in enumerate(centers):
            out.extend(self._probe_center(
                best, role, (self.lanes // 3) // nc,
                (self.lanes // 3) // nc, (self.lanes // 8) // nc))
        # recombination: uniform elite-pair crossover over mask bytes
        for _ in range(self.lanes // 8):
            p1, p2 = self._parent(), self._parent()
            if len(p2) != len(p1):
                continue
            b = bytearray(p1)
            for i in self.positions(p1):
                if self.rng.random() < 0.5 and i < len(p2):
                    b[i] = p2[i]
            out.append(bytes(b))
        # ES mutants fill the rest
        while len(out) < self.lanes:
            k = int(self.rng.integers(1, 4 + self.radius))
            out.append(self._mutate(self._parent(), k))
        return out[:self.lanes]

    def rank(self, cands: List[bytes], keys: List[tuple]) -> bool:
        """Rebuild the elite front from THIS batch's staged keys;
        True when the best key improved.  Elites ride in every batch,
        so rebuilding (rather than min-folding history) keeps the
        front monotone while letting curriculum progress re-score
        everything cleanly."""
        prev = self.elite[0][0]
        pool: Dict[bytes, tuple] = {}
        for c, k in zip(cands, keys):
            if c not in pool or k < pool[c]:
                pool[c] = k
        ranked = sorted(pool.items(), key=lambda kv: kv[1])
        # stratified keep: reserve slots for the best lanes of EACH
        # curriculum stage so back-stage progress survives the cut
        by_stage: Dict[int, List[Tuple[tuple, bytes]]] = {}
        for c, k in ranked:
            by_stage.setdefault(k[0], []).append((k, c))
        stages = sorted(by_stage)
        quota = max(N_ELITE // max(len(stages), 1), 2)
        elite: List[Tuple[tuple, bytes]] = []
        taken = set()
        for st in stages:
            for k, c in by_stage[st][:quota]:
                elite.append((k, c))
                taken.add(c)
        for c, k in ranked:             # fill with the global best
            if len(elite) >= N_ELITE:
                break
            if c not in taken:
                elite.append((k, c))
                taken.add(c)
        self.elite = sorted(elite)[:N_ELITE]
        # probe centers, probed EVERY batch with split quotas:
        #   * the best elite,
        #   * leading elites of DISTINCT LENGTHS (structurally
        #     different ties — e.g. the zero-extended sibling whose
        #     extra positions a moved checksum must land on),
        #   * one representative of the next stage back (a lane that
        #     re-broke an early guard while fixing a later operand is
        #     often one repair probe from the front).
        centers = [self.elite[0]]
        have = {self.elite[0][1]}
        have_lens = {len(self.elite[0][1])}
        for k, c in self.elite:
            if len(centers) >= 3:
                break
            if c not in have and len(c) not in have_lens:
                centers.append((k, c))
                have.add(c)
                have_lens.add(len(c))
        stage0 = self.elite[0][0][0]
        for st in stages:
            if st > stage0:
                k, c = by_stage[st][0][0], by_stage[st][0][1]
                if c not in have:
                    centers.append((k, c))
                break
        self.center_keys = [k for k, _ in centers]
        self.centers = [c for _, c in centers]
        return self.elite[0][0] < prev

    def restart(self) -> None:
        """Stagnation: widen the mutation radius and refresh the tail
        of the front from heavily-mutated seeds."""
        self.radius = min(self.radius + 2, 8)
        keep = self.elite[:max(2, N_ELITE // 4)]
        fresh = []
        for _ in range(N_ELITE - len(keep)):
            s = self.seeds[int(self.rng.integers(len(self.seeds)))]
            fresh.append((_KEY_UNREACHED,
                          self._mutate(s, 4 + self.radius)))
        self.elite = keep + fresh


def _concrete_trace(program, s: bytes, cache: Optional[Dict] = None):
    """``concrete_run`` memoized per input buffer — the reach filter
    and the path-guard extraction replay the same seeds."""
    if cache is None:
        return concrete_run(program, s)
    t = cache.get(s)
    if t is None:
        t = cache[s] = concrete_run(program, s)
    return t


def _path_guards(program, edge: Tuple[int, int],
                 seeds: Sequence[bytes],
                 cap: int = MAX_GUARDS,
                 trace_cache: Optional[Dict] = None
                 ) -> List[BranchObjective]:
    """The guard curriculum INTO the edge's source block: deciding
    branches of every edge along the first seed path that reaches it,
    in path order.  Mutations that break an earlier guard (shift a
    checksum, shorten a length field) stop sampling the target branch
    entirely; scoring these guards in the same dispatch tells the
    ranking WHERE such a lane died and how close it is to recovering."""
    f = int(edge[0])
    if f < 0:
        return []
    for s in seeds:
        tr = _concrete_trace(program, bytes(s), trace_cache)
        if f not in tr.blocks:
            continue
        guards: List[BranchObjective] = []
        for e2 in tr.edges:
            guards.extend(edge_objectives(program,
                                          (int(e2[0]), int(e2[1]))))
            if int(e2[1]) == f:
                break
        seen = set()
        out = []
        for g in guards:
            k = g.spec()
            if k not in seen:
                seen.add(k)
                out.append(g)
        return out[-cap:]
    return []


def _staged_keys(dists: np.ndarray) -> List[tuple]:
    """Per-lane curriculum rank key from the [B, K] guard distances:
    ``(guards past the DEEPEST one sampled, distance there)`` —
    lexicographically smaller = further along the path and closer at
    the frontier guard.  Ranking on the deepest SAMPLED guard (not
    the first non-zero one) matters on loops: a lane that takes the
    loop body exits through a different edge than the seed's
    zero-iteration path, leaving an early guard's distance nonzero
    forever even though the lane sailed past that region."""
    keys = []
    k_total = dists.shape[1]
    unreached = np.float32(DIST_UNREACHED)
    for row in dists:
        sampled = np.flatnonzero(row < unreached)
        if len(sampled):
            i = int(sampled[-1])
            keys.append((k_total - 1 - i, float(row[i])))
        else:
            keys.append((k_total, float(DIST_UNREACHED)))
    return keys


def descend_edge(program, edge: Tuple[int, int],
                 seeds: Sequence[bytes], *,
                 mask: Optional[Sequence[int]] = None,
                 lanes: int = DEFAULT_LANES,
                 budget: int = DEFAULT_DESCENT_BUDGET,
                 max_len: int = 64,
                 rng_seed: int = 0x6465,
                 trace=None,
                 trace_cache: Optional[Dict] = None) -> DescentResult:
    """Descend the branch-distance curriculum of ``edge`` until a
    verified witness traverses it or ``budget`` device dispatches are
    spent.  The curriculum is the deciding branches of the path INTO
    the edge's source block plus the edge's own deciding branches, in
    program order; one dispatch scores all of them for the whole
    population and lanes rank by how far along they got.  ``seeds``
    should be inputs whose paths reach the source block (the cracker
    filters the corpus; anything works, it just starts unranked).
    ``mask`` restricts mutation dimensions to the solver's
    dependency bytes; ``trace`` (a TraceRecorder) puts every dispatch
    on the ``descent`` lane."""
    f_idx, t_idx = int(edge[0]), int(edge[1])
    e_idx = _edge_index(program, edge)
    if e_idx is None:
        return DescentResult(edge=(f_idx, t_idx), status="exhausted",
                             reason="edge not in the static universe")
    seeds = [bytes(s) for s in seeds if s] or [b"\x00"]
    own = edge_objectives(program, edge)
    guards = _path_guards(program, edge, seeds,
                          cap=max(MAX_GUARDS - len(own), 0),
                          trace_cache=trace_cache)
    specs_objs: List[BranchObjective] = (guards + own)[-MAX_GUARDS:]
    rng = np.random.default_rng(rng_seed ^ ((f_idx & 0xFFFF) << 16)
                                ^ (t_idx & 0xFFFF))
    max_len = max(int(max_len), max(len(s) for s in seeds))
    L = max(8, ((max_len + 7) // 8) * 8)
    lanes = max(int(lanes), 2 * N_ELITE)
    k_total = len(specs_objs)

    try:
        from ..analysis.dataflow import extract_dictionary
        tokens = extract_dictionary(program)
    except Exception:
        tokens = []
    pop = _Population(list(seeds), list(mask) if mask else None,
                      lanes, rng, max_len=max_len, tokens=tokens)
    steps = evals = 0
    stagnant = 0
    best_primary = float(DIST_UNREACHED)
    best_desc = ""
    soft_used = False
    deps_cache: Dict[tuple, tuple] = {}

    def _slice_for(k_idx: int, buf: bytes):
        key = (k_idx, buf)
        if key not in deps_cache:
            sl = trace_slice(program, buf, specs_objs[k_idx])
            deps_cache[key] = (sl, slice_operand_deps(
                program, sl, specs_objs[k_idx]))
        return deps_cache[key]

    for it in range(max(int(budget), 1)):
        grads: List[bytes] = []
        obj = None
        if specs_objs:
            # each stage representative's first unsatisfied guard
            # contributes its operands' dynamic byte deps to the
            # probe focus — one concrete host replay per (guard,
            # center), cached
            focus: set = set()
            for ck, cb in zip(pop.center_keys, pop.centers):
                k_idx = min(max(k_total - 1 - ck[0], 0), k_total - 1)
                focus.update(_slice_for(k_idx, cb)[1])
            pop.focus = sorted(focus) or None
            # the soft tier relaxes the BEST elite's frontier guard
            # when its path slice is arithmetic-only
            ke, best = pop.elite[0]
            k_idx = min(max(k_total - 1 - ke[0], 0), k_total - 1)
            obj = specs_objs[k_idx]
            if it and it % 4 == 0:
                sl = _slice_for(k_idx, best)[0]
                if sl.eligible:
                    grads = soft_refine(program, best, obj,
                                        positions=pop.positions(best),
                                        slice_=sl)
                    soft_used = soft_used or bool(grads)
        cands = pop.batch(extra=grads)
        bufs, lens = _pack(cands, lanes, L)
        span = (trace.span("descend_batch", lane="descent",
                           args={"edge": f"{f_idx}:{t_idx}",
                                 "iter": it, "lanes": lanes,
                                 "guards": k_total})
                if trace is not None else contextlib.nullcontext())
        with span:
            if specs_objs:
                res, dists = run_batch_distances(
                    program, bufs, lens,
                    tuple(o.spec() for o in specs_objs))
                dists = np.asarray(dists)
            else:
                # unconditional edge: no branch to descend on — run
                # the population anyway (covering the source block
                # covers the edge) and rank everything equal
                from ..models.vm import run_batch
                res = run_batch(program, bufs, lens,
                                record_stream=False)
                dists = np.full((lanes, 1), DIST_UNREACHED,
                                dtype=np.float32)
            hits = np.asarray(res.counts[:, e_idx]) > 0
        steps += 1
        evals += len(cands)
        for r in np.flatnonzero(hits[:len(cands)]):
            buf = cands[int(r)]
            # honesty contract: the reference interpreter must agree
            # before the witness is reported
            if (f_idx, t_idx) in concrete_run(program, buf).edges:
                return DescentResult(
                    edge=(f_idx, t_idx), status="descended",
                    input=buf, steps=steps, evals=evals,
                    best_dist=0.0,
                    objective=obj.desc if obj else "",
                    soft_used=soft_used, engine="host",
                    dispatches=steps, iterations=steps)
        improved = pop.rank(cands, _staged_keys(dists[:len(cands)]))
        if specs_objs and own:
            primary = float(dists[:len(cands), -1].min())
            if primary < best_primary:
                best_primary = primary
                best_desc = specs_objs[-1].desc
        DEBUG_MSG("descend %d:%d iter %d best %s",
                  f_idx, t_idx, it, pop.elite[0][0])
        if improved:
            stagnant = 0
        else:
            stagnant += 1
            if stagnant >= STAGNATION_WINDOW:
                pop.restart()
                stagnant = 0
    return DescentResult(
        edge=(f_idx, t_idx), status="exhausted", steps=steps,
        evals=evals, best_dist=best_primary, objective=best_desc,
        reason=f"step budget exhausted ({budget} dispatches)",
        soft_used=soft_used, engine="host", dispatches=steps,
        iterations=steps)


def seeds_reaching_block(program, seeds: Sequence[bytes],
                         block: int, cap: int = 64,
                         trace_cache: Optional[Dict] = None
                         ) -> List[bytes]:
    """Filter ``seeds`` to those whose concrete path executes
    ``block`` (-1 = entry: every input).  The population wants to
    START at the branch, not re-discover the path to it.  Pass one
    ``trace_cache`` dict across calls (and into ``descend_edge``) so
    each seed is reference-interpreted once, not once per consumer."""
    if block < 0:
        return list(seeds)[:cap]
    out = []
    for s in seeds:
        if block in _concrete_trace(program, bytes(s),
                                    trace_cache).blocks:
            out.append(bytes(s))
            if len(out) >= cap:
                break
    return out
