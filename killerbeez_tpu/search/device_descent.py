"""Device-resident descent — R full rank -> probe -> mutate ->
re-score iterations per host dispatch.

PR 7's host engine (``descent.py``) round-trips every candidate
population through the host on every iteration: the host ranks the
returned distances, regenerates the probe batch in Python, and
dispatches again — the exact pipeline bubble the generation scans
(PRs 9-10) eliminated for the fuzzing loop.  This module closes the
descent loop ON the device: one jitted ``lax.scan`` runs

    rank elites -> emit probe/i2s/ES candidates -> execute with
    curriculum distances + operand capture -> re-rank -> append
    witnesses

R times per dispatch, with donated carry state (elite population,
per-center probe rotation cursors, captured compare operands, a
bounded best-witness ring) so the buffers update in place on the
accelerator and the host only drains one witness report per R
iterations.

Probe families mirror the host engine's — single-coordinate
+/-{1,2,4,16,64} probes, compensated pair probes, dictionary-token
insertion sweeps, ES mutants — but are keyed by DETERMINISTIC
per-lane rotation counters (pure uint32 mixing, no host RNG), so the
stepped mode (``scan_iters=1``: one device iteration per dispatch,
the host driving the loop) and the in-scan mode (``scan_iters=R``)
generate bit-identical candidate streams: the host-vs-device parity
pin compares elite ranked order and emitted witnesses between the
two at matched schedules (tests/test_device_descent.py).

NEW vs the host engine: **input-to-state operand matching**
(Redqueen, with Angora's distance framework underneath).  The
distance engine already observes the concrete compare operands at
every curriculum branch; ``vm.run_batch_distances(...,
capture_operands=True)`` returns them, and a dedicated lane block
copies the OBSERVED operand value back into the candidate at the
branch's dynamic byte-dependency positions — both endianness orders
plus +/-1 variants.  A 32-bit magic/checksum compare that coordinate
probes would walk byte-by-byte cracks in one generation: iteration j
samples the operands, iteration j+1 writes them into the input.

Honesty contract unchanged: a witness ring row is only ever REPORTED
after the pure-Python reference interpreter confirms the edge
traversal on the host.  The engine stands down to the host engine
when an edge has no deciding branches (unconditional edges descend
on block coverage alone, which the host engine handles).
"""

from __future__ import annotations

import contextlib
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.solver import concrete_run
from ..models.vm import (
    DIST_UNREACHED, _dist_loop_core, _mix32,
)
from ..ops.generations import carry_donation_argnums
from ..utils.logging import DEBUG_MSG
from .descent import (
    _PROBE_DELTAS, MAX_GUARDS, N_ELITE, DescentResult, _concrete_trace,
    _edge_index, _pack, _path_guards, descend_edge,
)
from .objective import BranchObjective, edge_objectives
from .soft import slice_operand_deps, soft_refine, trace_slice

#: iterations fused into one device dispatch (the R of the module
#: docstring); the stepped parity mode passes 1
DEFAULT_SCAN_ITERS = 8

#: best-witness ring capacity per dispatch (append-bounded like the
#: generations findings ring: the FIRST W edge-traversing lanes are
#: kept in (iteration, lane) order, the pointer counts overflow)
WITNESS_RING = 32

#: byte-dependency positions considered per guard for the i2s writes
#: (32-bit operands: four bytes)
I2S_DEPS = 4

#: i2s lanes per guard: 2 operand sides x {0, +1, -1} x {LE, BE}
I2S_PER_GUARD = 12

#: lane-family tags recorded in the witness ring (telemetry: a
#: witness with family FAM_I2S is an input-to-state crack)
FAM_ELITE, FAM_I2S, FAM_ONE, FAM_TWO, FAM_INS, FAM_ES = range(6)

_FNV_PRIME = 0x01000193
_UNREACHED_F32 = np.float32(DIST_UNREACHED)


def _layout(lanes: int, k: int) -> Dict[str, int]:
    """Static lane-block layout: [elites][i2s][one][two][ins][es].
    Pure function of the static config so the stepped and in-scan
    modes agree by construction."""
    n_el = N_ELITE
    n_i2s = I2S_PER_GUARD * k
    rest = max(lanes - n_el - n_i2s, 8)
    n_ins = rest // 6             # window-dup variants exist even
    probe = rest - n_ins          # with an empty dictionary
    n_one = (probe * 2 // 5) & ~1          # even: split over 2 roles
    n_two = (probe * 2 // 5) & ~1
    n_es = rest - n_ins - n_one - n_two
    return {"el": n_el, "i2s": n_i2s, "one": n_one, "two": n_two,
            "ins": n_ins, "es": n_es,
            "total": n_el + n_i2s + n_one + n_two + n_ins + n_es}


def _family_tags(lay: Dict[str, int]) -> np.ndarray:
    fams = []
    for name, tag in (("el", FAM_ELITE), ("i2s", FAM_I2S),
                      ("one", FAM_ONE), ("two", FAM_TWO),
                      ("ins", FAM_INS), ("es", FAM_ES)):
        fams.extend([tag] * lay[name])
    return np.asarray(fams, dtype=np.int32)


def _onehot_write(rows, pos, val):
    """rows[r, pos[r]] = val[r] without scatter: one-hot select over
    the (static) L axis."""
    L = rows.shape[1]
    m = jnp.arange(L, dtype=jnp.int32)[None, :] == pos[:, None]
    return jnp.where(m, val[:, None], rows)


def _clip_pos(raw, clen):
    """Map a rotation position into the live prefix: in-range raw
    positions pass through, out-of-range ones wrap (deterministic in
    both modes; double-weighting early bytes of short centers is
    acceptable)."""
    return jnp.where(raw < clen, raw, raw % jnp.maximum(clen, 1))


def _gen_i2s(e_bufs, e_lens, cap_x, cap_y, cap_valid, dep_pos, n_dep,
             k: int, L: int):
    """Input-to-state lane block: for every guard, copy each observed
    compare operand (+/-1 variants) into the best elite at the
    guard's byte-dependency positions, little- and big-endian byte
    orders.  Guards never sampled (cap_valid 0) degenerate to plain
    copies of the base."""
    n = I2S_PER_GUARD * k
    r = np.arange(n)
    k_r = jnp.asarray(r // I2S_PER_GUARD, jnp.int32)
    sub = r % I2S_PER_GUARD
    side = jnp.asarray(sub // 6, jnp.int32)           # 0 = x, 1 = y
    delta = jnp.asarray(np.array([0, 1, -1])[(sub % 6) // 2],
                        jnp.int32)
    order = jnp.asarray(sub % 2, jnp.int32)           # 0 LE, 1 BE

    base = e_bufs[0].astype(jnp.int32)
    blen = e_lens[0]
    vals = jnp.where(side == 0, cap_x[k_r], cap_y[k_r]) + delta
    valid = cap_valid[k_r] > 0
    w = jnp.clip(n_dep[k_r], 0, I2S_DEPS)
    rows = jnp.broadcast_to(base[None, :], (n, L))
    md = jnp.full((n,), -1, jnp.int32)
    for j in range(I2S_DEPS):
        p = dep_pos[k_r, j]
        active = (j < w) & (p >= 0) & valid
        byte_sel = jnp.where(order == 0, j, w - 1 - j)
        byte = (vals >> (8 * byte_sel)) & 0xFF
        rows = jnp.where(
            (jnp.arange(L, dtype=jnp.int32)[None, :] == p[:, None])
            & active[:, None], byte[:, None], rows)
        md = jnp.maximum(md, jnp.where(active, p, -1))
    lens = jnp.where(valid, jnp.maximum(blen, md + 1), blen)
    return rows, lens


def _gen_one(base0, len0, base1, len1, cur0, cur1, pos_order,
             n_one: int, L: int):
    """Single-coordinate finite-difference probes around the two
    centers, rotating through (position, signed delta) combos."""
    if not n_one:
        return (jnp.zeros((0, L), jnp.int32),
                jnp.zeros((0,), jnp.int32))
    half = n_one // 2
    local = np.arange(n_one)
    role = jnp.asarray((local >= half).astype(np.int32))
    off = jnp.asarray(np.where(local < half, local, local - half)
                      .astype(np.int32))
    c = jnp.where(role == 0, cur0, cur1) + off
    clen = jnp.where(role == 0, len0, len1)
    # signed delta fastest, position next — one full sweep of the
    # LIVE prefix costs ~10 * clen combos, so short centers cycle
    # every iteration or two instead of dragging the whole L axis
    raw = pos_order[(c // 10) % jnp.maximum(clen, 1)]
    pos = _clip_pos(raw, clen)
    deltas = jnp.asarray(_PROBE_DELTAS, jnp.int32)
    d = deltas[(c % 10) // 2] * (1 - 2 * (c % 2))
    cb = jnp.where(role[:, None] == 0, base0[None, :].astype(jnp.int32),
                   base1[None, :].astype(jnp.int32))
    at = jnp.sum(jnp.where(
        jnp.arange(L, dtype=jnp.int32)[None, :] == pos[:, None],
        cb, 0), axis=1)
    return _onehot_write(cb, pos, (at + d) & 0xFF), clen


def _gen_two(base0, len0, base1, len1, cur0, cur1, pos_order,
             n_two: int, L: int):
    """Compensated pair probes: +d on byte i, a compensating
    {+d,-d,+2d,-2d} on byte j — moves an operand THROUGH sum-style
    integrity checks instead of dying at them."""
    if not n_two:
        return (jnp.zeros((0, L), jnp.int32),
                jnp.zeros((0,), jnp.int32))
    half = n_two // 2
    local = np.arange(n_two)
    role = jnp.asarray((local >= half).astype(np.int32))
    off = jnp.asarray(np.where(local < half, local, local - half)
                      .astype(np.int32))
    c = jnp.where(role == 0, cur0, cur1) + off
    clen = jnp.where(role == 0, len0, len1)
    # compensator sign fastest, then the compensating position j,
    # then the operand position i (over the 8 hottest focus
    # positions), then the magnitude — the host engine's combo order,
    # so a (dep byte, +d) x (every j, -2d) sweep completes within the
    # first iterations where it matters (moved counters re-based on a
    # neighbour byte)
    smul = jnp.asarray([1, -1, 2, -2], jnp.int32)[c % 4]
    jm = jnp.maximum(clen - 1, 1)
    j_off = 1 + (c // 4) % jm
    i_pos = _clip_pos(pos_order[(c // (4 * jm)) % 8], clen)
    d = jnp.asarray([1, 4, 16, 64], jnp.int32)[
        (c // (32 * jm)) % 4]
    j_pos = (i_pos + j_off) % jnp.maximum(clen, 1)
    cb = jnp.where(role[:, None] == 0, base0[None, :].astype(jnp.int32),
                   base1[None, :].astype(jnp.int32))
    lidx = jnp.arange(L, dtype=jnp.int32)[None, :]
    ai = jnp.sum(jnp.where(lidx == i_pos[:, None], cb, 0), axis=1)
    rows = _onehot_write(cb, i_pos, (ai + d) & 0xFF)
    aj = jnp.sum(jnp.where(lidx == j_pos[:, None], rows, 0), axis=1)
    return _onehot_write(rows, j_pos, (aj + d * smul) & 0xFF), clen


def _gen_ins(base, blen, cur, tok_bufs, tok_lens, n_ins: int,
             n_tokens: int, L: int):
    """Structural insertion sweep around the best elite, tail
    shifted: dictionary-token splices, token + argument-byte splices
    (command-stream records are opcode + operand) and duplicated
    windows of {1, 2, 4} preceding bytes (re-inserting a well-formed
    record that is already there), every variant x position rotated
    across iterations.  Depth-counter guards need whole records ADDED
    before the branch — no fixed-position byte move can."""
    if not n_ins:
        return (jnp.zeros((0, L), jnp.int32),
                jnp.zeros((0,), jnp.int32))
    T = max(n_tokens, 0)
    n_var = 2 * T + 3             # raw token, token+arg, dup {1,2,4}
    c = cur + jnp.asarray(np.arange(n_ins, dtype=np.int32))
    # variant-MINOR: consecutive combos cycle the variant list so
    # every token/dup width gets tried each iteration even under
    # small per-iteration quotas; the position advances once per full
    # variant cycle (live buffers are much shorter than L — a
    # position-major order would starve late-dictionary tokens)
    p = (c // n_var) % jnp.maximum(blen + 1, 1)
    v = c % n_var
    is_dup = v >= 2 * T
    is_arg = (v >= T) & ~is_dup
    t = jnp.where(is_dup, 0, v % jnp.maximum(T, 1))
    w = jnp.asarray([1, 2, 4], jnp.int32)[
        jnp.clip(v - 2 * T, 0, 2)]
    base_tl = tok_lens[t] if T else jnp.zeros_like(c)
    tl = jnp.where(is_dup, jnp.minimum(w, jnp.maximum(blen, 1)),
                   base_tl + is_arg.astype(jnp.int32))
    new_len = jnp.minimum(blen + tl, L)
    q = jnp.arange(L, dtype=jnp.int32)[None, :]
    rel = q - p[:, None]
    TL = tok_bufs.shape[1]
    tok_rows = jnp.take(tok_bufs, t, axis=0).astype(jnp.int32)
    tok_byte = jnp.sum(jnp.where(
        rel[:, :, None] == jnp.arange(TL, dtype=jnp.int32)[None, None, :],
        tok_rows[:, None, :], 0), axis=2)
    # the argument byte trailing a token splice rotates with the
    # cursor so every opcode sweeps many operand values over time
    arg = (((c.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)) >> 13)
           & 0xFF).astype(jnp.int32)
    tok_byte = jnp.where(is_arg[:, None] & (rel == base_tl[:, None]),
                         arg[:, None], tok_byte)
    # duplicated-window bytes read the ORIGINAL buffer just before p
    dup_src = jnp.clip(p[:, None] - tl[:, None] + rel, 0, L - 1)
    dup_byte = jnp.take(base.astype(jnp.int32), dup_src)
    ins_byte = jnp.where(is_dup[:, None], dup_byte, tok_byte)
    in_ins = (rel >= 0) & (rel < tl[:, None])
    src = jnp.clip(q - tl[:, None], 0, L - 1)
    shifted = jnp.take(base.astype(jnp.int32), src)
    rows = jnp.where(q < p[:, None], base[None, :].astype(jnp.int32),
                     jnp.where(in_ins, ins_byte,
                               jnp.where(q < new_len[:, None],
                                         shifted, 0)))
    return rows, new_len


def _gen_es(e_bufs, e_lens, it, salt, lane0: int, n_es: int, L: int):
    """ES mutants: rank-picked parent, three byte edits (value or
    signed delta) + occasional zero-extension, all derived from
    ``_mix32`` counter mixing — deterministic, host-replayable, and
    fresh every iteration (the scan's restart-free diversity
    source)."""
    if not n_es:
        return (jnp.zeros((0, L), jnp.int32),
                jnp.zeros((0,), jnp.int32))
    lane = jnp.asarray(np.arange(lane0, lane0 + n_es,
                                 dtype=np.uint32))
    seed = _mix32(_mix32(it.astype(jnp.uint32)
                         * jnp.uint32(0x9E3779B9) ^ salt)
                  ^ lane * jnp.uint32(0x85EBCA6B))
    # rank-weighted parent pick (min of two uniforms ~ the host
    # engine's geometric bias toward the front)
    rank = jnp.minimum(seed % jnp.uint32(N_ELITE),
                       (seed >> 16) % jnp.uint32(N_ELITE)) \
        .astype(jnp.int32)
    rows = jnp.take(e_bufs, rank, axis=0).astype(jnp.int32)
    plen = jnp.take(e_lens, rank)
    deltas = jnp.asarray(_PROBE_DELTAS, jnp.int32)
    for e in range(3):
        h = _mix32(seed + jnp.uint32((0x6B8B4567 * (e + 1))
                                     & 0xFFFFFFFF))
        pos = (h % jnp.maximum(plen, 1).astype(jnp.uint32)) \
            .astype(jnp.int32)
        use_val = ((h >> 8) & 1).astype(jnp.int32)
        val = ((h >> 16) & 0xFF).astype(jnp.int32)
        didx = ((h >> 9) % 5).astype(jnp.int32)
        sgn = 1 - 2 * ((h >> 12) & 1).astype(jnp.int32)
        cur = jnp.take_along_axis(rows, pos[:, None], axis=1)[:, 0]
        nb = jnp.where(use_val == 1, val,
                       (cur + deltas[didx] * sgn) & 0xFF)
        rows = _onehot_write(rows, pos, nb)
    grow = ((seed >> 3) % 4) == 0
    glen = (1 + ((seed >> 5) % 8)).astype(jnp.int32)
    lens = jnp.where(grow, jnp.minimum(plen + glen, L), plen)
    return rows, lens


def _descent_scan_impl(instrs, edge_table, pos_order, dep_pos, n_dep,
                       tok_bufs, tok_lens,
                       e_bufs, e_lens, e_stage, e_dist, cursors,
                       cap_x, cap_y, cap_valid,
                       wit_bufs, wit_lens, wit_src, wit_iter, wit_ptr,
                       best_primary, it0, salt,
                       mem_size=0, max_steps=0, n_edges=0,
                       specs=(), e_idx=0, lanes=0, scan_iters=1,
                       n_tokens=0, i2s=True):
    """R descent iterations in ONE device program; see module
    docstring for the carry/report contract."""
    K = len(specs)
    L = int(e_bufs.shape[1])
    lay = _layout(lanes, K)
    B = lay["total"]
    fam = jnp.asarray(_family_tags(lay))
    pows = np.empty(L, dtype=np.uint32)
    acc = 1
    for i in range(L):
        pows[i] = acc
        acc = (acc * _FNV_PRIME) & 0xFFFFFFFF
    pows = jnp.asarray(pows)
    wcap = min(WITNESS_RING, B)
    k_last = K - 1

    def one_iteration(carry, it):
        # early-stop: once any witness is in the ring, the remaining
        # scan iterations idle (the host drains, verifies and stops
        # dispatching) — a mid-scan crack must not burn the rest of
        # the dispatch's budget executing deep candidates.  Idled
        # iterations report -1 (vs >= 0 hit counts) so the host's
        # eval accounting stays truthful.
        found = carry[12] > 0
        return jax.lax.cond(found,
                            lambda c: (c, jnp.int32(-1)),
                            lambda c: _one_iteration_work(c, it),
                            carry)

    def _one_iteration_work(carry, it):
        (e_bufs, e_lens, e_stage, e_dist, cursors, cap_x, cap_y,
         cap_valid, wit_bufs, wit_lens, wit_src, wit_iter, wit_ptr,
         best_primary) = carry
        # -- probe centers: the best elite, plus ONE of (in priority
        # order) the leading distinct-LENGTH elite (the structurally
        # different tie — e.g. the zero-extended sibling whose extra
        # positions a moved counter/checksum must land on), the first
        # next-stage-back elite (the repair lane), or elite 1
        idxs = jnp.arange(N_ELITE, dtype=jnp.int32)
        dl = jnp.min(jnp.where(e_lens != e_lens[0], idxs, N_ELITE))
        bk = jnp.min(jnp.where(e_stage > e_stage[0], idxs, N_ELITE))
        c1 = jnp.where(dl < N_ELITE, dl,
                       jnp.where(bk < N_ELITE, bk, 1))
        base0, len0 = e_bufs[0], e_lens[0]
        base1 = e_bufs[c1]
        len1 = e_lens[c1]

        # -- generate the candidate batch block by block
        blocks = [(e_bufs.astype(jnp.int32), e_lens)]
        if i2s:
            blocks.append(_gen_i2s(e_bufs, e_lens, cap_x, cap_y,
                                   cap_valid, dep_pos, n_dep, K, L))
        else:
            # i2s disabled (the ablation lane): plain base copies so
            # the layout — and every other family's cursor stream —
            # stays identical at equal budget
            blocks.append((jnp.broadcast_to(
                e_bufs[0].astype(jnp.int32)[None, :],
                (lay["i2s"], L)),
                jnp.broadcast_to(e_lens[0], (lay["i2s"],))))
        blocks.append(_gen_one(base0, len0, base1, len1, cursors[0],
                               cursors[1], pos_order, lay["one"], L))
        blocks.append(_gen_two(base0, len0, base1, len1, cursors[2],
                               cursors[3], pos_order, lay["two"], L))
        blocks.append(_gen_ins(base0, len0, cursors[4], tok_bufs,
                               tok_lens, lay["ins"],
                               max(n_tokens, 1), L))
        blocks.append(_gen_es(e_bufs, e_lens, it, salt,
                              lay["el"] + lay["i2s"] + lay["one"]
                              + lay["two"] + lay["ins"],
                              lay["es"], L))
        cand = jnp.concatenate([b for b, _ in blocks], axis=0)
        lens = jnp.concatenate([ln for _, ln in blocks], axis=0)
        lens = jnp.clip(lens, 0, L).astype(jnp.int32)
        # the zeros-past-length invariant (hashing + extension moves
        # rely on it)
        cand = jnp.where(jnp.arange(L, dtype=jnp.int32)[None, :]
                         < lens[:, None], cand, 0)
        cand = (cand & 0xFF).astype(jnp.uint8)

        # -- execute: curriculum distances + operand capture
        res, dists, cx, cy = _dist_loop_core(
            instrs, edge_table, cand, lens, mem_size, max_steps,
            n_edges, specs, True)

        # -- capture update: min-distance lane per guard
        m = jnp.argmin(dists, axis=0)
        dmin = jnp.min(dists, axis=0)
        sampled = dmin < _UNREACHED_F32
        ksel = jnp.arange(K, dtype=jnp.int32)
        cap_x = jnp.where(sampled, cx[m, ksel], cap_x)
        cap_y = jnp.where(sampled, cy[m, ksel], cap_y)
        cap_valid = jnp.where(sampled, 1, cap_valid)

        # -- witness ring: lanes that traversed the edge, (iteration,
        # lane) order, pointer counts overflow
        hits = res.counts[:, e_idx] > 0
        raw = jnp.sum(hits).astype(jnp.int32)
        (hidx,) = jnp.nonzero(hits, size=wcap, fill_value=0)
        wpos = wit_ptr + jnp.arange(wcap, dtype=jnp.int32)
        valid = (jnp.arange(wcap) < jnp.minimum(raw, wcap)) \
            & (wpos < WITNESS_RING)
        tgt = jnp.where(valid, wpos, WITNESS_RING)
        wit_bufs = wit_bufs.at[tgt].set(cand[hidx], mode="drop")
        wit_lens = wit_lens.at[tgt].set(lens[hidx], mode="drop")
        wit_src = wit_src.at[tgt].set(fam[hidx], mode="drop")
        wit_iter = wit_iter.at[tgt].set(it.astype(jnp.int32),
                                        mode="drop")
        wit_ptr = wit_ptr + raw

        # -- device-side curriculum ranking: staged key per lane
        sampled_m = dists < _UNREACHED_F32
        any_s = jnp.any(sampled_m, axis=1)
        deep = (K - 1) - jnp.argmax(sampled_m[:, ::-1], axis=1)
        stage = jnp.where(any_s, (K - 1) - deep,
                          K).astype(jnp.int32)
        dist_at = jnp.sum(jnp.where(
            ksel[None, :] == deep[:, None], dists, 0.0), axis=1)
        dist_l = jnp.where(any_s, dist_at, _UNREACHED_F32)

        # content hash (order-aware, length-mixed) for the dedup cut
        h = jnp.sum(cand.astype(jnp.uint32) * pows[None, :], axis=1,
                    dtype=jnp.uint32)
        h = _mix32(h ^ lens.astype(jnp.uint32))
        srt = jnp.lexsort((jnp.arange(B, dtype=jnp.int32), dist_l,
                           stage))
        hs = h[srt]
        earlier = jnp.arange(B, dtype=jnp.int32)[:, None] \
            > jnp.arange(B, dtype=jnp.int32)[None, :]
        dup = jnp.any((hs[:, None] == hs[None, :]) & earlier, axis=1)
        sel_rank = jnp.cumsum((~dup).astype(jnp.int32)) - 1
        svals = jnp.arange(N_ELITE, dtype=jnp.int32)[:, None]
        match = (sel_rank[None, :] == svals) & (~dup)[None, :]
        found = jnp.any(match, axis=1)
        fpos = jnp.argmax(match, axis=1)
        pos = jnp.where(found, fpos,
                        jnp.arange(N_ELITE, dtype=jnp.int32))
        # stratified tail: the last two slots go to the best lanes of
        # a LATER curriculum stage than the front's, when one exists
        # — the deceptive-fitness repair reservation (a lane that
        # re-broke an early guard while fixing a later operand is
        # often one probe from the front, and a pure global cut
        # evicts it)
        stage_s = stage[srt]
        back_m = (stage_s > stage_s[0]) & ~dup
        brank = jnp.cumsum(back_m.astype(jnp.int32)) - 1
        for slot, want in ((N_ELITE - 2, 0), (N_ELITE - 1, 1)):
            bm = back_m & (brank == want)
            bfound = jnp.any(bm)
            bpos = jnp.argmax(bm)
            pos = pos.at[slot].set(
                jnp.where(bfound, bpos, pos[slot]))
        # ... and one for the best distinct-LENGTH lane, so the
        # structural sibling (zero-extension, insertion survivor) the
        # second probe center wants never falls off the front while
        # it still ranks mid-pack
        lens_s = lens[srt]
        dlm = (lens_s != lens_s[0]) & ~dup
        dfound = jnp.any(dlm)
        dpos = jnp.argmax(dlm)
        pos = pos.at[N_ELITE - 3].set(
            jnp.where(dfound, dpos, pos[N_ELITE - 3]))
        sel = srt[pos]
        e_bufs = jnp.take(cand, sel, axis=0)
        e_lens = jnp.take(lens, sel)
        e_stage = jnp.take(stage, sel)
        e_dist = jnp.take(dist_l, sel)

        best_primary = jnp.minimum(best_primary,
                                   jnp.min(dists[:, k_last]))
        cursors = cursors + jnp.asarray(
            [lay["one"] // 2, lay["one"] - lay["one"] // 2,
             lay["two"] // 2, lay["two"] - lay["two"] // 2,
             lay["ins"]], jnp.int32)
        carry = (e_bufs, e_lens, e_stage, e_dist, cursors, cap_x,
                 cap_y, cap_valid, wit_bufs, wit_lens, wit_src,
                 wit_iter, wit_ptr, best_primary)
        return carry, raw

    carry0 = (e_bufs, e_lens, e_stage, e_dist, cursors, cap_x, cap_y,
              cap_valid, wit_bufs, wit_lens, wit_src, wit_iter,
              wit_ptr, best_primary)
    carry, raws = jax.lax.scan(
        one_iteration, carry0,
        it0 + jnp.arange(scan_iters, dtype=jnp.int32))
    return carry + (raws,)


#: positional args of _descent_scan_impl that are pure carry state —
#: elite front (7-10), cursors (11), operand captures (12-14), the
#: witness ring (15-19) and the best-primary fold (20).  The host
#: materializes each dispatch's outputs BEFORE re-feeding them, so
#: everything is donation-safe; CPU backends get no donation (same
#: policy as the generation scans).
_CARRY_ARGNUMS = tuple(range(7, 21))

_DESCENT_JIT = None


def _descent_scan(*args, **kwargs):
    global _DESCENT_JIT
    if _DESCENT_JIT is None:
        _DESCENT_JIT = jax.jit(
            _descent_scan_impl,
            static_argnames=("mem_size", "max_steps", "n_edges",
                             "specs", "e_idx", "lanes", "scan_iters",
                             "n_tokens", "i2s"),
            donate_argnums=carry_donation_argnums(
                jax.default_backend(), _CARRY_ARGNUMS))
    return _DESCENT_JIT(*args, **kwargs)


class DeviceDescent:
    """One edge's device-resident descent: owns the carry state and
    the per-dispatch drive.  ``descend_edge_device`` is the
    engine-shaped wrapper; the parity tests drive this class directly
    (stepped vs in-scan at matched schedules)."""

    def __init__(self, program, edge: Tuple[int, int],
                 seeds: Sequence[bytes], *,
                 mask: Optional[Sequence[int]] = None,
                 lanes: int = 256,
                 scan_iters: int = DEFAULT_SCAN_ITERS,
                 max_len: int = 64, i2s: bool = True,
                 trace_cache: Optional[Dict] = None):
        self.program = program
        self.edge = (int(edge[0]), int(edge[1]))
        self.e_idx = _edge_index(program, self.edge)
        if self.e_idx is None:
            raise ValueError("edge not in the static universe")
        seeds = [bytes(s) for s in seeds if s] or [b"\x00"]
        own = edge_objectives(program, self.edge)
        guards = _path_guards(program, self.edge, seeds,
                              cap=max(MAX_GUARDS - len(own), 0),
                              trace_cache=trace_cache)
        self.specs_objs: List[BranchObjective] = \
            (guards + own)[-MAX_GUARDS:]
        if not self.specs_objs:
            raise ValueError("unconditional edge (no deciding "
                             "branches) — host engine handles it")
        self.scan_iters = max(int(scan_iters), 1)
        self.i2s = bool(i2s)
        K = len(self.specs_objs)
        max_len = max(int(max_len), max(len(s) for s in seeds))
        self.L = max(8, ((max_len + 7) // 8) * 8)
        lay = _layout(max(int(lanes), N_ELITE + I2S_PER_GUARD * K
                          + 48), K)
        self.lanes = lay["total"]

        # per-guard dynamic byte deps (Angora's taint, read off one
        # concrete slice): the i2s write positions + probe priority
        self._mask = [p for p in (mask or []) if 0 <= p < self.L]
        deps_by_guard: List[List[int]] = []
        for obj in self.specs_objs:
            d: List[int] = []
            for s in seeds[:8]:
                sl = trace_slice(program, s, obj)
                if sl.reached:
                    d = slice_operand_deps(program, sl, obj)
                    break
            deps_by_guard.append([p for p in d if 0 <= p < self.L])
        self._set_focus(deps_by_guard)

        try:
            from ..analysis.dataflow import extract_dictionary
            toks = [bytes(t) for t in extract_dictionary(program) if t]
        except Exception:
            toks = []
        toks = [t for t in toks if len(t) <= 6][:12]
        self.n_tokens = len(toks)
        tl = max((len(t) for t in toks), default=1)
        tok_bufs = np.zeros((max(self.n_tokens, 1), tl), np.uint8)
        tok_lens = np.zeros((max(self.n_tokens, 1),), np.int32)
        for i, t in enumerate(toks):
            tok_bufs[i, :len(t)] = np.frombuffer(t, np.uint8)
            tok_lens[i] = len(t)
        self.tok_bufs, self.tok_lens = tok_bufs, tok_lens

        # zero-extended seed variants ride along (length guards need
        # longer inputs than any corpus entry — same move as the host
        # engine's population init)
        pool: List[bytes] = []
        for s in seeds:
            pool.append(s[:self.L])
            for ext in (4, 8, 16):
                if len(s) + ext <= self.L:
                    pool.append(s + b"\x00" * ext)
        e_bufs, e_lens = _pack(pool, N_ELITE, self.L)
        self.carry = (
            jnp.asarray(e_bufs), jnp.asarray(e_lens),
            jnp.full((N_ELITE,), K, jnp.int32),
            jnp.full((N_ELITE,), DIST_UNREACHED, jnp.float32),
            jnp.zeros((5,), jnp.int32),
            jnp.zeros((K,), jnp.int32), jnp.zeros((K,), jnp.int32),
            jnp.zeros((K,), jnp.int32),
            jnp.zeros((WITNESS_RING, self.L), jnp.uint8),
            jnp.zeros((WITNESS_RING,), jnp.int32),
            jnp.zeros((WITNESS_RING,), jnp.int32),
            jnp.zeros((WITNESS_RING,), jnp.int32),
            jnp.int32(0),
            jnp.float32(DIST_UNREACHED))
        self.it = 0
        self.salt = jnp.uint32(((self.edge[0] & 0xFFFF) << 16)
                               ^ (self.edge[1] & 0xFFFF) ^ 0x6465)
        self._wit_seen = 0
        self.last_worked = 0

    def _set_focus(self, deps_by_guard: List[List[int]]) -> None:
        K = len(self.specs_objs)
        dep_pos = np.full((K, I2S_DEPS), -1, dtype=np.int32)
        n_dep = np.zeros((K,), dtype=np.int32)
        for k, d in enumerate(deps_by_guard):
            d = d[:I2S_DEPS]
            dep_pos[k, :len(d)] = d
            n_dep[k] = len(d)
        prio: List[int] = []
        for d in deps_by_guard:
            for p in d:
                if p not in prio:
                    prio.append(p)
        for p in self._mask:
            if p not in prio:
                prio.append(p)
        rest = [p for p in range(self.L) if p not in prio]
        self.pos_order = np.asarray(prio + rest, dtype=np.int32)
        self.dep_pos, self.n_dep = dep_pos, n_dep
        self._deps_by_guard = deps_by_guard

    def refresh_focus(self) -> None:
        """Between dispatches: re-derive every guard's dynamic byte
        deps from the CURRENT best elite's concrete slice (the host
        engine recomputes its probe focus per iteration; here the
        cadence is per dispatch — R iterations).  Guards the new best
        does not reach keep their previous deps, so curriculum
        progress only ever ADDS focus.  The parity pin drives
        ``dispatch()`` directly without refreshing — focus arrays are
        part of the matched schedule."""
        bufs, lens, _stage, _dist = self.elite_front()
        best = bufs[0, :int(lens[0])].tobytes()
        deps: List[List[int]] = []
        for k, obj in enumerate(self.specs_objs):
            sl = trace_slice(self.program, best, obj)
            d = slice_operand_deps(self.program, sl, obj) \
                if sl.reached else []
            d = [p for p in d if 0 <= p < self.L]
            deps.append(d or self._deps_by_guard[k])
        self._set_focus(deps)

    @property
    def specs(self) -> tuple:
        return tuple(o.spec() for o in self.specs_objs)

    def inject_candidates(self, rows: Sequence[bytes]) -> int:
        """Host-proposed candidates (the soft-KBVM ``jax.grad``
        steps, chained witnesses, ...) overwrite the tail of the
        elite front between dispatches: they ride in the next batch's
        elite lanes and are scored/ranked on device like any other
        lane — proposals only, never witnesses (the honesty contract
        is enforced at the ring drain)."""
        rows = [bytes(r)[:self.L] for r in rows if r][:N_ELITE // 4]
        if not rows:
            return 0
        bufs, lens, stage, dist = \
            (np.asarray(a).copy() for a in self.carry[:4])
        K = len(self.specs_objs)
        for i, r in enumerate(rows):
            slot = N_ELITE - 1 - i
            row = np.zeros((self.L,), np.uint8)
            row[:len(r)] = np.frombuffer(r, np.uint8)
            bufs[slot] = row
            lens[slot] = len(r)
            stage[slot] = K
            dist[slot] = np.float32(DIST_UNREACHED)
        self.carry = (jnp.asarray(bufs), jnp.asarray(lens),
                      jnp.asarray(stage), jnp.asarray(dist)) \
            + self.carry[4:]
        return len(rows)

    def soft_propose(self) -> int:
        """Host-side soft-KBVM refinement at per-dispatch cadence:
        when the current best elite's path slice to its frontier
        guard is arithmetic-only, one ``jax.grad`` of the relaxed
        distance proposes multi-byte steps that are injected into the
        elite tail (the host engine runs the same tier every 4th
        iteration; here it rides the dispatch boundary the engine
        already returns to the host on)."""
        bufs, lens, stage, _dist = self.elite_front()
        best = bufs[0, :int(lens[0])].tobytes()
        K = len(self.specs_objs)
        k_idx = min(max(K - 1 - int(stage[0]), 0), K - 1)
        obj = self.specs_objs[k_idx]
        sl = trace_slice(self.program, best, obj)
        if not sl.eligible:
            return 0
        return self.inject_candidates(
            soft_refine(self.program, best, obj, slice_=sl))

    def dispatch(self, iters: Optional[int] = None
                 ) -> List[Tuple[bytes, int, int]]:
        """Run ``iters`` (default ``scan_iters``) descent iterations
        on device; returns the NEW witness ring rows as ``(buf,
        family, iteration)`` tuples in (iteration, lane) order
        (already deduped against rows seen in earlier dispatches of
        this descent).  ``iters`` exists for the TAIL dispatch of a
        budget that ``scan_iters`` does not divide — the engine never
        runs more iterations than asked (the equal-effort contract of
        every host-vs-device comparison); a non-default value
        compiles its own scan length once.  ``last_worked`` holds how
        many of the dispatch's iterations actually searched (the
        early-stop idles the rest once a witness lands)."""
        prog = self.program
        si = int(iters) if iters else self.scan_iters
        out = _descent_scan(
            jnp.asarray(prog.instrs), jnp.asarray(prog.edge_table),
            jnp.asarray(self.pos_order), jnp.asarray(self.dep_pos),
            jnp.asarray(self.n_dep), jnp.asarray(self.tok_bufs),
            jnp.asarray(self.tok_lens),
            *self.carry,
            jnp.int32(self.it), self.salt,
            mem_size=prog.mem_size, max_steps=prog.max_steps,
            n_edges=prog.n_edges, specs=self.specs, e_idx=self.e_idx,
            lanes=self.lanes, scan_iters=si,
            n_tokens=self.n_tokens, i2s=self.i2s)
        self.carry = out[:14]
        self.it += si
        self.last_worked = int(np.sum(np.asarray(out[14]) >= 0))
        wit_bufs = np.asarray(out[8])
        wit_lens = np.asarray(out[9])
        wit_src = np.asarray(out[10])
        wit_iter = np.asarray(out[11])
        ptr = int(np.asarray(out[12]))
        rows = []
        for r in range(self._wit_seen, min(ptr, WITNESS_RING)):
            rows.append((wit_bufs[r, :int(wit_lens[r])].tobytes(),
                         int(wit_src[r]), int(wit_iter[r])))
        self._wit_seen = min(ptr, WITNESS_RING)
        return rows

    def reset_witnesses(self) -> None:
        """Clear the witness ring and its host cursor.  The driver
        calls this when EVERY drained row failed reference
        verification (a device/reference divergence the honesty
        contract exists to catch): a nonzero ring pointer would
        otherwise idle every remaining iteration via the early-stop,
        silently burning the budget with zero search."""
        c = list(self.carry)
        for i in (8, 9, 10, 11):
            c[i] = jnp.zeros_like(c[i])
        c[12] = jnp.int32(0)
        self.carry = tuple(c)
        self._wit_seen = 0

    # -- inspection (parity pin / reports) ---------------------------

    def elite_front(self):
        """(bufs, lens, stage, dist) as numpy — the ranked order the
        parity pin compares between stepped and in-scan schedules."""
        return tuple(np.asarray(a) for a in self.carry[:4])

    @property
    def best_primary(self) -> float:
        return float(np.asarray(self.carry[13]))

    @property
    def witnesses_total(self) -> int:
        """Total edge-traversing lanes observed (overflow included)."""
        return int(np.asarray(self.carry[12]))


def descend_edge_device(program, edge: Tuple[int, int],
                        seeds: Sequence[bytes], *,
                        mask: Optional[Sequence[int]] = None,
                        lanes: int = 256,
                        budget: int = 48,
                        scan_iters: int = DEFAULT_SCAN_ITERS,
                        max_len: int = 64,
                        i2s: bool = True,
                        trace=None,
                        trace_cache: Optional[Dict] = None,
                        registry=None) -> DescentResult:
    """Device-resident twin of ``descent.descend_edge``: descend
    ``edge``'s branch-distance curriculum with R iterations fused per
    dispatch until a verified witness traverses it or ``budget``
    ITERATIONS are spent (budget is iteration-denominated so host/
    device comparisons run at equal search effort; dispatches =
    ceil(budget / scan_iters)).  Stands down to the host engine on
    unconditional edges.  Every witness is re-verified by the
    reference interpreter on the host before it is reported —
    identical honesty contract."""
    f_idx, t_idx = int(edge[0]), int(edge[1])
    try:
        eng = DeviceDescent(program, edge, seeds, mask=mask,
                            lanes=lanes, scan_iters=scan_iters,
                            max_len=max_len, i2s=i2s,
                            trace_cache=trace_cache)
    except ValueError as e:
        DEBUG_MSG("device descent stand-down on %d:%d (%s) — host "
                  "engine takes it", f_idx, t_idx, e)
        res = descend_edge(program, edge, seeds, mask=mask,
                           lanes=lanes, budget=budget,
                           max_len=max_len, trace=trace,
                           trace_cache=trace_cache)
        res.engine = "host"
        res.iterations = res.steps
        res.dispatches = res.steps
        return res
    if registry is not None:
        registry.gauge("descent_iterations_per_dispatch",
                       eng.scan_iters)
    dispatches = 0
    evals = 0
    soft_used = False
    remaining = max(int(budget), 1)
    first = True
    while remaining > 0:
        if not first:
            eng.refresh_focus()
            soft_used = bool(eng.soft_propose()) or soft_used
        first = False
        si = min(eng.scan_iters, remaining)
        span = (trace.span("descend_scan", lane="descent",
                           args={"edge": f"{f_idx}:{t_idx}",
                                 "iter0": eng.it,
                                 "scan_iters": si,
                                 "lanes": eng.lanes,
                                 "guards": len(eng.specs_objs)})
                if trace is not None else contextlib.nullcontext())
        with span:
            rows = eng.dispatch(si)
        dispatches += 1
        remaining -= si
        evals += eng.last_worked * eng.lanes
        for buf, fam_tag, it in rows:
            # honesty contract: the reference interpreter must agree
            # before the witness is reported
            if (f_idx, t_idx) in _concrete_trace(program, buf,
                                                 trace_cache).edges:
                if registry is not None and fam_tag == FAM_I2S:
                    registry.count("search_i2s_matches")
                return DescentResult(
                    edge=(f_idx, t_idx), status="descended",
                    input=buf, steps=it + 1, evals=evals,
                    best_dist=0.0,
                    objective=eng.specs_objs[-1].desc,
                    soft_used=soft_used,
                    engine="device", dispatches=dispatches,
                    iterations=it + 1, i2s=(fam_tag == FAM_I2S))
        if rows:
            # every drained row failed verification: clear the ring
            # or the early-stop idles the rest of the budget
            DEBUG_MSG("descend %d:%d: %d witness rows failed "
                      "reference verification — ring reset",
                      f_idx, t_idx, len(rows))
            eng.reset_witnesses()
    return DescentResult(
        edge=(f_idx, t_idx), status="exhausted",
        steps=eng.it, evals=evals, best_dist=eng.best_primary,
        objective=eng.specs_objs[-1].desc, soft_used=soft_used,
        reason=f"iteration budget exhausted ({eng.it} iterations / "
               f"{dispatches} dispatches)",
        engine="device", dispatches=dispatches, iterations=eng.it)
