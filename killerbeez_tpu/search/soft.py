"""Soft-KBVM: true ``jax.grad`` through a float32-relaxed path slice.

The descent engine's default moves are black-box (finite-difference
probes + evolution strategies).  When the concrete path from entry to
the objective branch is ARITHMETIC-ONLY — every executed op is one of
BLOCK / LDB / LDI / ADDI / LEN / JMP / BR or an ALU add/sub/mul, with
no memory traffic and no bit-twiddling — that path slice has an exact
float32 relaxation: freeze the control flow and the byte-load indices
recorded from one concrete execution, replay the slice as a float
computation over the input-byte vector, and differentiate the branch
distance with ``jax.grad``.  The gradient proposes whole multi-byte
steps a coordinate prober would need many dispatches to find.

Honesty contract: the relaxation only PROPOSES candidates.  Every
proposal re-enters the concrete engine (and, before emission, the
reference interpreter) exactly like an ES mutant — a wrong gradient
costs a wasted lane, never a wrong witness.  Eligibility is decided
from the executed trace itself (the executed ops ARE the path, so the
check is a proof for that path); ``analysis/dataflow.py`` branch
facts additionally narrow the differentiated dimensions to the bytes
the comparison can actually read.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..analysis.dataflow import _alu_const, _fold_cmp
from ..models.vm import (
    ALU_ADD, ALU_MUL, ALU_SUB, CMP_EQ, CMP_GE, CMP_LT, CMP_NE, N_REGS,
    OP_ADDI, OP_ALU, OP_BLOCK, OP_BR, OP_CRASH, OP_HALT, OP_JMP,
    OP_LDB, OP_LDI, OP_LDM, OP_LEN, OP_STM,
)
from .objective import BranchObjective

#: gradient step sizes tried per refinement, in byte units
_STEP_SCALES = (1.0, 4.0, 16.0, 64.0)


def _i32(v: int) -> int:
    v &= 0xFFFFFFFF
    return v - (1 << 32) if v & 0x80000000 else v


def _r(field: int) -> int:
    return min(max(field, 0), N_REGS - 1)


@dataclass
class SoftSlice:
    """One concrete execution's path slice up to the objective branch:
    the executed (pc, concrete LDB index) records plus eligibility.
    ``ldb_index`` is -1 for non-LDB steps; the final state's branch
    operands are recomputed by the float replay, not stored.
    ``reached`` distinguishes "stopped at the branch" (deps and
    relaxation are meaningful) from "path ended/crashed first"."""
    steps: List[Tuple[int, int]]
    eligible: bool
    reason: str = ""
    reached: bool = False


def trace_slice(program, data: bytes, obj: BranchObjective) -> SoftSlice:
    """Replay ``data`` concretely (lockstep with ``vm._step``
    semantics) up to the first execution of the objective branch with
    the edge's source block as last block; record the executed pcs and
    every LDB's concrete index, and judge arithmetic-only
    eligibility.  Ineligible slices keep exact integer semantics all
    the way, so the verdict is truthful even past the first
    disqualifying op."""
    instrs = np.asarray(program.instrs)
    ni = instrs.shape[0]
    rows = [tuple(int(x) for x in instrs[pc]) for pc in range(ni)]
    mem = [0] * int(program.mem_size)
    regs = [0] * N_REGS
    L = len(data)
    pc, last_block, steps = 0, -1, 0
    rec: List[Tuple[int, int]] = []
    eligible = True
    reason = ""
    while steps < int(program.max_steps):
        steps += 1
        if not (0 <= pc < ni):
            return SoftSlice(rec, False, "path crashes before branch")
        if pc == obj.branch_pc and last_block == obj.edge[0]:
            return SoftSlice(rec, eligible, reason, reached=True)
        cur = pc
        op, a, b, c = rows[pc]
        idx = -1
        if op == OP_BLOCK:
            last_block = b
            pc += 1
        elif op == OP_LDB:
            idx = regs[_r(b)]
            regs[_r(a)] = data[idx] if 0 <= idx < L else 0
            pc += 1
        elif op == OP_LDI:
            regs[_r(a)] = _i32(b)
            pc += 1
        elif op == OP_ALU:
            sel = c & 7
            x, y = regs[_r(b)], regs[(c >> 3) & (N_REGS - 1)]
            regs[_r(a)] = _alu_const(sel, x, y)
            if sel not in (ALU_ADD, ALU_SUB, ALU_MUL) and eligible:
                eligible, reason = False, \
                    f"non-arithmetic ALU op at pc {pc}"
            pc += 1
        elif op == OP_ADDI:
            regs[_r(a)] = _i32(regs[_r(b)] + c)
            pc += 1
        elif op == OP_LEN:
            regs[_r(a)] = L
            pc += 1
        elif op == OP_JMP:
            pc = a
        elif op == OP_BR:
            x = regs[_r(a)]
            y = regs[(b >> 2) & (N_REGS - 1)]
            pc = c if _fold_cmp(b & 3, x, y) else pc + 1
        elif op in (OP_LDM, OP_STM):
            if eligible:
                eligible, reason = False, f"memory op at pc {pc}"
            i = regs[_r(b if op == OP_LDM else a)]
            if not (0 <= i < program.mem_size):
                return SoftSlice(rec, False,
                                 "path crashes before branch")
            if op == OP_LDM:
                regs[_r(a)] = mem[i]
            else:
                mem[i] = regs[_r(b)]
            pc += 1
        elif op in (OP_HALT, OP_CRASH):
            return SoftSlice(rec, False, "path ends before branch")
        else:
            pc += 1
        rec.append((cur, idx))
    return SoftSlice(rec, False, "step budget before branch")


def slice_operand_deps(program, sl: SoftSlice,
                       obj: BranchObjective) -> List[int]:
    """Input-byte positions the objective branch's operands depend on
    along the traced path — Angora's dynamic byte-level taint, read
    off the recorded slice instead of a shadow runtime.  Memory is a
    single summary set (over-approximate), which is fine for its one
    consumer: probe prioritization, never correctness."""
    if not sl.reached:
        return []
    instrs = np.asarray(program.instrs)
    rows = [tuple(int(x) for x in instrs[pc])
            for pc in range(instrs.shape[0])]
    deps = [set() for _ in range(N_REGS)]
    mem_deps: set = set()
    for pc, idx in sl.steps:
        op, a, b, c = rows[pc]
        if op == OP_LDB:
            deps[_r(a)] = {idx} if idx >= 0 else set()
        elif op in (OP_LDI, OP_LEN):
            deps[_r(a)] = set()
        elif op == OP_ALU:
            deps[_r(a)] = deps[_r(b)] | deps[(c >> 3) & (N_REGS - 1)]
        elif op == OP_ADDI:
            deps[_r(a)] = set(deps[_r(b)])
        elif op == OP_LDM:
            deps[_r(a)] = set(mem_deps)
        elif op == OP_STM:
            mem_deps |= deps[_r(b)]
    return sorted(deps[obj.x_idx] | deps[obj.y_idx])


def _soft_distance(program, sl: SoftSlice, obj: BranchObjective,
                   length: int):
    """Build the differentiable ``float32[L] -> distance`` replay of
    an eligible slice.  Control flow and load indices are FROZEN from
    the recorded trace; register values are float32 closures over the
    input vector.  The distance relaxes the exact table smoothly:
    eq -> (x-y)^2, ne -> 1/(1+(x-y)^2), lt/ge -> softplus-free
    hinges (relu keeps the descent direction exact where it counts).
    """
    import jax.numpy as jnp

    instrs = np.asarray(program.instrs)
    rows = [tuple(int(x) for x in instrs[pc])
            for pc in range(instrs.shape[0])]

    def dist(x):
        regs = [jnp.float32(0.0)] * N_REGS
        for pc, idx in sl.steps:
            op, a, b, c = rows[pc]
            if op == OP_LDB:
                regs[_r(a)] = (x[idx] if 0 <= idx < length
                               else jnp.float32(0.0))
            elif op == OP_LDI:
                regs[_r(a)] = jnp.float32(_i32(b))
            elif op == OP_ALU:
                sel = c & 7
                u, v = regs[_r(b)], regs[(c >> 3) & (N_REGS - 1)]
                regs[_r(a)] = (u + v if sel == ALU_ADD else
                               u - v if sel == ALU_SUB else u * v)
            elif op == OP_ADDI:
                regs[_r(a)] = regs[_r(b)] + jnp.float32(c)
            elif op == OP_LEN:
                regs[_r(a)] = jnp.float32(length)
            # BLOCK / JMP / BR: control flow frozen by the trace
        # the loop above leaves regs as of branch entry
        u, v = regs[obj.x_idx], regs[obj.y_idx]
        d = u - v
        if obj.sel == CMP_EQ:
            return d * d
        if obj.sel == CMP_NE:
            return 1.0 / (1.0 + d * d)
        if obj.sel == CMP_LT:
            return jnp.maximum(d + 1.0, 0.0)
        return jnp.maximum(-d, 0.0)     # CMP_GE

    return dist


def soft_refine(program, data: bytes, obj: BranchObjective,
                positions: Optional[Sequence[int]] = None,
                slice_: Optional[SoftSlice] = None) -> List[bytes]:
    """Gradient-refinement proposals for ``data`` against the
    objective: trace the path slice, bail (empty list) unless it is
    arithmetic-only, then take one ``jax.grad`` of the relaxed
    distance and emit rounded byte candidates at several step scales,
    moved only along ``positions`` (default: every byte the trace
    actually loaded).  Proposals are CANDIDATES for the concrete
    engine, never emitted as witnesses."""
    import jax
    import jax.numpy as jnp

    sl = slice_ if slice_ is not None else trace_slice(program, data,
                                                       obj)
    if not sl.eligible:
        return []
    L = len(data)
    if positions is None:
        positions = sorted({i for _pc, i in sl.steps
                            if 0 <= i < L})
    positions = [p for p in positions if 0 <= p < L]
    if not positions:
        return []
    dist = _soft_distance(program, sl, obj, L)
    x0 = jnp.asarray(np.frombuffer(data, dtype=np.uint8)
                     .astype(np.float32))
    g = np.asarray(jax.grad(dist)(x0))
    if not np.isfinite(g).any() or not np.abs(g[positions]).max():
        return []
    mask = np.zeros(L, dtype=np.float32)
    mask[positions] = 1.0
    g = g * mask
    gmax = np.abs(g).max()
    out: List[bytes] = []
    base = np.frombuffer(data, dtype=np.uint8).astype(np.float32)
    for scale in _STEP_SCALES:
        step = np.clip(np.round(base - g * (scale / gmax)), 0, 255)
        cand = step.astype(np.uint8).tobytes()
        if cand != data:
            out.append(cand)
    return out
