"""Branch-distance objectives for the gradient-guided search tier.

Given a frontier edge ``(from_block, to_block)`` of the static
universe, find the branches that DECIDE it: the OP_BRs inside the
source block's body (the instruction region between block f's head
and the next BLOCK instructions) from which the target block's head
is reachable through exactly one successor.  Each deciding branch
yields a :class:`BranchObjective` — the static arguments of the
distance-returning execute variant (``vm.run_batch_distance``), with
the comparison canonicalized so distance 0 always means "the branch
goes the way the edge needs" (want the fall-through -> negate the
compare: eq<->ne, lt<->ge, Angora's distance table direction).

Objectives are ordered shallowest-first (BFS depth from the block
head): on a multi-guard path every deciding branch must go the right
way, and a deeper guard's distance is only observable once the lanes
pass the shallower ones — the descent engine satisfies them in
program order, carrying its population from guard to guard.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Set, Tuple

import numpy as np

from ..models.vm import (
    CMP_EQ, CMP_GE, CMP_LT, CMP_NE, N_REGS,
    OP_BLOCK, OP_BR, OP_CRASH, OP_HALT, OP_JMP,
)
from ..analysis.dataflow import CMP_NAMES

#: compare negation: "branch NOT taken on <sel>" == "taken on NEG[sel]"
NEG_CMP = {CMP_EQ: CMP_NE, CMP_NE: CMP_EQ, CMP_LT: CMP_GE,
           CMP_GE: CMP_LT}


@dataclass(frozen=True)
class BranchObjective:
    """Static arguments of one branch-distance objective."""
    edge: Tuple[int, int]
    branch_pc: int
    #: canonical compare: distance 0 <=> the edge's successor runs
    sel: int
    x_idx: int              # register index of the left operand
    y_idx: int              # register index of the right operand
    want_taken: bool        # which successor the edge needs
    desc: str

    def dist_kwargs(self) -> Dict[str, int]:
        """Keyword arguments for ``vm.run_batch_distance``."""
        return {"branch_pc": self.branch_pc, "from_idx": self.edge[0],
                "sel": self.sel, "x_idx": self.x_idx,
                "y_idx": self.y_idx}

    def spec(self) -> Tuple[int, int, int, int, int]:
        """The static spec row for ``vm.run_batch_distances``."""
        return (self.branch_pc, self.edge[0], self.sel, self.x_idx,
                self.y_idx)


def _succs(rows, pc: int) -> List[int]:
    op, a, b, c = rows[pc]
    if op in (OP_HALT, OP_CRASH):
        return []
    if op == OP_JMP:
        return [a]
    if op == OP_BR:
        return [c, pc + 1]
    return [pc + 1]


def _block_region(rows, ni: int, start: int
                  ) -> Tuple[Set[int], Dict[int, int]]:
    """(pcs reachable from ``start`` without executing another BLOCK,
    BFS depth of each) — the same stop-at-BLOCK walk that defines the
    static edge universe (``vm.compute_edges``)."""
    depth = {}
    frontier = [start]
    d = 0
    while frontier:
        nxt = []
        for pc in frontier:
            if pc in depth or not (0 <= pc < ni):
                continue
            depth[pc] = d
            if rows[pc][0] == OP_BLOCK:
                continue            # region boundary: don't expand
            nxt.extend(_succs(rows, pc))
        frontier = nxt
        d += 1
    return set(depth), depth


def _reaches_head(rows, ni: int, start: int, t_head: int) -> bool:
    """True when the stop-at-BLOCK walk from ``start`` can execute
    ``t_head`` as its first BLOCK instruction — i.e. entering the
    region at ``start`` can still traverse the (f, t) edge."""
    seen: Set[int] = set()
    stack = [start]
    while stack:
        pc = stack.pop()
        if pc in seen or not (0 <= pc < ni):
            continue
        seen.add(pc)
        if rows[pc][0] == OP_BLOCK:
            if pc == t_head:
                return True
            continue
        stack.extend(_succs(rows, pc))
    return False


def edge_objectives(program, edge: Tuple[int, int]
                    ) -> List[BranchObjective]:
    """Deciding-branch objectives for ``edge``, program order
    (shallowest-first).  Empty
    when the edge is outside the static universe or its source block
    reaches the target unconditionally (covering the source block
    then covers the edge for free — nothing to descend on)."""
    f_idx, t_idx = int(edge[0]), int(edge[1])
    instrs = np.asarray(program.instrs)
    ni = instrs.shape[0]
    rows = [tuple(int(x) for x in instrs[pc]) for pc in range(ni)]
    block_pcs = [pc for pc in range(ni) if rows[pc][0] == OP_BLOCK]
    if not (0 <= t_idx < len(block_pcs)) or \
            not (-1 <= f_idx < len(block_pcs)):
        return []
    t_head = block_pcs[t_idx]
    start = 0 if f_idx < 0 else block_pcs[f_idx] + 1
    region, depth = _block_region(rows, ni, start)

    out: List[BranchObjective] = []
    for pc in region:
        if rows[pc][0] != OP_BR:
            continue
        _op, a, b, c = rows[pc]
        taken_ok = _reaches_head(rows, ni, c, t_head)
        fall_ok = _reaches_head(rows, ni, pc + 1, t_head)
        if taken_ok == fall_ok:
            continue                # off-path, or both sides survive
        sel = b & 3
        canon = sel if taken_ok else NEG_CMP[sel]
        x_idx = min(max(a, 0), N_REGS - 1)
        y_idx = (b >> 2) & (N_REGS - 1)
        out.append(BranchObjective(
            edge=(f_idx, t_idx), branch_pc=pc, sel=canon,
            x_idx=x_idx, y_idx=y_idx, want_taken=taken_ok,
            desc=(f"pc {pc}: r{x_idx} {CMP_NAMES[canon]} r{y_idx} "
                  f"({'taken' if taken_ok else 'fall-through'} -> "
                  f"block {t_idx})")))
    out.sort(key=lambda o: depth.get(o.branch_pc, 0))
    return out
