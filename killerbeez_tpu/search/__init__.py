"""Gradient-guided search over KBVM branch distances.

The third coverage tier, picking up where the exact layers stop:

  static analysis (PR 3)  — describes every branch;
  exact solver (PR 4)     — solves the described conditions, honest
                            ``unknown`` on checksum-style loops;
  search (this package)   — descends the unknowns: Angora-style
                            branch-distance minimization with the
                            objective evaluated for thousands of
                            candidates per device dispatch.

  objective.py  deciding-branch extraction: which OP_BR (and which
                direction) a frontier edge needs, as the static args
                of ``vm.run_batch_distance``
  descent.py    the batched descent engine: elite front, coordinate
                probes, ES mutants, recombination, restarts — and
                the verified-witness honesty contract
  soft.py       float32-relaxed soft-KBVM: true ``jax.grad`` through
                arithmetic-only path slices, proposals only
  device_descent.py  the in-scan engine: R rank -> probe -> mutate ->
                re-score iterations fused into one device dispatch
                with donated carry state, plus Redqueen-style
                input-to-state operand matching off the captured
                compare operands

Consumers: the crack stage's escalation path (``fuzzer/crack.py``,
``--descend``), the ``kb-descend`` tool, and ``bench.py --descend``.
"""

from .descent import (
    DEFAULT_DESCENT_BUDGET, DEFAULT_LANES, DescentResult, descend_edge,
    seeds_reaching_block,
)
from .device_descent import (
    DEFAULT_SCAN_ITERS, DeviceDescent, descend_edge_device,
)
from .objective import BranchObjective, edge_objectives
from .soft import SoftSlice, soft_refine, trace_slice

__all__ = [
    "DEFAULT_DESCENT_BUDGET", "DEFAULT_LANES", "DescentResult",
    "descend_edge", "seeds_reaching_block",
    "DEFAULT_SCAN_ITERS", "DeviceDescent", "descend_edge_device",
    "BranchObjective", "edge_objectives",
    "SoftSlice", "soft_refine", "trace_slice",
]
