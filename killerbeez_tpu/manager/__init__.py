"""Distributed manager tier (reference SURVEY §2.8).

The reference pairs a Flask+SQLAlchemy REST manager with BOINC work
distribution. Here the same REST surface (Job / Results / Target /
Config / File / Minimize) runs on the standard library
(ThreadingHTTPServer + sqlite3), and BOINC is replaced by a pull
work-queue over DCN: workers claim workunits (`POST /api/work/claim`),
run the fuzzer CLI locally, and the assimilator posts findings back —
the same lifecycle as manager `create_work` -> BOINC wrapper ->
assimilator POST (python/manager/lib/boinc.py:63-91,
server/killerbeez_assimilator.py).

    python -m killerbeez_tpu.manager --port 8650          # serve
    python -m killerbeez_tpu.manager --seed               # demo rows
"""

from .db import ManagerDB
from .fuzzer_cmd import format_cmdline
from .api import ManagerServer

__all__ = ["ManagerDB", "format_cmdline", "ManagerServer"]
