"""Manager database — sqlite3 schema mirroring the reference's models
(python/manager/model/: FuzzingJob, FuzzingResults, FuzzingTarget,
Config, job_inputs, instrumentation_state, tracer_info — SURVEY §2.8).

sqlite stands in for MySQL/Postgres exactly as in the reference's test
config (python/manager/app/config.py:2-3). The connection is
per-thread (the REST tier serves from a thread pool).

Durability posture (the degraded-mode manager): file-backed
connections run in WAL mode with a busy timeout, every write retries
``database is locked`` with bounded backoff (a concurrent heartbeat
burst must not 500 a corpus POST — the worker would drop that entry
from the round forever under PR 2's reject rule), and a write that
STILL fails (ENOSPC, lock convoy beyond the budget) raises a typed
:class:`ManagerWriteError` and latches ``self.degraded`` — the REST
tier then keeps serving cursor GETs read-only instead of 500ing the
fleet, with the admission journal (``journal.py``) holding the ACKed
rows until writes recover.  The first successful write clears the
latch.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

from ..resilience.chaos import chaos_point
from ..telemetry.events import SCHEMA_VERSION
from ..utils.logging import WARNING_MSG


class ManagerWriteError(Exception):
    """A DB mutation failed after the retry budget — the manager is
    write-degraded (reads keep serving; the API tier decides whether
    the journal can still honor the POST)."""

_SCHEMA = """
CREATE TABLE IF NOT EXISTS targets (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT UNIQUE NOT NULL,
    platform TEXT NOT NULL DEFAULT 'linux_x86_64',
    path TEXT NOT NULL DEFAULT '',
    created REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS configs (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT NOT NULL,           -- e.g. driver_opts_file
    target_id INTEGER,            -- NULL = global default
    value TEXT NOT NULL,
    UNIQUE(name, target_id)
);
CREATE TABLE IF NOT EXISTS jobs (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    target_id INTEGER NOT NULL,
    status TEXT NOT NULL DEFAULT 'pending',
        -- pending -> claimed -> done | failed
    driver TEXT NOT NULL,
    instrumentation TEXT NOT NULL,
    mutator TEXT NOT NULL,
    iterations INTEGER NOT NULL DEFAULT 1000,
    seed_file TEXT NOT NULL DEFAULT '',
    driver_opts TEXT, instrumentation_opts TEXT, mutator_opts TEXT,
    mutator_state TEXT,           -- resumption (model/FuzzingJob.py:14)
    instrumentation_state_id INTEGER,
    assigned_to TEXT, claimed REAL, finished REAL,
    created REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS results (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    job_id INTEGER NOT NULL,
    result_type TEXT NOT NULL,    -- crash | hang | new_path
    repro_file TEXT NOT NULL,
    crash_info TEXT,              -- worker verification JSON (crashes)
    created REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS job_inputs (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    job_id INTEGER NOT NULL,
    file_id INTEGER NOT NULL
);
CREATE TABLE IF NOT EXISTS files (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT NOT NULL,
    content BLOB NOT NULL,
    created REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS instrumentation_state (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    target_id INTEGER NOT NULL,
    state TEXT NOT NULL,
    created REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS tracer_info (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    target_id INTEGER NOT NULL,
    input_file TEXT NOT NULL,
    edges TEXT NOT NULL,          -- JSON list of edge ids
    UNIQUE(target_id, input_file)
);
CREATE TABLE IF NOT EXISTS campaign_stats (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    campaign TEXT NOT NULL,       -- campaign key (job id by default)
    worker TEXT NOT NULL,
    snapshot TEXT NOT NULL,       -- telemetry registry snapshot JSON
    updated REAL NOT NULL,
    UNIQUE(campaign, worker)      -- latest heartbeat per worker
);
CREATE TABLE IF NOT EXISTS campaign_events (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    campaign TEXT NOT NULL,
    worker TEXT NOT NULL,
    seq INTEGER NOT NULL,         -- the worker's events.jsonl seq
    t REAL NOT NULL,              -- event wall time (worker clock)
    type TEXT NOT NULL,           -- crash | hang | plateau | ...
    payload TEXT NOT NULL,        -- full event record JSON
    created REAL NOT NULL,
    -- re-forwarded heartbeat windows dedup (identical record, same
    -- t); t is IN the key because seq is only monotone per log
    -- lifetime — a same-named worker restarting with a fresh output
    -- dir restarts seq at 0, and its events must still store
    UNIQUE(campaign, worker, seq, t)
);
CREATE TABLE IF NOT EXISTS fleet_workers (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    campaign TEXT NOT NULL,
    worker TEXT NOT NULL,
    first_seen REAL NOT NULL,     -- first heartbeat (registration)
    last_seen REAL NOT NULL,      -- newest heartbeat
    beats INTEGER NOT NULL DEFAULT 0,
    status TEXT NOT NULL DEFAULT 'healthy',
        -- healthy | stale | dead (the monitor's last classification;
        -- endpoints re-classify live against last_seen)
    meta TEXT,                    -- worker-supplied JSON (pid, host)
    UNIQUE(campaign, worker)
);
CREATE TABLE IF NOT EXISTS fleet_series (
    id INTEGER PRIMARY KEY AUTOINCREMENT,  -- the GET cursor
    campaign TEXT NOT NULL,
    t REAL NOT NULL,
    sample TEXT NOT NULL          -- fleet snapshot JSON (monitor)
);
CREATE TABLE IF NOT EXISTS corpus_entries (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    campaign TEXT NOT NULL,
    cov_hash TEXT NOT NULL,       -- coverage-signature dedup key
    md5 TEXT NOT NULL,
    worker TEXT NOT NULL,
    content BLOB NOT NULL,
    meta TEXT,                    -- entry sidecar JSON (corpus store)
    created REAL NOT NULL,
    UNIQUE(campaign, cov_hash)    -- one row per coverage frontier
);
"""


class ManagerDB:
    """Thread-safe sqlite wrapper; rows in/out as plain dicts."""

    #: ``database is locked`` retry budget: attempts and base backoff
    #: (exponential: 10ms, 20ms, 40ms, 80ms, 160ms — bounded, so a
    #: true lock convoy still surfaces as ManagerWriteError instead
    #: of wedging the request thread)
    LOCK_RETRIES = 5
    LOCK_BACKOFF_S = 0.01
    #: sqlite busy handler budget (ms) — the first line of defense
    #: against cross-process writers before our retry loop engages
    BUSY_TIMEOUT_MS = 2000

    def __init__(self, path: str = ":memory:"):
        self.path = path
        self._local = threading.local()
        # in-memory DBs are per-connection; share one with a lock
        self._shared: Optional[sqlite3.Connection] = None
        self._lock = threading.Lock()
        #: write-degraded latch: set when a mutation exhausts the
        #: retry budget, cleared by the next successful write; the
        #: API tier serves read-only (plus journal-backed admission
        #: ACKs) while it is up
        self.degraded = False
        self.write_failures = 0
        #: one-shot recovery signal: set when the degraded latch
        #: CLEARS (a write succeeded after a failing window) — the
        #: API tier consumes it to replay the journal backlog
        #: exactly once per recovery, never on the healthy hot path
        self.recovery_pending = False
        if path == ":memory:":
            self._shared = sqlite3.connect(":memory:",
                                           check_same_thread=False)
            self._shared.row_factory = sqlite3.Row
            self._shared.executescript(_SCHEMA)
            self._migrate(self._shared)
        else:
            with self._conn() as c:
                c.executescript(_SCHEMA)
                self._migrate(c)

    @staticmethod
    def _migrate(conn: sqlite3.Connection) -> None:
        """Columns added after a release: CREATE TABLE IF NOT EXISTS
        skips existing tables, so upgrades need explicit ALTERs."""
        cols = {r[1] for r in conn.execute(
            "PRAGMA table_info(results)")}
        if "crash_info" not in cols:
            conn.execute(
                "ALTER TABLE results ADD COLUMN crash_info TEXT")

    def _conn(self) -> sqlite3.Connection:
        if self._shared is not None:
            return self._shared
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.path)
            conn.row_factory = sqlite3.Row
            # durability pragmas (file-backed only): WAL lets cursor
            # GETs read while a write commits, NORMAL sync is safe
            # under WAL (a power cut loses at most the last commit,
            # which the admission journal replays), and the busy
            # handler absorbs cross-process lock contention before
            # our own retry loop has to
            try:
                conn.execute("PRAGMA journal_mode=WAL")
                conn.execute("PRAGMA synchronous=NORMAL")
                conn.execute(
                    f"PRAGMA busy_timeout={self.BUSY_TIMEOUT_MS}")
            except sqlite3.Error as e:
                WARNING_MSG("sqlite pragma setup failed: %s", e)
            self._local.conn = conn
        return conn

    def _write(self, conn: sqlite3.Connection, sql: str,
               params: tuple = ()) -> sqlite3.Cursor:
        """One mutation through the degraded-mode seam: chaos point,
        bounded ``database is locked`` retry, ManagerWriteError +
        degraded latch on exhaustion, latch cleared on success.
        Caller holds ``self._lock`` and commits."""
        last: Optional[Exception] = None
        try:
            # chaos seam: the manager write path — enospc/raise here
            # is how tests drive the manager into (and out of)
            # degraded mode; the url context is the statement head
            # ("INSERT INTO corpus_entries"), so ``match`` can scope
            # a fault to one table's writes
            chaos_point("manager_db_write",
                        url=" ".join(sql.split()[:3]))
        except Exception as e:          # injected fault = failed write
            last = e
        for attempt in range(self.LOCK_RETRIES if last is None else 0):
            try:
                cur = conn.execute(sql, params)
                if self.degraded:
                    self.degraded = False
                    self.recovery_pending = True
                return cur
            except sqlite3.OperationalError as e:
                msg = str(e).lower()
                if "locked" not in msg and "busy" not in msg:
                    last = e
                    break
                last = e
                time.sleep(self.LOCK_BACKOFF_S * (2 ** attempt))
            except (sqlite3.Error, OSError) as e:
                last = e
                break
        self.degraded = True
        self.write_failures += 1
        try:
            conn.rollback()     # never leave an open write txn on a
        except sqlite3.Error:   # shared connection
            pass
        raise ManagerWriteError(str(last))

    def consume_recovery(self) -> bool:
        """One-shot: True exactly once after a degraded->healthy
        transition (the caller then replays the journal backlog)."""
        if self.recovery_pending and not self.degraded:
            self.recovery_pending = False
            return True
        return False

    def _commit(self, conn: sqlite3.Connection) -> None:
        """Commit through the degraded seam: under WAL a disk-full
        or busy failure can surface at COMMIT time (appending the
        -wal file), not at execute — it must latch degraded and
        raise the typed error just like a failed execute, or the
        fleet sees raw 500s instead of the journal-backed 503."""
        try:
            conn.commit()
        except (sqlite3.Error, OSError) as e:
            self.degraded = True
            self.write_failures += 1
            try:
                conn.rollback()
            except sqlite3.Error:
                pass
            raise ManagerWriteError(str(e))

    def _exec(self, sql: str, params: tuple = ()) -> sqlite3.Cursor:
        with self._lock:
            conn = self._conn()
            cur = self._write(conn, sql, params)
            self._commit(conn)
            return cur

    def _rows(self, sql: str, params: tuple = ()) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in
                    self._conn().execute(sql, params).fetchall()]

    # -- targets --------------------------------------------------------

    def create_target(self, name: str, platform: str = "linux_x86_64",
                      path: str = "") -> int:
        cur = self._exec(
            "INSERT INTO targets (name, platform, path, created) "
            "VALUES (?, ?, ?, ?)", (name, platform, path, time.time()))
        return cur.lastrowid

    def get_targets(self) -> List[Dict[str, Any]]:
        return self._rows("SELECT * FROM targets")

    def get_target(self, target_id: int) -> Optional[Dict[str, Any]]:
        rows = self._rows("SELECT * FROM targets WHERE id = ?",
                          (target_id,))
        return rows[0] if rows else None

    # -- configs (reference lookup_config, model/FuzzingJob.py:52-74) ---

    def set_config(self, name: str, value: str,
                   target_id: Optional[int] = None) -> None:
        self._exec(
            "INSERT INTO configs (name, target_id, value) VALUES (?,?,?) "
            "ON CONFLICT(name, target_id) DO UPDATE SET value=excluded.value",
            (name, target_id, value))

    def lookup_config(self, name: str,
                      target_id: Optional[int] = None) -> Optional[str]:
        """Per-target value wins over the global default (reference
        job->target config resolution)."""
        if target_id is not None:
            rows = self._rows(
                "SELECT value FROM configs WHERE name=? AND target_id=?",
                (name, target_id))
            if rows:
                return rows[0]["value"]
        rows = self._rows(
            "SELECT value FROM configs WHERE name=? AND target_id IS NULL",
            (name,))
        return rows[0]["value"] if rows else None

    # -- jobs -----------------------------------------------------------

    def create_job(self, target_id: int, driver: str,
                   instrumentation: str, mutator: str,
                   iterations: int = 1000, seed_file: str = "",
                   **opts) -> int:
        """Option strings not given explicitly resolve through the
        config table as ``{type}_opts_{name}`` rows."""
        resolved = {}
        for kind, name in (("driver", driver),
                           ("instrumentation", instrumentation),
                           ("mutator", mutator)):
            key = f"{kind}_opts"
            resolved[key] = opts.get(key) or self.lookup_config(
                f"{kind}_opts_{name}", target_id)
        cur = self._exec(
            "INSERT INTO jobs (target_id, driver, instrumentation, "
            "mutator, iterations, seed_file, driver_opts, "
            "instrumentation_opts, mutator_opts, mutator_state, created) "
            "VALUES (?,?,?,?,?,?,?,?,?,?,?)",
            (target_id, driver, instrumentation, mutator, iterations,
             seed_file, resolved["driver_opts"],
             resolved["instrumentation_opts"], resolved["mutator_opts"],
             opts.get("mutator_state"), time.time()))
        return cur.lastrowid

    def get_job(self, job_id: int) -> Optional[Dict[str, Any]]:
        rows = self._rows("SELECT * FROM jobs WHERE id = ?", (job_id,))
        return rows[0] if rows else None

    def get_jobs(self, status: Optional[str] = None
                 ) -> List[Dict[str, Any]]:
        if status:
            return self._rows("SELECT * FROM jobs WHERE status = ?",
                              (status,))
        return self._rows("SELECT * FROM jobs")

    def claim_job(self, worker: str) -> Optional[Dict[str, Any]]:
        """Atomically hand the oldest pending job to ``worker`` (the
        BOINC scheduler-request replacement)."""
        with self._lock:
            conn = self._conn()
            row = conn.execute(
                "SELECT id FROM jobs WHERE status='pending' "
                "ORDER BY id LIMIT 1").fetchone()
            if row is None:
                return None
            self._write(
                conn,
                "UPDATE jobs SET status='claimed', assigned_to=?, "
                "claimed=? WHERE id=?",
                (worker, time.time(), row["id"]))
            self._commit(conn)
            job = conn.execute("SELECT * FROM jobs WHERE id=?",
                               (row["id"],)).fetchone()
            return dict(job)

    def finish_job(self, job_id: int, status: str = "done",
                   mutator_state: Optional[str] = None) -> None:
        self._exec(
            "UPDATE jobs SET status=?, finished=?, "
            "mutator_state=COALESCE(?, mutator_state) WHERE id=?",
            (status, time.time(), mutator_state, job_id))

    def requeue_stale_jobs(self, older_than_s: float) -> int:
        """Claimed-but-never-finished jobs go back to pending (BOINC
        workunit retry semantics — fleet-level failure recovery)."""
        cutoff = time.time() - older_than_s
        cur = self._exec(
            "UPDATE jobs SET status='pending', assigned_to=NULL "
            "WHERE status='claimed' AND claimed < ?", (cutoff,))
        return cur.rowcount

    # -- results --------------------------------------------------------

    def add_result(self, job_id: int, result_type: str,
                   repro_file: str,
                   crash_info: Optional[str] = None) -> int:
        if result_type not in ("crash", "hang", "new_path"):
            raise ValueError(f"bad result_type {result_type!r}")
        cur = self._exec(
            "INSERT INTO results (job_id, result_type, repro_file, "
            "crash_info, created) VALUES (?,?,?,?,?)",
            (job_id, result_type, repro_file, crash_info, time.time()))
        return cur.lastrowid

    def get_results(self, job_id: Optional[int] = None
                    ) -> List[Dict[str, Any]]:
        if job_id is not None:
            return self._rows("SELECT * FROM results WHERE job_id = ?",
                              (job_id,))
        return self._rows("SELECT * FROM results")

    # -- files ----------------------------------------------------------

    def add_file(self, name: str, content: bytes) -> int:
        cur = self._exec(
            "INSERT INTO files (name, content, created) VALUES (?,?,?)",
            (name, content, time.time()))
        return cur.lastrowid

    def get_file(self, file_id: int) -> Optional[Dict[str, Any]]:
        rows = self._rows("SELECT * FROM files WHERE id = ?", (file_id,))
        return rows[0] if rows else None

    # -- instrumentation state -----------------------------------------

    def add_instrumentation_state(self, target_id: int,
                                  state: str) -> int:
        cur = self._exec(
            "INSERT INTO instrumentation_state (target_id, state, "
            "created) VALUES (?,?,?)", (target_id, state, time.time()))
        return cur.lastrowid

    def get_instrumentation_states(self, target_id: int
                                   ) -> List[Dict[str, Any]]:
        return self._rows(
            "SELECT * FROM instrumentation_state WHERE target_id = ?",
            (target_id,))

    # -- campaign stats (worker heartbeat snapshots) -------------------

    def upsert_campaign_stats(self, campaign: str, worker: str,
                              snapshot: Dict[str, Any]) -> None:
        """Latest-wins per (campaign, worker): heartbeats carry full
        cumulative snapshots, so only the newest matters."""
        self._exec(
            "INSERT INTO campaign_stats (campaign, worker, snapshot, "
            "updated) VALUES (?,?,?,?) ON CONFLICT(campaign, worker) "
            "DO UPDATE SET snapshot=excluded.snapshot, "
            "updated=excluded.updated",
            (str(campaign), worker, json.dumps(snapshot), time.time()))

    def get_campaign_stats(self, campaign: str
                           ) -> List[Dict[str, Any]]:
        rows = self._rows(
            "SELECT worker, snapshot, updated FROM campaign_stats "
            "WHERE campaign = ? ORDER BY worker", (str(campaign),))
        for r in rows:
            r["snapshot"] = json.loads(r["snapshot"])
        return rows

    # -- fleet worker health registry ----------------------------------

    def note_fleet_worker(self, campaign: str, worker: str,
                          meta: Optional[Dict[str, Any]] = None,
                          now: Optional[float] = None
                          ) -> Optional[str]:
        """Record one heartbeat in the health registry: first beat
        registers the worker (first_seen), every beat refreshes
        last_seen and resets status to healthy.  Returns the PREVIOUS
        status (None for a new worker) so the caller can emit a
        ``worker_returned`` event when a stale/dead worker revives."""
        now = time.time() if now is None else now
        with self._lock:
            conn = self._conn()
            row = conn.execute(
                "SELECT status, meta FROM fleet_workers WHERE "
                "campaign=? AND worker=?",
                (str(campaign), worker)).fetchone()
            prev = row["status"] if row is not None else None
            # meta MERGES per key instead of replacing wholesale: the
            # gossip tier registers {"gossip": endpoint} through
            # /api/peers while heartbeats register {pid, host} — the
            # later writer must not clobber the other's keys
            if meta is not None and row is not None and row["meta"]:
                try:
                    old = json.loads(row["meta"])
                    if isinstance(old, dict):
                        meta = {**old, **meta}
                except ValueError:
                    pass
            self._write(
                conn,
                "INSERT INTO fleet_workers (campaign, worker, "
                "first_seen, last_seen, beats, status, meta) "
                "VALUES (?,?,?,?,1,'healthy',?) "
                "ON CONFLICT(campaign, worker) DO UPDATE SET "
                "last_seen=excluded.last_seen, beats=beats+1, "
                "status='healthy', "
                "meta=COALESCE(excluded.meta, meta)",
                (str(campaign), worker, now, now,
                 json.dumps(meta) if meta is not None else None))
            self._commit(conn)
        return prev

    def get_fleet_workers(self, campaign: Optional[str] = None
                          ) -> List[Dict[str, Any]]:
        if campaign is not None:
            rows = self._rows(
                "SELECT * FROM fleet_workers WHERE campaign=? "
                "ORDER BY worker", (str(campaign),))
        else:
            rows = self._rows(
                "SELECT * FROM fleet_workers ORDER BY campaign, "
                "worker")
        for r in rows:
            if r.get("meta"):
                try:
                    r["meta"] = json.loads(r["meta"])
                except ValueError:
                    r["meta"] = None
        return rows

    def set_fleet_worker_status(self, campaign: str, worker: str,
                                status: str,
                                expect_last_seen: Optional[float]
                                = None) -> bool:
        """Update a worker's stored status; with ``expect_last_seen``
        the write only lands if no heartbeat slipped in since the
        caller read the row (note_fleet_worker bumps last_seen under
        the same DB lock) — the monitor uses this so a beat racing
        the tick can't get a spurious worker_stale/worker_dead
        recorded over its fresh 'healthy'.  Returns whether the
        update applied."""
        if expect_last_seen is None:
            cur = self._exec(
                "UPDATE fleet_workers SET status=? WHERE campaign=? "
                "AND worker=?", (status, str(campaign), worker))
        else:
            cur = self._exec(
                "UPDATE fleet_workers SET status=? WHERE campaign=? "
                "AND worker=? AND last_seen=?",
                (status, str(campaign), worker,
                 float(expect_last_seen)))
        return cur.rowcount > 0

    def retire_fleet_workers(self, cutoff: float) -> int:
        """Drop health-registry rows (and their heartbeat snapshots)
        whose last beat predates ``cutoff`` — a finished campaign's
        workers leave the observatory instead of reading dead
        forever; fleet_series keeps the campaign's history."""
        with self._lock:
            conn = self._conn()
            cur = self._write(
                conn,
                "DELETE FROM fleet_workers WHERE last_seen < ?",
                (float(cutoff),))
            # snapshots follow the registry: a worker with no
            # registry row left has retired (any live worker's next
            # heartbeat re-registers it immediately)
            self._write(
                conn,
                "DELETE FROM campaign_stats WHERE NOT EXISTS "
                "(SELECT 1 FROM fleet_workers fw WHERE "
                "fw.campaign=campaign_stats.campaign AND "
                "fw.worker=campaign_stats.worker)")
            self._commit(conn)
            return cur.rowcount

    def fleet_campaigns(self) -> List[str]:
        """Every campaign the observatory knows: health-registry rows
        union heartbeat-snapshot rows."""
        rows = self._rows(
            "SELECT campaign FROM fleet_workers UNION "
            "SELECT campaign FROM campaign_stats ORDER BY campaign")
        return [r["campaign"] for r in rows]

    # -- fleet time-series (history that survives worker churn) -------

    def add_fleet_sample(self, campaign: str,
                         sample: Dict[str, Any],
                         max_rows: int = 0) -> int:
        """Insert one fleet sample; with ``max_rows`` > 0 the oldest
        rows beyond the cap are pruned in the same call, so the
        history table stays bounded however long the manager runs
        (cursors stay valid — ids only ever disappear from the old
        end)."""
        cur = self._exec(
            "INSERT INTO fleet_series (campaign, t, sample) "
            "VALUES (?,?,?)",
            (str(campaign), float(sample.get("t", time.time())),
             json.dumps(sample)))
        if max_rows > 0:
            self._exec(
                "DELETE FROM fleet_series WHERE campaign=? AND id "
                "NOT IN (SELECT id FROM fleet_series WHERE "
                "campaign=? ORDER BY id DESC LIMIT ?)",
                (str(campaign), str(campaign), int(max_rows)))
        return cur.lastrowid

    def get_fleet_series(self, campaign: str, since_id: int = 0,
                         limit: int = 0) -> List[Dict[str, Any]]:
        """Samples newer than the caller's cursor (``/api/events``
        since semantics); ``limit`` > 0 caps the page."""
        sql = ("SELECT id, t, sample FROM fleet_series WHERE "
               "campaign=? AND id>? ORDER BY id")
        params: tuple = (str(campaign), int(since_id))
        if limit > 0:
            sql += " LIMIT ?"
            params += (int(limit),)
        rows = self._rows(sql, params)
        out = []
        for r in rows:
            try:
                sample = json.loads(r["sample"])
            except ValueError:
                continue
            sample["id"] = r["id"]
            sample.setdefault("t", r["t"])
            out.append(sample)
        return out

    def fleet_series_latest_id(self, campaign: str) -> int:
        rows = self._rows(
            "SELECT MAX(id) AS m FROM fleet_series WHERE campaign=?",
            (str(campaign),))
        return int(rows[0]["m"] or 0) if rows else 0

    # -- campaign events (flight-recorder exchange) --------------------

    def add_campaign_events(self, campaign: str, worker: str,
                            events: List[Dict[str, Any]]) -> int:
        """Store forwarded event records, deduped by the worker's own
        (seq, t) — a retried heartbeat re-POSTs the same window and
        one row survives, while a restarted worker whose fresh log
        reuses seq 0 still stores (its wall times differ).  Returns
        how many were stored as new."""
        stored = 0
        with self._lock:
            conn = self._conn()
            for e in events:
                if not isinstance(e, dict) or "seq" not in e:
                    continue
                try:
                    seq, t = int(e["seq"]), float(e.get("t", 0.0))
                except (TypeError, ValueError):
                    continue             # malformed record: skip
                cur = self._write(
                    conn,
                    "INSERT INTO campaign_events (campaign, worker, "
                    "seq, t, type, payload, created) "
                    "VALUES (?,?,?,?,?,?,?) "
                    "ON CONFLICT(campaign, worker, seq, t) "
                    "DO NOTHING",
                    (str(campaign), worker, seq, t,
                     str(e.get("type", "")), json.dumps(e),
                     time.time()))
                stored += cur.rowcount
            self._commit(conn)
        return stored

    #: pseudo-worker name for manager-origin records (health
    #: transitions, alerts) in the campaign event stream
    MANAGER_WORKER = "_manager"

    def add_manager_event(self, campaign: str, etype: str,
                          now: Optional[float] = None,
                          **fields) -> Dict[str, Any]:
        """Emit one manager-origin record into the campaign stream
        under the ``_manager`` pseudo-worker with its own monotone
        seq, so cursor GETs, kb-timeline merging and the heartbeat
        dedup key apply to manager events unchanged."""
        now = time.time() if now is None else now
        with self._lock:
            conn = self._conn()
            row = conn.execute(
                "SELECT MAX(seq) AS m FROM campaign_events WHERE "
                "campaign=? AND worker=?",
                (str(campaign), self.MANAGER_WORKER)).fetchone()
            seq = int(row["m"] if row and row["m"] is not None
                      else -1) + 1
            rec: Dict[str, Any] = {"v": SCHEMA_VERSION, "seq": seq,
                                   "t": now, "type": str(etype)}
            rec.update(fields)
            self._write(
                conn,
                "INSERT INTO campaign_events (campaign, worker, seq, "
                "t, type, payload, created) VALUES (?,?,?,?,?,?,?) "
                "ON CONFLICT(campaign, worker, seq, t) DO NOTHING",
                (str(campaign), self.MANAGER_WORKER, seq, float(now),
                 str(etype), json.dumps(rec, default=str),
                 time.time()))
            self._commit(conn)
        return rec

    def get_campaign_events(self, campaign: str, since_id: int = 0
                            ) -> List[Dict[str, Any]]:
        """Events newer than the caller's server-id cursor (mirrors
        the corpus exchange's since semantics)."""
        rows = self._rows(
            "SELECT id, worker, payload FROM campaign_events "
            "WHERE campaign=? AND id>? ORDER BY id",
            (str(campaign), int(since_id)))
        out = []
        for r in rows:
            try:
                event = json.loads(r["payload"])
            except ValueError:
                continue
            out.append({"id": r["id"], "worker": r["worker"],
                        "event": event})
        return out

    def events_latest_id(self, campaign: str) -> int:
        rows = self._rows(
            "SELECT MAX(id) AS m FROM campaign_events WHERE campaign=?",
            (str(campaign),))
        return int(rows[0]["m"] or 0) if rows else 0

    # -- corpus exchange (fleet seed sharing) --------------------------

    def add_corpus_entry(self, campaign: str, cov_hash: str, md5: str,
                         worker: str, content: bytes,
                         meta: Optional[Dict[str, Any]] = None
                         ) -> tuple:
        """Store one corpus entry; dedup by (campaign, cov_hash) —
        two workers hitting the same coverage frontier store ONE row.
        Returns (row id, stored_as_new)."""
        with self._lock:
            conn = self._conn()
            cur = self._write(
                conn,
                "INSERT INTO corpus_entries (campaign, cov_hash, md5, "
                "worker, content, meta, created) VALUES (?,?,?,?,?,?,?) "
                "ON CONFLICT(campaign, cov_hash) DO NOTHING",
                (str(campaign), cov_hash, md5, worker, content,
                 json.dumps(meta) if meta is not None else None,
                 time.time()))
            self._commit(conn)
            if cur.rowcount:
                return cur.lastrowid, True
            row = conn.execute(
                "SELECT id FROM corpus_entries WHERE campaign=? AND "
                "cov_hash=?", (str(campaign), cov_hash)).fetchone()
            return (row["id"] if row else None), False

    def get_corpus_entries(self, campaign: str, since_id: int = 0,
                           exclude_worker: Optional[str] = None
                           ) -> List[Dict[str, Any]]:
        """Entries newer than ``since_id`` (the puller's cursor),
        optionally excluding the puller's own uploads."""
        if exclude_worker is not None:
            rows = self._rows(
                "SELECT * FROM corpus_entries WHERE campaign=? AND "
                "id>? AND worker != ? ORDER BY id",
                (str(campaign), int(since_id), exclude_worker))
        else:
            rows = self._rows(
                "SELECT * FROM corpus_entries WHERE campaign=? AND "
                "id>? ORDER BY id", (str(campaign), int(since_id)))
        for r in rows:
            if r.get("meta"):
                try:
                    r["meta"] = json.loads(r["meta"])
                except ValueError:
                    r["meta"] = None
        return rows

    def corpus_latest_id(self, campaign: str) -> int:
        rows = self._rows(
            "SELECT MAX(id) AS m FROM corpus_entries WHERE campaign=?",
            (str(campaign),))
        return int(rows[0]["m"] or 0) if rows else 0

    # -- tracer info / minimization ------------------------------------

    def add_tracer_info(self, target_id: int, input_file: str,
                        edges: List[int]) -> None:
        self._exec(
            "INSERT INTO tracer_info (target_id, input_file, edges) "
            "VALUES (?,?,?) ON CONFLICT(target_id, input_file) "
            "DO UPDATE SET edges=excluded.edges",
            (target_id, input_file, json.dumps(sorted(set(edges)))))

    def get_tracer_info(self, target_id: int) -> Dict[str, List[int]]:
        rows = self._rows(
            "SELECT input_file, edges FROM tracer_info WHERE target_id=?",
            (target_id,))
        return {r["input_file"]: json.loads(r["edges"]) for r in rows}

    def close(self) -> None:
        if self._shared is not None:
            self._shared.close()
