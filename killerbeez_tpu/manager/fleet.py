"""Fleet observatory — worker health registry, alert rules and the
fleet time-series sampler.

PR 5's flight recorder made one worker legible; this module makes
the FLEET legible (ROADMAP item 4 needs it before any gossip/
multi-pod work can be accepted): the manager classifies every
heartbeating worker healthy/stale/dead against configurable
timeouts, emits schema-versioned ``worker_stale`` / ``worker_dead``
/ ``worker_returned`` records into the SAME campaign event stream
the workers forward into (so kb-timeline, cursor GETs and the
heartbeat dedup machinery apply unchanged), persists periodic fleet
snapshots so history survives worker churn, and evaluates a small
declarative alert-rule set whose firings land in the stream and on
``/metrics`` as ``kbz_alert_active`` gauges.

The evaluator is deliberately declarative: each rule is a pure
function ``(view, cfg) -> (active, details)`` over a per-campaign
view the monitor maintains (merged counters, per-worker statuses,
find/exec recency, a trailing unique-crash window).  Thresholds all
live in ``FleetConfig`` (manager CLI flags).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..hybrid.reconcile import fold_tiers, tier_of, validation_summary
from ..telemetry import merge
from ..telemetry.aggregate import STATUS_RANK as _STATUS_RANK
from ..telemetry.openmetrics import (
    add_counter, add_gauge, add_snapshot, new_families,
    render_families,
)
from ..utils.logging import WARNING_MSG

HEALTHY, STALE, DEAD = "healthy", "stale", "dead"


@dataclass
class FleetConfig:
    """Manager-side observatory thresholds (CLI flags in
    ``python -m killerbeez_tpu.manager``)."""

    #: seconds without a heartbeat before a worker reads stale/dead
    stale_after: float = 15.0
    dead_after: float = 60.0
    #: health/alert evaluation cadence (<= 0 disables the thread;
    #: ``tick()`` can still be driven manually — tests do)
    monitor_interval: float = 2.0
    #: seconds between persisted fleet_series samples per campaign
    series_interval: float = 10.0
    #: newest samples kept per campaign (oldest pruned at insert —
    #: the history table must not grow unboundedly, same discipline
    #: as --events-max-mb; 0 = unbounded)
    series_max_rows: int = 20000
    #: fleet_plateau: no fleet-wide new path for this many seconds
    plateau_after: float = 300.0
    #: coverage_stall: execs still advancing but paths flat this long
    stall_after: float = 900.0
    #: crash_spike: >= this many new unique crashes inside the window
    crash_spike_count: int = 10
    crash_spike_window: float = 60.0
    #: findings_drop: the fleet's findings_ring_drops counter moved
    #: within this many seconds — --generations findings rings are
    #: overflowing and finding files/events under-report (raise
    #: gen_findings_cap); clears once drops stop for a full window
    drops_window: float = 120.0
    #: seconds after a worker's last heartbeat before its registry
    #: row (and heartbeat snapshot) is retired entirely — finished
    #: campaigns stop latching worker_death forever and /metrics
    #: label cardinality stays bounded (0 = never retire)
    retire_after: float = 86400.0
    #: validation_backlog: the hybrid bridge's oldest queued finding
    #: has waited this long — the native tier is falling behind the
    #: TPU tier and verdicts are going stale (docs/HYBRID.md)
    validation_backlog_after: float = 120.0


def classify(age: float, cfg: FleetConfig) -> str:
    """Heartbeat age -> health status."""
    if age >= cfg.dead_after:
        return DEAD
    if age >= cfg.stale_after:
        return STALE
    return HEALTHY


# -- alert rules --------------------------------------------------------
#
# A rule sees the campaign view:
#   {"now", "statuses": {worker: status}, "counters": merged counters,
#    "gauges": merged gauges, "paths_changed_t", "execs_changed_t",
#    "drops_changed_t", "crash_window": deque of (t, unique_crashes),
#    "started": bool}


def _rule_worker_death(view: Dict[str, Any], cfg: FleetConfig
                       ) -> Tuple[bool, Dict[str, Any]]:
    dead = sorted(w for w, s in view["statuses"].items() if s == DEAD)
    return bool(dead), {"dead_workers": dead}


def _rule_fleet_plateau(view: Dict[str, Any], cfg: FleetConfig
                        ) -> Tuple[bool, Dict[str, Any]]:
    if not view["started"]:
        return False, {}
    quiet = view["now"] - view["paths_changed_t"]
    return quiet >= cfg.plateau_after, {
        "seconds_without_new_path": round(quiet, 1)}


def _rule_crash_spike(view: Dict[str, Any], cfg: FleetConfig
                      ) -> Tuple[bool, Dict[str, Any]]:
    win = view["crash_window"]
    if not win:
        return False, {}
    delta = win[-1][1] - win[0][1]
    return delta >= cfg.crash_spike_count, {
        "unique_crashes_in_window": int(delta),
        "window_s": cfg.crash_spike_window}


def _rule_coverage_stall(view: Dict[str, Any], cfg: FleetConfig
                         ) -> Tuple[bool, Dict[str, Any]]:
    """Paths flat for ``stall_after`` while execs still advance —
    the fleet is burning cycles without learning anything (distinct
    from a plateau, which fires sooner and regardless of execs)."""
    if not view["started"]:
        return False, {}
    now = view["now"]
    stalled = now - view["paths_changed_t"] >= cfg.stall_after
    fuzzing = now - view["execs_changed_t"] < cfg.stall_after
    return stalled and fuzzing, {
        "seconds_without_new_path":
            round(now - view["paths_changed_t"], 1)}


def _rule_findings_drop(view: Dict[str, Any], cfg: FleetConfig
                        ) -> Tuple[bool, Dict[str, Any]]:
    """``findings_ring_drops`` advanced within the window: some
    worker's --generations findings ring is overflowing, so finding
    files and events UNDER-REPORT what the campaign is discovering
    (the counter is the only honest record).  Fires on recency, not
    on the lifetime total — a long-finished overflow must not alarm
    forever — and clears after ``drops_window`` quiet seconds."""
    drops = int(view["counters"].get("findings_ring_drops", 0))
    if drops <= 0:
        return False, {}
    recent = view["now"] - view["drops_changed_t"] < cfg.drops_window
    return recent, {"findings_ring_drops_total": drops,
                    "seconds_since_last_drop":
                        round(view["now"] - view["drops_changed_t"],
                              1)}


def _rule_validation_backlog(view: Dict[str, Any], cfg: FleetConfig
                             ) -> Tuple[bool, Dict[str, Any]]:
    """The hybrid bridge's validation queue has findings older than
    ``validation_backlog_after``: the native tier cannot keep up and
    cross-tier verdicts lag the frontier they should steer.  Only
    fires for campaigns that post the queue gauges at all — a pure
    TPU or pure native fleet never alarms."""
    g = view.get("gauges", {})
    depth = int(g.get("validation_queue_depth", 0))
    age = float(g.get("validation_queue_age", 0.0))
    active = depth > 0 and age >= cfg.validation_backlog_after
    return active, {"queue_depth": depth,
                    "oldest_age_s": round(age, 1)}


#: declarative rule table: name -> predicate
ALERT_RULES: Tuple[Tuple[str, Callable], ...] = (
    ("worker_death", _rule_worker_death),
    ("fleet_plateau", _rule_fleet_plateau),
    ("crash_spike", _rule_crash_spike),
    ("coverage_stall", _rule_coverage_stall),
    ("findings_drop", _rule_findings_drop),
    ("validation_backlog", _rule_validation_backlog),
)


class FleetMonitor(threading.Thread):
    """Periodic fleet evaluator: health transitions, alert rules and
    the fleet_series sampler, one ``tick()`` per interval.

    Manager-origin events go through ``ManagerDB.add_manager_event``
    (worker ``_manager``, its own monotone seq per campaign), so they
    ride the exact cursor/dedup path worker-forwarded events use.
    """

    def __init__(self, db, cfg: Optional[FleetConfig] = None,
                 time_fn=time.time):
        super().__init__(daemon=True)
        self.db = db
        self.cfg = cfg or FleetConfig()
        self._time = time_fn
        self._halt = threading.Event()
        #: campaign -> mutable evaluator state (touched only by
        #: tick(), which _lock serializes)
        self._state: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        #: campaign -> alert snapshot list, REPLACED (never mutated)
        #: at the end of each campaign pass so /api/fleet and
        #: /metrics read it lock-free — a scrape never stalls behind
        #: a tick's DB I/O
        self._alert_view: Dict[str, List[Dict[str, Any]]] = {}

    # -- lifecycle ------------------------------------------------------

    def run(self) -> None:
        while not self._halt.wait(self.cfg.monitor_interval):
            try:
                self.tick()
            except Exception as e:       # observability never crashes
                WARNING_MSG("fleet monitor tick failed: %s", e)

    def stop(self) -> None:
        self._halt.set()
        if self.is_alive():
            self.join(timeout=self.cfg.monitor_interval + 1)

    # -- evaluation -----------------------------------------------------

    def tick(self, now: Optional[float] = None) -> None:
        """One evaluation pass (tests drive this directly with a
        synthetic clock).  The lock only serializes concurrent
        ticks; readers go through the lock-free ``_alert_view``."""
        now = self._time() if now is None else now
        with self._lock:
            if self.cfg.retire_after > 0:
                self.db.retire_fleet_workers(
                    now - self.cfg.retire_after)
            rows = self.db.get_fleet_workers()   # one scan per tick
            by_campaign: Dict[str, List[Dict[str, Any]]] = {}
            for row in rows:
                by_campaign.setdefault(row["campaign"],
                                       []).append(row)
            self._tick_health(rows, now)
            campaigns = self.db.fleet_campaigns()
            for campaign in campaigns:
                self._tick_campaign(
                    campaign, now, by_campaign.get(campaign, []))
            # a retired campaign's evaluator state (and published
            # alert snapshot) goes with it — otherwise a stale
            # worker_death view outlives the workers it described
            known = set(campaigns)
            for gone in [c for c in self._alert_view
                         if c not in known]:
                self._alert_view.pop(gone, None)
                self._state.pop(gone, None)

    def _tick_health(self, rows, now: float) -> None:
        """Escalate stored worker statuses against heartbeat age and
        emit transition events.  De-escalation (``worker_returned``)
        happens at heartbeat ingest (api.h_stats) — absent
        heartbeats, classification only worsens over time.  The
        status write is conditioned on ``last_seen`` being unchanged,
        so a heartbeat racing the tick wins and no spurious
        stale/dead record lands in the append-only stream."""
        for row in rows:
            want = classify(now - row["last_seen"], self.cfg)
            have = row.get("status", HEALTHY)
            if _STATUS_RANK.get(want, 0) <= _STATUS_RANK.get(have, 0):
                continue
            if not self.db.set_fleet_worker_status(
                    row["campaign"], row["worker"], want,
                    expect_last_seen=row["last_seen"]):
                continue                 # a fresh beat won the race
            self.db.add_manager_event(
                row["campaign"], f"worker_{want}",
                worker=row["worker"],
                last_seen=row["last_seen"],
                age=round(now - row["last_seen"], 3))

    def _campaign_state(self, campaign: str, now: float
                        ) -> Dict[str, Any]:
        st = self._state.get(campaign)
        if st is None:
            st = self._state[campaign] = {
                "paths": -1, "paths_changed_t": now,
                "execs": -1, "execs_changed_t": now,
                # drops recency starts "long quiet": a restart must
                # not re-fire findings_drop on a stale lifetime total
                "drops": -1, "drops_changed_t": float("-inf"),
                "crash_window": deque(),
                "last_series_t": 0.0,
                "alerts": {name: {"active": False, "since": None,
                                  "details": {}}
                           for name, _ in ALERT_RULES},
            }
        return st

    def _tick_campaign(self, campaign: str, now: float,
                       workers: List[Dict[str, Any]]) -> None:
        cfg = self.cfg
        st = self._campaign_state(campaign, now)
        statuses = {w["worker"]: classify(now - w["last_seen"], cfg)
                    for w in workers}
        stats = self.db.get_campaign_stats(campaign)
        merged = merge([r["snapshot"] for r in stats]) or {}
        counters = merged.get("counters", {})

        # recency trackers for the plateau/stall rules
        paths = int(counters.get("new_paths", 0))
        if paths != st["paths"]:
            if st["paths"] >= 0 or paths > 0:
                st["paths_changed_t"] = now
            st["paths"] = paths
        execs = int(counters.get("execs", 0))
        if execs != st["execs"]:
            st["execs_changed_t"] = now
            st["execs"] = execs
        drops = int(counters.get("findings_ring_drops", 0))
        if drops != st["drops"]:
            # the FIRST observation only baselines (a manager restart
            # must not re-alarm on a lifetime total whose drops may
            # have stopped hours ago), and only an INCREASE counts as
            # movement — the merged total of a monotone counter can
            # shrink when a worker restarts or retires, which is not
            # a new drop
            if st["drops"] >= 0 and drops > st["drops"]:
                st["drops_changed_t"] = now
            st["drops"] = drops
        win = st["crash_window"]
        win.append((now, int(counters.get("unique_crashes", 0))))
        while win and win[0][0] < now - cfg.crash_spike_window:
            win.popleft()

        view = {"now": now, "statuses": statuses,
                "counters": counters,
                "gauges": merged.get("gauges", {}),
                "paths": st["paths"],
                "paths_changed_t": st["paths_changed_t"],
                "execs_changed_t": st["execs_changed_t"],
                "drops_changed_t": st["drops_changed_t"],
                "crash_window": win, "started": execs > 0}
        for name, rule in ALERT_RULES:
            active, details = rule(view, cfg)
            slot = st["alerts"][name]
            if active and not slot["active"]:
                slot.update(active=True, since=now, details=details)
                self.db.add_manager_event(
                    campaign, "alert", alert=name, active=True,
                    **details)
            elif not active and slot["active"]:
                slot.update(active=False, details=details)
                self.db.add_manager_event(
                    campaign, "alert", alert=name, active=False)
            elif active:
                slot["details"] = details
        # publish this pass's alert snapshot (atomic dict store —
        # readers never see a half-updated view and never block)
        self._alert_view[campaign] = [
            {"alert": name, **dict(st["alerts"][name])}
            for name, _ in ALERT_RULES]

        # fleet time-series: survives worker churn, feeds fleet-wide
        # plot_data and the kb-fleet history view
        if workers and now - st["last_series_t"] >= cfg.series_interval:
            st["last_series_t"] = now
            counts = {s: 0 for s in (HEALTHY, STALE, DEAD)}
            for s in statuses.values():
                counts[s] += 1
            gauges = merged.get("gauges", {})
            rates = merged.get("rates", {})
            self.db.add_fleet_sample(campaign, {
                "t": now,
                "n_workers": len(workers),
                "workers_healthy": counts[HEALTHY],
                "workers_stale": counts[STALE],
                "workers_dead": counts[DEAD],
                "execs": execs,
                "new_paths": paths,
                "crashes": int(counters.get("crashes", 0)),
                "unique_crashes":
                    int(counters.get("unique_crashes", 0)),
                "hangs": int(counters.get("hangs", 0)),
                "unique_hangs": int(counters.get("unique_hangs", 0)),
                "corpus_seen": int(gauges.get(
                    "corpus_seen", gauges.get("corpus_size", 0))),
                "execs_per_sec_ema":
                    float(rates.get("execs", {}).get("rate", 0.0)),
            }, max_rows=cfg.series_max_rows)

    # -- views ----------------------------------------------------------

    def alerts(self, campaign: str) -> List[Dict[str, Any]]:
        """Current alert states for a campaign (all configured rules,
        with an ``active`` flag — /metrics wants the zeros too).
        Lock-free: reads the snapshot the last tick published, so a
        Prometheus scrape never stalls behind a tick's DB I/O."""
        view = self._alert_view.get(campaign)
        if view is not None:
            return view
        return [{"alert": name, "active": False, "since": None,
                 "details": {}} for name, _ in ALERT_RULES]


# -- views shared by /api/fleet and kb-fleet ---------------------------


def worker_stats_summary(snap: Dict[str, Any]) -> Dict[str, Any]:
    """The compact per-worker numbers kb-fleet tabulates."""
    c = snap.get("counters", {})
    g = snap.get("gauges", {})
    d = snap.get("derived", {})
    return {
        "execs": int(c.get("execs", 0)),
        "new_paths": int(c.get("new_paths", 0)),
        "crashes": int(c.get("crashes", 0)),
        "unique_crashes": int(c.get("unique_crashes", 0)),
        "hangs": int(c.get("hangs", 0)),
        "unique_hangs": int(c.get("unique_hangs", 0)),
        "corpus_seen": int(g.get("corpus_seen",
                                 g.get("corpus_size", 0))),
        "findings_ring_drops": int(c.get("findings_ring_drops", 0)),
        # partition-tolerance row: quarantined synced-in entries,
        # gossip flow and the worker's current/lifetime peer bans —
        # the fleet-chaos CI lane asserts on these via kb-fleet --json
        "sync_quarantined": int(c.get("sync_quarantined", 0)),
        "gossip_entries_in": int(c.get("gossip_entries_in", 0)),
        "gossip_entries_out": int(c.get("gossip_entries_out", 0)),
        "peers_banned": int(c.get("peers_banned", 0)),
        "peers_banned_active": int(g.get("peers_banned_active", 0)),
        # hybrid bridge row: cross-tier verdict counters + the
        # validation queue the backlog alert watches (docs/HYBRID.md)
        "hybrid_validations": int(c.get("hybrid_validations", 0)),
        "hybrid_confirmed": int(c.get("hybrid_confirmed", 0)),
        "hybrid_proxy_only": int(c.get("hybrid_proxy_only", 0)),
        "hybrid_flaky": int(c.get("hybrid_flaky", 0)),
        "validation_queue_depth":
            int(g.get("validation_queue_depth", 0)),
        "execs_per_sec": float(d.get("execs_per_sec", 0.0)),
        "execs_per_sec_ema": float(d.get("execs_per_sec_ema", 0.0)),
    }


def peer_directory(db, cfg: FleetConfig, campaign: str,
                   exclude: Optional[str] = None,
                   now: Optional[float] = None
                   ) -> List[Dict[str, Any]]:
    """``/api/peers/<campaign>``: every NON-DEAD worker that has
    registered a gossip endpoint.  Liveness rides the same health
    registry as /api/fleet — the directory and the observatory can
    never disagree about who is alive.  EXCEPT while the manager is
    write-degraded: heartbeat writes are failing, so last_seen is
    frozen fleet-wide and the liveness classification is stale — the
    directory then serves every registered endpoint rather than
    falsely reading the whole fleet dead."""
    now = time.time() if now is None else now
    frozen = bool(getattr(db, "degraded", False))
    out: List[Dict[str, Any]] = []
    for row in db.get_fleet_workers(campaign):
        meta = row.get("meta")
        endpoint = meta.get("gossip") if isinstance(meta, dict) \
            else None
        if not endpoint or row["worker"] == exclude:
            continue
        status = classify(max(0.0, now - row["last_seen"]), cfg)
        if status == DEAD and not frozen:
            continue
        out.append({"worker": row["worker"], "endpoint": endpoint,
                    "status": status,
                    "last_seen": row["last_seen"]})
    return out


def fleet_view(db, cfg: FleetConfig, campaign: str,
               monitor: Optional[FleetMonitor] = None,
               now: Optional[float] = None) -> Dict[str, Any]:
    """The ``/api/fleet/<campaign>`` response body: live-classified
    worker health (age against the config, not the stored status —
    accurate between monitor ticks), per-worker stat summaries, the
    merged fleet snapshot (with a ``health`` section that folds via
    ``aggregate.merge_health``), and current alert states."""
    now = time.time() if now is None else now
    rows = db.get_fleet_workers(campaign)
    stats = {r["worker"]: r for r in db.get_campaign_stats(campaign)}
    workers: Dict[str, Any] = {}
    counts = {s: 0 for s in (HEALTHY, STALE, DEAD)}
    health: Dict[str, Any] = {}
    for row in rows:
        age = max(0.0, now - row["last_seen"])
        status = classify(age, cfg)
        counts[status] += 1
        entry = {
            "first_seen": row["first_seen"],
            "last_seen": row["last_seen"],
            "age": round(age, 3),
            "status": status,
            "beats": row.get("beats", 0),
            "meta": row.get("meta"),
        }
        srow = stats.get(row["worker"])
        if srow is not None:
            entry["stats"] = worker_stats_summary(srow["snapshot"])
        workers[row["worker"]] = entry
        health[row["worker"]] = {"status": status,
                                 "first_seen": row["first_seen"],
                                 "last_seen": row["last_seen"]}
    merged = merge([r["snapshot"] for r in stats.values()])
    if merged is not None and health:
        merged["health"] = health
    # per-tier fold (hybrid campaigns; docs/HYBRID.md): workers group
    # by meta["tier"] — a pure TPU fleet shows one "tpu" tier and the
    # validation rollup reads all-zero
    statuses = {w: e["status"] for w, e in workers.items()}
    tiers = fold_tiers(rows, stats, statuses)
    return {
        "campaign": campaign,
        "t": now,
        "config": {"stale_after": cfg.stale_after,
                   "dead_after": cfg.dead_after},
        "n_workers": len(rows),
        "counts": counts,
        "workers": workers,
        "merged": merged,
        "tiers": tiers,
        "validation": validation_summary(merged),
        "alerts": (monitor.alerts(campaign) if monitor is not None
                   else []),
    }


def _workers_by_campaign(db) -> Dict[str, List[Dict[str, Any]]]:
    """One all-campaigns scan grouped in python — the endpoints must
    not issue a fleet_workers query per campaign (N+1 under the DB
    lock on every scrape)."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    for row in db.get_fleet_workers():
        out.setdefault(row["campaign"], []).append(row)
    return out


def fleet_index(db, cfg: FleetConfig,
                now: Optional[float] = None) -> Dict[str, Any]:
    """``/api/fleet``: one summary row per known campaign."""
    now = time.time() if now is None else now
    by_campaign = _workers_by_campaign(db)
    out: Dict[str, Any] = {}
    for campaign in db.fleet_campaigns():
        rows = by_campaign.get(campaign, [])
        counts = {s: 0 for s in (HEALTHY, STALE, DEAD)}
        for row in rows:
            counts[classify(max(0.0, now - row["last_seen"]),
                            cfg)] += 1
        out[campaign] = {"n_workers": len(rows), **counts}
    return {"t": now, "campaigns": out,
            "degraded": bool(getattr(db, "degraded", False))}


def render_fleet_metrics(db, cfg: FleetConfig,
                         monitor: Optional[FleetMonitor] = None,
                         now: Optional[float] = None) -> str:
    """The manager's ``/metrics`` exposition: every campaign's
    per-worker registry snapshots labeled ``{campaign, worker}``,
    fleet folds under the ``kbz_fleet_`` namespace labeled
    ``{campaign}`` (so a Prometheus ``sum()`` over workers never
    double-counts the fold), worker liveness gauges, and
    ``kbz_alert_active`` per alert rule."""
    now = time.time() if now is None else now
    fams = new_families()
    add_gauge(fams, "kbz_manager_degraded",
              1.0 if getattr(db, "degraded", False) else 0.0,
              help_text="1 = DB writes failing; manager serving "
                        "read-only off the admission journal")
    by_campaign = _workers_by_campaign(db)
    for campaign in db.fleet_campaigns():
        labels_c = {"campaign": campaign}
        stats = db.get_campaign_stats(campaign)
        for row in stats:
            add_snapshot(fams, row["snapshot"],
                         {"campaign": campaign,
                          "worker": row["worker"]})
        merged = merge([r["snapshot"] for r in stats])
        if merged is not None:
            add_snapshot(fams, merged, labels_c,
                         prefix="kbz_fleet", include_hists=False)
        counts = {s: 0 for s in (HEALTHY, STALE, DEAD)}
        for row in by_campaign.get(campaign, []):
            status = classify(max(0.0, now - row["last_seen"]), cfg)
            counts[status] += 1
            wl = {"campaign": campaign, "worker": row["worker"]}
            add_gauge(fams, "kbz_worker_up",
                      1.0 if status == HEALTHY else 0.0, wl,
                      help_text="1 = heartbeat within stale_after")
            add_gauge(fams,
                      "kbz_worker_last_seen_timestamp_seconds",
                      row["last_seen"], wl)
            add_counter(fams, "kbz_worker_heartbeats",
                        row.get("beats", 0), wl)
        for status, n in counts.items():
            add_gauge(fams, "kbz_fleet_workers", n,
                      {"campaign": campaign, "status": status},
                      help_text="workers by health status")
        # hybrid campaign series (docs/HYBRID.md): per-tier worker
        # counts and the cross-tier verdict counters — only emitted
        # once a campaign actually posts tier tags / hybrid counters,
        # so pure TPU fleets keep their exact historical scrape
        tier_counts: Dict[str, int] = {}
        for row in by_campaign.get(campaign, []):
            t = tier_of(row.get("meta"))
            tier_counts[t] = tier_counts.get(t, 0) + 1
        if len(tier_counts) > 1 or (tier_counts and
                                    "tpu" not in tier_counts):
            for t, n in sorted(tier_counts.items()):
                add_gauge(fams, "kbz_fleet_tier_workers", n,
                          {"campaign": campaign, "tier": t},
                          help_text="workers by execution tier")
        mc = (merged or {}).get("counters", {})
        if "hybrid_validations" in mc:
            for verdict in ("confirmed", "proxy_only", "flaky"):
                add_counter(fams, "kbz_hybrid_validations",
                            mc.get(f"hybrid_{verdict}", 0),
                            {"campaign": campaign,
                             "verdict": verdict},
                            help_text="cross-tier validation "
                                      "verdicts (hybrid bridge)")
            mg = (merged or {}).get("gauges", {})
            add_gauge(fams, "kbz_validation_queue_depth",
                      mg.get("validation_queue_depth", 0), labels_c,
                      help_text="findings awaiting native "
                                "validation")
        if monitor is not None:
            for a in monitor.alerts(campaign):
                add_gauge(fams, "kbz_alert_active",
                          1.0 if a["active"] else 0.0,
                          {"campaign": campaign,
                           "alert": a["alert"]},
                          help_text="declarative alert rule state")
    return render_families(fams)
