"""Fleet worker + assimilator — the BOINC-client replacement.

Lifecycle parity with the reference's BOINC path (SURVEY §3.5):
claim a workunit from the manager, run the fuzzer on it, then
assimilate — stage each finding file to the manager and POST a result
row per finding (crash | hang | new_path, the same result-type mapping
as server/killerbeez_assimilator.py:36-39) — and mark the job done
(with the mutator state for resumption).

    python -m killerbeez_tpu.manager.worker http://mgr:8650 --once
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import shlex
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from .. import FUZZ_CRASH
from ..utils.logging import INFO_MSG, WARNING_MSG, setup_logging

RESULT_DIRS = {"crashes": "crash", "hangs": "hang",
               "new_paths": "new_path"}


def _request(url: str, payload: Optional[Dict[str, Any]] = None,
             method: str = "POST") -> Any:
    # chaos seam: every manager RPC (work claim, heartbeat, corpus
    # sync, event forward) can be made to 500 or partition mid-round
    from ..resilience.chaos import chaos_point
    chaos_point("manager_rpc", url=url)
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        if resp.status == 204:
            return None
        body = resp.read()
        return json.loads(body) if body else None


def _request_retry(url: str, payload: Optional[Dict[str, Any]] = None,
                   method: str = "POST", attempts: int = 5,
                   base_delay: float = 0.5) -> Any:
    """_request with exponential backoff on transport errors (manager
    restarts, DCN blips): 0.5s, 1s, 2s, 4s between tries.  HTTP-level
    errors (4xx/5xx with a response) are NOT retried — they mean the
    manager saw the request and rejected it."""
    last: Optional[Exception] = None
    for attempt in range(attempts):
        try:
            return _request(url, payload, method)
        except urllib.error.HTTPError:
            raise
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            last = e
            if attempt + 1 < attempts:
                delay = base_delay * (2 ** attempt)
                WARNING_MSG("request to %s failed (%s); retry in "
                            "%.1fs", url, e, delay)
                time.sleep(delay)
    raise last  # type: ignore[misc]


# the heartbeat's stats.jsonl tailer: O(1) tail window + torn-line
# tolerance, shared with kb-stats (telemetry.sink)
from ..telemetry import TERMINAL_EVENTS, read_latest_snapshot  # noqa: E402


class Heartbeat(threading.Thread):
    """Progress reporter for one running job: every ``interval``
    seconds, POST the job's latest telemetry snapshot to the
    manager's ``/api/stats/<campaign>`` and forward any new TERMINAL
    events (crash / hang / plateau) from the job's ``events.jsonl``
    to ``/api/events/<campaign>`` (retry-with-backoff; a dead manager
    degrades to warnings — the fuzz run itself never stops for
    observability)."""

    def __init__(self, manager_url: str, campaign: str, worker: str,
                 output_dir: str, interval: float = 5.0,
                 tier: Optional[str] = None):
        super().__init__(daemon=True)
        self.url = f"{manager_url}/api/stats/{campaign}"
        self.events_url = f"{manager_url}/api/events/{campaign}"
        self.worker = worker
        self.output_dir = output_dir
        self.interval = interval
        self._halt = threading.Event()
        self._ev_pos = 0                 # events.jsonl bytes consumed
        self._ev_ino = None              # inode the cursor belongs to
        self._ev1_pos = 0                # first-beat drain cursor (.1)
        self._ev1_done = False
        self.sent = 0
        self.events_sent = 0
        #: worker identity forwarded with each beat — the manager's
        #: health registry stores it as fleet_workers.meta
        try:
            import socket
            self.meta = {"pid": os.getpid(),
                         "host": socket.gethostname()}
        except OSError:
            self.meta = {"pid": os.getpid()}
        # execution tier tag (hybrid campaigns; docs/HYBRID.md) —
        # absent means "tpu" to every per-tier fold
        if tier:
            self.meta["tier"] = tier

    #: per-beat read window over events.jsonl: bounds memory and
    #: request size — a long backlog (worker restart against a
    #: resumed campaign's log) drains across beats instead of one
    #: whole-file read + one giant POST
    EV_WINDOW = 256 << 10

    def _read_terminal_window(self, path: str, pos: int):
        """One bounded read at ``pos``: returns (terminal event
        records from the COMPLETE lines, bytes consumed) — (.., 0)
        when nothing complete is available."""
        try:
            with open(path, "rb") as f:
                f.seek(pos)
                chunk = f.read(self.EV_WINDOW)
        except OSError:
            return [], 0
        nl = chunk.rfind(b"\n")
        if nl < 0:
            return [], 0
        events = []
        for line in chunk[:nl].splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and \
                    rec.get("type") in TERMINAL_EVENTS:
                events.append(rec)
        return events, nl + 1

    def _post_events(self, events) -> bool:
        if not events:
            return True
        try:
            _request_retry(self.events_url,
                           {"worker": self.worker, "events": events},
                           attempts=3)
        except Exception as e:
            WARNING_MSG("event forward to %s failed: %s",
                        self.events_url, e)
            return False
        self.events_sent += len(events)
        return True

    def _forward_events(self) -> int:
        """Ship terminal events appended since the last beat.  Only
        COMPLETE lines advance the cursor (a torn tail line stays for
        the next beat); on transport failure the cursor rewinds — the
        manager dedups by (worker, seq, t), so a re-send is
        harmless.  An ``--events-max-mb`` rotation (live file shrinks
        below the cursor) drains the rotated generation's tail from
        ``events.jsonl.1`` first, then restarts the cursor at the
        fresh live file."""
        path = os.path.join(self.output_dir, "events.jsonl")
        if not self._ev1_done:
            # first beats: a burst of events can rotate the log
            # BEFORE the heartbeat ever reads it, so the rotated
            # generation must be drained once up front (re-sends are
            # dedup-safe; the .1 file is bounded by the cap)
            while True:
                tail, used = self._read_terminal_window(
                    path + ".1", self._ev1_pos)
                if used == 0:
                    self._ev1_done = True
                    break
                if not self._post_events(tail):
                    return 0             # retry the same spot later
                self._ev1_pos += used
        try:
            st = os.stat(path)
        except OSError:
            st = None
        rotated = (self._ev_ino is not None
                   and (st is None or st.st_ino != self._ev_ino
                        or st.st_size < self._ev_pos))
        if rotated:
            # rotated under us (the cursor's inode is now
            # events.jsonl.1): finish the previous generation — the
            # .1 file is bounded by the rotation cap, so this drain
            # is bounded too — then restart at byte 0 of the fresh
            # live file.  Only one generation is kept on disk: a
            # double rotation within one beat loses the middle one.
            while True:
                tail, used = self._read_terminal_window(
                    path + ".1", self._ev_pos)
                if used == 0:
                    break
                if not self._post_events(tail):
                    return 0             # retry the same spot later
                self._ev_pos += used
            self._ev_pos = 0
            self._ev_ino = None
        if st is None:
            return 0
        self._ev_ino = st.st_ino
        events, consumed = self._read_terminal_window(path,
                                                      self._ev_pos)
        if consumed == 0:
            return 0
        if not self._post_events(events):
            return 0                     # cursor unmoved: re-read
        self._ev_pos += consumed
        return len(events)

    def beat(self) -> bool:
        self._forward_events()
        snap = read_latest_snapshot(self.output_dir)
        if snap is None:
            return False
        try:
            _request_retry(self.url,
                           {"worker": self.worker, "snapshot": snap,
                            "meta": self.meta},
                           attempts=3)
            self.sent += 1
            return True
        except Exception as e:
            WARNING_MSG("heartbeat to %s failed after retries: %s",
                        self.url, e)
            return False

    def run(self) -> None:
        while not self._halt.wait(self.interval):
            self.beat()

    def stop(self) -> None:
        """Stop the loop and send one final snapshot (the job's
        cumulative totals; makes short jobs visible even when they
        finish inside the first interval)."""
        self._halt.set()
        self.join(timeout=self.interval + 1)
        self.beat()


def verify_repro(job: Dict[str, Any], content: bytes,
                 cache: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
    """Re-run a crash repro ONCE before posting the result — the
    reference's server flow traces results back through verification
    (docs/Server.md:215-258); the repo's analogue re-executes the
    repro under the richest available tier and attaches what it saw:

      * device targets (jit_harness / ipt): re-execute on the KBVM,
        report the verdict + exit code;
      * host targets (afl / return_code, file or stdin driver): re-run
        under the ptrace debug instrumentation, harvesting
        signal / fault address / module-relative PC;
      * network deliveries can't be replayed without the live session
        and are marked unverifiable.
    """
    instr_name = job.get("instrumentation", "")
    driver = job.get("driver", "")
    try:
        dopts = json.loads(job["driver_opts"]) \
            if job.get("driver_opts") else {}
    except (ValueError, TypeError):
        dopts = {}
    try:
        if instr_name in ("jit_harness", "ipt"):
            from ..instrumentation.factory import instrumentation_factory
            # one device instrumentation per job (the cache) — a fresh
            # instance per crash file would re-trace/compile the XLA
            # step for every finding
            instr = (cache or {}).get("device_instr")
            if instr is None:
                instr = instrumentation_factory(
                    instr_name, job.get("instrumentation_opts"))
                if cache is not None:
                    cache["device_instr"] = instr
            instr.enable(content)
            st = instr.get_fuzz_result()
            if cache is None:
                instr.cleanup()
            return {"verified": st == FUZZ_CRASH, "tier": "device",
                    "status": int(st)}
        if driver not in ("file", "stdin"):
            return {"verified": None,
                    "reason": f"{driver} delivery is not replayable"}
        path = dopts.get("path")
        if not path or not os.path.exists(path):
            return {"verified": False,
                    "error": "target binary unavailable on this worker"}
        from ..instrumentation.debug import DebugInstrumentation
        dbg = DebugInstrumentation(None)
        args = (dopts.get("arguments") or "").strip()
        if driver == "stdin":
            dbg.enable(content,
                       cmd_line=f"{path} {args}".strip())
        else:
            fd, tmp = tempfile.mkstemp(prefix="kb_repro_")
            try:
                os.write(fd, content)
                os.close(fd)
                args = (dopts.get("arguments") or "@@").replace("@@", tmp)
                dbg.enable(None, cmd_line=f"{path} {args}")
            finally:
                os.unlink(tmp)
        verified = dbg.get_fuzz_result() == FUZZ_CRASH
        out: Dict[str, Any] = {"verified": verified, "tier": "debug"}
        if verified:
            out.update(dbg.last_crash_info)
            out["description"] = dbg.crash_description()
        dbg.cleanup()
        return out
    except Exception as e:   # verification must never block reporting
        return {"verified": False, "error": str(e)[:200]}


def assimilate(manager_url: str, job: Dict[str, Any],
               output_dir: str) -> int:
    """Upload findings and create result rows (crashes re-verified
    first, details attached to the row); returns count."""
    n = 0
    job_id = job["id"]
    verify_cache: Dict[str, Any] = {}
    try:
        for sub, result_type in RESULT_DIRS.items():
            d = os.path.join(output_dir, sub)
            if not os.path.isdir(d):
                continue
            for name in sorted(os.listdir(d)):
                with open(os.path.join(d, name), "rb") as f:
                    content = f.read()
                up = _request(f"{manager_url}/api/file", {
                    "name": f"job{job_id}_{sub}_{name}",
                    "content_b64": base64.b64encode(content).decode()})
                payload = {
                    "result_type": result_type,
                    "repro_file": f"/api/file/{up['id']}",
                }
                if result_type == "crash":
                    payload["crash_info"] = json.dumps(
                        verify_repro(job, content, verify_cache))
                _request(f"{manager_url}/api/job/{job_id}/results",
                         payload)
                n += 1
    finally:
        if "device_instr" in verify_cache:
            verify_cache["device_instr"].cleanup()
    return n


def run_job(manager_url: str, job: Dict[str, Any],
            in_process: bool = False, worker_name: str = "anon",
            heartbeat_s: float = 5.0,
            corpus_sync_s: float = 10.0,
            gossip: bool = False) -> str:
    """Execute one claimed job; returns 'done' or 'failed'.  While
    the fuzzer runs, a heartbeat thread tails its stats.jsonl and
    POSTs progress snapshots to the manager (campaign key = job id),
    so the fleet view updates DURING long campaigns, not just at
    assimilation time.  The fuzzer also runs with a local corpus
    store synced through the manager's ``/api/corpus/<job id>``
    (``corpus_sync_s`` cadence; 0 disables) — fleet workers on the
    same campaign fuzz each other's frontiers instead of rediscovering
    them."""
    with tempfile.TemporaryDirectory(prefix="kb_work_") as workdir:
        out_dir = os.path.join(workdir, "output")
        argv = shlex.split(job["cmdline"]) + ["-o", out_dir]
        if corpus_sync_s > 0:
            argv += ["--corpus-dir", os.path.join(workdir, "corpus"),
                     "--sync-manager", manager_url,
                     "--sync-campaign", str(job["id"]),
                     "--sync-worker", worker_name,
                     "--sync-interval", str(corpus_sync_s)]
            if gossip:
                # peer-to-peer corpus gossip (ephemeral sidecar
                # port; the fuzzer registers it with the manager's
                # peer directory) — docs/MANAGER.md
                argv += ["--gossip", "0"]
        hb = Heartbeat(manager_url, str(job["id"]), worker_name,
                       out_dir, interval=heartbeat_s)
        hb.start()
        try:
            if in_process:
                from ..fuzzer.cli import main as fuzzer_main
                # strip the "python -m killerbeez_tpu.fuzzer" prefix
                tail = argv[argv.index("killerbeez_tpu.fuzzer") + 1:] \
                    if "killerbeez_tpu.fuzzer" in argv else argv
                rc = fuzzer_main(tail)
            else:
                rc = subprocess.run(argv).returncode
        finally:
            hb.stop()
        status = "done" if rc == 0 else "failed"
        found = assimilate(manager_url, job, out_dir)
        INFO_MSG("job %d %s: %d findings, %d heartbeats",
                 job["id"], status, found, hb.sent)
        return status


def work_loop(manager_url: str, worker_name: str, once: bool = False,
              poll_s: float = 2.0, in_process: bool = False,
              corpus_sync_s: float = 10.0,
              gossip: bool = False) -> int:
    """Claim-run-report until the queue drains (once) or forever."""
    done = 0
    while True:
        job = _request_retry(f"{manager_url}/api/work/claim",
                             {"worker": worker_name})
        if job is None:
            if once:
                return done
            time.sleep(poll_s)
            continue
        try:
            status = run_job(manager_url, job, in_process=in_process,
                             worker_name=worker_name,
                             corpus_sync_s=corpus_sync_s,
                             gossip=gossip)
        except Exception as e:  # job must not wedge the worker
            WARNING_MSG("job %s failed: %s", job.get("id"), e)
            status = "failed"
        _request_retry(f"{manager_url}/api/work/{job['id']}/finish",
                       {"status": status})
        done += 1


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="killerbeez-tpu-worker",
        description="claim and run fuzzing jobs from a manager")
    p.add_argument("manager_url", help="e.g. http://127.0.0.1:8650")
    p.add_argument("--name", default=f"worker-{os.getpid()}")
    p.add_argument("--once", action="store_true",
                   help="drain the queue then exit")
    p.add_argument("--in-process", action="store_true",
                   help="run jobs in this interpreter (no subprocess)")
    p.add_argument("--corpus-sync", type=float, default=10.0,
                   help="seconds between fleet corpus-sync rounds "
                        "through /api/corpus/<job id> (0 disables; "
                        "default 10)")
    p.add_argument("--gossip", action="store_true",
                   help="run each job with peer-to-peer corpus "
                        "gossip (--gossip on the fuzzer: sidecar + "
                        "fanout pulls via the manager's peer "
                        "directory; corpus flow survives a dead or "
                        "partitioned manager — docs/MANAGER.md)")
    p.add_argument("-l", "--logging-options")
    args = p.parse_args(argv)
    setup_logging(args.logging_options)
    # chaos harness: a supervised/chaos-tested worker picks its fault
    # spec up from KBZ_CHAOS (the manager_rpc seam in _request fires
    # nothing otherwise)
    from ..resilience.chaos import configure_from_env
    configure_from_env()
    n = work_loop(args.manager_url, args.name, once=args.once,
                  in_process=args.in_process,
                  corpus_sync_s=args.corpus_sync,
                  gossip=args.gossip)
    INFO_MSG("worker finished: %d jobs", n)
    return 0


if __name__ == "__main__":
    sys.exit(main())
