"""Write-ahead admission journal — a manager SIGKILL loses zero
accepted POSTs.

The manager ACKs corpus entries and forwarded events with 201, and
PR 2's reject rule makes workers DROP entries the manager has seen
(retrying an acknowledged row forever would poison every future
round).  That contract means the ACK must be durable: a SIGKILL
between the ACK and the sqlite commit — or an sqlite write that
fails outright (ENOSPC, ``database is locked`` beyond the retry
budget) — must not silently lose the row the fleet believes is safe.

So every admission POST appends ONE JSON line here *before* the DB
write (`append` flushes + fsyncs per record — admissions are rare
next to heartbeats, the durability is worth one fsync), and
``replay()`` re-applies the journal into the DB on restart.  Both
target tables dedup on natural keys (``corpus_entries``
UNIQUE(campaign, cov_hash), ``campaign_events`` UNIQUE(campaign,
worker, seq, t)), so replay is idempotent: records that DID commit
before the kill are no-ops.  A torn tail line (the kill landed
mid-append) is skipped exactly like ``events.jsonl`` readers skip
theirs.

After a clean replay the journal truncates; during a run it
truncates whenever every record is known committed and the file
exceeds ``compact_bytes`` — the journal is a crash window, not a
second database.
"""

from __future__ import annotations

import base64
import json
import os
import threading
from typing import Any, Dict, Optional, Tuple

from ..utils.logging import INFO_MSG, WARNING_MSG

#: journal record kinds -> the DB call replay re-applies
KIND_CORPUS = "corpus"
KIND_EVENTS = "events"


class AdmissionJournal:
    """Append-only, fsync-per-record, torn-tail-tolerant."""

    def __init__(self, path: str, compact_bytes: int = 32 << 20):
        self.path = str(path)
        self.compact_bytes = int(compact_bytes)
        self._lock = threading.Lock()
        self._fh = None
        #: records appended since the last truncate that are NOT yet
        #: known committed to the DB (degraded-mode backlog); when it
        #: hits zero the journal is safe to compact
        self.uncommitted = 0
        self.appended_n = 0

    # -- append (the POST handlers call this BEFORE the DB write) -------

    def append_corpus(self, campaign: str, cov_hash: str, md5: str,
                      worker: str, content: bytes,
                      meta: Optional[Dict[str, Any]]) -> bool:
        return self._append({
            "kind": KIND_CORPUS, "campaign": str(campaign),
            "cov_hash": cov_hash, "md5": md5, "worker": worker,
            "content_b64": base64.b64encode(content).decode(),
            "meta": meta})

    def append_events(self, campaign: str, worker: str,
                      events: list) -> bool:
        return self._append({
            "kind": KIND_EVENTS, "campaign": str(campaign),
            "worker": worker, "events": events})

    def _append(self, rec: Dict[str, Any]) -> bool:
        """One line + flush + fsync; returns False when even the
        journal cannot be written (the caller then has NO durability
        to offer and must refuse the POST)."""
        line = json.dumps(rec, default=str) + "\n"
        with self._lock:
            try:
                if self._fh is None:
                    self._fh = open(self.path, "a")
                    # heal a torn tail before appending onto it
                    if self._fh.tell() > 0:
                        with open(self.path, "rb") as rf:
                            rf.seek(-1, os.SEEK_END)
                            if rf.read(1) != b"\n":
                                self._fh.write("\n")
                self._fh.write(line)
                self._fh.flush()
                os.fsync(self._fh.fileno())
            except (OSError, ValueError) as e:
                WARNING_MSG("admission journal append failed: %s", e)
                self._close_locked()
                return False
            self.uncommitted += 1
            self.appended_n += 1
        return True

    def note_committed(self, n: int = 1) -> None:
        """The DB write for ``n`` journaled records landed.  NEVER
        truncates: ``uncommitted`` is a plain counter, so "it hit
        zero" can coincide with another handler sitting between its
        append and its DB write — truncating here could destroy the
        only durable copy of an admission that was just ACKed
        journal-only.  The ONLY truncation path is ``replay()``,
        which holds the journal lock across read+apply+truncate, so
        every record in the file is in the DB before it goes."""
        with self._lock:
            self.uncommitted = max(0, self.uncommitted - int(n))

    def needs_compact(self) -> bool:
        """The file outgrew the cap — the API tier runs a (safe,
        lock-holding, idempotent) ``replay()`` to compact it when
        the DB is healthy."""
        try:
            return os.path.getsize(self.path) > self.compact_bytes
        except OSError:
            return False

    # -- replay (manager boot) ------------------------------------------

    def replay(self, db) -> Tuple[int, int]:
        """Re-apply every readable record into ``db`` (idempotent —
        natural-key dedup absorbs the already-committed ones), then
        truncate.  Returns (records replayed, records stored new).

        Holds the journal lock for the WHOLE read+apply+truncate:
        recovery replays run while request threads are live, and an
        append interleaved between the read and the truncate would
        be silently truncated away — losing the durability its ACK
        promised."""
        with self._lock:
            return self._replay_locked(db)

    def _replay_locked(self, db) -> Tuple[int, int]:
        replayed = stored = 0
        try:
            with open(self.path, "rb") as f:
                lines = f.read().splitlines()
        except OSError:
            return 0, 0
        db_failed = False
        for raw in lines:
            try:
                rec = json.loads(raw)
            except ValueError:
                continue                 # torn tail / corruption
            if not isinstance(rec, dict):
                continue
            try:
                kind = rec.get("kind")
                if kind == KIND_CORPUS:
                    _, new = db.add_corpus_entry(
                        rec["campaign"], rec["cov_hash"],
                        rec.get("md5", ""), rec.get("worker", "anon"),
                        base64.b64decode(rec["content_b64"]),
                        rec.get("meta"))
                    stored += int(bool(new))
                elif kind == KIND_EVENTS:
                    stored += db.add_campaign_events(
                        rec["campaign"], rec.get("worker", "anon"),
                        rec.get("events") or [])
                else:
                    continue
                replayed += 1
            except Exception as e:
                # a MALFORMED record is dropped (one bad line must
                # not wedge every boot), but a failed DB WRITE means
                # the DB is still sick — truncating now would destroy
                # the only durable copy of everything unapplied, so
                # keep the journal intact for the next recovery
                from .db import ManagerWriteError
                if isinstance(e, ManagerWriteError):
                    WARNING_MSG("journal replay aborted (DB still "
                                "failing): %s — journal kept", e)
                    db_failed = True
                    break
                WARNING_MSG("journal replay skipped a record: %s", e)
        if not db_failed:
            self.uncommitted = 0
            self._truncate_locked()
        if replayed:
            INFO_MSG("admission journal: replayed %d records "
                     "(%d stored new)", replayed, stored)
        return replayed, stored

    # -- internals ------------------------------------------------------

    def _close_locked(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None

    def _truncate_locked(self) -> None:
        self._close_locked()
        try:
            with open(self.path, "w"):
                pass
        except OSError as e:
            WARNING_MSG("journal truncate failed: %s", e)

    def close(self) -> None:
        with self._lock:
            self._close_locked()
