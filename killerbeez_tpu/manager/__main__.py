"""Manager CLI (reference python/manager/server.py parity, including
the ``--seed`` demo-row mode, server.py:13-44)."""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from ..utils.logging import INFO_MSG, setup_logging
from .api import ManagerServer


def seed_demo_rows(server: ManagerServer) -> None:
    """Populate the DB with demo rows for API testing (reference
    tests/seeds.py client_request set)."""
    db = server.db
    tid = db.create_target("corpus_test", path="corpus/build/test")
    db.set_config("driver_opts_file",
                  json.dumps({"path": "corpus/build/test",
                              "arguments": "@@"}), tid)
    db.set_config("mutator_opts_bit_flip",
                  json.dumps({"num_bits": 2}))
    db.create_job(tid, "file", "afl", "bit_flip", iterations=100,
                  seed_file="corpus/seed.bin")
    db.create_job(tid, "file", "jit_harness", "havoc", iterations=4096,
                  instrumentation_opts=json.dumps({"target": "test"}))
    INFO_MSG("seeded demo rows: 1 target, 2 configs, 2 jobs")


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="killerbeez-tpu-manager",
        description="distributed fuzzing manager (REST + work queue)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8650)
    p.add_argument("--db", default=":memory:",
                   help="sqlite path (default in-memory)")
    p.add_argument("--seed", action="store_true",
                   help="insert demo rows before serving")
    p.add_argument("-l", "--logging-options")
    args = p.parse_args(argv)
    setup_logging(args.logging_options)
    server = ManagerServer(args.host, args.port, args.db)
    if args.seed:
        seed_demo_rows(server)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
