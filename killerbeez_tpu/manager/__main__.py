"""Manager CLI (reference python/manager/server.py parity, including
the ``--seed`` demo-row mode, server.py:13-44)."""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from ..utils.logging import INFO_MSG, setup_logging
from .api import ManagerServer
from .fleet import FleetConfig


def seed_demo_rows(server: ManagerServer) -> None:
    """Populate the DB with demo rows for API testing (reference
    tests/seeds.py client_request set)."""
    db = server.db
    tid = db.create_target("corpus_test", path="corpus/build/test")
    db.set_config("driver_opts_file",
                  json.dumps({"path": "corpus/build/test",
                              "arguments": "@@"}), tid)
    db.set_config("mutator_opts_bit_flip",
                  json.dumps({"num_bits": 2}))
    db.create_job(tid, "file", "afl", "bit_flip", iterations=100,
                  seed_file="corpus/seed.bin")
    db.create_job(tid, "file", "jit_harness", "havoc", iterations=4096,
                  instrumentation_opts=json.dumps({"target": "test"}))
    INFO_MSG("seeded demo rows: 1 target, 2 configs, 2 jobs")


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="killerbeez-tpu-manager",
        description="distributed fuzzing manager (REST + work queue)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8650)
    p.add_argument("--db", default=":memory:",
                   help="sqlite path (default in-memory); file-backed "
                        "DBs run WAL + busy-timeout with locked-write "
                        "retry")
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="write-ahead admission journal path (default "
                        "<db>.journal for file-backed DBs, off for "
                        "in-memory): corpus/event POSTs are "
                        "journaled+fsynced before the DB write and "
                        "replayed on restart, so a manager SIGKILL "
                        "loses zero ACKed admissions and a failed DB "
                        "write degrades to journal-backed read-only "
                        "mode instead of 500ing the fleet")
    p.add_argument("--seed", action="store_true",
                   help="insert demo rows before serving")
    fl = p.add_argument_group(
        "fleet observatory",
        "worker health / alert thresholds (manager/fleet.py); the "
        "monitor classifies heartbeating workers, persists fleet "
        "time-series and serves /api/fleet + /metrics")
    fl.add_argument("--stale-after", type=float, default=15.0,
                    help="seconds without a heartbeat before a "
                         "worker reads stale (default 15)")
    fl.add_argument("--dead-after", type=float, default=60.0,
                    help="seconds before stale escalates to dead "
                         "(default 60)")
    fl.add_argument("--monitor-interval", type=float, default=2.0,
                    help="health/alert evaluation cadence in seconds "
                         "(default 2; 0 disables the monitor)")
    fl.add_argument("--series-interval", type=float, default=10.0,
                    help="seconds between persisted fleet time-"
                         "series samples (default 10)")
    fl.add_argument("--series-max-rows", type=int, default=20000,
                    help="newest fleet time-series samples kept per "
                         "campaign, oldest pruned (default 20000 "
                         "~= 2.3 days at the default interval; 0 = "
                         "unbounded)")
    fl.add_argument("--plateau-after", type=float, default=300.0,
                    help="fleet_plateau alert: seconds without a "
                         "fleet-wide new path (default 300)")
    fl.add_argument("--stall-after", type=float, default=900.0,
                    help="coverage_stall alert: paths flat this "
                         "long while execs advance (default 900)")
    fl.add_argument("--crash-spike-count", type=int, default=10,
                    help="crash_spike alert: unique crashes inside "
                         "the window (default 10)")
    fl.add_argument("--crash-spike-window", type=float, default=60.0,
                    help="crash_spike trailing window seconds "
                         "(default 60)")
    fl.add_argument("--drops-window", type=float, default=120.0,
                    help="findings_drop alert: active while the "
                         "fleet's findings_ring_drops counter moved "
                         "within this many seconds (--generations "
                         "ring overflow under-reports findings; "
                         "default 120)")
    fl.add_argument("--retire-after", type=float, default=86400.0,
                    help="seconds after a worker's last heartbeat "
                         "before its registry row + snapshot retire "
                         "entirely (finished campaigns stop alerting "
                         "and /metrics cardinality stays bounded; "
                         "default 86400 = 1 day, 0 = never)")
    p.add_argument("-l", "--logging-options")
    args = p.parse_args(argv)
    setup_logging(args.logging_options)
    fleet = FleetConfig(
        stale_after=args.stale_after, dead_after=args.dead_after,
        monitor_interval=args.monitor_interval,
        series_interval=args.series_interval,
        series_max_rows=args.series_max_rows,
        plateau_after=args.plateau_after,
        stall_after=args.stall_after,
        crash_spike_count=args.crash_spike_count,
        crash_spike_window=args.crash_spike_window,
        drops_window=args.drops_window,
        retire_after=args.retire_after)
    server = ManagerServer(args.host, args.port, args.db, fleet=fleet,
                           journal_path=args.journal)
    if args.seed:
        seed_demo_rows(server)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
