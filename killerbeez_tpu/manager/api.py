"""Manager REST API — stdlib ThreadingHTTPServer.

Route parity with the reference manager (python/manager/app/
__init__.py:38-53: Job, Results, Target, Config, File, Minimize),
plus the work-queue routes that replace BOINC's scheduler
(SURVEY §2.8, §3.5):

    POST /api/target                 {name, platform, path} -> {id}
    GET  /api/target                 -> [targets]
    POST /api/config                 {name, value, target_id?}
    GET  /api/config?name=&target_id= -> {value}
    POST /api/job                    {target_id, driver, ...} -> {id, cmdline}
    GET  /api/job[?status=]          -> [jobs]
    GET  /api/job/<id>               -> job
    GET  /api/job/<id>/results       -> [results]
    POST /api/job/<id>/results       {result_type, repro_file}
    GET  /api/results                -> [results]
    POST /api/file                   {name, content_b64} -> {id}
    GET  /api/file/<id>              -> raw bytes
    POST /api/state                  {target_id, state} -> {id}
    GET  /api/state/<target_id>      -> [states]
    POST /api/tracer_info            {target_id, input_file, edges}
    POST /api/minimize               {target_id} -> {working_set}
    POST /api/work/claim             {worker} -> job+cmdline | 204
    POST /api/work/<id>/finish       {status, mutator_state?}
    POST /api/stats/<campaign>       {worker, snapshot}  (heartbeat)
    GET  /api/stats/<campaign>       -> {merged, workers, n_workers}
    POST /api/corpus/<campaign>      {worker, md5, cov_hash,
                                      content_b64, meta} -> {id, new}
    GET  /api/corpus/<campaign>?since=&exclude=
                                     -> {entries, latest}
    POST /api/events/<campaign>      {worker, events} -> {stored}
    GET  /api/events/<campaign>?since=<id>
                                     -> {events, latest}
    POST /api/peers/<campaign>       {worker, endpoint} -> {peers}
                                     (gossip registration + directory
                                      in one round trip)
    GET  /api/peers/<campaign>[?exclude=] -> {peers}
    GET  /api/health                 -> {ok, degraded, journal}

Durability: admission POSTs (corpus, events) append to the
write-ahead journal BEFORE the DB write and the journal replays on
restart, so a manager SIGKILL between ACK and commit loses nothing.
A DB write failure (ENOSPC, lock convoy) flips the manager into
READ-ONLY DEGRADED mode: cursor GETs keep serving, journal-backed
admission POSTs still ACK (``journaled: true``), and everything else
returns 503 with ``degraded: true`` instead of tearing down the
fleet's sync rounds.  The first successful write clears the latch.

plus the fleet observatory (manager/fleet.py):

    GET  /api/fleet                  -> {campaigns: {name: counts}}
    GET  /api/fleet/<campaign>       -> worker health + merged stats
                                        + alert states
    GET  /api/fleet/<campaign>/series?since=<id>[&limit=][&format=plot]
                                     -> {samples, latest} | plot_data
    GET  /metrics                    -> OpenMetrics exposition
                                        (Prometheus scrape surface)
"""

from __future__ import annotations

import base64
import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..telemetry import merge
from ..telemetry.openmetrics import CONTENT_TYPE as _OM_CTYPE
from ..tools.minimize import greedy_edge_cover
from ..utils.logging import INFO_MSG, WARNING_MSG
from .db import ManagerDB, ManagerWriteError
from .fleet import (
    FleetConfig, FleetMonitor, fleet_index, fleet_view,
    peer_directory, render_fleet_metrics,
)
from .fuzzer_cmd import format_cmdline
from .journal import AdmissionJournal


class _Handler(BaseHTTPRequestHandler):
    db: ManagerDB  # set by ManagerServer
    fleet_config: FleetConfig
    monitor: Optional[FleetMonitor] = None
    journal: Optional[AdmissionJournal] = None

    # -- plumbing -------------------------------------------------------

    def log_message(self, fmt, *args):  # quiet; manager logs itself
        pass

    def _json(self, code: int, obj: Any) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _bytes(self, code: int, data: bytes,
               ctype: str = "application/octet-stream") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _body(self) -> Dict[str, Any]:
        n = int(self.headers.get("Content-Length") or 0)
        if n == 0:
            return {}
        return json.loads(self.rfile.read(n).decode())

    def _route(self, method: str) -> None:
        parsed = urlparse(self.path)
        path, query = parsed.path, parse_qs(parsed.query)
        try:
            for pattern, methods in _ROUTES:
                m = re.fullmatch(pattern, path)
                if m and method in methods:
                    methods[method](self, query, *m.groups())
                    return
            self._json(404, {"error": f"no route {method} {path}"})
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            self._json(400, {"error": str(e)})
        except ManagerWriteError as e:
            # read-only degraded mode: a failed DB write must not
            # tear the connection down as an opaque 500 — the worker
            # backs off on a clean, classified signal instead
            self._json(503, {"error": f"manager write-degraded: {e}",
                             "degraded": True})

    def do_GET(self):
        self._route("GET")

    def do_POST(self):
        self._route("POST")

    def _recover_journal(self) -> None:
        """DB writes are succeeding again: replay any journal-only
        backlog ACKed during the degraded window NOW, not at some
        future restart — cursor GETs must start serving those rows
        as soon as the disk lets them land.  Gated on the one-shot
        degraded->healthy transition (``consume_recovery``), NOT on
        the raw uncommitted counter: that counter is transiently
        nonzero whenever any concurrent POST sits between its append
        and its DB write, and gating on it would re-replay the whole
        journal inline in handler threads under perfectly healthy
        load.  The same (lock-holding, idempotent) replay doubles as
        compaction when the file outgrows its cap."""
        j = self.journal
        if j is None or self.db.degraded:
            return
        if not (self.db.consume_recovery() or j.needs_compact()):
            return
        try:
            j.replay(self.db)
        except Exception as e:
            WARNING_MSG("in-process journal recovery failed "
                        "(kept for restart): %s", e)

    # -- handlers -------------------------------------------------------

    def h_target(self, query):
        if self.command == "POST":
            b = self._body()
            tid = self.db.create_target(b["name"],
                                        b.get("platform", "linux_x86_64"),
                                        b.get("path", ""))
            self._json(201, {"id": tid})
        else:
            self._json(200, self.db.get_targets())

    def h_config(self, query):
        if self.command == "POST":
            b = self._body()
            self.db.set_config(b["name"], b["value"], b.get("target_id"))
            self._json(201, {"ok": True})
        else:
            name = query["name"][0]
            tid = int(query["target_id"][0]) if "target_id" in query \
                else None
            self._json(200, {"value": self.db.lookup_config(name, tid)})

    def h_job_collection(self, query):
        if self.command == "POST":
            b = self._body()
            jid = self.db.create_job(
                int(b["target_id"]), b["driver"], b["instrumentation"],
                b["mutator"], int(b.get("iterations", 1000)),
                b.get("seed_file", ""),
                driver_opts=b.get("driver_opts"),
                instrumentation_opts=b.get("instrumentation_opts"),
                mutator_opts=b.get("mutator_opts"),
                mutator_state=b.get("mutator_state"))
            job = self.db.get_job(jid)
            target = self.db.get_target(job["target_id"]) or {}
            self._json(201, {
                "id": jid,
                "cmdline": format_cmdline(
                    job, target.get("platform", "linux_x86_64")),
            })
        else:
            status = query.get("status", [None])[0]
            self._json(200, self.db.get_jobs(status))

    def h_job(self, query, job_id):
        job = self.db.get_job(int(job_id))
        if job is None:
            self._json(404, {"error": f"no job {job_id}"})
        else:
            self._json(200, job)

    def h_job_results(self, query, job_id):
        if self.command == "POST":
            b = self._body()
            rid = self.db.add_result(int(job_id), b["result_type"],
                                     b["repro_file"],
                                     b.get("crash_info"))
            self._json(201, {"id": rid})
        else:
            self._json(200, self.db.get_results(int(job_id)))

    def h_results(self, query):
        self._json(200, self.db.get_results())

    def h_file_collection(self, query):
        b = self._body()
        fid = self.db.add_file(
            b["name"], base64.b64decode(b["content_b64"]))
        self._json(201, {"id": fid})

    def h_file(self, query, file_id):
        row = self.db.get_file(int(file_id))
        if row is None:
            self._json(404, {"error": f"no file {file_id}"})
        else:
            self._bytes(200, row["content"])

    def h_state_collection(self, query):
        b = self._body()
        sid = self.db.add_instrumentation_state(int(b["target_id"]),
                                                b["state"])
        self._json(201, {"id": sid})

    def h_state(self, query, target_id):
        self._json(200, self.db.get_instrumentation_states(
            int(target_id)))

    def h_tracer_info(self, query):
        b = self._body()
        self.db.add_tracer_info(int(b["target_id"]), b["input_file"],
                                list(b["edges"]))
        self._json(201, {"ok": True})

    def h_minimize(self, query):
        """Greedy edge-cover working set from tracer_info rows
        (reference controller/Minimize.py:10-40)."""
        b = self._body()
        info = self.db.get_tracer_info(int(b["target_id"]))
        kept = greedy_edge_cover({k: set(v) for k, v in info.items()})
        self._json(200, {"working_set": kept,
                         "total_inputs": len(info)})

    def h_stats(self, query, campaign):
        """Worker heartbeat sink + fleet view: POST stores one
        worker's cumulative registry snapshot (latest wins), GET
        returns the telemetry.aggregate merge of every worker's
        newest snapshot plus per-worker freshness — the
        afl-whatsup-style campaign rollup."""
        if self.command == "POST":
            b = self._body()
            worker = b.get("worker", "anon")
            self.db.upsert_campaign_stats(campaign, worker,
                                          b["snapshot"])
            # health registry: the heartbeat IS the liveness signal;
            # a stale/dead worker beating again flips back to healthy
            # and the revival lands in the campaign event stream
            prev = self.db.note_fleet_worker(campaign, worker,
                                             meta=b.get("meta"))
            if prev in ("stale", "dead"):
                self.db.add_manager_event(campaign, "worker_returned",
                                          worker=worker,
                                          previous=prev)
            # heartbeats are the fleet's steady write pulse: the
            # first one to land after a degraded window drains the
            # journal backlog even if no admission POST follows
            self._recover_journal()
            self._json(201, {"ok": True})
            return
        rows = self.db.get_campaign_stats(campaign)
        self._json(200, {
            "campaign": campaign,
            "n_workers": len(rows),
            "workers": {r["worker"]: {"updated": r["updated"]}
                        for r in rows},
            "merged": merge([r["snapshot"] for r in rows]),
        })

    def h_corpus(self, query, campaign):
        """Fleet corpus exchange: POST stores one edge-novel entry
        (deduped by coverage hash — two workers hitting the same
        frontier store one row; the duplicate POST gets
        ``new: false``), GET returns entries newer than the caller's
        cursor so workers pull only each other's fresh findings.

        The POST journals BEFORE the DB write (a SIGKILL between the
        201 and the commit replays on restart), and a failed DB write
        still ACKs off the journal alone (``journaled: true``) — the
        ACK is the promise the fleet's reject rule depends on, so it
        must be backed by SOMETHING durable or refused outright."""
        if self.command == "POST":
            b = self._body()
            content = base64.b64decode(b["content_b64"])
            worker = b.get("worker", "anon")
            journaled = (self.journal is not None and
                         self.journal.append_corpus(
                             campaign, b["cov_hash"],
                             b.get("md5", ""), worker, content,
                             b.get("meta")))
            try:
                rid, new = self.db.add_corpus_entry(
                    campaign, b["cov_hash"], b.get("md5", ""),
                    worker, content, b.get("meta"))
            except ManagerWriteError as e:
                if not journaled:
                    raise               # nothing durable: honest 503
                WARNING_MSG("corpus POST held in journal only "
                            "(degraded): %s", e)
                self._json(201, {"id": None, "new": True,
                                 "journaled": True, "degraded": True})
                return
            if journaled:
                self.journal.note_committed()
            self._recover_journal()
            self._json(201 if new else 200, {"id": rid, "new": new})
            return
        since = int(query.get("since", ["0"])[0])
        exclude = query.get("exclude", [None])[0]
        rows = self.db.get_corpus_entries(campaign, since, exclude)
        latest = max((r["id"] for r in rows),
                     default=self.db.corpus_latest_id(campaign))
        self._json(200, {
            "campaign": campaign,
            "latest": latest,
            "entries": [{
                "id": r["id"], "md5": r["md5"],
                "cov_hash": r["cov_hash"], "worker": r["worker"],
                "content_b64":
                    base64.b64encode(r["content"]).decode(),
                "meta": r.get("meta"),
            } for r in rows],
        })

    def h_events(self, query, campaign):
        """Fleet event-log exchange (the flight recorder's terminal
        tier): POST stores a worker's forwarded event records (deduped
        by the worker's own monotone seq — a retried heartbeat window
        stores once), GET returns events newer than the caller's
        server-id cursor, mirroring ``/api/corpus`` semantics."""
        if self.command == "POST":
            b = self._body()
            worker = b.get("worker", "anon")
            events = b.get("events") or []
            journaled = (self.journal is not None and
                         self.journal.append_events(campaign, worker,
                                                    events))
            try:
                n = self.db.add_campaign_events(campaign, worker,
                                                events)
            except ManagerWriteError as e:
                if not journaled:
                    raise
                WARNING_MSG("events POST held in journal only "
                            "(degraded): %s", e)
                self._json(201, {"stored": len(events),
                                 "journaled": True, "degraded": True})
                return
            if journaled:
                self.journal.note_committed()
            self._recover_journal()
            self._json(201, {"stored": n})
            return
        since = int(query.get("since", ["0"])[0])
        rows = self.db.get_campaign_events(campaign, since)
        latest = max((r["id"] for r in rows),
                     default=self.db.events_latest_id(campaign))
        self._json(200, {
            "campaign": campaign,
            "latest": latest,
            "events": rows,
        })

    def h_peers(self, query, campaign):
        """Gossip peer directory: POST registers this worker's
        sidecar endpoint (into the SAME health registry heartbeats
        feed — a peer is live exactly when its worker is) and returns
        the current directory in the same response, so one round trip
        both advertises and discovers.  GET serves the directory
        read-only.  Dead workers drop out of the directory the same
        way they drop out of /api/fleet."""
        exclude = None
        if self.command == "POST":
            b = self._body()
            worker = str(b.get("worker", "anon"))
            endpoint = b.get("endpoint")
            if not isinstance(endpoint, str) or \
                    not endpoint.startswith("http") or \
                    len(endpoint) > 512:
                self._json(400, {"error": "bad gossip endpoint"})
                return
            exclude = worker
            try:
                self.db.note_fleet_worker(campaign, worker,
                                          meta={"gossip": endpoint})
            except ManagerWriteError as e:
                # registration is best-effort: a write-degraded
                # manager still serves the directory it has — the
                # phone book must outlive the pen
                WARNING_MSG("peer registration write failed "
                            "(degraded): %s", e)
        else:
            exclude = query.get("exclude", [None])[0]
        self._json(201 if self.command == "POST" else 200, {
            "campaign": campaign,
            "degraded": self.db.degraded,
            "peers": peer_directory(self.db, self.fleet_config,
                                    campaign, exclude=exclude),
        })

    def h_health(self, query):
        """Liveness + degraded-mode probe (kb-fleet, load balancers,
        the fleet-sim harness)."""
        j = self.journal
        self._json(200, {
            "ok": True,
            "degraded": self.db.degraded,
            "write_failures": self.db.write_failures,
            "journal": ({"appended": j.appended_n,
                         "uncommitted": j.uncommitted}
                        if j is not None else None),
        })

    # -- fleet observatory ---------------------------------------------

    def h_fleet_index(self, query):
        self._json(200, fleet_index(self.db, self.fleet_config))

    def h_fleet(self, query, campaign):
        """Worker health registry view: live-classified statuses,
        per-worker stat summaries, the merged fleet snapshot and the
        alert evaluator's current states."""
        self._json(200, fleet_view(self.db, self.fleet_config,
                                   campaign, self.monitor))

    def h_fleet_series(self, query, campaign):
        """Fleet time-series, cursor GET like ``/api/events``;
        ``format=plot`` renders the afl-plot-compatible fleet-wide
        plot_data CSV instead of JSON."""
        since = int(query.get("since", ["0"])[0])
        limit = int(query.get("limit", ["0"])[0])
        rows = self.db.get_fleet_series(campaign, since, limit)
        if query.get("format", [None])[0] == "plot":
            lines = ["# unix_time, execs_done, paths_total, crashes, "
                     "unique_crashes, hangs, unique_hangs, "
                     "corpus_count, execs_per_sec, n_workers"]
            for s in rows:
                lines.append(", ".join(str(v) for v in (
                    int(s.get("t", 0)), int(s.get("execs", 0)),
                    int(s.get("new_paths", 0)),
                    int(s.get("crashes", 0)),
                    int(s.get("unique_crashes", 0)),
                    int(s.get("hangs", 0)),
                    int(s.get("unique_hangs", 0)),
                    int(s.get("corpus_seen", 0)),
                    round(float(s.get("execs_per_sec_ema", 0.0)), 2),
                    int(s.get("n_workers", 0)))))
            self._bytes(200, ("\n".join(lines) + "\n").encode(),
                        ctype="text/plain; charset=utf-8")
            return
        # (not max()'s default= — that expression is evaluated
        # eagerly, costing a discarded MAX(id) query on every page)
        latest = (max(s["id"] for s in rows) if rows
                  else self.db.fleet_series_latest_id(campaign))
        self._json(200, {"campaign": campaign, "latest": latest,
                         "samples": rows})

    def h_metrics(self, query):
        """OpenMetrics exposition over every known campaign — the
        Prometheus scrape surface (conformance pinned in CI by the
        test suite's strict parser)."""
        text = render_fleet_metrics(self.db, self.fleet_config,
                                    self.monitor)
        self._bytes(200, text.encode(), ctype=_OM_CTYPE)

    def h_work_claim(self, query):
        b = self._body()
        job = self.db.claim_job(b.get("worker", "anon"))
        if job is None:
            self._bytes(204, b"")
            return
        target = self.db.get_target(job["target_id"]) or {}
        job["cmdline"] = format_cmdline(
            job, target.get("platform", "linux_x86_64"))
        self._json(200, job)

    def h_work_finish(self, query, job_id):
        b = self._body()
        self.db.finish_job(int(job_id), b.get("status", "done"),
                           b.get("mutator_state"))
        self._json(200, {"ok": True})


_ROUTES: Tuple = (
    (r"/api/target", {"GET": _Handler.h_target,
                      "POST": _Handler.h_target}),
    (r"/api/config", {"GET": _Handler.h_config,
                      "POST": _Handler.h_config}),
    (r"/api/job", {"GET": _Handler.h_job_collection,
                   "POST": _Handler.h_job_collection}),
    (r"/api/job/(\d+)", {"GET": _Handler.h_job}),
    (r"/api/job/(\d+)/results", {"GET": _Handler.h_job_results,
                                 "POST": _Handler.h_job_results}),
    (r"/api/results", {"GET": _Handler.h_results}),
    (r"/api/file", {"POST": _Handler.h_file_collection}),
    (r"/api/file/(\d+)", {"GET": _Handler.h_file}),
    (r"/api/state", {"POST": _Handler.h_state_collection}),
    (r"/api/state/(\d+)", {"GET": _Handler.h_state}),
    (r"/api/tracer_info", {"POST": _Handler.h_tracer_info}),
    (r"/api/stats/([\w.-]+)", {"GET": _Handler.h_stats,
                               "POST": _Handler.h_stats}),
    (r"/api/corpus/([\w.-]+)", {"GET": _Handler.h_corpus,
                                "POST": _Handler.h_corpus}),
    (r"/api/events/([\w.-]+)", {"GET": _Handler.h_events,
                                "POST": _Handler.h_events}),
    (r"/api/peers/([\w.-]+)", {"GET": _Handler.h_peers,
                               "POST": _Handler.h_peers}),
    (r"/api/health", {"GET": _Handler.h_health}),
    (r"/api/fleet", {"GET": _Handler.h_fleet_index}),
    (r"/api/fleet/([\w.-]+)", {"GET": _Handler.h_fleet}),
    (r"/api/fleet/([\w.-]+)/series", {"GET": _Handler.h_fleet_series}),
    (r"/metrics", {"GET": _Handler.h_metrics}),
    (r"/api/minimize", {"POST": _Handler.h_minimize}),
    (r"/api/work/claim", {"POST": _Handler.h_work_claim}),
    (r"/api/work/(\d+)/finish", {"POST": _Handler.h_work_finish}),
)


class ManagerServer:
    """Owns the HTTP server + DB; start()/stop() for embedding in
    tests, serve_forever() for the CLI."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8650,
                 db_path: str = ":memory:",
                 fleet: Optional[FleetConfig] = None,
                 journal_path: Optional[str] = None):
        self.db = ManagerDB(db_path)
        self.fleet_config = fleet or FleetConfig()
        # write-ahead admission journal: defaults on for file-backed
        # DBs (<db>.journal — durability should not be opt-in), off
        # for in-memory managers unless a path is given; REPLAYED
        # into the DB before the first request so a SIGKILL'd
        # manager restarts with every ACKed POST present
        if journal_path is None and db_path != ":memory:":
            journal_path = db_path + ".journal"
        self.journal: Optional[AdmissionJournal] = None
        if journal_path:
            self.journal = AdmissionJournal(journal_path)
            self.journal.replay(self.db)
        #: the observatory evaluator; its thread only starts with the
        #: server (monitor_interval <= 0 keeps it manual-tick-only —
        #: tests drive tick() deterministically)
        self.monitor = FleetMonitor(self.db, self.fleet_config)
        handler = type("BoundHandler", (_Handler,),
                       {"db": self.db,
                        "fleet_config": self.fleet_config,
                        "monitor": self.monitor,
                        "journal": self.journal})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self._serving = False

    def _start_monitor(self) -> None:
        if self.fleet_config.monitor_interval > 0 \
                and not self.monitor.is_alive():
            self.monitor.start()

    def start(self) -> None:
        self._start_monitor()
        self._serving = True
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        INFO_MSG("manager listening on :%d", self.port)

    def serve_forever(self) -> None:
        self._start_monitor()
        self._serving = True
        INFO_MSG("manager listening on :%d", self.port)
        self.httpd.serve_forever()

    def stop(self) -> None:
        self.monitor.stop()
        if self._serving:
            # shutdown() on a server whose serve_forever never ran
            # blocks forever (stdlib event handshake) — a constructed-
            # but-never-started server just closes its socket
            self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        if self.journal is not None:
            self.journal.close()
        self.db.close()
