"""Command-line synthesis for fuzzing jobs.

Parity with the reference's lib/fuzzer.py:59-95 ``format_cmdline``:
build the client invocation ``driver instrumentation mutator -sf seed
-n N [-d ..][-i ..][-m ..]`` with shell escaping per platform
(sh/bat, lib/fuzzer.py:15-53). Jobs stay reproducible shell commands
— an operator can paste a job row into a terminal.
"""

from __future__ import annotations

import shlex
from typing import Any, Dict, Optional


def _escape_sh(s: str) -> str:
    return shlex.quote(s)


def _escape_bat(s: str) -> str:
    """cmd.exe-style quoting (reference bat escaping): wrap in double
    quotes, double embedded double quotes."""
    return '"' + s.replace('"', '""') + '"'


def format_cmdline(job: Dict[str, Any], platform: str = "linux_x86_64",
                   program: str = "python -m killerbeez_tpu.fuzzer",
                   seed_file: Optional[str] = None) -> str:
    """Render a job row (db.py jobs schema) as an executable command."""
    esc = _escape_bat if platform.startswith("windows") else _escape_sh
    parts = [program, job["driver"], job["instrumentation"],
             job["mutator"]]
    seed = seed_file or job.get("seed_file")
    if seed:
        parts += ["-sf", esc(seed)]
    parts += ["-n", str(int(job.get("iterations", 1000)))]
    for flag, key in (("-d", "driver_opts"),
                      ("-i", "instrumentation_opts"),
                      ("-m", "mutator_opts"),
                      ("-msf", "mutator_state_file"),
                      ("-isf", "instrumentation_state_file")):
        val = job.get(key)
        if val:
            parts += [flag, esc(val)]
    return " ".join(parts)
