"""Abstract interpretation over the KBVM's 8-register ISA.

Two cheap analyses run in one fixpoint over the instruction graph:

* **constant propagation** — registers start at 0 and most target
  code builds compare operands with OP_LDI/OP_ADDI, so branch
  operands are very often statically known;
* **input-byte taint** — OP_LDB introduces taint (the set of input
  byte indices a value may depend on); OP_ALU/OP_ADDI propagate it;
  stores fold it into a single memory-summary taint set.

The combination yields exactly what byte-level guidance needs
statically: for each OP_BR, *which input bytes* the comparison
depends on and *which constant* guards it.  Angora buys this with
dynamic taint tracking at significant runtime cost (PAPERS.md); the
KBVM tier reads it off the program text.  Downstream consumers:

* ``extract_dictionary`` — branch-comparison constants as an
  automatic dictionary for the ``dictionary`` mutator (magic bytes,
  opcode bytes, length fields), with runs of consecutive
  single-byte-position compares merged into multi-byte tokens
  (``expect_byte`` chains become whole magic strings);
* lint — statically-dead blocks (CFG-reachable but unreachable once
  constants fold branches) and must-crash blocks (every path from
  the block head crashes: OP_CRASH, or LDM/STM with a known
  out-of-bounds index).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

import numpy as np

from ..models.vm import (
    ALU_ADD, ALU_AND, ALU_MUL, ALU_OR, ALU_SHL, ALU_SHR, ALU_SUB,
    ALU_XOR, CMP_EQ, CMP_GE, CMP_LT, CMP_NE, N_REGS,
    OP_ALU, OP_ADDI, OP_BLOCK, OP_BR, OP_CRASH, OP_HALT, OP_JMP,
    OP_LDB, OP_LDI, OP_LDM, OP_LEN, OP_STM,
)
from .cfg import instr_successors

CMP_NAMES = {CMP_EQ: "eq", CMP_NE: "ne", CMP_LT: "lt", CMP_GE: "ge"}

#: taint lattice top: "may depend on any input byte"
ANY = None

#: internal taint marker for OP_LEN results ("depends on the input
#: LENGTH, not on any byte value").  It rides the same frozensets as
#: byte indices during the fixpoint but is STRIPPED before facts are
#: published: ``BranchFact.deps`` still names byte positions only
#: (every downstream consumer — focus masks, dictionary runs, the
#: solver — indexes buffers with them), and the length dependency
#: surfaces as ``BranchFact.len_dep`` for the grammar auto-deriver.
_LEN_TAINT = -1

# an abstract register value: (const, taint)
#   const: int (known exact value) or None (unknown)
#   taint: frozenset of input byte indices, or ANY (= None)
_ZERO = (0, frozenset())
_UNKNOWN = (None, ANY)


def _i32(v: int) -> int:
    v &= 0xFFFFFFFF
    return v - (1 << 32) if v & 0x80000000 else v


def _reg(field: int) -> int:
    """Direct register fields follow the engine's ``jnp.clip(field,
    0, N_REGS - 1)``; packed subfields (ALU's rb, BR's cmp rb) are
    masked at extraction instead — matching vm._step exactly keeps
    analysis facts true even for malformed programs."""
    return min(max(field, 0), N_REGS - 1)


def _alu_const(sel: int, x: int, y: int) -> Optional[int]:
    """Exact int32 semantics of vm._step's ALU select."""
    ux, uy = x & 0xFFFFFFFF, y & 0xFFFFFFFF
    if sel == ALU_ADD:
        return _i32(x + y)
    if sel == ALU_SUB:
        return _i32(x - y)
    if sel == ALU_AND:
        return _i32(ux & uy)
    if sel == ALU_OR:
        return _i32(ux | uy)
    if sel == ALU_XOR:
        return _i32(ux ^ uy)
    s = min(max(y, 0), 31)
    if sel == ALU_SHL:
        return _i32(ux << s)
    if sel == ALU_SHR:
        return _i32(ux >> s)
    if sel == ALU_MUL:
        return _i32(x * y)
    return None


def _join_taint(a, b):
    if a is ANY or b is ANY:
        return ANY
    return a | b


def _join_val(a, b):
    const = a[0] if a[0] == b[0] else None
    return (const, _join_taint(a[1], b[1]))


def _join_state(a, b):
    if a is None:
        return b
    regs = tuple(_join_val(x, y) for x, y in zip(a[0], b[0]))
    return (regs, _join_taint(a[1], b[1]))


@dataclass(frozen=True)
class BranchFact:
    """One OP_BR as the abstract interpreter saw it."""
    pc: int
    block: int                      # nearest preceding block (-1 = entry)
    cmp: str                        # eq / ne / lt / ge
    #: comparison constant guarding the branch, when one side is a
    #: known constant and the other side is input-tainted
    const: Optional[int]
    #: input byte indices the comparison may depend on (ANY = unknown)
    deps: Optional[FrozenSet[int]]
    #: statically decided outcome (both sides constant), else None
    always: Optional[bool]
    #: True when the comparison may depend on the input LENGTH
    #: (OP_LEN taint) — the grammar auto-deriver's length-field
    #: signal; byte-position consumers keep reading ``deps``
    len_dep: bool = False


@dataclass
class DataflowResult:
    branches: List[BranchFact]
    reached_pcs: Set[int]
    #: blocks the CFG can reach but constant folding proves dead
    dead_blocks: Set[int] = field(default_factory=set)
    #: blocks from whose head EVERY path crashes
    must_crash_blocks: Set[int] = field(default_factory=set)
    #: pcs that crash unconditionally when executed (OP_CRASH, or a
    #: memory op with a known out-of-bounds index)
    crash_pcs: Set[int] = field(default_factory=set)


def analyze_dataflow(program) -> DataflowResult:
    instrs = np.asarray(program.instrs)
    ni = instrs.shape[0]
    mem_size = int(program.mem_size)
    rows = [tuple(int(x) for x in instrs[pc]) for pc in range(ni)]

    # nearest preceding OP_BLOCK, for human-facing reports
    block_of_pc: List[int] = []
    cur = -1
    for pc in range(ni):
        if rows[pc][0] == OP_BLOCK:
            cur += 1
        block_of_pc.append(cur)

    state_in: Dict[int, tuple] = {}
    worklist: List[int] = []
    if ni:
        state_in[0] = (tuple(_ZERO for _ in range(N_REGS)), frozenset())
        worklist.append(0)

    def flow(pc: int, st: tuple) -> None:
        prev = state_in.get(pc)
        joined = _join_state(prev, st)
        if joined != prev:
            state_in[pc] = joined
            worklist.append(pc)

    def transfer(pc: int, st: tuple):
        """Returns [(succ_pc, out_state)] for in-range successors."""
        regs, mem_taint = st
        op, a, b, c = rows[pc]
        out_regs = list(regs)
        if op == OP_LDB:
            idx_c, idx_t = regs[_reg(b)]
            if idx_c is not None and idx_c < 0:
                out_regs[_reg(a)] = (0, frozenset())
            else:
                taint = frozenset([idx_c]) if idx_c is not None else ANY
                out_regs[_reg(a)] = \
                    (None, _join_taint(taint, idx_t))
        elif op == OP_LDI:
            out_regs[_reg(a)] = (_i32(b), frozenset())
        elif op == OP_ALU:
            sel = c & 7
            xc, xt = regs[_reg(b)]
            yc, yt = regs[(c >> 3) & (N_REGS - 1)]
            const = _alu_const(sel, xc, yc) \
                if xc is not None and yc is not None else None
            out_regs[_reg(a)] = (const, _join_taint(xt, yt))
        elif op == OP_ADDI:
            xc, xt = regs[_reg(b)]
            const = _i32(xc + c) if xc is not None else None
            out_regs[_reg(a)] = (const, xt)
        elif op == OP_LEN:
            out_regs[_reg(a)] = (None, frozenset({_LEN_TAINT}))
        elif op == OP_LDM:
            out_regs[_reg(a)] = (None, mem_taint)
        elif op == OP_STM:
            mem_taint = _join_taint(mem_taint,
                                    regs[_reg(b)][1])
        new_st = (tuple(out_regs), mem_taint)

        if op == OP_BR:
            xc, _ = regs[_reg(a)]
            yc, _ = regs[(b >> 2) & (N_REGS - 1)]
            taken = _fold_cmp(b & 3, xc, yc)
            succs = instr_successors(instrs, pc)  # [target, pc + 1]
            if taken is True:
                succs = succs[:1]
            elif taken is False:
                succs = succs[1:]
            return [(s, new_st) for s in succs if 0 <= s < ni]
        return [(s, new_st) for s in instr_successors(instrs, pc)
                if 0 <= s < ni]

    while worklist:
        pc = worklist.pop()
        for s, out in transfer(pc, state_in[pc]):
            flow(s, out)

    # -- branch facts over the final in-states ------------------------
    branches: List[BranchFact] = []
    for pc in sorted(state_in):
        op, a, b, c = rows[pc]
        if op != OP_BR:
            continue
        regs, _ = state_in[pc]
        (xc, xt) = regs[_reg(a)]
        (yc, yt) = regs[(b >> 2) & (N_REGS - 1)]
        always = _fold_cmp(b & 3, xc, yc)
        const = None
        if xc is not None and yc is None:
            const = xc
        elif yc is not None and xc is None:
            const = yc
        deps = _join_taint(xt, yt)
        len_dep = False
        if deps is not ANY:
            len_dep = _LEN_TAINT in deps
            deps = frozenset(i for i in deps if i >= 0)
        branches.append(BranchFact(
            pc=pc, block=block_of_pc[pc], cmp=CMP_NAMES[b & 3],
            const=const, deps=deps, always=always, len_dep=len_dep))

    # -- definite-crash pcs (constant-index memory faults) ------------
    crash_pcs: Set[int] = set()
    for pc in sorted(state_in):
        op, a, b, c = rows[pc]
        if op == OP_CRASH:
            crash_pcs.add(pc)
        elif op in (OP_LDM, OP_STM):
            idx_reg = b if op == OP_LDM else a
            idx_c, _ = state_in[pc][0][_reg(idx_reg)]
            if idx_c is not None and not (0 <= idx_c < mem_size):
                crash_pcs.add(pc)
        elif op == OP_JMP and not (0 <= a < ni):
            crash_pcs.add(pc)

    # -- must-crash: least fixpoint over reached pcs ------------------
    # (loops stay False — a pure spin is a hang, not a crash)
    folded_succs: Dict[int, List[int]] = {}
    for pc in state_in:
        succs = [s for s, _ in transfer(pc, state_in[pc])]
        oob = [s for s in instr_successors(instrs, pc)
               if not (0 <= s < ni)]
        folded_succs[pc] = succs + oob
    must = {pc: False for pc in state_in}
    oob_must = True                     # off-end pc always crashes
    changed = True
    while changed:
        changed = False
        for pc in must:
            if must[pc]:
                continue
            if pc in crash_pcs:
                must[pc] = True
                changed = True
                continue
            succs = folded_succs[pc]
            if succs and all(
                    (must.get(s, oob_must) if 0 <= s < ni else True)
                    for s in succs):
                must[pc] = True
                changed = True

    block_pcs = [pc for pc in range(ni) if rows[pc][0] == OP_BLOCK]
    dead = {k for k, pc in enumerate(block_pcs) if pc not in state_in}
    must_blocks = {k for k, pc in enumerate(block_pcs)
                   if must.get(pc, False)}
    return DataflowResult(branches=branches,
                          reached_pcs=set(state_in),
                          dead_blocks=dead,
                          must_crash_blocks=must_blocks,
                          crash_pcs=crash_pcs)


def _fold_cmp(sel: int, x: Optional[int], y: Optional[int]
              ) -> Optional[bool]:
    if x is None or y is None:
        return None
    if sel == CMP_EQ:
        return x == y
    if sel == CMP_NE:
        return x != y
    if sel == CMP_LT:
        return x < y
    return x >= y


def extract_dictionary(program,
                       result: Optional[DataflowResult] = None,
                       max_tokens: int = 256) -> List[bytes]:
    """Branch-comparison constants as dictionary tokens.

    Every input-tainted branch guarded by a known constant donates
    the constant's byte encoding; runs of consecutive single-byte
    positional compares (``expect_byte`` chains: deps == {i}, one
    8-bit constant per position) merge into multi-byte tokens, so a
    magic header like ``"STK1"`` comes out whole.  This is the
    guidance Angora derives from dynamic byte-level taint — free
    here because the program text is ours (PAPERS.md).
    """
    return [tok for _pc, tok in
            dictionary_candidates(program, result,
                                  max_tokens=max_tokens)]


def dictionary_candidates(program,
                          result: Optional[DataflowResult] = None,
                          max_tokens: int = 256
                          ) -> List[Tuple[int, bytes]]:
    """``extract_dictionary`` with provenance: deduped
    ``(first-use pc, token)`` pairs in the same deterministic
    (pc, bytes) order.  The pc anchors message/handler scoping for
    sequence targets (stateful.dictionary.extract_dictionary_groups
    maps it to the guarding protocol state)."""
    result = result or analyze_dataflow(program)
    # (first-use pc, token) candidates; the FINAL order is sorted by
    # (first-use pc, bytes) and deduped — deterministic across runs
    # and across any reordering of the branch list (the order used to
    # follow collection order, so dictionary walks depended on
    # analysis-internal iteration details)
    cands: List[Tuple[int, bytes]] = []

    # positional single-byte compares -> merged runs (the most
    # valuable tokens), collected only when a position pins ONE value
    by_pos: Dict[int, Set[int]] = {}
    first_pc: Dict[int, int] = {}
    for f in result.branches:
        if (f.cmp in ("eq", "ne") and f.const is not None
                and 0 <= f.const <= 255 and f.deps is not ANY
                and f.deps is not None and len(f.deps) == 1):
            i = next(iter(f.deps))
            if isinstance(i, int) and i >= 0:
                by_pos.setdefault(i, set()).add(f.const)
                first_pc[i] = min(first_pc.get(i, f.pc), f.pc)
    run: List[int] = []

    def flush(run: List[int]) -> None:
        if len(run) >= 2:
            cands.append((min(first_pc[i] for i in run),
                          bytes(next(iter(by_pos[i])) for i in run)))

    for i in sorted(by_pos):
        single = len(by_pos[i]) == 1
        if single and run and i == run[-1] + 1:
            run.append(i)
            continue
        flush(run)
        run = [i] if single else []
    flush(run)

    # individual constants (any input-dependent guarded compare)
    for f in result.branches:
        if f.const is None:
            continue
        if f.deps is not ANY and not f.deps:
            continue                    # not input-dependent (e.g. len)
        c = f.const
        if c == 0:
            continue                    # zero bytes carry no signal
        u = c & 0xFFFFFFFF
        if 0 < c <= 0xFF:
            cands.append((f.pc, bytes([c])))
        elif 0 < c <= 0xFFFF:
            cands.append((f.pc, u.to_bytes(2, "little")))
            cands.append((f.pc, u.to_bytes(2, "big")))
        else:
            cands.append((f.pc, u.to_bytes(4, "little")))
            cands.append((f.pc, u.to_bytes(4, "big")))
        # compare-WIDTH little-endian encoding: a multi-byte eq/ne
        # compare (deps = {i..i+w-1}, e.g. a 32-bit field assembled
        # from 4 OP_LDBs) against a SMALL constant needs the wide
        # encoding in the input — value magnitude alone emits only
        # the short form (0x50 compared as a dword must land as
        # 50 00 00 00, never as a lone 0x50).  Endianness of the
        # assembly is unknowable statically; little-endian is the
        # KBVM convention (read_bytes/write_bytes default) and the
        # grammar token alphabets seed from exactly these.
        if (f.cmp in ("eq", "ne") and f.deps is not ANY
                and 2 <= len(f.deps) <= 4 and u < (1 << (8 * len(f.deps)))):
            cands.append((f.pc, u.to_bytes(len(f.deps), "little")))

    tokens: List[Tuple[int, bytes]] = []
    seen: Set[bytes] = set()
    for pc, tok in sorted(cands):
        if tok and tok not in seen:
            seen.add(tok)
            tokens.append((pc, tok))
        if len(tokens) >= max_tokens:
            break
    return tokens[:max_tokens]
