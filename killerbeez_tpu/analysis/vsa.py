"""Value-set analysis — a strided-interval abstract interpretation.

The constant-propagation fixpoint (dataflow.py) answers "is this
operand a known constant?"; everything else is top.  This second
fixpoint answers the much richer question Angora buys with dynamic
byte-level tracking (PAPERS.md, arxiv 1803.01307 / 1711.04596):
*which values* can each register — and each input-byte position —
take at each pc.  The domain is a reduced product of

  * a **small value set** (≤ ``SET_CAP`` concrete int32 values —
    exact, transfers run elementwise through ``_alu_const``), and
  * a **strided interval** ``lo + k*stride ⊆ [lo, hi]`` once a set
    overflows (sound over-approximation; transfers mirror
    ``vm._step``'s int32 wrap/clip semantics and go to TOP rather
    than model a wrap they cannot bound).

Alongside the domain every register carries an **affine byte
provenance** ``value == scale*byte[i] + offset`` (kept only while
provably wrap-free and identical across joined paths) — the handle
that lets a guard like ``b0 + 200 == 300`` be inverted back to the
byte domain ``b0 = 100`` exactly, which neither constprop (constant
300 is not a byte) nor the solver's per-path closures (they never
summarize across paths) surface statically.

Honesty contract (the same discipline as solver.py): every published
domain is an OVER-approximation of the concrete collecting
semantics, checkable by concrete replay — ``check_replay`` executes
an input through ``concrete_run`` and verifies every executed
branch's operands lie inside the branch's published domains and the
taken side was marked feasible.  Widening points (``WIDEN_AFTER``
joins per pc) and the single-cell memory summary are the two
deliberate imprecisions; both only ever widen, never narrow.

Consumers: solver seeding (``forced_byte_domains`` — see
solver.solve_edge_vsa), grammar derivation (grammar/derive.py
``vsa=``), value priors (analysis/priors.py), and the lint checks
``infeasible-edge`` / ``value-range-contradiction`` /
``guaranteed-oob-store`` (lint.py ``vsa=``).
"""

from __future__ import annotations

import hashlib
import json
import math
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

import numpy as np

from ..models.vm import (
    ALU_ADD, ALU_AND, ALU_MUL, ALU_OR, ALU_SHL, ALU_SHR, ALU_SUB,
    ALU_XOR, N_REGS,
    OP_ADDI, OP_ALU, OP_BLOCK, OP_BR, OP_CRASH, OP_HALT, OP_JMP,
    OP_LDB, OP_LDI, OP_LDM, OP_LEN, OP_STM,
)
from .cfg import instr_successors
from .dataflow import CMP_NAMES, _alu_const, _fold_cmp, _i32, _reg

INT32_MIN = -(1 << 31)
INT32_MAX = (1 << 31) - 1

#: small-value-set cap: beyond this many concrete values the domain
#: degrades to its strided-interval hull (16 matches the grammar
#: tier's alphabet cap — a position compared against more values is
#: a dispatch byte, not magic)
SET_CAP = 16

#: joins tolerated per pc before the moving interval bound widens to
#: the int32 extreme (the fixpoint's termination lever; byte domains
#: live in [0, 255] and never need it)
WIDEN_AFTER = 8

#: fixpoint iteration backstop (runaway guard, far above any real
#: program — the widening above is what actually bounds the chain)
_MAX_ITERS = 200_000

#: sidecar / checkpoint-section schema tag
VSA_SCHEMA = "kbz-vsa-v1"


def _gcd(a: int, b: int) -> int:
    return math.gcd(abs(a), abs(b))


# --------------------------------------------------------------------
# the value domain
# --------------------------------------------------------------------

@dataclass(frozen=True)
class VDom:
    """One abstract int32 value: ``vals`` (exact small set) when not
    None, else the strided interval ``{lo + k*stride} ∩ [lo, hi]``
    (``stride == 0`` means the singleton ``lo``)."""
    lo: int
    hi: int
    stride: int
    vals: Optional[FrozenSet[int]] = None

    # -- constructors -------------------------------------------------

    @staticmethod
    def top() -> "VDom":
        return _TOP

    @staticmethod
    def const(v: int) -> "VDom":
        v = _i32(v)
        return VDom(v, v, 0, frozenset((v,)))

    @staticmethod
    def from_vals(vs) -> "VDom":
        vs = frozenset(_i32(v) for v in vs)
        if not vs:
            raise ValueError("empty value set has no VDom")
        if len(vs) > SET_CAP:
            return VDom._hull(vs)
        lo, hi = min(vs), max(vs)
        return VDom(lo, hi, _set_stride(vs), vs)

    @staticmethod
    def _hull(vs) -> "VDom":
        lo, hi = min(vs), max(vs)
        return VDom(lo, hi, _set_stride(vs) if lo != hi else 0)

    @staticmethod
    def range(lo: int, hi: int, stride: int = 1) -> "VDom":
        lo, hi = max(lo, INT32_MIN), min(hi, INT32_MAX)
        if lo > hi:
            raise ValueError("empty interval has no VDom")
        if lo == hi:
            return VDom.const(lo)
        n = (hi - lo) // max(stride, 1) + 1
        if n <= SET_CAP:
            return VDom.from_vals(
                range(lo, hi + 1, max(stride, 1)))
        return VDom(lo, hi, max(stride, 1))

    # -- predicates ---------------------------------------------------

    @property
    def is_top(self) -> bool:
        return (self.vals is None and self.lo == INT32_MIN
                and self.hi == INT32_MAX and self.stride == 1)

    @property
    def is_const(self) -> bool:
        return self.lo == self.hi

    @property
    def const_val(self) -> Optional[int]:
        return self.lo if self.lo == self.hi else None

    def contains(self, v: int) -> bool:
        if self.vals is not None:
            return v in self.vals
        if not (self.lo <= v <= self.hi):
            return False
        return self.stride == 0 or (v - self.lo) % self.stride == 0

    def count(self) -> int:
        """How many concrete values the domain admits."""
        if self.vals is not None:
            return len(self.vals)
        if self.stride == 0:
            return 1
        return (self.hi - self.lo) // self.stride + 1

    def enum(self, cap: int = 256) -> Optional[List[int]]:
        """The concrete values, when there are at most ``cap``."""
        if self.count() > cap:
            return None
        if self.vals is not None:
            return sorted(self.vals)
        return list(range(self.lo, self.hi + 1, max(self.stride, 1)))

    # -- lattice ------------------------------------------------------

    def join(self, other: "VDom") -> "VDom":
        if self == other:
            return self
        if self.vals is not None and other.vals is not None:
            u = self.vals | other.vals
            if len(u) <= SET_CAP:
                return VDom.from_vals(u)
        lo, hi = min(self.lo, other.lo), max(self.hi, other.hi)
        s = _gcd(self.stride, other.stride)
        s = _gcd(s, abs(self.lo - other.lo))
        if lo == hi:
            return VDom.const(lo)
        return VDom(lo, hi, max(s, 1))

    def widen(self, newer: "VDom") -> "VDom":
        """Classic interval widening: a moving bound jumps to the
        int32 extreme; the stride collapses to 1 (documented
        imprecision — strides rarely survive loop-carried updates
        anyway)."""
        j = self.join(newer)
        if j == self:
            return self
        lo = self.lo if j.lo >= self.lo else INT32_MIN
        hi = self.hi if j.hi <= self.hi else INT32_MAX
        if lo == INT32_MIN and hi == INT32_MAX:
            return _TOP
        return VDom(lo, hi, 1 if lo != hi else 0)

    def as_doc(self) -> Dict:
        d: Dict = {"lo": int(self.lo), "hi": int(self.hi),
                   "stride": int(self.stride)}
        if self.vals is not None:
            d["vals"] = sorted(int(v) for v in self.vals)
        return d

    @staticmethod
    def from_doc(d: Dict) -> "VDom":
        return VDom(int(d["lo"]), int(d["hi"]), int(d["stride"]),
                    frozenset(d["vals"]) if "vals" in d else None)

    def __str__(self) -> str:
        if self.is_top:
            return "⊤"
        if self.vals is not None:
            return "{" + ",".join(str(v) for v in sorted(self.vals)) \
                + "}"
        s = f" step {self.stride}" if self.stride > 1 else ""
        return f"[{self.lo},{self.hi}]{s}"


def _set_stride(vs) -> int:
    xs = sorted(vs)
    if len(xs) < 2:
        return 0
    s = 0
    for a, b in zip(xs, xs[1:]):
        s = _gcd(s, b - a)
    return s


_TOP = VDom(INT32_MIN, INT32_MAX, 1)
_BYTE = VDom(0, 255, 1)


# --------------------------------------------------------------------
# transfer functions (int32-exact, mirroring vm._step)
# --------------------------------------------------------------------

def _nonneg(d: VDom) -> bool:
    return d.lo >= 0


def vdom_alu(sel: int, x: VDom, y: VDom) -> VDom:
    """Abstract transfer of one ALU select.  Exact (elementwise
    through ``_alu_const``) while both sides stay small sets; the
    interval tier is conservative and answers TOP wherever an int32
    wrap cannot be bounded — never a silently-wrong range."""
    if x.vals is not None and y.vals is not None \
            and len(x.vals) * len(y.vals) <= 64:
        return VDom.from_vals(_alu_const(sel, a, b)
                              for a in x.vals for b in y.vals)
    if sel == ALU_ADD:
        lo, hi = x.lo + y.lo, x.hi + y.hi
        if INT32_MIN <= lo and hi <= INT32_MAX:
            return VDom.range(lo, hi, _gcd(x.stride, y.stride) or 1)
        return _TOP
    if sel == ALU_SUB:
        lo, hi = x.lo - y.hi, x.hi - y.lo
        if INT32_MIN <= lo and hi <= INT32_MAX:
            return VDom.range(lo, hi, _gcd(x.stride, y.stride) or 1)
        return _TOP
    if sel == ALU_AND:
        # nonneg & nonneg stays within either operand's magnitude
        if _nonneg(x) and _nonneg(y):
            return VDom.range(0, min(x.hi, y.hi))
        return _TOP
    if sel == ALU_OR:
        if _nonneg(x) and _nonneg(y):
            hi = _or_upper(x.hi, y.hi)
            return VDom.range(max(x.lo, y.lo), hi) \
                if hi <= INT32_MAX else _TOP
        return _TOP
    if sel == ALU_XOR:
        if _nonneg(x) and _nonneg(y):
            hi = _or_upper(x.hi, y.hi)
            return VDom.range(0, hi) if hi <= INT32_MAX else _TOP
        return _TOP
    if sel == ALU_SHL:
        c = y.const_val
        if c is not None and _nonneg(x):
            s = min(max(c, 0), 31)
            lo, hi = x.lo << s, x.hi << s
            if hi <= INT32_MAX:
                return VDom.range(lo, hi, max(x.stride, 1) << s)
        return _TOP
    if sel == ALU_SHR:
        c = y.const_val
        if c is not None and _nonneg(x):
            s = min(max(c, 0), 31)
            return VDom.range(x.lo >> s, x.hi >> s)
        return _TOP
    if sel == ALU_MUL:
        c = y.const_val if y.is_const else \
            (x.const_val if x.is_const else None)
        v = x if y.is_const else y
        if c is not None and c >= 0 and _nonneg(v):
            lo, hi = v.lo * c, v.hi * c
            if hi <= INT32_MAX:
                return VDom.range(lo, hi, max(v.stride, 1) * max(c, 1))
        return _TOP
    return _TOP


def _or_upper(a: int, b: int) -> int:
    """Smallest all-ones bound covering OR/XOR of nonneg x ≤ a,
    y ≤ b: ``(a | b)`` rounded up to 2^k - 1."""
    m = a | b
    return (1 << m.bit_length()) - 1 if m else 0


def _cmp_feasible(sel: int, x: VDom, y: VDom, want: bool) -> bool:
    """May ``x sel y`` evaluate to ``want``?  Exact for small sets,
    bound-based (sound) for intervals."""
    if x.vals is not None and y.vals is not None \
            and len(x.vals) * len(y.vals) <= 4096:
        return any(_fold_cmp(sel, a, b) is want
                   for a in x.vals for b in y.vals)
    from ..models.vm import CMP_EQ, CMP_GE, CMP_LT, CMP_NE
    if sel == CMP_EQ:
        eq_possible = _may_intersect(x, y)
        return eq_possible if want else _may_differ(x, y)
    if sel == CMP_NE:
        return _may_differ(x, y) if want else _may_intersect(x, y)
    if sel == CMP_LT:
        return (x.lo < y.hi) if want else (x.hi >= y.lo)
    if sel == CMP_GE:
        return (x.hi >= y.lo) if want else (x.lo < y.hi)
    return True


def _may_intersect(x: VDom, y: VDom) -> bool:
    if x.hi < y.lo or y.hi < x.lo:
        return False
    c = y.const_val if y.is_const else (
        x.const_val if x.is_const else None)
    if c is not None:
        other = x if y.is_const else y
        return other.contains(c)
    # congruence test on the overlap (sound: sets already handled)
    s = _gcd(x.stride, y.stride)
    if s > 1 and (x.lo - y.lo) % s != 0:
        return False
    return True


def _may_differ(x: VDom, y: VDom) -> bool:
    return not (x.is_const and y.is_const and x.lo == y.lo)


def _refine_cmp(sel: int, d: VDom, k: int, want: bool
                ) -> Optional[VDom]:
    """Restrict ``d`` to values v with ``v sel k == want`` — None for
    bottom.  Exact on sets; interval clamping on eq/lt/ge hulls
    (ne over an interval is left unrefined: sound)."""
    from ..models.vm import CMP_EQ, CMP_GE, CMP_LT, CMP_NE
    if d.vals is not None:
        keep = frozenset(v for v in d.vals
                         if _fold_cmp(sel, v, k) is want)
        return VDom.from_vals(keep) if keep else None
    if sel == CMP_EQ:
        if want:
            return VDom.const(k) if d.contains(k) else None
        return d                        # drop one point: keep hull
    if sel == CMP_NE:
        if not want:
            return VDom.const(k) if d.contains(k) else None
        return d
    lt = (sel == CMP_LT)
    below = want if lt else not want    # keep v < k ?
    if below:
        hi = min(d.hi, k - 1)
        return VDom.range(d.lo, hi, max(d.stride, 1)) \
            if d.lo <= hi else None
    lo = max(d.lo, k)
    return VDom.range(lo, d.hi, max(d.stride, 1)) \
        if lo <= d.hi else None


# --------------------------------------------------------------------
# affine byte provenance
# --------------------------------------------------------------------

#: affine fact: value == scale * byte[idx] + offset, EXACT (no int32
#: wrap for any byte in [0, 255] — checked at construction)
Affine = Tuple[int, int, int]           # (idx, scale, offset)


def _affine_ok(scale: int, offset: int) -> bool:
    for b in (0, 255):
        v = scale * b + offset
        if not (INT32_MIN <= v <= INT32_MAX):
            return False
    return True


def _affine_shift(aff: Optional[Affine], d_scale: int,
                  d_offset: int, mul: bool) -> Optional[Affine]:
    if aff is None:
        return None
    i, s, o = aff
    if mul:
        s, o = s * d_scale, o * d_scale
    else:
        o = o + d_offset
    return (i, s, o) if _affine_ok(s, o) else None


def affine_sat_set(aff: Affine, sel: int, k: int,
                   want: bool) -> FrozenSet[int]:
    """Byte values b for which ``(scale*b + offset) sel k == want``
    — the exact inversion of a guard back to the byte domain."""
    _, s, o = aff
    return frozenset(b for b in range(256)
                     if _fold_cmp(sel, _i32(s * b + o), k) is want)


# --------------------------------------------------------------------
# abstract state
# --------------------------------------------------------------------

class _AbsVal:
    __slots__ = ("dom", "affine")

    def __init__(self, dom: VDom, affine: Optional[Affine] = None):
        self.dom = dom
        self.affine = affine

    def __eq__(self, other):
        return (self.dom == other.dom
                and self.affine == other.affine)

    def __hash__(self):
        return hash((self.dom, self.affine))


_ZERO_AV = _AbsVal(VDom.const(0))
_TOP_AV = _AbsVal(_TOP)


class _AbsState:
    """regs: tuple of 8 _AbsVal; bytes: per-position refined VDom
    (positions absent = full [0,255]); mem: one summary VDom over
    every stored value (plus the initial zeros)."""

    __slots__ = ("regs", "bytes", "mem")

    def __init__(self, regs, bytes_, mem):
        self.regs = regs
        self.bytes = bytes_
        self.mem = mem

    def __eq__(self, other):
        return (self.regs == other.regs and self.bytes == other.bytes
                and self.mem == other.mem)

    def byte_dom(self, i: int) -> VDom:
        return self.bytes.get(i, _BYTE)


def _join_states(a: Optional[_AbsState], b: _AbsState,
                 widen: bool) -> _AbsState:
    if a is None:
        return b
    regs = []
    for x, y in zip(a.regs, b.regs):
        dom = x.dom.widen(y.dom) if widen else x.dom.join(y.dom)
        aff = x.affine if x.affine == y.affine else None
        regs.append(_AbsVal(dom, aff))
    # byte domains only ever live in [0, 255]: plain join terminates
    keys = set(a.bytes) & set(b.bytes)
    bytes_ = {i: a.bytes[i].join(b.bytes[i]) for i in keys}
    bytes_ = {i: d for i, d in bytes_.items() if d != _BYTE}
    mem = a.mem.widen(b.mem) if widen else a.mem.join(b.mem)
    return _AbsState(tuple(regs), bytes_, mem)


# --------------------------------------------------------------------
# published facts
# --------------------------------------------------------------------

@dataclass(frozen=True)
class VsaFact:
    """One OP_BR as the value-set interpreter saw it (join over all
    modeled paths — every concrete execution's operands lie inside
    these domains; ``check_replay`` enforces exactly that)."""
    pc: int
    block: int
    cmp: str
    x_dom: VDom
    y_dom: VDom
    #: exact affine byte provenance of each side, when it survived
    #: every join into this pc
    x_affine: Optional[Affine]
    y_affine: Optional[Affine]
    #: may the comparison come out True / False?  One side False =
    #: the other side is FORCED (the infeasible-edge lint + the
    #: solver's forced-guard seeds)
    feasible_true: bool = True
    feasible_false: bool = False

    def feasible(self, want: bool) -> bool:
        return self.feasible_true if want else self.feasible_false

    def as_doc(self) -> Dict:
        return {
            "pc": int(self.pc), "block": int(self.block),
            "cmp": self.cmp,
            "x_dom": self.x_dom.as_doc(),
            "y_dom": self.y_dom.as_doc(),
            "x_affine": list(self.x_affine) if self.x_affine else None,
            "y_affine": list(self.y_affine) if self.y_affine else None,
            "feasible_true": bool(self.feasible_true),
            "feasible_false": bool(self.feasible_false),
        }

    @staticmethod
    def from_doc(d: Dict) -> "VsaFact":
        return VsaFact(
            pc=int(d["pc"]), block=int(d["block"]), cmp=d["cmp"],
            x_dom=VDom.from_doc(d["x_dom"]),
            y_dom=VDom.from_doc(d["y_dom"]),
            x_affine=tuple(d["x_affine"]) if d.get("x_affine") else None,
            y_affine=tuple(d["y_affine"]) if d.get("y_affine") else None,
            feasible_true=bool(d["feasible_true"]),
            feasible_false=bool(d["feasible_false"]))


@dataclass(frozen=True)
class MemFact:
    """One LDM/STM whose index register's domain the fixpoint
    bounded — the guaranteed-oob-store refinement's evidence."""
    pc: int
    block: int
    op: str                             # "ldm" / "stm"
    idx_dom: VDom

    def as_doc(self) -> Dict:
        return {"pc": int(self.pc), "block": int(self.block),
                "op": self.op, "idx_dom": self.idx_dom.as_doc()}

    @staticmethod
    def from_doc(d: Dict) -> "MemFact":
        return MemFact(pc=int(d["pc"]), block=int(d["block"]),
                       op=d["op"], idx_dom=VDom.from_doc(d["idx_dom"]))


@dataclass
class VsaResult:
    branches: List[VsaFact]
    mem_ops: List[MemFact]
    #: pcs that received abstract state (VSA-reachable); a pc
    #: constprop reaches but VSA does not is a value-range
    #: contradiction (accumulated refinements emptied every path in)
    reached_pcs: Set[int]
    #: per input-byte position: join of the refined domain at every
    #: USE — the priors/grammar surface, NOT a per-edge guarantee
    #: (solver seeding recomputes per-edge forced domains instead)
    byte_domains: Dict[int, VDom] = field(default_factory=dict)
    #: pcs whose in-state was widened (the honesty caveat surface)
    widened_pcs: Set[int] = field(default_factory=set)
    program_sig: str = ""

    @property
    def by_pc(self) -> Dict[int, VsaFact]:
        return {f.pc: f for f in self.branches}

    # -- persistence (corpus-store checkpoint section / sidecar) ------

    def to_doc(self) -> Dict:
        return {
            "schema": VSA_SCHEMA,
            "program_sig": self.program_sig,
            "branches": [f.as_doc() for f in self.branches],
            "mem_ops": [m.as_doc() for m in self.mem_ops],
            "reached_pcs": sorted(int(p) for p in self.reached_pcs),
            "byte_domains": {str(i): d.as_doc()
                             for i, d in sorted(
                                 self.byte_domains.items())},
            "widened_pcs": sorted(int(p) for p in self.widened_pcs),
        }

    @staticmethod
    def from_doc(doc: Dict, program=None) -> Optional["VsaResult"]:
        """Rehydrate a cached document; None when the schema or the
        program signature does not match (a stale cache must re-run
        the fixpoint, never serve another program's domains)."""
        try:
            if doc.get("schema") != VSA_SCHEMA:
                return None
            if program is not None and \
                    doc.get("program_sig") != program_sig(program):
                return None
            return VsaResult(
                branches=[VsaFact.from_doc(d)
                          for d in doc["branches"]],
                mem_ops=[MemFact.from_doc(d)
                         for d in doc.get("mem_ops", [])],
                reached_pcs=set(doc["reached_pcs"]),
                byte_domains={int(i): VDom.from_doc(d)
                              for i, d in
                              doc.get("byte_domains", {}).items()},
                widened_pcs=set(doc.get("widened_pcs", [])),
                program_sig=doc.get("program_sig", ""))
        except (KeyError, TypeError, ValueError):
            return None


def program_sig(program) -> str:
    """Stable identity of the analyzed text: instructions + the
    engine parameters the transfer functions depend on."""
    h = hashlib.sha1()
    h.update(np.ascontiguousarray(
        np.asarray(program.instrs, dtype=np.int64)).tobytes())
    h.update(json.dumps([int(program.mem_size),
                         int(program.max_steps)]).encode())
    return h.hexdigest()


# --------------------------------------------------------------------
# the fixpoint
# --------------------------------------------------------------------

def analyze_vsa(program) -> VsaResult:
    instrs = np.asarray(program.instrs)
    ni = instrs.shape[0]
    rows = [tuple(int(x) for x in instrs[pc]) for pc in range(ni)]

    block_of_pc: List[int] = []
    cur = -1
    for pc in range(ni):
        if rows[pc][0] == OP_BLOCK:
            cur += 1
        block_of_pc.append(cur)

    state_in: Dict[int, _AbsState] = {}
    joins: Dict[int, int] = {}
    widened: Set[int] = set()
    worklist: List[int] = []
    if ni:
        state_in[0] = _AbsState(tuple(_ZERO_AV for _ in range(N_REGS)),
                                {}, VDom.const(0))
        worklist.append(0)

    #: per-position: join of refined byte domains observed at uses
    use_doms: Dict[int, VDom] = {}

    def flow(pc: int, st: _AbsState) -> None:
        prev = state_in.get(pc)
        n = joins.get(pc, 0)
        widen = n >= WIDEN_AFTER
        joined = _join_states(prev, st, widen)
        if prev is None or joined != prev:
            if widen and prev is not None:
                widened.add(pc)
            state_in[pc] = joined
            joins[pc] = n + 1
            worklist.append(pc)

    def transfer(pc: int, st: _AbsState
                 ) -> List[Tuple[int, _AbsState]]:
        op, a, b, c = rows[pc]
        regs = list(st.regs)
        bytes_, mem = st.bytes, st.mem
        if op == OP_LDB:
            idx = regs[_reg(b)].dom.const_val
            if idx is not None and idx < 0:
                regs[_reg(a)] = _ZERO_AV
            elif idx is not None:
                # in-bounds reads see the byte; short inputs read 0 —
                # the loaded domain must admit both (the replay
                # contract); the affine fact reads "the value LDB
                # produced", which is byte[idx] in-bounds and 0 on
                # short inputs — exactness of the affine inversion is
                # restored per-path by the solver's len >= idx+1
                # constraint
                d = st.byte_dom(idx)
                if not d.contains(0):
                    d = d.join(VDom.const(0))
                regs[_reg(a)] = _AbsVal(d, (idx, 1, 0))
            else:
                regs[_reg(a)] = _AbsVal(_BYTE)
        elif op == OP_LDI:
            regs[_reg(a)] = _AbsVal(VDom.const(b))
        elif op == OP_ALU:
            sel = c & 7
            x = regs[_reg(b)]
            y = regs[(c >> 3) & (N_REGS - 1)]
            dom = vdom_alu(sel, x.dom, y.dom)
            aff = None
            if sel == ALU_ADD and y.dom.is_const:
                aff = _affine_shift(x.affine, 1, y.dom.lo, False)
            elif sel == ALU_ADD and x.dom.is_const:
                aff = _affine_shift(y.affine, 1, x.dom.lo, False)
            elif sel == ALU_SUB and y.dom.is_const:
                aff = _affine_shift(x.affine, 1, -y.dom.lo, False)
            elif sel == ALU_MUL and y.dom.is_const and y.dom.lo >= 0:
                aff = _affine_shift(x.affine, y.dom.lo, 0, True)
            elif sel == ALU_MUL and x.dom.is_const and x.dom.lo >= 0:
                aff = _affine_shift(y.affine, x.dom.lo, 0, True)
            elif sel == ALU_SHL and y.dom.is_const \
                    and 0 <= y.dom.lo <= 31:
                aff = _affine_shift(x.affine, 1 << y.dom.lo, 0, True)
            regs[_reg(a)] = _AbsVal(dom, aff)
        elif op == OP_ADDI:
            x = regs[_reg(b)]
            dom = vdom_alu(ALU_ADD, x.dom, VDom.const(c))
            regs[_reg(a)] = _AbsVal(
                dom, _affine_shift(x.affine, 1, _i32(c), False))
        elif op == OP_LEN:
            # the input length: nonnegative, otherwise unbounded by
            # this analysis (the solver's max_len is a SEARCH cap,
            # not an engine property)
            regs[_reg(a)] = _AbsVal(VDom.range(0, INT32_MAX))
        elif op == OP_LDM:
            regs[_reg(a)] = _AbsVal(mem)
        elif op == OP_STM:
            mem = mem.join(regs[_reg(b)].dom)
        new = _AbsState(tuple(regs), bytes_, mem)

        if op == OP_BR:
            sel = b & 3
            xi, yi = _reg(a), (b >> 2) & (N_REGS - 1)
            x, y = st.regs[xi], st.regs[yi]
            out = []
            for want, succ in ((True, c), (False, pc + 1)):
                if not (0 <= succ < ni):
                    continue
                if not _cmp_feasible(sel, x.dom, y.dom, want):
                    continue
                sregs = list(new.regs)
                sbytes = dict(new.bytes)
                dead = False
                # operand refinement against a constant other side
                for vi, v, o, is_x in ((xi, x, y, True),
                                       (yi, y, x, False)):
                    k = o.dom.const_val
                    if k is None:
                        continue
                    trip = _side_pred(sel, k, want, is_x)
                    if trip is None:
                        continue        # no usable refinement
                    msel, mk, mwant = trip
                    r = _refine_cmp(msel, v.dom, mk, mwant)
                    if r is None:
                        dead = True
                        break
                    sregs[vi] = _AbsVal(r, v.affine)
                    if v.affine is not None:
                        i = v.affine[0]
                        sat = affine_sat_set(v.affine, msel, mk,
                                             mwant)
                        # the guard constrains the LOADED value; on
                        # an in-bounds read that IS byte[i], so the
                        # byte refines to ``current ∩ sat`` — a
                        # short-input path reads 0 instead and the
                        # byte (which then does not exist in the
                        # input) stays vacuously inside any domain
                        cur_b = sbytes.get(i, _BYTE)
                        keep = frozenset(
                            bv for bv in range(256)
                            if cur_b.contains(bv) and bv in sat)
                        if keep:
                            nd = VDom.from_vals(keep)
                            if nd != _BYTE:
                                sbytes[i] = nd
                                use_doms[i] = use_doms.get(
                                    i, nd).join(nd)
                        # empty keep: the guard can only pass via
                        # the short-input zero read; byte stays free
                if dead:
                    continue
                out.append((succ, _AbsState(tuple(sregs), sbytes,
                                            new.mem)))
            return out
        return [(s, new) for s in instr_successors(instrs, pc)
                if 0 <= s < ni]

    iters = 0
    while worklist and iters < _MAX_ITERS:
        iters += 1
        pc = worklist.pop()
        if rows[pc][0] in (OP_HALT, OP_CRASH):
            continue
        for s, out in transfer(pc, state_in[pc]):
            flow(s, out)

    # -- publish branch facts -----------------------------------------
    branches: List[VsaFact] = []
    for pc in sorted(state_in):
        op, a, b, c = rows[pc]
        if op != OP_BR:
            continue
        st = state_in[pc]
        sel = b & 3
        x = st.regs[_reg(a)]
        y = st.regs[(b >> 2) & (N_REGS - 1)]
        branches.append(VsaFact(
            pc=pc, block=block_of_pc[pc], cmp=CMP_NAMES[sel],
            x_dom=x.dom, y_dom=y.dom,
            x_affine=x.affine, y_affine=y.affine,
            feasible_true=_cmp_feasible(sel, x.dom, y.dom, True),
            feasible_false=_cmp_feasible(sel, x.dom, y.dom, False)))

    mem_ops: List[MemFact] = []
    for pc in sorted(state_in):
        op, a, b, c = rows[pc]
        if op not in (OP_LDM, OP_STM):
            continue
        idx = state_in[pc].regs[_reg(b if op == OP_LDM else a)]
        mem_ops.append(MemFact(
            pc=pc, block=block_of_pc[pc],
            op="ldm" if op == OP_LDM else "stm", idx_dom=idx.dom))

    return VsaResult(
        branches=branches, mem_ops=mem_ops,
        reached_pcs=set(state_in),
        byte_domains={i: d for i, d in sorted(use_doms.items())
                      if d != _BYTE},
        widened_pcs=widened, program_sig=program_sig(program))


def _side_pred(sel: int, k: int, want: bool, is_x: bool
               ) -> Optional[Tuple[int, int, bool]]:
    """The branch outcome as a predicate ``v sel' k' == want'`` over
    ONE operand, the other side pinned to constant ``k``.  The x
    side is the predicate itself; the y side mirrors the selector
    (``k < y`` becomes ``y >= k+1``).  None = no usable mirror
    (k+1 would overflow — that side is infeasible anyway)."""
    from ..models.vm import CMP_EQ, CMP_GE, CMP_LT, CMP_NE
    if is_x or sel in (CMP_EQ, CMP_NE):
        return sel, k, want
    if k >= INT32_MAX:
        return None
    below = (sel == CMP_GE) == want     # k>=y is want  ->  y <= k
    # y <= k  <=>  y lt k+1 ; y > k  <=>  y ge k+1
    return (CMP_LT, k + 1, True) if below else (CMP_GE, k + 1, True)


# --------------------------------------------------------------------
# the honesty check: concrete replay conformance
# --------------------------------------------------------------------

def check_replay(program, data: bytes,
                 vsa: Optional[VsaResult] = None) -> List[str]:
    """Execute ``data`` concretely and verify every executed branch
    against the published VSA facts: operands inside the domains,
    taken side marked feasible, affine provenance exact on in-bounds
    reads.  Returns human-readable violations (empty = conformant) —
    the test suite's soundness oracle, and any consumer's spot-check
    before trusting a cached document."""
    from .solver import concrete_run
    vsa = vsa or analyze_vsa(program)
    by_pc = vsa.by_pc
    trace = concrete_run(program, data)
    out: List[str] = []
    for pc, x, y, taken in trace.branches:
        f = by_pc.get(pc)
        if f is None:
            out.append(f"pc {pc}: branch executed but unpublished "
                       f"(VSA missed a reachable pc)")
            continue
        if not f.x_dom.contains(x):
            out.append(f"pc {pc}: x={x} outside {f.x_dom}")
        if not f.y_dom.contains(y):
            out.append(f"pc {pc}: y={y} outside {f.y_dom}")
        if not f.feasible(taken):
            out.append(f"pc {pc}: took the {taken} side marked "
                       f"infeasible")
        for side, v, aff in (("x", x, f.x_affine),
                             ("y", y, f.y_affine)):
            if aff is None:
                continue
            i, s, o = aff
            b = data[i] if 0 <= i < len(data) else 0
            if _i32(s * b + o) != v:
                out.append(f"pc {pc}: {side}={v} breaks affine "
                           f"{s}*byte[{i}]+{o} (byte={b})")
    return out


# --------------------------------------------------------------------
# summary (the kb-lint --json "vsa" section)
# --------------------------------------------------------------------

def vsa_stats(vsa: VsaResult) -> Dict:
    """Mirror of lint.universe_stats for the value-set layer."""
    forced = sum(1 for f in vsa.branches
                 if not (f.feasible_true and f.feasible_false))
    return {
        "n_branch_facts": len(vsa.branches),
        "n_affine": sum(1 for f in vsa.branches
                        if f.x_affine or f.y_affine),
        "n_forced_sides": forced,
        "n_mem_facts": len(vsa.mem_ops),
        "n_byte_positions": len(vsa.byte_domains),
        "byte_domains": {str(i): str(d)
                         for i, d in sorted(vsa.byte_domains.items())},
        "widened_pcs": sorted(vsa.widened_pcs),
        "reached_pcs": len(vsa.reached_pcs),
    }
