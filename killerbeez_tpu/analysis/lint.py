"""Defect checks over a KBVM Program — the ``kb-lint`` core.

Each check turns a silent correctness hazard into a reported finding:

  error severity (kb-lint exits nonzero; the CI lint lane fails):
    empty-module          a (name, lo, hi) module with lo == hi: its
                          64KB map can never light up and per-module
                          novelty silently no-ops
    unreachable-block     a coverage block no path from entry reaches:
                          its edges pad the static universe and its
                          map slots read as permanently-cold targets
    field-bound           an instruction field at/beyond 2^24: the
                          batched engine's f32 matmul fetch goes
                          inexact (Program construction also rejects)
    max-steps-shortfall   the longest LOOP-FREE complete path needs
                          more steps than ``max_steps``: legitimate
                          hang-free executions get triaged as hangs

  warning severity (reported; exit stays 0):
    slot-collision        two distinct static edges land on one AFL
                          map slot: novelty conflates them (AFL lives
                          with this; here it is measurable)
    duplicate-block-id    ``assign_block_ids`` drew the same coverage
                          id for two blocks (birthday collision over
                          MAP_SIZE): whole blocks alias in the map
    dead-block            CFG-reachable but unreachable once constant
                          propagation folds branches — dead weight in
                          the edge universe and the rarity prior

  info severity:
    must-crash-block      every path from the block head crashes —
                          usually the PLANTED bug (expected in fuzz
                          targets; a whole-module must-crash is worth
                          a look)
    no-blocks             the program has no coverage blocks at all

  VSA checks (``lint_program(..., vsa=VsaResult)``; kb-lint enables
  them with ``--vsa``):
    infeasible-edge       (warning; error when constant propagation
                          independently folds the branch the same
                          way) a branch side whose VSA domains make
                          the outcome empty — the edge pads the
                          static universe and the solver's frontier.
                          For stateful targets, a side only message
                          sequences can take downgrades to info
                          (``session-infeasible-edge``), mirroring
                          the dead-block downgrade
    value-range-contradiction
                          (warning) a pc constant propagation says
                          is reached but the value-set fixpoint
                          proves no value combination enters —
                          refinement emptied every path in; info
                          under the same session-live downgrade
    guaranteed-oob-store  (warning) a LDM/STM whose VSA index
                          interval lies entirely outside
                          [0, mem_size) on a non-constant index —
                          the access always faults, but constant
                          propagation cannot see it (constant
                          indices already surface via crash-pc
                          analysis)

  stateful targets (``lint_program(..., stateful=StatefulSpec)``;
  kb-lint resolves the spec from the target registry automatically):
    state-unreachable     (warning) a protocol state the program
                          guards on or assigns that NO session can
                          reach from the initial state in the static
                          CFG (stateful/protocol.py fixpoint) — dead
                          protocol surface, almost certainly a state-
                          machine bug in the target
    state-clip            (warning) a state assignment at/beyond
                          n_states: the session tier clips it into
                          the top bucket, aliasing distinct states
                          in the state x edge map
    session-only-block    (info) a block dead under SINGLE-SHOT
                          constant propagation that a session CAN
                          light — the dead-block warning is
                          downgraded to this for stateful targets
                          (these blocks are the tier's target
                          surface, not dead weight)
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from .cfg import ControlFlowGraph, build_cfg
from .dataflow import DataflowResult, analyze_dataflow

SEV_ERROR = "error"
SEV_WARNING = "warning"
SEV_INFO = "info"


@dataclass
class Finding:
    severity: str
    code: str
    message: str
    data: Dict = field(default_factory=dict)

    def as_dict(self) -> Dict:
        return {"severity": self.severity, "code": self.code,
                "message": self.message, **({"data": self.data}
                                            if self.data else {})}


def lint_program(program,
                 cfg: Optional[ControlFlowGraph] = None,
                 dataflow: Optional[DataflowResult] = None,
                 stateful=None, vsa=None) -> List[Finding]:
    """All checks over one Program, errors first.  ``stateful`` (a
    StatefulSpec) enables the session-tier checks and downgrades
    single-shot dead-block warnings for session-reachable blocks.
    ``vsa`` (a VsaResult) enables the value-set checks; ``None``
    (the default) leaves the finding list bit-identical to the
    pre-VSA linter — the parity anchor."""
    cfg = cfg or build_cfg(program)
    dataflow = dataflow or analyze_dataflow(program)
    out: List[Finding] = []

    session_live = None
    if stateful is not None:
        from ..stateful import protocol as _proto
        reached, live_by_state = _proto.reachable_states(program,
                                                         stateful)
        session_live = set()
        for blocks in live_by_state.values():
            session_live |= blocks
        for s in _proto.unreachable_states(program, stateful,
                                           _reached=reached):
            out.append(Finding(
                SEV_WARNING, "state-unreachable",
                f"protocol state {s} is guarded on or assigned but "
                f"no session reaches it from the initial state in "
                f"the static CFG — dead protocol surface (reachable "
                f"states: {sorted(reached)})",
                {"state": int(s), "reachable": sorted(reached)}))
        for pc, v in _proto.state_assignments(program,
                                              stateful.state_reg):
            if v >= stateful.n_states:
                out.append(Finding(
                    SEV_WARNING, "state-clip",
                    f"state assignment r{stateful.state_reg} = {v} "
                    f"at pc {pc} is at/beyond n_states="
                    f"{stateful.n_states}: the session tier clips "
                    f"it into bucket {stateful.n_states - 1}, "
                    f"aliasing distinct states in the state x edge "
                    f"map", {"pc": int(pc), "value": int(v),
                             "n_states": int(stateful.n_states)}))

    # -- empty modules ------------------------------------------------
    for name, lo, hi in program.modules:
        if lo >= hi:
            out.append(Finding(
                SEV_ERROR, "empty-module",
                f"module {name!r} spans no blocks "
                f"(lo == hi == {lo}); its coverage map can never "
                f"light up", {"module": name, "lo": int(lo),
                              "hi": int(hi)}))

    # -- unreachable blocks -------------------------------------------
    for k in cfg.unreachable_blocks():
        out.append(Finding(
            SEV_ERROR, "unreachable-block",
            f"block {k} (pc {cfg.block_pcs[k]}) is unreachable from "
            f"entry; its {sum(1 for f, _ in cfg.edge_cost if f == k)}"
            f" outgoing edges pad the static universe",
            {"block": k, "pc": cfg.block_pcs[k]}))

    # -- instruction field bounds -------------------------------------
    instrs = np.asarray(program.instrs)
    if instrs.size:
        bad = np.flatnonzero(
            (np.abs(instrs[:, 1:]) >= (1 << 24)).any(axis=1))
        for pc in bad:
            out.append(Finding(
                SEV_ERROR, "field-bound",
                f"instruction at pc {int(pc)} has a field >= 2^24; "
                f"the engine's f32 matmul fetch is inexact there",
                {"pc": int(pc)}))

    # -- register fields out of range ---------------------------------
    # the engine clips direct register fields to [0, 8) — defined
    # behavior, but never what the program meant (the assembler
    # rejects these; hand-built / file-loaded programs can carry them
    # and the abstract interpreter models the clip, not the intent)
    from ..models.vm import (
        N_REGS, OP_ADDI, OP_ALU, OP_BR, OP_LDB, OP_LDI, OP_LDM,
        OP_LEN, OP_STM,
    )
    _REG_FIELDS = {OP_LDB: (1, 2), OP_LDI: (1,), OP_ALU: (1, 2),
                   OP_ADDI: (1, 2), OP_BR: (1,), OP_LEN: (1,),
                   OP_LDM: (1, 2), OP_STM: (1, 2)}
    for pc in range(instrs.shape[0]):
        fields = _REG_FIELDS.get(int(instrs[pc, 0]), ())
        bad_f = [f for f in fields
                 if not (0 <= int(instrs[pc, f]) < N_REGS)]
        if bad_f:
            out.append(Finding(
                SEV_WARNING, "register-field-range",
                f"instruction at pc {pc} names register(s) "
                f"{[int(instrs[pc, f]) for f in bad_f]} outside "
                f"r0..r{N_REGS - 1}; the engine clips them — almost "
                f"certainly not what was meant",
                {"pc": int(pc),
                 "fields": [int(instrs[pc, f]) for f in bad_f]}))

    # -- max_steps vs the longest loop-free path ----------------------
    need = cfg.longest_acyclic_path
    if need > program.max_steps:
        out.append(Finding(
            SEV_ERROR, "max-steps-shortfall",
            f"max_steps={program.max_steps} but the longest loop-free "
            f"path needs {need} steps: hang-free executions would be "
            f"triaged as hangs",
            {"max_steps": int(program.max_steps),
             "longest_acyclic_path": int(need)}))

    # -- AFL map-slot collisions in the static edge universe ----------
    slots = np.asarray(program.edge_slot)
    ef = np.asarray(program.edge_from)
    et = np.asarray(program.edge_to)
    by_slot: Dict[int, List] = {}
    for i in range(len(slots)):
        by_slot.setdefault(int(slots[i]), []).append(
            (int(ef[i]), int(et[i])))
    for slot, pairs in sorted(by_slot.items()):
        if len(pairs) > 1:
            out.append(Finding(
                SEV_WARNING, "slot-collision",
                f"{len(pairs)} static edges alias AFL map slot "
                f"{slot}: {pairs} — novelty cannot tell them apart",
                {"slot": slot, "edges": pairs}))

    # -- duplicate coverage ids (assign_block_ids birthday draws) -----
    dup = {bid: n for bid, n in
           Counter(program.block_ids).items() if n > 1}
    for bid, n in sorted(dup.items()):
        blocks = [k for k, b in enumerate(program.block_ids)
                  if b == bid]
        out.append(Finding(
            SEV_WARNING, "duplicate-block-id",
            f"blocks {blocks} share coverage id {bid}: every edge "
            f"into/out of them aliases in the AFL map (re-seed "
            f"assign_block_ids)", {"id": int(bid), "blocks": blocks}))

    # -- statically-dead blocks (constant folding) --------------------
    for k in sorted(dataflow.dead_blocks):
        if k not in cfg.reachable:
            continue                    # already an unreachable error
        if session_live is not None and k in session_live:
            # dead SINGLE-SHOT, alive in sessions: the stateful
            # tier's target surface, not dead weight
            out.append(Finding(
                SEV_INFO, "session-only-block",
                f"block {k} (pc {cfg.block_pcs[k]}) is dead under "
                f"single-shot constant propagation but reachable by "
                f"message sequences — deep-state coverage only the "
                f"session tier can earn",
                {"block": k, "pc": cfg.block_pcs[k]}))
            continue
        out.append(Finding(
            SEV_WARNING, "dead-block",
            f"block {k} (pc {cfg.block_pcs[k]}) is CFG-reachable but "
            f"dead under constant propagation (a branch before it "
            f"always goes the other way)",
            {"block": k, "pc": cfg.block_pcs[k]}))

    # -- value-set checks (--vsa) -------------------------------------
    if vsa is not None:
        out.extend(_vsa_findings(program, cfg, dataflow, vsa,
                                 session_live))

    # -- must-crash blocks --------------------------------------------
    for k in sorted(dataflow.must_crash_blocks):
        out.append(Finding(
            SEV_INFO, "must-crash-block",
            f"every path from block {k} (pc {cfg.block_pcs[k]}) "
            f"crashes — planted bug or dead-end worth confirming",
            {"block": k, "pc": cfg.block_pcs[k]}))

    if cfg.n_blocks == 0:
        out.append(Finding(
            SEV_INFO, "no-blocks",
            "program has no coverage blocks: every input looks "
            "identical to the novelty scan"))

    sev_rank = {SEV_ERROR: 0, SEV_WARNING: 1, SEV_INFO: 2}
    out.sort(key=lambda f: sev_rank[f.severity])
    return out


def _block_of_pc(cfg: ControlFlowGraph, pc: int) -> Optional[int]:
    """Block index containing ``pc`` (the nearest OP_BLOCK at/before
    it), None for pre-block prologue pcs."""
    k = None
    for i, bpc in enumerate(cfg.block_pcs):
        if bpc <= pc:
            k = i
        else:
            break
    return k


def _vsa_findings(program, cfg: ControlFlowGraph,
                  dataflow: DataflowResult, vsa,
                  session_live) -> List[Finding]:
    """The three value-set checks.  All anchored on pcs so the SARIF
    emitter places them like every other finding."""
    out: List[Finding] = []
    instrs = np.asarray(program.instrs)
    ni = instrs.shape[0]
    always_by_pc = {f.pc: f.always for f in dataflow.branches}

    def live_downgrade(block: Optional[int]) -> bool:
        return (session_live is not None and block is not None
                and block in session_live)

    # -- infeasible-edge: a branch side VSA proves empty --------------
    for f in vsa.branches:
        for want, feas in ((True, f.feasible_true),
                           (False, f.feasible_false)):
            if feas:
                continue
            succ = int(instrs[f.pc, 3]) if want else f.pc + 1
            sblk = _block_of_pc(cfg, succ) if 0 <= succ < ni else None
            side = "taken" if want else "fallthrough"
            agrees = always_by_pc.get(f.pc) == (not want)
            if live_downgrade(sblk):
                out.append(Finding(
                    SEV_INFO, "session-infeasible-edge",
                    f"branch at pc {f.pc} ({f.cmp} with "
                    f"x={f.x_dom} y={f.y_dom}) cannot go "
                    f"{side} in a single shot, but message "
                    f"sequences can — the session tier's target "
                    f"surface, not dead weight",
                    {"pc": f.pc, "side": side, "block": f.block,
                     "succ_block": sblk}))
                continue
            sev = SEV_ERROR if agrees else SEV_WARNING
            out.append(Finding(
                sev, "infeasible-edge",
                f"branch at pc {f.pc} ({f.cmp} with x={f.x_dom} "
                f"y={f.y_dom}) can never go {side}: the value-set "
                f"domains make that outcome empty"
                + (" — constant propagation independently agrees"
                   if agrees else "")
                + "; the edge pads the static universe and the "
                  "solver frontier",
                {"pc": f.pc, "side": side, "block": f.block,
                 "succ_block": sblk,
                 "constprop_agrees": bool(agrees)}))

    # -- value-range-contradiction: constprop reaches, VSA refutes ----
    contradicted = sorted(dataflow.reached_pcs - vsa.reached_pcs)
    by_block: Dict[Optional[int], List[int]] = {}
    for pc in contradicted:
        by_block.setdefault(_block_of_pc(cfg, pc), []).append(pc)
    for blk, pcs in sorted(by_block.items(),
                           key=lambda kv: (kv[0] is None, kv[0])):
        if live_downgrade(blk):
            out.append(Finding(
                SEV_INFO, "session-value-range-contradiction",
                f"pcs {pcs} (block {blk}) are reached under "
                f"constant propagation but the value-set fixpoint "
                f"proves no single-shot value combination enters — "
                f"session-only surface",
                {"block": blk, "pcs": pcs}))
            continue
        out.append(Finding(
            SEV_WARNING, "value-range-contradiction",
            f"pcs {pcs}" + (f" (block {blk})" if blk is not None
                            else "")
            + " are reached under constant propagation but the "
              "value-set fixpoint proves no value combination "
              "enters: byte-domain refinement emptied every path "
              "in", {"block": blk, "pcs": pcs}))

    # -- guaranteed-oob-store: non-constant index, interval all OOB ---
    mem = int(program.mem_size)
    for m in vsa.mem_ops:
        d = m.idx_dom
        if d.const_val is not None:
            continue                    # crash-pc analysis owns these
        if d.hi < 0 or d.lo >= mem:
            out.append(Finding(
                SEV_WARNING, "guaranteed-oob-store",
                f"{m.op} at pc {m.pc} indexes mem[{d}] — entirely "
                f"outside [0, {mem}): every execution reaching it "
                f"faults, invisible to constant propagation "
                f"(non-constant index)",
                {"pc": m.pc, "op": m.op, "block": m.block,
                 "index_domain": str(d), "mem_size": mem}))
    return out


def universe_stats(program, cfg: Optional[ControlFlowGraph] = None
                   ) -> Dict:
    """Static-universe summary shared by kb-lint / showmap / picker."""
    cfg = cfg or build_cfg(program)
    slots = np.asarray(program.edge_slot)
    return {
        "name": program.name,
        "n_blocks": int(program.n_blocks),
        "n_edges": int(program.n_edges),
        "n_slots": int(len(np.unique(slots))) if len(slots) else 0,
        "n_modules": len(program.modules),
        "max_steps": int(program.max_steps),
        "longest_acyclic_path": int(cfg.longest_acyclic_path),
        "loop_headers": sorted(cfg.loop_headers),
        "unreachable_blocks": cfg.unreachable_blocks(),
    }
