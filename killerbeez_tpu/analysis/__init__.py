"""Static analysis over KBVM programs.

Killerbeez's side tools (picker, tracer) learn about a target by
RUNNING it; the KBVM tier has the whole program text and the exact
static edge universe at build time (``vm.compute_edges``), so a class
of facts AFL can only estimate dynamically is simply computable here:

  cfg.py       control-flow graph reconstruction from the instruction
               array — reachability, dominators, loop headers, the
               longest loop-free path (validates ``max_steps``), and a
               static edge-frequency prior for rare-edge scheduling
  dataflow.py  abstract interpretation over the 8-register ISA —
               constant propagation + input-byte taint; yields the
               comparison constants guarding each branch (an automatic
               fuzzing dictionary), per-branch input-byte dependency
               sets, and statically-dead / must-crash blocks
  lint.py      defect checks over both (slot collisions, unreachable
               blocks, empty modules, max_steps shortfalls, ...) —
               the ``kb-lint`` tool and the CI lint lane
"""

from .cfg import ControlFlowGraph, build_cfg, static_edge_prior
from .dataflow import (
    BranchFact, DataflowResult, analyze_dataflow, extract_dictionary,
)
from .lint import Finding, lint_program

__all__ = [
    "ControlFlowGraph", "build_cfg", "static_edge_prior",
    "BranchFact", "DataflowResult", "analyze_dataflow",
    "extract_dictionary",
    "Finding", "lint_program",
]
