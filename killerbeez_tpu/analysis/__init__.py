"""Static analysis over KBVM programs.

Killerbeez's side tools (picker, tracer) learn about a target by
RUNNING it; the KBVM tier has the whole program text and the exact
static edge universe at build time (``vm.compute_edges``), so a class
of facts AFL can only estimate dynamically is simply computable here:

  cfg.py       control-flow graph reconstruction from the instruction
               array — reachability, dominators, loop headers, the
               longest loop-free path (validates ``max_steps``), and a
               static edge-frequency prior for rare-edge scheduling
  dataflow.py  abstract interpretation over the 8-register ISA —
               constant propagation + input-byte taint; yields the
               comparison constants guarding each branch (an automatic
               fuzzing dictionary), per-branch input-byte dependency
               sets, and statically-dead / must-crash blocks
  lint.py      defect checks over both (slot collisions, unreachable
               blocks, empty modules, max_steps shortfalls, ...) —
               the ``kb-lint`` tool and the CI lint lane
  solver.py    path-condition collection + input synthesis — given a
               target edge, collect the branch conditions a path
               there must satisfy and solve them into concrete input
               bytes (exact for expect_byte chains and linear ALU
               compositions, budget-capped enumeration beyond, every
               emitted input concretely verified) — the ``kb-solve``
               tool and the fuzzing loop's plateau crack stage
"""

from .cfg import ControlFlowGraph, build_cfg, static_edge_prior
from .dataflow import (
    BranchFact, DataflowResult, analyze_dataflow,
    dictionary_candidates, extract_dictionary,
)
from .lint import Finding, lint_program
from .solver import (
    SolveResult, concrete_run, edge_dep_mask, solve_edge, solve_edges,
)

__all__ = [
    "ControlFlowGraph", "build_cfg", "static_edge_prior",
    "BranchFact", "DataflowResult", "analyze_dataflow",
    "dictionary_candidates", "extract_dictionary",
    "Finding", "lint_program",
    "SolveResult", "concrete_run", "edge_dep_mask", "solve_edge",
    "solve_edges",
]
