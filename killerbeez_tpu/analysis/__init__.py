"""Static analysis over KBVM programs.

Killerbeez's side tools (picker, tracer) learn about a target by
RUNNING it; the KBVM tier has the whole program text and the exact
static edge universe at build time (``vm.compute_edges``), so a class
of facts AFL can only estimate dynamically is simply computable here:

  cfg.py       control-flow graph reconstruction from the instruction
               array — reachability, dominators, loop headers, the
               longest loop-free path (validates ``max_steps``), and a
               static edge-frequency prior for rare-edge scheduling
  dataflow.py  abstract interpretation over the 8-register ISA —
               constant propagation + input-byte taint; yields the
               comparison constants guarding each branch (an automatic
               fuzzing dictionary), per-branch input-byte dependency
               sets, and statically-dead / must-crash blocks
  lint.py      defect checks over both (slot collisions, unreachable
               blocks, empty modules, max_steps shortfalls, ...) —
               the ``kb-lint`` tool and the CI lint lane
  solver.py    path-condition collection + input synthesis — given a
               target edge, collect the branch conditions a path
               there must satisfy and solve them into concrete input
               bytes (exact for expect_byte chains and linear ALU
               compositions, budget-capped enumeration beyond, every
               emitted input concretely verified) — the ``kb-solve``
               tool and the fuzzing loop's plateau crack stage
  conformance.py  counterexample-guided proxy conformance — ingest
               the hybrid tier's proxy-gap reports, replay-cluster
               them through the reference interpreter, localize the
               diverging guard (``kbz-proxy-blame-v1``), and lint
               the gap backlog / drift (``kb-lint --gaps-dir``)
  repair.py    verified proxy repair — bounded typed patch space
               over the blamed guard, accepted ONLY when verdict-
               identical to the native tier on every accumulated
               counterexample + certification seed; anything else is
               an honest ``unrepairable`` — the ``kb-repair`` tool
               and the fuzzing loop's ``--auto-repair`` stage
  vsa.py       value-set analysis — a second abstract-interpretation
               fixpoint over a reduced product of strided intervals
               and small value sets per register and input byte,
               int32-exact transfer functions, affine byte
               provenance; every published domain checkable by
               concrete replay (``check_replay``).  Consumers:
               solver seeding (``kb-solve --vsa``), grammar
               alphabets (``derive_grammar(vsa=)``), value priors
               (priors.py), and the infeasible-edge lint class
               (``kb-lint --vsa``)
  priors.py    static per-position value histograms from VSA — the
               ``kbz-value-prior-v1`` sidecar initializing ROADMAP
               item 4's value-conditioned model
"""

from .cfg import ControlFlowGraph, build_cfg, static_edge_prior
from .conformance import (
    BLAME_SCHEMA, GAP_SCHEMA, BlameRecord, GapParseError, GapReport,
    conformance_lint, load_gap_reports, localize, parse_gap_report,
    replay_gaps, verdict_class,
)
from .dataflow import (
    BranchFact, DataflowResult, analyze_dataflow,
    dictionary_candidates, extract_dictionary,
)
from .lint import Finding, lint_program
from .repair import (
    REPAIR_SCHEMA, Patch, apply_patch, enumerate_patches, run_repair,
    save_patched_program, write_repair_ledger,
)
from .priors import PRIOR_SCHEMA, load_priors, save_priors, value_priors
from .solver import (
    SolveResult, concrete_run, edge_dep_mask, solve_edge,
    solve_edge_vsa, solve_edges, vsa_seed_domains,
)
from .vsa import (
    VSA_SCHEMA, VDom, VsaFact, VsaResult, analyze_vsa, check_replay,
    program_sig, vsa_stats,
)

__all__ = [
    "ControlFlowGraph", "build_cfg", "static_edge_prior",
    "BranchFact", "DataflowResult", "analyze_dataflow",
    "dictionary_candidates", "extract_dictionary",
    "Finding", "lint_program",
    "SolveResult", "concrete_run", "edge_dep_mask", "solve_edge",
    "solve_edge_vsa", "solve_edges", "vsa_seed_domains",
    "VSA_SCHEMA", "VDom", "VsaFact", "VsaResult", "analyze_vsa",
    "check_replay", "program_sig", "vsa_stats",
    "PRIOR_SCHEMA", "value_priors", "save_priors", "load_priors",
    "GAP_SCHEMA", "BLAME_SCHEMA", "REPAIR_SCHEMA",
    "GapReport", "GapParseError", "BlameRecord", "Patch",
    "parse_gap_report", "load_gap_reports", "replay_gaps",
    "localize", "verdict_class", "conformance_lint",
    "apply_patch", "enumerate_patches", "run_repair",
    "save_patched_program", "write_repair_ledger",
]
