"""Verified proxy repair: bounded patch synthesis over blamed guards.

The synthesis half of the conformance loop (conformance.py is the
analysis half).  Given a blamed branch and the cluster of gap
counterexamples that indict it, search a **bounded, typed patch
space** and accept a candidate ONLY under the honesty contract:

    a patch is accepted iff the patched program is verdict-identical
    to the native tier on EVERY accumulated gap input AND still
    passes the binding's bind-time certification seeds (benign +
    crash reproducers).  Anything else is an honest ``unrepairable``
    verdict with a machine-readable reason — never a silent
    best-effort patch.

The patch space (all row-local — pcs never shift, so coverage block
ids, module ranges and the rest of the static universe survive):

  ===============  ==================================================
  kind             rewrite at the blamed site
  ===============  ==================================================
  const-nudge      the nearest preceding ``LDI`` that loads the
                   guard's constant is re-aimed at the operand values
                   the counterexamples actually observed (±1)
  negate-cmp       flip the comparison (eq<->ne, lt<->ge)
  force-taken      replace the branch with ``JMP target`` (delete
                   the guard, always take)
  force-fall       replace the branch with ``JMP pc+1`` (delete the
                   guard, never take)
  retarget-crash   re-aim the branch target at a must-crash pc (add
                   a crash guard: native crashes where the proxy
                   exits clean)
  ===============  ==================================================

Every candidate is verified through the lockstep reference
interpreter (solver.concrete_run) — the same "a solved result is
always concretely verified" guarantee the crack stage makes.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..models.vm import (
    OP_BR, OP_JMP, OP_LDI, Program,
)
from .conformance import (
    BlameRecord, GapCluster, ReplayResult, load_gap_reports, localize,
    replay_gaps, verdict_class,
)
from .dataflow import analyze_dataflow
from .solver import ConcreteTrace, concrete_run

REPAIR_SCHEMA = "kbz-proxy-repair-v1"

#: total candidate patches tried per cluster (bounded search)
MAX_PATCHES_PER_CLUSTER = 32

#: instruction-window scanned backwards for the guarding LDI
CONST_SCAN_WINDOW = 8

#: LDI immediates must stay inside the engine's exact-field bound
_FIELD_BOUND = (1 << 24) - 1

_NEGATE = {0: 1, 1: 0, 2: 3, 3: 2}      # eq<->ne, lt<->ge


@dataclass
class Patch:
    """One row-local rewrite."""

    kind: str
    pc: int                         # rewritten instruction
    site_pc: int                    # blamed branch it services
    old_row: Tuple[int, int, int, int]
    new_row: Tuple[int, int, int, int]

    @property
    def desc(self) -> str:
        return (f"{self.kind}@pc{self.pc}:"
                f"{list(self.old_row)}->{list(self.new_row)}")

    def as_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "pc": self.pc,
                "site_pc": self.site_pc,
                "old": list(self.old_row), "new": list(self.new_row)}


@dataclass
class Obligation:
    """One input the patched program must classify exactly like the
    native tier."""

    label: str
    buf: bytes
    expect_cls: str


def _row(program, pc: int) -> Tuple[int, int, int, int]:
    return tuple(int(v) for v in np.asarray(program.instrs)[pc])


def apply_patch(program: Program, patch: Patch) -> Program:
    """New Program with one row rewritten; edges/universe recomputed
    by Program construction, coverage identity preserved."""
    instrs = np.array(program.instrs, dtype=np.int32, copy=True)
    instrs[patch.pc] = patch.new_row
    return Program(
        instrs=instrs, name=program.name,
        mem_size=program.mem_size, max_steps=program.max_steps,
        n_blocks=program.n_blocks, block_ids=program.block_ids,
        modules=program.modules)


def _guard_ldi(program, site_pc: int, ra: int, rb: int,
               ) -> Optional[Tuple[int, int]]:
    """The nearest preceding LDI (within a bounded window, not past
    control flow) defining one of the branch's operand registers.
    Returns (pc, reg) or None."""
    instrs = np.asarray(program.instrs)
    for p in range(site_pc - 1,
                   max(-1, site_pc - 1 - CONST_SCAN_WINDOW), -1):
        op, a, b, c = (int(v) for v in instrs[p])
        if op in (OP_BR, OP_JMP):
            return None             # merge point: scan unsound
        if op == OP_LDI and (a & 7) in (ra, rb):
            return p, (a & 7)
    return None


def enumerate_patches(program: Program, blame: BlameRecord,
                      dataflow=None) -> List[Patch]:
    """The bounded, typed patch space for one blame record — most
    targeted first.  The verifier is the soundness gate; this only
    proposes."""
    dataflow = dataflow or analyze_dataflow(program)
    instrs = np.asarray(program.instrs)
    ni = instrs.shape[0]
    crash_pcs = sorted(getattr(dataflow, "crash_pcs", ()) or ())
    out: List[Patch] = []

    for site in blame.candidates or [blame.pc]:
        if not (0 <= site < ni):
            continue
        op, a, b, c = (int(v) for v in instrs[site])
        if op != OP_BR:
            continue
        row = (op, a, b, c)
        ra, rb = a & 7, (b >> 2) & 7
        obs = blame.observed if site == blame.pc else []

        # 1. const-nudge: re-aim the guarding LDI at the operand
        #    values the counterexamples observed
        found = _guard_ldi(program, site, ra, rb)
        if found is not None:
            lpc, lreg = found
            lrow = _row(program, lpc)
            # the OTHER operand's observed values are the targets
            want: List[int] = []
            for x, y, _tk in obs:
                v = y if lreg == ra else x
                for cand in (v, v + 1, v - 1):
                    if abs(cand) <= _FIELD_BOUND and \
                            cand != lrow[2] and cand not in want:
                        want.append(cand)
            for v in want[:6]:
                out.append(Patch(
                    kind="const-nudge", pc=lpc, site_pc=site,
                    old_row=lrow,
                    new_row=(lrow[0], lrow[1], v, lrow[3])))

        # 2. negate-cmp
        out.append(Patch(
            kind="negate-cmp", pc=site, site_pc=site, old_row=row,
            new_row=(op, a, (b & ~3) | _NEGATE[b & 3], c)))

        # 3/4. delete the guard (always / never taken)
        if 0 <= c < ni:
            out.append(Patch(kind="force-taken", pc=site,
                             site_pc=site, old_row=row,
                             new_row=(OP_JMP, c, 0, 0)))
        if site + 1 < ni:
            out.append(Patch(kind="force-fall", pc=site,
                             site_pc=site, old_row=row,
                             new_row=(OP_JMP, site + 1, 0, 0)))

        # 5. add a crash guard: branch into a must-crash pc
        for cpc in crash_pcs[:2]:
            if cpc != c:
                out.append(Patch(
                    kind="retarget-crash", pc=site, site_pc=site,
                    old_row=row, new_row=(op, a, b, int(cpc))))

        if len(out) >= MAX_PATCHES_PER_CLUSTER:
            break
    return out[:MAX_PATCHES_PER_CLUSTER]


def verify_program(program: Program, obligations: List[Obligation],
                   trace_cache: Optional[Dict[bytes, ConcreteTrace]]
                   = None) -> List[Dict[str, Any]]:
    """Replay every obligation; returns the failures ([] = verified).
    The cache must be private to one candidate program — traces are
    keyed by input only."""
    failures = []
    cache: Dict[bytes, ConcreteTrace] = \
        trace_cache if trace_cache is not None else {}
    for ob in obligations:
        trace = cache.get(ob.buf)
        if trace is None:
            trace = concrete_run(program, ob.buf)
            cache[ob.buf] = trace
        got = verdict_class(trace.status)
        if got != ob.expect_cls:
            failures.append({"label": ob.label,
                             "expect": ob.expect_cls, "got": got})
    return failures


# --------------------------------------------------------------------
# the repair driver
# --------------------------------------------------------------------

def certification_obligations(binding, program: Program
                              ) -> List[Obligation]:
    """Bind-time seeds as repair obligations.  Expected classes come
    from the ORIGINAL proxy — certification guarantees they equal
    the native tier's, so no native execution is needed here."""
    obs = [Obligation(
        label="cert:benign", buf=bytes(binding.benign_seed),
        expect_cls=verdict_class(
            concrete_run(program, bytes(binding.benign_seed)).status))]
    for i, seed in enumerate(getattr(binding, "crash_seeds", ()) or ()):
        obs.append(Obligation(
            label=f"cert:crash[{i}]", buf=bytes(seed),
            expect_cls=verdict_class(
                concrete_run(program, bytes(seed)).status)))
    return obs


def _repair_cluster(program: Program, cluster: GapCluster,
                    obligations: List[Obligation], dataflow
                    ) -> Tuple[Optional[Program], Optional[Patch],
                               Optional[BlameRecord], str]:
    """Try to patch one cluster.  Returns (patched program, patch,
    blame, reason) — program None when unrepairable."""
    cluster_obs = [
        Obligation(label=f"gap:{rep.md5[:12]}", buf=rep.input,
                   expect_cls=cluster.native_cls)
        for rep in cluster.reports]
    if not verify_program(program, cluster_obs):
        # an earlier cluster's patch already bent these inputs to
        # the native verdict — nothing left to synthesize
        return program, None, None, "already-conformant"
    blame = localize(program, cluster, dataflow)
    if blame is None:
        return None, None, None, "blame:no-input-dependent-branch"
    patches = enumerate_patches(program, blame, dataflow)
    if not patches:
        return None, None, blame, "patch:empty-space"
    for patch in patches:
        candidate = apply_patch(program, patch)
        if not verify_program(candidate, obligations + cluster_obs):
            return candidate, patch, blame, "repaired"
    return None, None, blame, "patch:space-exhausted"


def run_repair(binding, gaps_dir: str,
               backlog_threshold: int = 0,
               now: Optional[float] = None
               ) -> Tuple[Dict[str, Any], Optional[Program]]:
    """The full counterexample-guided repair pass for one binding.

    Returns ``(result, patched_program)``; result carries schema
    ``kbz-proxy-repair-v1`` and status:

    * ``repaired``     — every divergence cluster got a verified
      patch and the FINAL program is verdict-identical to the native
      tier on all gap inputs + all certification seeds.
    * ``unrepairable`` — at least one cluster resisted the bounded
      patch space (or verification failed); per-cluster
      machine-readable reasons.  ``patched_program`` is None: no
      silent best-effort.
    * ``no-gaps``      — nothing to do (no reports, or all stale).
    """
    t0 = now if now is not None else time.time()
    program = binding.program()
    reports, rejects = load_gap_reports(gaps_dir)
    result: Dict[str, Any] = {
        "schema": REPAIR_SCHEMA,
        "binding": binding.name,
        "proxy_target": binding.proxy_target,
        "gaps_dir": gaps_dir,
        "reports": len(reports),
        "rejects": [{"file": f, "reason": r} for f, r in rejects],
        "t": round(t0, 3),
    }
    mine = [r for r in reports if r.binding == binding.name]
    result["foreign"] = len(reports) - len(mine)
    if not mine:
        result.update(status="no-gaps", reason="gap:none-for-binding",
                      clusters=[])
        return result, None
    trace_cache: Dict[bytes, ConcreteTrace] = {}
    replay: ReplayResult = replay_gaps(program, mine, trace_cache)
    result["stale"] = len(replay.stale)
    result["skipped"] = [
        {"md5": rep.md5, "reason": why}
        for rep, why in replay.skipped]
    if not replay.clusters:
        if replay.skipped and not replay.stale:
            result.update(status="unrepairable",
                          reason="gap:no-replayable-inputs",
                          clusters=[])
        else:
            result.update(status="no-gaps", reason="gap:all-stale",
                          clusters=[])
        return result, None

    dataflow = analyze_dataflow(program)
    cert_obs = certification_obligations(binding, program)
    result["obligations"] = {
        "certification": [o.label for o in cert_obs],
        "gap_inputs": sum(len(c.reports) for c in replay.clusters),
    }
    clusters_out: List[Dict[str, Any]] = []
    patched = program
    done_obs: List[Obligation] = []     # repaired clusters' inputs
    all_ok = True
    # big clusters first: most counterexamples, strongest evidence
    for cluster in sorted(replay.clusters,
                          key=lambda c: -len(c.reports)):
        crec: Dict[str, Any] = {
            "edge": list(cluster.edge) if cluster.edge else None,
            "proxy_cls": cluster.proxy_cls,
            "native_cls": cluster.native_cls,
            "inputs": [r.md5 for r in cluster.reports],
        }
        prog2, patch, blame, reason = _repair_cluster(
            patched, cluster, cert_obs + done_obs, dataflow)
        crec["blame"] = blame.as_dict() if blame else None
        crec["status"] = "repaired" if prog2 is not None \
            else "unrepairable"
        if prog2 is not None:
            crec["patch"] = patch.as_dict() if patch else None
            crec["patch_desc"] = patch.desc if patch else reason
            patched = prog2
            # later clusters must keep THIS cluster fixed
            done_obs += [
                Obligation(label=f"gap:{rep.md5[:12]}",
                           buf=rep.input,
                           expect_cls=cluster.native_cls)
                for rep in cluster.reports]
            # patched program changed: facts must be recomputed for
            # the next cluster's localization/patch proposals
            dataflow = analyze_dataflow(patched)
        else:
            crec["reason"] = reason
            all_ok = False
        clusters_out.append(crec)
    result["clusters"] = clusters_out
    if not all_ok:
        reasons = sorted({c.get("reason") for c in clusters_out
                          if c.get("status") == "unrepairable"})
        result.update(status="unrepairable",
                      reason=";".join(r for r in reasons if r))
        return result, None
    # final gate: the WHOLE obligation set against the final program
    final_failures = verify_program(patched, cert_obs + done_obs)
    if final_failures:
        result.update(status="unrepairable",
                      reason="verify:final-program",
                      failures=final_failures)
        return result, None
    result.update(status="repaired", reason=None,
                  patches=[c["patch"] for c in clusters_out
                           if c.get("patch")])
    return result, patched


# --------------------------------------------------------------------
# artifacts
# --------------------------------------------------------------------

def save_patched_program(program: Program, path: str) -> str:
    """Write the patched proxy as a loadable ``.npz`` (the
    load_program_from_options / ProxyBinding.program_file format)."""
    payload: Dict[str, Any] = {
        "instrs": np.asarray(program.instrs, dtype=np.int32),
        "name": np.asarray(f"{program.name}+repaired"),
        "mem_size": np.asarray(int(program.mem_size)),
        "max_steps": np.asarray(int(program.max_steps)),
        "n_blocks": np.asarray(int(program.n_blocks)),
        "block_ids": np.asarray([int(b) for b in program.block_ids],
                                dtype=np.int32),
    }
    if program.modules:
        payload["module_names"] = np.asarray(
            [m[0] for m in program.modules])
        payload["modules_lo"] = np.asarray(
            [int(m[1]) for m in program.modules], dtype=np.int32)
        payload["modules_hi"] = np.asarray(
            [int(m[2]) for m in program.modules], dtype=np.int32)
    with open(path, "wb") as f:
        np.savez(f, **payload)
    return path


def write_repair_ledger(gaps_dir: str, result: Dict[str, Any]
                        ) -> int:
    """Fold one repair result into ``proxy_gaps/repairs.json`` — one
    ledger record per cluster (the conformance lint's consumed-set
    and drift baseline).  Returns how many records landed."""
    from ..hybrid.gaps import append_ledger

    n = 0
    for crec in result.get("clusters") or []:
        append_ledger(gaps_dir, {
            "binding": result["binding"],
            "edge": crec.get("edge"),
            "pc": (crec.get("blame") or {}).get("pc"),
            "status": crec.get("status"),
            "patch": crec.get("patch_desc"),
            "reason": crec.get("reason"),
            "consumed": list(crec.get("inputs") or []),
            "t": result.get("t"),
        })
        n += 1
    return n
