"""Path-condition collection and input synthesis over the KBVM.

The static layer (cfg.py / dataflow.py) already *describes* every
branch — which input bytes it reads, which constant guards it.  This
module closes the loop from description to ACTION: given a target
edge of the static universe, walk the instruction graph from entry,
collect the branch conditions a path to that edge must satisfy, and
solve them into a concrete input.  Angora needs dynamic byte-level
taint plus gradient search to do this against opaque binaries
(PAPERS.md); the KBVM tier reads the whole program text, so path
conditions are computed, not inferred.

Exactness tiers (honest by construction):

  * ``expect_byte``-style chains and linear LDI/ADDI/ALU
    compositions over single bytes solve EXACTLY: every constraint
    reduces to a domain filter over one 256-value byte (or the input
    length), evaluated under the engine's int32-wrap semantics.
  * multi-variable conditions (e.g. ``budget = b4 | (b5 << 8)``)
    fall back to budget-capped backtracking enumeration over the
    remaining domains.
  * loop-carried state is explored only up to ``max_visits`` passes
    per pc (default 2 — enough for once-around loop edges and
    two-command state machines); deeper iteration counts, symbolic
    memory indexing and checksum folds come back ``unknown``, never
    guessed.

The honesty guarantee the crack stage relies on: **a solved result
is always concretely verified** — the synthesized input is executed
through ``concrete_run`` (a pure-Python reference interpreter kept
in lockstep with ``vm._step``) and must actually traverse the target
edge before the solver will emit it.  ``unsat`` is only reported
when the edge is outside the static universe or every candidate path
was exhaustively refuted without hitting a budget/visit cap.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, List, Optional, Set, Tuple

import numpy as np

from .. import FUZZ_CRASH, FUZZ_HANG, FUZZ_NONE, FUZZ_RUNNING
from ..models.vm import (
    ALU_ADD, ALU_AND, ALU_MUL, ALU_OR, ALU_SHL, ALU_SHR, ALU_SUB,
    ALU_XOR, N_REGS,
    OP_ADDI, OP_ALU, OP_BLOCK, OP_BR, OP_CRASH, OP_HALT, OP_JMP,
    OP_LDB, OP_LDI, OP_LDM, OP_LEN, OP_STM,
)
from ..models.vm import CMP_EQ, CMP_GE, CMP_LT, CMP_NE
from .cfg import ENTRY, instr_successors
from .dataflow import (
    ANY, CMP_NAMES, DataflowResult, _alu_const, _fold_cmp, _i32, _reg,
    analyze_dataflow,
)

#: DFS state-expansion budget per edge (a Python-side walk; typical
#: magic-byte chains solve in a few hundred expansions — the budget
#: bounds the unsolvable-edge worst case)
DEFAULT_BUDGET = 100_000

#: assignment tries for the multi-variable enumeration fallback
DEFAULT_ENUM_BUDGET = 8_192

#: how many times one pc may appear on a candidate path (2 = one
#: full loop revisit: enough for loop self-edges and two-command
#: interpreter-state machines; raise for deeper protocols)
DEFAULT_MAX_VISITS = 2

#: synthesized inputs are capped at this length unless overridden
DEFAULT_MAX_LEN = 64

#: the input-length variable (shares the byte-variable namespace)
LEN_VAR = ("len", -1)


# --------------------------------------------------------------------
# concrete reference interpreter (lockstep with vm._step)
# --------------------------------------------------------------------

@dataclass
class ConcreteTrace:
    """One scalar execution: verdict + the exact edge/block walk."""
    status: int                 # FUZZ_NONE / FUZZ_CRASH / FUZZ_HANG
    exit_code: int
    steps: int
    edges: List[Tuple[int, int]]    # (from block, to block), -1 = entry
    blocks: List[int]
    #: every OP_BR executed, in order: (pc, x, y, taken) with the
    #: concrete operand values at that step — the conformance pass's
    #: blame-localization evidence (analysis/conformance.py)
    branches: List[Tuple[int, int, int, bool]] = field(
        default_factory=list)


def concrete_run(program, data: bytes) -> ConcreteTrace:
    """Execute ``data`` through the program with exact engine
    semantics (field clips, int32 wraps, OOB LDB -> 0, OOB memory ->
    crash, step budget -> hang).  The solver's proof obligation and
    the dataflow tests' ground truth."""
    instrs = np.asarray(program.instrs)
    ni = instrs.shape[0]
    rows = [tuple(int(x) for x in instrs[pc]) for pc in range(ni)]
    mem = [0] * int(program.mem_size)
    regs = [0] * N_REGS
    L = len(data)
    pc, prev = 0, -1
    status, exit_code, steps = FUZZ_RUNNING, 0, 0
    edges: List[Tuple[int, int]] = []
    blocks: List[int] = []
    branches: List[Tuple[int, int, int, bool]] = []
    while status == FUZZ_RUNNING and steps < int(program.max_steps):
        steps += 1
        if pc < 0 or pc >= ni:
            status = FUZZ_CRASH
            break
        op, a, b, c = rows[pc]
        if op == OP_HALT:
            status, exit_code = FUZZ_NONE, a
        elif op == OP_BLOCK:
            edges.append((prev, b))     # b = block ordinal (compute_edges)
            blocks.append(b)
            prev = b
            pc += 1
        elif op == OP_LDB:
            i = regs[_reg(b)]
            regs[_reg(a)] = data[i] if 0 <= i < L else 0
            pc += 1
        elif op == OP_LDI:
            regs[_reg(a)] = _i32(b)
            pc += 1
        elif op == OP_ALU:
            x, y = regs[_reg(b)], regs[(c >> 3) & (N_REGS - 1)]
            regs[_reg(a)] = _alu_const(c & 7, x, y)
            pc += 1
        elif op == OP_ADDI:
            regs[_reg(a)] = _i32(regs[_reg(b)] + c)
            pc += 1
        elif op == OP_JMP:
            pc = a
        elif op == OP_BR:
            x, y = regs[_reg(a)], regs[(b >> 2) & (N_REGS - 1)]
            taken = _fold_cmp(b & 3, x, y)
            branches.append((pc, x, y, bool(taken)))
            pc = c if taken else pc + 1
        elif op == OP_CRASH:
            status = FUZZ_CRASH
        elif op == OP_LEN:
            regs[_reg(a)] = L
            pc += 1
        elif op == OP_LDM:
            i = regs[_reg(b)]
            if not (0 <= i < program.mem_size):
                status = FUZZ_CRASH
            else:
                regs[_reg(a)] = mem[i]
                pc += 1
        elif op == OP_STM:
            i = regs[_reg(a)]
            if not (0 <= i < program.mem_size):
                status = FUZZ_CRASH
            else:
                mem[i] = regs[_reg(b)]
                pc += 1
        else:                           # unknown op: engine falls through
            pc += 1
    if status == FUZZ_RUNNING:
        status = FUZZ_HANG
    return ConcreteTrace(status=status, exit_code=exit_code, steps=steps,
                         edges=edges, blocks=blocks, branches=branches)


# --------------------------------------------------------------------
# symbolic values and constraints
# --------------------------------------------------------------------

class Sym:
    """An abstract register value along ONE path: a closure over the
    input variables it reads (``('byte', i)`` / ``LEN_VAR``), exact
    under int32 wrap.  ``opaque`` marks values the closure tier
    cannot evaluate (symbolic memory indexing) — constraints over
    them defer entirely to concrete verification."""

    __slots__ = ("vars", "opaque", "fn", "desc")

    def __init__(self, vars: FrozenSet, opaque: bool,
                 fn: Optional[Callable], desc: str):
        self.vars = vars
        self.opaque = opaque
        self.fn = fn
        self.desc = desc


def _const(v: int) -> Sym:
    v = _i32(v)
    return Sym(frozenset(), False, lambda env, v=v: v, str(v))


def _varsym(var) -> Sym:
    name = "len" if var == LEN_VAR else f"input[{var[1]}]"
    return Sym(frozenset([var]), False, lambda env, var=var: env[var],
               name)


def _opaque(vars: FrozenSet) -> Sym:
    return Sym(vars, True, None, "?")


_ALU_FNS = {
    ALU_ADD: (lambda x, y: x + y, "+"),
    ALU_SUB: (lambda x, y: x - y, "-"),
    ALU_AND: (lambda x, y: (x & 0xFFFFFFFF) & (y & 0xFFFFFFFF), "&"),
    ALU_OR: (lambda x, y: (x & 0xFFFFFFFF) | (y & 0xFFFFFFFF), "|"),
    ALU_XOR: (lambda x, y: (x & 0xFFFFFFFF) ^ (y & 0xFFFFFFFF), "^"),
    ALU_SHL: (lambda x, y: (x & 0xFFFFFFFF) << min(max(y, 0), 31), "<<"),
    ALU_SHR: (lambda x, y: (x & 0xFFFFFFFF) >> min(max(y, 0), 31), ">>"),
    ALU_MUL: (lambda x, y: x * y, "*"),
}


def _binop(sel: int, x: Sym, y: Sym) -> Sym:
    f, opname = _ALU_FNS[sel]
    if x.opaque or y.opaque:
        return _opaque(x.vars | y.vars)
    if not x.vars and not y.vars:
        return _const(f(x.fn({}), y.fn({})))
    return Sym(x.vars | y.vars, False,
               lambda env, f=f, x=x, y=y: _i32(f(x.fn(env), y.fn(env))),
               f"({x.desc}{opname}{y.desc})")


class Constraint:
    """One path condition: a predicate over input variables that the
    chosen path requires to hold."""

    __slots__ = ("vars", "opaque", "pred", "desc")

    def __init__(self, vars: FrozenSet, opaque: bool,
                 pred: Optional[Callable], desc: str):
        self.vars = vars
        self.opaque = opaque
        self.pred = pred
        self.desc = desc


def _br_constraint(pc: int, sel: int, x: Sym, y: Sym,
                   want: bool) -> Constraint:
    opaque = x.opaque or y.opaque
    pred = None if opaque else (
        lambda env, sel=sel, x=x, y=y, want=want:
        _fold_cmp(sel, x.fn(env), y.fn(env)) is want)
    return Constraint(x.vars | y.vars, opaque, pred,
                      f"pc {pc}: {x.desc} {CMP_NAMES[sel]} {y.desc}"
                      f" is {want}")


def _range_constraint(pc: int, idx: Sym, size: int,
                      what: str) -> Constraint:
    pred = None if idx.opaque else (
        lambda env, idx=idx, size=size: 0 <= idx.fn(env) < size)
    return Constraint(idx.vars, idx.opaque, pred,
                      f"pc {pc}: 0 <= {idx.desc} < {size} ({what})")


def _len_constraint(i: int) -> Constraint:
    return Constraint(frozenset([LEN_VAR]), False,
                      lambda env, i=i: env[LEN_VAR] >= i + 1,
                      f"len >= {i + 1}")


def _add_constraints(new_cs, domains, deferred):
    """Fold constraints into the domain state.  Returns the updated
    ``(domains, deferred)`` or None when provably infeasible.  Fully
    pinned constraints check immediately; single-free-variable
    constraints filter that variable's domain (exact — the domain is
    at most 256 bytes values or the length range); multi-variable and
    opaque constraints defer.  A domain shrink re-queues deferred
    constraints that mention the variable."""
    domains = dict(domains)
    deferred = list(deferred)
    queue = list(new_cs)
    while queue:
        c = queue.pop()
        if c.opaque:
            deferred.append(c)
            continue
        env = {v: next(iter(domains[v])) for v in c.vars
               if len(domains[v]) == 1}
        free = [v for v in c.vars if len(domains[v]) > 1]
        if not free:
            if not c.pred(env):
                return None
            continue
        if len(free) > 1:
            deferred.append(c)
            continue
        v = free[0]
        keep = frozenset(x for x in domains[v]
                         if c.pred({**env, v: x}))
        if not keep:
            return None
        if keep != domains[v]:
            domains[v] = keep
            still = []
            for d in deferred:
                if not d.opaque and v in d.vars:
                    queue.append(d)
                else:
                    still.append(d)
            deferred = still
    return domains, deferred


def _enum_deferred(hard: List[Constraint], domains, budget: int):
    """Backtracking search for an assignment satisfying the deferred
    multi-variable constraints.  Returns the (possibly empty)
    assignment dict, or None when refuted/budget-exhausted; the
    second value reports budget exhaustion."""
    free = sorted({v for c in hard for v in c.vars
                   if len(domains[v]) > 1},
                  key=lambda v: (len(domains[v]), v))
    pinned = {v: next(iter(domains[v]))
              for c in hard for v in c.vars if len(domains[v]) == 1}
    assignment: Dict = {}
    tries = [0]

    def ok() -> bool:
        env = {**pinned, **assignment}
        for c in hard:
            if all(v in env for v in c.vars):
                if not c.pred(env):
                    return False
        return True

    def search(i: int) -> Optional[bool]:
        if tries[0] > budget:
            return None                 # budget bail
        if i == len(free):
            return ok()
        v = free[i]
        for x in sorted(domains[v]):
            tries[0] += 1
            if tries[0] > budget:
                return None
            assignment[v] = x
            if ok():
                r = search(i + 1)
                if r:
                    return r
                if r is None:
                    return None
            assignment.pop(v, None)
        return False

    r = search(0)
    if r is None:
        return None, True
    if not r:
        return None, False
    return dict(assignment), False


# --------------------------------------------------------------------
# the edge solver
# --------------------------------------------------------------------

@dataclass
class SolveResult:
    """Outcome of one edge-cracking attempt.

    ``status``:
      solved   — ``input`` concretely traverses the edge (verified
                 against the reference interpreter; never guessed)
      unsat    — the edge is outside the static universe, or every
                 candidate path was exhaustively refuted
      unknown  — budget / visit-cap / modeling-tier limits; honest
                 "can't tell", NOT "no"
    """
    edge: Tuple[int, int]
    status: str
    input: Optional[bytes] = None
    reason: str = ""
    conditions: List[str] = field(default_factory=list)
    paths_tried: int = 0
    expansions: int = 0
    #: set ONLY by solve_edge_vsa (the --vsa path): seeded byte
    #: domains, escalation ladder, and — on unsat — the exhaustive-
    #: refutation certificate.  None on the default path, so the
    #: no-flag JSON surface stays bit-identical (the parity anchor)
    vsa: Optional[Dict] = None

    def as_dict(self) -> Dict:
        d = {"edge": list(self.edge), "status": self.status,
             "reason": self.reason, "paths_tried": self.paths_tried,
             "expansions": self.expansions}
        if self.input is not None:
            d["input_hex"] = self.input.hex()
            d["length"] = len(self.input)
        if self.conditions:
            d["conditions"] = self.conditions
        if self.vsa is not None:
            d["vsa"] = self.vsa
        return d


def unknown_kind(reason: str) -> str:
    """Stable category of an ``unknown`` reason string — the search
    tier's intake taxonomy, pinned by regression fixtures so a solver
    change that silently reshapes the frontier is caught:

      budget    — the path-search / enumeration budget ran out first
                  (raising --budget may flip the verdict)
      visit-cap — loop-carried state beyond max_visits passes is not
                  modeled (checksum-style loops; raising the cap
                  rarely helps — this is the descent tier's intake)
      model     — the bounded input model intervened (reads forced
                  in-bounds, length capped at max_len)
    """
    if "budget" in reason:
        return "budget"
    if "visit" in reason:
        return "visit-cap"
    if "bounded input model" in reason:
        return "model"
    return "other"


@dataclass
class _State:
    pc: int
    regs: tuple
    mem: Dict[int, Sym]
    mem_havoc: bool
    last_block: int
    steps: int
    visits: Dict[int, int]
    domains: Dict
    deferred: tuple
    conds: tuple


def _instr_reach(instrs, ni: int, target_pc: int) -> Tuple[Set[int],
                                                           Dict[int, int]]:
    """(pcs from which target_pc is reachable, BFS distance to it) —
    the DFS prune and the try-nearer-successors-first ordering.
    Successors come from ``cfg.instr_successors`` (one definition of
    the instruction semantics for cfg/dataflow/solver alike)."""
    preds: Dict[int, List[int]] = {pc: [] for pc in range(ni)}
    for pc in range(ni):
        for s in instr_successors(instrs, pc):
            if 0 <= s < ni:
                preds[s].append(pc)
    dist = {target_pc: 0}
    frontier = [target_pc]
    while frontier:
        nxt = []
        for n in frontier:
            for p in preds[n]:
                if p not in dist:
                    dist[p] = dist[n] + 1
                    nxt.append(p)
        frontier = nxt
    return set(dist), dist


def solve_edge(program, edge: Tuple[int, int], *,
               budget: int = DEFAULT_BUDGET,
               enum_budget: int = DEFAULT_ENUM_BUDGET,
               max_visits: int = DEFAULT_MAX_VISITS,
               max_len: int = DEFAULT_MAX_LEN,
               fill: int = 0,
               vsa_seeds: Optional[Dict] = None) -> SolveResult:
    """Synthesize an input whose execution traverses ``edge``
    (a ``(from_block, to_block)`` pair of the static universe,
    ``-1`` = entry).

    ``vsa_seeds`` (``('byte', i) -> frozenset``) replaces the
    256-value top domain of the named byte variables at creation —
    sound only when every seed is a NECESSARY condition of reaching
    the edge (``vsa_seed_domains`` computes exactly those, from VSA
    guards that instruction-dominate the target with one side forced)
    — a seeded refutation therefore remains exhaustive."""
    f_idx, t_idx = int(edge[0]), int(edge[1])
    pairs = set(zip(np.asarray(program.edge_from).tolist(),
                    np.asarray(program.edge_to).tolist()))
    if (f_idx, t_idx) not in pairs:
        return SolveResult(edge=(f_idx, t_idx), status="unsat",
                           reason="edge not in the static universe")
    instrs = np.asarray(program.instrs)
    ni = instrs.shape[0]
    rows = [tuple(int(x) for x in instrs[pc]) for pc in range(ni)]
    block_pcs = [pc for pc in range(ni) if rows[pc][0] == OP_BLOCK]
    t_head = block_pcs[t_idx]
    mem_size = int(program.mem_size)
    max_steps = int(program.max_steps)
    can_reach, dist = _instr_reach(instrs, ni, t_head)
    if 0 not in can_reach:
        return SolveResult(edge=(f_idx, t_idx), status="unsat",
                           reason="target block unreachable from entry")

    init = _State(pc=0, regs=tuple(_const(0) for _ in range(N_REGS)),
                  mem={}, mem_havoc=False, last_block=ENTRY, steps=0,
                  visits={}, domains={LEN_VAR:
                                      frozenset(range(max_len + 1))},
                  deferred=(), conds=())
    stack = [init]
    expansions = paths_tried = 0
    capped = False                      # any visit-cap / budget prune?
    # the model is an UNDER-approximation in two places — LDB reads
    # are modeled in-bounds only (the short-input zero-read
    # alternative is dropped) and the length domain is clipped at
    # max_len (a constraint satisfiable only by longer inputs reads
    # as refuted) — so the moment either is exercised, "exhaustively
    # refuted" can no longer be claimed and unsat degrades to unknown
    restricted = False

    def finalize(st: _State) -> Optional[Tuple[bytes, List[str]]]:
        nonlocal capped
        hard = [c for c in st.deferred if not c.opaque]
        assignment: Dict = {}
        if hard:
            assignment, bailed = _enum_deferred(hard, st.domains,
                                                enum_budget)
            if bailed:
                capped = True
            if assignment is None:
                return None
        env = {v: assignment.get(v, min(dom))
               for v, dom in st.domains.items()}
        byte_vars = [v for v in env if v != LEN_VAR]
        length = env.get(LEN_VAR,
                         max((v[1] + 1 for v in byte_vars), default=1))
        length = max(min(length, max_len), 1)
        data = bytearray([fill & 0xFF]) * length
        for v in byte_vars:
            if 0 <= v[1] < length:
                data[v[1]] = env[v] & 0xFF
        buf = bytes(data)
        trace = concrete_run(program, buf)
        if (f_idx, t_idx) in trace.edges:
            return buf, list(st.conds)
        return None

    while stack:
        if expansions >= budget:
            return SolveResult(
                edge=(f_idx, t_idx), status="unknown",
                reason=f"path-search budget exhausted "
                       f"({budget} expansions)",
                paths_tried=paths_tried, expansions=expansions)
        expansions += 1
        st = stack.pop()
        pc = st.pc
        if pc < 0 or pc >= ni or pc not in can_reach:
            continue                    # crash / cannot reach target
        if st.steps + 1 > max_steps:
            capped = True
            continue
        op, a, b, c = rows[pc]
        # -- target arrival: executing t's head right after block f --
        if op == OP_BLOCK and pc == t_head and st.last_block == f_idx:
            paths_tried += 1
            got = finalize(st)
            if got is not None:
                buf, conds = got
                return SolveResult(edge=(f_idx, t_idx), status="solved",
                                   input=buf, conditions=conds,
                                   paths_tried=paths_tried,
                                   expansions=expansions)
            # extensions past a failed arrival are not explored, so
            # exhaustiveness no longer holds on this subtree
            capped = True
            continue
        if st.visits.get(pc, 0) >= max_visits:
            capped = True
            continue
        st.visits = {**st.visits, pc: st.visits.get(pc, 0) + 1}
        st.steps += 1

        if op == OP_BLOCK:
            st.last_block = b           # ordinal (compute_edges rewrote)
            st.pc = pc + 1
            stack.append(st)
            continue
        if op in (OP_HALT, OP_CRASH):
            continue                    # terminal: target not reached
        if op == OP_JMP:
            st.pc = a
            stack.append(st)
            continue
        if op == OP_BR:
            sel = b & 3
            x = st.regs[_reg(a)]
            y = st.regs[(b >> 2) & (N_REGS - 1)]
            if LEN_VAR in (x.vars | y.vars):
                restricted = True       # length domain capped at
            branches = []               # max_len: not exhaustive
            for want, succ in ((True, c), (False, pc + 1)):
                if not (0 <= succ < ni) or succ not in can_reach:
                    continue
                folded = _add_constraints(
                    [_br_constraint(pc, sel, x, y, want)],
                    st.domains, st.deferred)
                if folded is None:
                    continue
                dom, defer = folded
                cdesc = f"pc {pc}: {x.desc} {CMP_NAMES[sel]} " \
                        f"{y.desc} is {want}"
                branches.append((succ, dom, tuple(defer),
                                 st.conds + (cdesc,)))
            # push farther-from-target first so the nearer branch
            # pops (and solves) first
            branches.sort(key=lambda t: -dist.get(t[0], 1 << 30))
            for succ, dom, defer, conds in branches:
                stack.append(_State(
                    pc=succ, regs=st.regs, mem=dict(st.mem),
                    mem_havoc=st.mem_havoc, last_block=st.last_block,
                    steps=st.steps, visits=dict(st.visits),
                    domains=dom, deferred=defer, conds=conds))
            continue

        # -- straight-line register/memory ops -----------------------
        regs = list(st.regs)
        if op == OP_LDB:
            idx = regs[_reg(b)]
            i = _concrete(idx, st.domains)
            if i is not None:
                if i < 0:
                    regs[_reg(a)] = _const(0)
                elif i > max_len - 1:
                    restricted = True   # would need len > max_len
                    continue
                else:
                    var = ("byte", i)
                    restricted = True   # in-bounds read modeled only
                    if var not in st.domains:
                        dom = frozenset(range(256))
                        if vsa_seeds:
                            dom = vsa_seeds.get(var, dom)
                        st.domains = {**st.domains, var: dom}
                    folded = _add_constraints([_len_constraint(i)],
                                              st.domains, st.deferred)
                    if folded is None:
                        continue
                    st.domains, defer = folded
                    st.deferred = tuple(defer)
                    regs[_reg(a)] = _varsym(var)
            else:
                regs[_reg(a)] = _opaque(idx.vars)
        elif op == OP_LDI:
            regs[_reg(a)] = _const(b)
        elif op == OP_ALU:
            regs[_reg(a)] = _binop(c & 7, regs[_reg(b)],
                                   regs[(c >> 3) & (N_REGS - 1)])
        elif op == OP_ADDI:
            regs[_reg(a)] = _binop(ALU_ADD, regs[_reg(b)], _const(c))
        elif op == OP_LEN:
            regs[_reg(a)] = _varsym(LEN_VAR)
        elif op == OP_LDM:
            idx = regs[_reg(b)]
            i = _concrete(idx, st.domains)
            if i is not None:
                if not (0 <= i < mem_size):
                    continue            # definite crash on this path
                regs[_reg(a)] = (_opaque(frozenset())
                                 if st.mem_havoc
                                 else st.mem.get(i, _const(0)))
            else:
                if LEN_VAR in idx.vars:
                    restricted = True   # length domain capped
                folded = _add_constraints(
                    [_range_constraint(pc, idx, mem_size, "ldm")],
                    st.domains, st.deferred)
                if folded is None:
                    continue
                st.domains, defer = folded
                st.deferred = tuple(defer)
                regs[_reg(a)] = _opaque(idx.vars)
        elif op == OP_STM:
            idx = regs[_reg(a)]
            i = _concrete(idx, st.domains)
            if i is not None:
                if not (0 <= i < mem_size):
                    continue            # definite crash on this path
                st.mem = {**st.mem, i: regs[_reg(b)]}
            else:
                if LEN_VAR in idx.vars:
                    restricted = True   # length domain capped
                folded = _add_constraints(
                    [_range_constraint(pc, idx, mem_size, "stm")],
                    st.domains, st.deferred)
                if folded is None:
                    continue
                st.domains, defer = folded
                st.deferred = tuple(defer)
                st.mem_havoc = True     # unknown cell overwritten
        st.regs = tuple(regs)
        st.pc = pc + 1
        stack.append(st)

    if capped:
        return SolveResult(
            edge=(f_idx, t_idx), status="unknown",
            reason="no satisfiable path within the visit/step caps "
                   "(loop-carried state beyond "
                   f"{max_visits} passes is not modeled)",
            paths_tried=paths_tried, expansions=expansions)
    if restricted:
        return SolveResult(
            edge=(f_idx, t_idx), status="unknown",
            reason="no satisfiable path under the bounded input "
                   "model (reads forced in-bounds, length capped at "
                   f"{max_len} — raise max_len or accept unknown)",
            paths_tried=paths_tried, expansions=expansions)
    return SolveResult(
        edge=(f_idx, t_idx), status="unsat",
        reason="every candidate path exhaustively refuted",
        paths_tried=paths_tried, expansions=expansions)


def _concrete(sym: Sym, domains) -> Optional[int]:
    """The sym's exact value when every variable it reads is pinned
    to a singleton domain, else None."""
    if sym.opaque:
        return None
    if not sym.vars:
        return sym.fn({})
    env = {}
    for v in sym.vars:
        dom = domains.get(v)
        if dom is None or len(dom) != 1:
            return None
        env[v] = next(iter(dom))
    return sym.fn(env)


def solve_edges(program, edges=None, **kw) -> Dict[Tuple[int, int],
                                                   SolveResult]:
    """Solve several edges (default: the whole static universe)."""
    if edges is None:
        edges = list(zip(np.asarray(program.edge_from).tolist(),
                         np.asarray(program.edge_to).tolist()))
    return {(int(f), int(t)): solve_edge(program, (f, t), **kw)
            for f, t in edges}


# --------------------------------------------------------------------
# VSA-seeded solving (the --vsa path; analysis/vsa.py consumer (a))
# --------------------------------------------------------------------

_CMP_BY_NAME = {"eq": CMP_EQ, "ne": CMP_NE, "lt": CMP_LT,
                "ge": CMP_GE}

#: visit-cap escalation ladder tried per edge under --vsa, shallow
#: first.  Soundness: a deeper unroll only ADDS candidate paths, so
#: per-edge take-best is monotone — solved stops the ladder (witness
#: verified), unsat stops it (already exhaustive), unknown escalates.
#: Measured on the gate targets: imgparse 36 -> 51 solved and
#: tlvstack 173 -> 183 at default budgets, rledec saturated at 58.
VSA_VISIT_LADDER = (2, 3, 4)


def _instr_dominators(instrs, ni: int) -> List[int]:
    """Instruction-level dominator sets from pc 0, as bitmasks
    (``doms[p] >> q & 1`` = q dominates p).  Unreached pcs keep the
    all-ones mask (vacuous — never consulted for them)."""
    preds: List[List[int]] = [[] for _ in range(ni)]
    reach = [False] * ni
    if ni:
        reach[0] = True
        frontier = [0]
        while frontier:
            p = frontier.pop()
            for s in instr_successors(instrs, p):
                if 0 <= s < ni:
                    preds[s].append(p)
                    if not reach[s]:
                        reach[s] = True
                        frontier.append(s)
    full = (1 << ni) - 1
    doms = [full] * ni
    if ni:
        doms[0] = 1
    changed = True
    while changed:
        changed = False
        for p in range(1, ni):
            if not reach[p]:
                continue
            m = full
            for q in preds[p]:
                m &= doms[q]
            m |= (1 << p)
            if m != doms[p]:
                doms[p] = m
                changed = True
    return doms


def vsa_seed_domains(program, vsa, edge: Tuple[int, int]
                     ) -> Tuple[Dict, List[Dict]]:
    """Byte-variable seed domains for ``edge``, derived from VSA
    branch facts that are NECESSARY conditions of traversing it:
    guards that (i) instruction-dominate the target block head,
    (ii) have exactly one successor that can still reach it (the
    forced side — taking the other permanently leaves the target's
    reach set), and (iii) carry an exact affine byte provenance
    against a constant, so the forced outcome inverts to a byte set.

    Returns ``(seeds, notes)``: ``('byte', i) -> frozenset`` plus
    one provenance note per contributing guard (the --explain and
    certificate payload).  Contradictory guards (empty intersection)
    drop the seed for that byte rather than claim bottom — the
    short-input zero-read path is outside this argument."""
    from .vsa import affine_sat_set, _side_pred
    instrs = np.asarray(program.instrs)
    ni = instrs.shape[0]
    rows = [tuple(int(x) for x in instrs[pc]) for pc in range(ni)]
    block_pcs = [pc for pc in range(ni) if rows[pc][0] == OP_BLOCK]
    t_idx = int(edge[1])
    if not (0 <= t_idx < len(block_pcs)):
        return {}, []
    t_head = block_pcs[t_idx]
    can_reach, _dist = _instr_reach(instrs, ni, t_head)
    doms = _instr_dominators(instrs, ni)
    dom_mask = doms[t_head]

    seeds: Dict = {}
    notes: List[Dict] = []
    for f in vsa.branches:
        p = f.pc
        if not (0 <= p < ni) or not (dom_mask >> p) & 1:
            continue
        _op, _a, b, c = rows[p]
        taken, fall = c, p + 1
        if taken == fall:
            continue                    # degenerate: no forcing
        live = [s for s in (taken, fall)
                if 0 <= s < ni and s in can_reach]
        if len(live) != 1:
            continue
        want = live[0] == taken
        sel = _CMP_BY_NAME[f.cmp]
        for aff, other, is_x in ((f.x_affine, f.y_dom, True),
                                 (f.y_affine, f.x_dom, False)):
            if aff is None or other.const_val is None:
                continue
            trip = _side_pred(sel, other.const_val, want, is_x)
            if trip is None:
                continue
            sat = affine_sat_set(aff, *trip)
            i = aff[0]
            var = ("byte", i)
            cur = seeds.get(var, frozenset(range(256)))
            nxt = cur & sat
            if not nxt:
                # contradictory guards: drop rather than claim
                # bottom (zero-read paths live outside the model)
                seeds.pop(var, None)
                break
            if len(nxt) == 256:
                continue                # guard does not constrain
            seeds[var] = nxt
            notes.append({
                "byte": i, "pc": p, "cmp": f.cmp,
                "const": other.const_val, "forced": bool(want),
                "affine": list(aff), "values": len(nxt)})
            break                       # one side used per guard
    return seeds, notes


def _seed_summary(seeds: Dict, notes: List[Dict],
                  dep_bytes) -> Dict[str, str]:
    """Per-position domain descriptions for --explain: seeded bytes
    name the guard that pruned them; dependency bytes without a seed
    name the honest failure (domain too wide to prune)."""
    out: Dict[str, str] = {}
    by_byte: Dict[int, List[Dict]] = {}
    for n in notes:
        by_byte.setdefault(n["byte"], []).append(n)
    for (kind, i), dom in sorted(seeds.items()):
        ns = by_byte.get(i, [])
        src = ", ".join(f"pc {n['pc']} ({n['cmp']} {n['const']})"
                        for n in ns)
        vals = sorted(dom)
        shown = ",".join(map(str, vals[:8])) + \
            (",…" if len(vals) > 8 else "")
        out[f"byte[{i}]"] = (f"seeded {{{shown}}} "
                             f"({len(vals)} of 256) from forced "
                             f"guard(s) {src}")
    for i in sorted(dep_bytes or []):
        key = f"byte[{i}]"
        if key not in out:
            out[key] = ("[0,255] — no dominating forced guard "
                        "constrains this position (interval too "
                        "wide to prune)")
    return out


def solve_edge_vsa(program, edge: Tuple[int, int], *, vsa=None,
                   budget: int = DEFAULT_BUDGET,
                   enum_budget: int = DEFAULT_ENUM_BUDGET,
                   max_visits: int = DEFAULT_MAX_VISITS,
                   max_len: int = DEFAULT_MAX_LEN,
                   fill: int = 0,
                   dataflow: Optional[DataflowResult] = None
                   ) -> SolveResult:
    """``solve_edge`` with VSA assistance: byte domains seed from
    the edge's dominating forced guards instead of top, and honest
    visit-cap unknowns escalate through ``VSA_VISIT_LADDER`` —
    deeper unrolls only ever ADD candidate paths, so the first
    solved (always concretely witness-verified) or unsat (already
    exhaustive at that rung) verdict stands, and an edge the ladder
    cannot settle stays an honest unknown carrying the domains that
    were too wide (``SolveResult.vsa['domains']``).

    The default-flag path never calls this function: no-flag
    behavior is bit-identical to ``solve_edge`` (the parity
    anchor)."""
    from .vsa import analyze_vsa
    if vsa is None:
        vsa = analyze_vsa(program)
    seeds, notes = vsa_seed_domains(program, vsa, edge)

    ladder = [v for v in VSA_VISIT_LADDER if v >= max_visits] \
        or [max_visits]
    if ladder[0] != max_visits and max_visits not in ladder:
        ladder = [max_visits] + ladder
    best: Optional[SolveResult] = None
    tried: List[int] = []
    for mv in ladder:
        res = solve_edge(program, edge, budget=budget,
                         enum_budget=enum_budget, max_visits=mv,
                         max_len=max_len, fill=fill,
                         vsa_seeds=seeds or None)
        tried.append(mv)
        best = res
        if res.status in ("solved", "unsat"):
            break
        if unknown_kind(res.reason) != "visit-cap":
            break                       # deeper unrolls cannot help

    meta: Dict = {
        "seeded_bytes": sorted(n["byte"] for n in notes),
        "seeds": {f"byte[{n['byte']}]": n for n in notes},
        "visit_ladder": tried,
    }
    if best.status == "unsat":
        # the exhaustive-refutation certificate: no caps were hit at
        # this rung (solve_edge only says unsat when capped and
        # restricted both stayed False), and every seed narrowed a
        # NECESSARY condition — so the refutation covers the full
        # input space
        meta["certificate"] = {
            "exhaustive": True, "max_visits": tried[-1],
            "expansions": best.expansions,
            "paths_tried": best.paths_tried,
            "forced_guards": notes,
        }
    if best.status == "unknown":
        if dataflow is None:
            dataflow = analyze_dataflow(program)
        dep = edge_dep_mask(program, [edge], dataflow)
        meta["domains"] = _seed_summary(seeds, notes, dep)
    best.vsa = meta
    return best


def edge_dep_mask(program, edges,
                  dataflow: Optional[DataflowResult] = None
                  ) -> Optional[List[int]]:
    """Byte positions the frontier ``edges`` depend on: the union of
    the input-byte dependency sets of every branch inside the SOURCE
    block of each edge (those branches decide which out-edge runs).
    Returns a sorted position list, or None when nothing usable is
    known (a branch with unknown deps contributes nothing — the mask
    must never exclude bytes an uncovered branch might read, so an
    all-unknown frontier disables focusing rather than guessing)."""
    dataflow = dataflow or analyze_dataflow(program)
    by_block: Dict[int, object] = {}
    for fct in dataflow.branches:
        cur = by_block.get(fct.block, frozenset())
        if cur is ANY:
            continue
        by_block[fct.block] = (ANY if fct.deps is ANY
                               else cur | fct.deps)
    missing = object()                  # ANY is None: distinguish a
    mask: Set[int] = set()              # branch-free source block
    any_unknown = False                 # (one out-edge, nothing to
    for f, _t in edges:                 # focus) from unknown deps
        deps = by_block.get(int(f), missing)
        if deps is ANY:
            any_unknown = True
        elif deps is not missing and deps:
            mask |= set(deps)
    if any_unknown or not mask:
        return None
    return sorted(mask)
